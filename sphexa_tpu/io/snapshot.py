"""Snapshot writer/reader: HDF5 (H5Part-style Step#n groups) + npz fallback.

Layout mirrors the reference (main/src/io/ifile_io_hdf5.cpp:49-314):

    dump.h5
    └── Step#0
        ├── attrs: iteration, time, minDt, minDt_m1, gravConstant, gamma,
        │          ng0, ngmax, Kcour, mui, box_lo, box_hi, box_boundaries, ...
        ├── x, y, z, x_m1, ..., alpha   (one dataset per conserved field)
        └── rho, p, ...                 (optional derived output fields)

Restart = read the conserved fields + attributes back into a ParticleState
and SimConstants (the FileInit path, main/src/init/file_init.hpp).
"""

import dataclasses
import os
from typing import Dict, List, Optional, Tuple

import numpy as np
import jax.numpy as jnp

from sphexa_tpu.dtypes import COORD_DTYPE, HYDRO_DTYPE
from sphexa_tpu.sfc.box import BoundaryType, Box
from sphexa_tpu.sph.particles import ParticleState, SimConstants

try:
    import h5py

    _HAVE_H5PY = True
except ImportError:  # pragma: no cover - h5py is present in the image
    _HAVE_H5PY = False

# conserved per-particle fields: the restartable set (ipropagator
# conservedFields + particles_data.hpp checkpoint list)
CONSERVED_FIELDS = (
    "x", "y", "z", "x_m1", "y_m1", "z_m1", "vx", "vy", "vz",
    "h", "m", "temp", "du", "du_m1", "alpha",
)

# SimConstants fields serialized as attributes, reference attribute names
# (particles_data.hpp:170-191)
_CONST_ATTRS = {
    "ng0": "ng0", "ngmax": "ngmax", "k_cour": "Kcour", "k_rho": "Krho",
    "gamma": "gamma", "mui": "muiConst", "alphamin": "alphamin",
    "alphamax": "alphamax", "decay_constant": "decay_constant",
    "at_min": "Atmin", "at_max": "Atmax", "g": "gravConstant",
    "eps": "eps", "eta_acc": "etaAcc", "max_dt_increase": "maxDtIncrease",
    "sinc_index": "sincIndex", "kernel_choice": "kernelChoice",
    # pair-cutoff convention: restarts must reproduce the writing run's
    # force convention (min-h symmetric vs reference one-sided) — a
    # continuation that silently flips it changes energies mid-run
    "sym_pairs": "symPairs",
}


def _is_h5(path: str) -> bool:
    return os.path.splitext(path)[1].lower() in (".h5", ".hdf5", ".h5part")


def _step_attrs(state: ParticleState, box: Box, const: SimConstants,
                iteration: int,
                num_particles_global: Optional[int] = None
                ) -> Dict[str, np.ndarray]:
    attrs = {
        "iteration": np.int64(iteration),
        # the H5Part convention (ifile_io_hdf5.cpp) records the GLOBAL
        # count on every rank's output; sharded part files override this
        # so external tools probing any single part see the true total
        "numParticlesGlobal": np.int64(
            state.n if num_particles_global is None else num_particles_global),
        "time": np.float64(state.ttot),
        "minDt": np.float64(state.min_dt),
        "minDt_m1": np.float64(state.min_dt_m1),
        "box_lo": np.asarray(box.lo, np.float64),
        "box_hi": np.asarray(box.hi, np.float64),
        "box_boundaries": np.asarray([int(b) for b in box.boundaries], np.int64),
    }
    for field, name in _CONST_ATTRS.items():
        v = getattr(const, field)
        attrs[name] = (
            np.bytes_(v.encode()) if isinstance(v, str) else np.float64(v)
        )
    return attrs


def write_snapshot(
    path: str,
    state: ParticleState,
    box: Box,
    const: SimConstants,
    iteration: int = 0,
    extra_fields: Optional[Dict[str, np.ndarray]] = None,
    case: str = "",
    case_settings: Optional[Dict] = None,
    num_particles_global: Optional[int] = None,
) -> int:
    """Append one restartable snapshot; returns the step index written.

    ``extra_fields`` adds derived output datasets (rho, p, ...) alongside
    the conserved set — the analog of the -f/--wextra field selection.
    ``case`` records the originating test-case name so a restarted run can
    re-select the matching observable (the reference records its init
    settings as file attributes for the same reason, settings.hpp:45-57).
    ``num_particles_global`` overrides the numParticlesGlobal attribute
    (sharded part files record the global count, not their row count).
    """
    fields = {f: np.asarray(getattr(state, f)) for f in CONSERVED_FIELDS}
    if extra_fields:
        fields.update({k: np.asarray(v) for k, v in extra_fields.items()})
    attrs = _step_attrs(state, box, const, iteration, num_particles_global)
    if case:
        attrs["initCase"] = np.bytes_(case)
    if case_settings:
        # the applied case-settings overrides ride along so a restart can
        # rebuild threshold-bearing observables identically (the reference
        # writes its init settings as file attributes, settings.hpp:45-57)
        import json

        attrs["caseSettings"] = np.bytes_(json.dumps(case_settings))

    if _is_h5(path):
        if not _HAVE_H5PY:
            raise RuntimeError("h5py unavailable; use a .npz path instead")
        with h5py.File(path, "a") as f:
            step = len([k for k in f.keys() if k.startswith("Step#")])
            g = f.create_group(f"Step#{step}")
            for k, v in attrs.items():
                g.attrs[k] = v
            for k, v in fields.items():
                g.create_dataset(k, data=v)
            return step

    arrays = {f"field_{k}": v for k, v in fields.items()}
    arrays.update({f"attr_{k}": v for k, v in attrs.items()})
    np.savez_compressed(path, **arrays)
    return 0


def _part_path(path: str, k: int, P: int) -> str:
    base, ext = os.path.splitext(path)
    return f"{base}.part{k:03d}of{P:03d}{ext}"


def _find_parts(path: str) -> List[str]:
    """Existing part files of a sharded snapshot base path (sorted)."""
    import glob as _glob

    base, ext = os.path.splitext(path)
    return sorted(_glob.glob(f"{base}.part*of*{ext}"))


def write_snapshot_sharded(
    path: str,
    state: ParticleState,
    box: Box,
    const: SimConstants,
    iteration: int = 0,
    extra_fields: Optional[Dict[str, np.ndarray]] = None,
    case: str = "",
    case_settings: Optional[Dict] = None,
) -> int:
    """Parallel snapshot: one part file per device shard, NO global
    gather — the role of the reference's collective MPI-IO writer
    (main/src/io/ifile_io_hdf5.cpp:49-314), transposed to the
    file-per-shard pattern: every host writes only the slab rows its
    devices own (on a multi-host mesh each process sees only its own
    ``addressable_shards``), so dump bandwidth scales with hosts and the
    64M-particle funnel through one writer disappears.

    Part files are ordinary snapshots (same Step# layout) of their slab
    rows; ``read_snapshot`` on the BASE path reassembles them. Returns
    the step index written (parts stay step-aligned because every dump
    writes all parts)."""
    xarr = state.x
    shards = getattr(xarr, "addressable_shards", None)
    if not shards or len(getattr(xarr.sharding, "device_set", [])) <= 1:
        # single-device state: plain snapshot (no parts)
        return write_snapshot(path, state, box, const, iteration,
                              extra_fields, case, case_settings)
    P = len(xarr.sharding.device_set)
    n = xarr.shape[0]
    if n % P != 0:
        raise ValueError(
            f"sharded snapshot requires n divisible by the device count "
            f"(n={n}, P={P}); the CLI trims ICs to a multiple of P")
    rows = n // P
    # ONE host fetch per extra field (inside the shard loop each
    # np.asarray would re-gather the full array P times)
    extras_np = {k2: np.asarray(v) for k2, v in (extra_fields or {}).items()}
    step = 0
    for sh in shards:
        sl = sh.index[0] if sh.index else slice(0, n)
        start = sl.start or 0
        k = start // rows

        class _Part:
            pass

        part = _Part()
        for f in CONSERVED_FIELDS:
            a = getattr(state, f)
            starts = [s.index[0].start or 0 for s in a.addressable_shards]
            if start not in starts:
                raise ValueError(
                    f"field {f}: no shard starting at row {start} "
                    f"(shard starts {sorted(starts)}) — uneven or "
                    "mismatched sharding across fields")
            ash = a.addressable_shards[starts.index(start)]
            if ash.data.shape[0] != rows:
                raise ValueError(
                    f"field {f}: shard at row {start} has "
                    f"{ash.data.shape[0]} rows, expected {rows} — "
                    "sharded snapshots require equal-size shards")
            setattr(part, f, np.asarray(ash.data))
        part.n = rows
        part.ttot = state.ttot
        part.min_dt = state.min_dt
        part.min_dt_m1 = state.min_dt_m1
        ex = None
        if extra_fields:
            # per-particle extras are sliced to the part's rows;
            # global tables (turbulence phases, chemistry scalars) go to
            # part 0 ONLY (the reader takes part-0-only fields verbatim)
            ex = {}
            for k2, va in extras_np.items():
                if va.ndim >= 1 and va.shape[0] == n:
                    ex[k2] = va[start:start + rows]
                elif k == 0:
                    ex[k2] = va
        step = write_snapshot(
            _part_path(path, k, P), part, box, const, iteration, ex,
            case, case_settings, num_particles_global=n,
        )
    return step


def list_steps(path: str) -> List[int]:
    """Step indices present in a snapshot file.

    On a sharded base path this is the INTERSECTION across part files, so
    a torn dump's extra part-0 step (which ``_read_raw`` would refuse to
    assemble) is never reported as readable."""
    if not os.path.exists(path):
        parts = _find_parts(path)
        if parts:
            common: Optional[set] = None
            for p in parts:
                s = set(list_steps(p))
                common = s if common is None else (common & s)
            return sorted(common or ())
    if _is_h5(path):
        with h5py.File(path, "r") as f:
            return sorted(
                int(k.split("#")[1]) for k in f.keys() if k.startswith("Step#")
            )
    return [0]


def _resolve_step(steps: List[int], step: int, path: str) -> int:
    """Validate a step selector against the file's Step#n indices;
    negative counts from the end."""
    if not steps:
        raise ValueError(f"{path} contains no Step#n groups")
    if step < 0:
        if -step > len(steps):
            raise ValueError(f"step {step} out of range for {path}; have {steps}")
        return steps[step]
    if step not in steps:
        raise ValueError(f"step {step} not in {path}; have {steps}")
    return step


def _h5_steps(f) -> List[int]:
    return sorted(int(k.split("#")[1]) for k in f.keys() if k.startswith("Step#"))


def _read_raw(path: str, step: int):
    if not os.path.exists(path):
        parts = _find_parts(path)
        if parts:
            # sharded snapshot: concatenate the slab-row parts in part
            # order (file names carry the order); attrs from part 0.
            # Guards: the part set must be complete (file names encode
            # P), and every part must resolve to the SAME dump — a torn
            # write (crash mid-dump) leaves later parts one step behind
            import re

            mP = re.search(r"part\d+of(\d+)", parts[0])
            P_declared = int(mP.group(1)) if mP else len(parts)
            if len(parts) != P_declared:
                raise ValueError(
                    f"{path}: sharded snapshot has {len(parts)} part files "
                    f"but names declare {P_declared} shards (incomplete "
                    "dump or mixed part sets from different runs)")
            # resolve the selector against the steps COMPLETE across all
            # parts (a torn dump leaves part 0 a step ahead; -1 must mean
            # the newest ASSEMBLABLE step, matching list_steps)
            step = _resolve_step(list_steps(path), step, path)
            fields_all, attrs = None, None
            for p in parts:
                f, a = _read_raw_one(p, step)
                if fields_all is None:
                    fields_all, attrs = {k: [v] for k, v in f.items()}, a
                else:
                    if (int(a["iteration"]) != int(attrs["iteration"])
                            or float(a["time"]) != float(attrs["time"])):
                        raise ValueError(
                            f"{p}: part resolves to iteration "
                            f"{int(a['iteration'])} != part 0's "
                            f"{int(attrs['iteration'])} — torn sharded "
                            "dump (crash mid-write?); pass an explicit "
                            "step index for the last complete dump")
                    for k, v in f.items():
                        fields_all.setdefault(k, []).append(v)
            # fields present only in part 0 are global tables — verbatim;
            # per-particle fields (present in every part) concatenate
            out = {k: (np.concatenate(v) if len(v) == len(parts) else v[0])
                   for k, v in fields_all.items()}
            return out, attrs
    return _read_raw_one(path, step)


def _read_raw_one(path: str, step: int):
    if _is_h5(path):
        with h5py.File(path, "r") as f:
            idx = _resolve_step(_h5_steps(f), step, path)
            g = f[f"Step#{idx}"]
            fields = {k: np.asarray(g[k]) for k in g.keys()}
            attrs = {k: np.asarray(v) for k, v in g.attrs.items()}
            return fields, attrs
    _resolve_step([0], step, path)  # npz files hold exactly one snapshot
    data = np.load(path)
    fields = {k[6:]: data[k] for k in data.files if k.startswith("field_")}
    attrs = {k[5:]: data[k] for k in data.files if k.startswith("attr_")}
    return fields, attrs


def read_step_attrs(path: str, step: int = -1) -> Dict[str, np.ndarray]:
    """Step attributes only (iteration, time, constants) — cheap restart
    metadata probe without loading the particle datasets."""
    if not os.path.exists(path):
        parts = _find_parts(path)
        if parts:
            # resolve the selector against the steps COMPLETE across all
            # parts (matching what _read_raw will accept), then probe
            # part 0's attrs for that step
            idx = _resolve_step(list_steps(path), step, path)
            step, path = idx, parts[0]
    if _is_h5(path):
        with h5py.File(path, "r") as f:
            idx = _resolve_step(_h5_steps(f), step, path)
            return {k: np.asarray(v) for k, v in f[f"Step#{idx}"].attrs.items()}
    _, attrs = _read_raw(path, step)
    return attrs


def read_snapshot(
    path: str, step: int = -1
) -> Tuple[ParticleState, Box, SimConstants, Dict[str, np.ndarray]]:
    """Restore (state, box, const, extra_fields) from a snapshot.

    ``step``: index into the file's Step#n groups; negative counts from the
    end (the reference's ``--init dump.h5:-1`` semantics, file_init.hpp).
    """
    state, box, const, extra, _ = read_snapshot_full(path, step)
    return state, box, const, extra


def read_snapshot_full(
    path: str, step: int = -1
) -> Tuple[ParticleState, Box, SimConstants, Dict[str, np.ndarray],
           Dict[str, np.ndarray]]:
    """read_snapshot + the raw step attributes (iteration, initCase, ...) —
    single-read restore for callers that need the restart metadata too."""
    fields, attrs = _read_raw(path, step)

    missing = [f for f in CONSERVED_FIELDS if f not in fields]
    if missing:
        raise ValueError(f"{path} is not restartable: missing fields {missing}")

    const_kw = {}
    for field, name in _CONST_ATTRS.items():
        if name in attrs:
            if field == "kernel_choice":
                v = attrs[name]
                v = v.item() if hasattr(v, "item") else v
                const_kw[field] = v.decode() if isinstance(v, bytes) else str(v)
            elif field == "sym_pairs":
                const_kw[field] = bool(int(float(attrs[name])))
            else:
                cast = int if field in ("ng0", "ngmax") else float
                const_kw[field] = cast(attrs[name])
    const = SimConstants(**const_kw).normalized()

    box = Box(
        lo=jnp.asarray(attrs["box_lo"], COORD_DTYPE),
        hi=jnp.asarray(attrs["box_hi"], COORD_DTYPE),
        boundaries=tuple(BoundaryType(int(b)) for b in attrs["box_boundaries"]),
    )

    f32 = lambda k: jnp.asarray(fields[k], HYDRO_DTYPE)
    state = ParticleState(
        **{f: f32(f) for f in CONSERVED_FIELDS},
        # the energy-update compensation carry is not serialized (it is
        # < 1 ulp of temp); restarting resets it
        temp_lo=jnp.zeros_like(jnp.asarray(fields["temp"], HYDRO_DTYPE)),
        ttot=HYDRO_DTYPE(attrs["time"]),
        min_dt=HYDRO_DTYPE(attrs["minDt"]),
        min_dt_m1=HYDRO_DTYPE(attrs["minDt_m1"]),
    )
    extra = {k: v for k, v in fields.items() if k not in CONSERVED_FIELDS}
    return state, box, const, extra, attrs


def write_ascii(
    path: str, columns: Dict[str, np.ndarray], delimiter: str = " "
) -> None:
    """Plain-text column dump (the --ascii output path,
    main/src/io/ifile_io_ascii.cpp): one header line, one row per particle."""
    names = list(columns)
    data = np.column_stack([np.asarray(columns[k]) for k in names])
    np.savetxt(path, data, delimiter=delimiter, header=delimiter.join(names))
