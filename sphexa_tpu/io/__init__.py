"""Snapshot/checkpoint file I/O.

Counterpart of the reference's ``main/src/io/`` (IFileWriter/IFileReader,
ifile_io_hdf5.cpp, h5part_wrapper.hpp): snapshots are HDF5 files with one
``Step#n`` group per dump, per-particle datasets inside the group, and the
restart metadata (iteration, time, minDt, physics constants, box) stored as
group attributes — the same layout the reference writes, so dumps are
restartable by construction (sphexa.cpp:227-231).

A dependency-free ``.npz`` container is supported as a fallback format
(single snapshot per file) selected by file extension.
"""

from sphexa_tpu.io.snapshot import (
    list_steps,
    read_snapshot,
    write_ascii,
    write_snapshot,
)

__all__ = ["write_snapshot", "read_snapshot", "list_steps", "write_ascii"]
