"""Default dtype policy for the framework.

The reference uses float64 coordinates + float32 hydro fields
(sph/include/sph/types.hpp:39-46 in SPH-EXA). TPUs have no fast f64, so the
TPU-native policy is:

- SFC keys: uint32 (30-bit keys, 10 octree levels). The key space, not the
  float coordinate, is the primary spatial ordering structure, mirroring the
  reference's 63-bit Hilbert keys at reduced depth.
- coordinates & hydro fields: float32.
- reductions that guard conservation diagnostics: compensated/f64-on-host.
"""

import jax.numpy as jnp

# Key type for space-filling-curve keys. 10 levels x 3 bits = 30 bits.
KEY_DTYPE = jnp.uint32
KEY_BITS = 10  # octree levels encodable in a key
# One past the largest key. A Python int, NOT a jnp scalar: a module-level
# jnp constant grabs a device at import time and, if the first import
# happens under a live trace, is born a tracer and leaks into every later
# trace that reads it (JXL001 — the parallel/exchange.py INF32 bug class).
KEY_MAX = 1 << (3 * KEY_BITS)

COORD_DTYPE = jnp.float32
HYDRO_DTYPE = jnp.float32
INDEX_DTYPE = jnp.int32
