"""sphexa-tpu command-line front-end.

Counterpart of the reference's ``main/src/sphexa/sphexa.cpp`` CLI: the same
flag vocabulary (--init, -n, -s, -w, --prop, --quiet, ...), factory wiring
from case name to initializer, and the iteration loop with per-step console
reporting. Flags the TPU build does not support yet are accepted and
reported, not silently ignored.
"""

import argparse
import sys
import time


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="sphexa-tpu",
        description="TPU-native SPH simulation (Sedov, Noh, ... test cases)",
    )
    p.add_argument("--init", default="sedov", help="test case name (sedov, ...)")
    p.add_argument("-n", type=int, default=50, dest="side",
                   help="particles per cube side (N = n^3)")
    p.add_argument("-s", type=float, default=10, dest="stop",
                   help="integer: number of iterations; float: simulated time")
    p.add_argument("-w", type=float, default=-1, dest="write_every",
                   help="integer: dump every N iterations; float: every t interval")
    p.add_argument("-f", default="", dest="out_fields", help="fields to dump")
    p.add_argument("-o", "--outDir", default=".", dest="out_dir")
    p.add_argument("--prop", default="std", help="propagator: std | ve")
    p.add_argument("--quiet", action="store_true")
    p.add_argument("--avclean", action="store_true")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    from sphexa_tpu.init import CASES, make_initializer
    from sphexa_tpu.observables import conserved_quantities
    from sphexa_tpu.simulation import _PROPAGATORS, Simulation

    if args.init not in CASES:
        print(f"unknown --init {args.init!r}; available: {sorted(CASES)}",
              file=sys.stderr)
        return 2
    if args.prop not in _PROPAGATORS:
        print(f"unknown --prop {args.prop!r}; available: {sorted(_PROPAGATORS)}",
              file=sys.stderr)
        return 2
    if args.avclean and args.prop != "ve":
        print("--avclean only applies to --prop ve; ignoring", file=sys.stderr)
    state, box, const = make_initializer(args.init)(args.side)

    sim = Simulation(state, box, const, prop=args.prop,
                     av_clean=args.avclean and args.prop == "ve")
    log = (lambda *a, **k: None) if args.quiet else print
    log(f"# sphexa-tpu --init {args.init} N={state.n} prop={args.prop}")

    num_steps = int(args.stop) if float(args.stop).is_integer() else None
    target_time = None if num_steps is not None else float(args.stop)

    t0 = time.time()
    it = 0
    while True:
        d = sim.step()
        it += 1
        e = conserved_quantities(sim.state, const)
        log(
            f"it {it:5d}  t={float(sim.state.ttot):.6g} dt={d['dt']:.4g} "
            f"etot={float(e['etot']):.6f} ecin={float(e['ecin']):.4g} "
            f"eint={float(e['eint']):.4g} nc~{d['nc_mean']:.0f}"
        )
        if num_steps is not None and it >= num_steps:
            break
        if target_time is not None and float(sim.state.ttot) >= target_time:
            break
    dt_wall = time.time() - t0
    log(f"# {it} iterations in {dt_wall:.2f}s "
        f"({state.n * it / dt_wall / 1e6:.3f}M particle-updates/s)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
