"""sphexa-tpu command-line front-end.

Counterpart of the reference's ``main/src/sphexa/sphexa.cpp`` CLI: the same
flag vocabulary (--init, -n, -s, -w, --prop, --quiet, ...), factory wiring
from case name to initializer, and the iteration loop with per-step console
reporting. Flags the TPU build does not support yet are accepted and
reported, not silently ignored.
"""

import argparse
import os
import sys
import time

import numpy as np


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="sphexa-tpu",
        description="TPU-native SPH simulation (Sedov, Noh, ... test cases)",
    )
    p.add_argument("--init", default="sedov", help="test case name (sedov, ...)")
    p.add_argument("-n", type=int, default=50, dest="side",
                   help="particles per cube side (N = n^3)")
    p.add_argument("-s", type=float, default=10, dest="stop",
                   help="integer: number of iterations; float: simulated time")
    p.add_argument("-w", type=float, default=-1, dest="write_every",
                   help="integer: dump every N iterations; float: every t interval")
    p.add_argument("-f", default="", dest="out_fields", help="fields to dump")
    p.add_argument("-o", "--outDir", default=".", dest="out_dir")
    p.add_argument("--prop", default="std",
                   help="propagator: std | ve | turb-ve | std-cooling | nbody")
    p.add_argument("--quiet", action="store_true")
    p.add_argument("--avclean", action="store_true")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    from sphexa_tpu.init import make_initializer
    from sphexa_tpu.observables import (
        ConstantsWriter,
        conserved_quantities,
        make_observable,
    )
    from sphexa_tpu.simulation import _PROPAGATORS, Simulation

    if args.prop not in _PROPAGATORS:
        print(f"unknown --prop {args.prop!r}; available: {sorted(_PROPAGATORS)}",
              file=sys.stderr)
        return 2
    if args.avclean and args.prop not in ("ve", "turb-ve"):
        print("--avclean only applies to --prop ve | turb-ve; ignoring",
              file=sys.stderr)

    # built-in case names take precedence over same-named files, exactly
    # like make_initializer; a restart reads the snapshot ONCE, recovering
    # state, metadata and any checkpointed turbulence stirring state
    from sphexa_tpu.init import CASES
    from sphexa_tpu.init.file_init import looks_like_file, parse_file_spec

    log = (lambda *a, **k: None) if args.quiet else print
    case_name = args.init
    is_restart = args.init not in CASES and looks_like_file(args.init)
    turb_state, turb_cfg, restart_iteration = None, None, 0
    if is_restart:
        from sphexa_tpu.io.snapshot import read_snapshot_full

        state, box, const, extra, attrs = read_snapshot_full(
            *parse_file_spec(args.init)
        )
        restart_iteration = int(attrs.get("iteration", 0))
        case_name = (
            np.asarray(attrs["initCase"]).item().decode()
            if "initCase" in attrs
            else ""
        )
        if args.prop == "turb-ve" and "turb_phases" in extra:
            # resume the OU stirring state + config (the reference
            # checkpoints phases + RNG the same way, turb_ve.hpp:88-97)
            from sphexa_tpu.sph.hydro_turb import turbulence_state_from_fields

            turb_state, turb_cfg = turbulence_state_from_fields(extra)
    else:
        try:
            initializer = make_initializer(args.init)
        except ValueError as e:
            print(str(e), file=sys.stderr)
            return 2
        state, box, const = initializer(args.side)

    # observable selected by the test case (observables/factory.hpp:46-70) —
    # on restart, by the case name the snapshot recorded; field-consuming
    # observables read rho/c straight from the step diagnostics
    observable = make_observable(case_name)
    sim = Simulation(state, box, const, prop=args.prop,
                     av_clean=args.avclean and args.prop in ("ve", "turb-ve"),
                     turb_state=turb_state, turb_cfg=turb_cfg,
                     keep_fields=observable.needs_fields)
    log(f"# sphexa-tpu --init {args.init} N={state.n} prop={args.prop}")

    # resuming from a snapshot continues the iteration numbering, and an
    # integer -s is the END iteration (sphexa.cpp main-loop semantics)
    if is_restart:
        sim.iteration = restart_iteration
        log(f"# restart from iteration {sim.iteration}, t={float(state.ttot):.6g}"
            + (f" (case {case_name})" if case_name else ""))

    num_steps = int(args.stop) if float(args.stop).is_integer() else None
    target_time = None if num_steps is not None else float(args.stop)

    os.makedirs(args.out_dir, exist_ok=True)

    # -w: integer = dump every N iterations, float = every t interval
    # (arg_parser.hpp:99-118 int-vs-float dispatch, same as -s)
    dump_path = None
    w = args.write_every
    w_steps = int(w) if w > 0 and float(w).is_integer() else None
    w_time = w if w > 0 and w_steps is None else None
    next_dump_time = [float(state.ttot) + w_time] if w_time else None
    if w > 0:
        case_tag = "".join(c if c.isalnum() else "_" for c in args.init)
        dump_path = f"{args.out_dir}/dump_{case_tag}.h5"
        if os.path.exists(dump_path):
            print(f"# removing stale {dump_path} (would interleave old steps)",
                  file=sys.stderr)
            os.remove(dump_path)

    want_fields = [f for f in args.out_fields.split(",") if f]

    constants_path = f"{args.out_dir}/constants.txt"
    if not is_restart and os.path.exists(constants_path):
        print(f"# truncating stale {constants_path}", file=sys.stderr)
        os.remove(constants_path)
    constants = ConstantsWriter(constants_path, observable)

    def output_fields():
        from sphexa_tpu.analysis import compute_output_fields

        pipeline = "ve" if args.prop in ("ve", "turb-ve") else "std"
        return compute_output_fields(sim.state, sim.box, sim._cfg,
                                     pipeline=pipeline)

    def maybe_dump(it):
        """Restartable snapshot on the -w schedule; derived fields are
        recomputed like the reference's saveFields pass, consistently with
        the active propagator."""
        due = (w_steps is not None and it % w_steps == 0) or (
            next_dump_time is not None and float(sim.state.ttot) >= next_dump_time[0]
        )
        if dump_path is None or not due:
            return
        if next_dump_time is not None:
            next_dump_time[0] += w_time
        from sphexa_tpu.io import write_snapshot

        extra = output_fields()
        if want_fields:
            unknown = [f for f in want_fields if f not in extra]
            if unknown:
                print(f"# -f fields not available, skipped: {unknown}",
                      file=sys.stderr)
            extra = {k: v for k, v in extra.items() if k in want_fields}
        if sim.turb_state is not None:
            from sphexa_tpu.sph.hydro_turb import turbulence_state_to_fields

            extra = {
                **extra,
                **turbulence_state_to_fields(sim.turb_state, sim.turb_cfg),
            }
        step = write_snapshot(
            dump_path, sim.state, sim.box, const, iteration=it,
            extra_fields=extra, case=case_name,
        )
        log(f"# wrote Step#{step} -> {dump_path}")

    t0 = time.time()
    it0 = sim.iteration
    while True:
        d = sim.step()
        it = sim.iteration
        e = conserved_quantities(sim.state, const, egrav=d.get("egrav", 0.0))
        fields = {"rho": d["rho"], "c": d["c"]} if observable.needs_fields else None
        row = constants.write(it, sim.state, sim.box, e, fields)
        maybe_dump(it)  # dumps recompute the full derived set (r, p, u, ...)
        extra_cols = " ".join(
            f"{n}={v:.4g}" for n, v in zip(observable.extra_columns, row[7:])
        )
        log(
            f"it {it:5d}  t={float(sim.state.ttot):.6g} dt={d['dt']:.4g} "
            f"etot={float(e['etot']):.6f} ecin={float(e['ecin']):.4g} "
            f"eint={float(e['eint']):.4g} nc~{d['nc_mean']:.0f}"
            + (f" {extra_cols}" if extra_cols else "")
        )
        if num_steps is not None and it >= num_steps:
            break
        if target_time is not None and float(sim.state.ttot) >= target_time:
            break
    dt_wall = time.time() - t0
    n_done = sim.iteration - it0
    log(f"# {n_done} iterations in {dt_wall:.2f}s "
        f"({state.n * n_done / dt_wall / 1e6:.3f}M particle-updates/s)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
