"""sphexa-tpu command-line front-end.

Counterpart of the reference's ``main/src/sphexa/sphexa.cpp`` CLI: the same
flag vocabulary (--init, -n, -s, -w, --prop, --quiet, ...), factory wiring
from case name to initializer, and the iteration loop with per-step console
reporting. Flags the TPU build does not support yet are accepted and
reported, not silently ignored.
"""

import argparse
import dataclasses as _dc
import os
import sys
import time

import numpy as np


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="sphexa-tpu",
        description="TPU-native SPH simulation (Sedov, Noh, ... test cases)",
    )
    p.add_argument("--init", default="sedov", help="test case name (sedov, ...)")
    p.add_argument("-n", type=int, default=50, dest="side",
                   help="particles per cube side (N = n^3)")
    p.add_argument("-s", type=float, default=10, dest="stop",
                   help="integer: number of iterations; float: simulated time")
    p.add_argument("-w", type=float, default=-1, dest="write_every",
                   help="integer: dump every N iterations; float: every t interval")
    p.add_argument("-f", default="", dest="out_fields", help="fields to dump")
    p.add_argument("-o", "--outDir", default=".", dest="out_dir")
    p.add_argument("--prop", default="std",
                   help="propagator: std | ve | turb-ve | std-cooling | nbody")
    p.add_argument("--quiet", action="store_true")
    p.add_argument("--avclean", action="store_true")
    p.add_argument("--theta", type=float, default=0.5,
                   help="gravity MAC accuracy parameter [0.5]")
    p.add_argument("--G", type=float, default=None, dest="grav_constant",
                   help="gravitational constant override (enables gravity)")
    p.add_argument("--m2p-cap-margin", type=float, default=None,
                   dest="m2p_cap_margin",
                   help="gravity M2P interaction-list cap margin [1.3]; "
                        "the M2P eval cost is linear in the cap, overflow "
                        "is diagnostic-guarded and auto-regrown; unset, "
                        "--tuned may resolve it from the tuning table")
    p.add_argument("--sym-pairs", default=None, choices=("on", "off"),
                   dest="sym_pairs",
                   help="momentum/energy pair-cutoff convention: on = min-h "
                        "symmetric (default), off = reference-parity "
                        "one-sided; overrides the snapshot's symPairs attr")
    p.add_argument("--evolve-chem", action="store_true", dest="evolve_chem",
                   help="std-cooling: evolve the 6-species primordial "
                        "network (H/H+/He/He+/He++/e) instead of the CIE "
                        "table with static fractions")
    p.add_argument("--glass", default=None,
                   help="glass template HDF5 file, tiled into every "
                        "lattice-based IC (init/utils.hpp glass blocks); "
                        "without it a procedural jittered lattice is used")
    p.add_argument("--wextra", default="",
                   help="comma-separated extra output triggers: integers = "
                        "iterations, floats = simulation times")
    p.add_argument("--ascii", action="store_true",
                   help="dump ASCII columns instead of HDF5 (not restartable)")
    p.add_argument("--duration", type=float, default=None,
                   help="maximum wall-clock run time in seconds; dumps a "
                        "final snapshot before exiting if -w is enabled")
    p.add_argument("--profile", action="store_true",
                   help="save a per-iteration timing series to profile.npz")
    p.add_argument("--telemetry-dir", default=None, dest="telemetry_dir",
                   help="write structured run telemetry (manifest.json + "
                        "events.jsonl) to this directory; summarize/diff "
                        "it with sphexa-telemetry (docs/OBSERVABILITY.md)")
    p.add_argument("--trace-dir", default=None, dest="trace_dir",
                   help="capture a jax.profiler trace of the run into "
                        "this directory (launch/flush/reconfigure scopes "
                        "are TraceAnnotation-named); view with "
                        "tensorboard/xprof")
    p.add_argument("--devices", type=int, default=None,
                   help="shard the run over N devices (SFC-slab domain "
                        "decomposition; default: single device)")
    p.add_argument("--cpu-mesh", action="store_true", dest="cpu_mesh",
                   help="force an N-virtual-device CPU mesh for --devices "
                        "runs on hosts with fewer real chips (validation "
                        "mode; same mechanism as the multi-chip dry run)")
    p.add_argument("--halo-mode", default="sparse",
                   choices=("sparse", "windowed"), dest="halo_mode",
                   help="multi-chip halo exchange: sparse cell-granular "
                        "per-distance buffers (default) or contiguous "
                        "per-peer windows")
    p.add_argument("--backend", default="auto",
                   choices=("auto", "pallas", "xla"),
                   help="force the engine backend (auto: pallas on TPU, "
                        "xla elsewhere); pallas off-TPU runs the Mosaic "
                        "kernels in interpret mode — the CPU-mesh "
                        "rehearsal path the multi-chip dry run uses")
    p.add_argument("--check-every", type=int, default=None,
                   dest="check_every",
                   help="deferred cap-checking window: launch N steps "
                        "with no device sync, fetch/verify diagnostics "
                        "in one batch at the window end (default 1 = "
                        "synchronous; unset, --tuned may resolve it "
                        "from the tuning table)")
    p.add_argument("--dt-bins", type=int, default=None, dest="dt_bins",
                   help="hierarchical block time steps: number of "
                        "power-of-two per-particle dt bins (std/ve "
                        "propagators; unset = the global-dt path, 1 = "
                        "bitwise-identical to it; docs/OBSERVABILITY.md "
                        "schema v6)")
    p.add_argument("--bin-sync-every", type=int, default=None,
                   dest="bin_sync_every",
                   help="cycles between bin reassignments at the sync "
                        "substep (block-dt mode; default 1)")
    p.add_argument("--bin-resort-drift", type=float, default=None,
                   dest="bin_resort_drift",
                   help="drift-aware resort threshold: keep the current "
                        "particle order while folded-key inversions stay "
                        "under this fraction of n (block-dt mode; "
                        "default 0 = resort on any inversion)")
    p.add_argument("--tuned", default=None,
                   help="resolve engine knobs through a committed tuning "
                        "table (docs/TUNING.md): 'auto' = the repo's "
                        "TUNING_TABLE.json, or a table path; explicit "
                        "flags always win over table entries")
    p.add_argument("--imbalance-ratio", type=float, default=1.5,
                   dest="imbalance_ratio",
                   help="imbalance-watchdog threshold on max/mean of the "
                        "per-shard load/comm metrics (telemetry "
                        "'imbalance' events) [1.5]")
    p.add_argument("--drift-budget", type=float, default=None,
                   dest="drift_budget",
                   help="conservation-drift watchdog: relative "
                        "total-energy budget |etot-etot0|/|etot0| per "
                        "check window (telemetry 'drift' events; "
                        "default: report-only, no watchdog)")
    p.add_argument("--memory-profile", default=None, dest="memory_profile",
                   help="write a jax.profiler device-memory profile "
                        "(pprof) to this path at the end of the run")
    p.add_argument("--insitu", default=None,
                   help="in-situ rendering: slice | projection (the "
                        "Ascent/Catalyst adaptor role, ascent_adaptor.h). "
                        "Frames render from the in-graph snapshot ring at "
                        "the check/flush boundary — zero added host syncs "
                        "(docs/OBSERVABILITY.md schema v8)")
    p.add_argument("--insitu-every", type=int, default=1, dest="insitu_every",
                   help="render every N iterations (default 1)")
    p.add_argument("--snap", default=None,
                   help="in-graph field snapshots riding the flush "
                        "boundary: comma-separated field list (e.g. "
                        "'rho' or 'rho,temp'; observables/snapshot.py). "
                        "Emits schema-v8 snapshot events + a snapshots/ "
                        ".npz ring next to events.jsonl (or --output)")
    p.add_argument("--snap-grid", type=int, default=16, dest="snap_grid",
                   help="snapshot grid side G (G x G projection) [16]")
    p.add_argument("--snap-every", type=int, default=None,
                   dest="snap_every",
                   help="emit a snapshot frame every N iterations "
                        "[--insitu-every when --insitu is on, else 1]")
    p.add_argument("--snap-keep", type=int, default=32, dest="snap_keep",
                   help="snapshot ring capacity in .npz frames (0 = "
                        "unbounded) [32]")
    p.add_argument("--kernel", default=None,
                   help="SPH kernel family: sinc | sinc-n1-n2 | wendland-c6 "
                        "(sph_kernel_tables.hpp SphKernelType)")
    p.add_argument("--debug-checks", action="store_true", dest="debug_checks",
                   help="run the step under the checkify sanitizer "
                        "(NaN/Inf + out-of-bounds-index checks); the "
                        "first failed check per step is reported per "
                        "iteration (slow; single-device)")
    p.add_argument("--sincIndex", type=float, default=None, dest="sinc_index",
                   help="sinc kernel exponent n (default: case setting)")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    if args.cpu_mesh:
        # explicit N-virtual-device CPU mesh (the mechanism the multi-chip
        # dry run and tests use) for driving --devices N on hosts with
        # fewer real chips; must run before jax's lazy backend init
        from sphexa_tpu.util.cpu_mesh import force_cpu_mesh

        try:
            force_cpu_mesh(args.devices or 8)
        except RuntimeError as e:
            print(f"--cpu-mesh: {e}", file=sys.stderr)
            return 2

    from sphexa_tpu.init import make_initializer
    from sphexa_tpu.observables import (
        ConstantsWriter,
        make_observable,
        make_observable_spec,
    )
    from sphexa_tpu.simulation import _PROPAGATORS, Simulation

    if args.prop not in _PROPAGATORS:
        print(f"unknown --prop {args.prop!r}; available: {sorted(_PROPAGATORS)}",
              file=sys.stderr)
        return 2
    if args.avclean and args.prop not in ("ve", "turb-ve"):
        print("--avclean only applies to --prop ve | turb-ve; ignoring",
              file=sys.stderr)

    # built-in case names take precedence over same-named files, exactly
    # like make_initializer; a restart reads the snapshot ONCE, recovering
    # state, metadata and any checkpointed turbulence stirring state
    from sphexa_tpu.init import CASES, split_case_spec
    from sphexa_tpu.init.file_init import looks_like_file, parse_file_spec

    log = (lambda *a, **k: None) if args.quiet else print
    # 'case:settings.json' selects the case with overrides; observables key
    # on the bare case name (with the overrides applied to their thresholds)
    case_name, settings_path = split_case_spec(args.init)
    case_overrides = None
    if settings_path is not None:
        import json

        try:
            with open(settings_path) as f:
                case_overrides = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            print(f"cannot read settings file {settings_path}: {e}",
                  file=sys.stderr)
            return 2
        if not isinstance(case_overrides, dict):
            print(f"{settings_path} must hold a JSON object", file=sys.stderr)
            return 2
    is_restart = args.init not in CASES and looks_like_file(args.init)
    turb_state, turb_cfg, restart_iteration = None, None, 0
    chem_restored = None
    if is_restart:
        from sphexa_tpu.io.snapshot import read_snapshot_full

        state, box, const, extra, attrs = read_snapshot_full(
            *parse_file_spec(args.init)
        )
        restart_iteration = int(attrs.get("iteration", 0))
        case_name = (
            np.asarray(attrs["initCase"]).item().decode()
            if "initCase" in attrs
            else ""
        )
        if case_overrides is None and "caseSettings" in attrs:
            # threshold-bearing observables (e.g. WindBubble) must see the
            # same overrides the original run used
            import json

            case_overrides = json.loads(
                np.asarray(attrs["caseSettings"]).item().decode()
            )
        if args.prop == "std-cooling" and "chem_hi" in extra:
            from sphexa_tpu.physics.cooling import chemistry_from_fields

            chem_restored = chemistry_from_fields(extra)
        if args.prop == "turb-ve" and "turb_phases" in extra:
            # resume the OU stirring state + config (the reference
            # checkpoints phases + RNG the same way, turb_ve.hpp:88-97)
            from sphexa_tpu.sph.hydro_turb import turbulence_state_from_fields

            turb_state, turb_cfg = turbulence_state_from_fields(extra)
    else:
        if args.glass:
            from sphexa_tpu.init.glass import set_glass_template

            try:
                set_glass_template(args.glass)
            except OSError as e:
                print(f"cannot read glass template {args.glass}: {e}",
                      file=sys.stderr)
                return 2
            log(f"# tiling glass template {args.glass}")
        try:
            initializer = make_initializer(args.init)
        except ValueError as e:
            print(str(e), file=sys.stderr)
            return 2
        try:
            state, box, const = initializer(args.side)
        finally:
            if args.glass:
                set_glass_template(None)

    if args.grav_constant is not None:
        # --G overrides the case's gravitational constant (sphexa.cpp --G)
        const = _dc.replace(const, g=args.grav_constant)
    if args.sym_pairs is not None:
        # explicit pair-cutoff convention override: reference-parity
        # comparisons and continuations of dumps that predate the
        # symPairs snapshot attribute need this (README round-4 notes)
        const = _dc.replace(const, sym_pairs=(args.sym_pairs == "on"))
    if args.kernel is not None or args.sinc_index is not None:
        from sphexa_tpu.sph.kernels import KERNEL_CHOICES, kernel_norm_3d

        kind = args.kernel or const.kernel_choice
        if kind not in KERNEL_CHOICES:
            print(f"unknown --kernel {kind!r}; choices: {KERNEL_CHOICES}",
                  file=sys.stderr)
            return 2
        n = args.sinc_index if args.sinc_index is not None else const.sinc_index
        const = _dc.replace(
            const, kernel_choice=kind, sinc_index=n,
            kernel_norm=kernel_norm_3d(n, kind),
        )

    # observable selected by the test case (observables/factory.hpp:46-70) —
    # on restart, by the case name the snapshot recorded. The observable
    # object only names the constants.txt columns now: the values are
    # computed IN-GRAPH by the step's science ledger (the matching
    # ObservableSpec below), so no second reduction program and no
    # per-step device sync remain — rows survive --check-every windows
    observable = make_observable(case_name, overrides=case_overrides)
    obs_spec = make_observable_spec(case_name, overrides=case_overrides)
    if args.devices and args.devices > 1 and state.n % args.devices:
        # slab sharding needs a mesh-divisible count; trim the trailing
        # SFC rows (cases with non-cubic counts, e.g. sphere cuts, already
        # truncate at an arbitrary boundary — this moves it by < P rows)
        import jax as _jax

        n_full = state.n
        keep = (n_full // args.devices) * args.devices
        print(f"# trimming {n_full - keep} trailing particles for an "
              f"even {args.devices}-way slab decomposition", file=sys.stderr)
        trim = lambda tree: _jax.tree.map(
            lambda a: a[:keep] if getattr(a, "ndim", 0) >= 1
            and a.shape[0] == n_full else a,
            tree,
        )
        state = trim(state)
        # per-particle aux state (std-cooling chemistry) must stay
        # row-aligned with the trimmed particle arrays
        if chem_restored is not None:
            chem_restored = trim(chem_restored)
    cooling_cfg = None
    if args.prop == "std-cooling" and args.evolve_chem:
        from sphexa_tpu.physics.cooling import CoolingConfig

        cooling_cfg = CoolingConfig(gamma=const.gamma, evolve_species=True)

    # telemetry registry shared by the driver, the loop Timer and the
    # profile series; --telemetry-dir adds the persisted JSONL sink (the
    # sink-less registry costs counters only)
    from sphexa_tpu.telemetry import JsonlSink, Telemetry

    sinks = []
    recorder = None
    if args.telemetry_dir:
        sinks.append(JsonlSink(os.path.join(args.telemetry_dir,
                                            "events.jsonl")))
    telemetry = Telemetry(sinks=sinks)
    if args.telemetry_dir:
        # crash flight recorder: ring-buffer the event tail and dump
        # blackbox.json (+ a first-class ``crash`` event) on abnormal
        # exit, so a killed/OOM'd/aborted run EXPLAINS its truncated
        # events.jsonl (telemetry/flightrec.py; summary/science read it)
        from sphexa_tpu.telemetry import FlightRecorder

        recorder = FlightRecorder(args.telemetry_dir, telemetry=telemetry)
        telemetry.sinks.append(recorder.sink)
        recorder.install()

    # --snap: in-graph field snapshots riding the flush boundary
    # (observables/snapshot.py). --insitu without an explicit --snap
    # defaults to a density grid so the viz hook consumes the ring
    # instead of syncing full particle state every frame.
    snap_spec = None
    snap_every = None
    snap_dir = None
    snap_fields = None
    if args.snap:
        snap_fields = tuple(f.strip() for f in args.snap.split(",")
                            if f.strip())
    elif args.insitu:
        snap_fields = ("rho",)
    if snap_fields:
        from sphexa_tpu.observables.snapshot import SnapshotSpec

        try:
            snap_spec = SnapshotSpec(fields=snap_fields, grid=args.snap_grid)
        except ValueError as e:
            print(str(e), file=sys.stderr)
            if recorder is not None:
                recorder.close()  # usage error, not a crash: no blackbox
            return 2
        snap_every = args.snap_every or (
            args.insitu_every if args.insitu else 1)
        if args.telemetry_dir:
            snap_dir = os.path.join(args.telemetry_dir, "snapshots")
        else:
            snap_dir = os.path.join(args.out_dir, "snapshots")
    try:
        sim = Simulation(state, box, const, prop=args.prop,
                         av_clean=args.avclean and args.prop in ("ve", "turb-ve"),
                         turb_state=turb_state, turb_cfg=turb_cfg,
                         chem=chem_restored, cooling_cfg=cooling_cfg,
                         theta=args.theta,
                         m2p_cap_margin=args.m2p_cap_margin,
                         num_devices=args.devices, halo_mode=args.halo_mode,
                         backend=args.backend,
                         check_every=args.check_every,
                         dt_bins=args.dt_bins,
                         bin_sync_every=args.bin_sync_every,
                         bin_resort_drift=args.bin_resort_drift,
                         imbalance_ratio=args.imbalance_ratio,
                         obs_spec=obs_spec, science_rows=True,
                         snap_spec=snap_spec, snap_every=snap_every,
                         snap_keep=args.snap_keep, snap_dir=snap_dir,
                         drift_budget=args.drift_budget,
                         debug_checks=args.debug_checks, telemetry=telemetry,
                         tuned=args.tuned,
                         workload=case_name or args.init)
    except (NotImplementedError, ValueError) as e:
        print(str(e), file=sys.stderr)
        if recorder is not None:
            # a run that cannot even construct is an abnormal end: leave
            # a blackbox naming the cause, then disarm cleanly
            recorder.dump(reason=f"simulation construction failed: {e}")
            recorder.close()
        return 2
    if args.telemetry_dir:
        from sphexa_tpu.telemetry import emit_memory_event, write_manifest

        mesh = getattr(sim, "_mesh", None)
        recorder.manifest = write_manifest(
            args.telemetry_dir,
            config={k: v for k, v in vars(args).items()
                    if isinstance(v, (str, int, float, bool, type(None)))},
            particles=state.n,
            mesh_shape=tuple(mesh.devices.shape) if mesh is not None
            else None,
            extra={"case": case_name or args.init, "prop": args.prop,
                   # which knobs the run is actually using and why —
                   # the manifest-side half of the `tuning` event, so
                   # history/diff can attribute a perf change to a knob
                   # change (docs/TUNING.md)
                   "tuning": sim.tuning_provenance},
        )
        # manifest-point HBM snapshot: pre-compile residency (the state
        # arrays + constants), the baseline the post-compile and flush
        # snapshots are read against (docs/OBSERVABILITY.md)
        emit_memory_event(
            telemetry, "manifest",
            devices=list(mesh.devices.flat) if mesh is not None else None,
        )
        log(f"# telemetry -> {args.telemetry_dir}")
    log(f"# sphexa-tpu --init {args.init} N={state.n} prop={args.prop}")

    # resuming from a snapshot continues the iteration numbering, and an
    # integer -s is the END iteration (sphexa.cpp main-loop semantics)
    if is_restart:
        sim.iteration = restart_iteration
        log(f"# restart from iteration {sim.iteration}, t={float(state.ttot):.6g}"
            + (f" (case {case_name})" if case_name else ""))

    num_steps = int(args.stop) if float(args.stop).is_integer() else None
    target_time = None if num_steps is not None else float(args.stop)

    os.makedirs(args.out_dir, exist_ok=True)

    # -w: integer = dump every N iterations, float = every t interval
    # (arg_parser.hpp:99-118 int-vs-float dispatch, same as -s)
    dump_path = None
    w = args.write_every
    w_steps = int(w) if w > 0 and float(w).is_integer() else None
    w_time = w if w > 0 and w_steps is None else None
    next_dump_time = [float(state.ttot) + w_time] if w_time else None
    if w > 0 or args.wextra:
        # on restart, keep dumping under the ORIGINAL case's name (the
        # reference appends Step#n to the restarted file) instead of a
        # mangled snapshot-path tag that grows on every restart
        tag_src = case_name if (is_restart and case_name) else args.init
        case_tag = "".join(c if c.isalnum() else "_" for c in tag_src)
        ext = "txt" if args.ascii else "h5"
        dump_path = f"{args.out_dir}/dump_{case_tag}.{ext}"
        # drop leftovers of a previous run (would interleave old steps);
        # a restart instead APPENDS new Step#n groups to the existing dump
        import glob as _glob

        if args.ascii:
            stale = _glob.glob(f"{args.out_dir}/dump_{case_tag}_it*.txt")
        elif not is_restart:
            # base file AND any sharded part files (a leftover part set
            # from a previous run — possibly with a DIFFERENT device
            # count — would be appended to / concatenated with new parts)
            from sphexa_tpu.io.snapshot import _find_parts

            stale = ([dump_path] if os.path.exists(dump_path) else [])
            stale += _find_parts(dump_path)
        else:
            stale = []
        for f in stale:
            print(f"# removing stale {f}", file=sys.stderr)
            os.remove(f)

    want_fields = [f for f in args.out_fields.split(",") if f]

    # --wextra: one-shot triggers, integers = iterations, floats = sim
    # times (arg_parser.hpp isExtraOutputStep)
    wextra_steps, wextra_times = set(), []
    for tok in (t for t in args.wextra.split(",") if t):
        try:
            val = float(tok)
        except ValueError:
            print(f"--wextra: cannot parse {tok!r} (expected comma-separated "
                  "integers or floats)", file=sys.stderr)
            if recorder is not None:
                recorder.close()  # usage error, not a crash: no blackbox
            return 2
        if val.is_integer() and "." not in tok:
            wextra_steps.add(int(val))
        else:
            wextra_times.append(val)
    wextra_times.sort()

    constants_path = f"{args.out_dir}/constants.txt"
    if not is_restart and os.path.exists(constants_path):
        print(f"# truncating stale {constants_path}", file=sys.stderr)
        os.remove(constants_path)
    constants = ConstantsWriter(
        constants_path, observable,
        restart_iteration=restart_iteration if is_restart else None,
    )

    def write_science_rows():
        """Drain the verified in-graph ledger rows into constants.txt —
        one row per step (deferred windows land whole at their flush
        boundary, so --check-every N loses no science). The scalars were
        fetched at the Simulation's existing check boundary: writing
        them is pure host I/O, no device sync."""
        rows = sim.drain_science()
        for r in rows:
            vals = [r["it"], r["t"], r["dt"], r["etot"], r["ecin"],
                    r["eint"], r["egrav"]]
            if "extra" in r:
                vals.append(r["extra"])
            constants.write_row(vals)
        return rows

    def output_fields():
        from sphexa_tpu.analysis import compute_output_fields

        pipeline = "ve" if args.prop in ("ve", "turb-ve") else "std"
        return compute_output_fields(sim.state, sim.box, sim._cfg,
                                     pipeline=pipeline)

    last_dump_iteration = [None]

    def dump_now(it):
        """Write one output (restartable HDF5 snapshot, or ASCII columns
        with --ascii); derived fields are recomputed like the reference's
        saveFields pass, consistently with the active propagator."""
        last_dump_iteration[0] = it
        extra = output_fields()
        if want_fields:
            unknown = [f for f in want_fields if f not in extra]
            if unknown:
                print(f"# -f fields not available, skipped: {unknown}",
                      file=sys.stderr)
            extra = {k: v for k, v in extra.items() if k in want_fields}

        if args.ascii:
            from sphexa_tpu.io import write_ascii
            from sphexa_tpu.io.snapshot import CONSERVED_FIELDS

            cols = {f: np.asarray(getattr(sim.state, f)) for f in CONSERVED_FIELDS}
            cols.update(extra)
            path = dump_path.replace(".txt", f"_it{it}.txt")
            write_ascii(path, cols)
            log(f"# wrote ASCII dump -> {path} (not restartable)")
            return

        from sphexa_tpu.io import write_snapshot
        from sphexa_tpu.io.snapshot import write_snapshot_sharded

        if sim.turb_state is not None:
            from sphexa_tpu.sph.hydro_turb import turbulence_state_to_fields

            extra = {
                **extra,
                **turbulence_state_to_fields(sim.turb_state, sim.turb_cfg),
            }
        if sim.chem is not None:
            from sphexa_tpu.physics.cooling import chemistry_to_fields

            extra = {**extra, **chemistry_to_fields(sim.chem)}
        # on a mesh, dump file-per-shard (no global gather — the
        # reference's parallel MPI-IO role); restart reads the base path
        writer = (write_snapshot_sharded
                  if getattr(sim, "_mesh", None) is not None
                  else write_snapshot)
        step = writer(
            dump_path, sim.state, sim.box, const, iteration=it,
            extra_fields=extra, case=case_name,
            case_settings=case_overrides,
        )
        log(f"# wrote Step#{step} -> {dump_path}")

    def maybe_dump(it):
        """-w schedule + --wextra one-shot triggers."""
        if dump_path is None:
            return
        t_now = float(sim.state.ttot)
        due = (w_steps is not None and it % w_steps == 0) or (
            next_dump_time is not None and t_now >= next_dump_time[0]
        )
        if it in wextra_steps:
            due = True
        while wextra_times and t_now >= wextra_times[0]:
            wextra_times.pop(0)
            due = True
        if not due:
            return
        if next_dump_time is not None:
            # catch up across multi-interval steps: one dump, schedule
            # advanced past t_now (not one redundant dump per interval)
            while t_now >= next_dump_time[0]:
                next_dump_time[0] += w_time
        dump_now(it)

    from sphexa_tpu.util.timer import ProfileRecorder, Timer

    timer = Timer(telemetry=telemetry)
    # in-situ viz adaptor: init before the loop, execute per iteration,
    # finalize after (sphexa.cpp:141-142,172,179 hook points)
    insitu = None
    if args.insitu:
        from sphexa_tpu.viz import InsituViz

        try:
            insitu = InsituViz(args.out_dir, mode=args.insitu,
                               every=args.insitu_every)
        except ValueError as e:
            print(str(e), file=sys.stderr)
            if recorder is not None:
                recorder.close()  # usage error, not a crash: no blackbox
            return 2
        insitu.init()

    def consume_snapshots():
        """Feed the in-graph snapshot ring into the viz hook. The frames
        were deposited inside the step and landed at the existing check/
        flush boundary (sim._emit_snapshot), so rendering here is pure
        host pixel work — no device sync, no full-state fetch (the old
        insitu.execute path pulled every particle array per frame)."""
        for fit, fpath in sim.drain_snapshots():
            if insitu is None:
                continue
            try:
                with np.load(fpath, allow_pickle=False) as z:
                    grid = np.asarray(z["grid"])
            except (OSError, ValueError, KeyError):
                continue  # frame pruned from the ring / partial write
            insitu.execute_grid(grid, fit)

    profile = ProfileRecorder()
    t0 = time.time()
    it0 = sim.iteration
    nan = float("nan")
    if args.trace_dir:
        # whole-run profiler capture: the TraceAnnotation scopes the
        # Simulation emits (sphexa:launch/flush/reconfigure/rebuild-lists)
        # name the spans inside this trace
        import jax as _jax

        os.makedirs(args.trace_dir, exist_ok=True)
        _jax.profiler.start_trace(args.trace_dir)
        telemetry.event("trace", dir=args.trace_dir)
    try:
        while True:
            timer.start()
            d = sim.step()
            timer.step("step")
            it = sim.iteration
            if args.debug_checks and d.get("check_error"):
                print(f"# debug-checks it {it}: {d['check_error']}",
                      file=sys.stderr)
            if d.get("deferred"):
                # mid-window step (--check-every > 1): NO device->host
                # sync may happen here — observables/constants would
                # fetch state scalars and defeat the deferred window, so
                # they run at check boundaries only (the flush emits the
                # window's telemetry). -s (iterations) and --duration
                # are pure host arithmetic and still apply; a -s TIME
                # target needs state.ttot and so only fires at check
                # boundaries
                timer.pop()
                log(f"it {it:5d}  (deferred check)")
                if num_steps is not None and it >= num_steps:
                    break
                if args.duration is not None \
                        and time.time() - t0 >= args.duration:
                    log(f"# wall-clock limit {args.duration}s reached "
                        f"at iteration {it}")
                    sim.flush()  # verify + land the window's rows
                    write_science_rows()
                    if dump_path is not None \
                            and last_dump_iteration[0] != it:
                        dump_now(it)
                    break
                continue
            rows = write_science_rows()
            timer.step("observables")
            maybe_dump(it)  # dumps recompute the full derived set (r, p, u, ...)
            consume_snapshots()  # ring frames -> PNG (when --insitu)
            timer.step("output")
            laps = timer.pop()
            telemetry.event(
                "phases", it=it, **{k: round(v, 6) for k, v in laps.items()}
            )
            if args.profile:
                profile.record(it, laps, dt=float(d.get("dt", nan)),
                               nc_mean=float(d.get("nc_mean", nan)))
            r = rows[-1] if rows else {}
            extra_cols = " ".join(
                f"{n}={v:.4g}" for n, v in zip(
                    observable.extra_columns,
                    [r["extra"]] if "extra" in r else [])
            )
            log(
                f"it {it:5d}  t={r.get('t', nan):.6g} "
                f"dt={float(d.get('dt', nan)):.4g} "
                f"etot={r.get('etot', nan):.6f} "
                f"ecin={r.get('ecin', nan):.4g} "
                f"eint={r.get('eint', nan):.4g} "
                f"nc~{float(d.get('nc_mean', nan)):.0f}"
                + (f" {extra_cols}" if extra_cols else "")
            )
            if num_steps is not None and it >= num_steps:
                break
            if target_time is not None and float(sim.state.ttot) >= target_time:
                break
            if args.duration is not None and time.time() - t0 >= args.duration:
                # graceful wall-clock cutoff with a final restartable dump
                # (sphexa.cpp:153-173 --duration semantics)
                log(f"# wall-clock limit {args.duration}s reached at iteration {it}")
                if dump_path is not None and last_dump_iteration[0] != it:
                    dump_now(it)
                break
    finally:
        if args.trace_dir:
            _jax.profiler.stop_trace()
            log(f"# profiler trace -> {args.trace_dir}")
            # in-run phase attribution (schema v4): aggregate the capture
            # by sphexa/<phase> scope right here so the run record itself
            # carries the per-phase device-time table (`sphexa-telemetry
            # trace <dir>` re-renders it offline); a failed parse must
            # never take the run down with it
            try:
                from sphexa_tpu.telemetry.traceview import (
                    phase_attr_digest,
                    summarize_trace,
                )

                s = summarize_trace(args.trace_dir, top=3)
                telemetry.event("phase_attr", dir=args.trace_dir,
                                **phase_attr_digest(s))
                log("# phase attribution: "
                    + " ".join(f"{p['phase']}={p['share']:.0%}"
                               for p in s["phases"][:5])
                    + f" (coverage {s['coverage']:.0%})")
            except Exception as e:
                print(f"# trace attribution failed: {e}", file=sys.stderr)
    # drain any open deferred window (--check-every > 1, -s not a
    # multiple): the state must be verified before the final report, the
    # telemetry window/flush events must land (Simulation.run's trailing
    # flush, mirrored) and the window's constants.txt rows with them
    sim.flush()
    write_science_rows()
    consume_snapshots()  # frames landed by the trailing flush
    dt_wall = time.time() - t0
    n_done = sim.iteration - it0
    if args.profile:
        profile_path = f"{args.out_dir}/profile.npz"
        # per-substep breakdown (the reference's per-phase Timer print,
        # util/timer.hpp): an equivalent SPLIT execution of the final
        # state, timed stage by stage (the fused production step has no
        # internal walls — its fusion is the design); skipped when there
        # is no series to attach it to
        from sphexa_tpu.util.substep_profile import substep_breakdown

        sub = substep_breakdown(sim, telemetry=telemetry) if profile.rows \
            else {}
        if sub:
            log("# substeps (s, split-execution upper bound): "
                + " ".join(f"{k}={v:.4f}" for k, v in sub.items()))
        if profile.save(profile_path, substeps=sub):
            means = profile.summary()
            log("# profile (mean s/iter): "
                + " ".join(f"{k}={v:.4f}" for k, v in means.items()
                           if k in ("step", "observables", "output")))
            log(f"# timing series -> {profile_path}")
        else:
            print("# --profile: no iterations recorded, profile.npz not "
                  "written", file=sys.stderr)
    if insitu is not None:
        log(f"# insitu: {insitu.finalize()} frames -> {args.out_dir}")
    if args.memory_profile:
        from sphexa_tpu.telemetry import save_memory_profile

        if save_memory_profile(args.memory_profile):
            log(f"# device-memory profile -> {args.memory_profile}")
        else:
            print("# --memory-profile: profiler unavailable, no dump "
                  "written", file=sys.stderr)
    telemetry.event("run_end", iterations=n_done, wall_s=round(dt_wall, 3))
    telemetry.close()
    if recorder is not None:
        recorder.close()  # clean exit: disarm the crash hooks, no blackbox
    log(f"# {n_done} iterations in {dt_wall:.2f}s "
        f"({state.n * n_done / dt_wall / 1e6:.3f}M particle-updates/s)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
