"""Crash flight recorder: a bounded in-memory event tail + abnormal-exit
hooks that dump ``blackbox.json``.

A crash — OOM kill, NaN abort, an unhandled exception, the tier-1
wall-clock kill — used to silently drop the un-flushed telemetry tail:
``events.jsonl`` ends mid-run and the summary can only *tolerate* the
truncation, not explain it. The recorder keeps the last-K events in a
ring buffer (one more sink on the registry — zero device access, the
telemetry hot-loop contract) and registers ``sys.excepthook`` /
``atexit`` / SIGTERM-class signal handlers plus ``faulthandler``; on
abnormal exit it writes ``<run-dir>/blackbox.json`` (reason, traceback,
the buffered event tail, watchdog counters, manifest) and appends one
first-class ``crash`` event (schema v4) to ``events.jsonl`` so the
stream itself records why it ends. ``sphexa-telemetry summary/science``
pick the blackbox up and explain crash-truncated runs.

A clean run never writes a blackbox: ``close()`` disarms the hooks (the
app calls it after ``run_end``). A SIGKILL/OOM-kill leaves no window to
run anything — the ring buffer cannot help there, but ``faulthandler``
still covers hard faults (segfault/abort) via ``fault.log``.

Deliberately jax-free, like the rest of the persistence layer.
"""

import atexit
import datetime
import faulthandler
import json
import os
import signal
import sys
import traceback
from collections import deque
from typing import Dict, Optional

#: blackbox.json schema (independent of the event schema)
BLACKBOX_SCHEMA = 1

#: counters worth replaying in the blackbox: the watchdog/health state
#: at the moment of death (the question a crash report must answer
#: first: was the run already sick?)
WATCHDOG_COUNTERS = ("retraces", "rollbacks", "reconfigures", "halo_trips",
                     "imbalances", "drifts", "field_health")

#: signals that mean "this run is being terminated" (SIGKILL cannot be
#: caught; SIGINT raises KeyboardInterrupt and rides the excepthook)
_SIGNALS = ("SIGTERM", "SIGHUP", "SIGQUIT", "SIGABRT")


class RingSink:
    """Bounded event tail (newest last). A sink like any other — the
    registry emits fully-materialized dicts, so buffering K of them
    costs K small dicts and nothing else."""

    def __init__(self, capacity: int = 200):
        self.events = deque(maxlen=int(capacity))

    def emit(self, event: dict) -> None:
        self.events.append(event)

    def close(self) -> None:
        pass


class FlightRecorder:
    """Owns the ring sink + the abnormal-exit hooks for one run dir.

    Usage (app/main.py wiring)::

        rec = FlightRecorder(run_dir, telemetry=tel, manifest=manifest)
        tel.sinks.append(rec.sink)
        rec.install()
        ...  # the run
        rec.close()   # clean exit: disarm, no blackbox
    """

    def __init__(self, run_dir: str, capacity: int = 200,
                 telemetry=None, manifest: Optional[Dict] = None):
        self.run_dir = run_dir
        self.sink = RingSink(capacity)
        self.telemetry = telemetry
        self.manifest = manifest
        self._installed = False
        self._closed = False
        self._dumped = False
        self._prev_excepthook = None
        self._prev_signals: Dict[int, object] = {}
        self._fault_file = None

    # -- hook management ---------------------------------------------------
    def install(self) -> "FlightRecorder":
        """Arm excepthook + atexit + signal handlers + faulthandler.
        Idempotent; safe to call in processes that already hook signals
        (previous handlers are chained, not clobbered)."""
        if self._installed:
            return self
        self._installed = True
        os.makedirs(self.run_dir, exist_ok=True)
        self._prev_excepthook = sys.excepthook
        sys.excepthook = self._on_exception
        atexit.register(self._on_atexit)
        for name in _SIGNALS:
            sig = getattr(signal, name, None)
            if sig is None:
                continue
            try:
                # a deliberately-ignored signal (nohup's SIGHUP) stays
                # ignored: hooking it would fabricate a crash record in
                # a run that then survives and finishes clean
                if signal.getsignal(sig) is signal.SIG_IGN:
                    continue
                self._prev_signals[sig] = signal.signal(sig, self._on_signal)
            except (ValueError, OSError):  # non-main thread / exotic host
                continue
        try:
            self._fault_file = open(
                os.path.join(self.run_dir, "fault.log"), "w")
            faulthandler.enable(self._fault_file)
        except (OSError, ValueError):
            self._fault_file = None
        return self

    def close(self) -> None:
        """Clean shutdown: disarm every hook; no blackbox is written.
        An already-written blackbox (a caught signal the run survived)
        is left in place — it happened, the record stands."""
        self._closed = True
        if not self._installed:
            return
        self._installed = False
        if self._prev_excepthook is not None:
            sys.excepthook = self._prev_excepthook
        atexit.unregister(self._on_atexit)
        for sig, prev in self._prev_signals.items():
            try:
                # None = the previous handler lived at the C level;
                # SIG_DFL is the closest restorable state
                signal.signal(sig, signal.SIG_DFL if prev is None else prev)
            except (ValueError, OSError, TypeError):
                pass
        self._prev_signals.clear()
        if self._fault_file is not None:
            try:
                faulthandler.disable()
                self._fault_file.close()
                # nothing faulted: don't leave an empty fault.log in
                # every clean run dir
                path = os.path.join(self.run_dir, "fault.log")
                if os.path.exists(path) and os.path.getsize(path) == 0:
                    os.remove(path)
            except (OSError, ValueError):
                pass
            self._fault_file = None

    # -- hook bodies -------------------------------------------------------
    def _on_exception(self, exc_type, exc, tb) -> None:
        self.dump(
            reason=f"exception {exc_type.__name__}: {exc}",
            tb="".join(traceback.format_exception(exc_type, exc, tb)),
        )
        if self._prev_excepthook is not None:
            self._prev_excepthook(exc_type, exc, tb)

    def _on_signal(self, signum, frame) -> None:
        name = signal.Signals(signum).name
        stack = "".join(traceback.format_stack(frame)) if frame else ""
        self.dump(reason=f"signal {name} ({signum})", tb=stack)
        # restore + re-raise so the process dies with the conventional
        # 128+N status the caller (driver, scheduler) keys on. A None
        # previous handler (installed at the C level — signal.signal
        # cannot restore it) maps to SIG_DFL: re-killing with OUR
        # handler still installed would loop forever
        prev = self._prev_signals.get(signum, signal.SIG_DFL)
        if prev is None:
            prev = signal.SIG_DFL
        try:
            signal.signal(signum, prev)
        except (ValueError, OSError, TypeError):
            pass
        if callable(prev) and prev not in (signal.SIG_IGN, signal.SIG_DFL):
            prev(signum, frame)
        else:
            os.kill(os.getpid(), signum)

    def _on_atexit(self) -> None:
        if not self._closed:
            # interpreter exiting without close(): sys.exit() from a
            # depth the run loop never unwound, or an exit path that
            # skipped the clean shutdown — record it
            self.dump(reason="abnormal-exit (no clean close before "
                             "interpreter shutdown)")

    # -- the dump ----------------------------------------------------------
    def dump(self, reason: str, tb: str = "") -> Optional[str]:
        """Write ``blackbox.json`` (once — the FIRST cause wins; a
        signal-then-atexit cascade must not overwrite the signal's
        record) and append one ``crash`` event to ``events.jsonl``."""
        if self._dumped:
            return None
        self._dumped = True
        from sphexa_tpu.telemetry.registry import SCHEMA_VERSION

        counters = {}
        if self.telemetry is not None:
            counters = {k: int(self.telemetry.counters.get(k, 0))
                        for k in WATCHDOG_COUNTERS}
            counters["events_total"] = int(sum(
                n for k, n in self.telemetry.counters.items()
                if k.startswith("events.")))
        fault_log = os.path.join(self.run_dir, "fault.log")
        box = {
            "schema": BLACKBOX_SCHEMA,
            "created": datetime.datetime.now(
                datetime.timezone.utc).isoformat(),
            "reason": reason,
            "traceback": tb,
            "watchdogs": counters,
            "events": list(self.sink.events),
            "manifest": self.manifest,
            "fault_log": "fault.log" if os.path.exists(fault_log) else None,
        }
        path = os.path.join(self.run_dir, "blackbox.json")
        try:
            os.makedirs(self.run_dir, exist_ok=True)
            with open(path, "w") as f:
                json.dump(box, f, indent=2, default=str)
                f.write("\n")
        except OSError:
            return None
        # the crash as a first-class event in the stream itself: append
        # directly (the JsonlSink's handle may be gone mid-teardown; a
        # line-append on our own fd is the crash-safe move)
        events_path = os.path.join(self.run_dir, "events.jsonl")
        if os.path.exists(events_path):
            try:
                # continue the run's real seq (monotone-per-run envelope
                # contract): the ring holds the newest events, so the
                # last buffered seq + 1 IS the next one the registry
                # would have assigned
                seq = (int(self.sink.events[-1].get("seq", -1)) + 1
                       if self.sink.events else 0)
                evt = {"v": SCHEMA_VERSION, "seq": seq,
                       "t": round(__import__("time").time(), 6),
                       "kind": "crash", "reason": reason}
                with open(events_path, "a") as f:
                    f.write(json.dumps(evt, separators=(",", ":")) + "\n")
            except OSError:
                pass
        return path


def read_blackbox(run_dir: str) -> Optional[Dict]:
    """The run's blackbox, or None. Unreadable/corrupt boxes (the dump
    itself was interrupted) degrade to a stub naming the problem — a
    crash report must never crash the reader."""
    path = os.path.join(run_dir, "blackbox.json")
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        return {"schema": None, "reason": f"unreadable blackbox ({e})",
                "traceback": "", "events": [], "watchdogs": {}}
