"""Offline per-phase attribution of a ``jax.profiler`` trace capture.

A ``--trace-dir`` capture used to be an anonymous wall of ``fusion.N``
ops nobody could attribute to tree-build vs neighbors vs force vs
gravity vs exchange. The step programs now wrap every major stage in
``jax.named_scope("sphexa/<phase>")`` (propagator.py, gravity/, sph/,
parallel/exchange.py — the taxonomy lives in util/phases.py and
docs/OBSERVABILITY.md), so XLA op *metadata* carries the phase. This
module turns a finished capture back into the per-phase device-time
table the reference lineage's optimization story is written in (the
Bédorf et al. 2014 per-phase breakdowns; SPH-EXA's own ``Timer``).

A capture session holds two artifacts:

- ``*.xplane.pb`` — the xprof XSpace proto: per-op execution events
  (``hlo_op``/``hlo_module`` stats + picosecond durations) AND the
  serialized HLO modules whose instruction metadata carries the
  ``op_name`` scope path (``jit(step)/.../sphexa/density/...``). This
  is the PRIMARY source: it is complete.
- ``*.trace.json.gz`` — the perfetto dump of the same events, capped
  (~1M events; a python-tracer-heavy capture floods the cap and drops
  the device ops). Used as a FALLBACK when no xplane sidecar exists.

Both are read with a ~80-line generic protobuf wire-format walker — no
tensorflow/xprof dependency, so attribution of a chip capture runs
anywhere: this CPU container today, the chip host the day it arrives
(``sphexa-telemetry trace <dir>``). Deliberately jax-free
(telemetry/cli.py contract).
"""

import glob
import gzip
import json
import os
import re
from collections import defaultdict
from typing import Dict, List, Optional, Tuple

#: phase extraction from an op_name metadata path: the FIRST
#: ``sphexa/<phase>`` segment (in-repo scopes nest specific-inside-
#: coarse only where both name the same stage family, so first wins)
PHASE_RE = re.compile(r"sphexa/([A-Za-z0-9_.:+-]+)")

#: trace-event args fields that may carry a scope path directly (TPU
#: device planes export these; the CPU runtime only exports hlo_op)
_SCOPE_ARGS = ("long_name", "tf_op", "op_name")


class TraceError(Exception):
    """Unreadable/absent capture (CLI exit code 2)."""


# ---------------------------------------------------------------------------
# protobuf wire-format primitives (no schema compile)
# ---------------------------------------------------------------------------


def _varint(data: bytes, i: int) -> Tuple[int, int]:
    shift = result = 0
    while True:
        b = data[i]
        i += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, i
        shift += 7


def _fields(data: bytes, start: int, end: int):
    """One message body as (field, wire, varint|span) records; raises
    ValueError/IndexError on non-message bytes (callers probe-and-skip)."""
    i = start
    out = []
    while i < end:
        key, i = _varint(data, i)
        f, wire = key >> 3, key & 7
        if wire == 0:
            v, i = _varint(data, i)
            out.append((f, 0, v))
        elif wire == 1:
            out.append((f, 1, (i, i + 8)))
            i += 8
        elif wire == 5:
            out.append((f, 5, (i, i + 4)))
            i += 4
        elif wire == 2:
            ln, i = _varint(data, i)
            if i + ln > end:
                raise ValueError("length-delimited field overruns message")
            out.append((f, 2, (i, i + ln)))
            i += ln
        else:
            raise ValueError(f"unsupported wire type {wire}")
    return out


def _ascii(data: bytes, span) -> Optional[str]:
    try:
        s = data[span[0]:span[1]].decode()
    except UnicodeDecodeError:
        return None
    return s if s and all(32 <= ord(c) < 127 for c in s) else None


def _map_entry(data: bytes, span):
    """(key:int, value_span) of one proto map<int64, Msg> entry."""
    k, vspan = None, None
    for f, w, v in _fields(data, *span):
        if f == 1 and w == 0:
            k = v
        elif f == 2 and w == 2:
            vspan = v
    return k, vspan


# ---------------------------------------------------------------------------
# HLO instruction metadata: {instr_name -> op_name scope path}
# ---------------------------------------------------------------------------


def _instr_record(data: bytes, fields) -> Optional[dict]:
    """{name, op_name?, called} when this message walks like an
    xla.HloInstructionProto: name (f1) + opcode (f2, a short slash-free
    token — the discriminator against xla.OpMetadata, whose f2 op_name
    is a scope path), optional metadata.op_name (f7.f2) and
    called_computation_ids (f38, bare or packed varints)."""
    f1 = [s for f, w, s in fields if f == 1 and w == 2]
    f2 = [s for f, w, s in fields if f == 2 and w == 2]
    if not f1 or not f2:
        return None
    name = _ascii(data, f1[0])
    opcode = _ascii(data, f2[0])
    if (name is None or opcode is None or len(opcode) > 24
            or "/" in opcode or "(" in opcode):
        return None
    rec = {"name": name, "op_name": None, "called": []}
    for f, w, v in fields:
        if f == 7 and w == 2:  # metadata: xla.OpMetadata
            try:
                meta = _fields(data, *v)
            except (ValueError, IndexError):
                continue
            op = [s for mf, mw, s in meta if mf == 2 and mw == 2]
            if op:
                rec["op_name"] = _ascii(data, op[0])
        elif f == 38 and w == 0:  # called_computation_ids, bare
            rec["called"].append(v)
        elif f == 38 and w == 2:  # packed
            i = v[0]
            try:
                while i < v[1]:
                    cid, i = _varint(data, i)
                    rec["called"].append(cid)
            except IndexError:
                pass
    return rec


def _scan_hlo(data: bytes, start: int, end: int, instrs: List[dict],
              comps: Dict[int, List[dict]]):
    """Recursively harvest HLO instruction records AND group them by
    their enclosing computation (HloComputationProto: instrs in f2,
    computation id in f5) — the blobs embed whole serialized modules."""
    try:
        fields = _fields(data, start, end)
    except (ValueError, IndexError):
        return
    # computation-shaped message? its f2 children parse as instrs
    children = []
    comp_id = next((v for f, w, v in fields if f == 5 and w == 0), None)
    for f, w, span in fields:
        if f == 2 and w == 2 and span[1] - span[0] > 8:
            try:
                rec = _instr_record(data, _fields(data, *span))
            except (ValueError, IndexError):
                rec = None
            if rec is not None:
                children.append(rec)
    if children:
        instrs.extend(children)
        if comp_id is not None:
            comps.setdefault(comp_id, []).extend(children)
    for f, w, span in fields:
        if w == 2 and span[1] - span[0] > 8 and not (f == 2 and children):
            _scan_hlo(data, span[0], span[1], instrs, comps)


def _resolve_scopes(instrs: List[dict],
                    comps: Dict[int, List[dict]]) -> Dict[str, str]:
    """{instr_name: op_name}: own metadata first; instructions the
    optimizer rebuilt WITHOUT metadata (cumsum -> reduce-window, late
    rewrites) inherit the first attributed op of a computation they
    call — the reduction/comparator subcomputation keeps the original
    scope path when the calling op loses it."""
    comp_scope: Dict[int, Optional[str]] = {}
    for cid, recs in comps.items():
        comp_scope[cid] = next(
            (r["op_name"] for r in recs if r["op_name"]), None)
    out: Dict[str, str] = {}
    for r in instrs:
        op_name = r["op_name"]
        if not op_name:
            op_name = next(
                (comp_scope.get(c) for c in r["called"]
                 if comp_scope.get(c)), None)
        if op_name:
            out[r["name"]] = op_name
    return out


# ---------------------------------------------------------------------------
# xplane.pb: op events + scope maps in one pass
# ---------------------------------------------------------------------------


def parse_xplane(path: str) -> Tuple[Dict[str, Dict[str, str]], List[dict]]:
    """(scope_maps, op_events) from one XSpace proto.

    scope_maps: {module_name: {instr_name: op_name}} harvested from the
    embedded HLO modules (metadata-plane entries named
    ``<module>(<program_id>)``; ``""`` holds the merged fallback).
    op_events: [{op, module, dur_us}] — every XEvent carrying an
    ``hlo_op`` stat (op/module are interned stat-metadata refs; the
    xprof trace viewer renders these same events as the perfetto
    dump's device-op rows)."""
    with open(path, "rb") as f:
        data = f.read()
    maps: Dict[str, Dict[str, str]] = defaultdict(dict)
    events: List[dict] = []
    try:
        top = _fields(data, 0, len(data))
    except (ValueError, IndexError):
        raise TraceError(f"{path}: not an xplane proto")
    for f, w, span in top:
        if f != 1 or w != 2:  # XSpace.planes
            continue
        try:
            plane = _fields(data, *span)
        except (ValueError, IndexError):
            continue
        # pass 1: this plane's interned metadata tables
        stat_names: Dict[int, str] = {}   # XStatMetadata id -> name
        for pf, pw, pspan in plane:
            if pw != 2 or pf not in (4, 5):
                continue
            try:
                k, vspan = _map_entry(data, pspan)
            except (ValueError, IndexError):
                continue
            if vspan is None:
                continue
            try:
                md = _fields(data, *vspan)
            except (ValueError, IndexError):
                continue
            names = [_ascii(data, s) for f2, w2, s in md
                     if f2 == 2 and w2 == 2]
            name = names[0] if names and names[0] else ""
            kid = k
            if kid is None:  # id also lives in the metadata msg (field 1)
                ids = [v for f2, w2, v in md if f2 == 1 and w2 == 0]
                kid = ids[0] if ids else None
            if pf == 5:
                if kid is not None:
                    stat_names[kid] = name
            else:
                # module entries ("<module>(<id>)") embed the HLO proto:
                # harvest instruction op_name metadata (+ computation
                # inheritance for optimizer-rebuilt metadata-less ops)
                m = re.match(r"(.+)\((\d+)\)$", name)
                instrs: List[dict] = []
                comps: Dict[int, List[dict]] = {}
                _scan_hlo(data, vspan[0], vspan[1], instrs, comps)
                found = _resolve_scopes(instrs, comps)
                if found:
                    module = m.group(1) if m else ""
                    maps[module].update(found)
                    if module:
                        maps[""].update(found)
        if not stat_names:
            continue
        hlo_op_ids = {i for i, n in stat_names.items() if n == "hlo_op"}
        hlo_mod_ids = {i for i, n in stat_names.items()
                       if n == "hlo_module"}
        if not hlo_op_ids:
            continue
        # pass 2: line events with an hlo_op stat = device-op samples
        for pf, pw, pspan in plane:
            if pf != 3 or pw != 2:  # XPlane.lines
                continue
            try:
                line = _fields(data, *pspan)
            except (ValueError, IndexError):
                continue
            for lf, lw, lspan in line:
                if lf != 4 or lw != 2:  # XLine.events
                    continue
                try:
                    ev = _fields(data, *lspan)
                except (ValueError, IndexError):
                    continue
                dur_ps = 0
                op = module = None
                for ef, ew, v in ev:
                    if ef == 3 and ew == 0:
                        dur_ps = v
                    elif ef == 4 and ew == 2:  # XEvent.stats
                        try:
                            st = _fields(data, *v)
                        except (ValueError, IndexError):
                            continue
                        smid = next((sv for sf, sw, sv in st
                                     if sf == 1 and sw == 0), None)
                        ref = next((sv for sf, sw, sv in st
                                    if sf == 7 and sw == 0), None)
                        if smid in hlo_op_ids and ref is not None:
                            op = stat_names.get(ref)
                        elif smid in hlo_mod_ids and ref is not None:
                            module = stat_names.get(ref)
                if op:
                    # events WITHOUT an hlo_op stat are host TraceMe
                    # spans — not device time, skipped
                    events.append({
                        "op": op,
                        "module": module or "",
                        "dur_us": dur_ps / 1e6,
                    })
    return dict(maps), events


# ---------------------------------------------------------------------------
# trace.json.gz fallback (no xplane sidecar in the dir)
# ---------------------------------------------------------------------------


def load_op_events(trace_json_path: str) -> List[dict]:
    """Device-op execution samples of one perfetto dump:
    {op, module, dur_us, scope?} per complete ("X") event that names an
    HLO op. NOTE the dump is event-capped upstream (~1M) — a
    python-tracer-heavy capture can flood device ops out of it, which
    is why the xplane is the primary source."""
    try:
        with gzip.open(trace_json_path, "rt") as f:
            trace = json.load(f)
    except (OSError, json.JSONDecodeError, EOFError) as e:
        raise TraceError(f"{trace_json_path}: unreadable trace ({e})")
    out = []
    for e in trace.get("traceEvents", []):
        if e.get("ph") != "X" or not isinstance(e.get("dur"), (int, float)):
            continue
        args = e.get("args") or {}
        op = args.get("hlo_op")
        if not op:
            continue
        ev = {"op": str(op), "module": str(args.get("hlo_module", "")),
              "dur_us": float(e["dur"])}
        for k in _SCOPE_ARGS:  # TPU planes may carry the path directly
            v = args.get(k)
            if isinstance(v, str) and "sphexa/" in v:
                ev["scope"] = v
                break
        out.append(ev)
    return out


def find_capture(trace_dir: str) -> Tuple[List[str], List[str]]:
    """(xplane_paths, trace_json_paths) under a --trace-dir; newest
    capture session only (a dir can hold several timestamped sessions).
    Bare dirs with the files dropped in directly (the committed test
    fixture's shape) work too."""
    sessions = sorted(glob.glob(
        os.path.join(trace_dir, "plugins", "profile", "*")))
    roots = sessions[-1:] if sessions else [trace_dir]
    xplanes: List[str] = []
    traces: List[str] = []
    for root in roots:
        xplanes += sorted(glob.glob(os.path.join(root, "**", "*.xplane.pb"),
                                    recursive=True))
        traces += sorted(glob.glob(os.path.join(root, "**",
                                                "*.trace.json.gz"),
                                   recursive=True))
    if not xplanes and not traces:
        raise TraceError(f"no *.xplane.pb / *.trace.json.gz under "
                         f"{trace_dir} — was the run started with "
                         f"--trace-dir?")
    return xplanes, traces


# ---------------------------------------------------------------------------
# attribution
# ---------------------------------------------------------------------------


def _phase_of(op_name: Optional[str]) -> Optional[str]:
    if not op_name:
        return None
    m = PHASE_RE.search(op_name)
    return m.group(1) if m else None


def _base(op: str) -> str:
    """'reduce-window.47' -> 'reduce-window' (the CPU runtime sometimes
    reports a thunk under the suffixless base name)."""
    head, _, tail = op.rpartition(".")
    return head if head and tail.isdigit() else op


def _base_phases(m: Dict[str, str]) -> Dict[str, Optional[str]]:
    """base op name -> phase, ONLY where every instr sharing the base
    agrees (an ambiguous base attributes nothing rather than guessing)."""
    out: Dict[str, Optional[str]] = {}
    for name, op_name in m.items():
        b = _base(name)
        p = _phase_of(op_name)
        if b in out and out[b] != p:
            out[b] = None
        else:
            out[b] = p
    return out


def summarize_trace(trace_dir: str, top: int = 8) -> Dict:
    """Aggregate one capture into the per-phase attribution summary.

    ``coverage`` = attributed device-op time / total device-op time —
    the acceptance number the chip-harvest gate pins (>= 0.8 on a
    5-step Sedov capture, scripts/check.sh)."""
    xplanes, traces = find_capture(trace_dir)
    maps: Dict[str, Dict[str, str]] = {}
    all_events: List[dict] = []
    for xp in xplanes:
        try:
            m, evs = parse_xplane(xp)
        except TraceError:
            continue  # a corrupt sidecar degrades to the json fallback
        for module, mm in m.items():
            maps.setdefault(module, {}).update(mm)
        all_events.extend(evs)
    if not all_events:
        for tp in traces:
            all_events.extend(load_op_events(tp))
    fallback = maps.get("", {})
    base_maps = {mod: _base_phases(m) for mod, m in maps.items()}

    phase_us: Dict[str, float] = defaultdict(float)
    phase_events: Dict[str, int] = defaultdict(int)
    phase_ops: Dict[str, set] = defaultdict(set)
    unattr_us: Dict[Tuple[str, str], float] = defaultdict(float)
    module_us: Dict[str, float] = defaultdict(float)
    total_us = 0.0
    for ev in all_events:
        total_us += ev["dur_us"]
        module_us[ev["module"]] += ev["dur_us"]
        scope = ev.get("scope")
        if scope is None:
            mod_map = maps.get(ev["module"], fallback)
            scope = mod_map.get(ev["op"]) or fallback.get(ev["op"])
        phase = _phase_of(scope)
        if phase is None and ev["op"] not in maps.get(ev["module"], {}):
            # suffixless thunk name: attribute via the base name when
            # every same-base instruction of the module agrees
            phase = base_maps.get(ev["module"], {}).get(_base(ev["op"]))
        if phase is None:
            unattr_us[(ev["module"], ev["op"])] += ev["dur_us"]
            continue
        phase_us[phase] += ev["dur_us"]
        phase_events[phase] += 1
        phase_ops[phase].add(ev["op"])
    attributed = sum(phase_us.values())
    phases = [
        {"phase": p, "us": round(us, 3),
         "share": us / total_us if total_us else 0.0,
         "ops": len(phase_ops[p]), "events": phase_events[p]}
        for p, us in sorted(phase_us.items(), key=lambda kv: -kv[1])
    ]
    unattributed = [
        {"module": m, "op": op, "us": round(us, 3),
         "share": us / total_us if total_us else 0.0}
        for (m, op), us in sorted(unattr_us.items(),
                                  key=lambda kv: -kv[1])[:top]
    ]
    return {
        "trace_dir": trace_dir,
        "xplane_files": [os.path.basename(x) for x in xplanes],
        "trace_files": [os.path.basename(t) for t in traces],
        "device_op_events": len(all_events),
        "total_device_us": round(total_us, 3),
        "attributed_us": round(attributed, 3),
        "coverage": attributed / total_us if total_us else 0.0,
        "phases": phases,
        "modules": {m: round(us, 3) for m, us in sorted(
            module_us.items(), key=lambda kv: -kv[1])},
        "unattributed_top": unattributed,
    }


def phase_attr_digest(summary: Dict) -> Dict:
    """The compact per-capture digest persisted into the run record —
    bench.py stamps it as ``extra.phase_attr`` and the app as the
    ``phase_attr`` event payload. One shape, built in one place, so the
    two records cannot silently diverge."""
    return {
        "phases": {p["phase"]: round(p["us"], 1)
                   for p in summary["phases"]},
        "coverage": round(summary["coverage"], 4),
        "total_device_us": summary["total_device_us"],
    }


def render_trace(s: Dict) -> str:
    from sphexa_tpu.devtools.common import render_table

    lines = [f"trace: {s['trace_dir']}"]
    lines.append(
        f"  {s['device_op_events']} device-op events, "
        f"{s['total_device_us'] / 1e3:.3f} ms device-op time, "
        f"{len(s['xplane_files'])} xplane(s), "
        f"{len(s['trace_files'])} perfetto dump(s)"
    )
    if not s["phases"]:
        lines.append("  no sphexa/ phases found — pre-attribution capture, "
                     "or the named scopes were stripped (run the HLO pin "
                     "test in tests/test_phase_attr.py)")
        return "\n".join(lines)
    rows = [(p["phase"], f"{p['us'] / 1e3:.3f} ms", f"{p['share']:.1%}",
             p["ops"], p["events"]) for p in s["phases"]]
    lines.append(render_table(
        rows, headers=("phase", "device time", "share", "ops", "events")))
    lines.append(f"attributed: {s['attributed_us'] / 1e3:.3f} ms "
                 f"({s['coverage']:.1%} of device-op time)")
    if s["unattributed_top"]:
        lines.append("top unattributed ops:")
        rows = [(u["module"], u["op"], f"{u['us'] / 1e3:.3f} ms",
                 f"{u['share']:.1%}") for u in s["unattributed_top"]]
        lines.append(render_table(rows))
    return "\n".join(lines)
