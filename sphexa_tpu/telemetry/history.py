"""Cross-run history: the perf trajectory as one trend model, plus the
lock-file regression gate.

The repo's performance record lives in loose committed files — eleven
``BENCH_r*``/``MULTICHIP_r*`` wrappers and any number of telemetry run
dirs — with no trend view and nothing stopping a chip-less PR from
quietly regressing a chip-measured number. This module gives both:

- ``load_history`` ingests any mix of bench JSONs (bench.py output, the
  ``BENCH_r*.json`` driver wrapper, ``MULTICHIP_r*.json``) and telemetry
  run directories into one row-per-round trend table
  (``sphexa-telemetry history``);
- ``evaluate_lock`` is the CI gate (``sphexa-telemetry regress --lock``):
  a committed lock file pins chip-measured metrics (value + relative
  threshold + direction + the committed source file they were read
  from); the gate re-extracts each metric and fails when it is worse
  than ``locked * (1 -/+ threshold)`` — so the chip harvest locks each
  gain in and chip-less rounds cannot regress it (ROADMAP item 2).

Deliberately jax-free (the telemetry/cli.py contract).
"""

import json
import os
import re
from typing import Dict, List, Optional, Sequence, Tuple

#: committed driver-wrapper rounds: BENCH_r05.json -> ("bench", 5)
ROUND_RE = re.compile(r"(BENCH|MULTICHIP)_r(\d+)\.json$")

#: lock-file schema (independent of the event schema; bump on shape
#: change and keep reading older locks)
LOCK_SCHEMA = 1


class HistoryError(Exception):
    """Unreadable/invalid input (CLI exit code 2)."""


# ---------------------------------------------------------------------------
# bench JSON parsing (shared with telemetry/cli.py's diff)
# ---------------------------------------------------------------------------


def parse_bench_json(path: str) -> Dict:
    """bench.py's JSON line, or a driver wrapper (``BENCH_r*.json`` /
    ``MULTICHIP_r*.json``) whose ``tail`` buries a metric/value line in
    captured output (measure_multichip.py --json emits the same shape,
    so multi-chip comm-volume rounds parse exactly like bench rounds)."""
    with open(path) as f:
        data = json.load(f)
    if "metric" in data and "value" in data:
        return data
    if "tail" in data:
        for line in reversed(str(data["tail"]).splitlines()):
            line = line.strip()
            if line.startswith("{"):
                try:
                    inner = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if "metric" in inner and "value" in inner:
                    return inner
    raise HistoryError(f"{path}: not a bench JSON (no metric/value line)")


def field_of(bench: Dict, field: str):
    """Dotted-path lookup into a parsed bench line (``value``,
    ``extra.ve_updates_per_sec``, ``extra.telemetry.retraces``, ...);
    None when any segment is missing or non-numeric."""
    cur = bench
    for seg in field.split("."):
        if not isinstance(cur, dict) or seg not in cur:
            return None
        cur = cur[seg]
    return cur if isinstance(cur, (int, float)) else None


# ---------------------------------------------------------------------------
# trend ingestion
# ---------------------------------------------------------------------------


def _row_from_bench(path: str) -> Dict:
    m = ROUND_RE.search(os.path.basename(path))
    try:
        bench = parse_bench_json(path)
    except HistoryError:
        # a committed wrapper WITHOUT a metric line is a real round that
        # measured nothing (the chip-less MULTICHIP dry runs stamp rc/ok
        # only) — the trend keeps the row, value-less, instead of
        # refusing the whole history. ONLY round-named files or files
        # carrying the driver-wrapper shape qualify: an arbitrary JSON
        # (a manifest, the lock file, a typo'd path) must raise (exit
        # 2), not fabricate a row
        with open(path) as f:
            wrapper = json.load(f)  # unreadable JSON still raises (exit 2)
        if not isinstance(wrapper, dict) or (
                m is None and "rc" not in wrapper and "ok" not in wrapper):
            raise
        return {
            "label": os.path.basename(path),
            "kind": m.group(1).lower() if m else "bench",
            "round": int(m.group(2)) if m else None,
            "metric": None, "value": None, "unit": None,
            "vs_baseline": None, "git_rev": None, "backend": None,
            "note": ("dry-run ok" if wrapper.get("ok")
                     else "no measurement"),
        }
    kind = bench_kind(path, bench)
    manifest = bench.get("manifest") or {}
    extra = bench.get("extra") or {}
    row = {
        "label": os.path.basename(path),
        "kind": kind,
        "round": int(m.group(2)) if m else None,
        "metric": bench.get("metric"),
        "value": bench.get("value"),
        "unit": bench.get("unit"),
        "vs_baseline": bench.get("vs_baseline"),
        "git_rev": manifest.get("git_rev"),
        "backend": manifest.get("backend"),
    }
    for k in ("ve_updates_per_sec", "gravity_1m_updates_per_sec",
              "std_energy_drift"):
        if isinstance(extra.get(k), (int, float)):
            row[k] = extra[k]
    tel = extra.get("telemetry") or {}
    for k in ("retraces", "rollbacks", "halo_trips"):
        if isinstance(tel.get(k), (int, float)):
            row[k] = tel[k]
    return row


def _row_from_run(run_dir: str) -> Dict:
    from sphexa_tpu.telemetry.cli import summarize_run

    s = summarize_run(run_dir)
    manifest = s.get("manifest") or {}
    p50 = (s.get("step_time") or {}).get("p50_s")
    n = manifest.get("particles")
    return {
        "label": run_dir,
        "kind": "run",
        "round": None,
        "metric": "run p50 throughput",
        "value": (float(n) / p50) if n and p50 else None,
        "unit": "particles/s",
        "vs_baseline": None,
        "git_rev": manifest.get("git_rev"),
        "backend": manifest.get("backend"),
        "step_p50_s": p50,
        "retraces": s.get("retraces"),
        "rollbacks": s.get("rollbacks"),
    }


def default_inputs(root: str = ".") -> List[str]:
    """The committed round files of a repo checkout, in round order."""
    import glob as _glob

    paths = sorted(
        _glob.glob(os.path.join(root, "BENCH_r*.json"))
        + _glob.glob(os.path.join(root, "MULTICHIP_r*.json"))
    )
    return paths


def load_history(inputs: Sequence[str]) -> List[Dict]:
    """One trend row per input (bench JSON or telemetry run dir), sorted
    kind-major / round-minor so the two trajectories read as two runs of
    consecutive rows. Unreadable inputs raise (exit 2): a trend over
    silently dropped rounds would claim a history it does not have."""
    rows: List[Dict] = []
    for p in inputs:
        if os.path.isdir(p):
            rows.append(_row_from_run(p))
        elif os.path.isfile(p):
            rows.append(_row_from_bench(p))
        else:
            raise HistoryError(f"{p}: neither a bench JSON nor a run dir")
    order = {"bench": 0, "multichip": 1, "run": 2}
    rows.sort(key=lambda r: (order.get(r["kind"], 3),
                             r["round"] if r["round"] is not None else 1 << 30,
                             r["label"]))
    # per-trajectory deltas: value change vs the previous round of the
    # SAME kind — the trend the eleven loose files never showed
    prev: Dict[str, float] = {}
    for r in rows:
        v = r.get("value")
        if isinstance(v, (int, float)) and r["kind"] in prev and prev[r["kind"]]:
            r["change"] = v / prev[r["kind"]] - 1.0
        if isinstance(v, (int, float)):
            prev[r["kind"]] = v
    return rows


def render_history(rows: List[Dict]) -> str:
    from sphexa_tpu.devtools.common import render_table

    if not rows:
        return ("no history inputs (expected BENCH_r*.json / "
                "MULTICHIP_r*.json or run dirs)")

    def val(r):
        v = r.get("value")
        if v is None:
            return r.get("note") or "-"
        if r["kind"] == "multichip":
            return f"{v:.3g}x"
        return f"{v / 1e6:.3f} M/s" if v >= 1e5 else f"{v:.4g}/s"

    def fmt(v, f="{:.3g}"):
        return "-" if v is None else f.format(v)

    trows = []
    for r in rows:
        trows.append((
            r["label"],
            r["kind"],
            "-" if r.get("round") is None else f"r{r['round']:02d}",
            val(r),
            "-" if r.get("change") is None else f"{r['change'] * 100:+.1f}%",
            fmt(r.get("vs_baseline"), "{:.4f}"),
            fmt(r.get("ve_updates_per_sec"), "{:.3g}"),
            fmt(r.get("gravity_1m_updates_per_sec"), "{:.3g}"),
            fmt(r.get("std_energy_drift"), "{:.2e}"),
        ))
    table = render_table(
        trows, headers=("source", "kind", "round", "headline", "change",
                        "vs_base", "ve", "grav 1M", "drift"))
    lines = [table]
    bench = [r for r in rows if r["kind"] == "bench"
             and isinstance(r.get("value"), (int, float))]
    if len(bench) >= 2:
        first, last = bench[0]["value"], bench[-1]["value"]
        if first:
            lines.append(
                f"bench trajectory: {first / 1e6:.3f} -> "
                f"{last / 1e6:.3f} M updates/s "
                f"({last / first:.2f}x over {len(bench)} rounds)")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# the regression lock (CI gate)
# ---------------------------------------------------------------------------


def load_lock(path: str) -> Dict:
    try:
        with open(path) as f:
            lock = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        raise HistoryError(f"{path}: unreadable lock file ({e})")
    if not isinstance(lock, dict) or not isinstance(
            lock.get("metrics"), list):
        raise HistoryError(f"{path}: lock file needs a 'metrics' list")
    for m in lock["metrics"]:
        for req in ("name", "source", "field", "value"):
            if req not in m:
                raise HistoryError(
                    f"{path}: lock metric {m.get('name', '?')!r} missing "
                    f"{req!r}")
    return lock


def bench_kind(path: str, bench: Optional[Dict] = None) -> str:
    """``bench`` vs ``multichip`` for a bench-JSON file: the committed
    wrapper naming convention when the filename carries it, else the
    metric-name heuristic ``load_history`` uses (measure_multichip's
    headline is the sparse-exchange *saving*)."""
    base = os.path.basename(path or "").upper()
    if base.startswith("MULTICHIP"):
        return "multichip"
    if base.startswith("BENCH"):
        return "bench"
    return ("multichip" if "saving" in str((bench or {}).get("metric", ""))
            else "bench")


def _source_kind(source: str, root: str) -> str:
    """``bench_kind`` for a locked metric's committed source: when the
    filename is inconclusive, parse the source itself so the
    metric-name heuristic sees real content (a saving locked from
    'chip_saving.json' must not classify as bench and get gated
    against a throughput candidate). Unreadable sources fall back to
    the filename verdict — non-candidate mode flags them properly."""
    base = os.path.basename(source or "").upper()
    if base.startswith(("MULTICHIP", "BENCH")):
        return bench_kind(source)
    try:
        return bench_kind(source, parse_bench_json(os.path.join(root, source)))
    except (HistoryError, OSError):
        return bench_kind(source)


def evaluate_lock(lock: Dict, root: str,
                  candidate: Optional[str] = None) -> Dict:
    """Check every locked metric against its committed source (or, with
    ``candidate``, against one fresh bench JSON — the pre-commit gate of
    a new chip measurement). A metric is REGRESSED when its current
    value is worse than ``value * (1 - threshold)`` (higher-is-better;
    flipped otherwise). A missing source/field is a failure too: a gate
    that cannot find its metric must not pass green.

    The lock mixes kinds (bench throughputs + the multichip saving) but
    a candidate file measures exactly one of them, so candidate mode
    gates only the locked metrics whose source is the same kind as the
    candidate — the rest are reported as ``skipped`` (a fresh BENCH run
    says nothing about the multichip saving; comparing a throughput
    field against a saving ratio would be a nonsense verdict either
    way). A candidate matching NO locked metric fails: that gate
    checked nothing."""
    rows: List[Dict] = []
    problems: List[str] = []
    cand = parse_bench_json(candidate) if candidate else None
    cand_kind = bench_kind(candidate, cand) if candidate else None
    for m in lock["metrics"]:
        thr = float(m.get("threshold", 0.05))
        hib = bool(m.get("higher_is_better", True))
        locked = float(m["value"])
        if cand_kind is not None \
                and _source_kind(m["source"], root) != cand_kind:
            rows.append({"name": m["name"], "source": m["source"],
                         "locked": locked, "current": None,
                         "threshold": thr, "regressed": False,
                         "change": None, "skipped": True})
            continue
        src = candidate if candidate else os.path.join(root, m["source"])
        try:
            bench = cand if cand is not None else parse_bench_json(src)
            current = field_of(bench, m["field"])
        except (HistoryError, OSError) as e:
            problems.append(f"{m['name']}: {e}")
            current = None
        if current is None:
            rows.append({"name": m["name"], "source": src,
                         "locked": locked, "current": None,
                         "threshold": thr, "regressed": True,
                         "change": None})
            if not problems or m["name"] not in problems[-1]:
                problems.append(
                    f"{m['name']}: field {m['field']!r} missing in {src}")
            continue
        current = float(current)
        floor = locked * (1.0 - thr)
        ceil = locked * (1.0 + thr)
        regressed = current < floor if hib else current > ceil
        rows.append({
            "name": m["name"], "source": src, "locked": locked,
            "current": current, "threshold": thr,
            "change": (current / locked - 1.0) if locked else None,
            "regressed": bool(regressed),
        })
    if candidate and rows and all(r.get("skipped") for r in rows):
        problems.append(
            f"{candidate}: {cand_kind} candidate matches no locked "
            f"{cand_kind} metric — nothing was gated")
    return {
        "lock_schema": lock.get("schema"),
        "rows": rows,
        "problems": problems,
        "regressed": (any(r["regressed"] for r in rows)
                      or bool(candidate and rows
                              and all(r.get("skipped") for r in rows))),
    }


def write_lock(lock_path: str, lock: Dict, root: str) -> Dict:
    """Re-read every metric's source and overwrite the locked values —
    the harvest-day locking step (measure on chip, commit the round
    file, point the lock's ``source`` at it, then ``regress --lock
    <file> --write``). Refuses when any metric is unreadable."""
    res = evaluate_lock(lock, root)
    if res["problems"]:
        raise HistoryError("cannot write lock: "
                           + "; ".join(res["problems"]))
    by_name = {r["name"]: r for r in res["rows"]}
    for m in lock["metrics"]:
        m["value"] = by_name[m["name"]]["current"]
    lock["schema"] = lock.get("schema", LOCK_SCHEMA)
    with open(lock_path, "w") as f:
        json.dump(lock, f, indent=2)
        f.write("\n")
    return lock


def render_regress(res: Dict) -> str:
    from sphexa_tpu.devtools.common import render_table

    rows = []
    for r in res["rows"]:
        rows.append((
            r["name"],
            f"{r['locked']:.6g}",
            "-" if r["current"] is None else f"{r['current']:.6g}",
            "-" if r.get("change") is None else f"{r['change'] * 100:+.1f}%",
            f"{r['threshold'] * 100:.0f}%",
            ("skipped" if r.get("skipped")
             else "REGRESSED" if r["regressed"] else "ok"),
        ))
    lines = [render_table(
        rows, headers=("metric", "locked", "current", "change", "budget",
                       ""))]
    for p in res["problems"]:
        lines.append(f"  problem: {p}")
    lines.append("regression vs lock" if res["regressed"]
                 else "all locked metrics hold")
    return "\n".join(lines)
