"""``sphexa-telemetry``: summarize a telemetry run or diff two of them.

    sphexa-telemetry summary <run-dir> [--format text|json] [--strict]
    sphexa-telemetry shards  <run-dir> [--format text|json]
    sphexa-telemetry science <run-dir> [--format text|json] [--budget F]
    sphexa-telemetry diff <baseline> <candidate> [--threshold F] [--drift]
    sphexa-telemetry trace <trace-dir> [--min-coverage F] [--top N]
    sphexa-telemetry history [inputs...] [--root DIR]
    sphexa-telemetry regress --lock <lock.json> [candidate] [--write]
    sphexa-telemetry tuning <run-dir | TUNING_TABLE.json> [--require K]
    sphexa-telemetry serve <dir|glob> [--out HTML] [--port N]
                                      [--refresh S] [--once]
    sphexa-telemetry fleet <glob> [--format text|json]

``summary`` reads ``<run-dir>/manifest.json`` + ``events.jsonl`` and
reports p50/p95/mean step time, retrace/rollback/reconfigure counts and
per-phase means. ``--strict`` exits 1 on any schema-invalid event or
unknown event kind (the check.sh --telemetry-only gate); unknown kinds
are COUNTED and reported either way, never silently dropped — a v2
reader meeting a future file degrades loudly.

``shards`` is the multi-chip view (schema-v2 ``shard_load`` /
``exchange`` / ``memory`` / ``imbalance`` events): per-shard load table,
halo-occupancy p95, comm rows + bytes/step, escape-trip counts, and
per-device HBM snapshots. Exit 1 when the run carries no per-shard
telemetry (so a mesh-rehearsal smoke can assert the instrumentation
actually fired).

``science`` is the physics view (schema-v3 ``physics`` / ``numerics`` /
``drift`` / ``field_health`` events from the in-graph ledger): the
conservation-drift table and rate, the timestep-limiter histogram, the
field-extrema timeline, nonfinite counts, and watchdog hits. Exit 1
when the run carries no physics telemetry, when ``--budget`` is given
and the run's max |Δetot|/|etot0| exceeds it, or (without ``--budget``)
when a drift/field-health watchdog fired during the run — so CI can
gate on conservation the way it already gates on step time.

``diff`` compares two run directories, two bench JSONs (``bench.py``
output, the ``BENCH_r*.json`` driver wrapper, or the
``MULTICHIP_r*.json`` wrapper whose tail carries
``scripts/measure_multichip.py --json``'s line), or a run against a
bench baseline (throughput derived as particles / p50 step time). Exit
codes are CI-shaped: 0 within threshold, 1 regression beyond it, 2
usage/unreadable input — so a pipeline can gate on step-time or
comm-volume regressions directly. ``--drift`` makes run-vs-run energy
drift a headline metric (drift-vs-drift with the same threshold exit
codes).

``trace`` is the time view (schema v4): per-phase device-time
attribution of a ``--trace-dir`` jax.profiler capture, joined from the
perfetto dump + the xplane sidecar's op metadata (the
``jax.named_scope("sphexa/<phase>")`` taxonomy the step programs carry;
telemetry/traceview.py). ``--min-coverage`` is the chip-harvest gate:
exit 1 when less than that fraction of device-op time lands in named
phases.

``history`` renders the cross-run trend (the committed
``BENCH_r*``/``MULTICHIP_r*`` rounds and/or run dirs) and ``regress``
gates the committed lock file (``TELEMETRY_LOCK.json``) so a chip-less
PR cannot regress a locked, chip-measured number (telemetry/history.py;
exit 0 hold / 1 regressed-or-missing / 2 unreadable).

``tuning`` is the autotuning view (schema v5): on a run dir it renders
the active knob set and its provenance (the manifest's ``tuning``
stamp + the ``tuning``/``sweep`` events), exit 1 when the run carries
no tuning telemetry; on a table file it schema- and registry-validates
the committed ``TUNING_TABLE.json`` (a stale knob name = exit 1) and
renders its coverage, with ``--require workload,n,p,backend`` exiting 1
on a coverage gap.

``serve`` / ``fleet`` are the live science surface (schema v8,
telemetry/serve.py): a self-contained auto-refreshing HTML dashboard
(or text table) over one or MANY run dirs — step-time sparklines,
drift/watchdog badges, per-shard load, dt_bins histograms, crash
blackboxes in red, and field frames rendered from the ``snapshots/``
.npz ring the in-graph snapshot deposit writes at the flush boundary
(observables/snapshot.py). Exit 0 rendered / 1 no runs matched / 2
every matched run unreadable.

Crash-truncated runs are EXPLAINED, not merely tolerated: when the
flight recorder (telemetry/flightrec.py) left a ``blackbox.json``,
``summary``/``science`` surface its reason, watchdog state and
traceback tail next to the partial aggregation.

Deliberately jax-free, with ONE documented exception: summarizing a run
must not drag in a backend, but ``tuning``'s table validation imports
``sphexa_tpu.tuning`` (whose import-time registry check needs the live
config dataclasses, and with them jax) lazily, inside that branch only.
"""

import argparse
import json
import os
import sys
from collections import Counter
from typing import Dict, List, Optional, Tuple

import numpy as np

from sphexa_tpu.devtools.common import render_table
from sphexa_tpu.telemetry.flightrec import read_blackbox
from sphexa_tpu.telemetry.history import (
    HistoryError,
    parse_bench_json as _parse_bench_json,
)
from sphexa_tpu.telemetry.manifest import read_manifest
from sphexa_tpu.telemetry.registry import EVENT_KINDS, validate_event
from sphexa_tpu.telemetry.traceview import TraceError


class TelemetryError(Exception):
    """Unreadable/invalid input (CLI exit code 2)."""


# ---------------------------------------------------------------------------
# loading
# ---------------------------------------------------------------------------


def load_events(run_dir: str) -> Tuple[List[dict], List[str]]:
    """(events, problems) from ``<run_dir>/events.jsonl``. Unparseable
    lines and schema violations are collected, not fatal — a killed run
    leaves a readable prefix and the summary should still work."""
    path = os.path.join(run_dir, "events.jsonl")
    if not os.path.exists(path):
        raise TelemetryError(f"no events.jsonl in {run_dir}")
    events: List[dict] = []
    problems: List[str] = []
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                e = json.loads(line)
            except json.JSONDecodeError as exc:
                problems.append(f"line {lineno}: unparseable ({exc})")
                continue
            bad = validate_event(e)
            if bad:
                problems.append(f"line {lineno}: " + "; ".join(bad))
            events.append(e)
    return events, problems


def _of_kind(events: List[dict], kind: str) -> List[dict]:
    return [e for e in events if e.get("kind") == kind]


def _crash_view(run_dir: str) -> Optional[Dict]:
    """Compact blackbox digest for the summary/science views (None when
    the run has no flight-recorder dump)."""
    box = read_blackbox(run_dir)
    if box is None:
        return None
    tb = (box.get("traceback") or "").strip().splitlines()
    return {
        "reason": box.get("reason"),
        "watchdogs": box.get("watchdogs") or {},
        "buffered_events": len(box.get("events") or []),
        "traceback_tail": tb[-3:],
        "fault_log": box.get("fault_log"),
    }


def summarize_run(run_dir: str) -> Dict:
    """Aggregate one run directory into the summary dict.

    "Step time" unifies both checking modes: synchronous steps contribute
    their own wall time (``step`` events); deferred windows contribute
    their per-step mean once per window step (``window`` events) — the
    only honest per-step number when the happy path never syncs
    (docs/OBSERVABILITY.md, deferred-window semantics).
    """
    events, problems = load_events(run_dir)
    # schema-invalid events are reported as problems, never fatal — a
    # killed run's truncated line must not take the summary down with it
    samples: List[float] = []
    for e in _of_kind(events, "step"):
        if isinstance(e.get("wall_s"), (int, float)):
            samples.append(float(e["wall_s"]))
    for e in _of_kind(events, "window"):
        if isinstance(e.get("per_step_s"), (int, float)) \
                and isinstance(e.get("steps"), int):
            samples.extend([float(e["per_step_s"])] * e["steps"])

    phases: Dict[str, List[float]] = {}
    for e in _of_kind(events, "phases"):
        for k, v in e.items():
            if k in ("v", "seq", "t", "kind", "it"):
                continue
            if isinstance(v, (int, float)):
                phases.setdefault(k, []).append(float(v))

    step_time = {}
    if samples:
        arr = np.asarray(samples)
        step_time = {
            "count": len(samples),
            "p50_s": float(np.percentile(arr, 50)),
            "p95_s": float(np.percentile(arr, 95)),
            "mean_s": float(arr.mean()),
            "max_s": float(arr.max()),
        }
    # forward compat: kinds this reader does not know are counted and
    # surfaced, not silently skipped (a v1 reader on a v2 file used to
    # drop exchange/shard_load/... without a trace)
    unknown_kinds = Counter(
        e.get("kind") for e in events if e.get("kind") not in EVENT_KINDS
    )
    return {
        "run_dir": run_dir,
        "manifest": read_manifest(run_dir),
        "events": len(events),
        "steps": len(samples),
        "windows": len(_of_kind(events, "window")),
        "launches": len(_of_kind(events, "launch")),
        "step_time": step_time,
        # partial/corrupt records (a killed run's half-written events)
        # degrade to defaults instead of TypeError-ing the aggregation
        "retraces": int(sum(
            e["delta"] if isinstance(e.get("delta"), (int, float)) else 1
            for e in _of_kind(events, "retrace"))),
        "rollbacks": len(_of_kind(events, "rollback")),
        "replayed_steps": int(sum(
            e["steps"] if isinstance(e.get("steps"), (int, float)) else 0
            for e in _of_kind(events, "replay"))),
        # construction-time sizing is expected once per run, not a
        # mid-run health signal — only non-initial rebuilds count
        "reconfigures": len([e for e in _of_kind(events, "reconfigure")
                             if e.get("reason") != "initial"]),
        "imbalances": len(_of_kind(events, "imbalance")),
        "phase_mean_s": {k: float(np.mean(v)) for k, v in sorted(
            phases.items())},
        "unknown_kinds": {str(k): int(n)
                          for k, n in sorted(unknown_kinds.items())},
        # the flight recorder's dump, when the run died abnormally: the
        # summary EXPLAINS a truncated record instead of tolerating it
        "crash": _crash_view(run_dir),
        "schema_problems": problems,
    }


# ---------------------------------------------------------------------------
# shards view (schema v2 distributed events)
# ---------------------------------------------------------------------------


def _per_shard_matrix(events: List[dict], key: str) -> Optional[np.ndarray]:
    """(n_events, P) float matrix of one per-shard list field; None when
    the field never appears. Ragged rows (a mid-run mesh change would be
    a different run anyway) are dropped rather than guessed at."""
    rows = [e[key] for e in events
            if isinstance(e.get(key), list) and e[key]]
    if not rows:
        return None
    width = len(rows[-1])
    rows = [r for r in rows if len(r) == width]
    try:
        return np.asarray(rows, dtype=np.float64)
    except (TypeError, ValueError):
        return None


def summarize_shards(run_dir: str) -> Dict:
    """Aggregate the distributed (schema-v2) events of one run into the
    per-shard view: load/work per shard, halo-exchange volume and
    occupancy percentiles, escape trips, imbalance-watchdog hits, and
    per-device HBM snapshots. Schema-v7 stages the exchange records:
    events with ``stage == "gravity"`` (the MAC-sized sparse gravity
    serve) aggregate into their own block next to the SPH one; pre-v7
    events carry no stage and read as SPH."""
    events, problems = load_events(run_dir)
    loads = _of_kind(events, "shard_load")
    all_ex = _of_kind(events, "exchange")
    exchanges = [e for e in all_ex if e.get("stage", "sph") != "gravity"]
    gexchanges = [e for e in all_ex if e.get("stage") == "gravity"]
    memories = _of_kind(events, "memory")
    imbalances = _of_kind(events, "imbalance")

    particles = _per_shard_matrix(loads, "particles")
    work = _per_shard_matrix(loads, "work")
    rows = _per_shard_matrix(exchanges, "rows")
    occ = _per_shard_matrix(exchanges, "occ")
    grows = _per_shard_matrix(gexchanges, "rows")
    gocc = _per_shard_matrix(gexchanges, "occ")

    shards: List[Dict] = []
    P = 0
    for m in (particles, work, rows, occ, grows, gocc):
        if m is not None:
            P = max(P, m.shape[1])
    for s in range(P):
        col = lambda m: None if m is None or s >= m.shape[1] else m[:, s]
        w = col(work)
        r = col(rows)
        o = col(occ)
        gr = col(grows)
        go = col(gocc)
        shards.append({
            "shard": s,
            "particles": int(particles[-1, s]) if particles is not None
            else None,
            "work_mean": float(w.mean()) if w is not None else None,
            "rows_mean": float(r.mean()) if r is not None else None,
            "occ_p95": float(np.percentile(o, 95)) if o is not None
            else None,
            "grav_rows_mean": float(gr.mean()) if gr is not None else None,
            "grav_occ_p95": float(np.percentile(go, 95)) if go is not None
            else None,
        })
    if work is not None and all(s["work_mean"] is not None for s in shards):
        total = sum(s["work_mean"] for s in shards) or 1.0
        for s in shards:
            s["work_share"] = s["work_mean"] / total
    last_ex = exchanges[-1] if exchanges else {}
    last_gex = gexchanges[-1] if gexchanges else {}
    gravity = None
    if gexchanges:
        gravity = {
            "windows": len(gexchanges),
            "mode": last_gex.get("mode"),
            "shipped_rows": last_gex.get("shipped_rows"),
            "bytes_per_step": last_gex.get("bytes_per_step"),
            "trips": last_gex.get("trips", 0),
        }
    # imbalance ratios over the run: max/mean of work per event row
    ratios = []
    if work is not None:
        means = work.mean(axis=1)
        with np.errstate(invalid="ignore", divide="ignore"):
            ratios = list(work.max(axis=1)[means > 0] / means[means > 0])
    return {
        "run_dir": run_dir,
        "manifest": read_manifest(run_dir),
        "shards": shards,
        "windows": len(exchanges),
        "mode": last_ex.get("mode"),
        "shipped_rows": last_ex.get("shipped_rows"),
        "bytes_per_step": last_ex.get("bytes_per_step"),
        "trips": last_ex.get("trips", 0),
        "gravity": gravity,
        "imbalance_events": len(imbalances),
        "work_ratio_p95": float(np.percentile(ratios, 95)) if ratios
        else None,
        "memory": [
            {k: e.get(k) for k in ("point", "it", "devices",
                                   "bytes_in_use", "peak_bytes_in_use")}
            for e in memories
        ],
        "schema_problems": problems,
    }


# ---------------------------------------------------------------------------
# science view (schema v3 physics-observability events)
# ---------------------------------------------------------------------------


def _concat_series(events: List[dict], key: str):
    """Flatten one per-step list field across physics/numerics events
    into a single python list (older/malformed events that carry a bare
    scalar contribute that scalar once; non-numeric entries drop)."""
    out: List[float] = []
    for e in events:
        v = e.get(key)
        if not isinstance(v, list):
            v = [v]
        out.extend(float(x) for x in v
                   if isinstance(x, (int, float)))
    return out


def summarize_science(run_dir: str) -> Dict:
    """Aggregate one run's physics-observability (schema v3) events:
    the per-step conservation series and its drift, the dt-limiter
    histogram, nonfinite counts, field extrema, watchdog hits. Partial
    records (crash before the first flush: no physics events at all)
    summarize to an empty-but-rendered view, never a traceback."""
    events, problems = load_events(run_dir)
    phys = _of_kind(events, "physics")
    nums = _of_kind(events, "numerics")
    bins = _of_kind(events, "dt_bins")

    its = [int(x) for x in _concat_series(phys, "its")]
    series = {k: _concat_series(phys, k)
              for k in ("t_sim", "dt", "etot", "ecin", "eint", "egrav",
                        "linmom", "angmom")}
    etot = np.asarray(series["etot"], dtype=np.float64)
    t_sim = np.asarray(series["t_sim"], dtype=np.float64)

    drift = {}
    finite = etot[np.isfinite(etot)]
    if finite.size:
        e0 = float(finite[0])
        denom = abs(e0) or 1.0
        with np.errstate(invalid="ignore"):
            d = np.abs(etot - e0) / denom
        dmax = float(np.nanmax(d)) if np.isfinite(d).any() else None
        dfin = float(d[-1]) if np.isfinite(d[-1]) else None
        drift = {"etot0": e0, "etot_final": float(etot[-1]),
                 "max": dmax, "final": dfin}
        if (dfin is not None and t_sim.size == etot.size
                and t_sim.size > 1 and t_sim[-1] > t_sim[0]):
            drift["per_time"] = dfin / float(t_sim[-1] - t_sim[0])

    limiter: Dict[str, int] = {}
    nonfinite: Dict[str, int] = {}
    extrema_rows: List[Dict] = []
    for e in nums:
        for name, n in (e.get("limiter") or {}).items():
            if isinstance(n, int):
                limiter[str(name)] = limiter.get(str(name), 0) + n
        for f, n in (e.get("nonfinite") or {}).items():
            if isinstance(n, int):
                nonfinite[str(f)] = max(nonfinite.get(str(f), 0), n)
        extrema_rows.append({
            k: e.get(k) for k in ("it", "rho_min", "rho_max", "h_min",
                                  "h_max", "du_max", "nc_clip", "h_sat")
        })

    # block-timestep view (schema v6 dt_bins events): the run-total
    # particle-update counters ARE the chip-free complexity proxy, the
    # last event's histogram shows where the bins settled
    dt_bins_view = None
    if bins:
        updates = sum(int(e.get("updates", 0)) for e in bins)
        full = sum(int(e.get("updates_full", 0)) for e in bins)
        dt_bins_view = {
            "events": len(bins),
            "pop": bins[-1].get("pop"),
            "updates": updates,
            "updates_full": full,
            "saved_factor": (full / updates) if updates else None,
            "resorts": sum(int(e.get("resorts", 0)) for e in bins),
            "keeps": sum(int(e.get("keeps", 0)) for e in bins),
        }

    return {
        "run_dir": run_dir,
        "manifest": read_manifest(run_dir),
        "physics_events": len(phys),
        "steps": len(its) or len(series["etot"]),
        "t_range": [float(t_sim[0]), float(t_sim[-1])] if t_sim.size
        else None,
        "drift": drift,
        "limiter": dict(sorted(limiter.items())),
        "nonfinite": nonfinite,
        "extrema": extrema_rows,
        "dt_bins": dt_bins_view,
        "drift_events": len(_of_kind(events, "drift")),
        "field_health_events": len(_of_kind(events, "field_health")),
        "crash": _crash_view(run_dir),
        "schema_problems": problems,
    }


def load_side(path: str) -> Dict:
    """One diff operand: a telemetry run dir or a bench JSON file
    (parsing shared with the history/regress machinery —
    telemetry/history.parse_bench_json owns the wrapper shapes)."""
    if os.path.isdir(path):
        s = summarize_run(path)
        return {"type": "run", "label": path, "summary": s}
    if os.path.isfile(path):
        try:
            b = _parse_bench_json(path)
        except HistoryError as e:
            raise TelemetryError(str(e))
        return {"type": "bench", "label": path, "bench": b}
    raise TelemetryError(f"{path}: neither a run directory nor a file")


# ---------------------------------------------------------------------------
# diff
# ---------------------------------------------------------------------------


def _run_updates_per_sec(side: Dict) -> Optional[float]:
    s = side["summary"]
    manifest = s.get("manifest") or {}
    n = manifest.get("particles")
    p50 = s.get("step_time", {}).get("p50_s")
    if not n or not p50:
        return None
    return float(n) / float(p50)


def diff_sides(base: Dict, cand: Dict, threshold: float,
               drift: bool = False) -> Dict:
    """Compare candidate against baseline. Returns the comparison dict;
    ``regressed`` is True when a headline metric moved past the
    threshold in the bad direction (step time up / throughput down /
    energy drift up). ``drift`` promotes run-vs-run energy drift to a
    headline metric (drift-vs-drift, the conservation regression gate)
    and errors when either side lacks physics telemetry."""
    if drift and not (base["type"] == "run" and cand["type"] == "run"):
        raise TelemetryError("--drift compares two run directories")
    rows: List[Dict] = []

    def row(metric, a, b, higher_is_better, headline=False):
        if a is None or b is None:
            return
        if a == 0:
            change = 0.0 if b == 0 else float("inf")
        else:
            change = b / a - 1.0
        bad = (change < -threshold) if higher_is_better \
            else (change > threshold)
        rows.append({
            "metric": metric, "baseline": a, "candidate": b,
            "change": change, "headline": headline,
            "regressed": bool(headline and bad),
        })

    if base["type"] == "run" and cand["type"] == "run":
        a, b = base["summary"], cand["summary"]
        at, bt = a.get("step_time", {}), b.get("step_time", {})
        row("step_time_p50_s", at.get("p50_s"), bt.get("p50_s"),
            higher_is_better=False, headline=True)
        row("step_time_p95_s", at.get("p95_s"), bt.get("p95_s"),
            higher_is_better=False)
        row("retraces", a["retraces"], b["retraces"],
            higher_is_better=False)
        row("rollbacks", a["rollbacks"], b["rollbacks"],
            higher_is_better=False)
        row("reconfigures", a["reconfigures"], b["reconfigures"],
            higher_is_better=False)
        for k in sorted(set(a["phase_mean_s"]) & set(b["phase_mean_s"])):
            row(f"phase_{k}_mean_s", a["phase_mean_s"][k],
                b["phase_mean_s"][k], higher_is_better=False)
        # conservation: drift-vs-drift, computed ONLY under --drift —
        # each science view re-parses events.jsonl, and a plain
        # step-time diff (incl. of pre-v3 runs) must not pay that or
        # change behavior
        if drift:
            da = summarize_science(base["label"]).get("drift", {}).get(
                "max")
            db = summarize_science(cand["label"]).get("drift", {}).get(
                "max")
            if da is None or db is None:
                raise TelemetryError(
                    "--drift needs physics telemetry on both sides "
                    "(re-run with --telemetry-dir on a v3 writer)")
            # drift is legitimately EXACTLY zero on short baselines; a
            # ratio-only gate would turn any nonzero candidate into an
            # infinite regression — floor the baseline at 1e-9 (f32
            # noise scale) before the relative comparison
            base_eff = max(da, 1e-9)
            rows.append({
                "metric": "energy_drift_max", "baseline": da,
                "candidate": db, "change": db / base_eff - 1.0,
                "headline": True,
                "regressed": bool(db > base_eff * (1.0 + threshold)),
            })
    elif base["type"] == "bench" and cand["type"] == "bench":
        a, b = base["bench"], cand["bench"]
        # the headline is whatever the bench line's metric is: throughput
        # for bench.py, a saving ratio for measure_multichip --json —
        # both higher-is-better by construction
        label = ("saving" if "saving" in str(a.get("metric", ""))
                 else "updates_per_sec")
        row(label, a.get("value"), b.get("value"),
            higher_is_better=True, headline=True)
        ea, eb = a.get("extra", {}) or {}, b.get("extra", {}) or {}
        for k in sorted(set(ea) & set(eb)):
            if isinstance(ea[k], (int, float)) and isinstance(
                    eb[k], (int, float)):
                # throughput/saving metrics improve upward; everything
                # else (times, comm rows/fractions, byte counts) downward
                row(k, ea[k], eb[k],
                    higher_is_better="updates_per_sec" in k
                    or "saving" in k)
    else:
        # mixed: throughput is the one commensurable axis
        def ups(side):
            if side["type"] == "bench":
                return side["bench"].get("value")
            return _run_updates_per_sec(side)

        a, b = ups(base), ups(cand)
        if a is None or b is None:
            raise TelemetryError(
                "run-vs-bench diff needs 'particles' in the run manifest "
                "and a step-time p50 (re-run with --telemetry-dir)"
            )
        row("updates_per_sec", a, b, higher_is_better=True, headline=True)

    if not rows:
        raise TelemetryError("nothing comparable between the two inputs")
    return {
        "baseline": base["label"],
        "candidate": cand["label"],
        "threshold": threshold,
        "rows": rows,
        "regressed": any(r["regressed"] for r in rows),
    }


# ---------------------------------------------------------------------------
# rendering
# ---------------------------------------------------------------------------


def _fmt_s(v: Optional[float]) -> str:
    return "-" if v is None else f"{v * 1e3:.3f} ms"


def render_summary(s: Dict) -> str:
    m = s.get("manifest") or {}
    lines = [f"run: {s['run_dir']}"]
    if m:
        lines.append(
            f"  git {m.get('git_rev', '?')}  jax {m.get('jax_version', '?')}"
            f"  backend {m.get('backend', '?')}"
            f"  devices {m.get('device_count', '?')}"
            + (f"  mesh {m['mesh_shape']}" if m.get("mesh_shape") else "")
            + (f"  N={m['particles']}" if m.get("particles") else "")
        )
    else:
        lines.append("  (no manifest.json)")
    st = s.get("step_time") or {}
    rows = [
        ("steps", s["steps"]),
        ("deferred windows", s["windows"]),
        ("step time p50", _fmt_s(st.get("p50_s"))),
        ("step time p95", _fmt_s(st.get("p95_s"))),
        ("step time mean", _fmt_s(st.get("mean_s"))),
        ("retraces", s["retraces"]),
        ("rollbacks", s["rollbacks"]),
        ("replayed steps", s["replayed_steps"]),
        ("reconfigures", s["reconfigures"]),
    ]
    for k, v in s["phase_mean_s"].items():
        rows.append((f"phase {k} (mean)", _fmt_s(v)))
    if s.get("imbalances"):
        rows.append(("imbalance events", s["imbalances"]))
    lines.append(render_table(rows))
    lines.extend(_render_crash(s.get("crash")))
    for kind, n in s.get("unknown_kinds", {}).items():
        lines.append(f"  unknown kind: {kind} x{n} (newer writer? "
                     f"upgrade this reader)")
    for p in s["schema_problems"]:
        lines.append(f"  schema: {p}")
    return "\n".join(lines)


def _render_crash(crash: Optional[Dict]) -> List[str]:
    """Lines explaining a flight-recorder dump (empty for clean runs)."""
    if not crash:
        return []
    lines = [f"CRASH: {crash.get('reason', '?')} (blackbox.json, "
             f"{crash.get('buffered_events', 0)} buffered events)"]
    hot = {k: v for k, v in (crash.get("watchdogs") or {}).items()
           if v and k != "events_total"}
    if hot:
        lines.append("  watchdog state at death: "
                     + " ".join(f"{k}={v}" for k, v in sorted(hot.items())))
    for t in crash.get("traceback_tail") or []:
        lines.append(f"  | {t}")
    if crash.get("fault_log"):
        lines.append(f"  fault log: {crash['fault_log']}")
    return lines


def _fmt_bytes(v) -> str:
    if v is None:
        return "-"
    v = float(v)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(v) < 1024 or unit == "GiB":
            return f"{v:.1f} {unit}" if unit != "B" else f"{int(v)} B"
        v /= 1024
    return f"{v:.1f} GiB"


def render_shards(s: Dict) -> str:
    m = s.get("manifest") or {}
    lines = [f"run: {s['run_dir']}"]
    if m:
        lines.append(
            f"  devices {m.get('device_count', '?')}"
            + (f"  mesh {m['mesh_shape']}" if m.get("mesh_shape") else "")
            + (f"  N={m['particles']}" if m.get("particles") else "")
            + f"  backend {m.get('backend', '?')}"
        )
    if not s["shards"]:
        lines.append("  no per-shard telemetry in this run "
                     "(single-device, or a pre-v2 writer)")
        return "\n".join(lines)
    fmt = lambda v, f="{:.3g}": "-" if v is None else f.format(v)
    # gravity-stage columns render only when a v7 writer staged them
    grav = any(sh.get("grav_rows_mean") is not None for sh in s["shards"])
    rows = []
    for sh in s["shards"]:
        row = (
            sh["shard"],
            fmt(sh["particles"], "{}"),
            fmt(sh["work_mean"], "{:.4g}"),
            fmt(sh.get("work_share"), "{:.1%}"),
            fmt(sh["rows_mean"], "{:.4g}"),
            fmt(sh["occ_p95"], "{:.2f}"),
        )
        if grav:
            row += (fmt(sh.get("grav_rows_mean"), "{:.4g}"),
                    fmt(sh.get("grav_occ_p95"), "{:.2f}"))
        rows.append(row)
    headers = ("shard", "particles", "work", "share", "halo rows",
               "occ p95")
    if grav:
        headers += ("grav rows", "grav occ")
    lines.append(render_table(rows, headers=headers))
    info = [
        ("windows recorded", s["windows"]),
        ("exchange mode", s.get("mode") or "-"),
        ("shipped rows/serve", s.get("shipped_rows") or "-"),
        ("bytes/step", _fmt_bytes(s.get("bytes_per_step"))
         if s.get("bytes_per_step") else "-"),
        ("escape trips", s.get("trips", 0)),
        ("imbalance events", s.get("imbalance_events", 0)),
    ]
    g = s.get("gravity")
    if g:
        info += [
            ("gravity mode", g.get("mode") or "-"),
            ("gravity rows/serve", g.get("shipped_rows") or "-"),
            ("gravity bytes/step", _fmt_bytes(g.get("bytes_per_step"))
             if g.get("bytes_per_step") else "-"),
            ("gravity trips", g.get("trips", 0)),
        ]
    if s.get("work_ratio_p95") is not None:
        info.append(("work max/mean p95", f"{s['work_ratio_p95']:.3f}"))
    lines.append(render_table(info))
    if s["memory"]:
        lines.append("memory snapshots:")
        mrows = []
        for e in s["memory"]:
            bts = e.get("bytes_in_use") or []
            pks = e.get("peak_bytes_in_use") or []
            mrows.append((
                e.get("point", "?"),
                e.get("it", "-"),
                len(e.get("devices") or []),
                _fmt_bytes(max(bts)) if bts else "-",
                _fmt_bytes(max(pks)) if pks else "-",
            ))
        lines.append(render_table(
            mrows, headers=("point", "it", "devices", "max bytes",
                            "max peak")))
    for p in s["schema_problems"]:
        lines.append(f"  schema: {p}")
    return "\n".join(lines)


def _fmt_g(v, fmt="{:.6g}") -> str:
    return "-" if v is None else fmt.format(v)


def render_science(s: Dict) -> str:
    m = s.get("manifest") or {}
    lines = [f"run: {s['run_dir']}"]
    if m:
        lines.append(
            f"  backend {m.get('backend', '?')}"
            + (f"  N={m['particles']}" if m.get("particles") else "")
            + (f"  case {m['case']}" if m.get("case") else "")
        )
    if not s["physics_events"]:
        lines.append("  no physics telemetry in this run "
                     "(pre-v3 writer, or it crashed before the first "
                     "check/flush boundary)")
        lines.extend(_render_crash(s.get("crash")))
        return "\n".join(lines)
    d = s.get("drift") or {}
    rows = [
        ("steps", s["steps"]),
        ("t range", "-" if not s.get("t_range") else
         f"{s['t_range'][0]:.6g} .. {s['t_range'][1]:.6g}"),
        ("etot first", _fmt_g(d.get("etot0", None), "{:.10g}")),
        ("etot final", _fmt_g(d.get("etot_final", None), "{:.10g}")),
        ("|drift| final", _fmt_g(d.get("final"), "{:.3e}")),
        ("|drift| max", _fmt_g(d.get("max"), "{:.3e}")),
    ]
    if d.get("per_time") is not None:
        rows.append(("drift rate (/sim-time)", f"{d['per_time']:.3e}"))
    rows.append(("drift watchdog events", s["drift_events"]))
    rows.append(("field-health events", s["field_health_events"]))
    for f, n in sorted((s.get("nonfinite") or {}).items()):
        if n:
            rows.append((f"nonfinite {f} (max/step)", n))
    lines.append(render_table(rows))
    if s.get("limiter"):
        total = sum(s["limiter"].values()) or 1
        lines.append("timestep limiter:")
        lines.append(render_table(
            [(name, n, f"{n / total:.1%}")
             for name, n in sorted(s["limiter"].items(),
                                   key=lambda kv: -kv[1])],
            headers=("limiter", "steps", "share")))
    b = s.get("dt_bins")
    if b:
        pop = b.get("pop") or []
        tot = sum(pop) or 1
        lines.append("dt bins (hierarchical block time steps):")
        lines.append(render_table(
            [(f"2^{k} x dt_min", n, f"{n / tot:.1%}")
             for k, n in enumerate(pop)],
            headers=("bin", "particles", "share")))
        saved = b.get("saved_factor")
        lines.append(render_table([
            ("particle updates", b["updates"]),
            ("global-dt equivalent", b["updates_full"]),
            ("updates saved", "-" if saved is None else f"{saved:.2f}x"),
            ("resorts / keeps", f"{b['resorts']} / {b['keeps']}"),
        ]))
    ext = [r for r in s.get("extrema", []) if r.get("it") is not None]
    if ext:
        lines.append("extrema timeline (per checked step / window):")
        show = ext if len(ext) <= 12 else ext[:3] + ext[-9:]
        rows = [(r["it"], _fmt_g(r.get("rho_min"), "{:.4g}"),
                 _fmt_g(r.get("rho_max"), "{:.4g}"),
                 _fmt_g(r.get("h_min"), "{:.4g}"),
                 _fmt_g(r.get("h_max"), "{:.4g}"),
                 _fmt_g(r.get("du_max"), "{:.4g}"),
                 _fmt_g(r.get("nc_clip"), "{}"),
                 _fmt_g(r.get("h_sat"), "{}"))
                for r in show]
        lines.append(render_table(
            rows, headers=("it", "rho min", "rho max", "h min", "h max",
                           "|du| max", "nc clip", "h sat")))
        if len(ext) > 12:
            lines.append(f"  ({len(ext) - 12} middle windows elided)")
    lines.extend(_render_crash(s.get("crash")))
    for p in s["schema_problems"]:
        lines.append(f"  schema: {p}")
    return "\n".join(lines)


def render_diff(d: Dict) -> str:
    lines = [f"baseline:  {d['baseline']}",
             f"candidate: {d['candidate']}",
             f"threshold: {d['threshold'] * 100:.1f}%"]
    rows = []
    for r in d["rows"]:
        mark = "REGRESSED" if r["regressed"] else (
            "*" if r["headline"] else "")
        rows.append((r["metric"], f"{r['baseline']:.6g}",
                     f"{r['candidate']:.6g}",
                     f"{r['change'] * 100:+.1f}%", mark))
    lines.append(render_table(
        rows, headers=("metric", "baseline", "candidate", "change", "")))
    lines.append("regression detected" if d["regressed"]
                 else "within threshold")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# entry point
# ---------------------------------------------------------------------------


# ---------------------------------------------------------------------------
# tuning view (schema v5: the autotuning evidence trail)
# ---------------------------------------------------------------------------


def summarize_tuning_run(run_dir: str) -> Dict:
    """The tuning story of one run dir: the manifest's top-level
    ``tuning`` stamp (what the Simulation resolved and why — the app
    passes it via write_manifest's ``extra``, which splats into the
    manifest root) plus the ``tuning`` decision events and the
    ``sweep`` candidates, if this dir is a sphexa-tune sweep."""
    manifest = read_manifest(run_dir)
    events, problems = load_events(run_dir)
    decisions = [e for e in events if e.get("kind") == "tuning"]
    sweeps = [e for e in events if e.get("kind") == "sweep"]
    stamp = (manifest or {}).get("tuning")
    by_status = Counter(e.get("status") for e in sweeps)
    ok = [e for e in sweeps
          if e.get("status") == "ok"
          and isinstance(e.get("value"), (int, float))]
    return {
        "run_dir": run_dir,
        "manifest_tuning": stamp,
        "decisions": decisions,
        "sweep_candidates": len(sweeps),
        "sweep_by_status": dict(by_status),
        "sweep_best": min(ok, key=lambda e: e["value"]) if ok else None,
        "schema_problems": problems,
    }


def render_tuning_run(s: Dict) -> str:
    lines = [f"tuning view: {s['run_dir']}"]
    stamp = s["manifest_tuning"]
    if stamp:
        lines.append(f"  active source: {stamp.get('source')}")
        if stamp.get("key"):
            k = stamp["key"]
            lines.append(f"  table entry:   {k.get('workload')} / "
                         f"{k.get('n_bucket')} / P={k.get('p')} / "
                         f"{k.get('backend')}")
        if stamp.get("knobs"):
            lines.append("  knobs:         " + ", ".join(
                f"{k}={v}" for k, v in sorted(stamp["knobs"].items())))
        if stamp.get("explicit"):
            lines.append("  explicit:      "
                         + ", ".join(stamp["explicit"]))
        prov = stamp.get("entry_provenance")
        if prov:
            lines.append(f"  provenance:    run={prov.get('source_run')} "
                         f"created={prov.get('created')} "
                         f"objective={prov.get('objective')} "
                         f"win={prov.get('win')}")
    for d in s["decisions"]:
        ctx = " ".join(f"{k}={v}" for k, v in d.items()
                       if k not in ("v", "seq", "t", "kind"))
        lines.append(f"  decision: {ctx}")
    if s["sweep_candidates"]:
        lines.append(f"  sweep: {s['sweep_candidates']} candidates "
                     + " ".join(f"{k}={v}" for k, v in
                                sorted(s["sweep_by_status"].items())))
        best = s["sweep_best"]
        if best:
            lines.append(f"  sweep best: {best.get('knobs')} -> "
                         f"{best.get('value')} ({best.get('objective')})")
    if not stamp and not s["decisions"] and not s["sweep_candidates"]:
        lines.append("  no tuning telemetry (run predates --tuned, or "
                     "heuristics-only)")
    return "\n".join(lines)


def _tuning_table_cmd(path: str, require: Optional[str],
                      fmt: str) -> int:
    """Validate + render a committed table file. Imports the tuning
    package (and with it jax) lazily — the documented exception to this
    CLI's jax-free rule; the import itself validates the knob registry
    against the live configs (drift = exit 1, same as a stale knob)."""
    try:
        from sphexa_tpu.tuning import coverage, resolve_entry, \
            validate_table
        from sphexa_tpu.tuning.table import load_table
    except RuntimeError as e:
        print(f"sphexa-telemetry: {e}", file=sys.stderr)
        return 1
    try:
        table = load_table(path)
    except FileNotFoundError:
        raise TelemetryError(f"no such table: {path}")
    except ValueError as e:
        raise TelemetryError(str(e))
    problems = validate_table(table)
    out = {"table": path, "entries": len(table.get("entries", [])),
           "problems": problems, "coverage": coverage(table)}
    gap = None
    if require:
        parts = require.split(",")
        if len(parts) != 4:
            raise TelemetryError(
                f"--require wants workload,n,p,backend, got {require!r}")
        w, n, p, b = parts
        try:
            # float() first so the natural "1e6" spelling works
            n_i, p_i = int(float(n)), int(p)
        except ValueError:
            raise TelemetryError(
                f"--require wants numeric n and p, got {require!r}")
        entry = resolve_entry(table, w, n_i, p_i, b)
        gap = entry is None
        out["require"] = {"workload": w, "n": n_i, "p": p_i,
                          "backend": b, "covered": not gap}
    if fmt == "json":
        print(json.dumps(out, indent=2))
    else:
        print(f"tuning table: {path} ({out['entries']} entries)")
        for key, cov in out["coverage"].items():
            print(f"  {key}: N {','.join(map(str, cov['n_buckets']))} "
                  f"P {','.join(map(str, cov['p']))}")
        for prob in problems:
            print(f"  PROBLEM: {prob}")
        if require:
            print(f"  require {require}: "
                  f"{'covered' if not gap else 'COVERAGE GAP'}")
    return 1 if (problems or gap) else 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="sphexa-telemetry",
        description="summarize / diff sphexa-tpu telemetry runs",
    )
    sub = p.add_subparsers(dest="cmd", required=True)
    ps = sub.add_parser("summary", help="summarize one run directory")
    ps.add_argument("run_dir")
    ps.add_argument("--format", choices=("text", "json"), default="text")
    ps.add_argument("--strict", action="store_true",
                    help="exit 1 on any schema-invalid event or unknown "
                         "event kind")
    ph = sub.add_parser(
        "shards", help="per-shard load/comm/HBM view of a multi-chip run")
    ph.add_argument("run_dir")
    ph.add_argument("--format", choices=("text", "json"), default="text")
    pc = sub.add_parser(
        "science",
        help="conservation/numerics view of a run (drift table + rate, "
             "dt-limiter histogram, extrema timeline, watchdog hits)")
    pc.add_argument("run_dir")
    pc.add_argument("--format", choices=("text", "json"), default="text")
    pc.add_argument("--budget", type=float, default=None,
                    help="exit 1 if max |etot-etot0|/|etot0| exceeds "
                         "this relative budget; without it, exit 1 when "
                         "a drift/field-health watchdog fired in-run")
    pd = sub.add_parser("diff", help="diff candidate against baseline")
    pd.add_argument("baseline", help="run dir or bench JSON")
    pd.add_argument("candidate", help="run dir or bench JSON")
    pd.add_argument("--threshold", type=float, default=0.10,
                    help="relative headline-regression threshold [0.10]")
    pd.add_argument("--drift", action="store_true",
                    help="run-vs-run: make energy drift a headline "
                         "metric (conservation regression gate)")
    pd.add_argument("--format", choices=("text", "json"), default="text")
    pt = sub.add_parser(
        "trace",
        help="per-phase device-time attribution of a --trace-dir "
             "jax.profiler capture (the sphexa/<phase> named scopes)")
    pt.add_argument("trace_dir")
    pt.add_argument("--format", choices=("text", "json"), default="text")
    pt.add_argument("--min-coverage", type=float, default=None,
                    dest="min_coverage",
                    help="exit 1 when less than this fraction of "
                         "device-op time is attributed to sphexa/ "
                         "phases (the chip-harvest gate)")
    pt.add_argument("--top", type=int, default=8,
                    help="unattributed ops to list [8]")
    pt.add_argument("--predict", action="store_true",
                    help="join the measured per-phase times against the "
                         "static roofline prediction of the capture's "
                         "committed calibration.json target; exit 1 when "
                         "any measured/predicted ratio leaves the "
                         "recorded band (the jaxcost calibration gate)")
    pt.add_argument("--device", default=None,
                    help="with --predict: override the calibration's "
                         "device model (devtools/audit/devices.py)")
    ph2 = sub.add_parser(
        "history",
        help="cross-run trend over BENCH_r*/MULTICHIP_r* rounds and "
             "run dirs")
    ph2.add_argument("inputs", nargs="*",
                     help="bench JSONs / run dirs (default: the "
                          "committed rounds under --root)")
    ph2.add_argument("--root", default=".",
                     help="where the committed round files live [.]")
    ph2.add_argument("--format", choices=("text", "json"), default="text")
    pr = sub.add_parser(
        "regress",
        help="gate the committed lock file: exit 1 when any locked, "
             "chip-measured metric regressed (or cannot be read)")
    pr.add_argument("candidate", nargs="?", default=None,
                    help="optional fresh bench JSON to check EVERY "
                         "locked metric against (pre-commit gate of a "
                         "new measurement); default: each metric's "
                         "committed source file")
    pr.add_argument("--lock", required=True,
                    help="lock file (TELEMETRY_LOCK.json)")
    pr.add_argument("--root", default=None,
                    help="base dir for the lock's source files "
                         "[the lock file's directory]")
    pr.add_argument("--write", action="store_true",
                    help="re-read every source and overwrite the locked "
                         "values (the harvest-day locking step)")
    pr.add_argument("--format", choices=("text", "json"), default="text")
    pn = sub.add_parser(
        "tuning",
        help="autotuning view: a run dir's active knobs + provenance, "
             "or a TUNING_TABLE.json's validity + coverage")
    pn.add_argument("target", help="run dir or tuning-table JSON file")
    pn.add_argument("--require", default=None,
                    help="workload,n,p,backend — exit 1 when the table "
                         "has no entry covering it (coverage-gap gate)")
    pn.add_argument("--format", choices=("text", "json"), default="text")
    pv = sub.add_parser(
        "serve",
        help="fleet dashboard: self-contained auto-refreshing HTML over "
             "one run dir or a glob of them (telemetry/serve.py)")
    pv.add_argument("target", help="run dir, fleet root, or glob")
    pv.add_argument("--out", default=None,
                    help="HTML output path [sphexa-dashboard.html]")
    pv.add_argument("--port", type=int, default=None,
                    help="serve live via http.server instead of writing "
                         "a file")
    pv.add_argument("--refresh", type=float, default=5.0,
                    help="page auto-refresh / rewrite interval in "
                         "seconds [5]")
    pv.add_argument("--once", action="store_true",
                    help="render one page and exit (the CI shape)")
    pf = sub.add_parser(
        "fleet",
        help="text aggregation table over a glob of run dirs")
    pf.add_argument("target", help="run dir, fleet root, or glob")
    pf.add_argument("--format", choices=("text", "json"), default="text")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    try:
        if args.cmd == "summary":
            s = summarize_run(args.run_dir)
            print(json.dumps(s, indent=2) if args.format == "json"
                  else render_summary(s))
            return 1 if (args.strict and (s["schema_problems"]
                                          or s["unknown_kinds"])) else 0
        if args.cmd == "shards":
            s = summarize_shards(args.run_dir)
            print(json.dumps(s, indent=2) if args.format == "json"
                  else render_shards(s))
            # a mesh smoke asserting the instrumentation fired needs a
            # distinct exit code for "run exists but no shard telemetry"
            return 0 if s["shards"] else 1
        if args.cmd == "science":
            s = summarize_science(args.run_dir)
            print(json.dumps(s, indent=2) if args.format == "json"
                  else render_science(s))
            if not s["physics_events"]:
                return 1  # no ledger: the smoke must notice broken wiring
            if args.budget is not None:
                dmax = (s.get("drift") or {}).get("max")
                return 1 if dmax is None or dmax > args.budget else 0
            return 1 if (s["drift_events"]
                         or s["field_health_events"]) else 0
        if args.cmd == "trace":
            from sphexa_tpu.telemetry.traceview import (
                render_trace,
                summarize_trace,
            )

            s = summarize_trace(args.trace_dir, top=args.top)
            joined = None
            if args.predict:
                # measured-vs-static calibration: the jaxcost gate
                from sphexa_tpu.devtools.audit.costmodel import (
                    calibration_join,
                    load_calibration,
                )

                calib = load_calibration(args.trace_dir)
                if calib is None:
                    raise TelemetryError(
                        f"{args.trace_dir}: no calibration.json — "
                        f"--predict needs the committed calibration "
                        f"declaration (scripts/make_trace_fixture.py "
                        f"writes the fixture's)")
                if args.device:
                    calib = dict(calib, device=args.device)
                joined = calibration_join(s, calib)
            if args.format == "json":
                out = dict(s, calibration=joined) if joined else s
                print(json.dumps(out, indent=2))
            else:
                print(render_trace(s))
                if joined:
                    print(f"calibration: {joined['target']} @ "
                          f"{joined['device']} (tolerance "
                          f"{joined['tolerance']:g}x)")
                    for row in joined["rows"]:
                        if "ratio" in row:
                            lo, hi = row["band"]
                            print(f"  {row['phase']:18s} measured "
                                  f"{row['measured_us']:10.1f}us  "
                                  f"predicted {row['predicted_us']:10.3f}us"
                                  f"  ratio {row['ratio']:8.3f} in "
                                  f"[{lo:.3f}, {hi:.3f}]  {row['status']}")
                        else:
                            print(f"  {row['phase']:18s} {row['status']}")
            if not s["phases"]:
                return 1  # an unattributed capture must not pass green
            if args.min_coverage is not None \
                    and s["coverage"] < args.min_coverage:
                print(f"sphexa-telemetry: coverage {s['coverage']:.1%} "
                      f"below --min-coverage {args.min_coverage:.1%}",
                      file=sys.stderr)
                return 1
            if joined and not joined["ok"]:
                for v in joined["violations"]:
                    print(f"sphexa-telemetry: calibration: {v}",
                          file=sys.stderr)
                return 1
            return 0
        if args.cmd == "history":
            from sphexa_tpu.telemetry.history import (
                default_inputs,
                load_history,
                render_history,
            )

            inputs = args.inputs or default_inputs(args.root)
            rows = load_history(inputs)
            print(json.dumps(rows, indent=2) if args.format == "json"
                  else render_history(rows))
            return 0 if rows else 1
        if args.cmd == "regress":
            from sphexa_tpu.telemetry.history import (
                evaluate_lock,
                load_lock,
                render_regress,
                write_lock,
            )

            lock = load_lock(args.lock)
            root = args.root if args.root is not None \
                else (os.path.dirname(os.path.abspath(args.lock)) or ".")
            if args.write:
                if args.candidate:
                    # --write re-reads the COMMITTED sources; accepting a
                    # candidate here would silently relock stale numbers
                    # while the user believes the fresh file was locked
                    raise TelemetryError(
                        "--write relocks from the committed sources and "
                        "ignores a candidate: gate the fresh file first "
                        "(regress --lock L <candidate>), commit it, point "
                        "the lock's sources at it, then --write")
                lock = write_lock(args.lock, lock, root)
                print(f"locked {len(lock['metrics'])} metrics -> "
                      f"{args.lock}")
                return 0
            res = evaluate_lock(lock, root, candidate=args.candidate)
            print(json.dumps(res, indent=2) if args.format == "json"
                  else render_regress(res))
            return 1 if res["regressed"] else 0
        if args.cmd == "serve":
            from sphexa_tpu.telemetry.serve import serve_cmd

            return serve_cmd(args.target, out=args.out, port=args.port,
                             refresh=args.refresh, once=args.once)
        if args.cmd == "fleet":
            from sphexa_tpu.telemetry.serve import fleet_cmd

            return fleet_cmd(args.target, fmt=args.format)
        if args.cmd == "tuning":
            if os.path.isdir(args.target):
                if args.require:
                    raise TelemetryError(
                        "--require applies to a table file, not a run dir")
                s = summarize_tuning_run(args.target)
                print(json.dumps(s, indent=2) if args.format == "json"
                      else render_tuning_run(s))
                return 0 if (s["manifest_tuning"] or s["decisions"]
                             or s["sweep_candidates"]) else 1
            return _tuning_table_cmd(args.target, args.require,
                                     args.format)
        d = diff_sides(load_side(args.baseline), load_side(args.candidate),
                       args.threshold, drift=args.drift)
        print(json.dumps(d, indent=2) if args.format == "json"
              else render_diff(d))
        return 1 if d["regressed"] else 0
    except (TelemetryError, TraceError, HistoryError) as e:
        print(f"sphexa-telemetry: {e}", file=sys.stderr)
        return 2
    except (OSError, json.JSONDecodeError) as e:
        print(f"sphexa-telemetry: {e}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(main())
