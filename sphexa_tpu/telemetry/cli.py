"""``sphexa-telemetry``: summarize a telemetry run or diff two of them.

    sphexa-telemetry summary <run-dir> [--format text|json] [--strict]
    sphexa-telemetry diff <baseline> <candidate> [--threshold F]

``summary`` reads ``<run-dir>/manifest.json`` + ``events.jsonl`` and
reports p50/p95/mean step time, retrace/rollback/reconfigure counts and
per-phase means. ``--strict`` exits 1 on any schema-invalid event (the
check.sh --telemetry-only gate).

``diff`` compares two run directories, two bench JSONs (``bench.py``
output or the ``BENCH_r*.json`` driver wrapper), or a run against a
bench baseline (throughput derived as particles / p50 step time). Exit
codes are CI-shaped: 0 within threshold, 1 regression beyond it, 2
usage/unreadable input — so a pipeline can gate on step-time
regressions directly.

Deliberately jax-free: summarizing a run must not drag in a backend.
"""

import argparse
import json
import os
import sys
from typing import Dict, List, Optional, Tuple

import numpy as np

from sphexa_tpu.devtools.common import render_table
from sphexa_tpu.telemetry.manifest import read_manifest
from sphexa_tpu.telemetry.registry import validate_event


class TelemetryError(Exception):
    """Unreadable/invalid input (CLI exit code 2)."""


# ---------------------------------------------------------------------------
# loading
# ---------------------------------------------------------------------------


def load_events(run_dir: str) -> Tuple[List[dict], List[str]]:
    """(events, problems) from ``<run_dir>/events.jsonl``. Unparseable
    lines and schema violations are collected, not fatal — a killed run
    leaves a readable prefix and the summary should still work."""
    path = os.path.join(run_dir, "events.jsonl")
    if not os.path.exists(path):
        raise TelemetryError(f"no events.jsonl in {run_dir}")
    events: List[dict] = []
    problems: List[str] = []
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                e = json.loads(line)
            except json.JSONDecodeError as exc:
                problems.append(f"line {lineno}: unparseable ({exc})")
                continue
            bad = validate_event(e)
            if bad:
                problems.append(f"line {lineno}: " + "; ".join(bad))
            events.append(e)
    return events, problems


def _of_kind(events: List[dict], kind: str) -> List[dict]:
    return [e for e in events if e.get("kind") == kind]


def summarize_run(run_dir: str) -> Dict:
    """Aggregate one run directory into the summary dict.

    "Step time" unifies both checking modes: synchronous steps contribute
    their own wall time (``step`` events); deferred windows contribute
    their per-step mean once per window step (``window`` events) — the
    only honest per-step number when the happy path never syncs
    (docs/OBSERVABILITY.md, deferred-window semantics).
    """
    events, problems = load_events(run_dir)
    # schema-invalid events are reported as problems, never fatal — a
    # killed run's truncated line must not take the summary down with it
    samples: List[float] = []
    for e in _of_kind(events, "step"):
        if isinstance(e.get("wall_s"), (int, float)):
            samples.append(float(e["wall_s"]))
    for e in _of_kind(events, "window"):
        if isinstance(e.get("per_step_s"), (int, float)) \
                and isinstance(e.get("steps"), int):
            samples.extend([float(e["per_step_s"])] * e["steps"])

    phases: Dict[str, List[float]] = {}
    for e in _of_kind(events, "phases"):
        for k, v in e.items():
            if k in ("v", "seq", "t", "kind", "it"):
                continue
            if isinstance(v, (int, float)):
                phases.setdefault(k, []).append(float(v))

    step_time = {}
    if samples:
        arr = np.asarray(samples)
        step_time = {
            "count": len(samples),
            "p50_s": float(np.percentile(arr, 50)),
            "p95_s": float(np.percentile(arr, 95)),
            "mean_s": float(arr.mean()),
            "max_s": float(arr.max()),
        }
    return {
        "run_dir": run_dir,
        "manifest": read_manifest(run_dir),
        "events": len(events),
        "steps": len(samples),
        "windows": len(_of_kind(events, "window")),
        "launches": len(_of_kind(events, "launch")),
        "step_time": step_time,
        "retraces": int(sum(e.get("delta", 1)
                            for e in _of_kind(events, "retrace"))),
        "rollbacks": len(_of_kind(events, "rollback")),
        "replayed_steps": int(sum(e.get("steps", 0)
                                  for e in _of_kind(events, "replay"))),
        # construction-time sizing is expected once per run, not a
        # mid-run health signal — only non-initial rebuilds count
        "reconfigures": len([e for e in _of_kind(events, "reconfigure")
                             if e.get("reason") != "initial"]),
        "phase_mean_s": {k: float(np.mean(v)) for k, v in sorted(
            phases.items())},
        "schema_problems": problems,
    }


def _parse_bench_json(path: str) -> Dict:
    """bench.py's JSON line, or the driver's BENCH_r*.json wrapper whose
    ``tail`` buries that line in captured output."""
    with open(path) as f:
        data = json.load(f)
    if "metric" in data and "value" in data:
        return data
    if "tail" in data:
        for line in reversed(str(data["tail"]).splitlines()):
            line = line.strip()
            if line.startswith("{"):
                try:
                    inner = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if "metric" in inner and "value" in inner:
                    return inner
    raise TelemetryError(f"{path}: not a bench JSON (no metric/value line)")


def load_side(path: str) -> Dict:
    """One diff operand: a telemetry run dir or a bench JSON file."""
    if os.path.isdir(path):
        s = summarize_run(path)
        return {"type": "run", "label": path, "summary": s}
    if os.path.isfile(path):
        b = _parse_bench_json(path)
        return {"type": "bench", "label": path, "bench": b}
    raise TelemetryError(f"{path}: neither a run directory nor a file")


# ---------------------------------------------------------------------------
# diff
# ---------------------------------------------------------------------------


def _run_updates_per_sec(side: Dict) -> Optional[float]:
    s = side["summary"]
    manifest = s.get("manifest") or {}
    n = manifest.get("particles")
    p50 = s.get("step_time", {}).get("p50_s")
    if not n or not p50:
        return None
    return float(n) / float(p50)


def diff_sides(base: Dict, cand: Dict, threshold: float) -> Dict:
    """Compare candidate against baseline. Returns the comparison dict;
    ``regressed`` is True when the headline metric moved past the
    threshold in the bad direction (step time up / throughput down)."""
    rows: List[Dict] = []

    def row(metric, a, b, higher_is_better, headline=False):
        if a is None or b is None:
            return
        if a == 0:
            change = 0.0 if b == 0 else float("inf")
        else:
            change = b / a - 1.0
        bad = (change < -threshold) if higher_is_better \
            else (change > threshold)
        rows.append({
            "metric": metric, "baseline": a, "candidate": b,
            "change": change, "headline": headline,
            "regressed": bool(headline and bad),
        })

    if base["type"] == "run" and cand["type"] == "run":
        a, b = base["summary"], cand["summary"]
        at, bt = a.get("step_time", {}), b.get("step_time", {})
        row("step_time_p50_s", at.get("p50_s"), bt.get("p50_s"),
            higher_is_better=False, headline=True)
        row("step_time_p95_s", at.get("p95_s"), bt.get("p95_s"),
            higher_is_better=False)
        row("retraces", a["retraces"], b["retraces"],
            higher_is_better=False)
        row("rollbacks", a["rollbacks"], b["rollbacks"],
            higher_is_better=False)
        row("reconfigures", a["reconfigures"], b["reconfigures"],
            higher_is_better=False)
        for k in sorted(set(a["phase_mean_s"]) & set(b["phase_mean_s"])):
            row(f"phase_{k}_mean_s", a["phase_mean_s"][k],
                b["phase_mean_s"][k], higher_is_better=False)
    elif base["type"] == "bench" and cand["type"] == "bench":
        a, b = base["bench"], cand["bench"]
        row("updates_per_sec", a.get("value"), b.get("value"),
            higher_is_better=True, headline=True)
        ea, eb = a.get("extra", {}) or {}, b.get("extra", {}) or {}
        for k in sorted(set(ea) & set(eb)):
            if isinstance(ea[k], (int, float)) and isinstance(
                    eb[k], (int, float)):
                row(k, ea[k], eb[k],
                    higher_is_better="updates_per_sec" in k)
    else:
        # mixed: throughput is the one commensurable axis
        def ups(side):
            if side["type"] == "bench":
                return side["bench"].get("value")
            return _run_updates_per_sec(side)

        a, b = ups(base), ups(cand)
        if a is None or b is None:
            raise TelemetryError(
                "run-vs-bench diff needs 'particles' in the run manifest "
                "and a step-time p50 (re-run with --telemetry-dir)"
            )
        row("updates_per_sec", a, b, higher_is_better=True, headline=True)

    if not rows:
        raise TelemetryError("nothing comparable between the two inputs")
    return {
        "baseline": base["label"],
        "candidate": cand["label"],
        "threshold": threshold,
        "rows": rows,
        "regressed": any(r["regressed"] for r in rows),
    }


# ---------------------------------------------------------------------------
# rendering
# ---------------------------------------------------------------------------


def _fmt_s(v: Optional[float]) -> str:
    return "-" if v is None else f"{v * 1e3:.3f} ms"


def render_summary(s: Dict) -> str:
    m = s.get("manifest") or {}
    lines = [f"run: {s['run_dir']}"]
    if m:
        lines.append(
            f"  git {m.get('git_rev', '?')}  jax {m.get('jax_version', '?')}"
            f"  backend {m.get('backend', '?')}"
            f"  devices {m.get('device_count', '?')}"
            + (f"  mesh {m['mesh_shape']}" if m.get("mesh_shape") else "")
            + (f"  N={m['particles']}" if m.get("particles") else "")
        )
    else:
        lines.append("  (no manifest.json)")
    st = s.get("step_time") or {}
    rows = [
        ("steps", s["steps"]),
        ("deferred windows", s["windows"]),
        ("step time p50", _fmt_s(st.get("p50_s"))),
        ("step time p95", _fmt_s(st.get("p95_s"))),
        ("step time mean", _fmt_s(st.get("mean_s"))),
        ("retraces", s["retraces"]),
        ("rollbacks", s["rollbacks"]),
        ("replayed steps", s["replayed_steps"]),
        ("reconfigures", s["reconfigures"]),
    ]
    for k, v in s["phase_mean_s"].items():
        rows.append((f"phase {k} (mean)", _fmt_s(v)))
    lines.append(render_table(rows))
    for p in s["schema_problems"]:
        lines.append(f"  schema: {p}")
    return "\n".join(lines)


def render_diff(d: Dict) -> str:
    lines = [f"baseline:  {d['baseline']}",
             f"candidate: {d['candidate']}",
             f"threshold: {d['threshold'] * 100:.1f}%"]
    rows = []
    for r in d["rows"]:
        mark = "REGRESSED" if r["regressed"] else (
            "*" if r["headline"] else "")
        rows.append((r["metric"], f"{r['baseline']:.6g}",
                     f"{r['candidate']:.6g}",
                     f"{r['change'] * 100:+.1f}%", mark))
    lines.append(render_table(
        rows, headers=("metric", "baseline", "candidate", "change", "")))
    lines.append("regression detected" if d["regressed"]
                 else "within threshold")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# entry point
# ---------------------------------------------------------------------------


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="sphexa-telemetry",
        description="summarize / diff sphexa-tpu telemetry runs",
    )
    sub = p.add_subparsers(dest="cmd", required=True)
    ps = sub.add_parser("summary", help="summarize one run directory")
    ps.add_argument("run_dir")
    ps.add_argument("--format", choices=("text", "json"), default="text")
    ps.add_argument("--strict", action="store_true",
                    help="exit 1 on any schema-invalid event")
    pd = sub.add_parser("diff", help="diff candidate against baseline")
    pd.add_argument("baseline", help="run dir or bench JSON")
    pd.add_argument("candidate", help="run dir or bench JSON")
    pd.add_argument("--threshold", type=float, default=0.10,
                    help="relative headline-regression threshold [0.10]")
    pd.add_argument("--format", choices=("text", "json"), default="text")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    try:
        if args.cmd == "summary":
            s = summarize_run(args.run_dir)
            print(json.dumps(s, indent=2) if args.format == "json"
                  else render_summary(s))
            return 1 if (args.strict and s["schema_problems"]) else 0
        d = diff_sides(load_side(args.baseline), load_side(args.candidate),
                       args.threshold)
        print(json.dumps(d, indent=2) if args.format == "json"
              else render_diff(d))
        return 1 if d["regressed"] else 0
    except TelemetryError as e:
        print(f"sphexa-telemetry: {e}", file=sys.stderr)
        return 2
    except (OSError, json.JSONDecodeError) as e:
        print(f"sphexa-telemetry: {e}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(main())
