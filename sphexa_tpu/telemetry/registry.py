"""The metrics registry: counters, gauges, phase timings, typed events.

One ``Telemetry`` instance is shared by everything that measures a run —
the Simulation driver, the app loop's ``Timer`` laps, bench.py — so every
surface reports into the same place instead of three disconnected ones
(the pre-telemetry state: util/timer.py wall laps, a one-shot
substep_breakdown, and the per-step diagnostics dict).

Host-side only, by construction: nothing here touches device arrays.
Callers hand in already-host scalars (floats, ints); the zero-sync
deferred-window contract lives in the CALLERS (Simulation.step/flush)
and is pinned by tests/test_telemetry.py.
"""

import contextlib
import time
from collections import Counter, defaultdict
from typing import Callable, Dict, List, Optional

import numpy as np

#: events.jsonl schema version; bump on any incompatible field change and
#: document the migration in docs/OBSERVABILITY.md. v2 added the
#: distributed kinds (exchange / shard_load / memory / imbalance), v3
#: the physics-observability kinds (physics / numerics / drift /
#: field_health), v4 the time-and-history kinds (phase_attr / crash),
#: v5 the autotuning kinds (sweep / tuning), v6 the block-timestep kind
#: (dt_bins); v7 the optional ``stage`` payload ("sph" | "gravity") on
#: the exchange / shard_load kinds — the gravity near field's MAC-sized
#: sparse serve emits its own exchange record next to the SPH one (no
#: new kinds and no new REQUIRED fields); v8 the live-science-surface
#: kind (snapshot) — in-graph field-grid frames riding the flush
#: boundary (observables/snapshot.py), rendered by ``sphexa-telemetry
#: serve``. v8 only ADDS a kind, so v8 readers accept v1-v7 files
#: strictly clean and v7 readers count ``snapshot`` under unknown_kinds.
SCHEMA_VERSION = 8

#: event schema versions this reader understands (older versions only
#: ever ADD kinds, so the per-kind field table below covers them all)
SUPPORTED_VERSIONS = (1, 2, 3, 4, 5, 6, 7, 8)

#: every event kind the schema admits, with its required payload fields
#: (beyond the envelope ``v``/``seq``/``t``/``kind``). The CLI's --strict
#: validation enforces exactly this table.
EVENT_KINDS: Dict[str, tuple] = {
    "launch": ("it",),            # one deferred-window step dispatched
    "step": ("it", "wall_s"),     # one synchronously checked step done
    "window": ("it", "steps", "wall_s", "per_step_s"),  # deferred flush
    "reconfigure": ("it", "reason"),
    "rollback": ("it", "steps", "reason"),
    "replay": ("it", "steps"),
    "retrace": ("it", "delta"),   # jit cache grew on a launch (recompile)
    "rebuild_lists": ("it",),
    "phases": ("it",),            # per-iteration host phase laps
    "trace": ("dir",),            # jax.profiler trace started
    "run_end": (),
    "note": (),
    # -- v2: distributed kinds (one run, P shards) ------------------------
    # per-window halo-exchange record: ``rows`` = per-shard TRUE candidate
    # need (device-measured), ``shipped_rows`` = the static sized volume
    # actually moved per serve (sum(hmax) sparse / (P-1)*Wmax windowed)
    "exchange": ("it", "shipped_rows", "rows"),
    # per-window load record: per-shard particle counts + work proxies
    "shard_load": ("it", "particles"),
    # per-device HBM snapshot at a named point (manifest / post-compile /
    # flush); bytes lists are empty on backends without memory_stats()
    "memory": ("point",),
    # imbalance watchdog: max/mean of a per-shard metric crossed the
    # configured ratio (the runtime analog of the retrace watchdog)
    "imbalance": ("it", "metric", "ratio", "threshold"),
    # -- v3: physics-observability kinds (the in-graph science ledger) ----
    # per-window conservation record: parallel per-step lists (``its``,
    # ``t``, ``dt``, ``etot``/``ecin``/``eint``/``egrav``, ``linmom``,
    # ``angmom``, optional ``extra``) — every step keeps its row even
    # under deferred checking
    "physics": ("it", "etot"),
    # per-window numerics health: dt-limiter histogram, neighbor-cap
    # clip / h-saturation counts, nonfinite counts, field extrema
    "numerics": ("it",),
    # conservation-drift watchdog: |etot - etot0|/|etot0| crossed the
    # configured budget (Simulation(drift_budget=...) / --drift-budget)
    "drift": ("it", "drift", "budget"),
    # field-health watchdog: nonfinite rho/h/du values appeared in a
    # verified step (localize with --debug-checks)
    "field_health": ("it", "nonfinite"),
    # -- v4: time-and-history kinds (profiler attribution + crash) --------
    # per-phase device-time attribution of a --trace-dir capture
    # (telemetry/traceview.py over the jax.profiler dump): ``phases`` =
    # {"<phase>": device_us}, plus coverage/total_device_us/dir context
    "phase_attr": ("phases",),
    # crash flight recorder (telemetry/flightrec.py): appended by the
    # abnormal-exit hooks alongside blackbox.json so the event stream
    # itself records WHY it ends mid-run
    "crash": ("reason",),
    # -- v5: autotuning kinds (sphexa_tpu/tuning/) ------------------------
    # one sweep candidate measured by the replay harness: the knob dict
    # tried, its status ("ok" / "overflow" / "failed"), and on success
    # the objective name + value (per_step_s, or phase:<name> device us)
    "sweep": ("candidate", "knobs", "status"),
    # one tuning decision: where the active knobs came from ("table" /
    # "heuristic" / "explicit"), plus key/knobs/provenance context —
    # also emitted by gravity_tuning when N sits within 10% of its
    # step-function threshold (the near-cliff attribution note)
    "tuning": ("source",),
    # -- v6: block-timestep kind (sph/blockdt.py) -------------------------
    # per-window hierarchical block-dt record: ``pop`` = the (dt_bins,)
    # bin-occupancy histogram at the window's last substep, ``updates``/
    # ``updates_full`` = particle updates performed vs the global-dt cost
    # of the same substeps (the chip-free complexity proxy, docs/NEXT.md),
    # plus the drift-aware resort decision counters (resorts/keeps) and
    # the worst observed key-drift inversion count (drift_max)
    "dt_bins": ("it", "pop", "updates", "updates_full"),
    # -- v8: live-science-surface kind (observables/snapshot.py) ----------
    # one in-graph snapshot frame fetched at the check/flush boundary:
    # grid meta + per-field extrema inline (``fields``/``grid``/``axis``/
    # ``reduce``/``vmin``/``vmax``), pixels in the sidecar ``snapshots/``
    # .npz ring with ``path`` as the pointer (null when no ring dir is
    # configured) — rendered by ``sphexa-telemetry serve``
    "snapshot": ("it", "fields", "grid"),
}

#: first schema version each kind appeared in (an older-versioned event
#: carrying a newer kind is writer confusion, not forward compatibility)
_V2_ONLY = frozenset({"exchange", "shard_load", "memory", "imbalance"})
_V3_ONLY = frozenset({"physics", "numerics", "drift", "field_health"})
_V4_ONLY = frozenset({"phase_attr", "crash"})
_V5_ONLY = frozenset({"sweep", "tuning"})
_V6_ONLY = frozenset({"dt_bins"})
_V8_ONLY = frozenset({"snapshot"})
KIND_SINCE: Dict[str, int] = {
    k: 8 if k in _V8_ONLY else 6 if k in _V6_ONLY else 5 if k in _V5_ONLY
    else 4 if k in _V4_ONLY else 3 if k in _V3_ONLY
    else 2 if k in _V2_ONLY else 1
    for k in EVENT_KINDS
}

#: kinds that already existed in schema v1 (kept for introspection)
V1_KINDS = frozenset(k for k, v in KIND_SINCE.items() if v == 1)


def _jsonable(v):
    """Coerce numpy scalars/arrays so sinks can json.dumps payloads
    directly (per-shard metrics arrive as small (P,) arrays)."""
    if isinstance(v, (np.floating, np.integer)):
        return v.item()
    if isinstance(v, np.ndarray):
        return v.tolist()
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    return v


def validate_event(e: dict) -> List[str]:
    """Schema problems with one event dict ([] = valid). Any supported
    version validates (v3 readers accept v1/v2 files). An UNKNOWN kind
    is deliberately NOT a problem here — unknownness is the
    forward-compat dimension the reader reports separately (summary's
    ``unknown_kinds`` counts, strict exit code), and flagging it twice
    would render every future-schema event as schema-invalid noise. A
    newer-only kind claiming an older ``v`` IS a problem (writer
    confusion, not forward compat)."""
    problems = []
    if not isinstance(e, dict):
        return ["event is not an object"]
    if e.get("v") not in SUPPORTED_VERSIONS:
        problems.append(f"bad schema version {e.get('v')!r}")
    kind = e.get("kind")
    if kind in EVENT_KINDS:
        since = KIND_SINCE[kind]
        if e.get("v") in SUPPORTED_VERSIONS and e["v"] < since:
            problems.append(
                f"v{since}-only kind {kind!r} on a v{e['v']} event")
        else:
            for field in EVENT_KINDS[kind]:
                if field not in e:
                    problems.append(f"{kind} event missing field {field!r}")
    for field in ("seq", "t"):
        if not isinstance(e.get(field), (int, float)):
            problems.append(f"missing/non-numeric envelope field {field!r}")
    return problems


class Telemetry:
    """Counters + gauges + phase timings + an event stream over sinks.

    With no sinks the registry still accumulates (bench.py uses that to
    report retrace/rollback counts without writing files); ``event()``
    then costs one Counter bump — cheap enough for the hot loop.
    """

    def __init__(self, sinks=()):
        self.sinks = list(sinks)
        self.counters: Counter = Counter()
        self.gauges: Dict[str, float] = {}
        self.phase_totals: Dict[str, float] = defaultdict(float)
        self.phase_counts: Counter = Counter()
        self._seq = 0

    # -- scalar metrics ----------------------------------------------------
    def count(self, name: str, n: int = 1) -> None:
        self.counters[name] += n

    def gauge(self, name: str, value: float) -> None:
        self.gauges[name] = float(value)

    def timing(self, name: str, seconds: float) -> None:
        """Accumulate one lap of a named phase (mean via timing_mean)."""
        self.phase_totals[name] += float(seconds)
        self.phase_counts[name] += 1

    def timing_mean(self, name: str) -> float:
        n = self.phase_counts[name]
        return self.phase_totals[name] / n if n else float("nan")

    # -- event stream ------------------------------------------------------
    def event(self, kind: str, **payload) -> None:
        """Emit one typed event to every sink (and count it regardless)."""
        self.counters[f"events.{kind}"] += 1
        if not self.sinks:
            return
        e = {
            "v": SCHEMA_VERSION,
            "seq": self._seq,
            "t": round(time.time(), 6),
            "kind": kind,
            **{k: _jsonable(v) for k, v in payload.items()},
        }
        self._seq += 1
        for s in self.sinks:
            s.emit(e)

    def phases(self, it: int, laps: Dict[str, float]) -> None:
        """Per-iteration host phase laps (the Timer's pop) as one event;
        each lap also feeds the registry's phase accumulators."""
        for k, v in laps.items():
            self.timing(k, v)
        self.event("phases",
                   it=int(it), **{k: round(float(v), 6)
                                  for k, v in laps.items()})

    # -- profiler hooks ----------------------------------------------------
    def annotate(self, name: str):
        """Named scope for jax.profiler traces (TraceAnnotation): shows up
        in a --trace-dir capture around launch/flush/reconfigure/rebuild.
        Falls back to a no-op context when jax is unavailable (the CLI
        never imports jax)."""
        global _TRACE_ANNOTATION
        if _TRACE_ANNOTATION is None:
            try:
                from jax.profiler import TraceAnnotation
                _TRACE_ANNOTATION = TraceAnnotation
            except Exception:
                _TRACE_ANNOTATION = False
        if not _TRACE_ANNOTATION:
            return contextlib.nullcontext()
        return _TRACE_ANNOTATION(name)

    # -- console routing ---------------------------------------------------
    def console_printer(self, fallback: Callable = print) -> Callable:
        """The first console sink's line writer, else ``fallback`` —
        Simulation.run routes its per-iteration report through this."""
        for s in self.sinks:
            w = getattr(s, "write_line", None)
            if w is not None:
                return w
        return fallback

    def close(self) -> None:
        for s in self.sinks:
            s.close()


_TRACE_ANNOTATION = None  # resolved lazily by Telemetry.annotate


# ---------------------------------------------------------------------------
# lap timing + per-iteration series (the util/timer.py implementations,
# now living on the registry so every consumer shares one accumulation)
# ---------------------------------------------------------------------------


class LapTimer:
    """Accumulates named wall-clock laps within one iteration
    (timer.hpp:46 semantics); each lap also feeds ``telemetry.timing``."""

    def __init__(self, telemetry: Optional[Telemetry] = None):
        self.telemetry = telemetry
        self.laps: Dict[str, float] = {}
        self._t = time.perf_counter()

    def start(self) -> None:
        self._t = time.perf_counter()

    def lap(self, name: str) -> float:
        """Record time since the last mark under ``name``."""
        now = time.perf_counter()
        elapsed = now - self._t
        self.laps[name] = self.laps.get(name, 0.0) + elapsed
        self._t = now
        if self.telemetry is not None:
            self.telemetry.timing(name, elapsed)
        return elapsed

    # reference-parity alias (util/timer.hpp's Timer::step)
    step = lap

    def pop(self) -> Dict[str, float]:
        out = self.laps
        self.laps = {}
        return out


class StepSeries:
    """Per-iteration timing/metric rows, saved as an npz series
    (ipropagator.hpp:83-87 writes the analogous HDF5 series). With a
    telemetry registry attached, every row is also emitted as a
    ``phases`` event."""

    def __init__(self, telemetry: Optional[Telemetry] = None):
        self.telemetry = telemetry
        self.rows: List[Dict[str, float]] = []

    def record(self, iteration: int, laps: Dict[str, float], **metrics):
        self.rows.append({"iteration": float(iteration), **laps, **metrics})
        if self.telemetry is not None:
            self.telemetry.phases(iteration, {**laps, **metrics})

    def save(self, path: str, substeps=None) -> bool:
        """Write the series (+ optional one-shot substep breakdown as
        substep_<name> scalars). Returns whether a file was written —
        with zero rows and no substeps nothing is, and the caller must
        not report a series that doesn't exist (app/main.py --profile)."""
        if not self.rows and not substeps:
            return False
        keys = sorted({k for row in self.rows for k in row})
        # ragged rows (a metric recorded only on some iterations) are
        # NaN-padded so every column is one dense array
        arrays = {
            k: np.array([row.get(k, np.nan) for row in self.rows])
            for k in keys
        }
        for k, v in (substeps or {}).items():
            arrays[f"substep_{k}"] = np.float64(v)
        np.savez(path, **arrays)
        return True

    def summary(self) -> Dict[str, float]:
        """Mean seconds per iteration for each recorded phase."""
        if not self.rows:
            return {}
        keys = {k for row in self.rows for k in row} - {"iteration"}
        return {
            k: float(np.nanmean([row.get(k, np.nan) for row in self.rows]))
            for k in sorted(keys)
        }
