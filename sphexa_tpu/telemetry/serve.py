"""``sphexa-telemetry serve`` / ``fleet``: the jax-free fleet dashboard.

    sphexa-telemetry serve <dir|glob> [--out HTML] [--port N]
                                      [--refresh S] [--once]
    sphexa-telemetry fleet <glob> [--format text|json]

The live science surface (ROADMAP item 5): tails ``events.jsonl``
across one or MANY run directories (a glob = a fleet) and emits a
single self-contained, auto-refreshing HTML page — per-run step-time
sparklines, energy-drift and watchdog status, per-shard load/imbalance,
dt_bins histograms, tuning provenance, crash blackboxes surfaced red,
and the latest field frame rendered from the ``snapshots/`` .npz ring
(observables/snapshot.py) through ``viz.render_grid``/``viz._png_bytes``
(base64-inlined, so the page has zero external assets). This is the
TPU-era stand-in for watching an Ascent/Catalyst in-situ pipeline
(Ayachit 2015, Larsen 2017, PAPERS.md): all reduction happened on the
compute resource; the dashboard only re-colors render-ready extracts.

Strictly jax-free like the rest of the telemetry CLI — numpy + stdlib
(``http.server`` for ``--port``). Exit codes are CI-shaped: 0 rendered,
1 no run directories matched, 2 every matched run was unreadable
(missing/corrupt events.jsonl).

``fleet`` is the text aggregation table over the same discovery: one
row per run with step count, p50 step time, drift, watchdog hits and
crash state — the ssh-window view of the same data.
"""

import base64
import glob as _glob
import html as _html
import json
import os
import sys
import time
import zipfile
from typing import Dict, List, Optional

import numpy as np

from sphexa_tpu.devtools.common import render_table
from sphexa_tpu.telemetry.cli import (
    TelemetryError,
    _of_kind,
    load_events,
    summarize_run,
    summarize_science,
    summarize_shards,
    summarize_tuning_run,
)

# ---------------------------------------------------------------------------
# discovery
# ---------------------------------------------------------------------------


def _is_run_dir(path: str) -> bool:
    return os.path.isdir(path) \
        and os.path.exists(os.path.join(path, "events.jsonl"))


def discover_runs(target: str) -> List[str]:
    """Run directories for one CLI target: a run dir itself, a fleet
    root (direct children that are run dirs), or a glob over either.
    Sorted for stable rendering; a live fleet's members keep their slots
    across refreshes."""
    candidates: List[str] = []
    if os.path.isdir(target):
        if _is_run_dir(target):
            candidates = [target]
        else:
            candidates = [os.path.join(target, d)
                          for d in sorted(os.listdir(target))]
    else:
        candidates = sorted(_glob.glob(target))
    return [c for c in candidates if _is_run_dir(c)]


# ---------------------------------------------------------------------------
# per-run card
# ---------------------------------------------------------------------------


def _latest_frame(run_dir: str, events: List[dict]) -> Optional[str]:
    """Path of the newest snapshot .npz frame: the last ``snapshot``
    event's path when it still exists (the ring prunes), else the
    newest file in ``<run_dir>/snapshots/`` (a copied/committed run's
    events may carry absolute paths from another machine)."""
    for e in reversed(_of_kind(events, "snapshot")):
        p = e.get("path")
        if isinstance(p, str):
            if os.path.exists(p):
                return p
            local = os.path.join(run_dir, "snapshots", os.path.basename(p))
            if os.path.exists(local):
                return local
    ring = sorted(_glob.glob(os.path.join(run_dir, "snapshots", "*.npz")))
    return ring[-1] if ring else None


def _frame_png(path: str) -> Optional[Dict]:
    """Render one .npz ring frame to PNG bytes + meta (None when the
    file is unreadable — a racing ring prune must not kill the page)."""
    from sphexa_tpu.viz import _png_bytes, render_grid

    try:
        with np.load(path, allow_pickle=False) as z:
            grid = np.asarray(z["grid"], np.float64)
            fields = [str(f) for f in z["fields"]] if "fields" in z else []
            it = int(z["it"]) if "it" in z else None
    except (OSError, ValueError, KeyError, zipfile.BadZipFile):
        return None
    if grid.ndim == 4:          # volume frame: render the axis-2 sum
        grid = grid.sum(axis=-1)
    if grid.ndim != 3 or grid.shape[-1] < 2:
        return None
    upsample = max(1, 192 // grid.shape[-1])
    png = _png_bytes(render_grid(grid[0], upsample=upsample))
    return {"png": png, "field": fields[0] if fields else "?", "it": it,
            "path": path}


def build_run_card(run_dir: str) -> Dict:
    """Everything the dashboard shows for one run, reusing the CLI
    summarizers. Unreadable runs degrade to an ``error`` card (rendered
    red) instead of taking the fleet page down."""
    try:
        events, _problems = load_events(run_dir)
        summary = summarize_run(run_dir)
        science = summarize_science(run_dir)
        shards = summarize_shards(run_dir)
        tuning = summarize_tuning_run(run_dir)
    except TelemetryError as e:
        return {"run_dir": run_dir, "name": os.path.basename(
            os.path.normpath(run_dir)), "error": str(e)}
    if not events and summary["schema_problems"]:
        # a file of unparseable lines is corruption, not an idle run
        return {"run_dir": run_dir, "name": os.path.basename(
            os.path.normpath(run_dir)),
            "error": "corrupt events.jsonl: "
                     + "; ".join(summary["schema_problems"][:3])}

    # step-time series for the sparkline (same unification as
    # summarize_run: checked steps + deferred windows' per-step means)
    samples: List[float] = []
    for e in _of_kind(events, "step"):
        if isinstance(e.get("wall_s"), (int, float)):
            samples.append(float(e["wall_s"]))
    for e in _of_kind(events, "window"):
        if isinstance(e.get("per_step_s"), (int, float)) \
                and isinstance(e.get("steps"), int):
            samples.extend([float(e["per_step_s"])] * e["steps"])

    # drift series (per-step etot excursion) for the drift sparkline
    etot = []
    for e in _of_kind(events, "physics"):
        v = e.get("etot")
        etot.extend(float(x) for x in (v if isinstance(v, list) else [v])
                    if isinstance(x, (int, float)))

    snap_events = _of_kind(events, "snapshot")
    frame_path = _latest_frame(run_dir, events)
    return {
        "run_dir": run_dir,
        "name": os.path.basename(os.path.normpath(run_dir)),
        "summary": summary,
        "science": science,
        "shards": shards,
        "tuning": tuning,
        "step_series": samples,
        "etot_series": etot,
        "snapshots": len(snap_events),
        "last_snapshot": snap_events[-1] if snap_events else None,
        "frame": _frame_png(frame_path) if frame_path else None,
        "watchdogs": {
            "drift": science["drift_events"],
            "field_health": science["field_health_events"],
            "imbalance": summary["imbalances"],
        },
        "crash": summary["crash"],
    }


# ---------------------------------------------------------------------------
# rendering
# ---------------------------------------------------------------------------

_CSS = """
body { background:#111418; color:#d8dee9; font-family:monospace;
       margin:1.2em; }
h1 { font-size:1.2em; } h2 { font-size:1.0em; margin:0.2em 0; }
.card { border:1px solid #2e3440; border-radius:6px; padding:0.8em;
        margin:0.8em 0; background:#161a20; }
.card.crash { border-color:#bf3f3f; background:#200909; }
.badge { display:inline-block; padding:0 0.5em; border-radius:3px;
         margin-right:0.4em; }
.ok { background:#1d3321; color:#a3be8c; }
.bad { background:#3b1113; color:#e06c75; }
.warn { background:#332b16; color:#ebcb8b; }
.crashbox { color:#e06c75; white-space:pre-wrap; }
table { border-collapse:collapse; } td, th { padding:0 0.7em 0 0;
        text-align:left; }
.grid { image-rendering:pixelated; border:1px solid #2e3440; }
svg { background:#0d1014; border:1px solid #2e3440; }
.muted { color:#6b7480; }
"""


def _sparkline(values: List[float], width: int = 220, height: int = 36,
               color: str = "#88c0d0") -> str:
    """Inline SVG polyline of one series (empty series -> empty box)."""
    vals = [v for v in values if np.isfinite(v)]
    if len(vals) < 2:
        return (f'<svg width="{width}" height="{height}">'
                f'<text x="4" y="{height - 6}" fill="#6b7480" '
                f'font-size="10">no data</text></svg>')
    lo, hi = min(vals), max(vals)
    span = (hi - lo) or 1.0
    pts = " ".join(
        f"{i * (width - 4) / (len(vals) - 1) + 2:.1f},"
        f"{height - 3 - (v - lo) / span * (height - 6):.1f}"
        for i, v in enumerate(vals))
    return (f'<svg width="{width}" height="{height}">'
            f'<polyline points="{pts}" fill="none" stroke="{color}" '
            f'stroke-width="1.2"/></svg>')


def _bars(pop: List[int], width: int = 160, height: int = 36,
          color: str = "#b48ead") -> str:
    """Inline SVG histogram (the dt_bins bin-occupancy view)."""
    if not pop:
        return ""
    peak = max(max(pop), 1)
    n = len(pop)
    bw = max(2.0, (width - 4) / n - 2)
    bars = "".join(
        f'<rect x="{2 + i * (width - 4) / n:.1f}" '
        f'y="{height - 2 - (v / peak) * (height - 6):.1f}" '
        f'width="{bw:.1f}" '
        f'height="{max(0.5, (v / peak) * (height - 6)):.1f}" '
        f'fill="{color}"/>'
        for i, v in enumerate(pop))
    return f'<svg width="{width}" height="{height}">{bars}</svg>'


def _esc(v) -> str:
    return _html.escape(str(v))


def _fmt_s(v: Optional[float]) -> str:
    if not isinstance(v, (int, float)):
        return "-"
    return f"{v * 1e3:.1f}ms" if v < 1.0 else f"{v:.2f}s"


def _card_html(card: Dict) -> str:
    name = _esc(card["name"])
    if card.get("error"):
        return (f'<div class="card crash"><h2>{name}</h2>'
                f'<div class="crashbox">UNREADABLE: '
                f'{_esc(card["error"])}</div></div>')
    s = card["summary"]
    sci = card["science"]
    sh = card["shards"]
    crash = card["crash"]
    cls = "card crash" if crash else "card"
    bits = [f'<div class="{cls}" id="{name}"><h2>{name}</h2>']

    # status badges: crash > watchdogs > clean
    wd = card["watchdogs"]
    if crash:
        bits.append('<span class="badge bad">CRASHED</span>')
    for key, n in wd.items():
        klass = "bad" if n else "ok"
        bits.append(f'<span class="badge {klass}">{key}: {n}</span>')
    drift = (sci.get("drift") or {}).get("max")
    if drift is not None:
        klass = "warn" if drift > 1e-3 else "ok"
        bits.append(
            f'<span class="badge {klass}">drift {drift:.2e}</span>')

    # headline numbers
    st = s.get("step_time") or {}
    bits.append("<table><tr>"
                f"<td>steps {s['steps']}</td>"
                f"<td>p50 {_fmt_s(st.get('p50_s'))}</td>"
                f"<td>p95 {_fmt_s(st.get('p95_s'))}</td>"
                f"<td>retraces {s['retraces']}</td>"
                f"<td>rollbacks {s['rollbacks']}</td>"
                f"<td>reconfigures {s['reconfigures']}</td>"
                "</tr></table>")

    # sparklines: step time + total energy
    bits.append("<table><tr><th>step time</th><th>etot</th></tr><tr>"
                f"<td>{_sparkline(card['step_series'])}</td>"
                f"<td>{_sparkline(card['etot_series'], color='#a3be8c')}"
                "</td></tr></table>")

    # per-shard load/imbalance
    if sh.get("shards"):
        rows = []
        for row in sh["shards"]:
            share = row.get("work_share")
            occ = row.get("occ_p95")
            rows.append(
                f"<tr><td>{row['shard']}</td>"
                f"<td>{row.get('particles') or '-'}</td>"
                f"<td>{'-' if share is None else f'{share:.1%}'}</td>"
                f"<td>{'-' if occ is None else f'{occ:.2f}'}</td></tr>")
        bits.append(
            "<details open><summary>shards "
            f"(imbalance events: {s['imbalances']})</summary>"
            "<table><tr><th>shard</th><th>particles</th>"
            "<th>work share</th><th>occ p95</th></tr>"
            + "".join(rows) + "</table></details>")

    # dt_bins histogram
    bins = sci.get("dt_bins")
    if bins:
        saved = bins.get("saved_factor")
        bits.append(
            "<details open><summary>dt_bins "
            f"(saved {'-' if saved is None else f'{saved:.1f}x'})"
            f"</summary>{_bars(bins.get('pop') or [])}</details>")

    # tuning provenance
    stamp = card["tuning"].get("manifest_tuning")
    if stamp:
        knobs = ", ".join(f"{k}={v}" for k, v in
                          sorted((stamp.get("knobs") or {}).items()))
        bits.append(f'<div class="muted">tuning: '
                    f'{_esc(stamp.get("source"))} {_esc(knobs)}</div>')

    # latest field frame from the snapshot ring
    frame = card.get("frame")
    if frame:
        b64 = base64.b64encode(frame["png"]).decode("ascii")
        bits.append(
            f'<div>field <b>{_esc(frame["field"])}</b> @ it '
            f'{frame["it"]} <span class="muted">'
            f'({card["snapshots"]} snapshot events)</span><br>'
            f'<img class="grid" src="data:image/png;base64,{b64}" '
            f'alt="field frame"/></div>')
    elif card["snapshots"]:
        bits.append(f'<div class="muted">{card["snapshots"]} snapshot '
                    f'events, no readable .npz frame</div>')

    # crash blackbox, rendered red
    if crash:
        tail = "\n".join(crash.get("traceback_tail") or [])
        wds = ", ".join(f"{k}={v}" for k, v in
                        (crash.get("watchdogs") or {}).items())
        bits.append(
            '<div class="crashbox"><b>CRASH</b>: '
            f'{_esc(crash.get("reason"))}\n'
            f'watchdogs: {_esc(wds or "-")}\n{_esc(tail)}</div>')
    bits.append("</div>")
    return "\n".join(bits)


def render_html(cards: List[Dict], refresh: Optional[float] = None,
                title: str = "sphexa fleet") -> str:
    """The whole dashboard as one self-contained HTML string."""
    meta = (f'<meta http-equiv="refresh" content="{refresh:g}">'
            if refresh else "")
    crashed = sum(1 for c in cards if c.get("crash") or c.get("error"))
    head = (f"<h1>{_esc(title)} — {len(cards)} run"
            f"{'s' if len(cards) != 1 else ''}, {crashed} "
            f"crashed/unreadable <span class='muted'>"
            f"({time.strftime('%Y-%m-%d %H:%M:%S')})</span></h1>")
    body = "\n".join(_card_html(c) for c in cards)
    return (f"<!doctype html><html><head><meta charset='utf-8'>{meta}"
            f"<title>{_esc(title)}</title><style>{_CSS}</style></head>"
            f"<body>{head}{body}</body></html>")


# ---------------------------------------------------------------------------
# fleet table
# ---------------------------------------------------------------------------


def fleet_rows(run_dirs: List[str]) -> List[Dict]:
    return [build_run_card(d) for d in run_dirs]


def render_fleet(cards: List[Dict]) -> str:
    rows = []
    for c in cards:
        if c.get("error"):
            rows.append((c["name"], "-", "-", "-", "-", "UNREADABLE"))
            continue
        st = (c["summary"].get("step_time") or {})
        drift = (c["science"].get("drift") or {}).get("max")
        wd = sum(c["watchdogs"].values())
        status = "CRASHED" if c["crash"] else (
            "watchdog" if wd else "ok")
        rows.append((
            c["name"], c["summary"]["steps"], _fmt_s(st.get("p50_s")),
            "-" if drift is None else f"{drift:.2e}",
            c["snapshots"], status,
        ))
    return render_table(
        rows, headers=("run", "steps", "p50", "drift", "frames",
                       "status"))


# ---------------------------------------------------------------------------
# CLI entry points (wired from telemetry/cli.py)
# ---------------------------------------------------------------------------


def serve_cmd(target: str, out: Optional[str] = None,
              port: Optional[int] = None, refresh: float = 5.0,
              once: bool = False) -> int:
    """The ``serve`` subcommand. ``--once`` renders a single page and
    exits (the CI shape); ``--port`` serves it via http.server,
    regenerating per request; the default loop rewrites ``--out`` every
    ``--refresh`` seconds until interrupted."""
    runs = discover_runs(target)
    if not runs:
        print(f"sphexa-telemetry serve: no run directories match "
              f"{target!r}", file=sys.stderr)
        return 1

    def render() -> str:
        cards = fleet_rows(discover_runs(target) or runs)
        return render_html(cards, refresh=None if once else refresh,
                           title=f"sphexa fleet: {target}")

    page = render()
    cards_now = fleet_rows(runs)
    if all(c.get("error") for c in cards_now):
        for c in cards_now:
            print(f"sphexa-telemetry serve: {c['run_dir']}: "
                  f"{c['error']}", file=sys.stderr)
        return 2

    out = out or "sphexa-dashboard.html"
    if port is not None:
        import http.server

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):      # noqa: N802 (stdlib API name)
                body = render().encode("utf-8")
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/html; charset=utf-8")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # quiet: the page IS the log
                pass

        with http.server.ThreadingHTTPServer(("", port), Handler) as srv:
            print(f"serving {len(runs)} run(s) on http://localhost:{port}")
            try:
                srv.serve_forever()
            except KeyboardInterrupt:
                pass
        return 0

    with open(out, "w") as f:
        f.write(page)
    print(f"wrote {out} ({len(runs)} run(s))")
    if once:
        return 0
    try:
        while True:
            time.sleep(max(0.5, refresh))
            with open(out, "w") as f:
                f.write(render())
    except KeyboardInterrupt:
        pass
    return 0


def fleet_cmd(target: str, fmt: str = "text") -> int:
    """The ``fleet`` subcommand: the text aggregation table."""
    runs = discover_runs(target)
    if not runs:
        print(f"sphexa-telemetry fleet: no run directories match "
              f"{target!r}", file=sys.stderr)
        return 1
    cards = fleet_rows(runs)
    if all(c.get("error") for c in cards):
        for c in cards:
            print(f"sphexa-telemetry fleet: {c['run_dir']}: "
                  f"{c['error']}", file=sys.stderr)
        return 2
    if fmt == "json":
        view = []
        for c in cards:
            status = ("UNREADABLE" if c.get("error")
                      else "CRASHED" if c.get("crash")
                      else "watchdog" if sum(c["watchdogs"].values())
                      else "ok")
            view.append({k: c.get(k) for k in
                         ("run_dir", "name", "error", "snapshots",
                          "watchdogs")} | {"status": status})
        print(json.dumps(view, indent=2))
    else:
        print(render_fleet(cards))
    return 0
