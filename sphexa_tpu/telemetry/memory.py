"""Per-device HBM accounting: memory_stats() snapshots as telemetry.

The multi-chip campaign's memory question is per-DEVICE: a 64M run on
v5e-16 lives or dies on the worst shard's peak, not the mean
(scripts/measure_hbm.py extrapolates 4M particles/chip against 16 GiB).
This module is the one place that folds ``device.memory_stats()`` into
the event stream, at three well-defined points:

- ``manifest``: right after Simulation construction (app/main.py) — the
  pre-compile residency (state arrays + constants);
- ``post-compile``: after the first step's fetch completes — first
  executable + workspace are resident, the number reconfigures grow from;
- ``flush``: at each deferred-window flush — the steady-state peak.

Host-side allocator metadata only: ``memory_stats()`` never syncs the
device stream, so snapshots are legal inside the zero-sync deferred
window (pinned by tests/test_telemetry.py). Backends without allocator
stats (CPU) report empty byte lists — the events still mark the points
so CPU-mesh rehearsals validate the same schema the chip run writes.
"""

from typing import Dict, List, Optional

#: memory_stats() keys folded into the snapshot, in event-field order
_STAT_KEYS = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit")


def device_memory_snapshot(devices=None) -> Dict[str, List]:
    """Per-device allocator stats: ``{"devices": [...], "bytes_in_use":
    [...], "peak_bytes_in_use": [...], "bytes_limit": [...]}``. Lists are
    parallel over devices; byte lists are empty when NO device reports
    stats (CPU), and 0-filled per device that individually lacks a key.
    Never raises — a telemetry probe must not sink the run it measures."""
    try:
        import jax

        devices = list(devices) if devices is not None \
            else jax.local_devices()
    except Exception:
        return {"devices": [], **{k: [] for k in _STAT_KEYS}}
    names: List[str] = []
    stats: List[dict] = []
    for d in devices:
        names.append(str(getattr(d, "id", d)))
        try:
            stats.append(d.memory_stats() or {})
        except Exception:
            stats.append({})
    out: Dict[str, List] = {"devices": names}
    if any(stats):
        for k in _STAT_KEYS:
            out[k] = [int(s.get(k, 0)) for s in stats]
    else:
        for k in _STAT_KEYS:
            out[k] = []
    return out


def emit_memory_event(telemetry, point: str, devices=None,
                      **extra) -> Optional[Dict[str, List]]:
    """Snapshot + emit one ``memory`` event (kind schema v2). Skipped
    entirely on a sink-less registry: the snapshot exists to be
    persisted, and a counter bump alone is not worth P devices' stat
    calls per flush. Returns the snapshot (None when skipped)."""
    if telemetry is None or not telemetry.sinks:
        return None
    snap = device_memory_snapshot(devices)
    telemetry.event("memory", point=point, **snap, **extra)
    return snap


def save_memory_profile(path: str) -> bool:
    """Opt-in ``jax.profiler`` device-memory-profile dump (pprof format):
    the allocation-site breakdown behind a surprising snapshot number.
    Returns whether a file was written (False when jax or the profiler
    is unavailable — callers report, never crash)."""
    try:
        import jax

        jax.profiler.save_device_memory_profile(path)
        return True
    except Exception:
        return False
