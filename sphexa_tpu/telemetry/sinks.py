"""Telemetry sinks: where the event stream lands.

All sinks implement ``emit(event: dict)`` and ``close()``. Events arrive
fully materialized (plain-Python payloads — the registry coerces numpy
scalars), so a sink never touches device arrays.
"""

import json
import os
from typing import Callable, List, Optional


class MemorySink:
    """In-memory event list (tests, bench introspection)."""

    def __init__(self):
        self.events: List[dict] = []

    def emit(self, event: dict) -> None:
        self.events.append(event)

    def close(self) -> None:
        pass

    def of_kind(self, kind: str) -> List[dict]:
        return [e for e in self.events if e.get("kind") == kind]


class JsonlSink:
    """JSONL event log — the persisted per-run record the
    ``sphexa-telemetry`` CLI consumes. One event per line, flushed per
    line so a killed run still leaves a readable prefix. The file is
    TRUNCATED on this sink's first emit: one sink = one run, matching
    the manifest overwrite — re-running into the same --telemetry-dir
    must not merge two runs' samples under one manifest."""

    def __init__(self, path: str):
        self.path = path
        self._f = None

    def emit(self, event: dict) -> None:
        if self._f is None:
            d = os.path.dirname(self.path)
            if d:
                os.makedirs(d, exist_ok=True)
            self._f = open(self.path, "w")
        self._f.write(json.dumps(event, separators=(",", ":")) + "\n")
        self._f.flush()

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None


#: event kinds worth a human line (the exceptional-control-flow ones a
#: console reader actually wants to see; per-step launch/phases spam is
#: left to the JSONL record)
_NOTABLE = ("reconfigure", "rollback", "replay", "retrace", "trace",
            "imbalance", "drift", "field_health", "tuning")


class ConsoleSink:
    """Human console: renders notable events as ``# telemetry ...`` lines
    and exposes ``write_line`` for the driver's per-iteration report
    (Simulation.run routes through it via console_printer)."""

    def __init__(self, printer: Callable = print,
                 kinds: Optional[tuple] = _NOTABLE):
        self._print = printer
        self._kinds = kinds

    def write_line(self, line: str) -> None:
        self._print(line)

    def emit(self, event: dict) -> None:
        if self._kinds is not None and event.get("kind") not in self._kinds:
            return
        body = " ".join(
            f"{k}={v}" for k, v in event.items()
            if k not in ("v", "seq", "t", "kind")
        )
        self._print(f"# telemetry {event.get('kind')}: {body}")

    def close(self) -> None:
        pass
