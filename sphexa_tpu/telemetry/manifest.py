"""Run manifest: the who/what/where stamp that makes runs comparable.

Every telemetry-enabled run writes ``manifest.json`` next to
``events.jsonl``; bench.py stamps the same structure into its JSON line.
``sphexa-telemetry diff`` refuses nothing but warns on mismatched
environments — a regression across different jax versions or mesh shapes
is a different conversation than one on identical setups.
"""

import datetime
import json
import os
import subprocess
import sys
from typing import Dict, Optional

#: manifest schema version (independent of the event schema)
MANIFEST_SCHEMA = 1


def git_rev() -> str:
    """Short git revision of the source tree, or 'unknown' outside a
    checkout (installed wheels, stripped containers)."""
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    try:
        out = subprocess.run(
            ["git", "-C", root, "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, timeout=10,
        )
        rev = out.stdout.strip()
        return rev if out.returncode == 0 and rev else "unknown"
    except (OSError, subprocess.SubprocessError):
        return "unknown"


def build_manifest(config: Optional[Dict] = None,
                   particles: Optional[int] = None,
                   mesh_shape=None,
                   extra: Optional[Dict] = None) -> Dict:
    """Assemble the manifest dict (jax/backend versions resolved here, so
    callers that already initialized a backend pay nothing extra)."""
    try:
        import jax

        jax_version = jax.__version__
        backend = jax.default_backend()
        device_count = jax.device_count()
    except Exception:  # manifest must never sink the run it describes
        jax_version, backend, device_count = "unknown", "unknown", 0
    from sphexa_tpu.telemetry.registry import SCHEMA_VERSION

    return {
        "schema": MANIFEST_SCHEMA,
        # the event-stream schema this run's writer speaks (events.jsonl
        # carries it per event too; stamped here so readers can tell a
        # pre-v3 run without scanning the stream)
        "events_schema": SCHEMA_VERSION,
        "created": datetime.datetime.now(datetime.timezone.utc).isoformat(),
        "git_rev": git_rev(),
        "jax_version": jax_version,
        "backend": backend,
        "device_count": device_count,
        "mesh_shape": list(mesh_shape) if mesh_shape is not None else None,
        "python": sys.version.split()[0],
        "platform": sys.platform,
        "particles": int(particles) if particles is not None else None,
        "config": config or {},
        **(extra or {}),
    }


def write_manifest(run_dir: str, **kwargs) -> Dict:
    """Build + persist ``<run_dir>/manifest.json``; returns the dict."""
    manifest = build_manifest(**kwargs)
    os.makedirs(run_dir, exist_ok=True)
    with open(os.path.join(run_dir, "manifest.json"), "w") as f:
        json.dump(manifest, f, indent=2, default=str)
        f.write("\n")
    return manifest


def read_manifest(run_dir: str) -> Optional[Dict]:
    path = os.path.join(run_dir, "manifest.json")
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f)
