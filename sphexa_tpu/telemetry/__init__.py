"""Unified telemetry: structured step metrics, zero-sync device timing,
profiler trace hooks, and persisted run artifacts.

The reference prints a per-phase Timer every iteration and dumps the
series with --profile (main/src/util/timer.hpp, ipropagator.hpp:80-119).
Here the same role is played by ONE registry (`Telemetry`) with pluggable
sinks:

- ``JsonlSink``  — append-only ``events.jsonl`` per run (the persisted,
  diffable record a regression gate can consume);
- ``MemorySink`` — in-memory event list for tests;
- ``ConsoleSink``— human-readable notable-event lines.

Design constraint (the reason this is not just a logger): on deferred
check windows (``Simulation(check_every > 1)``) the happy path is
sync-free by design — telemetry may only timestamp launches host-side
and count events; device time is attributed per WINDOW at ``flush()``,
whose batched diagnostics fetch is the block boundary that already
exists. Nothing in this package ever adds a device->host transfer to
the hot loop (pinned by tests/test_telemetry.py's no-sync guard).

``sphexa-telemetry`` (telemetry/cli.py) summarizes a run directory
(p50/p95 step time, retrace/rollback counts, phase means) and diffs two
runs — or a run against a ``BENCH_r*.json`` round — with threshold-based
exit codes. See docs/OBSERVABILITY.md for the event schema.
"""

from sphexa_tpu.telemetry.flightrec import (
    FlightRecorder,
    RingSink,
    read_blackbox,
)
from sphexa_tpu.telemetry.manifest import (
    MANIFEST_SCHEMA,
    build_manifest,
    read_manifest,
    write_manifest,
)
from sphexa_tpu.telemetry.memory import (
    device_memory_snapshot,
    emit_memory_event,
    save_memory_profile,
)
from sphexa_tpu.telemetry.registry import (
    EVENT_KINDS,
    SCHEMA_VERSION,
    LapTimer,
    StepSeries,
    Telemetry,
)
from sphexa_tpu.telemetry.sinks import ConsoleSink, JsonlSink, MemorySink

__all__ = [
    "Telemetry",
    "LapTimer",
    "StepSeries",
    "JsonlSink",
    "MemorySink",
    "ConsoleSink",
    "SCHEMA_VERSION",
    "EVENT_KINDS",
    "MANIFEST_SCHEMA",
    "build_manifest",
    "write_manifest",
    "read_manifest",
    "device_memory_snapshot",
    "emit_memory_event",
    "save_memory_profile",
    "FlightRecorder",
    "RingSink",
    "read_blackbox",
]
