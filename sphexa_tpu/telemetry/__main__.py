"""``python -m sphexa_tpu.telemetry`` — the sphexa-telemetry CLI."""

import sys

from sphexa_tpu.telemetry.cli import main

if __name__ == "__main__":
    sys.exit(main())
