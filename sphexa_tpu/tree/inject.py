"""Key injection: force specific SFC keys to exist as leaf boundaries.

Counterpart of ``cstone/focus/inject.hpp`` (injectKeys): guarantee
mandatory resolution at given keys (e.g. domain boundaries, focus
anchors) while preserving the cornerstone invariant that every leaf
spans an aligned power-of-8 key range — each refinement therefore adds
a full 8-child split of the containing leaf, level by level, until the
key is a boundary.
"""

import numpy as np

from sphexa_tpu.dtypes import KEY_BITS
from sphexa_tpu.tree.csarray import KEY_RANGE, _as_keys


def inject_keys(tree: np.ndarray, keys) -> np.ndarray:
    """Return a valid cornerstone tree with every ``key`` on a leaf
    boundary (injectKeys, inject.hpp:26-99)."""
    tree = _as_keys(tree)
    inject = np.unique(_as_keys(keys))
    inject = inject[(inject > 0) & (inject < KEY_RANGE)]
    boundaries = set(tree.tolist())

    for k in inject.tolist():
        if k in boundaries:
            continue
        # walk down from the root octant containing k; at each level add
        # the full sibling split of the containing node (7 interior
        # boundaries) so the power-of-8 invariant survives
        for level in range(1, KEY_BITS + 1):
            span = int(KEY_RANGE) >> (3 * level)
            if span == 0:
                break
            node_start = (k // (span * 8)) * (span * 8)
            for j in range(1, 8):
                boundaries.add(node_start + j * span)
            if k % span == 0:
                break

    return np.array(sorted(boundaries), dtype=np.uint64)
