"""Cornerstone octrees: sorted-key leaf arrays and domain decomposition.

TPU-native rethink of the reference's ``cstone/tree/csarray.hpp`` and
``cstone/domain/domaindecomp.hpp``: the octree IS a sorted array of SFC keys
(node i spans [tree[i], tree[i+1])), counts come from vectorized
searchsorted, and rebalancing is a scan + scatter — no pointers, no
recursion.
"""

from sphexa_tpu.tree.csarray import (
    compute_node_counts,
    compute_octree,
    make_root_tree,
    make_uniform_tree,
    node_levels,
    rebalance_tree,
    update_octree,
)
from sphexa_tpu.tree.decomposition import make_sfc_assignment, uniform_bins

__all__ = [
    "compute_node_counts",
    "compute_octree",
    "make_root_tree",
    "make_uniform_tree",
    "node_levels",
    "rebalance_tree",
    "update_octree",
    "make_sfc_assignment",
    "uniform_bins",
]
