"""Continuum octree: cornerstone build from an analytic density function.

Counterpart of ``cstone/tree/continuum.hpp`` (computeContinuumCsarray):
instead of counting particles per leaf, the expected count is the density
integral over the leaf's volume — used to pre-build trees for initial
conditions and tests without generating particles first.

The integral is estimated with a fixed 2x2x2 sub-sample per leaf (midpoint
rule per octant), which is exact for (tri)linear densities and within a
few percent for the smooth profiles ICs use; the count-rebalance loop
only needs counts at bucket-size accuracy.
"""

from typing import Callable, Tuple

import numpy as np

from sphexa_tpu.dtypes import KEY_BITS
from sphexa_tpu.sfc.hilbert import hilbert_decode
from sphexa_tpu.sfc.morton import morton_decode
from sphexa_tpu.tree.csarray import (
    make_root_tree,
    node_levels,
    rebalance_tree,
)


def _leaf_boxes(tree: np.ndarray, box_lo, box_lengths, curve: str):
    """(lo (L, 3), edge (L,)) AABBs of the leaves in box coordinates."""
    import jax.numpy as jnp

    starts = np.asarray(tree[:-1], np.uint64)
    levels = node_levels(tree)
    decode = hilbert_decode if curve == "hilbert" else morton_decode
    ix, iy, iz = decode(jnp.asarray(starts.astype(np.uint32)))
    cells = np.stack([np.asarray(ix), np.asarray(iy), np.asarray(iz)], axis=1)
    shift = (KEY_BITS - levels)[:, None]
    octant = cells >> shift
    inv = 1.0 / (1 << levels).astype(np.float64)
    lo = np.asarray(box_lo, np.float64)[None, :] + octant * (
        inv[:, None] * np.asarray(box_lengths, np.float64)[None, :]
    )
    edge = inv[:, None] * np.asarray(box_lengths, np.float64)[None, :]
    return lo, edge


def continuum_counts(
    tree: np.ndarray,
    rho_fn: Callable,
    box_lo,
    box_lengths,
    n_total: int,
    curve: str = "hilbert",
) -> np.ndarray:
    """Expected particle count per leaf: N * integral(rho)/integral_total,
    midpoint-sampled on a 2x2x2 subgrid per leaf (continuum.hpp role)."""
    lo, edge = _leaf_boxes(tree, box_lo, box_lengths, curve)
    vol = np.prod(edge, axis=1)
    acc = np.zeros(len(vol), np.float64)
    for ox in (0.25, 0.75):
        for oy in (0.25, 0.75):
            for oz in (0.25, 0.75):
                p = lo + edge * np.array([ox, oy, oz])
                acc += rho_fn(p[:, 0], p[:, 1], p[:, 2])
    mass = acc / 8.0 * vol
    total = mass.sum()
    if total <= 0.0:
        return np.zeros(len(vol), np.int64)
    return np.round(mass / total * n_total).astype(np.int64)


def compute_continuum_octree(
    rho_fn: Callable,
    box_lo,
    box_lengths,
    n_total: int,
    bucket_size: int,
    curve: str = "hilbert",
    max_iterations: int = 64,
) -> Tuple[np.ndarray, np.ndarray]:
    """Converged cornerstone tree for an analytic density
    (computeContinuumCsarray, continuum.hpp): iterate expected-count ->
    rebalance from the root until stable."""
    tree = make_root_tree()
    counts = continuum_counts(tree, rho_fn, box_lo, box_lengths, n_total, curve)
    for _ in range(max_iterations):
        tree, converged = rebalance_tree(tree, counts, bucket_size)
        counts = continuum_counts(
            tree, rho_fn, box_lo, box_lengths, n_total, curve
        )
        if converged:
            break
    return tree, counts
