"""Cornerstone leaf-array octree build (count -> rebalance iteration).

Re-designs the reference's ``cstone/tree/csarray.hpp`` (computeNodeCounts
:203, calculateNodeOp :291, rebalanceTree :399, updateOctree :433,
computeOctree :456) as vectorized array ops:

- a tree is a sorted uint32 key array ``tree`` of length ``numLeaves+1``
  with ``tree[0] == 0`` and ``tree[-1] == 2**30``; leaf ``i`` covers the key
  range ``[tree[i], tree[i+1])`` and every leaf spans a power-of-8 range
  aligned to its level (the cornerstone invariant, csarray.hpp:26-50);
- particle counts per leaf are two vectorized ``searchsorted`` calls;
- one rebalance step computes a per-node op (1 keep / 8 split / 0 merged
  into parent), an exclusive scan of ops, and a scatter of new node keys.

The build runs eagerly on host (numpy): tree construction happens at domain
sync granularity, not per interaction, and its output feeds static-shaped
device structures (cell grids, assignment bins). A fixed-capacity on-device
variant can be slotted in later without changing callers.
"""

from typing import Tuple

import numpy as np

from sphexa_tpu.dtypes import KEY_BITS

KEY_RANGE = np.uint64(1) << np.uint64(3 * KEY_BITS)


def _as_keys(a) -> np.ndarray:
    """Keys are widened to uint64 on host so 2**30 (one-past-max) is exact."""
    return np.asarray(a, dtype=np.uint64)


def make_root_tree() -> np.ndarray:
    """The minimal tree: a single root leaf covering the whole key space."""
    return np.array([0, KEY_RANGE], dtype=np.uint64)


def make_uniform_tree(level: int) -> np.ndarray:
    """Fully refined tree at ``level``: 8**level equal leaves."""
    n = 1 << (3 * level)
    return (np.arange(n + 1, dtype=np.uint64) * (KEY_RANGE // np.uint64(n)))


def node_levels(tree: np.ndarray) -> np.ndarray:
    """Octree level of each leaf, from its key span (power-of-8 invariant)."""
    spans = np.diff(_as_keys(tree))
    levels = (3 * KEY_BITS - np.round(np.log2(spans.astype(np.float64))).astype(np.int64)) // 3
    return levels


def compute_node_counts(tree: np.ndarray, sorted_keys: np.ndarray) -> np.ndarray:
    """Particle count per leaf via binary search over the sorted key array.

    Equivalent of computeNodeCounts (csarray.hpp:203) — but where the
    reference walks with upper/lower bounds per node, here a single
    vectorized searchsorted over all node boundaries does the job.
    """
    tree = _as_keys(tree)
    keys = _as_keys(sorted_keys)
    edges = np.searchsorted(keys, tree, side="left")
    return np.diff(edges).astype(np.int64)


def _node_ops(tree: np.ndarray, counts: np.ndarray, bucket_size: int) -> np.ndarray:
    """Per-leaf rebalance op: 8 = split, 1 = keep, 0 = merged into parent.

    Mirrors the decision logic of calculateNodeOp (csarray.hpp:291): split
    when over-full and not at max depth; merge 8 siblings into their parent
    when the parent total fits in the bucket (op 1 on the first sibling
    standing in for the parent, op 0 on the other seven).
    """
    tree = _as_keys(tree)
    spans = np.diff(tree)
    levels = node_levels(tree)
    n = len(counts)

    ops = np.ones(n, dtype=np.int64)
    ops[(counts > bucket_size) & (levels < KEY_BITS)] = 8

    # Merge candidates: groups of 8 consecutive leaves that are exact
    # siblings (same parent range, aligned) with combined count <= bucket.
    if n >= 8:
        starts = tree[:-1]
        parent_span = spans * np.uint64(8)
        is_first_sibling = (
            (np.arange(n) + 8 <= n)
            & (starts % np.maximum(parent_span, 1) == 0)
        )
        idx = np.flatnonzero(is_first_sibling)
        if len(idx):
            # all 8 spans equal and contiguous -> true sibling group
            span_ok = np.ones(len(idx), dtype=bool)
            total = np.zeros(len(idx), dtype=np.int64)
            for j in range(8):
                span_ok &= spans[np.minimum(idx + j, n - 1)] == spans[idx]
                total += counts[np.minimum(idx + j, n - 1)]
            merge = span_ok & (total <= bucket_size) & (levels[idx] > 0)
            for j in range(1, 8):
                ops[idx[merge] + j] = 0
            ops[idx[merge]] = 1  # becomes the parent
            # tag the merge so the scatter step emits the parent key span
            ops = ops.astype(np.int64)
            merged_first = np.zeros(n, dtype=bool)
            merged_first[idx[merge]] = True
            return ops, merged_first
    return ops, np.zeros(n, dtype=bool)


def rebalance_tree(
    tree: np.ndarray, counts: np.ndarray, bucket_size: int
) -> Tuple[np.ndarray, bool]:
    """One count-and-rebalance step; returns (new_tree, converged).

    Equivalent of rebalanceTree (csarray.hpp:399).
    """
    tree = _as_keys(tree)
    ops, merged_first = _node_ops(tree, counts, bucket_size)
    converged = bool(np.all(ops == 1) and not merged_first.any())
    if converged:
        return tree, True

    offsets = np.concatenate([[0], np.cumsum(ops)])
    new_tree = np.zeros(offsets[-1] + 1, dtype=np.uint64)
    spans = np.diff(tree)

    keep = np.flatnonzero(ops == 1)
    new_tree[offsets[keep]] = tree[keep]

    split = np.flatnonzero(ops == 8)
    if len(split):
        child_span = spans[split] // np.uint64(8)
        for j in range(8):
            new_tree[offsets[split] + j] = tree[split] + np.uint64(j) * child_span
    new_tree[-1] = KEY_RANGE
    return new_tree, False


def update_octree(
    sorted_keys: np.ndarray, tree: np.ndarray, bucket_size: int
) -> Tuple[np.ndarray, np.ndarray, bool]:
    """One iteration of (counts, rebalance); returns (tree, counts, converged).

    Equivalent of updateOctree (csarray.hpp:433).
    """
    counts = compute_node_counts(tree, sorted_keys)
    new_tree, converged = rebalance_tree(tree, counts, bucket_size)
    if not converged:
        counts = compute_node_counts(new_tree, sorted_keys)
    return new_tree, counts, converged


def compute_octree(
    sorted_keys: np.ndarray, bucket_size: int, max_iterations: int = 64
) -> Tuple[np.ndarray, np.ndarray]:
    """Build a converged cornerstone tree from scratch.

    Equivalent of computeOctree (csarray.hpp:456): iterate update_octree
    from the root until no node wants to split or merge.
    """
    tree = make_root_tree()
    counts = compute_node_counts(tree, sorted_keys)
    for _ in range(max_iterations):
        tree, counts, converged = update_octree(sorted_keys, tree, bucket_size)
        if converged:
            break
    return tree, counts
