"""SFC domain decomposition: equal-count key ranges per device.

Equivalent of the reference's ``cstone/domain/domaindecomp.hpp``
(uniformBins :49, SfcAssignment/makeSfcAssignment :74-116): split the
global, SFC-ordered leaf counts into contiguous segments of approximately
equal particle count. Each segment becomes the key range owned by one
device in the mesh.
"""

from typing import Tuple

import numpy as np


def uniform_bins(tree: np.ndarray, counts: np.ndarray, num_bins: int) -> np.ndarray:
    """Choose ``num_bins + 1`` split keys so each bin holds ~equal counts.

    Returns an array of SFC keys; bin ``r`` owns ``[keys[r], keys[r+1])``.
    Splits always fall on leaf boundaries of ``tree`` (like the reference,
    which never splits a leaf across ranks).
    """
    tree = np.asarray(tree, dtype=np.uint64)
    csum = np.concatenate([[0], np.cumsum(counts)])
    total = csum[-1]
    targets = (np.arange(1, num_bins) * total) // num_bins
    # leaf index whose cumulative count first reaches each target
    split_leaves = np.searchsorted(csum, targets, side="left")
    split_leaves = np.clip(split_leaves, 1, len(tree) - 1)
    # enforce strictly increasing boundaries even for tiny trees
    split_leaves = np.maximum.accumulate(split_leaves)
    for i in range(1, len(split_leaves)):
        if split_leaves[i] <= split_leaves[i - 1]:
            split_leaves[i] = min(split_leaves[i - 1] + 1, len(tree) - 1)
    return np.concatenate([[tree[0]], tree[split_leaves], [tree[-1]]])


def make_sfc_assignment(
    sorted_keys: np.ndarray, num_ranks: int, bucket_size: int = 64
) -> Tuple[np.ndarray, np.ndarray]:
    """Build a tree over all keys and return (assignment_keys, counts_per_rank).

    Equivalent of makeSfcAssignment (domaindecomp.hpp:116): the returned
    boundary keys define, for every device, the contiguous Hilbert-key slab
    it owns. Balance quality is bounded by bucket_size granularity.
    """
    from sphexa_tpu.tree.csarray import compute_octree

    tree, counts = compute_octree(sorted_keys, bucket_size)
    bins = uniform_bins(tree, counts, num_ranks)
    keys = np.asarray(sorted_keys, dtype=np.uint64)
    edges = np.searchsorted(keys, bins, side="left")
    return bins, np.diff(edges)
