"""Radiative cooling: reduced tabulated model (GRACKLE-equivalent role).

Counterpart of the reference's ``physics/cooling/`` (cooler.hpp wraps the
external GRACKLE C/Fortran library: per-particle chemistry, u<->T
conversion, cooling timestep limiter ct_crit, cooling-aware EOS,
std_hydro_grackle.hpp couples it after the force stage). The TPU build
replaces the library with a self-contained, jit-compatible model:

- a collisional-ionization-equilibrium (CIE) cooling curve Lambda(T),
  tabulated at solar composition (piecewise log-log interpolation; the
  table is a config field, so a user can substitute e.g. a Sutherland &
  Dopita or GRACKLE-generated table);
- optional constant photoelectric heating rate Gamma;
- a reduced ChemistryData carrying the ionization fractions the reference
  tracks (they set the mean molecular weight; the CIE assumption makes
  them diagnostic rather than evolved ODEs);
- sub-cycled semi-implicit integration of du/dt inside the jitted step
  (replacing GRACKLE's internal stiff solver), with the same ct_crit
  timestep limiter contract (eos_cooling.hpp:12-25).

Unit handling: the simulation runs in code units; CoolingConfig carries
the code->cgs conversions (mass, length, and the G=1 time unit), matching
the reference's cooling::m_code_in_ms / l_code_in_kpc attributes
(evrard_cooling_init.hpp:59-60).
"""

import dataclasses
from typing import Tuple

import numpy as np
import jax
import jax.numpy as jnp

# cgs constants
KB = 1.380658e-16          # erg/K
MH = 1.6726231e-24         # g
G_CGS = 6.6726e-8          # cm^3 g^-1 s^-2
MSUN = 1.98892e33          # g
KPC = 3.0856776e21         # cm

# Approximate solar-metallicity CIE cooling curve, log10 T [K] ->
# log10 Lambda [erg cm^3 / s]: H/He + metal line peak near 1e5 K,
# bremsstrahlung ~ sqrt(T) beyond 1e7.5 K. Control points follow the
# canonical shape of Sutherland & Dopita (1993) to ~0.1 dex.
_LOGT_TABLE = np.array(
    [3.8, 4.0, 4.2, 4.6, 5.0, 5.4, 5.8, 6.2, 6.6, 7.0, 7.5, 8.0, 8.5]
)
_LOGL_TABLE = np.array(
    [-28.0, -23.2, -21.8, -21.4, -21.1, -21.3, -21.7, -22.1, -22.5,
     -22.7, -22.65, -22.55, -22.4]
)


@dataclasses.dataclass(frozen=True)
class CoolingConfig:
    """Static cooling parameters + unit system (cooler.hpp attributes)."""

    ct_crit: float = 0.1            # cooling-time step fraction (cooler.hpp:90)
    gamma: float = 5.0 / 3.0
    mu: float = 0.6                 # mean molecular weight (ionized solar)
    hydrogen_fraction: float = 0.76
    heating_rate: float = 0.0       # Gamma, erg/s per H atom (photoelectric)
    # code -> cgs conversions (evrard_cooling_init: m_code_in_ms, l_code_in_kpc)
    m_code_g: float = 1e16 * MSUN
    l_code_cm: float = 46400.0 * KPC
    substeps: int = 8               # sub-cycles of the semi-implicit update
    logT_table: Tuple[float, ...] = tuple(_LOGT_TABLE)
    logL_table: Tuple[float, ...] = tuple(_LOGL_TABLE)
    # evolve the 6-species primordial network (physics/primordial.py) in
    # place of the CIE table: species ODEs + composition-resolved cooling
    # per step, the cooler.cpp solve_chemistry role. False keeps the
    # metal-inclusive CIE curve with diagnostic-only fractions.
    evolve_species: bool = False

    @property
    def t_code_s(self) -> float:
        """G=1 time unit: sqrt(l^3 / (G m))."""
        return float(np.sqrt(self.l_code_cm**3 / (G_CGS * self.m_code_g)))

    @property
    def rho_to_cgs(self) -> float:
        return float(self.m_code_g / self.l_code_cm**3)

    @property
    def u_to_cgs(self) -> float:
        """specific energy: (l/t)^2."""
        return float((self.l_code_cm / self.t_code_s) ** 2)

    # The raw cgs chain (rho_cgs ~ 1e-41 g/cm^3 at these units) under- and
    # overflows float32, so the conversions are folded into two host-side
    # prefactors and the device math stays in code-unit magnitudes:
    #   du/dt_cool [code] = -10^(logL + log_cool_prefac) * rho_code
    #   du/dt_heat [code] = heating_code
    @property
    def log_cool_prefac(self) -> float:
        """log10 of (X/m_H)^2 * rho_to_cgs * t_code / u_to_cgs."""
        x_over_mh = self.hydrogen_fraction / MH
        return float(
            2.0 * np.log10(x_over_mh)
            + np.log10(self.rho_to_cgs)
            + np.log10(self.t_code_s)
            - np.log10(self.u_to_cgs)
        )

    @property
    def heating_code(self) -> float:
        """specific heating rate X Gamma / m_H in code units per code time."""
        if self.heating_rate == 0.0:
            return 0.0
        return float(
            self.hydrogen_fraction * self.heating_rate / MH
            * self.t_code_s / self.u_to_cgs
        )


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class ChemistryData:
    """Reduced per-particle chemistry fractions (mass fractions).

    The reference's ChemistryData tracks 21 GRACKLE species
    (cooling/chemistry_data.hpp:47-116); under the CIE closure the model
    here needs only the composition that fixes the mean molecular weight.
    """

    hi: jax.Array      # neutral H mass fraction
    hii: jax.Array     # ionized H
    hei: jax.Array
    heii: jax.Array
    heiii: jax.Array
    # electron abundance as a per-MASS number fraction y_e = n_e m_H/rho
    # (the same convention primordial._y_of passes through unchanged:
    # fully-ionized primordial gives y_e = X + Y/2, NOT "per H")
    e: jax.Array
    metal: jax.Array

    @staticmethod
    def ionized(n: int, hydrogen_fraction: float = 0.76,
                metallicity: float = 0.0122) -> "ChemistryData":
        """Fully ionized primordial + solar-metal composition."""
        x = hydrogen_fraction
        y = 1.0 - x - metallicity
        f = lambda v: jnp.full(n, v, jnp.float32)
        return ChemistryData(
            hi=f(0.0), hii=f(x), hei=f(0.0), heii=f(0.0), heiii=f(y),
            e=f(x + y / 2.0), metal=f(metallicity),
        )

    def mean_molecular_weight(self) -> jax.Array:
        """mu from the composition: 1/mu = 2 X_HII + X_HI + ... (amu)."""
        inv_mu = (
            self.hi + 2.0 * self.hii
            + self.hei / 4.0 + self.heii / 2.0 + 3.0 * self.heiii / 4.0
            + self.metal / 2.0
        )
        return 1.0 / jnp.maximum(inv_mu, 1e-10)


def u_to_temp(u_code, mu, cfg: CoolingConfig):
    """T[K] = (gamma-1) mu m_H u_cgs / kB (cooler energy_to_temperature)."""
    u_cgs = u_code * cfg.u_to_cgs
    return (cfg.gamma - 1.0) * mu * MH * u_cgs / KB


def temp_to_u(temp, mu, cfg: CoolingConfig):
    """Inverse of u_to_temp, returns code units."""
    u_cgs = temp * KB / ((cfg.gamma - 1.0) * mu * MH)
    return u_cgs / cfg.u_to_cgs


def _log_lambda_cie(temp, cfg: CoolingConfig):
    """log10 Lambda(T) [erg cm^3/s] by interpolation of the CIE table."""
    logT = jnp.log10(jnp.maximum(temp, 1.0))
    return jnp.interp(
        logT,
        jnp.asarray(cfg.logT_table, jnp.float32),
        jnp.asarray(cfg.logL_table, jnp.float32),
        left=-60.0,  # no radiative cooling below the table
        right=float(cfg.logL_table[-1]),
    )


def _lambda_cie(temp, cfg: CoolingConfig):
    """Lambda(T) [erg cm^3/s] (diagnostic form of _log_lambda_cie)."""
    return 10.0 ** _log_lambda_cie(temp, cfg)


def cooling_rate(rho_code, u_code, chem: ChemistryData, cfg: CoolingConfig):
    """du/dt in code units: (n_H Gamma - n_H^2 Lambda(T)) / rho.

    Negative = net cooling. The n_H^2 scaling is the two-body CIE form the
    GRACKLE tabulated mode uses. The unit conversions are pre-folded into
    log-space prefactors (see CoolingConfig.log_cool_prefac) so all traced
    values stay in float32-safe magnitudes.
    """
    mu = chem.mean_molecular_weight()
    temp = u_to_temp(u_code, mu, cfg)
    log_lam = _log_lambda_cie(temp, cfg)
    cool = 10.0 ** (log_lam + cfg.log_cool_prefac) * rho_code
    return cfg.heating_code - cool


def cooling_timestep(rho_code, u_code, chem: ChemistryData, cfg: CoolingConfig):
    """min over particles of ct_crit * |u / (du/dt)| (eos_cooling.hpp:12-25)."""
    dudt = cooling_rate(rho_code, u_code, chem, cfg)
    tc = jnp.abs(u_code / jnp.where(jnp.abs(dudt) > 0, dudt, 1e-30))
    return cfg.ct_crit * jnp.min(tc)


def cool_particles(dt, rho_code, u_code, chem: ChemistryData, cfg: CoolingConfig):
    """Integrate the cooling source over dt; returns du/dt averaged over the
    step (the quantity the propagator adds to du,
    std_hydro_grackle.hpp:214-226).

    Sub-cycled semi-implicit update: cooling is applied as
    u' = u / (1 + dt_sub * L/u), which is unconditionally stable and
    positivity-preserving for net cooling; heating is added explicitly.
    """
    dt_sub = dt / cfg.substeps

    def body(u, _):
        dudt = cooling_rate(rho_code, u, chem, cfg)
        cool = jnp.where(dudt < 0, -dudt, 0.0)
        heat = jnp.where(dudt > 0, dudt, 0.0)
        u_new = u / (1.0 + dt_sub * cool / jnp.maximum(u, 1e-30)) + dt_sub * heat
        return u_new, None

    u_final, _ = jax.lax.scan(body, u_code, None, length=cfg.substeps)
    return (u_final - u_code) / dt


def cool_step(dt, rho_code, u_code, chem: ChemistryData, cfg: CoolingConfig):
    """One cooling source update: (du_avg, new ChemistryData).

    Dispatches on cfg.evolve_species — the evolved primordial network
    (physics/primordial.py, the cooler.cpp:313 solve_chemistry role) or
    the CIE table with pass-through fractions."""
    if cfg.evolve_species:
        from sphexa_tpu.physics.primordial import evolve_primordial

        return evolve_primordial(dt, rho_code, u_code, chem, cfg)
    return cool_particles(dt, rho_code, u_code, chem, cfg), chem


def cool_timestep(rho_code, u_code, chem: ChemistryData, cfg: CoolingConfig):
    """ct_crit cooling-time limiter, dispatching like cool_step."""
    if cfg.evolve_species:
        from sphexa_tpu.physics.primordial import primordial_cooling_timestep

        return primordial_cooling_timestep(rho_code, u_code, chem, cfg)
    return cooling_timestep(rho_code, u_code, chem, cfg)


def eos_cooling(rho_code, u_code, chem: ChemistryData, cfg: CoolingConfig):
    """EOS used by the cooling propagator's contract (eos_cooling.hpp:27-47).

    Under the CIE closure the composition enters only through the u <-> T
    conversion (mean molecular weight); pressure from specific internal
    energy is exactly the ideal-gas form p = (gamma-1) rho u, which is what
    the force stage (hydro_std.compute_eos_std) already evaluates — so the
    propagator needs no separate EOS hook. This function exists as the
    explicit statement of that identity (and the place a future
    variable-gamma chemistry model would plug in)."""
    from sphexa_tpu.sph.eos import ideal_gas_eos_u

    del chem  # composition-independent under the CIE closure
    return ideal_gas_eos_u(u_code, rho_code, cfg.gamma)


_CHEM_FIELDS = ("hi", "hii", "hei", "heii", "heiii", "e", "metal")


def chemistry_to_fields(chem: ChemistryData):
    """Flatten the chemistry pytree into snapshot datasets (prefixed
    ``chem_``), the checkpoint counterpart of the reference's per-particle
    GRACKLE fields (std_hydro_grackle.hpp:89-106)."""
    import numpy as np

    return {f"chem_{k}": np.asarray(getattr(chem, k)) for k in _CHEM_FIELDS}


def chemistry_from_fields(extra) -> ChemistryData:
    """Rebuild ChemistryData from snapshot datasets written by
    ``chemistry_to_fields``."""
    return ChemistryData(
        **{k: jnp.asarray(extra[f"chem_{k}"]) for k in _CHEM_FIELDS}
    )
