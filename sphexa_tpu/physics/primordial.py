"""Evolved 6-species primordial chemistry (H / H+ / He / He+ / He++ / e).

Replaces the CIE table's diagnostic-only fractions with a jitted
non-equilibrium network — the role of the reference's GRACKLE solver
(physics/cooling/cooler.cpp:313 solve_chemistry: species ODEs + cooling
integrated per particle each step; species list
cooling/chemistry_data.hpp:47-116). The TPU transposition keeps the
structure jit-friendly: fixed subcycle count (lax.scan), sequential
semi-implicit species updates (the Anninos et al. 1997 scheme GRACKLE
itself uses), and all unit conversions folded into two host-side
prefactors so every traced value stays in float32-safe magnitudes.

Reactions (collisional ionization + radiative/dielectronic
recombination; rate fits are the standard Cen 1992 / Katz, Weinberg &
Hernquist 1996 forms, also used by GRACKLE's primordial_chemistry=1):

    HI   + e -> HII   + 2e      k1      HII   + e -> HI   (+ photon) k2
    HeI  + e -> HeII  + 2e      k3      HeII  + e -> HeI  (incl. di) k4
    HeII + e -> HeIII + 2e      k5      HeIII + e -> HeII            k6

Cooling channels tied to the species (KWH96 Table 1): collisional
excitation (HI, HeII), collisional ionization (HI, HeI, HeII),
recombination (HII, HeII incl. dielectronic, HeIII), bremsstrahlung.

Number bookkeeping: species are MASS fractions (ChemistryData); the
solver works in per-mass number fractions y_X = X / A_X (O(1)) so the
only density scale is rho itself:

    n_X = rho_cgs * y_X / m_H
    dy/dt[code]   = k(T) * y_e * rho_code * R0,  R0 = rho_to_cgs/m_H * t_code
    du/dt[code]   = -rho_code * C0 * sum y_e * y_X * lam24(T),
                    C0 = rho_to_cgs/m_H^2 * t_code/u_to_cgs * 1e-24

with lam24 = Lambda * 1e24 (O(1)) and R0/C0 computed host-side in f64.
"""

import dataclasses
from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp

from sphexa_tpu.physics.cooling import (
    KB, MH, ChemistryData, CoolingConfig, u_to_temp,
)


# ---------------------------------------------------------------------------
# rate coefficients [cm^3/s] (Cen 1992; KWH96 eqs. 24-30)
# ---------------------------------------------------------------------------


def _t5(T):
    return 1.0 + jnp.sqrt(T * 1e-5)


def k1_ci_hi(T):
    """HI collisional ionization."""
    return 5.85e-11 * jnp.sqrt(T) / _t5(T) * jnp.exp(-157809.1 / T)


def k2_rec_hii(T):
    """HII radiative recombination (case A)."""
    return (8.4e-11 / jnp.sqrt(T) * (T * 1e-3) ** -0.2
            / (1.0 + (T * 1e-6) ** 0.7))


def k3_ci_hei(T):
    """HeI collisional ionization."""
    return 2.38e-11 * jnp.sqrt(T) / _t5(T) * jnp.exp(-285335.4 / T)


def k4_rec_heii(T):
    """HeII recombination: radiative + dielectronic."""
    rad = 1.5e-10 * T ** -0.6353
    di = (1.9e-3 * T ** -1.5 * jnp.exp(-470000.0 / T)
          * (1.0 + 0.3 * jnp.exp(-94000.0 / T)))
    return rad + di


def k5_ci_heii(T):
    """HeII collisional ionization."""
    return 5.68e-12 * jnp.sqrt(T) / _t5(T) * jnp.exp(-631515.0 / T)


def k6_rec_heiii(T):
    """HeIII radiative recombination."""
    return (3.36e-10 / jnp.sqrt(T) * (T * 1e-3) ** -0.2
            / (1.0 + (T * 1e-6) ** 0.7))


# ---------------------------------------------------------------------------
# cooling channels: Lambda * 1e24 [erg cm^3/s], per n_e * n_X (KWH96 T.1)
# ---------------------------------------------------------------------------


def lam24_channels(T):
    """Dict of per-(n_e n_X) cooling fits scaled by 1e24; key = which
    species' number fraction multiplies the channel."""
    sq = jnp.sqrt(T)
    return {
        # collisional excitation
        "ce_hi": 7.50e5 * jnp.exp(-118348.0 / T) / _t5(T),          # x n_HI
        "ce_heii": (5.54e7 * T ** -0.397 * jnp.exp(-473638.0 / T)
                    / _t5(T)),                                       # x n_HeII
        # collisional ionization
        "ci_hi": 1.27e3 * sq * jnp.exp(-157809.1 / T) / _t5(T),      # x n_HI
        "ci_hei": 9.38e2 * sq * jnp.exp(-285335.4 / T) / _t5(T),     # x n_HeI
        "ci_heii": 4.95e2 * sq * jnp.exp(-631515.0 / T) / _t5(T),    # x n_HeII
        # recombination
        "rec_hii": (8.70e-3 * sq * (T * 1e-3) ** -0.2
                    / (1.0 + (T * 1e-6) ** 0.7)),                    # x n_HII
        "rec_heii": 1.55e-2 * T ** 0.3647,                           # x n_HeII
        "rec_heiii": (3.48e-2 * sq * (T * 1e-3) ** -0.2
                      / (1.0 + (T * 1e-6) ** 0.7)),                  # x n_HeIII
        "di_heii": (1.24e11 * T ** -1.5 * jnp.exp(-470000.0 / T)
                    * (1.0 + 0.3 * jnp.exp(-94000.0 / T))),          # x n_HeII
        # bremsstrahlung (g_ff = 1.3), x (n_HII + n_HeII + 4 n_HeIII)
        "brem": 1.42e-3 * 1.3 * sq,
    }


def species_cooling24(T, y):
    """sum over channels of y_e * y_X * lam24(T): the composition-resolved
    CIE/non-equilibrium cooling function (per rho_code * C0)."""
    lam = lam24_channels(T)
    ye = y["e"]
    return ye * (
        lam["ce_hi"] * y["hi"] + lam["ce_heii"] * y["heii"]
        + lam["ci_hi"] * y["hi"] + lam["ci_hei"] * y["hei"]
        + lam["ci_heii"] * y["heii"]
        + lam["rec_hii"] * y["hii"]
        + (lam["rec_heii"] + lam["di_heii"]) * y["heii"]
        + lam["rec_heiii"] * y["heiii"]
        + lam["brem"] * (y["hii"] + y["heii"] + 4.0 * y["heiii"])
    )


def metal_cooling24(T, metal, cfg, x_h: Optional[float] = None):
    """Metal-line cooling on top of the primordial network — the
    GRACKLE decomposition (primordial network + Cloudy metal table,
    cooler.cpp metal_cooling flag): the metal channel is the RESIDUAL
    of the solar-metallicity CIE table over the primordial network's
    own equilibrium cooling at the same T, scaled linearly in the
    particle's metal mass fraction. Returns the lam24-normalized rate
    per (rho/m_H)^2 (the same units species_cooling24 uses).

    ``x_h`` defaults to ``cfg.hydrogen_fraction`` so a non-default
    composition gets the matching n_H^2 conversion (it used to
    hard-code 0.76, silently mis-scaling the table rate for any other
    CoolingConfig — ADVICE round 5)."""
    from sphexa_tpu.physics.cooling import _log_lambda_cie

    if x_h is None:
        x_h = cfg.hydrogen_fraction
    # table rate is per n_H^2 = (x_h rho/m_H)^2; convert to per
    # (rho/m_H)^2 with x_h^2
    lam_cie24 = 10.0 ** (_log_lambda_cie(T, cfg) + 24.0) * x_h**2
    eq = equilibrium_fractions(T, x_h, 1.0 - x_h)
    lam_prim24 = species_cooling24(T, eq)
    Z_SUN = 0.0122
    return jnp.maximum(lam_cie24 - lam_prim24, 0.0) * (metal / Z_SUN)


def equilibrium_fractions(T, x_h, x_he):
    """Analytic CIE ionization balance at temperature T: the fixed point
    the subcycled network must relax to (rate ratios only — density
    cancels). Returns the y-dict of per-mass number fractions."""
    r_h = k1_ci_hi(T) / k2_rec_hii(T)          # y_HII / y_HI
    r_he1 = k3_ci_hei(T) / k4_rec_heii(T)      # y_HeII / y_HeI
    r_he2 = k5_ci_heii(T) / k6_rec_heiii(T)    # y_HeIII / y_HeII
    y_h = x_h
    y_hi = y_h / (1.0 + r_h)
    y_hii = y_h - y_hi
    y_he = x_he / 4.0
    d = 1.0 + r_he1 + r_he1 * r_he2
    y_hei = y_he / d
    y_heii = y_hei * r_he1
    y_heiii = y_heii * r_he2
    return dict(hi=y_hi, hii=y_hii, hei=y_hei, heii=y_heii,
                heiii=y_heiii, e=y_hii + y_heii + 2.0 * y_heiii)


# ---------------------------------------------------------------------------
# the solver
# ---------------------------------------------------------------------------


def _prefactors(cfg: CoolingConfig):
    """(R0, C0) host-side f64 -> f32 unit folds (module docstring)."""
    r0 = cfg.rho_to_cgs / MH * cfg.t_code_s
    c0 = cfg.rho_to_cgs / MH**2 * cfg.t_code_s / cfg.u_to_cgs * 1e-24
    return np.float32(r0), np.float32(c0)


def _y_of(chem: ChemistryData):
    return dict(
        hi=chem.hi, hii=chem.hii,
        hei=chem.hei / 4.0, heii=chem.heii / 4.0, heiii=chem.heiii / 4.0,
        e=chem.e,
    )


def _mu_of_y(y, metal):
    inv_mu = (y["hi"] + y["hii"] + y["hei"] + y["heii"] + y["heiii"]
              + y["e"] + metal / 2.0)
    return 1.0 / jnp.maximum(inv_mu, 1e-10)


def _species_update(y, T, a, x_h, y_he_tot):
    """One network subcycle at temperature T with the dimensionless
    rate factor a = dt * n_H-equivalent * y_e.

    Each ionization pair is solved IMPLICITLY THROUGH ITS CLOSURE
    (substitute y_HII = X - y_HI into the backward-Euler update before
    solving), so stiff a*k factors relax monotonically to the exact
    balance instead of oscillating around it — the stability refinement
    of the Anninos et al. 1997 sequential scheme for subcycles much
    longer than the fastest reaction time. Fixed points are the exact
    CIE balances (k1 y_HI = k2 y_HII etc.; see
    tests/test_cooling.py::TestPrimordialNetwork)."""
    k1, k2 = k1_ci_hi(T), k2_rec_hii(T)
    y_hi = (y["hi"] + a * k2 * x_h) / (1.0 + a * (k1 + k2))
    y_hi = jnp.clip(y_hi, 0.0, x_h)
    y_hii = x_h - y_hi

    k3, k4 = k3_ci_hei(T), k4_rec_heii(T)
    k5, k6 = k5_ci_heii(T), k6_rec_heiii(T)
    y_hei = ((y["hei"] + a * k4 * y["heii"]) / (1.0 + a * k3))
    y_hei = jnp.clip(y_hei, 0.0, y_he_tot)
    # HeII: k6-recombination creation made implicit through the HeIII
    # closure (y_HeIII = Y - y_HeI - y_HeII) — same fixed point,
    # oscillation-free at large a*k6
    y_heii = ((y["heii"] + a * (k3 * y_hei + k6 * (y_he_tot - y_hei)))
              / (1.0 + a * (k4 + k5 + k6)))
    y_heii = jnp.clip(y_heii, 0.0, y_he_tot - y_hei)
    y_heiii = y_he_tot - y_hei - y_heii
    return dict(hi=y_hi, hii=y_hii, hei=y_hei, heii=y_heii,
                heiii=y_heiii, e=y_hii + y_heii + 2.0 * y_heiii)


def relax_to_equilibrium(T, rho_code, chem: ChemistryData,
                         cfg: CoolingConfig, dt_sub, steps: int = 2048):
    """Species-only relaxation at FIXED temperature: the CIE
    equilibrium limit (test pin) and an equilibrium-IC generator.
    ``dt_sub`` is the per-subcycle code-time step; pick it so the
    fastest rate factor a*k stays O(<=1)."""
    r0, _ = _prefactors(cfg)
    x_h = chem.hi + chem.hii
    y_he_tot = (chem.hei + chem.heii + chem.heiii) / 4.0
    dens = rho_code * r0

    def body(y, _):
        a = dt_sub * dens * y["e"]
        return _species_update(y, T, a, x_h, y_he_tot), None

    y_fin, _ = jax.lax.scan(body, _y_of(chem), None, length=steps)
    return ChemistryData(
        hi=y_fin["hi"], hii=y_fin["hii"],
        hei=y_fin["hei"] * 4.0, heii=y_fin["heii"] * 4.0,
        heiii=y_fin["heiii"] * 4.0, e=y_fin["e"], metal=chem.metal,
    )


def evolve_primordial(dt, rho_code, u_code, chem: ChemistryData,
                      cfg: CoolingConfig):
    """Subcycled coupled (species, energy) update over one step.

    Per subcycle (cooler.cpp solve_chemistry structure, jit-shaped):
    T from (u, mu) -> rates -> sequential semi-implicit species updates
    with exact closure (HII = X - HI; HeIII = Y/4 - HeI - HeII;
    e from charge balance) -> species-resolved + metal-residual cooling
    (metal_cooling24: the CIE-table residual over the network's own
    equilibrium, scaled by the particle's metal fraction — the GRACKLE
    network+metal-table decomposition) -> positivity-preserving
    implicit u update. Returns (du_avg, new ChemistryData); the metal
    FRACTION itself passes through unevolved.
    """
    r0, c0 = _prefactors(cfg)
    sub = cfg.substeps
    dt_sub = dt / sub
    x_h = chem.hi + chem.hii
    y_he_tot = (chem.hei + chem.heii + chem.heiii) / 4.0
    metal = chem.metal

    def body(carry, _):
        u, y = carry
        mu = _mu_of_y(y, metal)
        T = jnp.maximum(u_to_temp(u, mu, cfg), 10.0)
        dens = rho_code * r0  # k * dens * y_e = dy/dt per code time
        a = dt_sub * dens * y["e"]
        y_new = _species_update(y, T, a, x_h, y_he_tot)

        # species-resolved cooling, implicit positivity-preserving in u
        cool = rho_code * c0 * (
            species_cooling24(T, y_new) + metal_cooling24(T, metal, cfg)
        )
        heat = cfg.heating_code
        u_new = (u / (1.0 + dt_sub * cool / jnp.maximum(u, 1e-30))
                 + dt_sub * heat)
        return (u_new, y_new), None

    y0 = _y_of(chem)
    (u_fin, y_fin), _ = jax.lax.scan(body, (u_code, y0), None, length=sub)
    new_chem = ChemistryData(
        hi=y_fin["hi"], hii=y_fin["hii"],
        hei=y_fin["hei"] * 4.0, heii=y_fin["heii"] * 4.0,
        heiii=y_fin["heiii"] * 4.0,
        e=y_fin["e"], metal=metal,
    )
    return (u_fin - u_code) / dt, new_chem


def primordial_cooling_timestep(rho_code, u_code, chem: ChemistryData,
                                cfg: CoolingConfig):
    """ct_crit * min |u / du_dt| with the species-resolved rate
    (eos_cooling.hpp:12-25 contract, network flavor)."""
    r0, c0 = _prefactors(cfg)
    y = _y_of(chem)
    mu = _mu_of_y(y, chem.metal)
    T = jnp.maximum(u_to_temp(u_code, mu, cfg), 10.0)
    dudt = (rho_code * c0 * (species_cooling24(T, y)
                             + metal_cooling24(T, chem.metal, cfg))
            - cfg.heating_code)
    tc = jnp.abs(u_code / jnp.where(jnp.abs(dudt) > 0, dudt, 1e-30))
    return cfg.ct_crit * jnp.min(tc)
