"""Physics extensions beyond pure hydrodynamics.

Counterpart of the reference's ``physics/`` tree (GRACKLE radiative
cooling wrapper). The TPU build ships a reduced, self-contained tabulated
cooling model instead of the external C/Fortran GRACKLE library (SURVEY.md
§7 stage 7) — same propagator coupling (cooling timestep limiter, du
source term; under the CIE closure the EOS reduces to the ideal-gas form,
see eos_cooling), jit-compatible throughout.
"""

from sphexa_tpu.physics.cooling import (
    ChemistryData,
    CoolingConfig,
    cool_particles,
    cooling_rate,
    cooling_timestep,
    eos_cooling,
    temp_to_u,
    u_to_temp,
)

__all__ = [
    "ChemistryData",
    "CoolingConfig",
    "cool_particles",
    "cooling_rate",
    "cooling_timestep",
    "eos_cooling",
    "temp_to_u",
    "u_to_temp",
]
