"""Gresho-Chan vortex comparator.

Counterpart of the reference's ``main/src/analytical_solutions/
compare_gresho_chan.py``: the stationary triangular azimuthal-velocity
profile (Gresho & Chan 1990) evaluated at each particle's cylindrical
radius, and the same mean-absolute-deviation L1 metric.
"""

from typing import Dict

import numpy as np


def gresho_chan_vphi(r: np.ndarray) -> np.ndarray:
    """Analytic azimuthal velocity: 5r inside r=0.2, 2-5r to r=0.4, 0
    beyond (compare_gresho_chan.py analyticalVelocity)."""
    r = np.asarray(r, np.float64)
    return np.where(
        r < 0.2, 5.0 * r, np.where(r < 0.4, 2.0 - 5.0 * r, 0.0)
    )


def gresho_chan_pressure(r: np.ndarray, p0: float = 5.0) -> np.ndarray:
    """Analytic pressure profile of the stationary vortex."""
    r = np.asarray(r, np.float64)
    inner = p0 + 12.5 * r**2
    mid = p0 + 12.5 * r**2 + 4.0 * (1.0 - 5.0 * r - np.log(0.2) + np.log(r))
    outer = p0 - 2.0 + 4.0 * np.log(2.0)
    return np.where(r < 0.2, inner, np.where(r < 0.4, mid, outer))


def cylindrical_vt(x, y, vx, vy) -> Dict[str, np.ndarray]:
    """Per-particle cylindrical radius + tangential velocity component
    (compare_gresho_chan.py compute2DRadiiAndVt)."""
    x, y = np.asarray(x, np.float64), np.asarray(y, np.float64)
    vx, vy = np.asarray(vx, np.float64), np.asarray(vy, np.float64)
    r = np.sqrt(x * x + y * y)
    rs = np.maximum(r, 1e-12)
    vt = (x * vy - y * vx) / rs
    return {"r": r, "vt": vt}


def gresho_chan_l1(x, y, vx, vy) -> float:
    """Mean absolute deviation of the tangential velocity from the
    analytic profile (compare_gresho_chan.py computeL1Error)."""
    d = cylindrical_vt(x, y, vx, vy)
    return float(np.mean(np.abs(d["vt"] - gresho_chan_vphi(d["r"]))))
