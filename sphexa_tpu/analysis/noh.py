"""Noh spherical implosion analytic solution.

W.F. Noh, "Errors for Calculations of Strong Shocks Using an Artificial
Viscosity and an Artificial Heat Flux", JCP 72 (1987) 78-120 — the same
closed-form solution evaluated by the reference's
``main/src/analytical_solutions/compare_noh.py`` (nohRho/nohU/nohP/nohVel).
"""

from typing import Dict

import numpy as np


def noh_solution(
    r: np.ndarray,
    time: float,
    gamma: float = 5.0 / 3.0,
    rho0: float = 1.0,
    vel0: float = -1.0,
    u0: float = 0.0,
    p0: float = 0.0,
    cs0: float = 0.0,
    xgeom: float = 3.0,
) -> Dict[str, np.ndarray]:
    """Evaluate the Noh solution at radii ``r`` and time ``time``.

    Upstream of the shock the gas is in free radial fall (density piles up
    geometrically); downstream it is at rest at the stagnation density.
    Returns dict with 'rho', 'p', 'u', 'vel', 'cs' and scalar 'r_shock'.
    """
    r = np.asarray(r, np.float64)
    gamm1, gamp1 = gamma - 1.0, gamma + 1.0
    r_shock = 0.5 * gamm1 * abs(vel0) * time

    rsafe = np.maximum(r, 1e-30)
    inside = r <= r_shock

    rho_out = rho0 * (1.0 - vel0 * time / rsafe) ** (xgeom - 1.0)
    rho_in = rho0 * (gamp1 / gamm1) ** xgeom
    rho = np.where(inside, rho_in, rho_out)

    u = np.where(inside, 0.5 * vel0**2, u0)
    p = np.where(inside, gamm1 * rho * u, p0)
    vel = np.where(inside, 0.0, abs(vel0))
    with np.errstate(divide="ignore", invalid="ignore"):
        cs = np.where(inside, np.sqrt(gamma * p / rho), cs0)

    return {"rho": rho, "p": p, "u": u, "vel": vel, "cs": cs, "r_shock": r_shock}
