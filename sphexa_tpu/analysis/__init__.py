"""Semi-analytic solutions and L1-error comparison utilities.

Counterpart of the reference's ``main/src/analytical_solutions/``: the
Sedov-Taylor self-similar solution (sedov_solution/*.cpp), the Noh
implosion solution and the L1 comparisons (compare_solutions.py,
compare_noh.py) used as the de-facto physics correctness baseline
(SURVEY.md §6).
"""

from sphexa_tpu.analysis.noh import noh_solution
from sphexa_tpu.analysis.sedov import sedov_solution
from sphexa_tpu.analysis.compare import compute_output_fields, l1_error

__all__ = ["noh_solution", "sedov_solution", "compute_output_fields", "l1_error"]
