"""Evrard-collapse normalized-unit profiles.

Counterpart of the reference's ``main/src/analytical_solutions/
compare_evrard.py``: there is no closed-form solution — the comparator
converts a state into the normalized units of Evrard (1988) /
Steinmetz & Muller (1993) and produces binned radial profiles for
comparison against published curves (the reference CI runs evrard as
sanity-only, with L1 placeholders of 0.0, .jenkins/reframe_ci.py:364-369).
"""

from typing import Dict

import numpy as np


def evrard_norms(R: float = 1.0, M: float = 1.0, G: float = 1.0) -> Dict[str, float]:
    """Normalization constants (compare_evrard.py header): time, density,
    internal energy and velocity units of the collapse problem."""
    return {
        "time": float(np.sqrt(np.pi**2 / 8.0) * R**1.5 / np.sqrt(G * M)),
        "rho": float(3.0 * M / (4.0 * np.pi * R**3)),
        "u": float(G * M / R),
        "vel": float(np.sqrt(G * M / R)),
    }


def radial_profile(r, values, bins: int = 50, r_max=None) -> Dict[str, np.ndarray]:
    """Mass-less radial binning: mean of ``values`` per logarithmic-ish
    radius bin, the 1-D profile the reference's plots draw."""
    r = np.asarray(r, np.float64)
    values = np.asarray(values, np.float64)
    if r_max is None:
        r_max = float(r.max())
    edges = np.linspace(0.0, r_max, bins + 1)
    idx = np.clip(np.digitize(r, edges) - 1, 0, bins - 1)
    count = np.bincount(idx, minlength=bins).astype(np.float64)
    mean = np.bincount(idx, weights=values, minlength=bins)
    mean = np.divide(mean, count, out=np.zeros(bins), where=count > 0)
    centers = 0.5 * (edges[:-1] + edges[1:])
    return {"r": centers, "mean": mean, "count": count}


def evrard_normalized_profiles(
    fields: Dict[str, np.ndarray], time: float,
    R: float = 1.0, M: float = 1.0, G: float = 1.0, bins: int = 50,
) -> Dict[str, np.ndarray]:
    """Radial rho/u/vel profiles in normalized units at normalized time
    t' = t / timeNorm — directly comparable to the published curves
    (Steinmetz & Muller 1993, fig. 10; the collapse bounce is at
    t' ~ 0.77)."""
    norms = evrard_norms(R, M, G)
    out = {"t_norm": np.float64(time / norms["time"])}
    for key, norm in (("rho", norms["rho"]), ("u", norms["u"]),
                      ("vel", norms["vel"])):
        prof = radial_profile(fields["r"], fields[key] / norm, bins=bins,
                              r_max=R)
        out[f"{key}_profile"] = prof["mean"]
        out["r_bins"] = prof["r"]
    return out
