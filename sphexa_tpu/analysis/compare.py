"""L1-error comparison of a particle state against an analytic solution.

Counterpart of the reference's compare_solutions.py / compare_noh.py L1
metric (sum |sol - sim| / N, computed at every particle's radius) and of
the saveFields recompute pass (ve_hydro.hpp:225-286) that derives
rho/p/u/vel from the conserved fields before output.
"""

import functools
from typing import Dict

import numpy as np
import jax
import jax.numpy as jnp

from sphexa_tpu.neighbors.cell_list import find_neighbors
from sphexa_tpu.propagator import PropagatorConfig
from sphexa_tpu.sfc.box import Box
from sphexa_tpu.sfc.keys import compute_sfc_keys
from sphexa_tpu.sph import hydro_std, hydro_ve
from sphexa_tpu.sph.particles import ParticleState


@functools.partial(jax.jit, static_argnames=("cfg", "pipeline"))
def _output_fields(
    state: ParticleState, box: Box, cfg: PropagatorConfig, pipeline: str
):
    # Neighbor search needs key order; results are scattered back to the
    # caller's particle order so they stay aligned with the conserved
    # fields of `state` (which a snapshot writes as-is).
    keys = compute_sfc_keys(state.x, state.y, state.z, box, curve=cfg.curve)
    order = jnp.argsort(keys)
    skeys = keys[order]
    g = lambda a: a[order]
    x, y, z, h, m = (g(state.x), g(state.y), g(state.z), g(state.h), g(state.m))
    temp = g(state.temp)

    if cfg.backend == "pallas":
        # the fused engine avoids the XLA path's (N, W3*cap) candidate
        # materialization, which can exceed HBM for strongly compressed
        # states (e.g. Noh's center drives the cell cap into the 1000s)
        from sphexa_tpu.sph import pallas_pairs as pp

        interp = pp.pallas_interpret()
        ranges = pp.group_cell_ranges(x, y, z, h, skeys, box, cfg.nbr)
        if pipeline == "ve":
            xm, _, _ = pp.pallas_xmass(
                x, y, z, h, m, skeys, box, cfg.const, cfg.nbr,
                ranges=ranges, interpret=interp,
            )
            (kx, gradh), _ = pp.pallas_ve_def_gradh(
                x, y, z, h, m, xm, skeys, box, cfg.const, cfg.nbr,
                ranges=ranges, interpret=interp,
            )
            _, c, rho, p = hydro_ve.compute_eos_ve(
                temp, m, kx, xm, gradh, cfg.const
            )
        else:
            rho, _, _ = pp.pallas_density(
                x, y, z, h, m, skeys, box, cfg.const, cfg.nbr,
                ranges=ranges, interpret=interp,
            )
            p, c = hydro_std.compute_eos_std(temp, rho, cfg.const)
    elif pipeline == "ve":
        nidx, nmask, _, _ = find_neighbors(x, y, z, h, skeys, box, cfg.nbr)
        # VE-consistent density/EOS (the saveFields recompute pass,
        # ve_hydro.hpp:225-286): rho = kx m / xm with gradh normalization
        xm = hydro_ve.compute_xmass(
            x, y, z, h, m, nidx, nmask, box, cfg.const, cfg.block
        )
        kx, gradh = hydro_ve.compute_ve_def_gradh(
            x, y, z, h, m, xm, nidx, nmask, box, cfg.const, cfg.block
        )
        _, c, rho, p = hydro_ve.compute_eos_ve(temp, m, kx, xm, gradh, cfg.const)
    else:
        nidx, nmask, _, _ = find_neighbors(x, y, z, h, skeys, box, cfg.nbr)
        rho = hydro_std.compute_density(
            x, y, z, h, m, nidx, nmask, box, cfg.const, cfg.block
        )
        p, c = hydro_std.compute_eos_std(temp, rho, cfg.const)

    unsort = lambda a: jnp.zeros_like(a).at[order].set(a)
    rho, p, c = unsort(rho), unsort(p), unsort(c)
    u = cfg.const.cv * state.temp
    vel = jnp.sqrt(state.vx**2 + state.vy**2 + state.vz**2)
    r = jnp.sqrt(state.x**2 + state.y**2 + state.z**2)
    return {"r": r, "rho": rho, "p": p, "u": u, "vel": vel, "c": c}


def compute_output_fields(
    state: ParticleState, box: Box, cfg: PropagatorConfig, pipeline: str = "std"
) -> Dict[str, np.ndarray]:
    """Recompute the dependent output fields (rho, p, u, |v|, c) plus radii
    from a conserved-field state, as numpy arrays in the state's particle
    order. ``pipeline`` selects the density/EOS estimator consistent with
    the propagator that evolved the state ('std' or 've')."""
    out = _output_fields(state, box, cfg, "ve" if pipeline == "ve" else "std")
    return {k: np.asarray(v) for k, v in out.items()}


def l1_error(sim: np.ndarray, sol: np.ndarray) -> float:
    """Reference L1 metric: mean absolute deviation (compare_noh.py:146)."""
    sim = np.asarray(sim, np.float64)
    sol = np.asarray(sol, np.float64)
    return float(np.abs(sol - sim).sum() / sim.shape[0])
