"""Sedov-Taylor blast-wave semi-analytic solution (standard case).

Implements the Kamm & Timmes formulation ("On Efficient Generation of
Numerically Robust Sedov Solutions", LA-UR-07-2849) — the same solution the
reference evaluates in ``main/src/analytical_solutions/sedov_solution/
sedov_solution.cpp`` — as a vectorized numpy routine. Only the *standard*
case (shock ahead of the singular point, which holds for every built-in
test configuration: gamma = 5/3, omega = 0, spherical) is supported; the
singular/vacuum branches raise.

The self-similar profile is closed-form in the similarity variable v
(Kamm eqs. 29-41); radius -> v inversion is done by dense monotonic
tabulation + interpolation instead of per-point root finding, so evaluating
the solution at 10^6 particle radii is a single vectorized pass.
"""

from typing import Dict

import numpy as np
from scipy.integrate import quad


def _exponents(xgeom: float, omega: float, gamma: float):
    """Kamm eqs. 42-47 exponents + eqs. 33-37 coefficient combinations."""
    gamm1, gamp1 = gamma - 1.0, gamma + 1.0
    xg2 = xgeom + 2.0 - omega
    denom2 = 2.0 * gamm1 + xgeom - gamma * omega
    denom3 = xgeom * (2.0 - gamma) - omega
    if abs(denom2) < 1e-6 or abs(denom3) < 1e-6:
        raise NotImplementedError(
            "omega2/omega3 degenerate Sedov cases are not implemented"
        )
    a0 = 2.0 / xg2
    a2 = -gamm1 / denom2
    a1 = (
        xg2 * gamma / (2.0 + xgeom * gamm1)
        * (2.0 * (xgeom * (2.0 - gamma) - omega) / (gamma * xg2 * xg2) - a2)
    )
    a3 = (xgeom - omega) / denom2
    a4 = xg2 * (xgeom - omega) * a1 / denom3
    a5 = (omega * gamp1 - 2.0 * xgeom) / denom3
    coef = dict(
        a=0.25 * xg2 * gamp1,
        b=gamp1 / gamm1,
        c=0.5 * xg2 * gamma,
        d=(xg2 * gamp1) / (xg2 * gamp1 - 2.0 * (2.0 + xgeom * gamm1)),
        e=0.5 * (2.0 + xgeom * gamm1),
    )
    return (a0, a1, a2, a3, a4, a5), coef, xg2


def _similarity_funcs(v, expo, coef, xgeom, omega, xg2):
    """lambda(v), f(v), g(v), h(v): Kamm eqs. 38-41 (standard case).

    Returns (l_fun, dlamdv, f_fun, g_fun, h_fun), all vectorized over v.
    """
    a0, a1, a2, a3, a4, a5 = expo
    x1 = coef["a"] * v
    x2 = coef["b"] * np.maximum(coef["c"] * v - 1.0, 1e-30)
    x3 = coef["d"] * (1.0 - coef["e"] * v)
    x4 = coef["b"] * (1.0 - 0.5 * xg2 * v)
    l_fun = x1**-a0 * x2**-a2 * x3**-a1
    dlamdv = (
        -(a0 * coef["a"] / x1 + a2 * coef["b"] * coef["c"] / x2
          - a1 * coef["d"] * coef["e"] / x3) * l_fun
    )
    f_fun = x1 * l_fun
    g_fun = (
        x1 ** (a0 * omega) * x2 ** (a3 + a2 * omega)
        * x3 ** (a4 + a1 * omega) * x4**a5
    )
    h_fun = x1 ** (a0 * xgeom) * x3 ** (a4 + a1 * (omega - 2.0)) * x4 ** (1.0 + a5)
    return l_fun, dlamdv, f_fun, g_fun, h_fun


def _energy_alpha(expo, coef, xgeom, omega, gamma, xg2) -> float:
    """Dimensionless energy integral alpha (Kamm eqs. 57-58, 67-68)."""
    gamm1, gamp1 = gamma - 1.0, gamma + 1.0
    gpogm = gamp1 / gamm1
    v0 = 2.0 / (xg2 * gamma)
    v2 = 4.0 / (xg2 * gamp1)

    def integrand1(v):
        l_fun, dlamdv, f_fun, g_fun, _ = _similarity_funcs(
            v, expo, coef, xgeom, omega, xg2
        )
        return dlamdv * l_fun ** (xgeom + 1.0) * gpogm * g_fun * v**2

    def integrand2(v):
        l_fun, dlamdv, f_fun, g_fun, h_fun = _similarity_funcs(
            v, expo, coef, xgeom, omega, xg2
        )
        z = 8.0 / ((xgeom + 2.0 - omega) ** 2 * gamp1)
        return dlamdv * l_fun ** (xgeom - 1.0) * h_fun * z

    # integrable algebraic singularity at v0; scipy's adaptive QAGS handles it
    eval1, _ = quad(integrand1, v0, v2, epsabs=1e-12, epsrel=1e-10, limit=200)
    eval2, _ = quad(integrand2, v0, v2, epsabs=1e-12, epsrel=1e-10, limit=200)
    if xgeom == 1:
        return 0.5 * eval1 + eval2 / gamm1
    return (xgeom - 1.0) * np.pi * (eval1 + 2.0 * eval2 / gamm1)


def sedov_solution(
    r: np.ndarray,
    time: float,
    eblast: float = 1.0,
    gamma: float = 5.0 / 3.0,
    rho0: float = 1.0,
    omega: float = 0.0,
    xgeom: float = 3.0,
    u0: float = 0.0,
    p0: float = 0.0,
    vel0: float = 0.0,
    cs0: float = 0.0,
    grid: int = 4096,
) -> Dict[str, np.ndarray]:
    """Evaluate the standard-case Sedov solution at radii ``r``.

    Returns dict with 'rho', 'p', 'u', 'vel', 'cs' arrays (same shape as r)
    and scalar 'r_shock'. Mirrors SedovSolution::sedovSol outputs.
    """
    r = np.asarray(r, np.float64)
    gamm1, gamp1 = gamma - 1.0, gamma + 1.0
    expo, coef, xg2 = _exponents(xgeom, omega, gamma)

    v0 = 2.0 / (xg2 * gamma)
    v2 = 4.0 / (xg2 * gamp1)
    vstar = 2.0 / (gamm1 * xgeom + 2.0)
    if not v2 < vstar - 1e-4:
        raise NotImplementedError("only the standard Sedov case is supported")

    alpha = _energy_alpha(expo, coef, xgeom, omega, gamma, xg2)

    # post-shock state (Kamm eqs. 5, 13, 14, 16)
    r2 = (eblast / (alpha * rho0)) ** (1.0 / xg2) * time ** (2.0 / xg2)
    us = (2.0 / xg2) * r2 / time
    rho1 = rho0 * r2**-omega
    rho_shock = gamp1 / gamm1 * rho1
    p_shock = 2.0 * rho1 * us**2 / gamp1
    vel_shock = 2.0 * us / gamp1
    cs_shock = np.sqrt(gamma * p_shock / rho_shock)

    # dense monotone table lambda(v) on [v0, v2], clustered toward v0 where
    # lambda -> 0 steeply; inversion by interpolation
    s = np.linspace(0.0, 1.0, grid)
    vtab = v0 + (v2 - v0) * s**4
    vtab[0] = v0 * (1.0 + 1e-12)
    l_tab, _, f_tab, g_tab, h_tab = _similarity_funcs(
        vtab, expo, coef, xgeom, omega, xg2
    )
    l_tab[0] = 0.0

    lam = np.clip(r / max(r2, 1e-300), 0.0, None)
    inside = lam <= 1.0
    lam_in = np.where(inside, lam, 1.0)
    f = np.interp(lam_in, l_tab, f_tab)
    g = np.interp(lam_in, l_tab, g_tab)
    h = np.interp(lam_in, l_tab, h_tab)

    rho_in = rho_shock * g
    p_in = p_shock * h
    vel_in = vel_shock * f
    with np.errstate(divide="ignore", invalid="ignore"):
        u_in = np.where(rho_in > 0, p_in / (gamm1 * rho_in), 0.0)
        cs_in = np.where(rho_in > 0, np.sqrt(gamma * p_in / rho_in), 0.0)

    out = {
        "rho": np.where(inside, rho_in, rho0 * np.where(r > 0, r, 1.0) ** -omega),
        "p": np.where(inside, p_in, p0),
        "u": np.where(inside, u_in, u0),
        "vel": np.where(inside, vel_in, vel0),
        "cs": np.where(inside, cs_in, cs0),
        "r_shock": r2,
    }
    return out
