"""3D Hilbert key codec on uint32 arrays (Skilling's transpose algorithm).

Role-equivalent of the reference's ``cstone/sfc/hilbert.hpp`` (iHilbert /
decodeHilbert): the Hilbert curve is the default spatial sort order because
its locality is markedly better than Morton's, which shrinks halo surfaces
and makes sort-order windows good neighbor-candidate predictors.

This implementation vectorizes John Skilling's public-domain transpose
algorithm ("Programming the Hilbert curve", AIP Conf. Proc. 707, 2004) over
arbitrary batch shapes: the per-bit loop is unrolled at trace time (10
iterations), each iteration a handful of elementwise XOR/AND/select ops —
ideal VPU work, no data-dependent control flow.

The produced curve is the canonical self-similar Hilbert curve, so keys are
hierarchical: the top ``3*L`` bits of a key are the level-``L`` cell key,
which cell-range lookups (searchsorted) rely on. This prefix property is
asserted in tests/test_sfc.py.
"""

import jax.numpy as jnp

from sphexa_tpu.dtypes import KEY_BITS, KEY_DTYPE
from sphexa_tpu.sfc.morton import _compact_bits_3d, _spread_bits_3d


def _axes_to_transpose(x0, x1, x2, bits):
    """Map grid coords to Hilbert 'transpose' form (Skilling AxestoTranspose)."""
    X = [x0.astype(KEY_DTYPE), x1.astype(KEY_DTYPE), x2.astype(KEY_DTYPE)]
    # Inverse undo
    q = 1 << (bits - 1)
    while q > 1:
        p = KEY_DTYPE(q - 1)
        for i in range(3):
            cond = (X[i] & KEY_DTYPE(q)) != 0
            t = (X[0] ^ X[i]) & p
            x0_new = jnp.where(cond, X[0] ^ p, X[0] ^ t)
            xi_new = jnp.where(cond, X[i], X[i] ^ t)
            X[0] = x0_new
            if i != 0:
                X[i] = xi_new
        q >>= 1
    # Gray encode
    X[1] = X[1] ^ X[0]
    X[2] = X[2] ^ X[1]
    t = jnp.zeros_like(X[0])
    q = 1 << (bits - 1)
    while q > 1:
        t = jnp.where((X[2] & KEY_DTYPE(q)) != 0, t ^ KEY_DTYPE(q - 1), t)
        q >>= 1
    return [X[0] ^ t, X[1] ^ t, X[2] ^ t]


def _transpose_to_axes(x0, x1, x2, bits):
    """Inverse of :func:`_axes_to_transpose` (Skilling TransposetoAxes)."""
    X = [x0.astype(KEY_DTYPE), x1.astype(KEY_DTYPE), x2.astype(KEY_DTYPE)]
    # Gray decode by H ^ (H/2)
    t = X[2] >> 1
    X[2] = X[2] ^ X[1]
    X[1] = X[1] ^ X[0]
    X[0] = X[0] ^ t
    # Undo excess work
    q = 2
    while q != (1 << bits):
        p = KEY_DTYPE(q - 1)
        for i in (2, 1, 0):
            cond = (X[i] & KEY_DTYPE(q)) != 0
            t = (X[0] ^ X[i]) & p
            x0_new = jnp.where(cond, X[0] ^ p, X[0] ^ t)
            xi_new = jnp.where(cond, X[i], X[i] ^ t)
            X[0] = x0_new
            if i != 0:
                X[i] = xi_new
        q <<= 1
    return X


def hilbert_encode(ix, iy, iz, bits: int = KEY_BITS):
    """Encode integer grid coordinates in ``[0, 2**bits)`` into Hilbert keys."""
    x0, x1, x2 = _axes_to_transpose(ix, iy, iz, bits)
    # transpose form -> key: bit q of (x0, x1, x2) -> key bits (3q+2, 3q+1, 3q)
    return (_spread_bits_3d(x0) << 2) | (_spread_bits_3d(x1) << 1) | _spread_bits_3d(x2)


def hilbert_decode(key, bits: int = KEY_BITS):
    """Decode Hilbert keys back into (ix, iy, iz) grid coordinates."""
    key = key.astype(KEY_DTYPE)
    x0 = _compact_bits_3d(key >> 2)
    x1 = _compact_bits_3d(key >> 1)
    x2 = _compact_bits_3d(key)
    X = _transpose_to_axes(x0, x1, x2, bits)
    return X[0], X[1], X[2]
