"""Global bounding box and periodic-boundary-condition math.

TPU-native equivalent of the reference's ``cstone/sfc/box.hpp`` (Box,
BoundaryType, putInBox, applyPBC) and ``cstone/sfc/box_mpi.hpp``
(makeGlobalBox). The box limits are traced jnp scalars so a growing open
box does not trigger recompilation; the boundary *types* are static python
ints because they select code paths.
"""

import dataclasses
import enum
from typing import Tuple

import jax
import jax.numpy as jnp

from sphexa_tpu.dtypes import COORD_DTYPE


class BoundaryType(enum.IntEnum):
    """Per-dimension boundary behavior (cstone/sfc/box.hpp BoundaryType)."""

    open = 0
    periodic = 1
    fixed = 2


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class Box:
    """Axis-aligned global bounding box with per-dimension boundary types.

    ``lo``/``hi`` are shape-(3,) arrays (traced, may change step to step for
    open boundaries); ``boundaries`` is static metadata.
    """

    lo: jax.Array
    hi: jax.Array
    boundaries: Tuple[BoundaryType, BoundaryType, BoundaryType] = dataclasses.field(
        metadata=dict(static=True),
        default=(BoundaryType.open, BoundaryType.open, BoundaryType.open),
    )

    @staticmethod
    def create(xmin, xmax, ymin=None, ymax=None, zmin=None, zmax=None,
               boundary=BoundaryType.open) -> "Box":
        """Create a box; cubic if only (xmin, xmax) given, like cstone::Box."""
        if ymin is None:
            ymin, ymax, zmin, zmax = xmin, xmax, xmin, xmax
        if isinstance(boundary, BoundaryType):
            boundary = (boundary, boundary, boundary)
        lo = jnp.array([xmin, ymin, zmin], dtype=COORD_DTYPE)
        hi = jnp.array([xmax, ymax, zmax], dtype=COORD_DTYPE)
        return Box(lo=lo, hi=hi, boundaries=tuple(BoundaryType(b) for b in boundary))

    @property
    def lengths(self) -> jax.Array:
        return self.hi - self.lo

    @property
    def periodic_mask(self) -> jnp.ndarray:
        """Static (3,) bool array: which dims wrap around."""
        return jnp.array([b == BoundaryType.periodic for b in self.boundaries])


def apply_pbc(box: Box, dxyz: jax.Array) -> jax.Array:
    """Fold coordinate *differences* into the minimum image.

    ``dxyz``: (..., 3) separation vectors. Mirrors cstone applyPBC: only
    periodic dimensions are folded.
    """
    L = box.lengths
    folded = dxyz - L * jnp.round(dxyz / L)
    return jnp.where(box.periodic_mask, folded, dxyz)


def apply_pbc_xyz(box: Box, rx, ry, rz):
    """Minimum-image fold of per-component separations (the form the
    interaction kernels use; single source of truth with apply_pbc)."""
    L = box.lengths
    per = box.periodic_mask
    rx = jnp.where(per[0], rx - L[0] * jnp.round(rx / L[0]), rx)
    ry = jnp.where(per[1], ry - L[1] * jnp.round(ry / L[1]), ry)
    rz = jnp.where(per[2], rz - L[2] * jnp.round(rz / L[2]), rz)
    return rx, ry, rz


def put_in_box(box: Box, xyz: jax.Array) -> jax.Array:
    """Fold absolute positions back into the box along periodic dimensions."""
    L = box.lengths
    folded = box.lo + jnp.mod(xyz - box.lo, L)
    return jnp.where(box.periodic_mask, folded, xyz)


def make_global_box(x, y, z, prev: Box, pad_factor: float = 0.0) -> Box:
    """Grow the box to fit all particles; never change periodic/fixed dims.

    Equivalent of makeGlobalBox (cstone/sfc/box_mpi.hpp:26-120): open
    dimensions expand to the particle extrema (optionally padded); periodic
    and fixed dimensions keep their limits. Runs inside jit; in a sharded
    program the min/max reductions become cross-device collectives
    automatically.
    """
    lo_fit = jnp.stack([x.min(), y.min(), z.min()])
    hi_fit = jnp.stack([x.max(), y.max(), z.max()])
    if pad_factor:
        pad = (hi_fit - lo_fit) * pad_factor
        lo_fit = lo_fit - pad
        hi_fit = hi_fit + pad
    keep = jnp.array([b != BoundaryType.open for b in prev.boundaries])
    lo = jnp.where(keep, prev.lo, jnp.minimum(prev.lo, lo_fit))
    hi = jnp.where(keep, prev.hi, jnp.maximum(prev.hi, hi_fit))
    return Box(lo=lo, hi=hi, boundaries=prev.boundaries)
