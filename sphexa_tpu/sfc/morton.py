"""3D Morton (Z-order) key codec on uint32 arrays.

Vectorized equivalent of the reference's ``cstone/sfc/morton.hpp`` (iMorton,
decodeMortonX/Y/Z): 10 bits per dimension interleaved into a 30-bit key with
x the most significant dimension. All ops are elementwise integer bit
arithmetic, so a single fused XLA kernel handles any batch shape.
"""

import jax.numpy as jnp

from sphexa_tpu.dtypes import KEY_BITS, KEY_DTYPE


def _spread_bits_3d(v):
    """Insert two zero bits between each of the low 10 bits of ``v``."""
    v = v.astype(KEY_DTYPE) & KEY_DTYPE(0x3FF)
    v = (v | (v << 16)) & KEY_DTYPE(0x030000FF)
    v = (v | (v << 8)) & KEY_DTYPE(0x0300F00F)
    v = (v | (v << 4)) & KEY_DTYPE(0x030C30C3)
    v = (v | (v << 2)) & KEY_DTYPE(0x09249249)
    return v


def _compact_bits_3d(v):
    """Inverse of :func:`_spread_bits_3d`: extract every third bit."""
    v = v.astype(KEY_DTYPE) & KEY_DTYPE(0x09249249)
    v = (v | (v >> 2)) & KEY_DTYPE(0x030C30C3)
    v = (v | (v >> 4)) & KEY_DTYPE(0x0300F00F)
    v = (v | (v >> 8)) & KEY_DTYPE(0x030000FF)
    v = (v | (v >> 16)) & KEY_DTYPE(0x000003FF)
    return v


def morton_encode(ix, iy, iz, bits: int = KEY_BITS):
    """Interleave integer grid coordinates into Morton keys.

    Coordinates are interpreted at ``bits`` levels, i.e. in ``[0, 2**bits)``;
    the result is a key in ``[0, 2**(3*bits))`` with x most significant.
    ``bits`` only documents the coordinate range here — interleaving is
    range-agnostic, which is what gives Morton keys their prefix property.
    """
    del bits
    return (
        (_spread_bits_3d(ix) << 2)
        | (_spread_bits_3d(iy) << 1)
        | _spread_bits_3d(iz)
    )


def morton_decode(key, bits: int = KEY_BITS):
    """Recover (ix, iy, iz) grid coordinates from Morton keys."""
    del bits
    key = key.astype(KEY_DTYPE)
    ix = _compact_bits_3d(key >> 2)
    iy = _compact_bits_3d(key >> 1)
    iz = _compact_bits_3d(key)
    return ix, iy, iz
