"""Float coordinates -> integer grid -> SFC keys.

Equivalent of the reference's ``cstone/sfc/sfc.hpp`` (computeSfcKeys /
sfc3D): normalize positions by the global box into the integer key grid,
then encode with the chosen curve. Default curve is Hilbert, matching the
reference's ``SfcKind = HilbertKey`` default (sfc.hpp:53-55).
"""

import jax.numpy as jnp

from sphexa_tpu.dtypes import INDEX_DTYPE, KEY_BITS, KEY_DTYPE
from sphexa_tpu.sfc.box import Box
from sphexa_tpu.sfc.hilbert import hilbert_encode
from sphexa_tpu.sfc.morton import morton_encode


def coords_to_igrid(v, vmin, vmax, bits: int = KEY_BITS):
    """Map float coordinates in [vmin, vmax] to integers in [0, 2**bits)."""
    n = 1 << bits
    scaled = (v - vmin) / (vmax - vmin) * n
    return jnp.clip(scaled.astype(INDEX_DTYPE), 0, n - 1).astype(KEY_DTYPE)


def compute_sfc_keys(x, y, z, box: Box, bits: int = KEY_BITS, curve: str = "hilbert"):
    """Compute SFC keys for particle positions under the global box."""
    ix = coords_to_igrid(x, box.lo[0], box.hi[0], bits)
    iy = coords_to_igrid(y, box.lo[1], box.hi[1], bits)
    iz = coords_to_igrid(z, box.lo[2], box.hi[2], bits)
    if curve == "hilbert":
        return hilbert_encode(ix, iy, iz, bits)
    elif curve == "morton":
        return morton_encode(ix, iy, iz, bits)
    raise ValueError(f"unknown curve {curve!r}")
