"""Space-filling-curve keys, bounding boxes and periodic-boundary math.

TPU-native equivalent of the reference's ``domain/include/cstone/sfc/``
(hilbert.hpp, morton.hpp, sfc.hpp, box.hpp): pure integer bit arithmetic,
fully vectorized over particle arrays, no per-particle control flow.
"""

from sphexa_tpu.sfc.box import Box, BoundaryType, apply_pbc, put_in_box, make_global_box
from sphexa_tpu.sfc.morton import morton_encode, morton_decode
from sphexa_tpu.sfc.hilbert import hilbert_encode, hilbert_decode
from sphexa_tpu.sfc.keys import compute_sfc_keys, coords_to_igrid

__all__ = [
    "Box",
    "BoundaryType",
    "apply_pbc",
    "put_in_box",
    "make_global_box",
    "morton_encode",
    "morton_decode",
    "hilbert_encode",
    "hilbert_decode",
    "compute_sfc_keys",
    "coords_to_igrid",
]
