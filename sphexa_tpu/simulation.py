"""Host-side simulation driver: config selection, step loop, diagnostics.

Counterpart of the reference front-end main loop (main/src/sphexa/
sphexa.cpp:145-174). The host's only jobs are (a) choosing the static
neighbor-search configuration (grid level, cell cap) and re-choosing it
when particle motion invalidates it — the rare recompile boundary — and
(b) logging/IO. All physics runs inside the jitted step.
"""

import dataclasses
import os
import time
from typing import Callable, Dict, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from sphexa_tpu.telemetry import Telemetry, emit_memory_event

from sphexa_tpu.gravity.traversal import GravityConfig, estimate_gravity_caps
from sphexa_tpu.neighbors.cell_list import (
    NeighborConfig,
    choose_grid_level,
)
from sphexa_tpu.propagator import (
    PropagatorConfig,
    step_hydro_std,
    step_hydro_std_blockdt,
    step_hydro_std_blockdt_donated,
    step_hydro_std_cooling,
    step_hydro_std_cooling_donated,
    step_hydro_std_donated,
    step_hydro_ve,
    step_hydro_ve_blockdt,
    step_hydro_ve_blockdt_donated,
    step_hydro_ve_donated,
    step_nbody,
    step_nbody_donated,
    step_sim_state,
    step_turb_ve,
    step_turb_ve_donated,
)
from sphexa_tpu.sfc.box import BoundaryType, Box
from sphexa_tpu.sph.blockdt import make_blockdt_state
from sphexa_tpu.sph.particles import ParticleState, SimConstants
from sphexa_tpu.state import SimState

_PROPAGATORS: Dict[str, Callable] = {
    "std": step_hydro_std,
    "ve": step_hydro_ve,
    "nbody": step_nbody,
    "turb-ve": step_turb_ve,
    "std-cooling": step_hydro_std_cooling,
}

# donated twins (propagator._step_pair): the particle-state pytree is
# consumed in place — ONLY safe on launch paths that can never need the
# input again (the deferred happy-path window, which pins a copy for
# rollback); the checked/replay paths always use _PROPAGATORS
_PROPAGATORS_DONATED: Dict[str, Callable] = {
    "std": step_hydro_std_donated,
    "ve": step_hydro_ve_donated,
    "nbody": step_nbody_donated,
    "turb-ve": step_turb_ve_donated,
    "std-cooling": step_hydro_std_cooling_donated,
}

# hierarchical block-timestep twins (Simulation(dt_bins=...)): the std/ve
# builders that carry a BlockDtState through the aux slot and return a
# 4-tuple; the donated variants consume the ParticleState ONLY, so the
# carry is safe to pin by reference for window rollback
_PROPAGATORS_BLOCKDT: Dict[str, Callable] = {
    "std": step_hydro_std_blockdt,
    "ve": step_hydro_ve_blockdt,
}
_PROPAGATORS_BLOCKDT_DONATED: Dict[str, Callable] = {
    "std": step_hydro_std_blockdt_donated,
    "ve": step_hydro_ve_blockdt_donated,
}


def make_propagator_config(
    state: ParticleState,
    box: Box,
    const: SimConstants,
    ngmax: Optional[int] = None,
    block: Optional[int] = None,
    curve: str = "hilbert",
    min_cap: int = 0,
    av_clean: bool = False,
    keep_accels: bool = False,
    keep_fields: bool = False,
    backend: str = "auto",
    cell_target: Optional[int] = None,
    run_cap: Optional[int] = None,
    gap: Optional[int] = None,
    group: Optional[int] = None,
    device_sizing: bool = False,
    use_lists: bool = False,
    list_skin_rel: Optional[float] = None,
    list_slot_margin: float = 1.3,
    sizing_cache=None,
    obs_spec=None,
    snap_spec=None,
    tuned: object = None,
    workload: Optional[str] = None,
    dt_bins: Optional[int] = None,
    bin_sync_every: int = 1,
    bin_resort_drift: float = 0.0,
) -> PropagatorConfig:
    """Size the static neighbor-search config from the current particle
    distribution (single source of truth — used by Simulation, tests and
    the driver entry points).

    ``cell_target`` picks the grid level by mean cell occupancy;
    ``run_cap``/``gap`` control the pallas engine's merged-run streaming
    (cell_list.NeighborConfig). Defaults tuned on v5e (scripts/
    sweep_engine.py): ~128-per-cell grids beat finer levels (fragmented
    short runs waste 128-lane chunks), and aggressive run merging cuts
    the per-group DMA count ~3x.

    ``device_sizing``: compute every sizing statistic with jitted
    reductions on the (possibly sharded) device arrays and fetch only
    scalars — the O(N/P) path multi-device runs use (VERDICT r3 #3; the
    reference's rank-local assignment, assignment.hpp:84-122). The
    default host path keeps the native C++ runtime exercised
    single-device.

    ``sizing_cache``: optional precomputed (keys, order) device arrays
    for the device_sizing path, so a caller that also needs keys (the
    gravity reconfigure) computes them once.
    """
    if backend == "auto":
        # fused pallas kernels on TPU, portable gather path elsewhere
        backend = "pallas" if jax.default_backend() == "tpu" else "xla"
    # tuned knob resolution (docs/TUNING.md): the engine knobs default to
    # None so an explicit kwarg stays detectable; precedence is explicit
    # kwarg > table entry (``tuned=``) > the measured defaults below.
    # Table lookups here are single-device (P=1) — Simulation resolves
    # with the real mesh size and passes the winners explicitly.
    _defaults = {"block": 2048, "cell_target": 128, "run_cap": 1536,
                 "gap": 384, "group": 64, "list_skin_rel": 0.2}
    _explicit = {
        k: v for k, v in (("block", block), ("cell_target", cell_target),
                          ("run_cap", run_cap), ("gap", gap),
                          ("group", group),
                          ("list_skin_rel", list_skin_rel))
        if v is not None
    }
    _tuned = {}
    if tuned is not None:
        from sphexa_tpu.tuning.table import resolve_knobs

        _tuned, _ = resolve_knobs(tuned, workload=workload, n=state.n,
                                  p=1, backend=backend,
                                  explicit=_explicit)
    block, cell_target, run_cap, gap, group, list_skin_rel = (
        _explicit.get(k, _tuned.get(k, _defaults[k]))
        for k in ("block", "cell_target", "run_cap", "gap", "group",
                  "list_skin_rel"))
    from sphexa_tpu.neighbors.cell_list import pad_cap, window_cells

    if device_sizing:
        from sphexa_tpu.parallel import sizing

        lengths = np.asarray(sizing.fetch(box.lengths))
        h_max = float(sizing.fetch(jnp.max(state.h)))
        level = choose_grid_level(lengths, h_max)
        level_occ = max(
            1, round(np.log2(max(state.n / float(cell_target), 1.0)) / 3.0)
        )
        level = min(level, level_occ)
        occ, ext_d = sizing.sizing_stats(
            state.x, state.y, state.z, box, level, group, curve,
            *(sizing_cache or (None, None))
        )
        cap = pad_cap(int(sizing.fetch(occ)))
        ext = np.asarray(sizing.fetch(ext_d))
        if min_cap > 0:
            cap = max(cap, pad_cap(min_cap))
        ncell = 1 << level
    else:
        lengths = np.asarray(box.lengths)
        h = np.asarray(state.h)
        h_max = float(h.max())
        level = choose_grid_level(lengths, h_max)
        # group-window search covers the 2h radius at ANY level, so the
        # level is free to target cell occupancy instead; below
        # ~cell_target particles per cell the extra window cells stop
        # paying for the tighter candidate volume
        level_occ = max(
            1, round(np.log2(max(state.n / float(cell_target), 1.0)) / 3.0)
        )
        level = min(level, level_occ)

        # host-side sizing pass: one device->host transfer of the
        # coordinates, then the native C++ runtime (sphexa_tpu/native)
        # does keygen, sort and occupancy/window accounting (numpy/jax
        # fallback inside)
        from sphexa_tpu import native

        xa = np.asarray(state.x)
        ya = np.asarray(state.y)
        za = np.asarray(state.z)
        keys = native.compute_keys(xa, ya, za, np.asarray(box.lo), lengths,
                                   curve)
        order = native.argsort_keys(keys)

        cap = pad_cap(native.max_cell_occupancy(keys[order], level))
        if min_cap > 0:
            cap = max(cap, pad_cap(min_cap))  # quantized so retry caps cache
        ncell = 1 << level
        ext = native.group_extents(xa, ya, za, order, group)
    # 10% radius slack absorbs drift between reconfigurations; a whole
    # margin cell costs ~2x window cells (every cell is a kernel iteration),
    # and the window_ok guard reconfigures if the slack is ever outgrown.
    def size_window(radius):
        w = 1
        for e, edge in zip(ext, lengths / ncell):
            w = max(w, window_cells(e, radius, float(edge), ncell,
                                    margin_cells=0))
        return w

    def make_nbr(window):
        return NeighborConfig(
            level=level, cap=cap, ngmax=ngmax or const.ngmax, block=block,
            curve=curve, group=group, window=window,
            run_cap=run_cap, gap=gap,
        )

    nbr = make_nbr(size_window(4.0 * h_max * 1.1))
    slot_cap = 0
    skin = list_skin_rel * 2.0 * h_max
    if use_lists and backend == "pallas" and not device_sizing:
        from sphexa_tpu.sph.pair_lists import estimate_slot_cap
        from sphexa_tpu.sph.pallas_pairs import engine_fold

        # fold-mode eligibility is checked on the UNinflated window: the
        # skin inflation only pays off when lists actually engage
        if not engine_fold(box, nbr):
            import jax.numpy as _jnp

            # in list mode the window must additionally cover the skin
            nbr = make_nbr(size_window((4.0 * h_max + skin) * 1.1))
            if engine_fold(box, nbr):
                nbr = make_nbr(size_window(4.0 * h_max * 1.1))
            else:
                # reuse the native sizing pass's keys/order (a second
                # device keygen+argsort at 1M costs tens of ms per
                # reconfigure for nothing)
                skeys = _jnp.asarray(keys[order])
                slot_cap = estimate_slot_cap(
                    _jnp.asarray(xa[order]), _jnp.asarray(ya[order]),
                    _jnp.asarray(za[order]), _jnp.asarray(h[order]),
                    skeys, box, nbr, skin, margin=list_slot_margin,
                )
    return PropagatorConfig(
        const=const, nbr=nbr, curve=curve, block=block, av_clean=av_clean,
        keep_accels=keep_accels, keep_fields=keep_fields, backend=backend,
        list_slot_cap=slot_cap, list_skin_rel=list_skin_rel, obs=obs_spec,
        snap=snap_spec,
        dt_bins=dt_bins, bin_sync_every=bin_sync_every,
        bin_resort_drift=bin_resort_drift,
    )


def _dealias_leaves(tree):
    """Copy pytree leaves that are the SAME array object as an earlier
    leaf, so the whole tree is donatable (XLA: `f(donate(a), donate(a))`
    is an error)."""
    seen = set()

    def fix(a):
        if not hasattr(a, "ndim"):
            return a
        if id(a) in seen:
            return jnp.copy(a)
        seen.add(id(a))
        return a

    return jax.tree.map(fix, tree)


#: tuned knobs the configure paths forward wholesale (rather than
#: resolving through ``_knob`` in the constructor): neighbor-engine
#: shape into make_propagator_config, gravity-solver shape into the
#: gravity_tuning override
_NBR_FORWARDED = ("cell_target", "run_cap", "gap", "group")
_GRAV_FORWARDED = ("target_block", "blocks_per_chunk", "super_factor")

#: every knob name the Simulation constructor actually consumes — the
#: ``_knob``-resolved set plus the forwarded groups above. This is the
#: LIVE consumption surface ``tuning.knobs.validate_off_sentinels``
#: cross-checks the off-sentinel declarations against: rename a
#: resolution site without updating this tuple (or vice versa) and the
#: registry validation fails at import, instead of JXA402's inertness
#: probe passing vacuously because ``tuned={name: ...}`` stopped
#: reaching the lowering.
CONSUMED_KNOBS = (
    "block", "list_skin_rel", "m2p_cap_margin", "check_every",
    "grav_window", "grav_window_margin", "dt_bins", "bin_sync_every",
    "bin_resort_drift", "donate",
) + _NBR_FORWARDED + _GRAV_FORWARDED


class Simulation:
    """Owns state + static configs; reconfigures (recompiles) only when the
    cell grid no longer covers the interaction radius or a cell overflows
    its candidate cap."""

    def __init__(
        self,
        state: ParticleState,
        box: Box,
        const: SimConstants,
        prop: str = "std",
        ngmax: Optional[int] = None,
        block: Optional[int] = None,
        curve: str = "hilbert",
        av_clean: bool = False,
        theta: float = 0.5,
        grav_bucket: int = 64,
        keep_accels: bool = False,
        keep_fields: bool = False,
        backend: str = "auto",
        turb_cfg=None,
        turb_state=None,
        turb_settings: Optional[Dict] = None,
        cooling_cfg=None,
        chem=None,
        check_every: Optional[int] = None,
        num_devices: Optional[int] = None,
        use_lists: bool = True,
        list_skin_rel: Optional[float] = None,
        halo_mode: str = "sparse",
        grav_window: Optional[int] = None,
        grav_window_margin: Optional[float] = None,
        m2p_cap_margin: Optional[float] = None,
        donate: object = "auto",
        debug_checks: bool = False,
        telemetry: Optional[Telemetry] = None,
        imbalance_ratio: float = 1.5,
        obs_spec=None,
        snap_spec=None,
        snap_every: Optional[int] = None,
        snap_keep: Optional[int] = None,
        snap_dir: Optional[str] = None,
        drift_budget: Optional[float] = None,
        science_rows: bool = False,
        tuned: object = None,
        workload: Optional[str] = None,
        dt_bins: Optional[int] = None,
        bin_sync_every: Optional[int] = None,
        bin_resort_drift: Optional[float] = None,
    ):
        # telemetry registry: every driver-visible control-flow event
        # (reconfigure/rollback/replay/retrace) and step timing reports
        # here. A sink-less default keeps counters for free; pass a
        # Telemetry with sinks (app --telemetry-dir) to persist them.
        # Hot-loop contract: the instrumentation below is host-only —
        # perf_counter stamps, Counter bumps, jit-cache-size reads — and
        # must NEVER add a device->host transfer to the deferred happy
        # path (pinned by tests/test_telemetry.py's no-sync guard).
        self.telemetry = telemetry if telemetry is not None else Telemetry()
        self._window_t0 = None  # host stamp of the open window's 1st launch
        # tuned knob resolution (sphexa_tpu/tuning): precedence is
        # explicit kwarg > table entry > gravity_tuning/default heuristic,
        # resolved ONCE here and applied through the normal configure
        # paths below. The tuning-covered constructor params default to
        # None so explicitness is detectable; ``tuned`` is None / "auto" /
        # a table path / a knob dict (the sweep's candidate path) and
        # ``workload`` keys the table lookup (the init case name).
        explicit_knobs = {
            k: v for k, v in (("block", block),
                              ("list_skin_rel", list_skin_rel),
                              ("m2p_cap_margin", m2p_cap_margin),
                              ("check_every", check_every),
                              ("grav_window", grav_window),
                              ("grav_window_margin", grav_window_margin),
                              ("dt_bins", dt_bins),
                              ("bin_sync_every", bin_sync_every),
                              ("bin_resort_drift", bin_resort_drift),
                              # "auto" is donate's unset marker (the
                              # param predates the knob registry and
                              # keeps its legacy default)
                              ("donate", None if donate == "auto"
                               else donate))
            if v is not None
        }
        from sphexa_tpu.tuning.table import resolve_knobs

        tuned_knobs, self.tuning_provenance = resolve_knobs(
            tuned, workload=workload, n=state.n, p=num_devices or 1,
            backend=backend if backend != "auto" else
            ("pallas" if jax.default_backend() == "tpu" else "xla"),
            explicit=explicit_knobs,
        )

        def _knob(name, default):
            return explicit_knobs.get(name, tuned_knobs.get(name, default))

        block = _knob("block", 2048)
        list_skin_rel = _knob("list_skin_rel", 0.2)
        m2p_cap_margin = _knob("m2p_cap_margin", 1.3)
        check_every = _knob("check_every", 1)
        donate = _knob("donate", "auto")
        # MAC-sized sparse gravity near field (parallel/sizing.
        # device_gravity_halo): grav_window is the per-distance cap
        # padding quantum in rows (caps cache across retries at its
        # multiples); 0 = ship full peer slabs (the pre-sizing behavior,
        # byte-identical lowering). grav_window_margin pads the measured
        # MAC need and is GROWN 1.5x per escape-sentinel trip, with full
        # slabs as the retry ceiling.
        self.grav_window = int(_knob("grav_window", 256))
        if self.grav_window < 0:
            raise ValueError(
                f"grav_window must be >= 0, got {self.grav_window}")
        self._grav_halo_margin = float(_knob("grav_window_margin", 1.4))
        # hierarchical block time steps (sph/blockdt.py): dt_bins=None is
        # today's global-dt path, bitwise unchanged; dt_bins=1 runs the
        # blockdt machinery pinned bitwise-equal to it (tests/
        # test_blockdt.py); dt_bins>1 activates per-particle Δt bins
        dt_bins = _knob("dt_bins", None)
        bin_sync_every = int(_knob("bin_sync_every", 1))
        bin_resort_drift = float(_knob("bin_resort_drift", 0.0))
        self._blockdt = dt_bins is not None
        if self._blockdt:
            if prop not in _PROPAGATORS_BLOCKDT:
                raise ValueError(
                    f"dt_bins (hierarchical block time steps) supports "
                    f"the std/ve propagators, not prop={prop!r}"
                )
            dt_bins = int(dt_bins)
            if dt_bins < 1:
                raise ValueError(f"dt_bins must be >= 1, got {dt_bins}")
            if bin_sync_every < 1:
                raise ValueError(
                    f"bin_sync_every must be >= 1, got {bin_sync_every}")
            if bin_resort_drift < 0.0:
                raise ValueError(
                    f"bin_resort_drift must be >= 0, got {bin_resort_drift}")
        self.dt_bins = dt_bins
        self.bin_sync_every = bin_sync_every
        self.bin_resort_drift = bin_resort_drift
        # host-side block-dt accounting across fetch boundaries — the
        # chip-free complexity proxy (docs/NEXT.md): particle updates
        # actually performed vs what global-dt would have performed over
        # the same substeps (each substep advances dt_min either way)
        self.bdt_updates = 0
        self.bdt_updates_full = 0
        self.bdt_resorts = 0
        self.bdt_keeps = 0
        # reconfigure-cost knobs the configure paths consume each time
        self._nbr_knobs = {k: tuned_knobs[k]
                           for k in _NBR_FORWARDED if k in tuned_knobs}
        self._grav_knobs = {k: tuned_knobs[k]
                            for k in _GRAV_FORWARDED if k in tuned_knobs}
        if tuned is not None:
            # the decision is itself telemetry: which knobs are active
            # and WHY (table entry key + its provenance, or the
            # heuristic fallthrough on a coverage miss)
            self.telemetry.event("tuning", workload=workload,
                                 **self.tuning_provenance)
        # distributed observability (schema v2): the imbalance watchdog
        # fires a first-class event when max/mean of a per-shard metric
        # (pair work, halo rows, halo occupancy) crosses this ratio —
        # the runtime mirror of the retrace watchdog, for the quantity
        # the tree-code lineage says scaling lives on (Warren-Salmon
        # per-processor work accounting, PAPERS.md)
        self._imbalance_ratio = float(imbalance_ratio)
        # static shape of the active halo exchange (mode + shipped rows),
        # stamped by _configure_sharded for the exchange events
        self._halo_info: Optional[Dict] = None
        # gravity-stage analog (schema-v7 stage="gravity" events): the
        # MAC-sized sparse near-field caps + volume, or the full-slab
        # fallback's shape; None when no explicit gravity exchange runs
        self._grav_halo_info: Optional[Dict] = None
        self._grav_cells: Tuple[int, ...] = ()
        self._mem_post_compile = False  # one "post-compile" HBM snapshot
        # physics observability (schema v3): the in-graph science ledger
        # (propagator OBS/NUM_DIAG_KEYS) is fetched with the step
        # diagnostics at the existing check/flush boundaries and emitted
        # as physics/numerics events. Two watchdogs mirror the imbalance
        # one: conservation drift (|etot - etot0| / |etot0| past
        # ``drift_budget``; None = report-only) and field health (any
        # nonfinite rho/h/du — the pointer to --debug-checks for
        # localization).
        self._obs_spec = obs_spec
        self._drift_budget = (None if drift_budget is None
                              else float(drift_budget))
        self._etot0: Optional[float] = None
        #: |Δetot|/|etot0| at the last fetch boundary (bench stamps it)
        self.energy_drift: Optional[float] = None
        # per-step science rows (constants.txt material) accumulated at
        # verified boundaries for drain_science(); opt-in so library
        # drivers that never drain don't grow an unbounded list
        self._collect_science = bool(science_rows)
        self._science: list = []
        # live science surface (schema v8, observables/snapshot.py): the
        # in-graph field-grid deposit rides the diagnostics dict and is
        # fetched at the SAME check/flush boundaries — zero added host
        # syncs under deferral (pinned by the no-sync guard). Frames go
        # to a sidecar ``snapshots/`` ring of .npz files (capped by
        # snap_keep); (it, path) pairs accumulate for drain_snapshots()
        # (the --insitu consumer).
        self._snap_spec = snap_spec
        self._snap_every = max(1, int(snap_every)) if snap_every else 1
        self._snap_keep = int(snap_keep) if snap_keep else 0
        self._snap_dir = snap_dir
        if snap_spec is not None and snap_dir is None and telemetry is not None:
            # default the ring next to events.jsonl (the JsonlSink's dir)
            for sink in getattr(telemetry, "sinks", ()) or ():
                p = getattr(sink, "path", None)
                if p:
                    self._snap_dir = os.path.join(
                        os.path.dirname(str(p)) or ".", "snapshots")
                    break
        self._snap_frames: list = []   # (iteration, path) for drain
        self._snap_ring: list = []     # paths live in the ring, oldest first
        self.state = state
        self.box = box
        self.const = const
        self.prop_name = prop
        self.block = block
        self.curve = curve
        self.av_clean = av_clean
        self.keep_accels = keep_accels
        self.keep_fields = keep_fields
        self.backend = backend
        self.ngmax = ngmax or const.ngmax
        self.theta = theta
        self.grav_bucket = grav_bucket
        self.m2p_cap_margin = m2p_cap_margin
        # multi-chip: shard the state over a device mesh and drive the
        # sharded step (parallel/mesh.py) through the SAME loop —
        # reconfiguration re-sizes the per-peer halo window exactly like
        # the neighbor caps (the sphexa.cpp main loop never special-cases
        # rank count either)
        self._mesh = None
        self._halo_margin = 1.4
        # sparse: cell-granular per-distance halo buffers (the measured
        # fix for the degenerate contiguous windows, docs/NEXT.md);
        # windowed: contiguous per-peer row windows (kept for equivalence
        # tests and as a fallback)
        if halo_mode not in ("sparse", "windowed"):
            raise ValueError(f"halo_mode must be sparse|windowed, got "
                             f"{halo_mode!r}")
        self._halo_mode = halo_mode
        # buffer donation (propagator step_*_donated): the deferred
        # happy-path windows launch the donated twins so XLA aliases the
        # step output into the input state buffers (no double-buffering
        # of the dominant allocation). "auto" engages on TPU only — CPU
        # honors donation too, but tier-1 discard-and-replay semantics
        # are pinned to the undonated path there; donate=True opts in
        # anywhere (the rollback pin becomes a copy, see step()).
        if donate not in ("auto", True, False):
            raise ValueError(f"donate must be 'auto'|True|False, got "
                             f"{donate!r}")
        self._donate_active = donate is True or (
            donate == "auto" and jax.default_backend() == "tpu"
        )
        # runtime sanitizer (--debug-checks): the step runs under
        # jax.experimental.checkify with NaN/Inf + out-of-bounds-index
        # checks; the first triggered check is surfaced through the step
        # diagnostics as ``check_error``. Synchronous checking only (the
        # sanitizer exists to LOCALIZE failures, deferral would smear
        # them across a window), lists/donation fast paths disabled.
        self.debug_checks = bool(debug_checks)
        self._check_err = None
        self._checked_cache: Dict = {}
        if self.debug_checks:
            if num_devices is not None and num_devices > 1:
                raise ValueError(
                    "debug_checks is single-device (wrap the sharded "
                    "stepper is future work); drop num_devices or the flag"
                )
            check_every = 1
            use_lists = False
            self._donate_active = False
        if num_devices is not None and num_devices > 1:
            from sphexa_tpu.parallel import make_mesh, shard_state

            if state.n % num_devices:
                raise ValueError(
                    f"particle count {state.n} not divisible by "
                    f"{num_devices} devices; pad the state first"
                )
            self._mesh = make_mesh(num_devices)
            self.state = shard_state(state, self._mesh)
            # donation is wired on the single-device launch paths only;
            # the sharded stepper (make_sharded_step) owns its own jit
            self._donate_active = False
        if self._donate_active:
            # take ownership: donated launches consume state buffers in
            # place, and the INITIAL state belongs to the caller (tests
            # and restart flows reuse it) — one construction-time copy
            # keeps the caller's arrays alive
            self.state = jax.tree.map(
                lambda a: jnp.copy(a) if hasattr(a, "ndim") else a,
                self.state,
            )
        # block-dt carry: per-particle bins + cycle scalars, built AFTER
        # sharding so the (n,) leaves come from the placed state; never
        # donated (the blockdt donated twins consume the ParticleState
        # only), so window rollback pins it by reference
        self._bstate = (make_blockdt_state(self.state, dt_bins)
                        if self._blockdt else None)
        if prop == "nbody" and const.g == 0.0:
            raise ValueError(
                "prop='nbody' needs a gravitational constant: set SimConstants(g=...)"
            )
        self.gravity_on = const.g != 0.0
        any_periodic = any(b == BoundaryType.periodic for b in box.boundaries)
        all_periodic = all(b == BoundaryType.periodic for b in box.boundaries)
        self.ewald_on = self.gravity_on and all_periodic
        if self.gravity_on and any_periodic and not all_periodic:
            raise NotImplementedError(
                "self-gravity supports fully periodic (Ewald) or fully "
                "open boundaries, not mixed ones (same restriction as the "
                "reference's computeGravityEwald)"
            )
        if self.ewald_on:
            lx = np.asarray(box.lengths)
            if not np.allclose(lx, lx[0]):
                raise ValueError(
                    "Ewald gravity requires a cubic periodic box "
                    "(traversal_ewald_cpu.hpp:366)"
                )
        # turbulence stirring state (turb-ve propagator): built from the
        # case settings unless an explicit (cfg, state) pair is given,
        # e.g. restored from a checkpoint
        self.turb_cfg = turb_cfg
        self.turb_state = turb_state
        if prop == "turb-ve" and self.turb_cfg is None:
            from sphexa_tpu.init.turbulence import turbulence_constants
            from sphexa_tpu.sph.hydro_turb import create_stirring_modes

            s = dict(turbulence_constants(), **(turb_settings or {}))
            self.turb_cfg, fresh_state = create_stirring_modes(
                lbox=float(np.max(np.asarray(box.lengths))),
                st_max_modes=int(s["stMaxModes"]),
                energy_prefac=s["stEnergyPrefac"],
                mach_velocity=s["stMachVelocity"],
                sol_weight=s["solWeight"],
                spect_form=int(s["stSpectForm"]),
                seed=int(s["rngSeed"]),
                power_law_exp=float(s.get("powerLawExp", 5.0 / 3.0)),
                angles_exp=float(s.get("anglesExp", 2.0)),
            )
            # a caller-provided state (checkpoint restore) overrides the
            # fresh OU phases but keeps the derived static config
            if self.turb_state is None:
                self.turb_state = fresh_state
        # radiative cooling (std-cooling propagator): reduced CIE model
        self.cooling_cfg = cooling_cfg
        self.chem = chem
        if prop == "std-cooling":
            from sphexa_tpu.physics.cooling import ChemistryData, CoolingConfig

            if self.cooling_cfg is None:
                self.cooling_cfg = CoolingConfig(gamma=const.gamma)
            if self.chem is None:
                self.chem = ChemistryData.ionized(state.n)
            if self._mesh is not None:
                from sphexa_tpu.parallel import shard_state

                # per-particle chemistry rides the slab sharding like the
                # state (std_hydro_grackle.hpp runs under the full domain)
                self.chem = shard_state(self.chem, self._mesh)
        # persistent neighbor lists (sph/pair_lists.py): steady steps skip
        # the global sort + prologue and lane-compact the momentum ops;
        # enabled on the single-device pallas path without gravity (the
        # gravity tree rebuild needs fresh keys per step today). The
        # eligibility re-derives at every _configure (fold mode depends
        # on the sized grid).
        self._want_lists = use_lists
        self._list_skin_rel = list_skin_rel
        self._lists = None
        self._slot_margin = 1.3
        self.iteration = 0
        # deferred cap-checking (check_every > 1): the happy path launches
        # steps without any device->host sync; diagnostics of the last
        # ``check_every`` steps are fetched in ONE batched transfer at the
        # check boundary. JAX arrays are immutable, so the rollback point
        # costs one pinned state: we keep the window-start pytree refs
        # alive and replay the window if a deferred check finds an
        # overflow.
        self.check_every = max(1, check_every)
        # executable signatures THIS run has launched (compile-watchdog
        # per-run baseline; see _launch_signature)
        self._launched_sigs: set = set()
        self._pending = []  # per-step diagnostics of the open window
        self._window_prior = None  # (SimState pin, iteration) at window start
        self._last_diag: Dict[str, float] = {"reconfigured": 0.0}
        self._cfg: Optional[PropagatorConfig] = None
        self._gtree = None
        self._configure(reason="initial")

    # -- static config management ------------------------------------------
    @property
    def _lists_eligible(self) -> bool:
        # blockdt steps run their own fold-key sort prologue and have no
        # frozen-order fast path — lists stay off under dt_bins
        return (
            self._want_lists
            and self._mesh is None
            and not self.gravity_on
            and self.prop_name != "nbody"
            and not self._blockdt
        )

    def _configure(self, min_cap: int = 0, grav_margin: float = 1.5,
                   reason: str = "reconfigure"):
        with self.telemetry.annotate("sphexa:reconfigure"):
            self._configure_impl(min_cap, grav_margin)
        # a reconfigure used to be visible only as one dict entry
        # (``reconfigured``) on one step's diagnostics — as telemetry it
        # is a first-class event with the WHY attached; the expected
        # construction-time sizing stays out of the health counter
        if reason != "initial":
            self.telemetry.count("reconfigures")
        self.telemetry.event("reconfigure", it=self.iteration, reason=reason)

    def _configure_impl(self, min_cap: int = 0, grav_margin: float = 1.5):
        self._lists = None  # any static re-size invalidates the lists
        if self._mesh is not None:
            # drain in-flight steps before dispatching the sizing jits:
            # those jits contain their own collectives, and on CPU meshes
            # two concurrently executing programs' collective channels can
            # collide (observed as an all-reduce rendezvous hang when a
            # mid-run reconfigure overlapped the previous step)
            jax.block_until_ready(jax.tree.leaves(self.state))
        # multi-device: every sizing statistic comes from jitted device
        # reductions (O(N/P) transfers, parallel/sizing.py); single-device
        # keeps the native C++ host sizing pass. Multi-device consumers
        # of device keys (sizing_stats, the gravity tree build/need
        # sizing, AND _configure_sharded's halo-need scan) share ONE
        # keygen+argsort over N computed here (the round-4 reviewer's
        # double-keygen finding — _configure_sharded used to redo the
        # pair). Keys are generated against the make_global_box fit so
        # the shared cache matches what the halo scan keyed on; open
        # dims only ever expand to the particle extrema, so on a
        # post-step state (box already refit by the step prologue) the
        # values coincide with self.box.
        sizing_cache = None
        if self.gravity_on or (self._mesh is not None
                               and self._halo_sizing_needed()):
            from sphexa_tpu.sfc.box import make_global_box
            from sphexa_tpu.sfc.keys import compute_sfc_keys

            gbox = make_global_box(
                self.state.x, self.state.y, self.state.z, self.box
            )
            keys_d = compute_sfc_keys(
                self.state.x, self.state.y, self.state.z, gbox,
                curve=self.curve,
            )
            sizing_cache = (keys_d, jnp.argsort(keys_d), gbox)
        self._cfg = make_propagator_config(
            self.state, self.box, self.const,
            ngmax=self.ngmax, block=self.block, curve=self.curve, min_cap=min_cap,
            av_clean=self.av_clean, keep_accels=self.keep_accels,
            keep_fields=self.keep_fields, backend=self.backend,
            device_sizing=self._mesh is not None,
            use_lists=self._lists_eligible,
            list_skin_rel=self._list_skin_rel,
            list_slot_margin=self._slot_margin,
            sizing_cache=sizing_cache[:2] if sizing_cache else None,
            obs_spec=self._obs_spec,
            snap_spec=self._snap_spec,
            dt_bins=self.dt_bins, bin_sync_every=self.bin_sync_every,
            bin_resort_drift=self.bin_resort_drift,
            # table-resolved neighbor-engine knobs (cell_target/run_cap/
            # gap/group); absent keys fall to the factory defaults
            **self._nbr_knobs,
        )
        if self.gravity_on:
            self._configure_gravity(grav_margin, keys_cache=sizing_cache)
        if self._mesh is not None:
            self._configure_sharded(sizing_cache)

    def _halo_sizing_needed(self) -> bool:
        """Whether _configure_sharded will run the explicit halo-need
        scan (pallas fast path) — i.e. whether it consumes device keys
        and should share _configure_impl's keygen cache."""
        if self.prop_name == "nbody":
            return False
        backend = self.backend
        if backend == "auto":
            backend = "pallas" if jax.default_backend() == "tpu" else "xla"
        return backend == "pallas"

    def _configure_sharded(self, sizing_cache=None):
        """(Re)build the sharded stepper: size the per-peer halo window
        from the current distribution (estimate_halo_window) and bind it
        into make_sharded_step. Called at every reconfiguration, so an
        escape-sentinel overflow grows the window via _halo_margin.
        ``sizing_cache``: _configure_impl's shared (keys, order, gbox)
        so the halo-need scan reuses the one keygen+argsort over N
        instead of redoing it (the round-4 double-keygen finding)."""
        from sphexa_tpu.parallel import make_sharded_step
        from sphexa_tpu.sfc.box import make_global_box

        wmax = 0
        hcells = ()
        if self._cfg.backend == "pallas" and self.prop_name != "nbody":
            # device-side discovery: the needs scan runs as jitted
            # reductions over the sharded arrays and only P-1 scalars
            # reach the host (parallel/sizing.py — the rank-local
            # assignment analog, assignment.hpp:84-122)
            from sphexa_tpu.parallel.sizing import (
                device_halo_window, device_sparse_halo,
            )
            from sphexa_tpu.sfc.keys import compute_sfc_keys

            s = self.state
            if sizing_cache is not None:
                keys, _, gbox = sizing_cache
            else:
                gbox = make_global_box(s.x, s.y, s.z, self.box)
                keys = compute_sfc_keys(s.x, s.y, s.z, gbox,
                                        curve=self.curve)
            if self._halo_mode == "sparse":
                hcells = device_sparse_halo(
                    s.x, s.y, s.z, s.h, keys, gbox, self._cfg.nbr,
                    P=self._mesh.size, margin=self._halo_margin,
                )
            else:
                wmax = device_halo_window(
                    s.x, s.y, s.z, s.h, keys, gbox, self._cfg.nbr,
                    P=self._mesh.size, margin=self._halo_margin,
                )
        aux_cfg = None
        if self.prop_name == "turb-ve":
            aux_cfg = self.turb_cfg
        elif self.prop_name == "std-cooling":
            aux_cfg = self.cooling_cfg
        # static exchange shape for telemetry: shipped rows per serve is
        # a config-time constant (the measure_multichip.py size formula),
        # bytes/step = rows x per-propagator serve fields x 4B
        from sphexa_tpu.propagator import exchange_fields_per_step

        P = self._mesh.size
        S = self.state.n // P
        nf = exchange_fields_per_step(self.prop_name, self.av_clean)
        if hcells:
            shipped = int(sum(min(c, S) for c in hcells))
            self._halo_info = {"mode": "sparse", "caps": tuple(hcells),
                               "shipped_rows": shipped}
        elif self._cfg.backend == "pallas" and self.prop_name != "nbody":
            w = min(wmax, S) or S
            self._halo_info = {"mode": "windowed", "wmax": w,
                               "shipped_rows": (P - 1) * w}
        else:
            # GSPMD path: XLA owns the collectives, no explicit exchange
            self._halo_info = {"mode": "gspmd", "shipped_rows": 0}
        self._halo_info["bytes_per_step"] = (
            self._halo_info["shipped_rows"] * nf * 4)
        # gravity-stage exchange shape (schema-v7 stage="gravity"
        # events): the explicit near-field serve runs only on the pallas
        # fast path (the GSPMD/nbody fallback leaves collectives to XLA)
        self._grav_halo_info = None
        if (self.gravity_on and self._cfg.backend == "pallas"
                and self.prop_name != "nbody"):
            # the Ewald replica loop serves once per shell — the volume
            # accounting scales with the static shell count
            nshell = 1
            if self._cfg.ewald is not None:
                r = self._cfg.ewald.num_replica_shells
                nshell = (2 * r + 1) ** 3
            if self._grav_cells:
                caps = tuple(min(int(c), S) for c in self._grav_cells)
                shipped = int(sum(caps))
                self._grav_halo_info = {"mode": "sparse", "caps": caps,
                                        "shipped_rows": shipped}
            else:
                self._grav_halo_info = {"mode": "windowed", "wmax": S,
                                        "shipped_rows": (P - 1) * S}
            # 5 served fields (x, y, z, m, h) x f32
            self._grav_halo_info["bytes_per_step"] = (
                self._grav_halo_info["shipped_rows"] * 5 * 4 * nshell)
        self._stepper = make_sharded_step(
            self._mesh, self._cfg, self._step_fn(),
            halo_window=wmax, halo_cells=hcells,
            grav_cells=self._grav_cells, aux_cfg=aux_cfg,
        )

    def _configure_gravity(self, margin: float, keys_cache=None):
        """(Re)build the gravity tree structure from the current particle
        distribution and size the interaction-list caps (the gravity analog
        of re-sizing the neighbor cell grid — reconfiguration granularity
        only). The histogram-pyramid device build
        (sizing.leaf_array_from_device_keys — the update_mpi.hpp
        node-count allreduce transposed) plus device-side sort/multipoles
        is the ONLY build path, single- and multi-device alike, so only
        O(#cells) histograms and O(tree) arrays ever reach the host; the
        host-numpy ``build_gravity_tree`` survives purely as the test
        oracle the pyramid is pinned equal to. ``keys_cache`` carries
        _configure's (keys, order) so keygen+argsort over N runs once per
        reconfigure, not once per consumer."""
        s = self.state
        from sphexa_tpu.gravity.tree import linkage_from_leaves
        from sphexa_tpu.parallel.sizing import leaf_array_from_device_keys
        from sphexa_tpu.sfc.keys import compute_sfc_keys

        if keys_cache is not None:
            keys_d, order = keys_cache[0], keys_cache[1]
        else:
            keys_d = compute_sfc_keys(s.x, s.y, s.z, self.box,
                                      curve=self.curve)
            order = jnp.argsort(keys_d)
        leaf_tree = leaf_array_from_device_keys(
            keys_d, bucket_size=self.grav_bucket
        )
        gtree, meta = linkage_from_leaves(leaf_tree, curve=self.curve)
        skeys = keys_d[order]
        xs, ys, zs, ms = s.x[order], s.y[order], s.z[order], s.m[order]
        # scale-dependent solver shape (target_block / hierarchical
        # bitmask compaction at >= 500k, gravity_tuning) — bench.py uses
        # the same helper so the benchmarked config IS this one
        from sphexa_tpu.gravity.traversal import gravity_tuning

        shape = gravity_tuning(self.state.n,
                               self._cfg.backend == "pallas",
                               telemetry=self.telemetry)
        if self._grav_knobs:
            shape.update(self._grav_knobs)
            if "super_factor" in self._grav_knobs:
                # keep the heuristic's invariant under overrides: the
                # two-level classification exists only as the pallas
                # bitmask compaction; sf=0 means the flat sort path
                shape["compaction"] = (
                    "bitmask" if shape["super_factor"] > 0
                    and shape["use_pallas"] else "sort")
        gcfg = estimate_gravity_caps(
            xs, ys, zs, ms, skeys, self.box, gtree, meta,
            GravityConfig(theta=self.theta, bucket_size=self.grav_bucket,
                          G=self.const.g,
                          m2p_cap_margin=self.m2p_cap_margin,
                          **shape),
            margin=margin,
            # sharded solves classify against the per-shard essential
            # node set (LET analog) instead of the full replicated tree
            let_shards=self._mesh.size if self._mesh is not None else 0,
        )
        self._gtree = gtree
        ewald = None
        if self.ewald_on:
            from sphexa_tpu.gravity.ewald import EwaldConfig

            ewald = EwaldConfig()
        # MAC-need sizing of the sparse gravity near-field exchange
        # (parallel/sizing.device_gravity_halo — the Warren-Salmon
        # essential-set volume): per-distance row caps for the leaf-
        # granular serve inside compute_gravity's shard path. Skipped at
        # grav_window=0 (full peer slabs, the pre-sizing lowering) and
        # on the GSPMD fallback, where no explicit serve runs.
        self._grav_cells = ()
        if (self._mesh is not None and self._mesh.size > 1
                and self.grav_window > 0 and self._halo_sizing_needed()):
            from itertools import product

            from sphexa_tpu.parallel.sizing import device_gravity_halo

            shifts = None
            if ewald is not None:
                # union the opened set over the replica-shell offsets:
                # a shifted target slab reaches wrap-around leaves the
                # base pass never opens
                r = ewald.num_replica_shells
                shells = np.array(
                    [sh for sh in product(range(-r, r + 1), repeat=3)],
                    np.float32,
                )
                shifts = jnp.asarray(shells) * self.box.lengths[0]
            self._grav_cells = device_gravity_halo(
                xs, ys, zs, ms, skeys, self.box, gtree, meta,
                theta=self.theta, P=self._mesh.size, shifts=shifts,
                margin=self._grav_halo_margin, quantum=self.grav_window,
            )
        self._cfg = dataclasses.replace(
            self._cfg, gravity=gcfg, grav_meta=meta, ewald=ewald
        )

    def _gravity_overflowed(self, diagnostics) -> bool:
        # with full-slab windows (grav_cells empty) the near field's
        # escape sentinel cannot fire — the run splitter sizes its slots
        # from the mesh (exchange._split_runs extra=max(8, P-1)) and
        # every remote row is in reach — so any p2p_max > p2p_cap is a
        # REAL interaction-list overflow and cap regrowth is the right
        # recovery. Under the MAC-sized sparse serve the sentinel CAN
        # fire (encoded as p2p_cap + 1, see _grav_window_blown): the
        # recovery is then a halo-margin regrowth, not a cap ratchet.
        if not self.gravity_on:
            return False
        g = self._cfg.gravity
        return (
            int(diagnostics["m2p_max"]) > g.m2p_cap
            or int(diagnostics["p2p_max"]) > g.p2p_cap
            or int(diagnostics["leaf_occ"]) > g.leaf_cap
            or int(diagnostics.get("c_max", 0)) > g.super_cap
            or (g.let_cap > 0
                and int(diagnostics.get("let_max", 0)) > g.let_cap)
        )

    def _grav_window_blown(self, diagnostics) -> bool:
        """The MAC-sized gravity serve's escape sentinel: exactly
        p2p_cap + 1 while the sparse caps are active. Same cap+1
        ambiguity contract as the SPH window sentinel (occupancy ==
        nbr.cap + 1): a real overflow landing exactly on cap+1 is
        handled identically — the margin regrowth converges to full
        slabs, where need <= S guarantees the sentinel cannot fire and
        a persisting overflow is then re-attributed to the caps."""
        if not self.gravity_on or not self._grav_cells:
            return False
        return int(diagnostics["p2p_max"]) == self._cfg.gravity.p2p_cap + 1

    def _config_still_valid(self, diagnostics) -> bool:
        nbr = self._cfg.nbr
        if int(diagnostics["occupancy"]) > nbr.cap:
            return False
        if self.prop_name == "nbody":
            return True
        # h_max is part of the step diagnostics (one batched transfer)
        h_max = float(diagnostics["h_max"])
        cell_edge = float(np.min(np.asarray(self.box.lengths))) / (1 << nbr.level)
        return 2.0 * h_max <= cell_edge

    @property
    def _use_lists(self) -> bool:
        # slot_cap == 0 also covers the fold-mode grids where lists are
        # structurally unavailable (make_propagator_config leaves it 0)
        return self._lists_eligible and self._cfg.list_slot_cap > 0

    # rebuild proactively below this remaining-skin fraction: the next
    # step would likely expire mid-flight and be discarded — rebuilding
    # now costs one sort+mark, not a wasted step
    _LIST_SLACK_REBUILD = 0.25

    def _maybe_rebuild_lists(self, diagnostics):
        if self._use_lists and (
            float(diagnostics.get("list_slack", 1.0))
            < self._LIST_SLACK_REBUILD
        ):
            self._rebuild_lists()

    def _rebuild_lists(self):
        """(Re)build the persistent lists: one jitted sort + mark pass.
        Replaces the per-step rebuild the reference does
        (find_neighbors.cuh) — between rebuilds the steady steps run on
        the frozen order. A slot-cap overflow re-sizes the static budget
        (recompile) and retries, like every other cap."""
        import jax as _jax

        from sphexa_tpu.propagator import rebuild_pair_lists

        self.telemetry.event("rebuild_lists", it=self.iteration)
        for _ in range(3):
            if not self._use_lists:
                # a reconfigure flipped the grid into fold mode or left
                # list_slot_cap == 0: fall back to per-step streaming
                # (self._lists stays None; steps run with lists=None)
                return
            aux = self.chem if self.prop_name == "std-cooling" else None
            with self.telemetry.annotate("sphexa:rebuild-lists"):
                state, box, lists, aux = rebuild_pair_lists(
                    self.state, self.box, self._cfg, aux
                )
                overflow = int(_jax.device_get(lists.overflow))
            if not overflow:
                self.state, self.box, self._lists = state, box, lists
                if aux is not None:
                    self.chem = aux
                return
            self._slot_margin *= 1.5
            self._configure(reason="list-slot")
        raise RuntimeError("pair-list slot cap failed to converge")

    def _step_fn(self, donated: bool = False):
        """Active step builder for the configured mode: the blockdt twin
        when ``dt_bins`` is set, the plain propagator otherwise."""
        if self._blockdt:
            table = (_PROPAGATORS_BLOCKDT_DONATED if donated
                     else _PROPAGATORS_BLOCKDT)
        else:
            table = _PROPAGATORS_DONATED if donated else _PROPAGATORS
        return table[self.prop_name]

    # -- main loop ----------------------------------------------------------
    def _drain(self, out):
        """CPU-mesh collective serialization: a program's scalar outputs
        can materialize before its trailing collectives retire, and a
        second program entering the per-thread queues mid-flight deadlocks
        the all-reduce rendezvous (observed: evrard-cooling CLI hang).
        Real TPU meshes execute programs FIFO per core — no drain there."""
        if self._mesh is not None and jax.default_backend() == "cpu":
            jax.block_until_ready(
                [a for a in jax.tree.leaves(out) if hasattr(a, "block_until_ready")]
            )
        return out

    def _checkified_step(self):
        """jit(checkify(step)) with the static configs closed over —
        rebuilt whenever the active config changes (reconfigure), cached
        otherwise so steady debug steps reuse one executable."""
        from jax.experimental import checkify

        key = (self.prop_name, self._cfg, self.turb_cfg, self.cooling_cfg)
        if self._checked_cache.get("key") != key:
            step_fn = self._step_fn()
            cfg = self._cfg
            if self.prop_name == "turb-ve":
                aux_cfg = self.turb_cfg
                base = lambda s, b, g, aux: step_fn(s, b, cfg, g, aux,
                                                    aux_cfg)
            elif self.prop_name == "std-cooling":
                aux_cfg = self.cooling_cfg
                base = lambda s, b, g, aux: step_fn(s, b, cfg, g, aux,
                                                    aux_cfg)
            elif self._blockdt:
                # the BlockDtState rides the aux slot; 4-tuple return
                base = lambda s, b, g, aux: step_fn(s, b, cfg, g, aux)
            else:
                base = lambda s, b, g, aux: step_fn(s, b, cfg, g)
            errors = checkify.float_checks | checkify.index_checks
            self._checked_cache = {
                "key": key,
                "fn": jax.jit(checkify.checkify(base, errors=errors)),
            }
        return self._checked_cache["fn"]

    @property
    def _aux_slot(self) -> Optional[str]:
        """The SimState aux slot the active propagator family carries
        (None for the plain 3-tuple families) — the driver-level mirror
        of propagator.STEP_AUX_SLOT, keyed on the configured mode."""
        if self.prop_name == "turb-ve":
            return "turb"
        if self.prop_name == "std-cooling":
            return "chem"
        if self._blockdt:
            return "bdt"
        return None

    @property
    def sim_state(self) -> SimState:
        """The driver's state attributes as the unified carry pytree
        (state.SimState): what every launch path consumes and returns."""
        return SimState(particles=self.state, box=self.box,
                        turb=self.turb_state, chem=self.chem,
                        bdt=self._bstate)

    def _set_sim_state(self, sim: SimState) -> None:
        """Write a SimState carry back onto the driver attributes —
        the single commit point for step outputs AND window rollbacks."""
        self.state = sim.particles
        self.box = sim.box
        self.turb_state = sim.turb
        self.chem = sim.chem
        self._bstate = sim.bdt

    def _launch_debug(self):
        """Sanitizer-mode launch: run the checkified step and stash the
        checkify Error for _step_checked to surface."""
        sim = self.sim_state
        slot = self._aux_slot
        aux = getattr(sim, slot) if slot else None
        self._check_err, out = self._checkified_step()(
            sim.particles, sim.box, self._gtree, aux
        )
        if slot:
            new_state, new_box, diagnostics, new_aux = out
        else:
            (new_state, new_box, diagnostics), new_aux = out, None
        return sim.with_slot(slot, new_aux, particles=new_state,
                             box=new_box), diagnostics

    def _compiled_cache_size(self) -> int:
        """Total jit-cache entries behind the ACTIVE launch path — the
        compile-watchdog's probe (the runtime analog of jaxaudit JXA102's
        cache-size-delta check, tests/test_audit.py). Pure host-side
        metadata: safe on the sync-free deferred happy path."""
        if self.debug_checks:
            fns = [self._checked_cache.get("fn")]
        elif self._mesh is not None:
            fns = [getattr(self, "_stepper", None)]
        else:
            fns = [self._step_fn(), self._step_fn(donated=True)]
        total = 0
        for f in fns:
            size = getattr(f, "_cache_size", None)
            if size is not None:
                total += size()
        return total

    def _launch_signature(self, donate_now: bool):
        """Hashable identity of the executable THIS launch needs — the
        per-run half of the compile watchdog. The jit caches are
        process-global, so the cache-size delta alone under-counts when
        another Simulation in the same process already compiled the
        identical config (the suite-order coupling between
        test_simulation_async and the telemetry retrace pin): this run
        still *traces differently than its own previous launches*, and
        in any fresh process it would compile. Baselining per Simulation
        on the signature set makes the watchdog count THIS run's
        (re)traces under any suite order."""
        if self.debug_checks:
            return ("debug", self.prop_name, self._cfg, self.turb_cfg,
                    self.cooling_cfg)
        if self._mesh is not None:
            info = self._halo_info or {}
            ginfo = self._grav_halo_info or {}
            return ("sharded", self.prop_name, self._cfg,
                    info.get("caps"), info.get("wmax"),
                    ginfo.get("caps"), ginfo.get("wmax"))
        return (self.prop_name, self._cfg, self.turb_cfg,
                self.cooling_cfg, donate_now,
                self._use_lists and self._lists is not None)

    def _launch(self, donate_ok: bool = False):
        """Instrumented dispatch: the compile watchdog samples the active
        jit cache around the launch — any growth means THIS launch traced
        (first compile or a silent retrace) and is recorded as a
        first-class ``retrace`` event instead of vanishing into an
        unexplained slow step. A launch whose executable signature this
        Simulation has never used counts too, even when the
        process-global cache was pre-warmed by another run (``warm``
        rides the event payload): the watchdog reports per-RUN compile
        behavior, independent of suite order."""
        c0 = self._compiled_cache_size()
        # debug_checks rebuilds the checkified jit INSIDE the launch on a
        # config change (new object, cache size resets to 1) — identity
        # drift is a from-scratch compile the size delta alone would miss
        fn0 = id(self._checked_cache.get("fn")) if self.debug_checks \
            else None
        donate_now = donate_ok and self._donate_active
        with self.telemetry.annotate("sphexa:launch"):
            out = self._launch_impl(donate_ok)
        delta = self._compiled_cache_size() - c0
        if (self.debug_checks and delta <= 0
                and id(self._checked_cache.get("fn")) != fn0):
            delta = 1
        sig = self._launch_signature(donate_now)
        warm = delta <= 0 and sig not in self._launched_sigs
        self._launched_sigs.add(sig)
        if delta > 0 or warm:
            n = max(delta, 1)
            self.telemetry.count("retraces", n)
            self.telemetry.event("retrace", it=self.iteration, delta=n,
                                 warm=warm)
        return out

    def _launch_impl(self, donate_ok: bool = False):
        """Dispatch one jitted step on the current state (no host sync
        beyond the CPU-mesh drain). Returns the unified carry:
        ``(new SimState, diagnostics)`` on every launch path.

        ``donate_ok``: the caller guarantees it will never need the
        CURRENT input state again (deferred happy-path windows pin a
        rollback copy first) — with donation active, launch the donated
        twin so the state is updated in place."""
        if self.debug_checks:
            return self._launch_debug()
        if self._mesh is not None:
            return self._drain(
                self._stepper.step_sim(self.sim_state, self._gtree)
            )
        donate_now = donate_ok and self._donate_active
        if donate_now:
            # freshly-built states alias leaves (build_state shares one
            # zeros array across temp_lo/du/du_m1; restarts may too) and
            # XLA refuses to donate the same buffer twice — copy the
            # duplicates once (step outputs are always distinct, so this
            # only ever pays on the first donated launch of a state)
            self.state = _dealias_leaves(self.state)
        step_fn = self._step_fn(donated=donate_now)
        kw = {}
        if self._use_lists:
            if self._lists is None:
                self._rebuild_lists()
            kw["lists"] = self._lists
        aux_cfg = (self.turb_cfg if self.prop_name == "turb-ve"
                   else self.cooling_cfg if self.prop_name == "std-cooling"
                   else None)
        return step_sim_state(step_fn, self.sim_state, self._cfg,
                              self._gtree, aux_cfg, **kw)

    def _apply(self, out):
        sim, _diagnostics = out
        self._set_sim_state(sim)

    @staticmethod
    def _scalar_view(diagnostics) -> Dict:
        """Scalars + the tiny (P,) per-shard telemetry arrays
        (SHARD_DIAG_KEYS), (B,) bin populations (BLOCKDT_DIAG_KEYS) and
        the (F, G, G)-sized snapshot grids (SNAP_DIAG_KEYS) —
        everything the flush boundary fetches in one batch. Per-particle
        arrays (keep_fields/keep_accels) stay on device."""
        from sphexa_tpu.propagator import (
            BLOCKDT_DIAG_KEYS, GRAV_SHARD_DIAG_KEYS, SHARD_DIAG_KEYS,
            SNAP_DIAG_KEYS)

        return {
            k: v for k, v in diagnostics.items()
            if getattr(v, "ndim", 0) == 0 or k in SHARD_DIAG_KEYS
            or k in BLOCKDT_DIAG_KEYS or k in GRAV_SHARD_DIAG_KEYS
            or k in SNAP_DIAG_KEYS
        }

    @classmethod
    def _fetch_scalars(cls, diagnostics) -> Dict:
        """ONE batched device->host transfer for all scalar diagnostics
        (separate float()/int() conversions each pay a full round trip,
        which dominates on remote-attached TPUs)."""
        return jax.device_get(cls._scalar_view(diagnostics))

    def _overflowed(self, diagnostics) -> bool:
        return (
            int(diagnostics["occupancy"]) > self._cfg.nbr.cap
            or self._gravity_overflowed(diagnostics)
            or not self._lists_fresh(diagnostics)
        )

    def _emit_distributed(self, diagnostics, steps: int) -> None:
        """Schema-v2 distributed telemetry at the fetch boundary: one
        ``shard_load`` + one ``exchange`` event per checked step / clean
        window, plus the imbalance watchdog. ``diagnostics`` is the
        already-FETCHED dict — everything here is host arithmetic on
        (P,) numpy arrays, so the deferred-window zero-sync contract is
        untouched (pinned by tests/test_telemetry.py)."""
        if self._mesh is None:
            return
        tel = self.telemetry
        P = self._mesh.size
        particles = [self.state.n // P] * P  # equal SFC slabs by design

        def arr(key):
            v = diagnostics.get(key)
            return None if v is None else np.asarray(v)

        work, rows, occ = arr("shard_work"), arr("shard_rows"), \
            arr("shard_occ")
        # per-shard trips reaching this point are always zero — a tripped
        # sentinel folds into occupancy==cap+1 and the step/window is
        # discarded before any emit; halo_trips is counted at the ONE
        # place that sees the sentinel (_reconfigure_after_overflow)
        load = {"it": self.iteration, "steps": steps,
                "particles": particles, "stage": "sph"}
        if work is not None:
            load["work"] = [float(w) for w in work]
        tel.event("shard_load", **load)
        info = self._halo_info or {}
        if rows is not None:
            tel.event(
                "exchange", it=self.iteration, steps=steps,
                mode=info.get("mode", "?"),
                shipped_rows=int(info.get("shipped_rows", 0)),
                rows=[int(r) for r in rows],
                occ=None if occ is None else [round(float(o), 4)
                                              for o in occ],
                bytes_per_step=int(info.get("bytes_per_step", 0)),
                trips=int(tel.counters.get("halo_trips", 0)),
                stage="sph",
            )
        # schema-v7: the gravity near field gets its own exchange event
        # when the MAC-sized sparse serve is active (gshard_* diagnostics
        # present) — same fetch, zero added syncs
        grows, gocc = arr("gshard_rows"), arr("gshard_occ")
        ginfo = self._grav_halo_info or {}
        if grows is not None:
            tel.event(
                "exchange", it=self.iteration, steps=steps,
                mode=ginfo.get("mode", "?"),
                shipped_rows=int(ginfo.get("shipped_rows", 0)),
                rows=[int(r) for r in grows],
                occ=None if gocc is None else [round(float(o), 4)
                                               for o in gocc],
                bytes_per_step=int(ginfo.get("bytes_per_step", 0)),
                trips=int(tel.counters.get("grav_halo_trips", 0)),
                stage="gravity",
            )
        # the watchdog: max/mean per metric against the configured ratio
        for metric, a in (("work", work), ("halo_rows", rows),
                          ("halo_occ", occ)):
            if a is None or a.size == 0:
                continue
            mean = float(a.mean())
            if mean <= 0.0:
                continue
            ratio = float(a.max()) / mean
            if ratio >= self._imbalance_ratio:
                tel.count("imbalances")
                tel.event("imbalance", it=self.iteration, metric=metric,
                          ratio=round(ratio, 4),
                          threshold=self._imbalance_ratio)

    def _emit_memory(self, point: str) -> None:
        """Per-device HBM snapshot event (telemetry/memory.py): host
        allocator metadata only, never a device sync. ``post-compile``
        fires once (after the first fetched step/window — executable +
        workspace resident); ``flush`` at every window flush."""
        if point == "post-compile":
            if self._mem_post_compile:
                return
            self._mem_post_compile = True
        devices = None
        if self._mesh is not None:
            devices = list(self._mesh.devices.flat)
        emit_memory_event(self.telemetry, point, devices=devices,
                          it=self.iteration)

    def drain_science(self) -> list:
        """Per-step science rows (constants.txt material: it, t, dt,
        energies, momenta, the case extra) accumulated since the last
        drain — one dict per VERIFIED step, in iteration order, built
        from the already-fetched ledger scalars (no device access).
        Rows appear only at check/flush boundaries, so under deferral a
        whole window's rows land at once; rolled-back windows never
        produce rows (their replay does). Requires
        ``Simulation(science_rows=True)``."""
        rows, self._science = self._science, []
        return rows

    def _emit_science(self, fetched, its) -> None:
        """Schema-v3 physics observability at the fetch boundary: one
        ``physics`` + one ``numerics`` event per checked step / clean
        window (per-step parallel lists, like the v2 shard events), the
        science rows for drain_science(), and the two watchdogs.
        ``fetched`` holds the already-FETCHED per-step diagnostics —
        host arithmetic only, the deferred-window zero-sync contract is
        untouched (pinned by tests/test_telemetry.py)."""
        from sphexa_tpu.propagator import DT_LIMITERS

        steps = [(it, d) for it, d in zip(its, fetched)
                 if "obs_etot" in d]
        if not steps:
            return
        tel = self.telemetry
        rows = []
        for it, d in steps:
            row = {"it": int(it), "t": float(d["obs_ttot"]),
                   "dt": float(d["dt"]), "etot": float(d["obs_etot"]),
                   "ecin": float(d["obs_ecin"]),
                   "eint": float(d["obs_eint"]),
                   "egrav": float(d["obs_egrav"]),
                   "linmom": float(d["obs_linmom"]),
                   "angmom": float(d["obs_angmom"])}
            if "obs_extra" in d:
                row["extra"] = float(d["obs_extra"])
            rows.append(row)
        if self._collect_science:
            self._science.extend(rows)
        if self._etot0 is None and np.isfinite(rows[0]["etot"]):
            self._etot0 = rows[0]["etot"]
        payload = {k: [r[k] for r in rows]
                   for k in ("dt", "etot", "ecin", "eint", "egrav",
                             "linmom", "angmom")}
        # simulated time travels as t_sim: the envelope already owns "t"
        # (epoch seconds), and a payload key must never shadow it
        payload["t_sim"] = [r["t"] for r in rows]
        if all("extra" in r for r in rows):
            payload["extra"] = [r["extra"] for r in rows]
        tel.event("physics", it=rows[-1]["it"], steps=len(rows),
                  its=[r["it"] for r in rows], **payload)

        # numerics: limiter histogram + window-aggregate health scalars
        lim: Dict[str, int] = {}
        bad = {"rho": 0, "h": 0, "du": 0}
        first_bad = None
        for it, d in steps:
            if "dt_limiter" in d:
                name = DT_LIMITERS[int(d["dt_limiter"])]
                lim[name] = lim.get(name, 0) + 1
            step_bad = {f: int(d.get(f"n_bad_{f}", 0)) for f in bad}
            for f in bad:
                bad[f] = max(bad[f], step_bad[f])
            if first_bad is None and sum(step_bad.values()) > 0:
                first_bad = (it, step_bad)
        ds = [d for _, d in steps]

        def ext(key, fn):
            # aggregate over the window's FINITE samples only: Python
            # min/max NaN-propagation is order-dependent (a NaN would be
            # sticky or masked depending on which step produced it) —
            # corruption is reported by the nonfinite counts/field_health
            # event, the extrema stay deterministic
            arr = np.asarray([float(d.get(key, np.nan)) for d in ds])
            finite = arr[np.isfinite(arr)]
            return float(fn(finite)) if finite.size else float("nan")

        agg = {
            "nc_clip": max(int(d.get("n_nc_clip", 0)) for d in ds),
            "h_sat": max(int(d.get("n_h_sat", 0)) for d in ds),
            "rho_min": ext("rho_min", np.min),
            "rho_max": ext("rho_max", np.max),
            "h_min": ext("h_min", np.min),
            "h_max": ext("h_max", np.max),
            "du_max": ext("du_max", np.max),
        }
        tel.event("numerics", it=rows[-1]["it"], steps=len(rows),
                  limiter=lim, nonfinite=bad, **agg)

        # conservation-drift watchdog: relative total-energy excursion
        # vs the run's first verified step, evaluated over EVERY step of
        # the window (a mid-window spike that relaxes by the flush must
        # still fire — the offline science --budget gate checks the full
        # series, the runtime watchdog must agree); energy_drift exposes
        # the latest verified value (the bench stamp)
        if self._etot0 is not None:
            denom = abs(self._etot0) or 1.0
            drifts = [abs(r["etot"] - self._etot0) / denom for r in rows]
            self.energy_drift = drifts[-1]
            worst = max(range(len(rows)), key=lambda i: (
                drifts[i] if np.isfinite(drifts[i]) else -1.0))
            if (self._drift_budget is not None
                    and drifts[worst] > self._drift_budget):
                tel.count("drifts")
                tel.event("drift", it=rows[worst]["it"],
                          drift=drifts[worst],
                          budget=self._drift_budget, etot0=self._etot0,
                          etot=rows[worst]["etot"])
        # field-health watchdog: any nonfinite rho/h/du is a first-class
        # event naming the first bad step; --debug-checks localizes it
        if first_bad is not None:
            it_bad, step_bad = first_bad
            tel.count("field_health")
            tel.event("field_health", it=it_bad,
                      nonfinite=sum(step_bad.values()), fields=step_bad,
                      hint="re-run with --debug-checks to localize")

    def _emit_blockdt(self, fetched, its) -> None:
        """Schema-v6 block-timestep telemetry at the fetch boundary: one
        ``dt_bins`` event per checked step / clean window, built from the
        already-FETCHED per-substep bdt_* diagnostics — host arithmetic
        only, the deferred-window zero-sync contract is untouched.  The
        updates/updates_full counters double as the chip-free complexity
        proxy (docs/NEXT.md): every substep advances sim-time by dt_min
        under BOTH schemes, so the global-dt cost of the same span is
        exactly n updates per substep."""
        steps = [(it, d) for it, d in zip(its, fetched)
                 if "bdt_active" in d]
        if not steps:
            return
        ds = [d for _, d in steps]
        n = self.state.n
        updates = sum(int(d["bdt_active"]) for d in ds)
        full = n * len(ds)
        resorts = sum(int(d["bdt_resort"]) for d in ds)
        self.bdt_updates += updates
        self.bdt_updates_full += full
        self.bdt_resorts += resorts
        self.bdt_keeps += len(ds) - resorts
        self.telemetry.event(
            "dt_bins", it=steps[-1][0], steps=len(ds),
            pop=[int(v) for v in np.asarray(ds[-1]["bdt_pop"])],
            updates=updates, updates_full=full,
            saved=round(1.0 - updates / full, 6) if full else 0.0,
            resorts=resorts, keeps=len(ds) - resorts,
            drift_max=max(int(d["bdt_drift"]) for d in ds),
            work=sum(float(d["bdt_work"]) for d in ds),
        )

    def drain_snapshots(self) -> list:
        """(iteration, npz_path) pairs for snapshot frames written since
        the last drain, in iteration order — the thin interface the
        --insitu renderer consumes (host file IO only, no device
        access). Frames appear only at check/flush boundaries, so under
        deferral a whole window's due frames land at once."""
        frames, self._snap_frames = self._snap_frames, []
        return frames

    def _emit_snapshot(self, fetched, its) -> None:
        """Schema-v8 live science surface at the fetch boundary: for
        every due step (``it % snap_every == 0``) write one .npz frame
        into the ``snapshots/`` ring (grid + meta; capped at snap_keep)
        and emit one ``snapshot`` event (grid meta + extrema inline, the
        frame path as the pointer). ``fetched`` holds the
        already-FETCHED diagnostics — host numpy + file IO only, the
        deferred-window zero-sync contract is untouched (pinned by
        tests/test_telemetry.py's snapshot guard)."""
        if self._snap_spec is None:
            return
        spec = self._snap_spec
        steps = [(it, d) for it, d in zip(its, fetched)
                 if "snap_grid" in d and it % self._snap_every == 0]
        if not steps:
            return
        tel = self.telemetry
        # box extents travel with every frame so a jax-free renderer can
        # label axes; fetched once per boundary (the boundary is already
        # a sync point)
        lo = np.asarray(jax.device_get(self.box.lo), np.float64)
        lengths = np.asarray(jax.device_get(self.box.lengths), np.float64)
        for it, d in steps:
            grid = np.asarray(d["snap_grid"])
            vmin = [float(v) for v in np.asarray(d["snap_min"])]
            vmax = [float(v) for v in np.asarray(d["snap_max"])]
            path = None
            if self._snap_dir:
                os.makedirs(self._snap_dir, exist_ok=True)
                path = os.path.join(self._snap_dir,
                                    f"snap_{int(it):06d}.npz")
                payload = {
                    "grid": grid, "it": np.int64(it),
                    "fields": np.asarray(spec.fields),
                    "axis": np.int64(spec.axis),
                    "reduce": np.asarray(spec.reduce),
                    "volume": np.bool_(spec.volume),
                    "lo": lo, "lengths": lengths,
                    "vmin": np.asarray(vmin), "vmax": np.asarray(vmax),
                }
                if "snap_pts" in d:
                    payload["pts"] = np.asarray(d["snap_pts"])
                np.savez(path, **payload)
                self._snap_frames.append((int(it), path))
                self._snap_ring.append(path)
                while self._snap_keep > 0 \
                        and len(self._snap_ring) > self._snap_keep:
                    old = self._snap_ring.pop(0)
                    try:
                        os.remove(old)
                    except OSError:
                        pass
            tel.event("snapshot", it=int(it), fields=list(spec.fields),
                      grid=spec.grid, axis=spec.axis, reduce=spec.reduce,
                      volume=spec.volume, vmin=vmin, vmax=vmax,
                      path=path)

    @staticmethod
    def _lists_fresh(diagnostics) -> bool:
        """False when the step ran on EXPIRED lists (drift/growth ate
        the Verlet skin before launch): its pair sums may have missed
        neighbors, so the step must be discarded and replayed on fresh
        lists — the same discard semantics as a cap overflow, but the
        recovery is a cheap list rebuild, not a static re-size."""
        return int(diagnostics.get("list_ok", 1)) != 0

    def _reconfigure_after_overflow(self, diagnostics, grav_margin: float):
        occ = int(diagnostics["occupancy"])
        if self._mesh is not None and occ == self._cfg.nbr.cap + 1:
            # the cap+1 SENTINEL (not a real occupancy) is how escaped
            # halo runs surface under sharding; grow the window margin so
            # the rebuild converges — but never for unrelated gravity/
            # cell-cap overflows, which would inflate comm volume for the
            # rest of the run
            self._halo_margin *= 1.5
            # every sentinel trip is telemetry: the exchange events stamp
            # the cumulative count so a drift-heavy run's resize churn is
            # visible in the record, not just in wall time
            self.telemetry.count("halo_trips")
        # occ == cap+1 is the window-blowout SENTINEL, not a real
        # occupancy — feeding it back as min_cap would ratchet the cap
        # (and force a fresh compile) on every blowout; a plain
        # re-estimate resizes the window instead
        window_blown = occ == self._cfg.nbr.cap + 1
        nbr_over = occ > self._cfg.nbr.cap
        self._configure(
            min_cap=0 if window_blown or not nbr_over else occ,
            grav_margin=grav_margin, reason="overflow",
        )

    def _step_checked(self) -> Dict[str, float]:
        """Advance one step synchronously; a step whose own diagnostics
        reveal a cell-cap overflow (truncated neighbor candidates) is
        discarded and re-run under a freshly sized config — overflow must
        never corrupt state."""
        reconfigured = False
        grav_margin = 1.5
        grav_blown_once = False
        t0 = time.perf_counter()
        for _attempt in range(4):
            out = self._launch()
            diagnostics = {**out[1], **self._fetch_scalars(out[1])}
            if not self._overflowed(diagnostics):
                break
            if not self._lists_fresh(diagnostics):
                # stale persistent lists: discard + rebuild (no re-size)
                self._rebuild_lists()
                continue
            if self._grav_window_blown(diagnostics):
                # escaped sparse near-field runs (the cap+1 sentinel):
                # grow the MAC-need margin so the re-size converges —
                # NOT the interaction-list caps, which would recompile a
                # bigger engine for a comm problem. A second trip within
                # one step jumps straight to the full-slab ceiling
                # (caps == S, where the sentinel provably cannot fire)
                # so convergence fits the 4-attempt budget.
                self._grav_halo_margin = (
                    1e9 if grav_blown_once else self._grav_halo_margin * 1.5
                )
                grav_blown_once = True
                self.telemetry.count("grav_halo_trips")
            elif self._gravity_overflowed(diagnostics):
                grav_margin *= 1.5
            self._reconfigure_after_overflow(diagnostics, grav_margin)
            reconfigured = True
        else:
            raise RuntimeError(
                "neighbor/gravity caps failed to converge in 4 attempts"
            )
        # launch -> batched scalar fetch is the step's device span (the
        # fetch drains the dispatched program); retries charge here too,
        # exactly like a recompile charges the reference's Timer
        wall = time.perf_counter() - t0
        self._apply(out)
        self.iteration += 1
        if not self._config_still_valid(diagnostics):
            # config check FIRST: _configure() drops self._lists, so a
            # proactive rebuild before it would be wasted work
            self._configure(reason="stale-grid")
            reconfigured = True
        else:
            self._maybe_rebuild_lists(diagnostics)
        result = {
            k: np.asarray(v) if getattr(v, "ndim", 0) else float(v)
            for k, v in diagnostics.items()
        }
        result["reconfigured"] = float(reconfigured)
        self.telemetry.timing("step", wall)
        self.telemetry.event(
            "step", it=self.iteration, wall_s=round(wall, 6),
            dt=float(result["dt"]) if "dt" in result else None,
            reconfigured=bool(reconfigured),
        )
        self._emit_distributed(diagnostics, steps=1)
        self._emit_science([diagnostics], [self.iteration])
        self._emit_blockdt([diagnostics], [self.iteration])
        self._emit_snapshot([diagnostics], [self.iteration])
        self._emit_memory("post-compile")
        if self.debug_checks:
            # first triggered checkify predicate of THIS step ("" = all
            # NaN/Inf/OOB checks passed); .get() syncs, which is the
            # sanitizer's contract — locate the failing step exactly
            msg = self._check_err.get() if self._check_err is not None \
                else None
            result["check_error"] = msg or ""
        self._last_diag = result
        return result

    def step(self) -> Dict[str, float]:
        """Advance one step.

        With ``check_every == 1`` (default) the step is checked
        synchronously. With ``check_every > 1`` steps are launched with NO
        device->host sync on the happy path; every ``check_every`` steps
        the accumulated diagnostics are fetched in one transfer and, if an
        overflow is found, the simulation rolls back to the last verified
        state and replays the lost steps under a fresh config (the same
        discard-and-retry semantics, checked late). Diagnostics returned
        between check boundaries are the last verified ones, marked
        ``{"deferred": 1.0}``.
        """
        if self.check_every <= 1:
            return self._step_checked()
        if not self._pending:
            # host stamp of the window's first launch: flush() attributes
            # the whole window's device time against it — the only
            # per-step timing the sync-free happy path can honestly give
            self._window_t0 = time.perf_counter()
            # only the WINDOW-START state is pinned for rollback (one
            # extra state, not check_every of them — 68 MB/state at 100^3).
            # With donation active the window's first launch CONSUMES
            # self.state, so the pin must be a real copy — one copy per
            # window, amortized over check_every donated steps
            pin = self.state
            if self._donate_active:
                pin = jax.tree.map(jnp.copy, self.state)
            # aux slots (turb/chem/_bstate) are never donated, so the
            # carry pin holds them by reference around the copied slab
            self._window_prior = (
                dataclasses.replace(self.sim_state, particles=pin),
                self.iteration,
            )
        out = self._launch(donate_ok=True)
        self._apply(out)
        self.iteration += 1
        # happy-path telemetry is launch-count only: diagnostics stay on
        # device, timestamps are host-side — zero added transfers
        self.telemetry.event("launch", it=self.iteration)
        self._pending.append(out[1])
        if len(self._pending) >= self.check_every:
            return self.flush()
        return {**self._last_diag, "deferred": 1.0}

    def flush(self) -> Dict[str, float]:
        """Drain the deferred-check queue: one batched fetch of every
        pending step's scalar diagnostics; if any step overflowed, roll
        back to the window-start state and replay the whole window through
        the synchronous checked path."""
        if not self._pending:
            return self._last_diag
        pending, self._pending = self._pending, []
        prior, self._window_prior = self._window_prior, None
        t0, self._window_t0 = self._window_t0, None
        with self.telemetry.annotate("sphexa:flush"):
            fetched = jax.device_get([self._scalar_view(d) for d in pending])
        # the batched fetch drains every launched program, so this host
        # span IS the window's device time; per-step attribution is its
        # mean (what "step time" means under deferral, docs/OBSERVABILITY)
        window_wall = time.perf_counter() - t0 if t0 is not None else 0.0
        bad = next(
            (i for i, scal in enumerate(fetched) if self._overflowed(scal)),
            None,
        )
        if bad is None:
            self.telemetry.timing("step", window_wall)
            self.telemetry.event(
                "window", it=self.iteration, steps=len(pending),
                wall_s=round(window_wall, 6),
                per_step_s=round(window_wall / len(pending), 6),
            )
            # distributed telemetry rides the SAME fetch: per-shard
            # load/exchange events + HBM snapshot, at window granularity
            self._emit_distributed(fetched[-1], steps=len(pending))
            # science ledger rides it too: one physics/numerics event +
            # a constants row per step of the window (every step keeps
            # its row even under --check-every N)
            win_its = list(range(self.iteration - len(pending) + 1,
                                 self.iteration + 1))
            self._emit_science(fetched, win_its)
            self._emit_blockdt(fetched, win_its)
            self._emit_snapshot(fetched, win_its)
            self._emit_memory("post-compile")
            self._emit_memory("flush")
            diagnostics = {**pending[-1], **fetched[-1]}
            result = {
                k: np.asarray(v) if getattr(v, "ndim", 0) else float(v)
                for k, v in diagnostics.items()
            }
            result["reconfigured"] = 0.0
            self._last_diag = result
            if not self._config_still_valid(fetched[-1]):
                self._configure(reason="stale-grid")
                self._last_diag["reconfigured"] = 1.0
            else:
                self._maybe_rebuild_lists(fetched[-1])
            return self._last_diag
        # roll back to the window start and replay every window step
        diag_bad = fetched[bad]
        expiry_only = (
            not self._lists_fresh(diag_bad)
            and int(diag_bad["occupancy"]) <= self._cfg.nbr.cap
            and not self._gravity_overflowed(diag_bad)
        )
        self.telemetry.count("rollbacks")
        self.telemetry.event(
            "rollback", it=self.iteration, to_it=prior[1],
            steps=len(pending), bad_index=bad,
            reason="list-expiry" if expiry_only else "overflow",
        )
        self._set_sim_state(prior[0])
        self.iteration = prior[1]
        if expiry_only:
            # expiry only: fresh lists on the rolled-back state suffice
            self._rebuild_lists()
        else:
            grav_margin = 1.5
            if self._grav_window_blown(diag_bad):
                # escaped sparse gravity runs (cap+1 sentinel): regrow
                # the MAC-need margin, not the interaction-list caps.
                # The replay below goes through _step_checked, which
                # escalates to the full-slab ceiling on a repeat trip.
                self._grav_halo_margin *= 1.5
                self.telemetry.count("grav_halo_trips")
            elif self._gravity_overflowed(diag_bad):
                grav_margin = 1.5 * 1.5
            self._reconfigure_after_overflow(diag_bad, grav_margin)
        for _ in range(len(pending)):
            result = self._step_checked()
        self.telemetry.event("replay", it=self.iteration, steps=len(pending))
        result["reconfigured"] = 1.0
        self._last_diag = result
        return result

    def run(self, num_steps: int, log_every: int = 0, printer=print):
        # per-iteration report routes through the telemetry console sink
        # when one is attached (``printer`` stays the fallback); scalar
        # keys are propagator-dependent beyond STEP_DIAG_KEYS, so missing
        # ones render as nan instead of KeyError-ing the whole run
        emit = self.telemetry.console_printer(printer)
        nan = float("nan")
        for _ in range(num_steps):
            d = self.step()
            if log_every and self.iteration % log_every == 0:
                if d.get("deferred"):
                    emit(f"it {self.iteration:5d}  (deferred check)")
                else:
                    emit(
                        f"it {self.iteration:5d}  t={float(self.state.ttot):.6g}  "
                        f"dt={float(d.get('dt', nan)):.4g}  "
                        f"nc~{float(d.get('nc_mean', nan)):.1f}  "
                        f"rho_max={float(d.get('rho_max', nan)):.4g}"
                    )
        # the final partial window must be verified before the state is
        # handed back — overflow must never corrupt state
        self.flush()
        return self.state
