"""Neighbor search: SFC-sorted cell-list gather with bounded candidate sets.

TPU-native replacement for BOTH of the reference's neighbor paths — the CPU
per-particle octree traversal (cstone/findneighbors.hpp:96-172) and the GPU
warp-centric breadth-first traversal (cstone/traversal/find_neighbors.cuh):
instead of tree walks, particles are sorted by SFC key, a uniform cell grid
at a chosen octree level is addressed through searchsorted on the key
array, and each particle gathers a fixed-size masked candidate set from its
27-cell stencil. Everything is static-shape, fully vectorized, and fuses
into a handful of XLA gather/reduce kernels.
"""

from sphexa_tpu.neighbors.cell_list import (
    NeighborConfig,
    choose_grid_level,
    estimate_cell_cap,
    find_neighbors,
)

__all__ = [
    "NeighborConfig",
    "choose_grid_level",
    "estimate_cell_cap",
    "find_neighbors",
]
