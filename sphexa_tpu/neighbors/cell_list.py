"""Cell-list neighbor search over SFC-sorted particle arrays.

Design (SURVEY.md §7 'cell-list/gather formulation', reshaped for TPU
memory bandwidth like the reference's warp-centric traversal,
cstone/traversal/find_neighbors.cuh TravConfig):

1. Particles arrive sorted by SFC key. A uniform grid at octree level
   ``L`` is implied by the key hierarchy: the level-``L`` cell of a
   particle is the top ``3L`` bits of its key — cell membership ranges in
   the sorted array are two ``searchsorted`` calls, no bucket structure.
2. Particles are processed in *target groups* of ``group`` SFC-consecutive
   particles (the analog of the reference's 64-particle GPU targets,
   find_neighbors.cuh:45-82). Each group computes its bounding box once,
   expands it by the search radius, and gathers ONE shared candidate set
   from the static ``window^3`` cell block covering it — amortizing the
   range lookups and candidate gathers over the whole group instead of
   paying 27 gathers per particle.
3. Candidates are filtered by ``|r_ij| < 2 h_i``; the first ``ngmax`` hits
   per particle are compacted with a masked cumsum + scatter (matching the
   reference's first-found truncation semantics, findneighbors.hpp:96-172
   — no distance sort).

Correctness guards (all surfaced as diagnostics, re-checked by the
caller): cell occupancy <= cap, and the window block must cover every
group's search extent (``window_ok``); either failing triggers a
reconfiguration exactly like the reference's traversal-stack overflow.
"""

import dataclasses
import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from sphexa_tpu.dtypes import KEY_BITS, KEY_DTYPE
from sphexa_tpu.sfc.box import Box, apply_pbc_xyz
from sphexa_tpu.sfc.hilbert import hilbert_encode
from sphexa_tpu.sfc.keys import coords_to_igrid
from sphexa_tpu.sfc.morton import morton_encode
from sphexa_tpu.util.phases import named_phase


@dataclasses.dataclass(frozen=True)
class NeighborConfig:
    """Static configuration of the neighbor search (hashable, jit-safe)."""

    level: int  # octree level of the cell grid
    cap: int  # max particles gathered per cell
    ngmax: int = 150  # max neighbors kept per particle (reference ngmax);
    # NOTE: only the list-building XLA path truncates at ngmax (the
    # reference's memory-bound semantics, findneighbors.hpp) — the pallas
    # engine sums over ALL neighbors within 2h (physically the more
    # accurate behavior; lists never materialize there)
    block: int = 2048  # particles per processing chunk (memory bound)
    curve: str = "hilbert"
    group: int = 64  # particles per target group (TravConfig targetSize)
    window: int = 4  # cells per dimension of the group candidate block
    # pallas engine: merge SFC-adjacent candidate cells into one streamed
    # run of at most run_cap slots, bridging key-space gaps up to ``gap``
    # particles (gap particles are legitimate extra candidates — masked by
    # the distance test, or genuine neighbors counted once). 0 disables.
    run_cap: int = 0
    gap: int = 0
    # chunks per engine inner-loop trip (pair math on (G, 128*chunk_pair)
    # tiles). 0 = default 1, overridable by SPHEXA_CHUNK_PAIR at engine
    # build. Measured SLOWER at 2 on v5e (docs/NEXT.md); kept for future
    # hardware.
    chunk_pair: int = 0

    @property
    def num_candidates(self) -> int:
        return self.window**3 * self.cap

    @property
    def dma_cap(self) -> int:
        """Largest candidate span one kernel DMA must cover (cells when
        merging is off, merged runs when on). SINGLE source of truth for
        the engine's transfer shape and the packed-buffer tail pad."""
        return max(self.cap, self.run_cap)


def choose_grid_level(box_lengths, h_max: float) -> int:
    """Deepest grid level whose cell edge still covers the 2h search radius.

    With cell edge >= 2*h_max, a group window of
    ceil(extent/edge) + 2 cells per dimension covers every interaction
    sphere of the group.
    """
    min_extent = float(np.min(np.asarray(box_lengths)))
    if h_max <= 0:
        return KEY_BITS
    level = int(np.floor(np.log2(min_extent / (2.0 * h_max))))
    return max(1, min(KEY_BITS, level))


def pad_cap(occ: int, margin: float = 1.3, quantum: int = 8) -> int:
    """Pad an observed max cell occupancy into a static cap: the margin
    absorbs particle motion between reconfigurations; the quantum rounds up
    so small occupancy drifts do not change the static cap (and thus do
    not recompile). SINGLE source of truth for the sizing constants."""
    return max(quantum, int(np.ceil(occ * margin / quantum) * quantum))


def window_cells(ext: float, radius: float, edge: float, ncell: int,
                 margin_cells: int = 1) -> int:
    """Cells needed along one dimension to cover a group extent + search
    radius, clamped to the grid (whole-grid coverage always suffices)."""
    return min(int(np.ceil((ext + radius) / edge)) + 1 + margin_cells, ncell)


def estimate_cell_cap(keys, level: int, margin: float = 1.3, quantum: int = 8) -> int:
    """Max level-``level`` cell occupancy of ``keys``, padded with slack
    (host-side helper run at (re)configuration time)."""
    shift = 3 * (KEY_BITS - level)
    cells = np.asarray(keys, dtype=np.uint64) >> np.uint64(shift)
    occ = int(np.bincount(cells.astype(np.int64)).max()) if len(cells) else 1
    return pad_cap(occ, margin, quantum)


def estimate_group_window(
    x, y, z, h, box_lengths, level: int, group: int, margin_cells: int = 1
) -> int:
    """Cells per dimension needed to cover any group's search extent.

    Host-side sizing: per dimension, ceil((max group extent + 2*2h)/edge_d)
    + 1 (+margin for drift), clamped to the grid size — a window spanning
    the whole grid always covers (essential for thin-slab boxes whose
    per-dim edges differ wildly). The window_ok diagnostic remains the
    runtime guard.
    """
    ncell = 1 << level
    edges = np.asarray(box_lengths, np.float64) / ncell  # (3,)
    n = len(np.asarray(x))
    ng = -(-n // group)
    pad = ng * group - n
    radius = 2.0 * 2.0 * float(np.max(np.asarray(h)))
    need = 1
    for a, edge in zip((x, y, z), edges):
        a = np.asarray(a)
        if pad:
            a = np.concatenate([a, np.repeat(a[-1], pad)])
        g = a.reshape(ng, group)
        ext = float((g.max(axis=1) - g.min(axis=1)).max())
        need = max(need, window_cells(ext, radius, edge, ncell, margin_cells))
    return need


@functools.lru_cache(maxsize=None)
def _window_offsets(window: int) -> np.ndarray:
    """(window^3, 3) integer offsets of the group candidate cell block."""
    r = np.arange(window, dtype=np.int32)
    return np.stack(np.meshgrid(r, r, r, indexing="ij"), axis=-1).reshape(-1, 3)


@functools.partial(jax.jit, static_argnames=("cfg",))
@named_phase("neighbors")
def find_neighbors(
    x, y, z, h, sorted_keys, box: Box, cfg: NeighborConfig
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Neighbor lists for all particles.

    Arguments are the SFC-sorted particle arrays and their keys. Returns:

    - ``nidx`` (N, ngmax) int32: neighbor indices, first-found order;
      invalid slots hold the particle's own index (safe to gather, must be
      masked);
    - ``nmask`` (N, ngmax) bool: validity of each slot;
    - ``nc`` (N,) int32: true neighbor count within 2h (excluding self, may
      exceed ngmax — used by the smoothing-length update like the
      reference's nc field);
    - ``occupancy`` () int32: an overflow diagnostic encoding BOTH guards:
      the densest cell seen, or cap+1 if some group's search extent
      outgrew the window block. If > cfg.cap the config must be re-sized
      and the search re-run.
    """
    n = x.shape[0]
    level = cfg.level
    shift = KEY_DTYPE(3 * (KEY_BITS - level))
    ncell = 1 << level
    encode = hilbert_encode if cfg.curve == "hilbert" else morton_encode
    edge = box.lengths / ncell  # (3,)
    periodic = box.periodic_mask

    g = cfg.group
    num_groups = -(-n // g)
    idx_groups = jnp.arange(num_groups * g, dtype=jnp.int32).reshape(num_groups, g)
    offsets = jnp.asarray(_window_offsets(cfg.window))  # (W3, 3)

    def process_group(idx):
        idx = jnp.minimum(idx, n - 1)  # padded tail re-processes the last row
        gx, gy, gz, gh = x[idx], y[idx], z[idx], h[idx]

        lo = jnp.stack([jnp.min(gx), jnp.min(gy), jnp.min(gz)])
        hi = jnp.stack([jnp.max(gx), jnp.max(gy), jnp.max(gz)])
        radius = 2.0 * jnp.max(gh)
        # first cell of the window block: floor((lo - 2h) / edge)
        box_lo = jnp.stack([box.lo[0], box.lo[1], box.lo[2]])
        base = jnp.floor((lo - radius - box_lo) / edge).astype(jnp.int32)
        # window must cover hi + radius: last needed cell index
        need = jnp.floor((hi + radius - box_lo) / edge).astype(jnp.int32)
        # open dims: slide the window inside the existing grid (coverage
        # is never lost — cells outside [0, ncell) don't exist); a window
        # spanning the whole grid always covers
        base = jnp.where(
            periodic, base, jnp.clip(base, 0, max(0, ncell - cfg.window))
        )
        need_eff = jnp.where(periodic, need, jnp.minimum(need, ncell - 1))
        window_ok = jnp.all(
            (need_eff - base + 1 <= cfg.window) | (cfg.window >= ncell)
        )

        cells = base[None, :] + offsets  # (W3, 3)
        wrapped = jnp.mod(cells, ncell)
        in_range = (cells >= 0) & (cells < ncell)
        # periodic dims wrap but must not alias (offsets beyond the grid
        # revisit the same cells — drop them); open dims clip-and-exclude
        unique = offsets < ncell
        cell_ok = jnp.all(
            jnp.where(periodic[None, :], unique, in_range), axis=-1
        )  # (W3,)
        cells = jnp.where(periodic[None, :], wrapped, jnp.clip(cells, 0, ncell - 1))

        ckey = encode(
            cells[:, 0].astype(KEY_DTYPE),
            cells[:, 1].astype(KEY_DTYPE),
            cells[:, 2].astype(KEY_DTYPE),
            bits=level,
        )
        start = jnp.searchsorted(sorted_keys, ckey << shift).astype(jnp.int32)
        end = jnp.searchsorted(sorted_keys, (ckey + KEY_DTYPE(1)) << shift).astype(
            jnp.int32
        )
        occupancy = jnp.max(end - start)

        cand = start[:, None] + jnp.arange(cfg.cap, dtype=jnp.int32)  # (W3, cap)
        cand_ok = (cand < end[:, None]) & cell_ok[:, None]
        cand = jnp.clip(cand, 0, n - 1).reshape(-1)  # (C,) shared by the group
        cand_ok = cand_ok.reshape(-1)

        cx, cy, cz = x[cand], y[cand], z[cand]  # ONE gather for the whole group
        dx, dy, dz = apply_pbc_xyz(
            box,
            gx[:, None] - cx[None, :],
            gy[:, None] - cy[None, :],
            gz[:, None] - cz[None, :],
        )
        d2 = dx * dx + dy * dy + dz * dz  # (g, C)

        r2 = (2.0 * gh) ** 2
        hit = cand_ok[None, :] & (d2 < r2[:, None]) & (cand[None, :] != idx[:, None])

        # first-ngmax compaction WITHOUT scatter (TPU scatters serialize):
        # inclusive hit-count cumsum per row, then the k-th neighbor is the
        # first candidate slot where the count reaches k+1 — a batched
        # binary search (pure gathers)
        csum = jnp.cumsum(hit.astype(jnp.int32), axis=-1)  # (g, C)
        nc = csum[:, -1]
        ks = jnp.arange(1, cfg.ngmax + 1, dtype=jnp.int32)  # (ngmax,)
        slot = jax.vmap(
            lambda row: jnp.searchsorted(row, ks, side="left")
        )(csum)  # (g, ngmax)
        nmask = ks[None, :] <= nc[:, None]
        nidx = jnp.where(
            nmask, cand[jnp.minimum(slot, cand.shape[0] - 1)], idx[:, None]
        )
        return nidx, nmask, nc, occupancy, window_ok

    # honor the caller's transient-memory bound: ~block particles per chunk
    chunk = max(1, cfg.block // g)
    pad_groups = -(-num_groups // chunk) * chunk - num_groups
    idx_groups = jnp.concatenate(
        [idx_groups, jnp.broadcast_to(idx_groups[-1:], (pad_groups, g))]
    ) if pad_groups else idx_groups
    batched = idx_groups.reshape(-1, chunk, g)

    def one_chunk(ig):
        return jax.vmap(process_group)(ig)

    nidx, nmask, nc, occ, wok = jax.lax.map(one_chunk, batched)
    nidx = nidx.reshape(-1, cfg.ngmax)[:n]
    nmask = nmask.reshape(-1, cfg.ngmax)[:n]
    nc = nc.reshape(-1)[:n]
    # fold the window guard into the occupancy diagnostic: a blown window
    # reports cap+1, forcing the caller to reconfigure
    occupancy = jnp.where(jnp.all(wok), jnp.max(occ), jnp.int32(cfg.cap + 1))
    return nidx, nmask, nc, occupancy
