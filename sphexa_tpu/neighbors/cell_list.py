"""Cell-list neighbor search over SFC-sorted particle arrays.

Design (SURVEY.md §7 'cell-list/gather formulation'):

1. Particles arrive sorted by SFC key (the global sort order everything in
   the framework shares). A uniform grid at octree level ``L`` is implied by
   the key hierarchy: the level-``L`` cell of a particle is the top ``3L``
   bits of its key — so cell membership ranges in the sorted array are two
   ``searchsorted`` calls, no bucket data structure at all.
2. Each particle turns its 27-cell stencil into 27 contiguous index ranges
   and gathers up to ``cap`` candidates per cell (masked beyond the actual
   occupancy).
3. Candidates are filtered by ``|r_ij| < 2 h_i`` and the closest ``ngmax``
   are kept (matching the reference's ngmax truncation semantics,
   findneighbors.hpp:96-172).

Correctness requires the cell edge >= the search radius ``2*h`` in every
dimension (choose_grid_level guarantees it at config time) and cell
occupancy <= cap (estimate_cell_cap + the returned max_occupancy
diagnostic guard it).

All shapes are static: (N, ngmax) neighbor indices + mask. The search is
chunked over particle blocks with lax.map to bound the transient
(B, 27*cap) gather memory.
"""

import dataclasses
import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from sphexa_tpu.dtypes import KEY_BITS, KEY_DTYPE
from sphexa_tpu.sfc.box import Box, apply_pbc_xyz
from sphexa_tpu.sfc.hilbert import hilbert_encode
from sphexa_tpu.sfc.keys import coords_to_igrid
from sphexa_tpu.sfc.morton import morton_encode


@dataclasses.dataclass(frozen=True)
class NeighborConfig:
    """Static configuration of the neighbor search (hashable, jit-safe)."""

    level: int  # octree level of the cell grid
    cap: int  # max particles gathered per cell
    ngmax: int = 150  # max neighbors kept per particle (reference ngmax)
    block: int = 2048  # particles per lax.map block
    curve: str = "hilbert"

    @property
    def num_candidates(self) -> int:
        return 27 * self.cap


def choose_grid_level(box_lengths, h_max: float) -> int:
    """Deepest grid level whose cell edge still covers the 2h search radius.

    Stands in for the reference's adaptive tree traversal: with cell edge
    >= 2*h_max, the 27-stencil is guaranteed to cover every interaction
    sphere.
    """
    min_extent = float(np.min(np.asarray(box_lengths)))
    if h_max <= 0:
        return KEY_BITS
    level = int(np.floor(np.log2(min_extent / (2.0 * h_max))))
    return max(1, min(KEY_BITS, level))


def estimate_cell_cap(keys, level: int, margin: float = 1.3, quantum: int = 8) -> int:
    """Max level-``level`` cell occupancy of ``keys``, padded with slack.

    Host-side helper run at (re)configuration time. The margin absorbs
    particle motion between reconfigurations; the quantum rounds up so small
    occupancy drifts do not change the static cap (and thus do not
    recompile).
    """
    shift = 3 * (KEY_BITS - level)
    cells = np.asarray(keys, dtype=np.uint64) >> np.uint64(shift)
    occ = int(np.bincount(cells.astype(np.int64)).max()) if len(cells) else 1
    padded = int(np.ceil(occ * margin / quantum) * quantum)
    return max(quantum, padded)


@functools.lru_cache(maxsize=None)
def _stencil(ncell: int) -> np.ndarray:
    """Stencil offsets, deduplicated for coarse grids.

    On a grid with fewer than 3 cells per dimension the -1/+1 offsets alias
    the same cell (mod ncell); emitting both would double-count candidates.
    """
    per_dim = (-1, 0, 1) if ncell >= 3 else ((0, 1) if ncell == 2 else (0,))
    return np.array(
        [(dx, dy, dz) for dx in per_dim for dy in per_dim for dz in per_dim],
        dtype=np.int32,
    )


@functools.partial(jax.jit, static_argnames=("cfg",))
def find_neighbors(
    x, y, z, h, sorted_keys, box: Box, cfg: NeighborConfig
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Neighbor lists for all particles.

    Arguments are the SFC-sorted particle arrays and their keys. Returns:

    - ``nidx`` (N, ngmax) int32: neighbor indices, closest-first; invalid
      slots hold the particle's own index (safe to gather, must be masked);
    - ``nmask`` (N, ngmax) bool: validity of each slot;
    - ``nc`` (N,) int32: true neighbor count within 2h (excluding self, may
      exceed ngmax — used by the smoothing-length update like the
      reference's nc field);
    - ``max_occupancy`` () int32: densest cell seen; if > cfg.cap the cap
      must be raised and the search re-run (overflow diagnostic standing in
      for the reference's GPU stack-overflow detection).
    """
    n = x.shape[0]
    level = cfg.level
    shift = KEY_DTYPE(3 * (KEY_BITS - level))
    ncell = 1 << level
    encode = hilbert_encode if cfg.curve == "hilbert" else morton_encode

    ix = coords_to_igrid(x, box.lo[0], box.hi[0], level).astype(jnp.int32)
    iy = coords_to_igrid(y, box.lo[1], box.hi[1], level).astype(jnp.int32)
    iz = coords_to_igrid(z, box.lo[2], box.hi[2], level).astype(jnp.int32)

    periodic = box.periodic_mask
    stencil = jnp.asarray(_stencil(ncell))  # (<=27, 3)

    num_blocks = -(-n // cfg.block)
    pad = num_blocks * cfg.block - n
    idx_blocks = jnp.arange(num_blocks * cfg.block, dtype=jnp.int32).reshape(
        num_blocks, cfg.block
    )

    def process_block(idx):
        idx = jnp.minimum(idx, n - 1)  # padded tail re-processes the last row
        ci = jnp.stack([ix[idx], iy[idx], iz[idx]], axis=-1)  # (B, 3)
        cells = ci[:, None, :] + stencil[None, :, :]  # (B, 27, 3)
        wrapped = jnp.mod(cells, ncell)
        in_range = (cells >= 0) & (cells < ncell)
        cell_ok = jnp.all(in_range | periodic[None, None, :], axis=-1)  # (B, 27)
        cells = jnp.where(periodic[None, None, :], wrapped, jnp.clip(cells, 0, ncell - 1))

        ckey = encode(
            cells[..., 0].astype(KEY_DTYPE),
            cells[..., 1].astype(KEY_DTYPE),
            cells[..., 2].astype(KEY_DTYPE),
            bits=level,
        )
        start = jnp.searchsorted(sorted_keys, ckey << shift).astype(jnp.int32)
        end = jnp.searchsorted(sorted_keys, (ckey + KEY_DTYPE(1)) << shift).astype(jnp.int32)
        occupancy = jnp.max(end - start)

        cand = start[..., None] + jnp.arange(cfg.cap, dtype=jnp.int32)  # (B,27,cap)
        cand_ok = (cand < end[..., None]) & cell_ok[..., None]
        cand = jnp.clip(cand, 0, n - 1).reshape(idx.shape[0], -1)
        cand_ok = cand_ok.reshape(idx.shape[0], -1)

        dx, dy, dz = apply_pbc_xyz(
            box,
            x[idx][:, None] - x[cand],
            y[idx][:, None] - y[cand],
            z[idx][:, None] - z[cand],
        )
        d2 = dx * dx + dy * dy + dz * dz

        radius = 2.0 * h[idx]
        hit = cand_ok & (d2 < (radius * radius)[:, None]) & (cand != idx[:, None])
        nc = jnp.sum(hit, axis=-1).astype(jnp.int32)

        score = jnp.where(hit, -d2, -jnp.inf)
        top_score, top_pos = jax.lax.top_k(score, cfg.ngmax)
        nidx = jnp.take_along_axis(cand, top_pos, axis=1)
        nmask = top_score > -jnp.inf
        nidx = jnp.where(nmask, nidx, idx[:, None])
        return nidx, nmask, nc, occupancy

    nidx, nmask, nc, occ = jax.lax.map(process_block, idx_blocks)
    nidx = nidx.reshape(-1, cfg.ngmax)[:n]
    nmask = nmask.reshape(-1, cfg.ngmax)[:n]
    nc = nc.reshape(-1)[:n]
    del pad
    return nidx, nmask, nc, jnp.max(occ)
