"""Shared pair-interaction geometry for the SPH j-reductions.

Every SPH op is a masked reduction over a static-shape neighbor list
(N, ngmax). This module holds the common block-level machinery: gather the
j-side fields, compute minimum-image displacements, normalized kernel
distances, and safe masked divisions.
"""

from typing import NamedTuple

import jax.numpy as jnp

from sphexa_tpu.sfc.box import Box, apply_pbc_xyz


class PairGeom(NamedTuple):
    idx: jnp.ndarray  # (B,) i-particle indices
    nj: jnp.ndarray  # (B, ngmax) j-particle indices
    mask: jnp.ndarray  # (B, ngmax) valid-pair mask
    rx: jnp.ndarray  # (B, ngmax) minimum-image displacement x_i - x_j
    ry: jnp.ndarray
    rz: jnp.ndarray
    dist: jnp.ndarray  # (B, ngmax) |r_ij|, 1 where masked (safe divisor)
    v1: jnp.ndarray  # (B, ngmax) dist / h_i


def pair_geometry(idx, x, y, z, h, nidx, nmask, box: Box) -> PairGeom:
    """Gather the pair geometry for one particle block."""
    nj = nidx[idx]
    mask = nmask[idx]
    rx = x[idx][:, None] - x[nj]
    ry = y[idx][:, None] - y[nj]
    rz = z[idx][:, None] - z[nj]
    rx, ry, rz = apply_pbc_xyz(box, rx, ry, rz)
    d2 = rx * rx + ry * ry + rz * rz
    dist = jnp.sqrt(jnp.where(mask, d2, 1.0))
    dist = jnp.where(mask, dist, 1.0)
    v1 = dist / h[idx][:, None]
    return PairGeom(idx, nj, mask, rx, ry, rz, dist, v1)


def iad_project(c11, c12, c13, c22, c23, c33, rx, ry, rz, w=None, sign=-1.0):
    """Project the pair displacement through the symmetric IAD tensor:
    tA_k = sign * (C r)_k * w. The same expression appears in every kernel
    consuming the IAD (iad_divv_curlv, av_switches, momentum_energy std/ve);
    keeping it in one place keeps the index pattern consistent.

    c* may be i-side columns of shape (B, 1) or j-side gathers (B, ngmax).
    """
    t1 = c11 * rx + c12 * ry + c13 * rz
    t2 = c12 * rx + c22 * ry + c23 * rz
    t3 = c13 * rx + c23 * ry + c33 * rz
    if w is not None:
        t1, t2, t3 = t1 * w, t2 * w, t3 * w
    if sign != 1.0:
        t1, t2, t3 = sign * t1, sign * t2, sign * t3
    return t1, t2, t3


def msum(mask, terms):
    """Masked j-sum: zero out invalid pairs, reduce over the neighbor axis."""
    return jnp.sum(jnp.where(mask, terms, 0.0), axis=-1)


def mmax(mask, terms, init=0.0):
    """Masked j-max with explicit identity."""
    return jnp.max(jnp.where(mask, terms, init), axis=-1)
