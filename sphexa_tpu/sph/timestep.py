"""Global time-step selection.

Physics-equivalent of the reference's ``sph/timestep.hpp``: the minimum of
the Courant condition, the density-change condition Krho/|divv|max, the
acceleration condition (with gravity), capped at 1.1x the previous step.
The min-reduction is written with plain jnp.min so that under shard_map /
jit-with-sharding it lowers to a cross-device collective automatically
(replacing the reference's MPI_Allreduce at timestep.hpp:106).
"""

import jax.numpy as jnp

from sphexa_tpu.sph.particles import SimConstants


def acceleration_timestep(ax, ay, az, const: SimConstants):
    """eta * sqrt(eps / |a|_max) (timestep.hpp:46-68), used when gravity is on."""
    max_acc = jnp.sqrt(jnp.max(ax * ax + ay * ay + az * az))
    return const.eta_acc * jnp.sqrt(const.eps / max_acc)


def rho_timestep(divv, const: SimConstants):
    """Krho / |max divv| (timestep.hpp:71-94).

    Deliberately max(divv) then abs — matching the reference exactly: the
    limiter targets the fastest *expansion* (it bounds relative density
    decrease per step); converging flow is bounded by the Courant signal
    velocity instead.
    """
    return const.k_rho / jnp.abs(jnp.max(divv))


def compute_timestep(min_dt_prev, min_dt_courant, *extra_dts, const: SimConstants):
    """Combine all local dt candidates into the global dt (timestep.hpp:97-112)."""
    dt = jnp.minimum(min_dt_courant, const.max_dt_increase * min_dt_prev)
    for e in extra_dts:
        dt = jnp.minimum(dt, e)
    return dt
