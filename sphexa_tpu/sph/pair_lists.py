"""Persistent neighbor lists for the Pallas pair engine.

The streaming engine (sph/pallas_pairs.py) processes ~3500 candidate
lanes per target against ~110 true neighbors — measured AT the
architectural floor of cell-run streaming (chunk quantization c ~ 5 dx
is irreducible for any particle ordering; docs/NEXT.md floor analysis).
Persistent lists break that floor by LANE COMPACTION: a cheap Mosaic
"mark" pass records, for every (target group, 128-lane candidate chunk),
which lanes fall inside the group's skin-inflated bounding box, as a
compacted per-chunk gather-index vector. The list-walk engine variant
then compacts each DMA'd chunk with an in-register lane gather
(``take_along_axis`` along lanes), merges compacted lanes into a dense
staging window with a dynamic ``pltpu.roll``, and runs the pair math only
on FULL 128-lane staging chunks — the per-target lane count drops to the
exact inflated-bbox occupancy (~(G^(1/3) + 4h/dx + skin/dx)^3, ~2.5x
fewer VPU ops than the streamed floor).

Lists persist across steps (the Verlet-list idea, re-shaped for TPU tile
granularity): they are rebuilt only when accumulated drift or smoothing-
length growth exhausts the skin — and between rebuilds the step skips
the global SFC sort AND the candidate-range prologue entirely (the
sorted order is frozen; positions drift in place). Validity is a cheap
O(N) reduction checked in-step; an invalid step is discarded and
replayed after a rebuild, exactly like a neighbor-cap overflow.

Role-wise this replaces the reference's per-step neighbor rebuild
(cstone/traversal/find_neighbors.cuh rebuilds warp-local lists every
step — cheap on GPU SIMT, wasteful on TPU where the equivalent is the
full streaming pass).
"""

import functools
from typing import NamedTuple

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from sphexa_tpu.neighbors.cell_list import NeighborConfig
from sphexa_tpu.sfc.box import Box
from sphexa_tpu.sph.pallas_pairs import (
    GroupRanges,
    _dma_rows,
    _prep_i,
    engine_fold,
    group_cell_ranges,
    pack_j_fields,
)


class PairLists(NamedTuple):
    """Build-time candidate structure shared by every list-walk pair op."""

    ranges: GroupRanges   # candidate runs at build time (skin-inflated)
    gidx: jax.Array       # (NG, S_cap, 128) int32 — per-chunk compacted
    #                       lane gather indices, PRE-ROTATED by the
    #                       staging fill (lanes [fill, fill+cnt) mod 256
    #                       carry the selected source lanes)
    cnt: jax.Array        # (NG, S_cap) int32 — selected lanes per chunk
    fill: jax.Array       # (NG, S_cap) int32 — staging fill before chunk
    emit: jax.Array       # (NG, S_cap) int32 0/1 — chunk completes a full
    #                       128-lane staging chunk
    tail: jax.Array       # (NG,) int32 — flush lanes after the last chunk
    overflow: jax.Array   # () int32 — 1 if any group needed > S_cap slots
    lanes_total: jax.Array  # () int64-ish f32 — sum of cnt (diagnostics)
    xb: jax.Array         # build positions + smoothing lengths: the
    yb: jax.Array         # validity reduction compares current state
    zb: jax.Array         # against these (Verlet skin condition)
    hb: jax.Array
    skin: jax.Array       # () f32 — the coverage slack baked into ranges

    @property
    def slot_cap(self) -> int:
        return self.gidx.shape[1]


def list_slack(x, y, z, h, lists: PairLists):
    """Remaining skin fraction in [-inf, 1]: positive = the build-time
    candidate coverage (bbox inflated by 2*h_build + skin) still covers
    every current 2h_i sphere, which holds while
    2*(max h-growth + max drift) <= skin.

    Drift is measured UNFOLDED: a particle wrapping the periodic box
    shows a ~L jump and correctly forces a rebuild (its build-time image
    shift no longer resolves its pairs). The host watches the slack to
    rebuild PROACTIVELY before a step would have to be discarded."""
    dx = x - lists.xb
    dy = y - lists.yb
    dz = z - lists.zb
    d2 = dx * dx + dy * dy + dz * dz
    drift = jnp.sqrt(jnp.max(d2))
    growth = jnp.maximum(jnp.max(h - lists.hb), 0.0)
    used = 2.0 * (growth + drift)
    return (lists.skin - used) / jnp.maximum(lists.skin, 1e-30)


def lists_valid(x, y, z, h, lists: PairLists):
    """Verlet-skin validity (see list_slack). The boundary (zero used
    skin, e.g. right after a rebuild with list_skin_rel=0) is VALID."""
    return list_slack(x, y, z, h, lists) >= 0.0


def _mark_kernel_builder(cfg: NeighborConfig, slot_cap: int,
                         interpret: bool):
    """Mosaic mark pass: stream the build-time candidate runs once with a
    minimal body (inflated-bbox lane test) and write each chunk's lane
    BITS; counts/compaction/rotation are batched XLA post-passes."""
    R = _dma_rows(cfg.dma_cap)
    G = cfg.group

    def kernel(starts, lens, shx_r, shy_r, shz_r, ncells, skin_s,
               xi_r, yi_r, zi_r, hi_r, jref,
               gidx_out, total_out,
               buf, sems):
        nc_g = ncells[0, 0, 0]

        def dma(w, slot):
            row_s = starts[0, 0, w] // 128
            return pltpu.make_async_copy(
                jref.at[pl.ds(row_s, R), :, :],
                buf.at[slot], sems.at[slot],
            )

        @pl.when(nc_g > 0)
        def _():
            dma(0, 0).start()

        xi = xi_r[0, 0][:, None]
        yi = yi_r[0, 0][:, None]
        zi = zi_r[0, 0][:, None]
        hi = hi_r[0, 0][:, None]
        # group bbox inflated by the build search radius (2*max h + skin):
        # the EXACT volume the walk engine's compacted lanes cover
        r = 2.0 * jnp.max(hi) + skin_s[0, 0, 0]
        glo_x, ghi_x = jnp.min(xi) - r, jnp.max(xi) + r
        glo_y, ghi_y = jnp.min(yi) - r, jnp.max(yi) + r
        glo_z, ghi_z = jnp.min(zi) - r, jnp.max(zi) + r
        lane = jax.lax.broadcasted_iota(jnp.int32, (1, 128), 1)

        def cell_body(w, slot_base):
            slot = w % 2

            @pl.when(w + 1 < nc_g)
            def _():
                dma(w + 1, 1 - slot).start()

            dma(w, slot).wait()
            s = starts[0, 0, w]
            ln = lens[0, 0, w]
            shx = shx_r[0, 0, w]
            shy = shy_r[0, 0, w]
            shz = shz_r[0, 0, w]
            row0 = s // 128
            off = s - row0 * 128
            nch = (off + ln + 127) // 128

            def chunk_body(t, _c):
                part = buf[slot, t]  # (8, 128): rows 0-2 = x, y, z
                jx = part[0][None, :] + shx
                jy = part[1][None, :] + shy
                jz = part[2][None, :] + shz
                cand = (row0 + t) * 128 + lane
                mask = (
                    (cand >= s) & (cand < s + ln)
                    & (jx >= glo_x) & (jx <= ghi_x)
                    & (jy >= glo_y) & (jy <= ghi_y)
                    & (jz >= glo_z) & (jz <= ghi_z)
                )
                # the kernel emits BITS only; counts, compaction indices
                # and pre-rotation are cheap batched XLA (a 128-wide sort
                # beats in-register rank conversion ~5x at build time)
                slot_i = slot_base + t

                @pl.when(slot_i < slot_cap)
                def _():
                    gidx_out[0, pl.ds(slot_i, 1)] = mask.astype(jnp.int32)

                return _c

            jax.lax.fori_loop(0, nch, chunk_body, 0)
            return slot_base + nch

        # dead slots must read as empty (no bits set)
        gidx_out[...] = jnp.zeros((1, slot_cap, 128), jnp.int32)
        total = jax.lax.fori_loop(0, nc_g, cell_body, 0)
        total_out[0, 0, 0] = total

    def call(ranges: GroupRanges, i_fields, j_packed, skin):
        num_groups = ranges.num_groups
        w3 = ranges.starts.shape[1]
        i_fields = [a.reshape(num_groups, 1, G) for a in i_fields]
        smem3 = lambda a: a.reshape(num_groups, 1, w3)
        smem_spec = lambda shape: pl.BlockSpec(
            shape, lambda g: (g, 0, 0), memory_space=pltpu.SMEM
        )
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=0,
            grid=(num_groups,),
            in_specs=[
                smem_spec((1, 1, w3)),  # starts
                smem_spec((1, 1, w3)),  # lens
                smem_spec((1, 1, w3)),  # shift x/y/z
                smem_spec((1, 1, w3)),
                smem_spec((1, 1, w3)),
                smem_spec((1, 1, 1)),   # ncells
                pl.BlockSpec((1, 1, 1), lambda g: (0, 0, 0),
                             memory_space=pltpu.SMEM),  # skin
            ]
            + [
                pl.BlockSpec((1, 1, G), lambda g: (g, 0, 0))
                for _ in range(4)   # x, y, z, h
            ]
            + [pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=[
                pl.BlockSpec((1, slot_cap, 128), lambda g: (g, 0, 0)),
                pl.BlockSpec((1, 1, 1), lambda g: (g, 0, 0),
                             memory_space=pltpu.SMEM),
            ],
            scratch_shapes=[
                pltpu.VMEM((2, R, 8, 128), jnp.float32),
                pltpu.SemaphoreType.DMA((2,)),
            ],
        )
        out_shape = [
            jax.ShapeDtypeStruct((num_groups, slot_cap, 128), jnp.int32),
            jax.ShapeDtypeStruct((num_groups, 1, 1), jnp.int32),
        ]
        skin_s = jnp.asarray(skin, jnp.float32).reshape(1, 1, 1)
        return pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=out_shape,
            interpret=interpret,
        )(smem3(ranges.starts), smem3(ranges.lens),
          smem3(ranges.shift_x), smem3(ranges.shift_y),
          smem3(ranges.shift_z),
          ranges.ncells.reshape(num_groups, 1, 1), skin_s,
          *i_fields, j_packed)

    return call


def _prune_empty_chunks(ranges: GroupRanges, cnt, slot_cap: int):
    """Rebuild the candidate runs to exclude chunks with NO marked lane:
    every engine pass then neither DMAs nor iterates them (the measured
    per-chunk base cost is ~115 ns even when the math is skipped).

    New runs are maximal consecutive kept-chunk intervals WITHIN one
    original run, with exact particle bounds (the intersection of the
    original [s, s+len) with the kept rows) — never merged across
    original runs, so the in-run candidate mask admits exactly the
    original run's particles and no cross-run double counting can occur.
    Dropped chunks had no lane inside any group's inflated bbox, so no
    pair is lost. Returns (new_ranges, perm) where perm[k] is the
    ORIGINAL slot index of new slot k (for compacting the per-slot
    arrays; the compacted chunk sequence preserves original order, so
    staging fills computed on the zero-preserving cumsum are unchanged).
    """
    starts, lens = ranges.starts, ranges.lens
    ng, w3 = starts.shape
    s_idx = jnp.arange(slot_cap, dtype=jnp.int32)

    # slot -> (run w, chunk c, row, shift, exact bounds)
    off = starts % 128
    nch_w = jnp.where(lens > 0, (off + lens + 127) // 128, 0)  # (NG, W3)
    cum_w = jnp.cumsum(nch_w, axis=1) - nch_w                  # exclusive
    w_of_s = jnp.sum(
        (cum_w[:, None, :] <= s_idx[None, :, None]).astype(jnp.int32)
        & (nch_w[:, None, :] > 0), axis=2,
    ) - 1  # (NG, S_cap); -1 for slots before any run (none) / past-end dup
    w_of_s = jnp.clip(w_of_s, 0, w3 - 1)
    take = lambda a: jnp.take_along_axis(a, w_of_s, axis=1)
    s_w = take(starts)
    ln_w = take(lens)
    c_of_s = s_idx[None, :] - take(cum_w)
    row_s = s_w // 128 + c_of_s
    lo_s = jnp.maximum(s_w, row_s * 128)
    hi_s = jnp.minimum(s_w + ln_w, (row_s + 1) * 128)
    total = jnp.sum(nch_w, axis=1)  # (NG,)

    kept = (cnt > 0) & (s_idx[None, :] < total[:, None])
    kept_prev = jnp.concatenate(
        [jnp.zeros((ng, 1), bool), kept[:, :-1]], axis=1
    )
    head = kept & ((c_of_s == 0) | ~kept_prev)

    # run end = hi of the last consecutive kept slot (reverse scan, the
    # _merge_runs pattern)
    end_eff = jnp.where(kept, hi_s, -1)
    head_next = jnp.concatenate(
        [head[:, 1:], jnp.ones((ng, 1), bool)], axis=1
    )

    def rstep(carry, inp):
        e_w, hn_w = inp
        r = jnp.maximum(e_w, jnp.where(hn_w, jnp.int32(-1), carry))
        return r, r

    xs_r = (end_eff[:, ::-1].T, head_next[:, ::-1].T)
    _, r_t = jax.lax.scan(rstep, jnp.full_like(end_eff[:, 0], -1), xs_r)
    run_end = r_t.T[:, ::-1]

    shx_s = take(ranges.shift_x)
    shy_s = take(ranges.shift_y)
    shz_s = take(ranges.shift_z)
    INF = jnp.int32(2**30)
    _, hk_i, hs_r, hlen, cshx, cshy, cshz = jax.lax.sort(
        (jnp.where(head, s_idx[None, :], INF), head.astype(jnp.int32),
         lo_s, run_end - lo_s, shx_s, shy_s, shz_s),
        num_keys=1, dimension=1, is_stable=True,
    )
    hk = hk_i.astype(bool)
    new_ranges = GroupRanges(
        starts=jnp.where(hk, hs_r, 0),
        lens=jnp.where(hk, hlen, 0),
        shift_x=jnp.where(hk, cshx, 0.0),
        shift_y=jnp.where(hk, cshy, 0.0),
        shift_z=jnp.where(hk, cshz, 0.0),
        ncells=jnp.sum(head, axis=1).astype(jnp.int32),
        occupancy=ranges.occupancy,
        boxl=ranges.boxl,
    )
    # kept slots compacted to the front, original order preserved
    _, perm = jax.lax.sort(
        (jnp.where(kept, s_idx[None, :], INF),
         jnp.broadcast_to(s_idx[None, :], kept.shape)),
        num_keys=1, dimension=1, is_stable=True,
    )
    return new_ranges, perm


def build_pair_lists(
    x, y, z, h, sorted_keys, box: Box, cfg: NeighborConfig,
    skin, slot_cap: int, interpret: bool = False, table=None,
) -> PairLists:
    """Build the persistent lists from SFC-SORTED arrays (jit-safe).

    ``skin`` (traced f32) is the coverage slack; ``slot_cap`` the static
    per-group chunk-slot budget (sized at configure time, guarded by the
    ``overflow`` sentinel like every other static cap)."""
    if engine_fold(box, cfg):
        raise ValueError(
            "persistent lists need per-cell image shifts; the tiny-grid "
            "fold mode streams instead (lists are a large-N optimization)")
    ranges = group_cell_ranges(
        x, y, z, h, sorted_keys, box, cfg, table=table, radius_pad=skin,
    )
    i_fields = _prep_i(x, y, z, h, (), cfg.group)
    jp = pack_j_fields((x, y, z), cfg.dma_cap)
    mark = _mark_kernel_builder(cfg, slot_cap, interpret)
    bits, total = mark(ranges, i_fields, jp, skin)
    total = total.reshape(-1)
    cnt = jnp.sum(bits, axis=-1)

    # drop empty chunks from the runs (the engines then neither DMA nor
    # iterate them) and compact the per-slot arrays to the new order
    ranges, perm = _prune_empty_chunks(ranges, cnt, slot_cap)
    cnt = jnp.take_along_axis(cnt, perm, axis=1)
    bits = jnp.take_along_axis(bits, perm[:, :, None], axis=1)

    # staging bookkeeping, precomputed so the walk kernel carries no
    # sequential fill state: fill before chunk s = (exclusive cumsum of
    # cnt) mod 128; a chunk emits a full staging chunk iff fill+cnt >= 128
    # (cnt <= 128 crosses at most one boundary per chunk)
    csum = jnp.cumsum(cnt, axis=1)
    excl = csum - cnt
    fill = excl % 128
    emit = ((fill + cnt) >= 128).astype(jnp.int32)
    tail = csum[:, -1] % 128
    overflow = jnp.max(total).astype(jnp.int32) > slot_cap

    # PRE-ROTATED compaction indices in ONE batched 128-wide sort: lane
    # l's destination slot is (fill + rank-among-selected) % 128 when
    # marked, and the remaining slots (in wrap order) when not — all 128
    # keys are distinct, so sorting (dst, lane) scatters each lane to its
    # exact slot. This folds the staging rotation into the sort: both a
    # minor-axis take_along_axis here (measured 6.4 s at 1M — XLA's
    # pathological gather) and a per-chunk pltpu.roll in the walk kernel
    # (measured 90 ns/chunk) disappear.
    lane = jnp.broadcast_to(
        jnp.arange(128, dtype=jnp.int32), bits.shape
    )
    rank1 = jnp.cumsum(bits, axis=2) - bits   # rank among selected
    rank0 = lane - rank1                      # rank among unselected
    dst = jnp.where(
        bits > 0, fill[:, :, None] + rank1,
        fill[:, :, None] + cnt[:, :, None] + rank0,
    ) % 128
    _, rot = jax.lax.sort((dst, lane), num_keys=1, dimension=2)
    return PairLists(
        ranges=ranges, gidx=rot, cnt=cnt, fill=fill, emit=emit,
        tail=tail, overflow=overflow.astype(jnp.int32),
        lanes_total=jnp.sum(csum[:, -1].astype(jnp.float32)),
        xb=x, yb=y, zb=z, hb=h,
        skin=jnp.asarray(skin, jnp.float32),
    )


@functools.partial(jax.jit, static_argnames=("cfg",))
def _slot_need(x, y, z, h, sorted_keys, box, cfg, skin):
    ranges = group_cell_ranges(x, y, z, h, sorted_keys, box, cfg,
                               radius_pad=skin)
    off = ranges.starts % 128
    nch = jnp.where(ranges.lens > 0, (off + ranges.lens + 127) // 128, 0)
    return jnp.max(jnp.sum(nch, axis=1))


def estimate_slot_cap(
    x, y, z, h, sorted_keys, box: Box, cfg: NeighborConfig, skin: float,
    margin: float = 1.3, quantum: int = 8,
) -> int:
    """Host-side sizing of the static per-group chunk-slot budget from
    the current (SFC-sorted) distribution — configure-time, like cell
    caps; the build-time ``overflow`` sentinel guards outgrowth."""
    from sphexa_tpu.neighbors.cell_list import pad_cap

    need = int(_slot_need(x, y, z, h, sorted_keys, box, cfg,
                          jnp.float32(skin)))
    return pad_cap(need, margin, quantum)
