"""Generalized volume-element (VE) SPH pipeline.

Physics-equivalent of the reference's ``sph/hydro_ve/`` kernel family
(xmass_kern.hpp, ve_def_gradh_kern.hpp, iad_kern.hpp, divv_curlv_kern.hpp,
av_switches_kern.hpp, momentum_energy_kern.hpp): the SPHYNX volume-element
formulation with grad-h terms, per-particle artificial-viscosity switches,
and the Atwood-number crossed/uncrossed momentum ramp. Each op is a masked
vectorized j-reduction; the IAD tensor op is shared with the std pipeline
(sph/hydro_std.py compute_iad with vol_j = xm/kx).
"""

from typing import Tuple

import jax.numpy as jnp

from sphexa_tpu.sfc.box import Box
from sphexa_tpu.sph.kernels import (
    artificial_viscosity,
    sinc_dterh_u,
    sinc_kernel_u,
    ts_k_courant,
)
from sphexa_tpu.sph.pairs import iad_project, mmax, msum, pair_geometry
from sphexa_tpu.sph.particles import SimConstants
from sphexa_tpu.util.blocking import blocked_map
from sphexa_tpu.util.phases import named_phase


@named_phase("xmass")
def compute_xmass(x, y, z, h, m, nidx, nmask, box: Box, const: SimConstants, block=2048):
    """Generalized volume element xm_i = m_i / rho0_i (xmass_kern.hpp:50-79),
    rho0 the standard kernel-summed density estimate."""
    n = x.shape[0]

    def body(idx):
        g = pair_geometry(idx, x, y, z, h, nidx, nmask, box)
        w = sinc_kernel_u(g.v1 * g.v1, const.sinc_index, const.kernel_choice)
        rho0 = m[idx] + msum(g.mask, m[g.nj] * w)
        h_i = h[idx]
        return m[idx] / (rho0 * const.K / (h_i * h_i * h_i))

    return blocked_map(body, n, block)


@named_phase("gradh")
def compute_ve_def_gradh(
    x, y, z, h, m, xm, nidx, nmask, box: Box, const: SimConstants, block=2048
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """VE normalization kx and grad-h correction (ve_def_gradh_kern.hpp:43-90).

    kx_i = K h^-3 sum_j xm_j W; gradh from the h-derivative terms
    dW/dh = -(3 W + v dW/dv)/h summed over both xm and m weights.
    """
    n = x.shape[0]

    def body(idx):
        g = pair_geometry(idx, x, y, z, h, nidx, nmask, box)
        w = sinc_kernel_u(g.v1 * g.v1, const.sinc_index, const.kernel_choice)
        dterh = sinc_dterh_u(g.v1 * g.v1, const.sinc_index, const.kernel_choice)

        xm_i = xm[idx]
        m_i = m[idx]
        kx = xm_i + msum(g.mask, xm[g.nj] * w)
        whomega = -3.0 * xm_i + msum(g.mask, xm[g.nj] * dterh)
        wrho0 = -3.0 * m_i + msum(g.mask, m[g.nj] * dterh)

        h_i = h[idx]
        h3inv = 1.0 / (h_i * h_i * h_i)
        kx = kx * const.K * h3inv
        whomega = whomega * const.K * h3inv / h_i
        wrho0 = wrho0 * const.K * h3inv / h_i

        whomega = whomega * m_i / xm_i + (kx - const.K * xm_i * h3inv) * wrho0
        rho = kx * m_i / xm_i
        dhdrho = -h_i / (rho * 3.0)
        gradh = 1.0 - dhdrho * whomega
        return kx, gradh

    return blocked_map(body, n, block)


@named_phase("eos")
def compute_eos_ve(temp, m, kx, xm, gradh, const: SimConstants):
    """VE ideal-gas EOS (hydro_ve/eos.hpp:52-77): returns (prho, c, rho, p).

    prho = p / (kx m^2 gradh) is the quantity entering the momentum sum.
    """
    rho = kx * m / xm
    tmp = const.cv * temp * (const.gamma - 1.0)
    p = rho * tmp
    c = jnp.sqrt(tmp)
    prho = p / (kx * m * m * gradh)
    return prho, c, rho, p


@named_phase("divv-curlv")
def compute_iad_divv_curlv(
    x, y, z, vx, vy, vz, h, kx, xm,
    c11, c12, c13, c22, c23, c33,
    nidx, nmask, box: Box, const: SimConstants, block=2048, with_gradv=False,
):
    """Velocity divergence/curl through the IAD gradient (divv_curlv_kern.hpp
    :43-120); optionally the full symmetrized velocity-gradient tensor for
    the avClean momentum correction. The reference fuses IAD+divv+curlv in
    one pass (iad_divv_curlv.hpp); here IAD comes from hydro_std.compute_iad
    and this op consumes its output — XLA's fusion takes the place of the
    hand-fused kernel.
    """
    n = x.shape[0]

    def body(idx):
        g = pair_geometry(idx, x, y, z, h, nidx, nmask, box)
        w = sinc_kernel_u(g.v1 * g.v1, const.sinc_index, const.kernel_choice)

        tA1, tA2, tA3 = iad_project(
            c11[idx][:, None], c12[idx][:, None], c13[idx][:, None],
            c22[idx][:, None], c23[idx][:, None], c33[idx][:, None],
            g.rx, g.ry, g.rz, w,
        )

        vx_ji = vx[g.nj] - vx[idx][:, None]
        vy_ji = vy[g.nj] - vy[idx][:, None]
        vz_ji = vz[g.nj] - vz[idx][:, None]
        xm_j = xm[g.nj]

        dvx = (msum(g.mask, vx_ji * xm_j * tA1), msum(g.mask, vx_ji * xm_j * tA2),
               msum(g.mask, vx_ji * xm_j * tA3))
        dvy = (msum(g.mask, vy_ji * xm_j * tA1), msum(g.mask, vy_ji * xm_j * tA2),
               msum(g.mask, vy_ji * xm_j * tA3))
        dvz = (msum(g.mask, vz_ji * xm_j * tA1), msum(g.mask, vz_ji * xm_j * tA2),
               msum(g.mask, vz_ji * xm_j * tA3))

        h_i = h[idx]
        norm_kxi = const.K / (h_i * h_i * h_i) / kx[idx]
        divv = norm_kxi * (dvx[0] + dvy[1] + dvz[2])
        curl = (dvz[1] - dvy[2], dvx[2] - dvz[0], dvy[0] - dvx[1])
        curlv = norm_kxi * jnp.sqrt(curl[0] ** 2 + curl[1] ** 2 + curl[2] ** 2)

        if with_gradv:
            dv11 = norm_kxi * dvx[0]
            dv12 = norm_kxi * (dvx[1] + dvy[0])
            dv13 = norm_kxi * (dvx[2] + dvz[0])
            dv22 = norm_kxi * dvy[1]
            dv23 = norm_kxi * (dvy[2] + dvz[1])
            dv33 = norm_kxi * dvz[2]
            return divv, curlv, dv11, dv12, dv13, dv22, dv23, dv33
        return divv, curlv

    return blocked_map(body, n, block)


@named_phase("av-switches")
def compute_av_switches(
    x, y, z, vx, vy, vz, h, c, kx, xm, divv, alpha,
    c11, c12, c13, c22, c23, c33,
    nidx, nmask, box: Box, dt, const: SimConstants, block=2048,
):
    """Per-particle viscosity switch evolution (av_switches_kern.hpp:43-137):
    alpha grows toward alphamax in converging flow with strong grad(divv),
    decays toward alphamin on the signal-velocity time scale otherwise."""
    n = x.shape[0]

    def body(idx):
        g = pair_geometry(idx, x, y, z, h, nidx, nmask, box)
        h_i = h[idx]
        w = const.K / (h_i * h_i * h_i)[:, None] * sinc_kernel_u(g.v1 * g.v1, const.sinc_index, const.kernel_choice)

        vx_ij = vx[idx][:, None] - vx[g.nj]
        vy_ij = vy[idx][:, None] - vy[g.nj]
        vz_ij = vz[idx][:, None] - vz[g.nj]
        rv = g.rx * vx_ij + g.ry * vy_ij + g.rz * vz_ij

        c_i = c[idx][:, None]
        vijsignal_pair = jnp.where(
            rv < 0.0, c_i + c[g.nj] - 3.0 * rv / g.dist, 0.0
        )
        vijsignal = jnp.maximum(mmax(g.mask, vijsignal_pair), 1e-40 * c[idx])

        tA1, tA2, tA3 = iad_project(
            c11[idx][:, None], c12[idx][:, None], c13[idx][:, None],
            c22[idx][:, None], c23[idx][:, None], c33[idx][:, None],
            g.rx, g.ry, g.rz, w,
        )

        vol_j = xm[g.nj] / kx[g.nj]
        factor = vol_j * (divv[idx][:, None] - divv[g.nj])
        gdx = msum(g.mask, factor * tA1)
        gdy = msum(g.mask, factor * tA2)
        gdz = msum(g.mask, factor * tA3)
        graddivv = jnp.sqrt(gdx * gdx + gdy * gdy + gdz * gdz)

        divv_i = divv[idx]
        a_const = h_i * h_i * graddivv
        alphaloc = jnp.where(
            divv_i < 0.0,
            const.alphamax * a_const / (a_const + h_i * jnp.abs(divv_i) + 0.05 * c[idx]),
            0.0,
        )

        alpha_i = alpha[idx]
        decay = h_i / (const.decay_constant * vijsignal)
        target = jnp.where(alphaloc >= const.alphamin, alphaloc, const.alphamin)
        alphadot = (target - alpha_i) / decay
        alpha_decayed = alpha_i + alphadot * dt
        return jnp.where(alphaloc >= alpha_i, alphaloc, alpha_decayed)

    return blocked_map(body, n, block)


def _av_rv_correction(rx, ry, rz, eta_ab, eta_crit, gv_i, gv_j):
    """avClean correction to the projected pair velocity
    (momentum_energy_kern.hpp avRvCorrection:43-63)."""
    sym_dot = lambda gv, rx, ry, rz: (
        rx * (gv[0] * rx + gv[1] * ry + gv[2] * rz)
        + ry * (gv[3] * ry + gv[4] * rz)
        + rz * (gv[5] * rz)
    )
    d1 = sym_dot(gv_i, rx, ry, rz)
    d2 = sym_dot(gv_j, rx, ry, rz)
    eta_diff = 5.0 * (eta_ab - eta_crit)
    d3 = jnp.where(eta_ab < eta_crit, jnp.exp(-(eta_diff**2)), 1.0)
    A = jnp.where(d2 != 0.0, d1 / d2, 0.0)
    Ap1 = 1.0 + A
    phi = 0.5 * d3 * jnp.clip(4.0 * A / (Ap1 * Ap1), 0.0, 1.0)
    return -phi * (d1 + d2)


@named_phase("momentum-energy")
def compute_momentum_energy_ve(
    x, y, z, vx, vy, vz, h, m, prho, c, kx, xm, alpha,
    c11, c12, c13, c22, c23, c33,
    nidx, nmask, nc, box: Box, const: SimConstants, block=1024,
    gradv=None,
):
    """VE momentum + energy (momentum_energy_kern.hpp:65-222): Atwood-ramped
    crossed/uncrossed volume elements, per-particle alpha viscosity, signal
    velocity 0.5(ci+cj) - 2 w_ij; optional avClean gradV correction when
    ``gradv`` (6-tuple of dV arrays) is given.

    Returns (ax, ay, az, du, min_dt_courant).
    """
    n = x.shape[0]
    av_clean = gradv is not None

    def body(idx):
        g = pair_geometry(idx, x, y, z, h, nidx, nmask, box)
        h_i = h[idx][:, None]
        h_j = h[g.nj]
        if getattr(const, "sym_pairs", True):
            # min-h symmetric cutoff: exact pairwise antisymmetry (see
            # SimConstants.sym_pairs; matches the engine's sym_jf mask)
            g = g._replace(mask=g.mask & (g.dist < 2.0 * h_j))
        hi3 = h_i * h_i * h_i
        hj3 = h_j * h_j * h_j
        w_i = sinc_kernel_u(g.v1 * g.v1, const.sinc_index, const.kernel_choice) / hi3
        v2 = g.dist / h_j
        w_j = sinc_kernel_u(v2 * v2, const.sinc_index, const.kernel_choice) / hj3

        vx_ij = vx[idx][:, None] - vx[g.nj]
        vy_ij = vy[idx][:, None] - vy[g.nj]
        vz_ij = vz[idx][:, None] - vz[g.nj]
        rv = g.rx * vx_ij + g.ry * vy_ij + g.rz * vz_ij

        if av_clean:
            eta_crit = jnp.cbrt(32.0 * jnp.pi / 3.0 / (nc[idx].astype(jnp.float32) + 1.0))
            gv_i = tuple(a[idx][:, None] for a in gradv)
            gv_j = tuple(a[g.nj] for a in gradv)
            rv = rv + _av_rv_correction(
                g.rx, g.ry, g.rz, jnp.minimum(g.v1, v2), eta_crit[:, None], gv_i, gv_j
            )

        w_ij = rv / g.dist
        c_i = c[idx][:, None]
        c_j = c[g.nj]
        visc = artificial_viscosity(alpha[idx][:, None], alpha[g.nj], c_i, c_j, w_ij)

        vijsignal = 0.5 * (c_i + c_j) - 2.0 * w_ij
        maxvsignal = mmax(g.mask, vijsignal)

        tA1_i, tA2_i, tA3_i = iad_project(
            c11[idx][:, None], c12[idx][:, None], c13[idx][:, None],
            c22[idx][:, None], c23[idx][:, None], c33[idx][:, None],
            g.rx, g.ry, g.rz, w_i,
        )
        tA1_j, tA2_j, tA3_j = iad_project(
            c11[g.nj], c12[g.nj], c13[g.nj], c22[g.nj], c23[g.nj], c33[g.nj],
            g.rx, g.ry, g.rz, w_j,
        )

        m_i = m[idx][:, None]
        m_j = m[g.nj]
        xm_i = xm[idx][:, None]
        xm_j = xm[g.nj]
        rho_i = kx[idx][:, None] * m_i / xm_i
        rho_j = kx[g.nj] * m_j / xm_j

        # Atwood-number ramp between uncrossed (xm_i^2, xm_j^2) and crossed
        # (xm_i xm_j) volume-element weightings
        atwood = jnp.abs(rho_i - rho_j) / (rho_i + rho_j)
        sigma = const.ramp * (atwood - const.at_min)
        a_uncrossed, b_uncrossed = xm_i * xm_i, xm_j * xm_j
        crossed = xm_i * xm_j
        a_ramp = xm_i ** (2.0 - sigma) * xm_j**sigma
        b_ramp = xm_j ** (2.0 - sigma) * xm_i**sigma
        a_mom = jnp.where(atwood < const.at_min, a_uncrossed,
                          jnp.where(atwood > const.at_max, crossed, a_ramp))
        b_mom = jnp.where(atwood < const.at_min, b_uncrossed,
                          jnp.where(atwood > const.at_max, crossed, b_ramp))

        a_visc = m_j / rho_i * visc
        b_visc = m_j / rho_j * visc
        a_visc_x = 0.5 * (a_visc * tA1_i + b_visc * tA1_j)
        a_visc_y = 0.5 * (a_visc * tA2_i + b_visc * tA2_j)
        a_visc_z = 0.5 * (a_visc * tA3_i + b_visc * tA3_j)
        a_visc_energy = msum(
            g.mask, a_visc_x * vx_ij + a_visc_y * vy_ij + a_visc_z * vz_ij
        )

        prho_i = prho[idx][:, None]
        energy = msum(
            g.mask,
            m_j * a_mom * (vx_ij * tA1_i + vy_ij * tA2_i + vz_ij * tA3_i),
        )
        mom_i = m_j * prho_i * a_mom
        mom_j = m_j * prho[g.nj] * b_mom
        mom_x = msum(g.mask, mom_i * tA1_i + mom_j * tA1_j + a_visc_x)
        mom_y = msum(g.mask, mom_i * tA2_i + mom_j * tA2_j + a_visc_y)
        mom_z = msum(g.mask, mom_i * tA3_i + mom_j * tA3_j + a_visc_z)

        a_visc_energy = jnp.maximum(a_visc_energy, 0.0)
        du = const.K * (prho[idx] * energy + 0.5 * a_visc_energy)

        dt_i = ts_k_courant(maxvsignal, h[idx], c[idx], const.k_cour)
        return (-const.K * mom_x, -const.K * mom_y, -const.K * mom_z, du, dt_i)

    ax, ay, az, du, dt = blocked_map(body, n, block)
    return ax, ay, az, du, jnp.min(dt)
