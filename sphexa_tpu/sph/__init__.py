"""SPH numerics: smoothing kernels, std and volume-element pipelines.

TPU-native re-design of the reference's ``sph/include/sph/`` library: every
kernel is a vectorized masked j-reduction over static-shape neighbor lists
instead of a per-particle scalar loop; pipelines are pure functions over a
ParticleState pytree.
"""

from sphexa_tpu.sph.kernels import (
    artificial_viscosity,
    kernel_norm_3d,
    sinc_kernel,
    sinc_kernel_derivative,
    ts_k_courant,
    update_h,
)
from sphexa_tpu.sph.particles import ParticleState, SimConstants

__all__ = [
    "artificial_viscosity",
    "kernel_norm_3d",
    "sinc_kernel",
    "sinc_kernel_derivative",
    "ts_k_courant",
    "update_h",
    "ParticleState",
    "SimConstants",
]
