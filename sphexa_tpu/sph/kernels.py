"""Smoothing kernels and per-pair closed forms.

Physics-equivalent of the reference's ``sph/kernels.hpp`` and
``sph_kernel_tables.hpp``: the sinc^n kernel family (SPHYNX,
DOI 10.1051/0004-6361/201630208), its derivative, the 3D normalization
constant, Monaghan-style artificial viscosity, the Courant signal-velocity
time step, and the neighbor-count-driven smoothing-length update.

Where the reference tabulates the kernel at 20000 points and does linear
lookups (table_lookup.hpp), the TPU build evaluates ``sin`` directly: a
transcendental on the VPU is cheaper than a gather from a lookup table,
and it fuses into the surrounding j-loop kernel.
"""

import numpy as np
import jax.numpy as jnp

SUPPORT = 2.0  # kernel support radius in units of h


def sinc_kernel(v, n: float = 6.0):
    """W_n(v) = sinc(pi/2 * v)^n on v in [0, 2]; 0 outside.

    v is dist/h. Clamping to the support makes out-of-range j-side
    evaluations (h_j < h_i) return exactly 0.
    """
    v = jnp.clip(v, 0.0, SUPPORT)
    pv = (0.5 * jnp.pi) * v
    sinc = jnp.where(v > 0.0, jnp.sin(pv) / jnp.where(v > 0.0, pv, 1.0), 1.0)
    return sinc**n


def sinc_kernel_derivative(v, n: float = 6.0):
    """dW_n/dv = n * sinc^(n-1)(pi/2 v) * d sinc/dv; 0 at v=0 and v>=2."""
    v = jnp.clip(v, 0.0, SUPPORT)
    pv = (0.5 * jnp.pi) * v
    safe_pv = jnp.where(v > 0.0, pv, 1.0)
    sinc = jnp.where(v > 0.0, jnp.sin(pv) / safe_pv, 1.0)
    # d/dv sinc(pi/2 v) = sinc * (pi/2) * (cot(pv) - 1/pv)
    dsinc = sinc * (0.5 * jnp.pi) * (
        jnp.cos(pv) / jnp.where(v > 0.0, jnp.sin(pv), 1.0) - 1.0 / safe_pv
    )
    return jnp.where(v > 0.0, n * sinc ** (n - 1.0) * dsinc, 0.0)


def kernel_norm_3d(n: float = 6.0, support: float = SUPPORT, num: int = 20001) -> float:
    """3D normalization K with ∫ K W(|x|/h) h^-3 d^3x = 1.

    Same quantity as the reference's kernel_3D_k (sph_kernel_tables.hpp:77-84),
    computed here with numpy float64 Simpson integration at config time.
    """
    if num % 2 == 0:
        num += 1  # composite Simpson needs an even interval count
    x = np.linspace(0.0, support, num)
    pv = 0.5 * np.pi * x
    sinc = np.ones_like(x)
    sinc[1:] = np.sin(pv[1:]) / pv[1:]
    f = 4.0 * np.pi * x**2 * sinc**n
    dx = x[1] - x[0]
    integral = dx / 3.0 * (f[0] + f[-1] + 4.0 * f[1:-1:2].sum() + 2.0 * f[2:-1:2].sum())
    return float(1.0 / integral)


def artificial_viscosity(alpha_i, alpha_j, c_i, c_j, w_ij, beta: float = 2.0):
    """Monaghan signal-velocity artificial viscosity (kernels.hpp:60-84).

    w_ij is the pair velocity projected on the separation axis; only
    approaching pairs (w_ij < 0) dissipate.
    """
    v_signal = 0.25 * (alpha_i + alpha_j) * (c_i + c_j) - beta * w_ij
    return jnp.where(w_ij < 0.0, -v_signal * w_ij, 0.0)


def ts_k_courant(maxvsignal, h, c, k_cour):
    """Courant time step from the max signal velocity (kernels.hpp:9-16)."""
    v = jnp.where(maxvsignal > 0.0, maxvsignal, c)
    return k_cour * h / v


def update_h(ng0: int, nc, h):
    """Nudge h so the neighbor count drifts toward ng0 (kernels.hpp:18-32).

    nc includes the particle itself, like the reference's usage.
    """
    c0 = 1023.0
    return h * 0.5 * (1.0 + c0 * ng0 / jnp.maximum(nc, 1)) ** 0.1
