"""Smoothing kernels and per-pair closed forms.

Physics-equivalent of the reference's ``sph/kernels.hpp`` and
``sph_kernel_tables.hpp``: the sinc^n kernel family (SPHYNX,
DOI 10.1051/0004-6361/201630208), its derivative, the 3D normalization
constant, Monaghan-style artificial viscosity, the Courant signal-velocity
time step, and the neighbor-count-driven smoothing-length update.

Where the reference tabulates the kernel at 20000 points and does linear
lookups (table_lookup.hpp), the TPU build fits W as a degree-13 polynomial
in v^2 (``sinc_kernel_u``): a table gather would serialize on the VPU, and
the polynomial (a) needs no sqrt — the pair loops have d2, not dist —
(b) is 14 fused multiply-adds with no transcendental, and (c) matches the
exact kernel to ~3e-7 absolute (the f32 rounding floor, comparable to the
reference table's own interpolation+storage error). The exact ``sin``
forms below remain the accuracy reference and provide the derivative.
"""

import functools

import numpy as np
import jax.numpy as jnp

SUPPORT = 2.0  # kernel support radius in units of h

# Kernel families (the reference's SphKernelType enum,
# sph_kernel_tables.hpp:122-160, plus one non-sinc family):
#   "sinc"        — sinc(pi v / 2)^n (SPHYNX default, n = sinc_index)
#   "sinc-n1-n2"  — 0.9 sinc^4 + 0.1 sinc^9 (SincN1SincN2, fixed mix)
#   "wendland-c6" — Wendland C6 (Dehnen & Aly 2012), support 2h
KERNEL_CHOICES = ("sinc", "sinc-n1-n2", "wendland-c6")


def _kernel_samples(v: np.ndarray, n: float, kind: str) -> np.ndarray:
    """W(v) on v in [0, 2] in float64 (fit/normalization reference)."""
    def sincn(e):
        pv = 0.5 * np.pi * v
        s = np.ones_like(v)
        nz = v > 0
        s[nz] = np.sin(pv[nz]) / pv[nz]
        return s ** float(e)

    if kind == "sinc":
        return sincn(n)
    if kind == "sinc-n1-n2":
        return 0.9 * sincn(4.0) + 0.1 * sincn(9.0)
    if kind == "wendland-c6":
        q = np.clip(v / 2.0, 0.0, 1.0)
        return (1.0 - q) ** 8 * (1.0 + 8.0 * q + 25.0 * q**2 + 32.0 * q**3)
    raise ValueError(f"unknown kernel kind {kind!r} (choices: {KERNEL_CHOICES})")


@functools.lru_cache(maxsize=None)
def kernel_poly_coeffs(n: float, kind: str = "sinc", degree: int = 0) -> tuple:
    """Power coefficients of W as a polynomial in s = v^2/2 - 1.

    Sinc-family kernels are even entire functions of v, hence analytic in
    u = v^2; a Chebyshev fit on u in [0, 4] evaluated in the centered
    variable s in [-1, 1] keeps every Horner intermediate O(1), so the
    f32 evaluation stays at the ~3e-7 rounding floor (a plain fit in u
    overflows to ~5e-5 through coefficient cancellation). Works for any
    real exponent n — the reference's integer-n table restriction
    (sph_kernel_tables.hpp:122-160) does not apply. Wendland C6 has odd
    powers of v (C^6 at the origin in u), so it gets a higher degree;
    its fit error is ~2e-6 (pinned by tests/test_kernels).
    """
    if degree == 0:
        degree = 13 if kind.startswith("sinc") else 19
    t = np.cos(np.linspace(0.0, np.pi, 4000))  # [-1, 1] chebyshev nodes
    u = 2.0 * (t + 1.0)  # [0, 4]
    w = _kernel_samples(np.sqrt(u), float(n), kind)
    cheb = np.polynomial.chebyshev.Chebyshev.fit(t, w, degree, domain=[-1, 1])
    coeffs = cheb.convert(kind=np.polynomial.Polynomial).coef
    return tuple(float(c) for c in coeffs)


def sinc_poly_coeffs(n: float, degree: int = 13) -> tuple:
    """Back-compat alias: the default sinc-family fit."""
    return kernel_poly_coeffs(n, "sinc", degree)


def sinc_poly_eval(u, coeffs):
    """Horner evaluation of a ``sinc_poly_coeffs`` fit from the SQUARED
    normalized distance u = (dist/h)^2: clamped to the support, floored at
    0 (the fit crosses ~-3e-7 in the flat tail near the support edge).
    SINGLE implementation shared by the XLA ops and the Pallas tile
    kernels so both paths compute identical W."""
    s = jnp.clip(u * 0.5 - 1.0, -1.0, 1.0)
    acc = jnp.full_like(s, coeffs[-1])
    for c in coeffs[-2::-1]:
        acc = acc * s + c
    return jnp.maximum(acc, 0.0)


def sinc_kernel_u(u, n: float = 6.0, kind: str = "sinc"):
    """W from the SQUARED normalized distance (polynomial form, see
    kernel_poly_coeffs; the name keeps the historical sinc default)."""
    return sinc_poly_eval(u, kernel_poly_coeffs(float(n), kind))


@functools.lru_cache(maxsize=None)
def kernel_dterh_coeffs(n: float, kind: str = "sinc", degree: int = 0) -> tuple:
    """Coefficients of dterh(v) = -(3 W + v dW/dv) in s = v^2/2 - 1.

    The h-derivative combination of ve_def_gradh_kern.hpp:58-66, derived
    ANALYTICALLY from the W fit: with W = p(s), v dW/dv = 2(s+1) p'(s),
    so dterh = -(3 p + 2(s+1) p') — exactly consistent with the W the
    pair ops evaluate (f32 error ~2e-6, and dterh(0) = -3 by
    construction)."""
    c = kernel_poly_coeffs(n, kind, degree)
    d = []
    for k in range(len(c)):
        v = (3.0 + 2.0 * k) * c[k]
        if k + 1 < len(c):
            v += 2.0 * (k + 1) * c[k + 1]
        d.append(-v)
    return tuple(d)


def dterh_poly_eval(u, coeffs):
    """Horner in s = u/2 - 1 WITHOUT the zero floor (dterh is negative
    inside the support). SINGLE evaluator shared by the XLA ops and the
    Pallas tile kernels (mirror of sinc_poly_eval)."""
    s = jnp.clip(u * 0.5 - 1.0, -1.0, 1.0)
    acc = jnp.full_like(s, coeffs[-1])
    for c in coeffs[-2::-1]:
        acc = acc * s + c
    return acc


def sinc_dterh_u(u, n: float = 6.0, kind: str = "sinc"):
    """dterh = -(3 W + v dW/dv) from the SQUARED normalized distance."""
    return dterh_poly_eval(u, kernel_dterh_coeffs(float(n), kind))


def sinc_kernel(v, n: float = 6.0):
    """W_n(v) = sinc(pi/2 * v)^n on v in [0, 2]; 0 outside.

    v is dist/h. Clamping to the support makes out-of-range j-side
    evaluations (h_j < h_i) return exactly 0.
    """
    v = jnp.clip(v, 0.0, SUPPORT)
    pv = (0.5 * jnp.pi) * v
    sinc = jnp.where(v > 0.0, jnp.sin(pv) / jnp.where(v > 0.0, pv, 1.0), 1.0)
    return sinc**n


def sinc_kernel_derivative(v, n: float = 6.0):
    """dW_n/dv = n * sinc^(n-1)(pi/2 v) * d sinc/dv; 0 at v=0 and v>=2."""
    v = jnp.clip(v, 0.0, SUPPORT)
    pv = (0.5 * jnp.pi) * v
    safe_pv = jnp.where(v > 0.0, pv, 1.0)
    sinc = jnp.where(v > 0.0, jnp.sin(pv) / safe_pv, 1.0)
    # d/dv sinc(pi/2 v) = sinc * (pi/2) * (cot(pv) - 1/pv)
    dsinc = sinc * (0.5 * jnp.pi) * (
        jnp.cos(pv) / jnp.where(v > 0.0, jnp.sin(pv), 1.0) - 1.0 / safe_pv
    )
    return jnp.where(v > 0.0, n * sinc ** (n - 1.0) * dsinc, 0.0)


def kernel_norm_3d(n: float = 6.0, kind: str = "sinc",
                   support: float = SUPPORT, num: int = 20001) -> float:
    """3D normalization K with ∫ K W(|x|/h) h^-3 d^3x = 1.

    Same quantity as the reference's kernel_3D_k (sph_kernel_tables.hpp:77-84),
    computed here with numpy float64 Simpson integration at config time.
    """
    if num % 2 == 0:
        num += 1  # composite Simpson needs an even interval count
    x = np.linspace(0.0, support, num)
    f = 4.0 * np.pi * x**2 * _kernel_samples(x, n, kind)
    dx = x[1] - x[0]
    integral = dx / 3.0 * (f[0] + f[-1] + 4.0 * f[1:-1:2].sum() + 2.0 * f[2:-1:2].sum())
    return float(1.0 / integral)


def artificial_viscosity(alpha_i, alpha_j, c_i, c_j, w_ij, beta: float = 2.0):
    """Monaghan signal-velocity artificial viscosity (kernels.hpp:60-84).

    w_ij is the pair velocity projected on the separation axis; only
    approaching pairs (w_ij < 0) dissipate.
    """
    v_signal = 0.25 * (alpha_i + alpha_j) * (c_i + c_j) - beta * w_ij
    return jnp.where(w_ij < 0.0, -v_signal * w_ij, 0.0)


def ts_k_courant(maxvsignal, h, c, k_cour):
    """Courant time step from the max signal velocity (kernels.hpp:9-16)."""
    v = jnp.where(maxvsignal > 0.0, maxvsignal, c)
    return k_cour * h / v


def update_h(ng0: int, nc, h):
    """Nudge h so the neighbor count drifts toward ng0 (kernels.hpp:18-32).

    nc includes the particle itself, like the reference's usage.
    """
    c0 = 1023.0
    return h * 0.5 * (1.0 + c0 * ng0 / jnp.maximum(nc, 1)) ** 0.1
