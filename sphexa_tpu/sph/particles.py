"""Particle state pytree and simulation constants.

TPU-native counterpart of the reference's ``sph/particles_data.hpp``: the
SoA field registry becomes a dataclass-of-arrays pytree (so the whole state
flows through jit/shard_map/checkpoint as one object), and the runtime
constants (particles_data.hpp:89-138) become a static, hashable config that
selects compiled code paths.

Instead of the reference's acquire/release field aliasing (which caps live
arrays by hand), transient fields (rho, c11.., divv, ...) are simply values
inside the jitted step function — XLA's buffer liveness analysis reuses
their memory automatically, which is the same optimization done by the
compiler instead of by hand.
"""

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from sphexa_tpu.dtypes import HYDRO_DTYPE
from sphexa_tpu.sph.kernels import kernel_norm_3d


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class ParticleState:
    """Conserved per-particle fields + integrator scalars.

    Mirrors the reference's *conserved* field list (the set written to
    checkpoints, propagator ConservedFields): positions, position deltas of
    the previous step (x_m1 ... stored as deltas, positions.hpp:66-80),
    velocities, smoothing length, mass, temperature, du_m1, AV alpha.
    Dependent fields (rho, p, c, IAD tensors, ...) are recomputed every step
    and live only inside the step function.
    """

    x: jax.Array
    y: jax.Array
    z: jax.Array
    x_m1: jax.Array
    y_m1: jax.Array
    z_m1: jax.Array
    vx: jax.Array
    vy: jax.Array
    vz: jax.Array
    h: jax.Array
    m: jax.Array
    temp: jax.Array
    # compensation carry of the energy update (two-sum): the true
    # internal energy is cv*(temp + temp_lo). The reference integrates u
    # in DOUBLE (positions.hpp:54-63 'double u_new'); on TPU the f32
    # accumulation would swallow increments below u*eps (~2e-3 relative
    # over 200 Sedov steps — the round-2/3 std drift), so the lost low
    # bits ride along explicitly. Physics reads temp (error <= 1 ulp);
    # conservation diagnostics add the carry back.
    temp_lo: jax.Array
    du: jax.Array
    du_m1: jax.Array
    alpha: jax.Array
    # integrator scalars (traced so steps don't recompile)
    ttot: jax.Array
    min_dt: jax.Array
    min_dt_m1: jax.Array

    @property
    def n(self) -> int:
        return self.x.shape[0]

    @staticmethod
    def zeros(n: int, dtype=HYDRO_DTYPE) -> "ParticleState":
        f = lambda: jnp.zeros(n, dtype)
        s = lambda v: jnp.asarray(v, dtype)
        return ParticleState(
            x=f(), y=f(), z=f(), x_m1=f(), y_m1=f(), z_m1=f(),
            vx=f(), vy=f(), vz=f(), h=f(), m=f(), temp=f(), temp_lo=f(),
            du=f(), du_m1=f(), alpha=f(),
            ttot=s(0.0), min_dt=s(1e-12), min_dt_m1=s(1e-12),
        )


# universal gas constant in cgs, as used by the reference (sph/eos.hpp:16)
R_GAS = 8.317e7


def ideal_gas_cv(mui: float, gamma: float) -> float:
    """Heat capacity for mean molecular weight mui (sph/eos.hpp:13-18)."""
    return R_GAS / mui / (gamma - 1.0)


@dataclasses.dataclass(frozen=True)
class SimConstants:
    """Static physics constants (particles_data.hpp:89-138 defaults)."""

    ng0: int = 100
    ngmax: int = 150
    k_cour: float = 0.2
    k_rho: float = 0.06
    gamma: float = 5.0 / 3.0
    mui: float = 10.0
    alphamin: float = 0.05
    alphamax: float = 1.0
    decay_constant: float = 0.2
    at_min: float = 0.1
    at_max: float = 0.2
    g: float = 0.0
    eps: float = 0.005
    eta_acc: float = 0.2
    max_dt_increase: float = 1.1
    sinc_index: float = 6.0
    # symmetric (min-h) pair cutoff on the momentum/energy ops: the
    # reference's gather search keeps pairs with 2h_j < d < 2h_i that j
    # never sees, so j never feels the reaction terms — the resulting
    # one-sided forces are the measured dt- and precision-INDEPENDENT
    # energy drift at shocks (scripts/probe_du_precision.py: the f64
    # closure Sum m(du + v.a) = -1.5e-5/step while f32-f64 differs by
    # 1e-9). Masking momentum/energy pairs with d < 2*min(h_i, h_j)
    # restores exact pairwise antisymmetry; the dropped half-pairs sit at
    # the support edge where W_i vanishes, so the force change is tiny.
    # (Deviation from momentum_energy_kern.hpp by design; Gadget-style
    # symmetrization. False = reference-parity one-sided cutoff.)
    sym_pairs: bool = True
    # kernel family (kernels.KERNEL_CHOICES; sph_kernel_tables.hpp:122-160)
    kernel_choice: str = "sinc"
    kernel_norm: Optional[float] = None  # filled by normalized()

    @property
    def ramp(self) -> float:
        return 1.0 / (self.at_max - self.at_min)

    @property
    def cv(self) -> float:
        return ideal_gas_cv(self.mui, self.gamma)

    @property
    def K(self) -> float:
        if self.kernel_norm is None:
            raise ValueError("use SimConstants.normalized() to fill kernel_norm")
        return self.kernel_norm

    def normalized(self) -> "SimConstants":
        """Return a copy with the kernel normalization constant computed."""
        if self.kernel_norm is not None:
            return self
        return dataclasses.replace(
            self, kernel_norm=kernel_norm_3d(self.sinc_index, self.kernel_choice)
        )
