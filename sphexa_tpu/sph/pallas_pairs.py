"""Pallas TPU engine for SPH pair interactions: stream candidate cells
through VMEM per target group.

TPU-native re-design of the hot j-loops following the reference's GPU
strategy (cstone/traversal/find_neighbors.cuh: 64-particle warp targets,
neighbors found on the fly inside each kernel, no stored lists) mapped to
the TPU memory system:

- targets are groups of G = 128 SFC-consecutive particles (one VMEM block);
- the group's candidate cells are found in a jax-side prologue
  (``group_cell_ranges``): the static ``window^3`` block of grid cells
  covering the group's search extent is CULLED by exact cell-AABB vs
  group-bbox distance and COMPACTED, so the kernel loops over only the
  ~dozen cells that can actually contain neighbors (the analog of the
  reference's per-warp tree traversal pruning, find_neighbors.cuh:45-82);
- every surviving cell's particles are CONTIGUOUS in the SFC-sorted
  arrays, and all the op's j-side fields are pre-packed into ONE
  interleaved (rows, nfields, 128) HBM buffer, so each cell is ONE
  dynamic-slice DMA into a VMEM ring buffer regardless of how many fields
  the op consumes — no XLA gathers anywhere, no per-field DMA storms;
- the pair physics runs chunk-by-chunk on (G, 128) tiles on the VPU while
  the next cell's DMA is in flight (double buffering); the number of
  128-wide chunks per cell is dynamic (ceil(len/128)), so padded cap
  slack costs no FLOPs;
- periodic images are handled by a per-cell precomputed shift (each
  window cell corresponds to exactly one box image), replacing per-pair
  minimum-image folds;
- each op instantiates the shared engine with its own per-pair math and
  accumulators, fusing neighbor search INTO the op (the reference GPU
  does exactly this, SURVEY.md §2 'neighbors recomputed on the fly').

The XLA gather-based path (neighbors/cell_list.py + the ops' j-loops)
remains the portable fallback; this engine is used on TPU where the
gather rate, not FLOPs, limits throughput.
"""

import functools
from typing import Any, Callable, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from sphexa_tpu.dtypes import KEY_BITS, KEY_DTYPE
from sphexa_tpu.neighbors.cell_list import NeighborConfig, _window_offsets
from sphexa_tpu.sfc.box import BoundaryType, Box
from sphexa_tpu.util.phases import named_phase
from sphexa_tpu.sfc.hilbert import hilbert_encode
from sphexa_tpu.sfc.morton import morton_encode
from sphexa_tpu.sph.kernels import (
    dterh_poly_eval,
    kernel_dterh_coeffs,
    kernel_poly_coeffs,
    sinc_poly_eval,
)

GROUP = 128  # default targets per group (NeighborConfig.group overrides)

# chunks processed per inner-loop trip: 2 = the pair math runs on (G, 256)
# tiles (two 128-lane chunks). MEASURED SLOWER on v5e (467 vs 410 ms for
# the std Sedov 100^3 pipeline): the per-field lane concats cost more than
# the halved loop overhead saves — the per-chunk overhead is accumulator
# read-modify-write + field loads, which pairing cannot reduce. Kept for
# future hardware; configured via NeighborConfig.chunk_pair (0 = take the
# SPHEXA_CHUNK_PAIR env default, read at engine build so late env changes
# take effect). (docs/NEXT.md round-4 notes.)
import os as _os


def _chunk_pair(cfg) -> int:
    cp = getattr(cfg, "chunk_pair", 0)
    if not cp:
        cp = int(_os.environ.get("SPHEXA_CHUNK_PAIR", "1"))
    return max(1, cp)


class PairGeom(NamedTuple):
    """Per-(target, candidate) geometry handed to the pair body."""

    rx: jax.Array     # (G, 128) x_i - x_j, image-resolved
    ry: jax.Array
    rz: jax.Array
    d2: jax.Array     # squared distance
    mask: jax.Array   # valid pair: in-range candidate, within 2h_i, not self


class GroupRanges(NamedTuple):
    """Compacted candidate-cell lists of every target group (the engine's
    shared prologue output; one per step, consumed by all pair ops)."""

    starts: jax.Array     # (NG, W3) int32 — sorted-array offset of cell w
    lens: jax.Array       # (NG, W3) int32 — particles in cell w (<= cap)
    shift_x: jax.Array    # (NG, W3) f32 — periodic image offset of cell w
    shift_y: jax.Array
    shift_z: jax.Array
    ncells: jax.Array     # (NG,) int32 — cells surviving the cull
    occupancy: jax.Array  # () int32 — cap/window overflow diagnostic
    boxl: jax.Array       # (3,) f32 — fold periods (1e30 on open dims);
    # consumed only when the engine runs in fold mode (see engine_fold)

    @property
    def num_groups(self) -> int:
        return self.starts.shape[0]


def engine_fold(box: Box, cfg: NeighborConfig) -> bool:
    """Static choice of the kernel's periodic-image strategy.

    Per-cell shifts are exact when every needed cell *instance* fits in
    the window (guaranteed by the window_ok guard whenever
    window < ncell). When the window spans the whole grid — the tiny-grid
    escape hatch where window_ok is forced true — a single instance per
    wrapped cell cannot represent both images a target may need, so the
    kernel must fall back to the per-pair minimum-image fold (and the
    prologue must not distance-cull cells, since the kept instance's AABB
    says nothing about its other image)."""
    any_periodic = any(b == BoundaryType.periodic for b in box.boundaries)
    return any_periodic and cfg.window >= (1 << cfg.level)


@named_phase("neighbors")
def group_cell_ranges(
    x, y, z, h, sorted_keys, box: Box, cfg: NeighborConfig,
    table=None, radius_pad=0.0,
) -> GroupRanges:
    """Candidate cells of every group, culled and compacted.

    Vectorized over all groups (the jax-side prologue all pair ops
    share). A window cell survives when it (a) exists (periodic images
    de-aliased, open-boundary cells inside the grid), (b) is non-empty,
    and (c) its AABB intersects the group's bbox inflated by the group's
    search radius 2*max(h). Survivors are compacted to the front so the
    kernel's cell loop trips only ``ncells`` times. ``occupancy`` encodes
    the cap AND window guards exactly like find_neighbors.

    ``table``: optional externally built cell-starts table of the
    level-``cfg.level`` grid, (ncell^3 + 1,) int32 of sorted-array
    offsets. Under shard_map the table is GLOBAL (psum of per-shard cid
    histograms, parallel/exchange.py) while x/y/z/h are the local slab:
    the returned ranges are then global rows of the distributed array.
    When given, ``sorted_keys`` may be None (the deep-grid searchsorted
    fallback needs keys and is unavailable).
    """
    n = x.shape[0]
    level = cfg.level
    shift = KEY_DTYPE(3 * (KEY_BITS - level))
    ncell = 1 << level
    encode = hilbert_encode if cfg.curve == "hilbert" else morton_encode
    edge = box.lengths / ncell
    periodic = box.periodic_mask

    g = cfg.group
    num_groups = -(-n // g)
    pad = num_groups * g - n
    gather_pad = lambda a: jnp.concatenate([a, jnp.broadcast_to(a[-1:], (pad,))]) if pad else a
    xg = gather_pad(x).reshape(num_groups, g)
    yg = gather_pad(y).reshape(num_groups, g)
    zg = gather_pad(z).reshape(num_groups, g)
    hg = gather_pad(h).reshape(num_groups, g)

    lo = jnp.stack([xg.min(1), yg.min(1), zg.min(1)], axis=1)  # (NG, 3)
    hi = jnp.stack([xg.max(1), yg.max(1), zg.max(1)], axis=1)
    # radius_pad: extra coverage slack (the list-build skin) so candidate
    # runs stay valid while particles drift between list rebuilds
    radius = 2.0 * hg.max(1) + radius_pad  # (NG,)
    box_lo = jnp.stack([box.lo[0], box.lo[1], box.lo[2]])
    base = jnp.floor((lo - radius[:, None] - box_lo) / edge).astype(jnp.int32)
    need = jnp.floor((hi + radius[:, None] - box_lo) / edge).astype(jnp.int32)
    # open dims: cells outside [0, ncell) don't exist — slide the window
    # inside the grid (never loses coverage); a window spanning the whole
    # grid always covers
    base = jnp.where(
        periodic[None, :], base,
        jnp.clip(base, 0, max(0, ncell - cfg.window)),
    )
    need_eff = jnp.where(periodic[None, :], need, jnp.minimum(need, ncell - 1))
    window_ok = jnp.all((need_eff - base + 1 <= cfg.window) | (cfg.window >= ncell))

    offsets = jnp.asarray(_window_offsets(cfg.window))  # (W3, 3)
    cells = base[:, None, :] + offsets[None, :, :]  # (NG, W3, 3) unwrapped
    wrapped = jnp.mod(cells, ncell)
    in_range = (cells >= 0) & (cells < ncell)
    unique = offsets[None, :, :] < ncell
    cell_ok = jnp.all(
        jnp.where(periodic[None, None, :], unique, in_range), axis=-1
    )  # (NG, W3)
    lookup = jnp.where(
        periodic[None, None, :], wrapped, jnp.clip(cells, 0, ncell - 1)
    )

    ckey = encode(
        lookup[..., 0].astype(KEY_DTYPE),
        lookup[..., 1].astype(KEY_DTYPE),
        lookup[..., 2].astype(KEY_DTYPE),
        bits=level,
    )
    if table is not None or ncell**3 <= 4 * max(n, 1024):
        # ONE cell-starts table for the whole grid, then per-(group, cell)
        # range lookups are gathers from it — a binary search per window
        # cell into the N-element u64 key array costs ~20 emulated-u64
        # gathers each and dominated the prologue
        if table is None:
            cid = (sorted_keys >> shift).astype(jnp.int32)  # ascending
            table = jnp.searchsorted(
                cid, jnp.arange(ncell**3 + 1, dtype=jnp.int32)
            ).astype(jnp.int32)
        ck32 = ckey.astype(jnp.int32)
        start = table[ck32]
        end = table[ck32 + 1]
    else:
        # deep grids (possible when a caller bypasses the occupancy-driven
        # level heuristic): the table would be O(8^level) — search instead
        start = jnp.searchsorted(sorted_keys, ckey << shift).astype(jnp.int32)
        end = jnp.searchsorted(
            sorted_keys, (ckey + KEY_DTYPE(1)) << shift
        ).astype(jnp.int32)
    raw_len = end - start
    lens = jnp.where(cell_ok, jnp.minimum(raw_len, cfg.cap), 0)

    if engine_fold(box, cfg):
        # tiny-grid fallback: the kernel min-image-folds every pair, so
        # image-position culling is meaningless — keep all non-empty cells
        keep = cell_ok & (lens > 0)
        shifts = jnp.zeros(cells.shape, jnp.float32)
    else:
        # cull: drop cells whose AABB (at their image position) cannot
        # contain any neighbor of the group — exact box-vs-box distance
        # test against the group bbox inflated by its search radius
        cell_lo = (
            box_lo[None, None, :] + cells.astype(jnp.float32) * edge[None, None, :]
        )
        cell_hi = cell_lo + edge[None, None, :]
        r = radius[:, None, None]
        overlap = jnp.all(
            (cell_hi >= lo[:, None, :] - r) & (cell_lo <= hi[:, None, :] + r),
            axis=-1,
        )  # (NG, W3)
        keep = cell_ok & overlap & (lens > 0)

        # each window cell corresponds to exactly ONE box image: its offset
        # resolves periodicity for every pair in the cell (no per-pair fold)
        img = jnp.floor_divide(cells, ncell).astype(jnp.float32)  # (NG, W3, 3)
        shifts = img * box.lengths[None, None, :]

    if cfg.run_cap > 0:
        # merge SFC-adjacent survivors into long streamed runs (fewer,
        # fuller chunks; see _merge_runs)
        starts_c, lens_c, sh, ncells = _merge_runs(
            start, lens, keep, shifts, cfg.run_cap, cfg.gap
        )
    else:
        # compact survivors to the front (stable: preserves SFC cell order)
        _, kc_i, starts_c, lens_s, shx_c, shy_c, shz_c = jax.lax.sort(
            ((~keep).astype(jnp.int32), keep.astype(jnp.int32), start, lens,
             shifts[..., 0], shifts[..., 1], shifts[..., 2]),
            num_keys=1, dimension=1, is_stable=True,
        )
        keep_c = kc_i.astype(bool)
        lens_c = jnp.where(keep_c, lens_s, 0)
        # dead slots DMA row 0 harmlessly (len 0 masks every pair)
        starts_c = jnp.where(keep_c, starts_c, 0)
        sh = [jnp.where(keep_c, a, 0.0) for a in (shx_c, shy_c, shz_c)]
        ncells = jnp.sum(keep, axis=1).astype(jnp.int32)

    # cap overflow only matters for cells the kernel will visit: a culled
    # cell's clipped length truncates nothing
    occupancy = jnp.where(
        window_ok,
        jnp.max(jnp.where(keep, raw_len, 0)),
        jnp.int32(cfg.cap + 1),
    )

    # fold periods: open dims get an effectively-infinite period so the
    # fold is a no-op there (only consumed in fold mode)
    boxl = jnp.where(box.periodic_mask, box.lengths, jnp.float32(1e30))

    return GroupRanges(
        starts=starts_c, lens=lens_c,
        shift_x=sh[0], shift_y=sh[1], shift_z=sh[2],
        ncells=ncells, occupancy=occupancy, boxl=boxl.astype(jnp.float32),
    )


def _merge_runs(start, lens, keep, shifts, run_cap: int, gap: int):
    """Merge kept cells into contiguous streamed RUNS per group.

    The SFC sort makes spatially adjacent cells often key-adjacent, so
    their sorted-array ranges concatenate; merging them (and bridging
    key gaps of up to ``gap`` slots) turns many short cell DMAs with
    mostly-padded 128-lane chunks into few long runs with full chunks.
    Gap slots are pure bounded waste-work, never spurious physics: a gap
    particle belongs to a culled or out-of-window cell, and any such
    cell's AABB — at the single image position the window block can
    contain (window < ncell) — lies outside the group's inflated search
    bbox, so the particle cannot pass the distance mask under the run's
    shift; in fold mode (window >= ncell) every non-empty cell is kept,
    so gaps contain no particles at all. Runs never span different box
    images and are clipped to ``run_cap`` slots (the engine's static DMA
    window, NeighborConfig.dma_cap).

    Returns (starts, lens, [shift_x, shift_y, shift_z], nruns), shaped
    like the unmerged compaction.
    """
    ng, w3 = start.shape
    INF = jnp.int32(2**30)
    # variadic sort carries every payload through the sorting network —
    # argsort + take_along_axis would pay ~6 full-array gathers instead
    _, s, l, ki, shx, shy, shz = jax.lax.sort(
        (jnp.where(keep, start, INF), start, lens, keep.astype(jnp.int32),
         shifts[..., 0], shifts[..., 1], shifts[..., 2]),
        num_keys=1, dimension=1,
    )
    k = ki.astype(bool)
    # unkept tail entries must not extend any run's end
    end_eff = jnp.where(k, s + l, -1)

    # forward scan: mark run heads (kept cells that cannot join the
    # running span: image mismatch, gap too wide, or span over run_cap)
    def fstep(carry, inp):
        run_start, prev_end, px, py, pz = carry
        s_w, l_w, k_w, sx, sy, sz = inp
        same = (sx == px) & (sy == py) & (sz == pz)
        join = (
            k_w & same
            & (s_w - prev_end <= gap)
            & (s_w + l_w - run_start <= run_cap)
        )
        new_start = jnp.where(join, run_start, s_w)
        carry = (
            jnp.where(k_w, new_start, run_start),
            jnp.where(k_w, s_w + l_w, prev_end),
            jnp.where(k_w, sx, px),
            jnp.where(k_w, sy, py),
            jnp.where(k_w, sz, pz),
        )
        return carry, k_w & ~join

    # inits derived from the inputs so their varying-manual-axes match
    # under shard_map (a plain jnp.zeros carry is rejected by check_vma)
    init = (
        jnp.zeros_like(s[:, 0]),
        jnp.full_like(s[:, 0], -INF),
        jnp.zeros_like(shx[:, 0]),
        jnp.zeros_like(shy[:, 0]),
        jnp.zeros_like(shz[:, 0]),
    )
    xs = tuple(a.T for a in (s, l, k, shx, shy, shz))
    _, is_head_t = jax.lax.scan(fstep, init, xs)
    is_head = is_head_t.T  # (ng, w3)

    # reverse scan: each run head's END = max cell end before the next head
    head_next = jnp.concatenate(
        [is_head[:, 1:], jnp.ones((ng, 1), bool)], axis=1
    )
    def rstep(carry, inp):
        e_w, hn_w = inp
        r = jnp.maximum(e_w, jnp.where(hn_w, jnp.int32(-1), carry))
        return r, r

    xs_r = (end_eff[:, ::-1].T, head_next[:, ::-1].T)
    _, r_t = jax.lax.scan(rstep, jnp.full_like(end_eff[:, 0], -1), xs_r)
    run_end = r_t.T[:, ::-1]

    # compact heads to the front (stable: preserves key order)
    _, hk_i, hs_r, hlen, cshx, cshy, cshz = jax.lax.sort(
        ((~is_head).astype(jnp.int32), is_head.astype(jnp.int32), s,
         run_end - s, shx, shy, shz),
        num_keys=1, dimension=1, is_stable=True,
    )
    hk = hk_i.astype(bool)
    hs = jnp.where(hk, hs_r, 0)
    hl = jnp.where(hk, hlen, 0)
    sh = [jnp.where(hk, a, 0.0) for a in (cshx, cshy, cshz)]
    nruns = jnp.sum(is_head, axis=1).astype(jnp.int32)
    return hs, hl, sh, nruns


def _round_up(v: int, q: int) -> int:
    return -(-v // q) * q


def _dma_rows(cap: int) -> int:
    """Rows of 128 covering any cell range [s, s+len<=cap): the range
    starts at lane offset s%128 inside row s//128 and extends at most
    127+cap slots, i.e. ceil((127+cap)/128) rows. SINGLE source of truth —
    the kernel's transfer shape and pack_j_fields' tail padding must
    agree or the DMA reads out of bounds."""
    return -(-(127 + cap) // 128)


def pack_j_fields(fields: Sequence[jax.Array], cap: int,
                  nf_min: int = 0) -> jax.Array:
    """Interleave the j-side fields into one (rows, nf_pad, 128) HBM
    buffer: slot j of field f lives at [j // 128, f, j % 128], so one
    dynamic row-slice DMA fetches EVERY field of a candidate cell.
    The tail is padded by a full DMA window so a range starting at the
    last particle still reads in-bounds garbage (masked); nf is padded
    to the f32 sublane quantum. ``nf_min``: minimum field rows (the
    list-walk engine stages one extra in-kernel row for the candidate's
    global index)."""
    n = fields[0].shape[0]
    nf = len(fields)
    nf_pad = _round_up(max(nf, nf_min), 8)
    rows = -(-n // 128) + _dma_rows(cap)
    flat = jnp.zeros((nf_pad, rows * 128), jnp.float32)
    flat = flat.at[:nf, :n].set(jnp.stack(fields))
    return flat.reshape(nf_pad, rows, 128).transpose(1, 0, 2)


def chunk_aabb_table(x, y, z, cap: int) -> jax.Array:
    """Per-128-chunk bounding boxes of the sorted coordinate arrays,
    (rows, 128) f32 rows [lo_x, lo_y, lo_z, hi_x, hi_y, hi_z, 0...] — the
    engine's chunk-cull input (row r covers sorted slots [128r, 128r+128)).
    lane-padded to 128. Rows match pack_j_fields' padded row count; tail
    rows get an empty (inverted) box so they never pass the cull."""
    n = x.shape[0]
    rows_n = -(-n // 128)
    rows = rows_n + _dma_rows(cap)
    pad = rows_n * 128 - n
    def padded(a, fill):
        a = jnp.concatenate([a, jnp.full((pad,), fill, a.dtype)]) if pad else a
        return a.reshape(rows_n, 128)
    BIG = jnp.float32(1e30)
    lo = [jnp.min(padded(a, BIG), axis=1) for a in (x, y, z)]
    hi = [jnp.max(padded(a, -BIG), axis=1) for a in (x, y, z)]
    tbl = jnp.stack(lo + hi, axis=1)  # (rows_n, 6)
    tail = jnp.tile(
        jnp.asarray([[BIG, BIG, BIG, -BIG, -BIG, -BIG]], jnp.float32),
        (rows - rows_n, 1),
    )
    tbl = jnp.concatenate([tbl, tail], axis=0)
    # minor dim padded to the 128-lane tile (Mosaic DMAs cannot slice a
    # narrower HBM minor dimension)
    return jnp.pad(tbl, ((0, 0), (0, 122)))


def pallas_interpret() -> bool:
    """Run Mosaic kernels in interpret mode off-TPU (single policy for
    every engine consumer — SPH ops, gravity, analysis)."""
    return jax.default_backend() != "tpu"


def group_pair_engine(
    pair_body: Callable,
    finalize: Callable,
    num_i: int,
    num_j: int,
    num_acc: int,
    cfg: NeighborConfig,
    fold: bool = False,
    interpret: bool = False,
    pair_cutoff: bool = True,
    chunk_skip: Optional[bool] = None,
    want_nc: bool = True,
    sym_jf: Optional[int] = None,
    skip_slots: int = 0,
):
    """Build a pallas_call for one SPH pair op.

    - ``pair_body(geom, i_fields, j_fields, accs) -> accs``: per-chunk pair
      math on (G, 128) tiles; i_fields are (G, 1) columns, j_fields are
      (1, 128) rows; accs is a tuple of (G, 128) f32 LANE-WISE partial
      accumulators — the body adds/maxes elementwise and must NOT reduce
      (cross-lane reductions inside the chunk loop cost more than the pair
      math; the epilogue reduces once).
    - ``finalize(i_fields, accs, nc) -> outs``: per-target epilogue; accs
      arrive unreduced (G, 128), nc is the reduced (G, 1) neighbor count;
      outs is a tuple of (G,) arrays (f32), one per output.
    - ``num_i``/``num_j``: how many target/candidate fields the op reads
      (x, y, z are always fields 0-2 on both sides; h is i-field 3).
    - ``pair_cutoff``: include the d2 < (2 h_i)^2 support test in the
      pair mask (SPH); gravity's near field keeps every ranged pair.
    - ``sym_jf``: j-field index of inv_h2j; when set the mask ALSO
      requires d2 < (2 h_j)^2 — the min-h symmetric cutoff that makes
      the momentum/energy pairing exactly antisymmetric (SimConstants
      .sym_pairs rationale; a strict subset of the i-cutoff, so the
      prologue's candidate coverage is unaffected).
    - ``chunk_skip``: cull whole 128-candidate chunks whose bbox misses
      the group's inflated bbox (defaults to ``pair_cutoff and not
      fold``); only meaningful for cutoff ops — gravity's near field has
      no distance cutoff, so every chunk contributes.
    - ``want_nc``: accumulate per-target neighbor counts (the trailing
      output). Ops that ignore the counts pass False and save the
      count's read-modify-write in every chunk.
    - ``skip_slots``: when > 0, the call takes a PairLists whose per-chunk
      counts (sph/pair_lists.py mark bits) gate each chunk's math — the
      AABB chunk-cull for free (no AABB table, no in-kernel bbox math),
      available to every op while lists are valid. Requires CW == 1 and
      excludes ``chunk_skip``.
    - returns fn(ranges, i_fields(NG,G) x num_i, j_packed, i_offset,
      allow_self) -> (outs (NG, G) x num_out, nc (NG, G)); ``allow_self``
      (traced bool) admits the self-index pair — replica-image passes of
      periodic gravity need it.
    """
    R = _dma_rows(cfg.dma_cap)
    nf_pad = _round_up(num_j, 8)
    CW = _chunk_pair(cfg)  # chunks per inner-loop trip
    LW = 128 * CW            # lane width of the pair-math tiles
    SKIP = skip_slots > 0
    if SKIP:
        if CW != 1:
            raise ValueError("skip_slots requires chunk_pair == 1")
        chunk_skip = False
    if chunk_skip is None:
        # bitmask bits live in one int32, so the DMA window must fit 31
        # chunks; beyond that (huge run_cap) the cull is simply skipped
        chunk_skip = pair_cutoff and not fold and R <= 31
    elif chunk_skip and R > 31:
        raise ValueError(
            f"chunk_skip needs a DMA window of <= 31 chunks (got {R}); "
            "the per-run cull verdicts are bits of one int32"
        )

    def kernel(*refs):
        starts, lens, shx_r, shy_r, shz_r, ncells, boxl, ioff, aself = refs[:9]
        base = 10 if SKIP else 9
        cnt_r = refs[9] if SKIP else None
        i_refs = refs[base : base + num_i]
        jref = refs[base + num_i]
        nj_in = base + 2 + num_i if chunk_skip else base + 1 + num_i
        aabb_ref = refs[base + 1 + num_i] if chunk_skip else None
        out_refs = refs[nj_in : -2]
        nc_ref = refs[-2]
        (buf, sems, acc_refs, ncacc_ref, abuf, asems) = refs[-1]

        gi = pl.program_id(0)
        G = cfg.group

        nc_g = ncells[0, 0, 0]

        def dma(w, slot):
            row_s = starts[0, 0, w] // 128
            # dst slices off the CW-1 tail pad rows (uninitialized garbage
            # the odd-tail paired read may touch — every accumulation is
            # mask-selected, so garbage never reaches an output)
            return pltpu.make_async_copy(
                jref.at[pl.ds(row_s, R), :, :],
                buf.at[slot, pl.ds(0, R)], sems.at[slot]
            )

        def dma_aabb(w, slot):
            row_s = starts[0, 0, w] // 128
            return pltpu.make_async_copy(
                aabb_ref.at[pl.ds(row_s, R), :], abuf.at[slot], asems.at[slot]
            )

        @pl.when(nc_g > 0)
        def _():
            dma(0, 0).start()
            if chunk_skip:
                dma_aabb(0, 0).start()

        i_fields = [r[0, 0][:, None] for r in i_refs]  # (G, 1) each
        xi, yi, zi, hi = i_fields[:4]
        # group bbox inflated by the search radius, for the per-chunk cull
        # (recomputed from the i-fields already in VMEM — no new inputs);
        # matches the prologue's cell cull exactly: radius = 2 * max h_i
        if chunk_skip:
            g_r = 2.0 * jnp.max(hi)
            g_lo = (jnp.min(xi) - g_r, jnp.min(yi) - g_r, jnp.min(zi) - g_r)
            g_hi = (jnp.max(xi) + g_r, jnp.max(yi) + g_r, jnp.max(zi) + g_r)
        # global index of the first target: shard offset + group offset
        # (candidate indices are GLOBAL sorted-array positions, so the
        # self-pair test must compare in global index space)
        tgt_idx = (
            ioff[0, 0, 0] + gi * G
            + jax.lax.broadcasted_iota(jnp.int32, (G, 1), 0)
        )
        lane = jax.lax.broadcasted_iota(jnp.int32, (1, LW), 1)
        h4 = 4.0 * hi * hi
        lx, ly, lz = boxl[0, 0, 0], boxl[0, 0, 1], boxl[0, 0, 2]

        def cell_body(w, carry):
            slot = w % 2

            @pl.when(w + 1 < nc_g)
            def _():
                dma(w + 1, 1 - slot).start()
                if chunk_skip:
                    dma_aabb(w + 1, 1 - slot).start()

            dma(w, slot).wait()

            s = starts[0, 0, w]
            ln = lens[0, 0, w]
            shx = shx_r[0, 0, w]
            shy = shy_r[0, 0, w]
            shz = shz_r[0, 0, w]
            row0 = s // 128
            off = s - row0 * 128
            nch = (off + ln + 127) // 128

            if chunk_skip:
                # once-per-run chunk cull: compare every chunk's AABB row
                # (DMAed alongside the j-fields) against the group's
                # inflated bbox, pack the verdicts into ONE scalar bitmask;
                # the chunk loop then tests a single bit per chunk instead
                # of paying cross-lane reductions on the candidate data
                dma_aabb(w, slot).wait()
                ab = abuf[slot]  # (R, 128)
                hit_rows = (
                    (ab[:, 3:4] + shx >= g_lo[0]) & (ab[:, 0:1] + shx <= g_hi[0])
                    & (ab[:, 4:5] + shy >= g_lo[1]) & (ab[:, 1:2] + shy <= g_hi[1])
                    & (ab[:, 5:6] + shz >= g_lo[2]) & (ab[:, 2:3] + shz <= g_hi[2])
                )  # (R, 1)
                pow2 = jnp.left_shift(
                    jnp.int32(1),
                    jax.lax.broadcasted_iota(jnp.int32, (R, 1), 0),
                )
                # AABB rows beyond the run's nch describe the NEXT run's
                # rows — mask them so a paired trip (CW > 1) whose tail
                # chunk is past the run never fires on a stale verdict
                in_run = jax.lax.broadcasted_iota(
                    jnp.int32, (R, 1), 0) < nch
                bits = jnp.sum(jnp.where(hit_rows & in_run, pow2, 0))

            def chunk_math(t):
                # one trip covers CW consecutive 128-lane chunks: the pair
                # math runs on (G, 128*CW) tiles, amortizing the per-trip
                # scalar/loop overhead over CW chunks
                c = t * CW
                parts = [buf[slot, c + k] for k in range(CW)]  # (nf_pad, 128)
                if CW == 1:
                    j_fields = [parts[0][f][None, :] for f in range(num_j)]
                else:
                    j_fields = [
                        jnp.concatenate(
                            [p[f][None, :] for p in parts], axis=1
                        )
                        for f in range(num_j)
                    ]
                if fold:
                    # tiny-grid path: shifts are all zero, fold per pair
                    jx, jy, jz = j_fields[0], j_fields[1], j_fields[2]
                    rx = xi - jx
                    ry = yi - jy
                    rz = zi - jz
                    rx = rx - lx * jnp.round(rx / lx)
                    ry = ry - ly * jnp.round(ry / ly)
                    rz = rz - lz * jnp.round(rz / lz)
                else:
                    jx = j_fields[0] + shx
                    jy = j_fields[1] + shy
                    jz = j_fields[2] + shz
                    rx = xi - jx
                    ry = yi - jy
                    rz = zi - jz
                d2 = rx * rx + ry * ry + rz * rz
                cand = (row0 + c) * 128 + lane
                mask = (cand >= s) & (cand < s + ln)
                if pair_cutoff:
                    mask = mask & (d2 < h4)
                if sym_jf is not None:
                    mask = mask & (d2 * j_fields[sym_jf] < 4.0)
                mask = mask & ((cand != tgt_idx) | (aself[0, 0, 0] != 0))
                geom = PairGeom(rx=rx, ry=ry, rz=rz, d2=d2, mask=mask)
                # accumulators live in VMEM scratch (read-modify-write):
                # a skipped chunk touches nothing, and the fori carries
                # stay scalar so Mosaic never spills vector loop state
                accs = tuple(r[...] for r in acc_refs)
                accs = pair_body(geom, i_fields, j_fields, accs)
                for r, a in zip(acc_refs, accs):
                    r[...] = a
                if want_nc:
                    ncacc_ref[...] = ncacc_ref[...] + mask.astype(jnp.int32)

            def chunk_body(t, carry2):
                if SKIP:
                    # persistent-list mark bits: a chunk with no lane in
                    # the group's inflated bbox skips its math for one
                    # SMEM test (the AABB cull with zero DMA cost)
                    @pl.when(cnt_r[0, 0, carry2 + t] > 0)
                    def _():
                        chunk_math(t)

                    return carry2
                if not chunk_skip:
                    chunk_math(t)
                    return carry2

                # the trip's AABB verdict is CW bits of the run's bitmask —
                # skipping the whole (G, 128*CW) tile's pair math for
                # gap-bridged / overshoot chunks costs one scalar test
                @pl.when(
                    (jax.lax.shift_right_logical(bits, t * CW)
                     & ((1 << CW) - 1)) != 0
                )
                def _():
                    chunk_math(t)

                return carry2

            ntrip = (nch + CW - 1) // CW
            slot_base = jax.lax.fori_loop(0, ntrip, chunk_body, carry)
            return slot_base + nch if SKIP else slot_base

        if CW > 1:
            # zero the pad rows the odd-tail paired read may touch:
            # uninitialized VMEM can hold inf/NaN bit patterns, and bodies
            # may multiply a mask-zeroed factor by raw geometry (0*inf=NaN)
            for s_ in range(2):
                for k_ in range(CW - 1):
                    buf[s_, R + k_] = jnp.zeros((nf_pad, 128), jnp.float32)
        for r in acc_refs:
            r[...] = jnp.zeros((G, LW), jnp.float32)
        ncacc_ref[...] = jnp.zeros((G, LW), jnp.int32)
        jax.lax.fori_loop(0, nc_g, cell_body, 0)
        accs = tuple(r[...] for r in acc_refs)

        nc_acc = jnp.sum(ncacc_ref[...], axis=1, keepdims=True)
        outs = finalize(i_fields, accs, nc_acc)
        for r, o in zip(out_refs, outs):
            r[0, 0] = o.reshape(G)
        nc_ref[0, 0] = nc_acc.reshape(G)

    def scalar_kernel(*refs):
        # scratch unpack shim: keep kernel() readable
        # buf, sems, accs x num_acc, nc[, aabb buf, aabb sems]
        ns = num_acc + (5 if chunk_skip else 3)
        buf, sems = refs[-ns], refs[-ns + 1]
        if chunk_skip:
            acc_refs = refs[-ns + 2 : -3]
            kernel(*refs[:-ns],
                   (buf, sems, acc_refs, refs[-3], refs[-2], refs[-1]))
        else:
            acc_refs = refs[-ns + 2 : -1]
            kernel(*refs[:-ns], (buf, sems, acc_refs, refs[-1], None, None))

    def call(ranges: GroupRanges, i_fields: Sequence, j_packed,
             i_offset=0, allow_self=False, aabb=None, skip=None):
        if chunk_skip and aabb is None:
            raise ValueError("chunk_skip engine needs the chunk AABB table")
        if SKIP and skip is None:
            raise ValueError("skip_slots engine needs the PairLists")
        num_groups = ranges.num_groups
        # run-slot width comes from the ranges themselves: the sharded
        # path appends boundary-split slots beyond the window block
        w3 = ranges.starts.shape[1]
        ioff = jnp.asarray(i_offset, jnp.int32).reshape(1, 1, 1)
        aself = jnp.asarray(allow_self, jnp.int32).reshape(1, 1, 1)
        smem3 = lambda a: a.reshape(num_groups, 1, w3)
        starts = smem3(ranges.starts)
        lens = smem3(ranges.lens)
        shx = smem3(ranges.shift_x)
        shy = smem3(ranges.shift_y)
        shz = smem3(ranges.shift_z)
        ncells = ranges.ncells.reshape(num_groups, 1, 1)
        boxl = ranges.boxl.reshape(1, 1, 3)
        G = cfg.group
        i_fields = [a.reshape(num_groups, 1, G) for a in i_fields]
        num_out_arrays = len(
            finalize(
                [jnp.zeros((G, 1))] * num_i,
                tuple(jnp.zeros((G, 1)) for _ in range(num_acc)),
                jnp.zeros((G, 1), jnp.int32),
            )
        )
        smem_spec = lambda shape: pl.BlockSpec(
            shape, lambda g: (g, 0, 0), memory_space=pltpu.SMEM
        )
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=0,
            grid=(num_groups,),
            in_specs=[
                smem_spec((1, 1, w3)),  # starts
                smem_spec((1, 1, w3)),  # lens
                smem_spec((1, 1, w3)),  # shift x/y/z
                smem_spec((1, 1, w3)),
                smem_spec((1, 1, w3)),
                smem_spec((1, 1, 1)),   # ncells
                pl.BlockSpec((1, 1, 3), lambda g: (0, 0, 0),
                             memory_space=pltpu.SMEM),  # boxl
                pl.BlockSpec((1, 1, 1), lambda g: (0, 0, 0),
                             memory_space=pltpu.SMEM),  # i_offset
                pl.BlockSpec((1, 1, 1), lambda g: (0, 0, 0),
                             memory_space=pltpu.SMEM),  # allow_self
            ]
            + ([smem_spec((1, 1, skip_slots))] if SKIP else [])  # cnt
            + [
                pl.BlockSpec((1, 1, G), lambda g: (g, 0, 0))
                for _ in range(num_i)
            ]
            + [pl.BlockSpec(memory_space=pl.ANY)]
            + ([pl.BlockSpec(memory_space=pl.ANY)] if chunk_skip else []),
            out_specs=[
                pl.BlockSpec((1, 1, G), lambda g: (g, 0, 0))
                for _ in range(num_out_arrays)
            ]
            + [pl.BlockSpec((1, 1, G), lambda g: (g, 0, 0))],
            scratch_shapes=[
                # CW-1 pad rows absorb the paired read's odd-run tail
                pltpu.VMEM((2, R + CW - 1, nf_pad, 128), jnp.float32),
                pltpu.SemaphoreType.DMA((2,)),
            ]
            + [pltpu.VMEM((G, LW), jnp.float32) for _ in range(num_acc)]
            + [pltpu.VMEM((G, LW), jnp.int32)]
            + (
                [pltpu.VMEM((2, R, 128), jnp.float32),
                 pltpu.SemaphoreType.DMA((2,))]
                if chunk_skip else []
            ),
        )
        out_shape = [
            jax.ShapeDtypeStruct((num_groups, 1, G), jnp.float32)
            for _ in range(num_out_arrays)
        ] + [jax.ShapeDtypeStruct((num_groups, 1, G), jnp.int32)]
        args = (
            (starts, lens, shx, shy, shz, ncells, boxl, ioff, aself)
            + ((skip.cnt.reshape(num_groups, 1, skip_slots),)
               if SKIP else ())
            + (*i_fields, j_packed)
            + ((aabb,) if chunk_skip else ())
        )
        outs = pl.pallas_call(
            scalar_kernel,
            grid_spec=grid_spec,
            out_shape=out_shape,
            interpret=interpret,
        )(*args)
        return outs

    return call


def group_pair_engine_lists(
    pair_body: Callable,
    finalize: Callable,
    num_i: int,
    num_j: int,
    num_acc: int,
    cfg: NeighborConfig,
    interpret: bool = False,
    pair_cutoff: bool = True,
    want_nc: bool = True,
    sym_jf: Optional[int] = None,
):
    """List-walk variant of ``group_pair_engine``: identical DMA-run
    streaming, but every chunk's candidate lanes are COMPACTED with the
    persistent lists' per-chunk gather indices (sph/pair_lists.py) and
    merged into a dense 256-lane staging window; the pair math fires only
    on FULL 128-lane staging chunks (plus one flush). Per-target lane
    count drops from the streamed-chunk floor to the exact inflated-bbox
    occupancy (~2.5x fewer VPU ops on the measured Sedov configs).

    Contract differences from the streaming engine:
    - call(lists, i_fields, j_packed, i_offset, allow_self) — runs come
      from lists.ranges (build-time, skin-inflated);
    - no fold mode (lists are disabled on tiny grids), no chunk pairing,
      no AABB chunk-skip (the cnt>0 test replaces it at zero DMA cost);
    - the candidate's GLOBAL sorted-array index is staged as an f32 row
      (exact for n < 2^24; the HBM-headroom bound is 8M rows/chip), so
      the self-pair and shard-offset tests read it from staging.
    """
    R = _dma_rows(cfg.dma_cap)
    nf_pad = _round_up(num_j + 1, 8)  # +1: staged global-index row
    IDXR = num_j                       # sublane row of the staged index

    def kernel(*refs):
        (starts, lens, shx_r, shy_r, shz_r, ncells, ioff, aself,
         cnt_r, fill_r, emit_r, tail_r) = refs[:12]
        i_refs = refs[12 : 12 + num_i]
        jref = refs[12 + num_i]
        gidx_ref = refs[13 + num_i]
        out_refs = refs[14 + num_i : -2]
        nc_ref = refs[-2]
        (buf, sems, acc_refs, ncacc_ref, stage) = refs[-1]

        gi = pl.program_id(0)
        G = cfg.group
        nc_g = ncells[0, 0, 0]

        def dma(w, slot):
            row_s = starts[0, 0, w] // 128
            return pltpu.make_async_copy(
                jref.at[pl.ds(row_s, R), :, :],
                buf.at[slot], sems.at[slot],
            )

        @pl.when(nc_g > 0)
        def _():
            dma(0, 0).start()

        i_fields = [r[0, 0][:, None] for r in i_refs]  # (G, 1) each
        xi, yi, zi, hi = i_fields[:4]
        tgt_f = (
            ioff[0, 0, 0] + gi * G
            + jax.lax.broadcasted_iota(jnp.int32, (G, 1), 0)
        ).astype(jnp.float32)
        lane = jax.lax.broadcasted_iota(jnp.int32, (1, 128), 1)
        lane_f = jax.lax.broadcasted_iota(jnp.int32, (nf_pad, 128), 1)
        subl = jax.lax.broadcasted_iota(jnp.int32, (nf_pad, 128), 0)
        h4 = 4.0 * hi * hi

        def stage_math(valid):
            st = stage[:, :128]  # (nf_pad, 128) value read
            j_fields = [st[f][None, :] for f in range(num_j)]
            cand_f = st[IDXR][None, :]
            jx, jy, jz = j_fields[0], j_fields[1], j_fields[2]
            rx = xi - jx
            ry = yi - jy
            rz = zi - jz
            d2 = rx * rx + ry * ry + rz * rz
            mask = jnp.broadcast_to(lane < valid, d2.shape)
            if pair_cutoff:
                mask = mask & (d2 < h4)
            if sym_jf is not None:
                mask = mask & (d2 * j_fields[sym_jf] < 4.0)
            mask = mask & ((cand_f != tgt_f) | (aself[0, 0, 0] != 0))
            geom = PairGeom(rx=rx, ry=ry, rz=rz, d2=d2, mask=mask)
            accs = tuple(r[...] for r in acc_refs)
            accs = pair_body(geom, i_fields, j_fields, accs)
            for r, a in zip(acc_refs, accs):
                r[...] = a
            if want_nc:
                ncacc_ref[...] = ncacc_ref[...] + mask.astype(jnp.int32)

        def cell_body(w, slot_base):
            slot = w % 2

            @pl.when(w + 1 < nc_g)
            def _():
                dma(w + 1, 1 - slot).start()

            dma(w, slot).wait()
            s = starts[0, 0, w]
            ln = lens[0, 0, w]
            shx = shx_r[0, 0, w]
            shy = shy_r[0, 0, w]
            shz = shz_r[0, 0, w]
            row0 = s // 128
            off = s - row0 * 128
            nch = (off + ln + 127) // 128

            def chunk_body(t, _c):
                si = slot_base + t
                cnt = cnt_r[0, 0, si]
                fill = fill_r[0, 0, si]

                @pl.when(cnt > 0)
                def _():
                    # gidx arrives PRE-ROTATED by the staging fill, so
                    # the compaction + rotation is ONE lane gather
                    gi_row = gidx_ref[0, si][None, :]  # (1, 128) int32
                    rolled = jnp.take_along_axis(
                        buf[slot, t],
                        jnp.broadcast_to(gi_row, (nf_pad, 128)), axis=1,
                    )
                    # image-resolve the coordinate rows and insert the
                    # global-index row — one (nf_pad, 1) shift column +
                    # one sublane select
                    shift_col = jnp.where(
                        subl[:, :1] == 0, shx,
                        jnp.where(subl[:, :1] == 1, shy,
                                  jnp.where(subl[:, :1] == 2, shz, 0.0)),
                    )
                    rolled = rolled + shift_col
                    idx_f = ((row0 + t) * 128 + gi_row).astype(jnp.float32)
                    rolled = jnp.where(
                        subl == IDXR, jnp.broadcast_to(idx_f, rolled.shape),
                        rolled,
                    )
                    m0 = (lane_f >= fill) & (lane_f < fill + cnt)
                    m1 = lane_f < (fill + cnt - 128)
                    stage[:, :128] = jnp.where(m0, rolled, stage[:, :128])
                    stage[:, 128:] = jnp.where(m1, rolled, stage[:, 128:])

                @pl.when(emit_r[0, 0, si] > 0)
                def _():
                    stage_math(jnp.int32(128))
                    stage[:, :128] = stage[:, 128:]
                    stage[:, 128:] = jnp.zeros((nf_pad, 128), jnp.float32)

                return _c

            jax.lax.fori_loop(0, nch, chunk_body, 0)
            return slot_base + nch

        stage[...] = jnp.zeros((nf_pad, 256), jnp.float32)
        for r in acc_refs:
            r[...] = jnp.zeros((G, 128), jnp.float32)
        ncacc_ref[...] = jnp.zeros((G, 128), jnp.int32)
        jax.lax.fori_loop(0, nc_g, cell_body, 0)

        tail = tail_r[0, 0, 0]

        @pl.when(tail > 0)
        def _():
            stage_math(tail)

        accs = tuple(r[...] for r in acc_refs)
        nc_acc = jnp.sum(ncacc_ref[...], axis=1, keepdims=True)
        outs = finalize(i_fields, accs, nc_acc)
        for r, o in zip(out_refs, outs):
            r[0, 0] = o.reshape(G)
        nc_ref[0, 0] = nc_acc.reshape(G)

    def scalar_kernel(*refs):
        ns = num_acc + 4  # buf, sems, accs x num_acc, ncacc, stage
        buf, sems = refs[-ns], refs[-ns + 1]
        acc_refs = refs[-ns + 2 : -2]
        kernel(*refs[:-ns], (buf, sems, acc_refs, refs[-2], refs[-1]))

    def call(lists, i_fields: Sequence, j_packed,
             i_offset=0, allow_self=False):
        ranges = lists.ranges
        num_groups = ranges.num_groups
        w3 = ranges.starts.shape[1]
        S_cap = lists.slot_cap
        ioff = jnp.asarray(i_offset, jnp.int32).reshape(1, 1, 1)
        aself = jnp.asarray(allow_self, jnp.int32).reshape(1, 1, 1)
        smem3 = lambda a: a.reshape(num_groups, 1, -1)
        G = cfg.group
        i_fields = [a.reshape(num_groups, 1, G) for a in i_fields]
        num_out_arrays = len(
            finalize(
                [jnp.zeros((G, 1))] * num_i,
                tuple(jnp.zeros((G, 1)) for _ in range(num_acc)),
                jnp.zeros((G, 1), jnp.int32),
            )
        )
        smem_spec = lambda shape: pl.BlockSpec(
            shape, lambda g: (g, 0, 0), memory_space=pltpu.SMEM
        )
        rep_spec = lambda shape: pl.BlockSpec(
            shape, lambda g: (0, 0, 0), memory_space=pltpu.SMEM
        )
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=0,
            grid=(num_groups,),
            in_specs=[
                smem_spec((1, 1, w3)),     # starts
                smem_spec((1, 1, w3)),     # lens
                smem_spec((1, 1, w3)),     # shift x/y/z
                smem_spec((1, 1, w3)),
                smem_spec((1, 1, w3)),
                smem_spec((1, 1, 1)),      # ncells
                rep_spec((1, 1, 1)),       # i_offset
                rep_spec((1, 1, 1)),       # allow_self
                smem_spec((1, 1, S_cap)),  # cnt
                smem_spec((1, 1, S_cap)),  # fill
                smem_spec((1, 1, S_cap)),  # emit
                smem_spec((1, 1, 1)),      # tail
            ]
            + [
                pl.BlockSpec((1, 1, G), lambda g: (g, 0, 0))
                for _ in range(num_i)
            ]
            + [
                pl.BlockSpec(memory_space=pl.ANY),             # j_packed
                pl.BlockSpec((1, S_cap, 128), lambda g: (g, 0, 0)),  # gidx
            ],
            out_specs=[
                pl.BlockSpec((1, 1, G), lambda g: (g, 0, 0))
                for _ in range(num_out_arrays)
            ]
            + [pl.BlockSpec((1, 1, G), lambda g: (g, 0, 0))],
            scratch_shapes=[
                pltpu.VMEM((2, R, nf_pad, 128), jnp.float32),
                pltpu.SemaphoreType.DMA((2,)),
            ]
            + [pltpu.VMEM((G, 128), jnp.float32) for _ in range(num_acc)]
            + [pltpu.VMEM((G, 128), jnp.int32)]
            + [pltpu.VMEM((nf_pad, 256), jnp.float32)],
        )
        out_shape = [
            jax.ShapeDtypeStruct((num_groups, 1, G), jnp.float32)
            for _ in range(num_out_arrays)
        ] + [jax.ShapeDtypeStruct((num_groups, 1, G), jnp.int32)]
        args = (
            smem3(ranges.starts), smem3(ranges.lens),
            smem3(ranges.shift_x), smem3(ranges.shift_y),
            smem3(ranges.shift_z),
            ranges.ncells.reshape(num_groups, 1, 1), ioff, aself,
            smem3(lists.cnt), smem3(lists.fill), smem3(lists.emit),
            lists.tail.reshape(num_groups, 1, 1),
            *i_fields, j_packed, lists.gidx,
        )
        outs = pl.pallas_call(
            scalar_kernel,
            grid_spec=grid_spec,
            out_shape=out_shape,
            interpret=interpret,
        )(*args)
        return outs

    return call


def _prep_i(x, y, z, h, extra_i, group: int = GROUP):
    """Block the target-side fields (NG, group); tail groups re-read the
    last particle (masked out by the self/index tests)."""
    n = x.shape[0]
    num_groups = -(-n // group)
    pad_i = num_groups * group - n

    def block_i(a):
        a = jnp.concatenate([a, jnp.broadcast_to(a[-1:], (pad_i,))]) if pad_i else a
        return a.reshape(num_groups, group)

    return [block_i(a) for a in (x, y, z, h, *extra_i)]


# W on (G, 128) tiles from u = d2/h^2: 14 FMAs, no sqrt/sin/div
# (shared evaluator — both backends compute identical W)
_w_poly = sinc_poly_eval


def _op_aabb(jfields: Sequence, box: Box, cfg: NeighborConfig):
    """Chunk-AABB cull table for an op's j-side source arrays (None when
    the engine runs without the cull: fold mode or oversized DMA window).
    All ops of one step build it from the same coordinates inside one jit,
    so XLA CSE collapses the copies."""
    if engine_fold(box, cfg) or _dma_rows(cfg.dma_cap) > 31:
        return None
    return chunk_aabb_table(jfields[0], jfields[1], jfields[2], cfg.dma_cap)


@named_phase("density")
def pallas_density(
    x, y, z, h, m, sorted_keys, box: Box, const, cfg: NeighborConfig,
    ranges=None, interpret: bool = False, jdata=None, i_offset=0,
    lists=None,
):
    """rho_i = K h_i^-3 (m_i + sum_j m_j W(|r_ij|/h_i)) + neighbor counts.

    Pallas instantiation of hydro_std.compute_density (density.hpp:41) with
    the search fused in. Returns (rho (n,), nc (n,), occupancy).

    Under shard_map, the i-side arrays are the local slab while ``jdata``
    supplies the GLOBAL (all-gathered) candidate arrays (x, y, z, m) that
    ``sorted_keys``/``ranges`` index into, and ``i_offset`` is the slab's
    global start index (for the self-pair test).

    ``lists``: persistent PairLists (sph/pair_lists.py) — the list-walk
    engine replaces the streaming engine and ``sorted_keys``/``ranges``
    are unused (candidate runs come from the build-time lists).
    """
    n = x.shape[0]
    coeffs = kernel_poly_coeffs(float(const.sinc_index), const.kernel_choice)
    K = float(const.K)

    if ranges is None and lists is None:
        ranges = group_cell_ranges(x, y, z, h, sorted_keys, box, cfg)

    def pair_body(geom, i_fields, j_fields, accs):
        (rho_sum,) = accs
        inv_h2 = i_fields[4]
        mj = j_fields[3]
        w = _w_poly(geom.d2 * inv_h2, coeffs)
        return (rho_sum + jnp.where(geom.mask, mj * w, 0.0),)

    def finalize(i_fields, accs, nc):
        hi = i_fields[3]
        mi = i_fields[5]
        rho_sum = jnp.sum(accs[0], axis=1, keepdims=True)
        rho = K * (mi + rho_sum) / (hi * hi * hi)
        return (rho,)

    i_fields = _prep_i(x, y, z, h, (1.0 / (h * h), m), cfg.group)
    jf = jdata or (x, y, z, m)
    if lists is not None:
        # cheap body: the mark-bit chunk skip beats in-kernel compaction
        # (compaction's src-side take_along exceeds the ~10-op body)
        engine = group_pair_engine(
            pair_body, finalize, num_i=6, num_j=4, num_acc=1, cfg=cfg,
            fold=False, interpret=interpret, chunk_skip=False,
            skip_slots=lists.slot_cap,
        )
        jp = pack_j_fields(jf, cfg.dma_cap)
        rho, nc = engine(lists.ranges, i_fields, jp, i_offset, skip=lists)
        return rho.reshape(-1)[:n], nc.reshape(-1)[:n], \
            lists.ranges.occupancy
    engine = group_pair_engine(
        pair_body, finalize, num_i=6, num_j=4, num_acc=1, cfg=cfg,
        fold=engine_fold(box, cfg), interpret=interpret, chunk_skip=False,
    )
    jp = pack_j_fields(jf, cfg.dma_cap)
    rho, nc = engine(ranges, i_fields, jp, i_offset)
    return rho.reshape(-1)[:n], nc.reshape(-1)[:n], ranges.occupancy


@named_phase("iad")
def pallas_iad(
    x, y, z, h, vol, sorted_keys, box: Box, const, cfg: NeighborConfig,
    ranges=None, interpret: bool = False, jdata=None, i_offset=0,
    lists=None,
):
    """IAD tensor components (hydro_std.compute_iad, iad_kern.hpp) with the
    neighbor search fused in. ``vol`` is the per-particle volume estimate
    (m/rho std, xm/kx VE). Returns (c11..c33, occupancy).

    Under shard_map, ``jdata = (x, y, z, vol)`` supplies the GLOBAL
    j-side arrays (making the local ``vol`` argument j-side-dead) and
    ``i_offset`` the slab's global start index — same contract as
    pallas_density."""
    n = x.shape[0]
    coeffs = kernel_poly_coeffs(float(const.sinc_index), const.kernel_choice)
    K = float(const.K)

    if ranges is None and lists is None:
        ranges = group_cell_ranges(x, y, z, h, sorted_keys, box, cfg)
    fold = engine_fold(box, cfg)

    def pair_body_lanes(geom, i_fields, j_fields, accs):
        inv_h2 = i_fields[4]
        vj = j_fields[3]
        w = _w_poly(geom.d2 * inv_h2, coeffs)
        vw = jnp.where(geom.mask, vj * w, 0.0)
        terms = (
            geom.rx * geom.rx, geom.rx * geom.ry, geom.rx * geom.rz,
            geom.ry * geom.ry, geom.ry * geom.rz, geom.rz * geom.rz,
        )
        return tuple(acc + t * vw for acc, t in zip(accs, terms))

    def finalize(i_fields, accs, nc):
        t11, t12, t13, t22, t23, t33 = (
            jnp.sum(a, axis=1, keepdims=True) for a in accs
        )
        return _invert(i_fields, t11, t12, t13, t22, t23, t33)

    def _invert(i_fields, t11, t12, t13, t22, t23, t33):
        hi = i_fields[3]
        # exponent renormalization (iad_kern.hpp ilogb/ldexp trick) via
        # exp2/log2 — exact because the factor cancels in adj/det
        exp_of = lambda v: jnp.where(
            v != 0.0, jnp.floor(jnp.log2(jnp.abs(v) + 1e-45)), 0.0
        )
        esum = (exp_of(t11) + exp_of(t12) + exp_of(t13)
                + exp_of(t22) + exp_of(t23) + exp_of(t33))
        norm = jnp.exp2(-jnp.floor(esum / 6.0))
        t11, t12, t13 = t11 * norm, t12 * norm, t13 * norm
        t22, t23, t33 = t22 * norm, t23 * norm, t33 * norm
        det = (t11 * t22 * t33 + 2.0 * t12 * t23 * t13
               - t11 * t23 * t23 - t22 * t13 * t13 - t33 * t12 * t12)
        factor = norm * (hi * hi * hi) / (det * K)
        return (
            (t22 * t33 - t23 * t23) * factor,
            (t13 * t23 - t33 * t12) * factor,
            (t12 * t23 - t22 * t13) * factor,
            (t11 * t33 - t13 * t13) * factor,
            (t13 * t12 - t11 * t23) * factor,
            (t11 * t22 - t12 * t12) * factor,
        )

    # NOTE: an MXU variant (second moments around the group center via one
    # (G,128)x(128,16) dot_general per chunk, engine commit 42af8de)
    # hook) measured SLOWER than the lane path on v5e (484 vs 434 ms/step,
    # Sedov 100^3): the per-chunk NT-dot relayout exceeds the ~20 VPU ops
    # it saves. Revisit if Mosaic grows a cheap lane-contraction.
    i_fields = _prep_i(x, y, z, h, (1.0 / (h * h),), cfg.group)
    jf = jdata or (x, y, z, vol)
    if lists is not None:
        engine = group_pair_engine(
            pair_body_lanes, finalize, num_i=5, num_j=4, num_acc=6,
            cfg=cfg, fold=False, interpret=interpret, chunk_skip=False,
            want_nc=False, skip_slots=lists.slot_cap,
        )
        jp = pack_j_fields(jf, cfg.dma_cap)
        *cs, _nc = engine(lists.ranges, i_fields, jp, i_offset,
                          skip=lists)
        return tuple(c.reshape(-1)[:n] for c in cs), \
            lists.ranges.occupancy
    engine = group_pair_engine(
        pair_body_lanes, finalize, num_i=5, num_j=4, num_acc=6, cfg=cfg,
        fold=fold, interpret=interpret, chunk_skip=False, want_nc=False,
    )
    jp = pack_j_fields(jf, cfg.dma_cap)
    *cs, _nc = engine(ranges, i_fields, jp, i_offset)
    return tuple(c.reshape(-1)[:n] for c in cs), ranges.occupancy


@named_phase("momentum-energy")
def pallas_momentum_energy_std(
    x, y, z, vx, vy, vz, h, m, rho, p, c,
    c11, c12, c13, c22, c23, c33,
    sorted_keys, box: Box, const, cfg: NeighborConfig,
    ranges=None, interpret: bool = False, jdata=None, i_offset=0,
    lists=None,
):
    """Pressure-gradient accelerations + energy rate + Courant dt
    (hydro_std.compute_momentum_energy_std, momentum_energy_kern.hpp:12-134)
    with the neighbor search fused in. Returns (ax, ay, az, du, min_dt, occ).

    The per-particle ratios the reference computes per PAIR
    (momentum_energy_kern.hpp: p/rho^2, m/rho, 1/h^3) are precombined into
    the i-columns / packed j-fields here, so the inner tile math has no
    divisions and a single rsqrt.
    """
    n = x.shape[0]
    coeffs = kernel_poly_coeffs(float(const.sinc_index), const.kernel_choice)
    K = float(const.K)
    k_cour = float(const.k_cour)

    if ranges is None and lists is None:
        ranges = group_cell_ranges(x, y, z, h, sorted_keys, box, cfg)

    def pair_body(geom, i_fields, j_fields, accs):
        momx, momy, momz, energy, maxvs = accs
        (xi, yi, zi, hi, inv_h2i, inv_h3i, vxi, vyi, vzi, ci, pro_i, mi_roi,
         c11i, c12i, c13i, c22i, c23i, c33i) = i_fields
        (cx, cy, cz, inv_h2j, vxj, vyj, vzj, cj, mj, mjroj3, pjroj,
         c11j, c12j, c13j, c22j, c23j, c33j) = j_fields

        w_i = _w_poly(geom.d2 * inv_h2i, coeffs) * inv_h3i
        # support clamp inside _w_poly zeroes pairs beyond 2 h_j, matching
        # the reference's table lookup clamp
        mjw = mjroj3 * _w_poly(geom.d2 * inv_h2j, coeffs)  # m_j/rho_j W_j

        # self/masked pairs have d2 = 0 -> rsqrt = inf -> NaNs confined to
        # masked lanes; every accumulation below selects on geom.mask
        inv_dist = jax.lax.rsqrt(geom.d2)
        vx_ij = vxi - vxj
        vy_ij = vyi - vyj
        vz_ij = vzi - vzj
        rv = geom.rx * vx_ij + geom.ry * vy_ij + geom.rz * vz_ij
        w_ij = rv * inv_dist

        # Monaghan constant-alpha AV, halved per pair (kernels.hpp:60-84)
        cij = ci + cj
        v_signal = 0.5 * cij - 2.0 * w_ij
        visc = 0.5 * jnp.where(w_ij < 0.0, -v_signal * w_ij, 0.0)

        maxvs = jnp.maximum(
            maxvs, jnp.where(geom.mask, cij - 3.0 * w_ij, 0.0)
        )

        tA1_i = c11i * geom.rx + c12i * geom.ry + c13i * geom.rz
        tA2_i = c12i * geom.rx + c22i * geom.ry + c23i * geom.rz
        tA3_i = c13i * geom.rx + c23i * geom.ry + c33i * geom.rz
        tA1_j = c11j * geom.rx + c12j * geom.ry + c13j * geom.rz
        tA2_j = c12j * geom.rx + c22j * geom.ry + c23j * geom.rz
        tA3_j = c13j * geom.rx + c23j * geom.ry + c33j * geom.rz

        mj_pro_i = mj * pro_i
        vmi = visc * mi_roi
        a = w_i * (mj_pro_i + vmi)
        b = mjw * (pjroj + visc)
        mm = geom.mask
        momx = momx + jnp.where(mm, a * tA1_i + b * tA1_j, 0.0)
        momy = momy + jnp.where(mm, a * tA2_i + b * tA2_j, 0.0)
        momz = momz + jnp.where(mm, a * tA3_i + b * tA3_j, 0.0)

        a_e = w_i * (2.0 * mj_pro_i + vmi)
        b_e = visc * mjw
        energy = energy + jnp.where(
            mm,
            vx_ij * (a_e * tA1_i + b_e * tA1_j)
            + vy_ij * (a_e * tA2_i + b_e * tA2_j)
            + vz_ij * (a_e * tA3_i + b_e * tA3_j),
            0.0,
        )
        return momx, momy, momz, energy, maxvs

    def finalize(i_fields, accs, nc):
        hi = i_fields[3]
        ci = i_fields[9]
        momx, momy, momz, energy, maxvs = accs
        red = lambda a: jnp.sum(a, axis=1, keepdims=True)
        du = -K * 0.5 * red(energy)
        mv = jnp.max(maxvs, axis=1, keepdims=True)
        v = jnp.where(mv > 0.0, mv, ci)
        dt_i = k_cour * hi / v
        return (K * red(momx), K * red(momy), K * red(momz), du, dt_i)

    inv_h2 = 1.0 / (h * h)
    inv_h3 = inv_h2 / h
    i_fields = _prep_i(
        x, y, z, h,
        (inv_h2, inv_h3, vx, vy, vz, c, p / (rho * rho), m / rho,
         c11, c12, c13, c22, c23, c33),
        cfg.group,
    )
    if jdata is None:
        jfields = (x, y, z, inv_h2, vx, vy, vz, c, m,
                   m / (rho * h * h * h), p / rho,
                   c11, c12, c13, c22, c23, c33)
    else:
        (xj, yj, zj, hj, vxj, vyj, vzj, mj, rhoj, pj, cj,
         j11, j12, j13, j22, j23, j33) = jdata
        jfields = (xj, yj, zj, 1.0 / (hj * hj), vxj, vyj, vzj, cj, mj,
                   mj / (rhoj * hj * hj * hj), pj / rhoj,
                   j11, j12, j13, j22, j23, j33)
    sym = 3 if getattr(const, "sym_pairs", True) else None
    f = lambda a: a.reshape(-1)[:n]
    if lists is not None:
        engine = group_pair_engine_lists(
            pair_body, finalize, num_i=18, num_j=17, num_acc=5, cfg=cfg,
            interpret=interpret, want_nc=False, sym_jf=sym,
        )
        jp = pack_j_fields(jfields, cfg.dma_cap, nf_min=18)
        ax, ay, az, du, dt_i, _nc = engine(lists, i_fields, jp, i_offset)
        return (f(ax), f(ay), f(az), f(du), jnp.min(f(dt_i)),
                lists.ranges.occupancy)
    engine = group_pair_engine(
        pair_body, finalize, num_i=18, num_j=17, num_acc=5, cfg=cfg,
        fold=engine_fold(box, cfg), interpret=interpret, want_nc=False,
        sym_jf=sym,
    )
    jp = pack_j_fields(jfields, cfg.dma_cap)
    ax, ay, az, du, dt_i, _nc = engine(ranges, i_fields, jp, i_offset,
                                       aabb=_op_aabb(jfields, box, cfg))
    return f(ax), f(ay), f(az), f(du), jnp.min(f(dt_i)), ranges.occupancy


# ---------------------------------------------------------------------------
# VE pipeline ops (sph/hydro_ve counterparts with the search fused in).
# The reference's flagship propagator is VE (main/src/propagator/
# ve_hydro.hpp:51); every op below mirrors its hydro_ve kernel
# (xmass_kern.hpp, ve_def_gradh_kern.hpp, divv_curlv_kern.hpp,
# av_switches_kern.hpp, momentum_energy_kern.hpp) with the same
# precombined-ratio strategy as the std momentum op.
# ---------------------------------------------------------------------------


@named_phase("xmass")
def pallas_xmass(
    x, y, z, h, m, sorted_keys, box: Box, const, cfg: NeighborConfig,
    ranges=None, interpret: bool = False, jdata=None, i_offset=0,
    lists=None,
):
    """Generalized volume element xm_i = m_i / rho0_i (xmass_kern.hpp:50-79)
    + neighbor counts. rho0 is exactly the std kernel-summed density, so
    this delegates to pallas_density. Returns (xm (n,), nc (n,), occ)."""
    rho0, nc, occ = pallas_density(
        x, y, z, h, m, sorted_keys, box, const, cfg,
        ranges=ranges, interpret=interpret, jdata=jdata, i_offset=i_offset,
        lists=lists,
    )
    return m / rho0, nc, occ


@named_phase("gradh")
def pallas_ve_def_gradh(
    x, y, z, h, m, xm, sorted_keys, box: Box, const, cfg: NeighborConfig,
    ranges=None, interpret: bool = False, jdata=None, i_offset=0,
    lists=None,
):
    """VE normalization kx + grad-h correction (ve_def_gradh_kern.hpp:43-90)
    with the search fused in. Returns ((kx, gradh), occupancy).

    Under shard_map, ``jdata = (x, y, z, m, xm)`` supplies the j-side
    candidate arrays (slab + halo annex) the ranges index into — same
    contract as pallas_density."""
    n = x.shape[0]
    wc = kernel_poly_coeffs(float(const.sinc_index), const.kernel_choice)
    dc = kernel_dterh_coeffs(float(const.sinc_index), const.kernel_choice)
    K = float(const.K)

    if ranges is None and lists is None:
        ranges = group_cell_ranges(x, y, z, h, sorted_keys, box, cfg)

    def pair_body(geom, i_fields, j_fields, accs):
        kxs, who, wro = accs
        inv_h2 = i_fields[4]
        mj = j_fields[3]
        xmj = j_fields[4]
        u = geom.d2 * inv_h2
        w = _w_poly(u, wc)
        dterh = dterh_poly_eval(u, dc)
        mm = geom.mask
        kxs = kxs + jnp.where(mm, xmj * w, 0.0)
        who = who + jnp.where(mm, xmj * dterh, 0.0)
        wro = wro + jnp.where(mm, mj * dterh, 0.0)
        return kxs, who, wro

    def finalize(i_fields, accs, nc):
        hi = i_fields[3]
        mi = i_fields[5]
        xmi = i_fields[6]
        red = lambda a: jnp.sum(a, axis=1, keepdims=True)
        h3inv = 1.0 / (hi * hi * hi)
        kx = (xmi + red(accs[0])) * K * h3inv
        whomega = (-3.0 * xmi + red(accs[1])) * K * h3inv / hi
        wrho0 = (-3.0 * mi + red(accs[2])) * K * h3inv / hi
        whomega = whomega * mi / xmi + (kx - K * xmi * h3inv) * wrho0
        rho = kx * mi / xmi
        dhdrho = -hi / (rho * 3.0)
        gradh = 1.0 - dhdrho * whomega
        return (kx, gradh)

    i_fields = _prep_i(x, y, z, h, (1.0 / (h * h), m, xm), cfg.group)
    jf = jdata or (x, y, z, m, xm)
    f = lambda a: a.reshape(-1)[:n]
    if lists is not None:
        engine = group_pair_engine(
            pair_body, finalize, num_i=7, num_j=5, num_acc=3, cfg=cfg,
            fold=False, interpret=interpret, chunk_skip=False,
            want_nc=False, skip_slots=lists.slot_cap,
        )
        jp = pack_j_fields(jf, cfg.dma_cap)
        kx, gradh, _nc = engine(lists.ranges, i_fields, jp, i_offset,
                                skip=lists)
        return (f(kx), f(gradh)), lists.ranges.occupancy
    engine = group_pair_engine(
        pair_body, finalize, num_i=7, num_j=5, num_acc=3, cfg=cfg,
        fold=engine_fold(box, cfg), interpret=interpret, chunk_skip=False,
        want_nc=False,
    )
    jp = pack_j_fields(jf, cfg.dma_cap)
    kx, gradh, _nc = engine(ranges, i_fields, jp, i_offset)
    return (f(kx), f(gradh)), ranges.occupancy


@named_phase("divv-curlv")
def pallas_iad_divv_curlv(
    x, y, z, vx, vy, vz, h, kx, xm,
    c11, c12, c13, c22, c23, c33,
    sorted_keys, box: Box, const, cfg: NeighborConfig,
    ranges=None, with_gradv: bool = False, interpret: bool = False,
    jdata=None, i_offset=0, lists=None, list_walk=None,
):
    """Velocity divergence/curl through the IAD gradient
    (divv_curlv_kern.hpp:43-120), optionally the full symmetrized
    velocity-gradient tensor for avClean. Returns (outs, occupancy) with
    outs = (divv, curlv[, dv11..dv33]).

    Under shard_map, ``jdata = (x, y, z, xm, vx, vy, vz)`` supplies the
    j-side candidate arrays — same contract as pallas_density."""
    n = x.shape[0]
    wc = kernel_poly_coeffs(float(const.sinc_index), const.kernel_choice)
    K = float(const.K)

    if ranges is None and lists is None:
        ranges = group_cell_ranges(x, y, z, h, sorted_keys, box, cfg)

    def pair_body(geom, i_fields, j_fields, accs):
        (xi, yi, zi, hi, inv_h2,
         c11i, c12i, c13i, c22i, c23i, c33i, _knorm) = i_fields[:12]
        (cx, cy, cz, xmj, vxj, vyj, vzj) = j_fields[:7]
        vxi, vyi, vzi = i_fields[12], i_fields[13], i_fields[14]

        # negated projection: the VE kernels use tA = -(C r) W
        # (iad_project sign=-1, divv_curlv_kern.hpp)
        w = -_w_poly(geom.d2 * inv_h2, wc)
        tA1 = (c11i * geom.rx + c12i * geom.ry + c13i * geom.rz) * w
        tA2 = (c12i * geom.rx + c22i * geom.ry + c23i * geom.rz) * w
        tA3 = (c13i * geom.rx + c23i * geom.ry + c33i * geom.rz) * w
        vx_ji = vxj - vxi
        vy_ji = vyj - vyi
        vz_ji = vzj - vzi
        mm = geom.mask
        mw = jnp.where(mm, xmj, 0.0)
        if with_gradv:
            dvx1, dvx2, dvx3, dvy1, dvy2, dvy3, dvz1, dvz2, dvz3 = accs
            dvx1 = dvx1 + mw * vx_ji * tA1
            dvx2 = dvx2 + mw * vx_ji * tA2
            dvx3 = dvx3 + mw * vx_ji * tA3
            dvy1 = dvy1 + mw * vy_ji * tA1
            dvy2 = dvy2 + mw * vy_ji * tA2
            dvy3 = dvy3 + mw * vy_ji * tA3
            dvz1 = dvz1 + mw * vz_ji * tA1
            dvz2 = dvz2 + mw * vz_ji * tA2
            dvz3 = dvz3 + mw * vz_ji * tA3
            return dvx1, dvx2, dvx3, dvy1, dvy2, dvy3, dvz1, dvz2, dvz3
        adiv, acx, acy, acz = accs
        adiv = adiv + mw * (vx_ji * tA1 + vy_ji * tA2 + vz_ji * tA3)
        acx = acx + mw * (vz_ji * tA2 - vy_ji * tA3)
        acy = acy + mw * (vx_ji * tA3 - vz_ji * tA1)
        acz = acz + mw * (vy_ji * tA1 - vx_ji * tA2)
        return adiv, acx, acy, acz

    def finalize(i_fields, accs, nc):
        knorm = i_fields[11]
        red = lambda a: jnp.sum(a, axis=1, keepdims=True)
        if with_gradv:
            dvx1, dvx2, dvx3, dvy1, dvy2, dvy3, dvz1, dvz2, dvz3 = (
                red(a) for a in accs
            )
            divv = knorm * (dvx1 + dvy2 + dvz3)
            cx_ = dvz2 - dvy3
            cy_ = dvx3 - dvz1
            cz_ = dvy1 - dvx2
            curlv = knorm * jnp.sqrt(cx_ * cx_ + cy_ * cy_ + cz_ * cz_)
            return (
                divv, curlv,
                knorm * dvx1, knorm * (dvx2 + dvy1), knorm * (dvx3 + dvz1),
                knorm * dvy2, knorm * (dvy3 + dvz2), knorm * dvz3,
            )
        adiv, acx, acy, acz = (red(a) for a in accs)
        divv = knorm * adiv
        curlv = knorm * jnp.sqrt(acx * acx + acy * acy + acz * acz)
        return (divv, curlv)

    knorm = K / (h * h * h * kx)
    i_fields = _prep_i(
        x, y, z, h,
        (1.0 / (h * h), c11, c12, c13, c22, c23, c33, knorm, vx, vy, vz),
        cfg.group,
    )
    jf = jdata or (x, y, z, xm, vx, vy, vz)
    f = lambda a: a.reshape(-1)[:n]
    if lists is not None:
        if list_walk is None:
            # measured at 80^3: divv/curlv body is a WASH vs chunk-skip
            # (59.1 vs 58.2 ms) but the 9-accumulator gradv (avClean)
            # body pays for lane compaction (60.3 vs 71.3 ms) — default
            # per body weight
            list_walk = with_gradv
        if list_walk:
            engine = group_pair_engine_lists(
                pair_body, finalize, num_i=15, num_j=7,
                num_acc=9 if with_gradv else 4, cfg=cfg,
                interpret=interpret, want_nc=False,
            )
            jp = pack_j_fields(jf, cfg.dma_cap)
            *outs, _nc = engine(lists, i_fields, jp, i_offset)
            return tuple(f(a) for a in outs), lists.ranges.occupancy
        engine = group_pair_engine(
            pair_body, finalize, num_i=15, num_j=7,
            num_acc=9 if with_gradv else 4, cfg=cfg,
            fold=False, interpret=interpret, chunk_skip=False,
            want_nc=False, skip_slots=lists.slot_cap,
        )
        jp = pack_j_fields(jf, cfg.dma_cap)
        *outs, _nc = engine(lists.ranges, i_fields, jp, i_offset,
                            skip=lists)
        return tuple(f(a) for a in outs), lists.ranges.occupancy
    engine = group_pair_engine(
        pair_body, finalize, num_i=15, num_j=7,
        num_acc=9 if with_gradv else 4, cfg=cfg,
        fold=engine_fold(box, cfg), interpret=interpret, want_nc=False,
    )
    jp = pack_j_fields(jf, cfg.dma_cap)
    *outs, _nc = engine(ranges, i_fields, jp, i_offset,
                        aabb=_op_aabb(jf, box, cfg))
    return tuple(f(a) for a in outs), ranges.occupancy


@named_phase("av-switches")
def pallas_av_switches(
    x, y, z, vx, vy, vz, h, c, kx, xm, divv, alpha,
    c11, c12, c13, c22, c23, c33,
    sorted_keys, box: Box, dt, const, cfg: NeighborConfig,
    ranges=None, interpret: bool = False, jdata=None, i_offset=0,
    lists=None, list_walk: bool = True,
):
    """Per-particle viscosity switch evolution (av_switches_kern.hpp:43-137)
    with the search fused in. Returns (alpha_new (n,), occupancy).

    Under shard_map, ``jdata = (x, y, z, c, vx, vy, vz, xm/kx, divv)``
    supplies the j-side candidate arrays — same contract as
    pallas_density."""
    n = x.shape[0]
    wc = kernel_poly_coeffs(float(const.sinc_index), const.kernel_choice)
    K = float(const.K)
    alphamax = float(const.alphamax)
    alphamin = float(const.alphamin)
    decay_c = float(const.decay_constant)

    if ranges is None and lists is None:
        ranges = group_cell_ranges(x, y, z, h, sorted_keys, box, cfg)

    def pair_body(geom, i_fields, j_fields, accs):
        vs_max, gdx, gdy, gdz = accs
        (xi, yi, zi, hi, inv_h2, kh3, ci, divvi,
         c11i, c12i, c13i, c22i, c23i, c33i) = i_fields[:14]
        vxi, vyi, vzi = i_fields[14], i_fields[15], i_fields[16]
        (cx, cy, cz, cj, vxj, vyj, vzj, volj, divvj) = j_fields[:9]

        # negated projection (iad_project sign=-1, av_switches_kern.hpp)
        w = -_w_poly(geom.d2 * inv_h2, wc) * kh3
        vx_ij = vxi - vxj
        vy_ij = vyi - vyj
        vz_ij = vzi - vzj
        rv = geom.rx * vx_ij + geom.ry * vy_ij + geom.rz * vz_ij
        inv_dist = jax.lax.rsqrt(geom.d2)
        vsig = jnp.where(rv < 0.0, ci + cj - 3.0 * rv * inv_dist, 0.0)
        vs_max = jnp.maximum(vs_max, jnp.where(geom.mask, vsig, 0.0))

        tA1 = (c11i * geom.rx + c12i * geom.ry + c13i * geom.rz) * w
        tA2 = (c12i * geom.rx + c22i * geom.ry + c23i * geom.rz) * w
        tA3 = (c13i * geom.rx + c23i * geom.ry + c33i * geom.rz) * w
        factor = jnp.where(geom.mask, volj * (divvi - divvj), 0.0)
        gdx = gdx + factor * tA1
        gdy = gdy + factor * tA2
        gdz = gdz + factor * tA3
        return vs_max, gdx, gdy, gdz

    def finalize(i_fields, accs, nc):
        hi = i_fields[3]
        ci = i_fields[6]
        divvi = i_fields[7]
        alpha_i = i_fields[17]
        dt_b = i_fields[18]
        vs = jnp.max(accs[0], axis=1, keepdims=True)
        red = lambda a: jnp.sum(a, axis=1, keepdims=True)
        gdx, gdy, gdz = red(accs[1]), red(accs[2]), red(accs[3])
        vijsignal = jnp.maximum(vs, 1e-40 * ci)
        graddivv = jnp.sqrt(gdx * gdx + gdy * gdy + gdz * gdz)
        a_const = hi * hi * graddivv
        alphaloc = jnp.where(
            divvi < 0.0,
            alphamax * a_const
            / (a_const + hi * jnp.abs(divvi) + 0.05 * ci),
            0.0,
        )
        decay = hi / (decay_c * vijsignal)
        target = jnp.maximum(alphaloc, alphamin)
        alphadot = (target - alpha_i) / decay
        alpha_decayed = alpha_i + alphadot * dt_b
        return (jnp.where(alphaloc >= alpha_i, alphaloc, alpha_decayed),)

    # dt rides along as a constant i-field: one (1, 1, G) block DMA per
    # group (~256 B) — not worth a second engine scalar-operand mechanism
    dt_b = jnp.broadcast_to(jnp.asarray(dt, jnp.float32), x.shape)
    i_fields = _prep_i(
        x, y, z, h,
        (1.0 / (h * h), K / (h * h * h), c, divv,
         c11, c12, c13, c22, c23, c33, vx, vy, vz, alpha, dt_b),
        cfg.group,
    )
    jf = jdata or (x, y, z, c, vx, vy, vz, xm / kx, divv)
    if lists is not None:
        if list_walk:
            # rsqrt + signal-velocity max make this body heavy enough
            # for lane compaction: 62.0 vs 67.4 ms at 80^3
            # (scripts/bench_lists.py --ve)
            engine = group_pair_engine_lists(
                pair_body, finalize, num_i=19, num_j=9, num_acc=4,
                cfg=cfg, interpret=interpret, want_nc=False,
            )
            jp = pack_j_fields(jf, cfg.dma_cap, nf_min=10)
            alpha_new, _nc = engine(lists, i_fields, jp, i_offset)
            return alpha_new.reshape(-1)[:n], lists.ranges.occupancy
        engine = group_pair_engine(
            pair_body, finalize, num_i=19, num_j=9, num_acc=4, cfg=cfg,
            fold=False, interpret=interpret, chunk_skip=False,
            want_nc=False, skip_slots=lists.slot_cap,
        )
        jp = pack_j_fields(jf, cfg.dma_cap)
        alpha_new, _nc = engine(lists.ranges, i_fields, jp, i_offset,
                                skip=lists)
        return alpha_new.reshape(-1)[:n], lists.ranges.occupancy
    engine = group_pair_engine(
        pair_body, finalize, num_i=19, num_j=9, num_acc=4, cfg=cfg,
        fold=engine_fold(box, cfg), interpret=interpret, want_nc=False,
    )
    jp = pack_j_fields(jf, cfg.dma_cap)
    alpha_new, _nc = engine(ranges, i_fields, jp, i_offset,
                            aabb=_op_aabb(jf, box, cfg))
    return alpha_new.reshape(-1)[:n], ranges.occupancy


@named_phase("momentum-energy")
def pallas_momentum_energy_ve(
    x, y, z, vx, vy, vz, h, m, prho, c, kx, xm, alpha,
    c11, c12, c13, c22, c23, c33,
    sorted_keys, box: Box, const, cfg: NeighborConfig, nc=None,
    gradv=None, ranges=None, interpret: bool = False,
    jdata=None, i_offset=0, lists=None,
):
    """VE momentum + energy (momentum_energy_kern.hpp:65-222) with the
    search fused in: Atwood-ramped crossed/uncrossed volume elements,
    per-particle alpha viscosity, optional avClean gradV correction.
    Returns (ax, ay, az, du, min_dt, occupancy).

    The Atwood ramp's per-pair powers xm^(2-sigma) xm_j^sigma are
    evaluated as xm_i^2 exp(sigma (ln xm_j - ln xm_i)) with the logs
    precomputed per particle — one exp per pair side instead of pow().

    Under shard_map, ``jdata = (x, y, z, h, vx, vy, vz, c, alpha, m, xm,
    kx, prho, c11..c33[, gv11..gv33])`` supplies the RAW j-side candidate
    arrays (derived per-j ratios are computed here); the trailing gradv
    fields are present iff avClean. Same contract as pallas_density."""
    n = x.shape[0]
    wc = kernel_poly_coeffs(float(const.sinc_index), const.kernel_choice)
    K = float(const.K)
    k_cour = float(const.k_cour)
    at_min = float(const.at_min)
    at_max = float(const.at_max)
    ramp = float(const.ramp)
    av_clean = gradv is not None

    if ranges is None and lists is None:
        ranges = group_cell_ranges(x, y, z, h, sorted_keys, box, cfg)

    NI = 23 + (7 if av_clean else 0)
    NJ = 23 + (6 if av_clean else 0)

    def pair_body(geom, i_fields, j_fields, accs):
        momx, momy, momz, energy, avisc_e, maxvs = accs
        (xi, yi, zi, hi, inv_h2i, inv_h3i, vxi, vyi, vzi, ci, ali,
         xmi, xm2i, lxi, rhoi, irhoi, prhoi,
         c11i, c12i, c13i, c22i, c23i, c33i) = i_fields[:23]
        (cx, cy, cz, inv_h2j, inv_h3j, vxj, vyj, vzj, cj, alj,
         mj, xmj, xm2j, lxj, rhoj, irhoj, prhoj,
         c11j, c12j, c13j, c22j, c23j, c33j) = j_fields[:23]

        u_i = geom.d2 * inv_h2i
        u_j = geom.d2 * inv_h2j
        # negative normalization bakes the VE kernels' tA = -(C r) W
        # projection sign into w (iad_project sign=-1)
        w_i = -_w_poly(u_i, wc) * inv_h3i
        w_j = -_w_poly(u_j, wc) * inv_h3j

        vx_ij = vxi - vxj
        vy_ij = vyi - vyj
        vz_ij = vzi - vzj
        rv = geom.rx * vx_ij + geom.ry * vy_ij + geom.rz * vz_ij
        inv_dist = jax.lax.rsqrt(geom.d2)

        if av_clean:
            eta_crit = i_fields[23]
            gvi = i_fields[24:30]
            gvj = j_fields[23:29]
            sym = lambda gv: (
                geom.rx * (gv[0] * geom.rx + gv[1] * geom.ry + gv[2] * geom.rz)
                + geom.ry * (gv[3] * geom.ry + gv[4] * geom.rz)
                + geom.rz * (gv[5] * geom.rz)
            )
            d1 = sym(gvi)
            d2_ = sym(gvj)
            eta_ab = jnp.minimum(jnp.sqrt(u_i), jnp.sqrt(u_j))
            eta_diff = 5.0 * (eta_ab - eta_crit)
            d3 = jnp.where(
                eta_ab < eta_crit, jnp.exp(-(eta_diff * eta_diff)), 1.0
            )
            A = jnp.where(d2_ != 0.0, d1 / d2_, 0.0)
            Ap1 = 1.0 + A
            phi = 0.5 * d3 * jnp.clip(4.0 * A / (Ap1 * Ap1), 0.0, 1.0)
            rv = rv - phi * (d1 + d2_)

        w_ij = rv * inv_dist
        # per-particle-alpha Monaghan AV (kernels.hpp:60-84)
        cij = ci + cj
        v_sig = 0.25 * (ali + alj) * cij - 2.0 * w_ij
        visc = jnp.where(w_ij < 0.0, -v_sig * w_ij, 0.0)
        maxvs = jnp.maximum(
            maxvs, jnp.where(geom.mask, 0.5 * cij - 2.0 * w_ij, 0.0)
        )

        tA1_i = (c11i * geom.rx + c12i * geom.ry + c13i * geom.rz) * w_i
        tA2_i = (c12i * geom.rx + c22i * geom.ry + c23i * geom.rz) * w_i
        tA3_i = (c13i * geom.rx + c23i * geom.ry + c33i * geom.rz) * w_i
        tA1_j = (c11j * geom.rx + c12j * geom.ry + c13j * geom.rz) * w_j
        tA2_j = (c12j * geom.rx + c22j * geom.ry + c23j * geom.rz) * w_j
        tA3_j = (c13j * geom.rx + c23j * geom.ry + c33j * geom.rz) * w_j

        # Atwood ramp between uncrossed (xm_i^2, xm_j^2) and crossed
        # (xm_i xm_j) volume elements
        atwood = jnp.abs(rhoi - rhoj) / (rhoi + rhoj)
        sigma = ramp * (atwood - at_min)
        dl = lxj - lxi
        a_ramp = xm2i * jnp.exp(sigma * dl)
        b_ramp = xm2j * jnp.exp(-sigma * dl)
        crossed = xmi * xmj
        a_mom = jnp.where(
            atwood < at_min, xm2i,
            jnp.where(atwood > at_max, crossed, a_ramp),
        )
        b_mom = jnp.where(
            atwood < at_min, xm2j,
            jnp.where(atwood > at_max, crossed, b_ramp),
        )

        a_visc = mj * irhoi * visc
        b_visc = mj * irhoj * visc
        avx = 0.5 * (a_visc * tA1_i + b_visc * tA1_j)
        avy = 0.5 * (a_visc * tA2_i + b_visc * tA2_j)
        avz = 0.5 * (a_visc * tA3_i + b_visc * tA3_j)
        mm = geom.mask
        avisc_e = avisc_e + jnp.where(
            mm, avx * vx_ij + avy * vy_ij + avz * vz_ij, 0.0
        )
        energy = energy + jnp.where(
            mm,
            mj * a_mom * (vx_ij * tA1_i + vy_ij * tA2_i + vz_ij * tA3_i),
            0.0,
        )
        mom_i = mj * prhoi * a_mom
        mom_j = mj * prhoj * b_mom
        momx = momx + jnp.where(mm, mom_i * tA1_i + mom_j * tA1_j + avx, 0.0)
        momy = momy + jnp.where(mm, mom_i * tA2_i + mom_j * tA2_j + avy, 0.0)
        momz = momz + jnp.where(mm, mom_i * tA3_i + mom_j * tA3_j + avz, 0.0)
        return momx, momy, momz, energy, avisc_e, maxvs

    def finalize(i_fields, accs, nc_):
        hi = i_fields[3]
        ci = i_fields[9]
        prhoi = i_fields[16]
        momx, momy, momz, energy, avisc_e, maxvs = accs
        red = lambda a: jnp.sum(a, axis=1, keepdims=True)
        avisc = jnp.maximum(red(avisc_e), 0.0)
        du = K * (prhoi * red(energy) + 0.5 * avisc)
        mv = jnp.max(maxvs, axis=1, keepdims=True)
        v = jnp.where(mv > 0.0, mv, ci)
        dt_i = k_cour * hi / v
        return (-K * red(momx), -K * red(momy), -K * red(momz), du, dt_i)

    inv_h2 = 1.0 / (h * h)
    inv_h3 = inv_h2 / h
    rho = kx * m / xm
    inv_rho = 1.0 / rho
    lx = jnp.log(xm)
    extra_i = [inv_h2, inv_h3, vx, vy, vz, c, alpha, xm, xm * xm, lx,
               rho, inv_rho, prho, c11, c12, c13, c22, c23, c33]
    if av_clean:
        eta_crit = jnp.cbrt(
            32.0 * np.pi / 3.0 / (nc.astype(jnp.float32) + 1.0)
        )
        extra_i = extra_i + [eta_crit] + list(gradv)
    if jdata is None:
        jfields = [x, y, z, inv_h2, inv_h3, vx, vy, vz, c, alpha, m, xm,
                   xm * xm, lx, rho, inv_rho, prho,
                   c11, c12, c13, c22, c23, c33]
        if av_clean:
            jfields = jfields + list(gradv)
    else:
        (xj, yj, zj, hj, vxj, vyj, vzj, cj, alj, mj, xmj, kxj, prhoj,
         j11, j12, j13, j22, j23, j33, *gvj) = jdata
        inv_h2j = 1.0 / (hj * hj)
        rhoj = kxj * mj / xmj
        jfields = [xj, yj, zj, inv_h2j, inv_h2j / hj, vxj, vyj, vzj, cj,
                   alj, mj, xmj, xmj * xmj, jnp.log(xmj), rhoj, 1.0 / rhoj,
                   prhoj, j11, j12, j13, j22, j23, j33]
        if av_clean:
            jfields = jfields + list(gvj)
    i_fields = _prep_i(x, y, z, h, tuple(extra_i), cfg.group)
    sym = 3 if getattr(const, "sym_pairs", True) else None
    f = lambda a: a.reshape(-1)[:n]
    if lists is not None:
        engine = group_pair_engine_lists(
            pair_body, finalize, num_i=NI, num_j=NJ, num_acc=6, cfg=cfg,
            interpret=interpret, want_nc=False, sym_jf=sym,
        )
        jp = pack_j_fields(tuple(jfields), cfg.dma_cap, nf_min=NJ + 1)
        ax, ay, az, du, dt_i, _nc = engine(lists, i_fields, jp, i_offset)
        return (f(ax), f(ay), f(az), f(du), jnp.min(f(dt_i)),
                lists.ranges.occupancy)
    engine = group_pair_engine(
        pair_body, finalize, num_i=NI, num_j=NJ, num_acc=6, cfg=cfg,
        fold=engine_fold(box, cfg), interpret=interpret, want_nc=False,
        sym_jf=sym,
    )
    jp = pack_j_fields(tuple(jfields), cfg.dma_cap)
    ax, ay, az, du, dt_i, _nc = engine(ranges, i_fields, jp, i_offset,
                                       aabb=_op_aabb(jfields, box, cfg))
    return f(ax), f(ay), f(az), f(du), jnp.min(f(dt_i)), ranges.occupancy
