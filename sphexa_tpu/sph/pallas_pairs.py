"""Pallas TPU engine for SPH pair interactions: stream candidate cells
through VMEM per target group.

TPU-native re-design of the hot j-loops following the reference's GPU
strategy (cstone/traversal/find_neighbors.cuh: 64-particle warp targets,
neighbors found on the fly inside each kernel, no stored lists) mapped to
the TPU memory system:

- targets are groups of G = 128 SFC-consecutive particles (one VMEM block);
- the group's candidate set is the static ``window^3`` block of grid cells
  covering its search extent; every cell's particles are CONTIGUOUS in the
  SFC-sorted arrays, so each cell is ONE dynamic-slice DMA from HBM into a
  VMEM ring buffer — no XLA gathers anywhere;
- the pair physics runs cell-by-cell on (G, cap) tiles on the VPU while
  the next cell's DMA is in flight (double buffering);
- each op instantiates the shared engine with its own per-pair math and
  accumulators, fusing neighbor search INTO the op (the reference GPU
  does exactly this, SURVEY.md §2 'neighbors recomputed on the fly').

The XLA gather-based path (neighbors/cell_list.py + the ops' j-loops)
remains the portable fallback; this engine is used on TPU where the
gather rate, not FLOPs, limits throughput.
"""

import functools
from typing import Any, Callable, List, NamedTuple, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from sphexa_tpu.dtypes import KEY_BITS, KEY_DTYPE
from sphexa_tpu.neighbors.cell_list import NeighborConfig, _window_offsets
from sphexa_tpu.sfc.box import Box
from sphexa_tpu.sfc.hilbert import hilbert_encode
from sphexa_tpu.sfc.morton import morton_encode

GROUP = 128  # targets per group: one f32 lane row


class PairGeom(NamedTuple):
    """Per-(target, candidate) geometry handed to the pair body."""

    rx: jax.Array     # (G, cap) x_i - x_j, minimum image
    ry: jax.Array
    rz: jax.Array
    d2: jax.Array     # squared distance
    mask: jax.Array   # valid pair: in-range candidate, within 2h_i, not self


def group_cell_ranges(x, y, z, h, sorted_keys, box: Box, cfg: NeighborConfig):
    """(starts, lens, occupancy) of every group's window cells.

    Vectorized over all groups (the jax-side prologue both the engine and
    find_neighbors share conceptually); starts index the SFC-sorted
    arrays, lens <= cap. occupancy encodes the cap AND window guards like
    find_neighbors.
    """
    n = x.shape[0]
    level = cfg.level
    shift = KEY_DTYPE(3 * (KEY_BITS - level))
    ncell = 1 << level
    encode = hilbert_encode if cfg.curve == "hilbert" else morton_encode
    edge = box.lengths / ncell
    periodic = box.periodic_mask

    g = GROUP
    num_groups = -(-n // g)
    pad = num_groups * g - n
    gather_pad = lambda a: jnp.concatenate([a, jnp.broadcast_to(a[-1:], (pad,))]) if pad else a
    xg = gather_pad(x).reshape(num_groups, g)
    yg = gather_pad(y).reshape(num_groups, g)
    zg = gather_pad(z).reshape(num_groups, g)
    hg = gather_pad(h).reshape(num_groups, g)

    lo = jnp.stack([xg.min(1), yg.min(1), zg.min(1)], axis=1)  # (NG, 3)
    hi = jnp.stack([xg.max(1), yg.max(1), zg.max(1)], axis=1)
    radius = 2.0 * hg.max(1)  # (NG,)
    box_lo = jnp.stack([box.lo[0], box.lo[1], box.lo[2]])
    base = jnp.floor((lo - radius[:, None] - box_lo) / edge).astype(jnp.int32)
    need = jnp.floor((hi + radius[:, None] - box_lo) / edge).astype(jnp.int32)
    # open dims: cells outside [0, ncell) don't exist — slide the window
    # inside the grid (never loses coverage); a window spanning the whole
    # grid always covers
    base = jnp.where(
        periodic[None, :], base,
        jnp.clip(base, 0, max(0, ncell - cfg.window)),
    )
    need_eff = jnp.where(periodic[None, :], need, jnp.minimum(need, ncell - 1))
    window_ok = jnp.all((need_eff - base + 1 <= cfg.window) | (cfg.window >= ncell))

    offsets = jnp.asarray(_window_offsets(cfg.window))  # (W3, 3)
    cells = base[:, None, :] + offsets[None, :, :]  # (NG, W3, 3)
    wrapped = jnp.mod(cells, ncell)
    in_range = (cells >= 0) & (cells < ncell)
    unique = offsets[None, :, :] < ncell
    cell_ok = jnp.all(
        jnp.where(periodic[None, None, :], unique, in_range), axis=-1
    )  # (NG, W3)
    cells = jnp.where(
        periodic[None, None, :], wrapped, jnp.clip(cells, 0, ncell - 1)
    )

    ckey = encode(
        cells[..., 0].astype(KEY_DTYPE),
        cells[..., 1].astype(KEY_DTYPE),
        cells[..., 2].astype(KEY_DTYPE),
        bits=level,
    )
    start = jnp.searchsorted(sorted_keys, ckey << shift).astype(jnp.int32)
    end = jnp.searchsorted(sorted_keys, (ckey + KEY_DTYPE(1)) << shift).astype(
        jnp.int32
    )
    raw_len = end - start
    occupancy = jnp.where(window_ok, jnp.max(raw_len), jnp.int32(cfg.cap + 1))
    lens = jnp.where(cell_ok, jnp.minimum(raw_len, cfg.cap), 0)
    return start, lens, occupancy


def _round_up(v: int, q: int) -> int:
    return -(-v // q) * q


def _dma_geometry(cap: int):
    """(span, buf_rows): each cell range [s, s+len) is covered by an
    8-row-aligned DMA window of buf_rows rows; the valid range sits at
    offset s % 128 within the first ``span`` slots. SINGLE source of truth
    — the kernel's transfer shape and _prep's tail padding must agree or
    the DMA reads out of bounds."""
    span = _round_up(128 + cap, 128)
    buf_rows = max(8, _round_up(span, 1024) // 128)
    return span, buf_rows


def group_pair_engine(
    pair_body: Callable,
    finalize: Callable,
    num_i: int,
    num_j: int,
    num_acc: int,
    cfg: NeighborConfig,
    interpret: bool = False,
):
    """Build a pallas_call for one SPH pair op.

    - ``pair_body(geom, i_fields, j_fields, accs) -> accs``: per-cell pair
      math on (G, cap) tiles; i_fields are (G, 1) columns, j_fields are
      (1, cap) rows; accs is a tuple of (G, 1) f32 accumulators.
    - ``finalize(i_fields, accs, nc) -> outs``: per-target epilogue; outs
      is a tuple of (G,) arrays (f32), one per output.
    - ``num_i``/``num_j``: how many target/candidate fields follow
      (x, y, z, h are always fields 0-3 on both sides).
    - returns fn(starts, lens, boxl, i_fields(NG,G) x num_i,
      j_fields(n_pad,) x num_j) -> (outs (NG, G) x num_out, nc (NG, G)).
    """
    w3 = cfg.window**3
    span, buf_rows = _dma_geometry(cfg.cap)

    def kernel(*refs):
        starts, lens, boxl = refs[0], refs[1], refs[2]
        i_refs = refs[3 : 3 + num_i]
        j_refs = refs[3 + num_i : 3 + num_i + num_j]
        out_refs = refs[3 + num_i + num_j : -2 - num_j]
        nc_ref = refs[-2 - num_j]
        bufs = refs[-1 - num_j : -1]
        sems = refs[-1]

        gi = pl.program_id(0)
        G = GROUP

        def dma(w, slot):
            row_s = starts[0, 0, w] // 128
            return [
                pltpu.make_async_copy(
                    j_refs[f].at[pl.ds(row_s, buf_rows), :],
                    bufs[f].at[slot],
                    sems.at[slot, f],
                )
                for f in range(num_j)
            ]

        for d in dma(0, 0):
            d.start()

        i_fields = [r[0, 0][:, None] for r in i_refs]  # (G, 1) each
        xi, yi, zi, hi = i_fields[:4]
        lx, ly, lz = boxl[0, 0, 0], boxl[0, 0, 1], boxl[0, 0, 2]
        tgt_idx = gi * G + jax.lax.broadcasted_iota(jnp.int32, (G, 1), 0)
        span_iota = jax.lax.broadcasted_iota(jnp.int32, (1, span), 1)

        def body(w, carry):
            accs, nc_acc = carry
            slot = w % 2

            @pl.when(w + 1 < w3)
            def _():
                for d in dma(w + 1, (w + 1) % 2):
                    d.start()

            for d in dma(w, slot):
                d.wait()

            s = starts[0, 0, w]
            ln = lens[0, 0, w]
            off = s - (s // 128) * 128
            j_fields = [
                bufs[f][slot].reshape(1, buf_rows * 128)[:, :span]
                for f in range(num_j)
            ]  # (1, span)
            cx, cy, cz = j_fields[0], j_fields[1], j_fields[2]

            rx = xi - cx
            ry = yi - cy
            rz = zi - cz
            rx = rx - lx * jnp.round(rx / lx)
            ry = ry - ly * jnp.round(ry / ly)
            rz = rz - lz * jnp.round(rz / lz)
            d2 = rx * rx + ry * ry + rz * rz

            cand_idx = (s - off) + span_iota
            mask = (
                (span_iota >= off)
                & (span_iota < off + ln)
                & (d2 < 4.0 * hi * hi)
                & (cand_idx != tgt_idx)
            )
            geom = PairGeom(rx=rx, ry=ry, rz=rz, d2=d2, mask=mask)
            accs = pair_body(geom, i_fields, j_fields, accs)
            nc_acc = nc_acc + jnp.sum(mask, axis=1, keepdims=True)
            return accs, nc_acc

        acc0 = tuple(jnp.zeros((G, 1), jnp.float32) for _ in range(num_acc))
        nc0 = jnp.zeros((G, 1), jnp.int32)
        accs, nc_acc = jax.lax.fori_loop(0, w3, body, (acc0, nc0))

        outs = finalize(i_fields, accs, nc_acc)
        for r, o in zip(out_refs, outs):
            r[0, 0] = o.reshape(GROUP)
        nc_ref[0, 0] = nc_acc.reshape(GROUP)

    def call(starts, lens, boxl, i_fields: Sequence, j_fields: Sequence):
        num_groups = starts.shape[0]
        starts = starts.reshape(num_groups, 1, w3)
        lens = lens.reshape(num_groups, 1, w3)
        boxl = boxl.reshape(1, 1, 3)
        i_fields = [a.reshape(num_groups, 1, GROUP) for a in i_fields]
        num_out_arrays = len(
            finalize(
                [jnp.zeros((GROUP, 1))] * num_i,
                tuple(jnp.zeros((GROUP, 1)) for _ in range(num_acc)),
                jnp.zeros((GROUP, 1), jnp.int32),
            )
        )
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=0,
            grid=(num_groups,),
            in_specs=[
                pl.BlockSpec((1, 1, w3), lambda g: (g, 0, 0), memory_space=pltpu.SMEM),
                pl.BlockSpec((1, 1, w3), lambda g: (g, 0, 0), memory_space=pltpu.SMEM),
                pl.BlockSpec((1, 1, 3), lambda g: (0, 0, 0), memory_space=pltpu.SMEM),
            ]
            + [
                pl.BlockSpec((1, 1, GROUP), lambda g: (g, 0, 0))
                for _ in range(num_i)
            ]
            + [pl.BlockSpec(memory_space=pl.ANY) for _ in range(num_j)],
            out_specs=[
                pl.BlockSpec((1, 1, GROUP), lambda g: (g, 0, 0))
                for _ in range(num_out_arrays)
            ]
            + [pl.BlockSpec((1, 1, GROUP), lambda g: (g, 0, 0))],
            scratch_shapes=[
                pltpu.VMEM((2, buf_rows, 128), jnp.float32) for _ in range(num_j)
            ]
            + [pltpu.SemaphoreType.DMA((2, num_j))],
        )
        out_shape = [
            jax.ShapeDtypeStruct((num_groups, 1, GROUP), jnp.float32)
            for _ in range(num_out_arrays)
        ] + [jax.ShapeDtypeStruct((num_groups, 1, GROUP), jnp.int32)]
        outs = pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=out_shape,
            interpret=interpret,
        )(starts, lens, boxl, *i_fields, *j_fields)
        return outs

    return call


def _prep(x, y, z, h, extra_i, extra_j, box: Box, cfg: NeighborConfig):
    """Common jax-side prologue: padded/blocked field layouts.

    j-side fields are reshaped (rows, 128) so the kernel can DMA 8-row
    aligned windows; the tail is padded by one full window so a range
    starting at the last particle still reads in-bounds garbage (masked).
    """
    n = x.shape[0]
    _, buf_rows = _dma_geometry(cfg.cap)
    pad_tail = buf_rows * 128
    num_groups = -(-n // GROUP)
    pad_i = num_groups * GROUP - n

    def block_i(a):
        a = jnp.concatenate([a, jnp.broadcast_to(a[-1:], (pad_i,))]) if pad_i else a
        return a.reshape(num_groups, GROUP)

    def pad_j(a):
        rows = _round_up(n + pad_tail, 128) // 128
        out = jnp.zeros(rows * 128, a.dtype)
        return out.at[:n].set(a).reshape(rows, 128)

    # open dims use an effectively-infinite period so the fold is a no-op
    big = jnp.float32(1e30)
    boxl = jnp.where(box.periodic_mask, box.lengths, big).astype(jnp.float32)
    boxl = boxl.reshape(1, 3)

    i_fields = [block_i(a) for a in (x, y, z, h, *extra_i)]
    j_fields = [pad_j(a) for a in (x, y, z, *extra_j)]
    return i_fields, j_fields, boxl, num_groups


def pallas_density(
    x, y, z, h, m, sorted_keys, box: Box, const, cfg: NeighborConfig,
    ranges=None, interpret: bool = False,
):
    """rho_i = K h_i^-3 (m_i + sum_j m_j W(|r_ij|/h_i)) + neighbor counts.

    Pallas instantiation of hydro_std.compute_density (density.hpp:41) with
    the search fused in. Returns (rho (n,), nc (n,), occupancy).
    """
    n = x.shape[0]
    sinc_n = _int_sinc_index(const)
    K = float(const.K)

    starts, lens, occ = (
        ranges
        if ranges is not None
        else group_cell_ranges(x, y, z, h, sorted_keys, box, cfg)
    )

    def pair_body(geom, i_fields, j_fields, accs):
        (rho_sum,) = accs
        hi = i_fields[3]
        mj = j_fields[3]
        w = _sinc_w(geom.d2, hi, sinc_n)
        rho_sum = rho_sum + jnp.sum(
            jnp.where(geom.mask, mj * w, 0.0), axis=1, keepdims=True
        )
        return (rho_sum,)

    def finalize(i_fields, accs, nc):
        hi = i_fields[3]
        mi = i_fields[4]
        (rho_sum,) = accs
        rho = K * (mi + rho_sum) / (hi * hi * hi)
        return (rho,)

    engine = group_pair_engine(
        pair_body, finalize, num_i=5, num_j=4, num_acc=1, cfg=cfg,
        interpret=interpret,
    )
    i_fields, j_fields, boxl, _ = _prep(x, y, z, h, (m,), (m,), box, cfg)
    rho, nc = engine(starts, lens, boxl, i_fields, j_fields)
    return rho.reshape(-1)[:n], nc.reshape(-1)[:n], occ


def _int_sinc_index(const) -> int:
    """The pallas kernels unroll the sinc power; fractional indices must
    use the XLA backend."""
    n = int(const.sinc_index)
    if const.sinc_index != n:
        raise ValueError(
            f"pallas backend supports integer sinc indices only "
            f"(got {const.sinc_index}); use backend='xla'"
        )
    return n


def _sinc_w(d2, hi, sinc_n: int):
    """sinc^n kernel on (G, span) tiles from squared distance and h_i."""
    v = jnp.sqrt(d2) / hi
    pv = (0.5 * np.pi) * v
    sinc = jnp.where(v > 0.0, jnp.sin(pv) / jnp.where(v > 0.0, pv, 1.0), 1.0)
    w = sinc
    for _ in range(sinc_n - 1):
        w = w * sinc
    return w


def pallas_iad(
    x, y, z, h, vol, sorted_keys, box: Box, const, cfg: NeighborConfig,
    ranges=None, interpret: bool = False,
):
    """IAD tensor components (hydro_std.compute_iad, iad_kern.hpp) with the
    neighbor search fused in. ``vol`` is the per-particle volume estimate
    (m/rho std, xm/kx VE). Returns (c11..c33, occupancy)."""
    n = x.shape[0]
    sinc_n = _int_sinc_index(const)
    K = float(const.K)

    starts, lens, occ = (
        ranges
        if ranges is not None
        else group_cell_ranges(x, y, z, h, sorted_keys, box, cfg)
    )

    def pair_body(geom, i_fields, j_fields, accs):
        hi = i_fields[3]
        vj = j_fields[3]
        w = _sinc_w(geom.d2, hi, sinc_n)
        vw = jnp.where(geom.mask, vj * w, 0.0)
        terms = (
            geom.rx * geom.rx, geom.rx * geom.ry, geom.rx * geom.rz,
            geom.ry * geom.ry, geom.ry * geom.rz, geom.rz * geom.rz,
        )
        return tuple(
            acc + jnp.sum(t * vw, axis=1, keepdims=True)
            for acc, t in zip(accs, terms)
        )

    def finalize(i_fields, accs, nc):
        hi = i_fields[3]
        t11, t12, t13, t22, t23, t33 = accs
        # exponent renormalization (iad_kern.hpp ilogb/ldexp trick) via
        # exp2/log2 — exact because the factor cancels in adj/det
        exp_of = lambda v: jnp.where(
            v != 0.0, jnp.floor(jnp.log2(jnp.abs(v) + 1e-45)), 0.0
        )
        esum = (exp_of(t11) + exp_of(t12) + exp_of(t13)
                + exp_of(t22) + exp_of(t23) + exp_of(t33))
        norm = jnp.exp2(-jnp.floor(esum / 6.0))
        t11, t12, t13 = t11 * norm, t12 * norm, t13 * norm
        t22, t23, t33 = t22 * norm, t23 * norm, t33 * norm
        det = (t11 * t22 * t33 + 2.0 * t12 * t23 * t13
               - t11 * t23 * t23 - t22 * t13 * t13 - t33 * t12 * t12)
        factor = norm * (hi * hi * hi) / (det * K)
        return (
            (t22 * t33 - t23 * t23) * factor,
            (t13 * t23 - t33 * t12) * factor,
            (t12 * t23 - t22 * t13) * factor,
            (t11 * t33 - t13 * t13) * factor,
            (t13 * t12 - t11 * t23) * factor,
            (t11 * t22 - t12 * t12) * factor,
        )

    engine = group_pair_engine(
        pair_body, finalize, num_i=4, num_j=4, num_acc=6, cfg=cfg,
        interpret=interpret,
    )
    i_fields, j_fields, boxl, _ = _prep(x, y, z, h, (), (vol,), box, cfg)
    *cs, _nc = engine(starts, lens, boxl, i_fields, j_fields)
    return tuple(c.reshape(-1)[:n] for c in cs), occ


def pallas_momentum_energy_std(
    x, y, z, vx, vy, vz, h, m, rho, p, c,
    c11, c12, c13, c22, c23, c33,
    sorted_keys, box: Box, const, cfg: NeighborConfig,
    ranges=None, interpret: bool = False,
):
    """Pressure-gradient accelerations + energy rate + Courant dt
    (hydro_std.compute_momentum_energy_std, momentum_energy_kern.hpp:12-134)
    with the neighbor search fused in. Returns (ax, ay, az, du, min_dt, occ).
    """
    n = x.shape[0]
    sinc_n = _int_sinc_index(const)
    K = float(const.K)
    k_cour = float(const.k_cour)

    starts, lens, occ = (
        ranges
        if ranges is not None
        else group_cell_ranges(x, y, z, h, sorted_keys, box, cfg)
    )

    def pair_body(geom, i_fields, j_fields, accs):
        momx, momy, momz, energy, maxvs = accs
        (xi, yi, zi, hi, vxi, vyi, vzi, ci, rhoi, pi, mi,
         c11i, c12i, c13i, c22i, c23i, c33i) = i_fields
        (cx, cy, cz, hj, vxj, vyj, vzj, cj, rhoj, pj, mj,
         c11j, c12j, c13j, c22j, c23j, c33j) = j_fields

        dist = jnp.sqrt(jnp.where(geom.mask, geom.d2, 1.0))
        dist = jnp.where(geom.mask, dist, 1.0)
        w_i = _sinc_w(geom.d2, hi, sinc_n) / (hi * hi * hi)
        v2 = jnp.clip(dist / hj, 0.0, 2.0)
        pv = (0.5 * np.pi) * v2
        sincj = jnp.where(v2 > 0.0, jnp.sin(pv) / jnp.where(v2 > 0.0, pv, 1.0), 1.0)
        w_j = sincj
        for _ in range(sinc_n - 1):
            w_j = w_j * sincj
        w_j = w_j / (hj * hj * hj)

        vx_ij = vxi - vxj
        vy_ij = vyi - vyj
        vz_ij = vzi - vzj
        rv = geom.rx * vx_ij + geom.ry * vy_ij + geom.rz * vz_ij
        w_ij = rv / dist

        # Monaghan constant-alpha AV, halved per pair (kernels.hpp:60-84)
        v_signal = 0.5 * (ci + cj) - 2.0 * w_ij
        visc = 0.5 * jnp.where(w_ij < 0.0, -v_signal * w_ij, 0.0)

        vijsignal = ci + cj - 3.0 * w_ij
        maxvs = jnp.maximum(
            maxvs, jnp.max(jnp.where(geom.mask, vijsignal, 0.0), axis=1,
                           keepdims=True)
        )

        tA1_i = c11i * geom.rx + c12i * geom.ry + c13i * geom.rz
        tA2_i = c12i * geom.rx + c22i * geom.ry + c23i * geom.rz
        tA3_i = c13i * geom.rx + c23i * geom.ry + c33i * geom.rz
        tA1_j = c11j * geom.rx + c12j * geom.ry + c13j * geom.rz
        tA2_j = c12j * geom.rx + c22j * geom.ry + c23j * geom.rz
        tA3_j = c13j * geom.rx + c23j * geom.ry + c33j * geom.rz

        mj_pro_i = mj * pi / (rhoi * rhoi)
        mj_roj_wj = mj / rhoj * w_j
        mi_roi = mi / rhoi

        a = w_i * (mj_pro_i + visc * mi_roi)
        b = mj_roj_wj * (pj / rhoj + visc)
        mm = geom.mask
        momx = momx + jnp.sum(jnp.where(mm, a * tA1_i + b * tA1_j, 0.0), 1, keepdims=True)
        momy = momy + jnp.sum(jnp.where(mm, a * tA2_i + b * tA2_j, 0.0), 1, keepdims=True)
        momz = momz + jnp.sum(jnp.where(mm, a * tA3_i + b * tA3_j, 0.0), 1, keepdims=True)

        a_e = w_i * (2.0 * mj_pro_i + visc * mi_roi)
        b_e = visc * mj_roj_wj
        energy = energy + jnp.sum(
            jnp.where(
                mm,
                vx_ij * (a_e * tA1_i + b_e * tA1_j)
                + vy_ij * (a_e * tA2_i + b_e * tA2_j)
                + vz_ij * (a_e * tA3_i + b_e * tA3_j),
                0.0,
            ),
            1, keepdims=True,
        )
        return momx, momy, momz, energy, maxvs

    def finalize(i_fields, accs, nc):
        hi = i_fields[3]
        ci = i_fields[7]
        momx, momy, momz, energy, maxvs = accs
        du = -K * 0.5 * energy
        v = jnp.where(maxvs > 0.0, maxvs, ci)
        dt_i = k_cour * hi / v
        return (K * momx, K * momy, K * momz, du, dt_i)

    engine = group_pair_engine(
        pair_body, finalize, num_i=17, num_j=17, num_acc=5, cfg=cfg,
        interpret=interpret,
    )
    i_fields, j_fields, boxl, _ = _prep(
        x, y, z, h,
        (vx, vy, vz, c, rho, p, m, c11, c12, c13, c22, c23, c33),
        (h, vx, vy, vz, c, rho, p, m, c11, c12, c13, c22, c23, c33),
        box, cfg,
    )
    ax, ay, az, du, dt_i, _nc = engine(starts, lens, boxl, i_fields, j_fields)
    f = lambda a: a.reshape(-1)[:n]
    return f(ax), f(ay), f(az), f(du), jnp.min(f(dt_i)), occ
