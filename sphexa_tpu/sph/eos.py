"""Equations of state.

Counterpart of the reference's ``sph/include/sph/eos.hpp``: the
temperature-based and u-based ideal gas forms and the polytropic
neutron-star EOS. The std/VE pipelines call their fused variants in
hydro_std/hydro_ve; this module is the standalone catalog.
"""

from typing import Tuple

import jax
import jax.numpy as jnp

from sphexa_tpu.sph.particles import SimConstants, ideal_gas_cv

# Kpol for a 1.4 M_sun, 12.8 km neutron star (eos.hpp:52-53); not valid
# for other masses/radii
KPOL_NS = 2.246341237993810232e-10
GAMMA_POL = 3.0


def ideal_gas_eos(temp, rho, mui: float, gamma: float) -> Tuple[jax.Array, jax.Array]:
    """(p, c) from temperature (eos.hpp:31-41)."""
    tmp = ideal_gas_cv(mui, gamma) * temp * (gamma - 1.0)
    return rho * tmp, jnp.sqrt(tmp)


def ideal_gas_eos_u(u, rho, gamma: float) -> Tuple[jax.Array, jax.Array]:
    """(p, c) from specific internal energy: p = (gamma-1) rho u."""
    tmp = u * (gamma - 1.0)
    return rho * tmp, jnp.sqrt(gamma * tmp)


def polytropic_eos(rho, k_pol: float = KPOL_NS, gamma_pol: float = GAMMA_POL):
    """(p, c) for a polytrope p = K rho^Gamma (eos.hpp:43-60)."""
    p = k_pol * rho**gamma_pol
    c = jnp.sqrt(gamma_pol * p / jnp.maximum(rho, 1e-30))
    return p, c
