"""Turbulence stirring: Ornstein-Uhlenbeck forcing in Fourier modes.

TPU-native counterpart of the reference's ``sph/include/sph/hydro_turb/``
(turbulence_data.hpp, create_modes.hpp, driver.hpp, phases.hpp,
stirring.hpp): an OU process drives a fixed set of Fourier modes whose
Helmholtz (solenoidal/compressive) projection accelerates the gas
(Eswaran & Pope 1988 forcing, Mach-controlled).

Differences from the reference by design:
- the OU random stream is a jax PRNG key carried in the (checkpointable)
  TurbulenceState pytree instead of a host mt19937, so the whole update
  runs inside the jitted step;
- the per-particle stirring sum over modes is phrased as two (N,M) x (M,3)
  matmuls (cos/sin of the phase matrix), which XLA tiles onto the MXU
  instead of the reference's per-particle mode loop (stirring.hpp:42-78).
"""

import dataclasses
from typing import Dict, Tuple

import numpy as np
import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class TurbulenceConfig:
    """Static stirring parameters (turbulence_data.hpp:57-71,155-175)."""

    num_modes: int
    sol_weight: float
    sol_weight_norm: float
    decay_time: float
    variance: float
    ndim: int = 3


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class TurbulenceState:
    """Checkpointable stirring state: fixed mode table + OU phases + RNG key
    (the reference serializes phases and the mt19937 stream the same way,
    turbulence_data.hpp:88-100)."""

    modes: jax.Array       # (M, 3) wave vectors
    amplitudes: jax.Array  # (M,) spectrum amplitudes
    phases: jax.Array      # (M, 3, 2) OU phases, [..., 0]=real, [..., 1]=imag
    key: jax.Array         # jax PRNG key


def create_stirring_modes(
    lbox: float,
    st_max_modes: int = 100000,
    energy_prefac: float = 5.0e-3,
    mach_velocity: float = 0.3,
    sol_weight: float = 0.5,
    spect_form: int = 1,
    ndim: int = 3,
    seed: int = 251299,
    eps: float = 1e-15,
    power_law_exp: float = 5.0 / 3.0,
    angles_exp: float = 2.0,
) -> Tuple[TurbulenceConfig, TurbulenceState]:
    """Build the stirring mode table + initial OU state.

    Mirrors TurbulenceData's constructor pipeline: stirring band
    k in [2pi/L, 3*2pi/L], band (spect_form=0), parabolic (=1) or
    power-law random-angle (=2, create_modes.hpp:179-238) spectrum,
    mirrored +-ky/+-kz modes (create_modes.hpp:30-160), OU variance from
    the target Mach energy input rate.
    """
    if spect_form not in (0, 1, 2):
        raise ValueError("spect_form must be 0 (band), 1 (parabolic) or "
                         "2 (power law)")
    twopi = 2.0 * np.pi
    velocity = mach_velocity
    energy = energy_prefac * velocity**3 / lbox
    stir_min = (1.0 - eps) * twopi / lbox
    stir_max = (3.0 + eps) * twopi / lbox
    decay_time = lbox / (2.0 * velocity)
    variance = np.sqrt(energy / decay_time)
    sol_weight_norm = (
        np.sqrt(3.0) * np.sqrt(3.0 / ndim)
        / np.sqrt(1.0 - 2.0 * sol_weight + ndim * sol_weight**2)
    )

    kc = 0.5 * (stir_min + stir_max) if spect_form == 1 else stir_min
    parab_prefact = -4.0 / (stir_max - stir_min) ** 2

    ik_max = int(np.ceil(stir_max / twopi * lbox)) + 1
    modes, amplitudes = [], []
    if spect_form == 2:
        # power-law spectrum, random-angle shell sampling
        # (create_modes.hpp:179-238): nang ~ 2^ndim ceil(ik^anglesExp)
        # directions per k-shell, amplitude (k/kc)^powerLawExp with the
        # angle-count correction
        rng = np.random.default_rng(seed)
        ik_min = max(1, int(stir_min * lbox / twopi + 0.5))
        ik_hi = int(stir_max * lbox / twopi + 0.5)
        for ik in range(ik_min, ik_hi + 1):
            nang = int(2**ndim * np.ceil(ik**angles_exp))
            for _ in range(nang):
                phi = twopi * rng.uniform()
                theta = (np.arccos(1.0 - 2.0 * rng.uniform())
                         if ndim > 2 else 0.5 * np.pi)
                rand = ik + rng.uniform() - 0.5
                kx = twopi * np.round(rand * np.sin(theta) * np.cos(phi)) / lbox
                ky = (twopi * np.round(rand * np.sin(theta) * np.sin(phi)) / lbox
                      if ndim > 1 else 0.0)
                kz = (twopi * np.round(rand * np.cos(theta)) / lbox
                      if ndim > 2 else 0.0)
                k = np.sqrt(kx**2 + ky**2 + kz**2)
                if not (stir_min <= k <= stir_max):
                    continue
                # PARITY NOTE: the reference computes pow(k/kc, +powerLawExp)
                # with default powerLawExp = 5/3 (create_modes.hpp:222,
                # turbulence_init.hpp:61) — a spectrum RISING with k over
                # the driving band; reproduced verbatim. A decaying
                # Kolmogorov band needs powerLawExp = -5/3 in the settings.
                amp = (k / kc) ** power_law_exp
                amp = np.sqrt(
                    amp * (ik ** (ndim - 1) * 4.0 * np.sqrt(3.0) / nang)
                ) * (kc / k) ** (0.5 * (ndim - 1))
                modes.append((kx, ky, kz))
                amplitudes.append(amp)
                if len(modes) > st_max_modes:
                    raise ValueError(
                        f"too many stirring modes ({len(modes)} > {st_max_modes})"
                    )
    else:
      for ikx in range(0, ik_max + 1):
        kx = twopi * ikx / lbox
        for iky in range(0, ik_max + 1 if ndim > 1 else 1):
            ky = twopi * iky / lbox
            for ikz in range(0, ik_max + 1 if ndim > 2 else 1):
                kz = twopi * ikz / lbox
                k = np.sqrt(kx**2 + ky**2 + kz**2)
                if not (stir_min <= k <= stir_max):
                    continue
                amp = 1.0
                if spect_form == 1:
                    amp = abs(parab_prefact * (k - kc) ** 2 + 1.0)
                amp = 2.0 * np.sqrt(amp) * (kc / k) ** (0.5 * (ndim - 1))
                # mirrored sign combinations of ky/kz cover the half-space
                # of independent modes (create_modes.hpp:126-158)
                signsets = [(kx, ky, kz)]
                if ndim > 1:
                    signsets.append((kx, -ky, kz))
                if ndim > 2:
                    signsets += [(kx, ky, -kz), (kx, -ky, -kz)]
                for kvec in signsets:
                    modes.append(kvec)
                    amplitudes.append(amp)
                if len(modes) > st_max_modes:
                    raise ValueError(
                        f"too many stirring modes ({len(modes)} > {st_max_modes})"
                    )

    m = len(modes)
    cfg = TurbulenceConfig(
        num_modes=m,
        sol_weight=sol_weight,
        sol_weight_norm=float(sol_weight_norm),
        decay_time=float(decay_time),
        variance=float(variance),
        ndim=ndim,
    )
    key = jax.random.PRNGKey(seed)
    key, sub = jax.random.split(key)
    phases = variance * jax.random.normal(sub, (m, 3, 2), dtype=jnp.float32)
    state = TurbulenceState(
        modes=jnp.asarray(np.asarray(modes), jnp.float32),
        amplitudes=jnp.asarray(np.asarray(amplitudes), jnp.float32),
        phases=phases,
        key=key,
    )
    return cfg, state


def update_noise(
    turb: TurbulenceState, dt, cfg: TurbulenceConfig
) -> TurbulenceState:
    """One OU step: x' = f x + sigma sqrt(1 - f^2) z, f = exp(-dt/ts)
    (driver.hpp:43-91, Bartosch 2001)."""
    damping_a = jnp.exp(-dt / cfg.decay_time)
    damping_b = jnp.sqrt(1.0 - damping_a**2)
    key, sub = jax.random.split(turb.key)
    z = jax.random.normal(sub, turb.phases.shape, dtype=turb.phases.dtype)
    phases = turb.phases * damping_a + cfg.variance * damping_b * z
    return dataclasses.replace(turb, phases=phases, key=key)


def compute_phases(turb: TurbulenceState, cfg: TurbulenceConfig):
    """Helmholtz projection of the OU phases: solenoidal weight sw blends
    the curl (divergence-free) and div (compressive) parts per mode
    (phases.hpp:45-71). Returns (phases_real, phases_imag), each (M, 3)."""
    k = turb.modes                       # (M, 3)
    ph_re = turb.phases[..., 0]          # (M, 3)
    ph_im = turb.phases[..., 1]
    kk = jnp.sum(k * k, axis=1, keepdims=True)
    ka = jnp.sum(k * ph_im, axis=1, keepdims=True)
    kb = jnp.sum(k * ph_re, axis=1, keepdims=True)
    diva = k * ka / kk
    divb = k * kb / kk
    curla = ph_re - divb
    curlb = ph_im - diva
    sw = cfg.sol_weight
    return sw * curla + (1.0 - sw) * divb, sw * curlb + (1.0 - sw) * diva


def st_calc_accel(
    x, y, z, turb: TurbulenceState, cfg: TurbulenceConfig,
    phases_real, phases_imag,
):
    """Stirring accelerations: a_i += norm * sum_m amp_m Re[(P_m) e^{i k_m x_i}]
    (stirring.hpp stirParticle), phrased as (N,M)@(M,3) matmuls."""
    kdotx = (
        x[:, None] * turb.modes[None, :, 0]
        + y[:, None] * turb.modes[None, :, 1]
        + z[:, None] * turb.modes[None, :, 2]
    )                                    # (N, M)
    ck = jnp.cos(kdotx)
    sk = jnp.sin(kdotx)
    amp_pr = turb.amplitudes[:, None] * phases_real   # (M, 3)
    amp_pi = turb.amplitudes[:, None] * phases_imag
    acc = cfg.sol_weight_norm * (ck @ amp_pr - sk @ amp_pi)  # (N, 3)
    return acc[:, 0], acc[:, 1], acc[:, 2]


def drive_turbulence(
    x, y, z, ax, ay, az, dt, turb: TurbulenceState, cfg: TurbulenceConfig
) -> Tuple[jax.Array, jax.Array, jax.Array, TurbulenceState]:
    """OU update + projection + stirring add, one step (driver.hpp:104-130).
    Returns updated accelerations and the advanced TurbulenceState."""
    turb = update_noise(turb, dt, cfg)
    pr, pi = compute_phases(turb, cfg)
    tx, ty, tz = st_calc_accel(x, y, z, turb, cfg, pr, pi)
    return ax + tx, ay + ty, az + tz, turb


def turbulence_state_to_fields(
    turb: TurbulenceState, cfg: TurbulenceConfig
) -> Dict[str, np.ndarray]:
    """Flatten the stirring state AND config scalars into named arrays for
    checkpointing — a restart must resume the same forcing (variance,
    decay time, solenoidal weight), not rebuilt defaults
    (turbulence_data.hpp:88-100 serializes the same set)."""
    return {
        "turb_modes": np.asarray(turb.modes),
        "turb_amplitudes": np.asarray(turb.amplitudes),
        "turb_phases": np.asarray(turb.phases),
        "turb_key": np.asarray(turb.key),
        "turb_cfg": np.asarray(
            [cfg.sol_weight, cfg.sol_weight_norm, cfg.decay_time,
             cfg.variance, float(cfg.ndim)],
            np.float64,
        ),
    }


def turbulence_state_from_fields(
    fields: Dict[str, np.ndarray]
) -> Tuple[TurbulenceState, TurbulenceConfig]:
    """Inverse of turbulence_state_to_fields (restart path)."""
    state = TurbulenceState(
        modes=jnp.asarray(fields["turb_modes"]),
        amplitudes=jnp.asarray(fields["turb_amplitudes"]),
        phases=jnp.asarray(fields["turb_phases"]),
        key=jnp.asarray(fields["turb_key"]),
    )
    sw, swn, ts, var, ndim = (float(v) for v in fields["turb_cfg"])
    cfg = TurbulenceConfig(
        num_modes=state.modes.shape[0],
        sol_weight=sw,
        sol_weight_norm=swn,
        decay_time=ts,
        variance=var,
        ndim=int(ndim),
    )
    return state, cfg
