"""Time integration: 2nd-order Press position update + Adams-Bashforth energy.

Physics-equivalent of the reference's ``sph/positions.hpp``: the previous
step's position *deltas* (x_m1 ...) act as the velocity memory, the
temperature is advanced from du/du_m1 with a 2nd-order Adams-Bashforth
step, and particles in fixed-boundary skin layers stay frozen.
"""

from typing import Tuple

import jax.numpy as jnp

from sphexa_tpu.sfc.box import BoundaryType, Box, put_in_box
from sphexa_tpu.sph.particles import SimConstants


def position_update(dt, dt_m1, x, y, z, ax, ay, az, dx_m1, dy_m1, dz_m1, box: Box):
    """Press 2nd-order update (positions.hpp:66-80).

    Returns new positions (PBC-folded), velocities, and the new deltas.
    """
    delta_a = dt + 0.5 * dt_m1
    delta_b = 0.5 * (dt + dt_m1)
    inv_dtm1 = 1.0 / dt_m1

    valx, valy, valz = dx_m1 * inv_dtm1, dy_m1 * inv_dtm1, dz_m1 * inv_dtm1
    vx = valx + ax * delta_a
    vy = valy + ay * delta_a
    vz = valz + az * delta_a
    dx = dt * valx + ax * delta_b * dt
    dy = dt * valy + ay * delta_b * dt
    dz = dt * valz + az * delta_b * dt

    pos = jnp.stack([x + dx, y + dy, z + dz], axis=-1)
    pos = put_in_box(box, pos)
    return pos[..., 0], pos[..., 1], pos[..., 2], vx, vy, vz, dx, dy, dz


def fixed_boundary_frozen(x, y, z, h, vx, vy, vz, box: Box):
    """Mask of particles frozen in fixed-boundary skin layers.

    Mirrors fbcCheck + the v==0 condition of updatePositionsHost
    (positions.hpp:46-101): stationary particles within 2h of a fixed wall
    do not move.
    """
    stationary = (vx == 0.0) & (vy == 0.0) & (vz == 0.0)
    frozen = jnp.zeros_like(stationary)
    for dim, coord in enumerate((x, y, z)):
        if box.boundaries[dim] == BoundaryType.fixed:
            near = (jnp.abs(box.hi[dim] - coord) < 2.0 * h) | (
                jnp.abs(coord - box.lo[dim]) < 2.0 * h
            )
            frozen = frozen | near
    return stationary & frozen


def energy_update(u_old, dt, dt_m1, du, du_m1, u_lo=None):
    """2nd-order Adams-Bashforth internal-energy step (positions.hpp:54-63).

    The exponential fallback keeps u positive under strong cooling.
    The reference accumulates u in DOUBLE; with ``u_lo`` given, the f32
    accumulation is COMPENSATED (two-sum): the returned (u_new, lo_new)
    pair carries the low bits the f32 sum would swallow (~u*eps per
    step, the dominant 200-step drift term at Sedov's central energies).
    """
    delta_a = 0.5 * dt * dt / dt_m1
    delta_b = dt + delta_a
    incr = du * delta_b - du_m1 * delta_a
    if u_lo is None:
        u_new = u_old + incr
        return jnp.where(
            u_new < 0.0,
            u_old * jnp.exp(u_new * dt / jnp.maximum(u_old, 1e-30)), u_new,
        )
    y = incr + u_lo
    s = u_old + y
    bb = s - u_old
    err = (u_old - (s - bb)) + (y - bb)
    neg = s < 0.0
    u_new = jnp.where(
        neg, u_old * jnp.exp(s * dt / jnp.maximum(u_old, 1e-30)), s
    )
    return u_new, jnp.where(neg, 0.0, err)


def compute_positions(
    state_fields: Tuple, ax, ay, az, dt, dt_m1, box: Box, const: SimConstants
):
    """Advance positions, velocities, and temperature for one step.

    ``state_fields`` = (x, y, z, x_m1, y_m1, z_m1, vx, vy, vz, h, temp,
    temp_lo, du, du_m1); returns the same tuple advanced. Equivalent of
    computePositions + updateTempHost (positions.hpp:115-164), with the
    compensated energy accumulation (see energy_update).
    """
    (x, y, z, x_m1, y_m1, z_m1, vx, vy, vz, h, temp, temp_lo, du,
     du_m1) = state_fields

    frozen = fixed_boundary_frozen(x, y, z, h, vx, vy, vz, box)
    nx, ny, nz, nvx, nvy, nvz, dx, dy, dz = position_update(
        dt, dt_m1, x, y, z, ax, ay, az, x_m1, y_m1, z_m1, box
    )
    keep = lambda new, old: jnp.where(frozen, old, new)
    nx, ny, nz = keep(nx, x), keep(ny, y), keep(nz, z)
    nvx, nvy, nvz = keep(nvx, vx), keep(nvy, vy), keep(nvz, vz)
    dx, dy, dz = keep(dx, x_m1), keep(dy, y_m1), keep(dz, z_m1)

    # compensate in TEMP units: converting the STATE through cv each
    # step (u = cv*T then back) re-rounds the large value twice per step
    # and defeats the carry; dividing only the small INCREMENT keeps the
    # per-step untracked error at ulp(increment), not ulp(u)
    n_temp, n_temp_lo = energy_update(
        temp, dt, dt_m1, du / const.cv, du_m1 / const.cv, u_lo=temp_lo
    )
    n_temp = jnp.where(frozen, temp, n_temp)
    n_temp_lo = jnp.where(frozen, temp_lo, n_temp_lo)
    n_du_m1 = jnp.where(frozen, du_m1, du)

    return (nx, ny, nz, dx, dy, dz, nvx, nvy, nvz, h, n_temp, n_temp_lo,
            du, n_du_m1)
