"""Hierarchical block time steps: per-particle power-of-two Δt bins.

The reference lineage's biggest untouched algorithmic lever (Bonsai's
block steps, Bédorf et al. 2014 §3.4; PAPERS.md): instead of advancing
every particle at the global minimum dt, each particle is assigned a bin
``k`` and kicked with ``dt_min * 2**k`` every ``2**k``-th substep.  On
deep-dynamic-range workloads (Sedov's cold quiet ambient around a hot
core, Evrard collapse, disks) almost the whole box sits in deep bins and
the particle-updates per unit sim-time drop by the bin-occupancy factor
— the complexity proxy the schema-v6 ``dt_bins`` telemetry event records
(no chip this round; docs/NEXT.md round-12 protocol).

Scheme (the classic synchronized block layout):

- ``B = dt_bins`` bins, cycle length ``C = 2**(B-1)`` substeps, each
  substep advancing ``ttot`` by the cycle's ``dt_min``;
- bin ``k`` is due at substep ``s`` iff ``(s+1) % 2**k == 0`` — bin 0
  every substep, the deepest bin once per cycle, and EVERY bin is due at
  ``s = C-1``, so the cycle boundary is a full synchronization point;
- at ``s = 0`` (right after the all-due substep) ``dt_min`` is
  recomputed with the SAME ``compute_timestep`` expression as the global
  path, and bins are reassigned from the elementwise limiter candidates
  every ``bin_sync_every``-th cycle;
- inactive particles drift ``x += v * dt_min`` each substep (they are
  force SOURCES at current positions); when a particle comes due, the
  accumulated drift is rebased away and one full Press update of size
  ``dt_min * 2**k`` runs from its last-kick position
  (propagator._integrate_and_finish_blockdt).

``dt_bins = 1`` degenerates to C = 1, every substep a sync, every
particle due, ``dt_eff = dt_min * 2**0`` — bitwise-identical to the
global-dt step (pinned in tests/test_blockdt.py).

The bin candidates are ELEMENTWISE mirrors of the timestep.py limiters
(which are global min-reductions): Courant ``k_cour*h/c`` and, under
gravity, ``eta_acc*sqrt(eps/|a|)``.  The VE rho limiter (``k_rho/|divv|``)
is not mirrored — plumbing divv out of the sharded force stage would
change the existing shard_map signature (and its lowering, which
dt_bins=None pins byte-identical); compressing regions have small
``h/c`` anyway, and the global ``dt_min`` keeps the rho bound.  The
Courant mirror uses the particle's own sound speed, not the pairwise
max signal speed the kernels min-reduce — the standard local estimate in
block-step codes; the deepest admissible bin is a heuristic, safety
comes from ``dt_min`` itself.
"""

import dataclasses

import jax
import jax.numpy as jnp

from sphexa_tpu.dtypes import HYDRO_DTYPE, INDEX_DTYPE, KEY_BITS, KEY_DTYPE
from sphexa_tpu.gravity.pallas_compact import IDX_BITS, compact_class_lists
from sphexa_tpu.util.phases import named_phase

#: secondary-key bits available below the 3*KEY_BITS spatial key in one
#: uint32 sort key (keys.py packs 30 bits -> 2 spare)
FOLD_BITS = 32 - 3 * KEY_BITS


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class BlockDtState:
    """Per-particle bin bookkeeping + cycle scalars, carried by the
    Simulation alongside the ParticleState and permuted through the
    step's SFC sort via the aux channel (scalars pass through untouched,
    like ParticleState's integrator scalars)."""

    bins: jax.Array      # (n,) int32  power-of-two Δt bin per particle
    dt_prev: jax.Array   # (n,) f32    dt of each particle's previous kick
    substep: jax.Array   # ()  int32   position within the current cycle
    cycle: jax.Array     # ()  int32   completed-cycle counter
    dt_min: jax.Array    # ()  f32     bin-0 dt of the current cycle


def make_blockdt_state(state, nbins: int) -> BlockDtState:
    """Fresh carry: everyone in bin 0 (the first sync substep re-bins),
    dt_prev = the state's min_dt so the first Press update sees the same
    dt_m1 the global path would."""
    del nbins  # bins start at 0 regardless of depth
    n = state.n
    return BlockDtState(
        bins=jnp.zeros(n, INDEX_DTYPE),
        dt_prev=jnp.full((n,), 1.0, HYDRO_DTYPE) * state.min_dt,
        substep=jnp.zeros((), INDEX_DTYPE),
        cycle=jnp.zeros((), INDEX_DTYPE),
        dt_min=jnp.asarray(state.min_dt, HYDRO_DTYPE),
    )


def cycle_length(nbins: int) -> int:
    """Substeps per cycle: the deepest bin steps once per cycle."""
    return 1 << (nbins - 1)


@named_phase("dt-bins")
def particle_dt_candidates(h, c, const, ax=None, ay=None, az=None):
    """Elementwise dt candidates per particle (see module docstring):
    Courant ``k_cour*h/c`` plus, when accelerations are given, the
    acceleration limiter ``eta_acc*sqrt(eps/|a|)`` (the per-particle
    mirror of timestep.acceleration_timestep's global max|a|).  |a| = 0
    gives inf — harmless, the bin clip saturates."""
    dt = const.k_cour * h / c
    if ax is not None:
        acc = jnp.sqrt(ax * ax + ay * ay + az * az)
        dt = jnp.minimum(dt, const.eta_acc * jnp.sqrt(const.eps / acc))
    return dt


@named_phase("dt-bins")
def assign_bins(dt_part, dt_min, nbins: int):
    """Bin index ``k = clip(floor(log2(dt_i / dt_min)), 0, nbins-1)`` —
    the deepest power-of-two multiple of dt_min the particle's own
    candidate admits.  The clip runs in f32 BEFORE the int cast so inf
    candidates (zero acceleration) saturate instead of overflowing."""
    ratio = jnp.maximum(dt_part / dt_min, 1.0)
    k = jnp.clip(jnp.floor(jnp.log2(ratio)), 0.0, float(nbins - 1))
    return k.astype(INDEX_DTYPE)


def due_mask(bins, substep):
    """Bin k is due every 2**k-th substep, all bins aligned at the cycle
    end: due iff ``(substep + 1) % 2**k == 0``.  Bitmask form (the period
    is a power of two) so it is one shift + and + compare."""
    period_mask = jnp.left_shift(jnp.int32(1), bins) - 1
    return jnp.bitwise_and(substep + 1, period_mask) == 0


@named_phase("dt-bins")
def bin_populations(bins, nbins: int):
    """(nbins,) occupancy histogram — one-hot sum, not scatter-add (TPU
    scatters serialize; nbins is tiny so the (n, nbins) one-hot is
    cheap).  This is the complexity-proxy source: updates per cycle =
    sum_k pop[k] * C / 2**k."""
    hot = bins[:, None] == jnp.arange(nbins, dtype=bins.dtype)[None, :]
    return jnp.sum(hot, axis=0, dtype=INDEX_DTYPE)


def fold_bin_key(keys, bins):
    """Secondary-key fold: spatial SFC key in the high bits, (saturated)
    bin index in the low FOLD_BITS.  One uint32 argsort then yields a
    spatially sorted order with equal-key particles grouped by bin.

    Deviation from the ISSUE's bin-major prefix wording, by design: a
    global bin prefix would break the SFC cell-range neighbor engines,
    which require the permuted state to be spatially sorted — the GLOBAL
    contiguous active set is realized by the compaction index lists
    (compact_active) instead.  Bins beyond 2**FOLD_BITS - 1 saturate in
    the FOLD ONLY (grouping granularity; the bins array keeps full
    depth), which also keeps the fold inside uint32 at any dt_bins.
    """
    b = jnp.minimum(bins, (1 << FOLD_BITS) - 1).astype(KEY_DTYPE)
    return jnp.bitwise_or(jnp.left_shift(keys, FOLD_BITS), b)


@named_phase("dt-bins")
def compact_active(due, use_kernel: bool = False, interpret: bool = False):
    """Active-index list + count from the due mask.

    ``use_kernel``: route through the PR 1 bitmask+popcount-rank Mosaic
    compaction (gravity/pallas_compact.py) — one (1, n) row, class 0 =
    due, class 1 = dropped; requires n < 2**IDX_BITS.  Otherwise (XLA
    fallback off-TPU and on sharded runs, where the argsort turns into
    the GSPMD-planned global sort) a stable argsort of the class ints —
    both paths return the active indices first, in candidate order.

    Returns ``(idx (n,) i32, n_active () i32)``; idx entries beyond
    n_active are inactive rows (argsort) or zero-padding (kernel) and
    must be masked by the caller.
    """
    n = due.shape[0]
    cls = jnp.where(due, 0, 1).astype(jnp.int32)
    n_active = jnp.sum(due.astype(INDEX_DTYPE))
    if use_kernel and n < (1 << IDX_BITS):
        packed = jnp.bitwise_or(jnp.left_shift(cls, IDX_BITS),
                                jnp.arange(n, dtype=jnp.int32))
        lst0, n0, _, _ = compact_class_lists(packed[None, :], n, 1,
                                             interpret=interpret)
        return lst0[0], n0[0]
    return jnp.argsort(cls).astype(INDEX_DTYPE), n_active
