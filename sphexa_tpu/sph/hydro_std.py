"""Standard SPH pipeline: density -> EOS -> IAD -> momentum+energy.

Physics-equivalent of the reference's ``sph/hydro_std/`` kernels
(density via xmass_kern.hpp:50-79, eos.hpp:54-70, iad_kern.hpp:12-77,
momentum_energy_kern.hpp:12-134), re-expressed as masked vectorized
j-reductions over (N, ngmax) neighbor lists. Each op is chunked with
blocked_map so the transient gathered tiles stay HBM-friendly; XLA fuses
the math of one block into a single kernel.
"""

import jax.numpy as jnp

from sphexa_tpu.sfc.box import Box
from sphexa_tpu.sph.kernels import artificial_viscosity, sinc_kernel_u, ts_k_courant
from sphexa_tpu.sph.pairs import iad_project, mmax, msum, pair_geometry
from sphexa_tpu.sph.particles import SimConstants
from sphexa_tpu.util.blocking import blocked_map
from sphexa_tpu.util.phases import named_phase


@named_phase("density")
def compute_density(x, y, z, h, m, nidx, nmask, box: Box, const: SimConstants, block=2048):
    """rho_i = K h_i^-3 (m_i + sum_j m_j W(|r_ij|/h_i)).

    Same quantity as the reference's computeDensity (which routes through
    the xmass kernel and undoes the volume element in the EOS).
    """
    n = x.shape[0]

    def body(idx):
        g = pair_geometry(idx, x, y, z, h, nidx, nmask, box)
        w = sinc_kernel_u(g.v1 * g.v1, const.sinc_index, const.kernel_choice)
        rho0 = m[idx] + msum(g.mask, m[g.nj] * w)
        h_i = h[idx]
        return const.K * rho0 / (h_i * h_i * h_i)

    return blocked_map(body, n, block)


@named_phase("eos")
def compute_eos_std(temp, rho, const: SimConstants):
    """Ideal-gas EOS from temperature (eos.hpp idealGasEOS): returns (p, c)."""
    tmp = const.cv * temp * (const.gamma - 1.0)
    return rho * tmp, jnp.sqrt(tmp)


@named_phase("iad")
def compute_iad(x, y, z, h, vol_j, nidx, nmask, box: Box, const: SimConstants, block=2048):
    """Integral-approach-to-derivatives tensor (Garcia-Senz et al.).

    Builds the moment matrix tau = sum_j vol_j W r (x) r and returns the six
    components of its inverse scaled by h^3/K. ``vol_j`` is the per-particle
    volume estimate: m/rho in the std pipeline, xm/kx in the VE pipeline
    (this one function covers both reference kernels iad_kern.hpp std:42 /
    ve:74). The exponent renormalization mirrors the reference's
    ilogb/ldexp conditioning trick — essential in f32, and exact because
    the factor cancels in adj(tau)/det(tau).
    """
    n = x.shape[0]

    def body(idx):
        g = pair_geometry(idx, x, y, z, h, nidx, nmask, box)
        w = sinc_kernel_u(g.v1 * g.v1, const.sinc_index, const.kernel_choice)
        vw = jnp.where(g.mask, vol_j[g.nj] * w, 0.0)
        t11 = jnp.sum(g.rx * g.rx * vw, -1)
        t12 = jnp.sum(g.rx * g.ry * vw, -1)
        t13 = jnp.sum(g.rx * g.rz * vw, -1)
        t22 = jnp.sum(g.ry * g.ry * vw, -1)
        t23 = jnp.sum(g.ry * g.rz * vw, -1)
        t33 = jnp.sum(g.rz * g.rz * vw, -1)

        exp_of = lambda v: jnp.where(v != 0.0, jnp.frexp(v)[1], 0)
        esum = (exp_of(t11) + exp_of(t12) + exp_of(t13)
                + exp_of(t22) + exp_of(t23) + exp_of(t33))
        norm = jnp.ldexp(jnp.ones_like(t11), -(esum // 6))
        t11, t12, t13 = t11 * norm, t12 * norm, t13 * norm
        t22, t23, t33 = t22 * norm, t23 * norm, t33 * norm

        det = (t11 * t22 * t33 + 2.0 * t12 * t23 * t13
               - t11 * t23 * t23 - t22 * t13 * t13 - t33 * t12 * t12)
        h_i = h[idx]
        factor = norm * (h_i * h_i * h_i) / (det * const.K)
        return (
            (t22 * t33 - t23 * t23) * factor,
            (t13 * t23 - t33 * t12) * factor,
            (t12 * t23 - t22 * t13) * factor,
            (t11 * t33 - t13 * t13) * factor,
            (t13 * t12 - t11 * t23) * factor,
            (t11 * t22 - t12 * t12) * factor,
        )

    return blocked_map(body, n, block)


@named_phase("momentum-energy")
def compute_momentum_energy_std(
    x, y, z, vx, vy, vz, h, m, rho, p, c,
    c11, c12, c13, c22, c23, c33,
    nidx, nmask, box: Box, const: SimConstants, block=1024,
):
    """Pressure-gradient accelerations + energy rate + Courant dt.

    Follows momentum_energy_kern.hpp:12-134: symmetrized IAD gradient terms,
    constant-alpha artificial viscosity halved per pair, signal velocity
    ci + cj - 3 w_ij. Returns (ax, ay, az, du, min_dt_courant).
    """
    n = x.shape[0]

    def body(idx):
        g = pair_geometry(idx, x, y, z, h, nidx, nmask, box)
        h_i = h[idx][:, None]
        h_j = h[g.nj]
        if getattr(const, "sym_pairs", True):
            # min-h symmetric cutoff: exact pairwise antisymmetry (see
            # SimConstants.sym_pairs; matches the engine's sym_jf mask)
            g = g._replace(mask=g.mask & (g.dist < 2.0 * h_j))
        w_i = sinc_kernel_u(g.v1 * g.v1, const.sinc_index, const.kernel_choice) / (h_i * h_i * h_i)
        v2 = g.dist / h_j
        w_j = sinc_kernel_u(v2 * v2, const.sinc_index, const.kernel_choice) / (h_j * h_j * h_j)

        vx_ij = vx[idx][:, None] - vx[g.nj]
        vy_ij = vy[idx][:, None] - vy[g.nj]
        vz_ij = vz[idx][:, None] - vz[g.nj]
        rv = g.rx * vx_ij + g.ry * vy_ij + g.rz * vz_ij
        w_ij = rv / g.dist

        c_i = c[idx][:, None]
        c_j = c[g.nj]
        visc = 0.5 * artificial_viscosity(1.0, 1.0, c_i, c_j, w_ij)

        vijsignal = c_i + c_j - 3.0 * w_ij
        maxvsignal = mmax(g.mask, vijsignal)

        tA1_i, tA2_i, tA3_i = iad_project(
            c11[idx][:, None], c12[idx][:, None], c13[idx][:, None],
            c22[idx][:, None], c23[idx][:, None], c33[idx][:, None],
            g.rx, g.ry, g.rz, sign=1.0,
        )
        tA1_j, tA2_j, tA3_j = iad_project(
            c11[g.nj], c12[g.nj], c13[g.nj], c22[g.nj], c23[g.nj], c33[g.nj],
            g.rx, g.ry, g.rz, sign=1.0,
        )

        rho_i = rho[idx][:, None]
        rho_j = rho[g.nj]
        m_j = m[g.nj]
        p_i = p[idx][:, None]
        mi_roi = (m[idx] / rho[idx])[:, None]
        mj_pro_i = m_j * p_i / (rho_i * rho_i)
        mj_roj_wj = m_j / rho_j * w_j

        a = w_i * (mj_pro_i + visc * mi_roi)
        b = mj_roj_wj * (p[g.nj] / rho_j + visc)
        mom_x = msum(g.mask, a * tA1_i + b * tA1_j)
        mom_y = msum(g.mask, a * tA2_i + b * tA2_j)
        mom_z = msum(g.mask, a * tA3_i + b * tA3_j)

        a_e = w_i * (2.0 * mj_pro_i + visc * mi_roi)
        b_e = visc * mj_roj_wj
        energy = msum(
            g.mask,
            vx_ij * (a_e * tA1_i + b_e * tA1_j)
            + vy_ij * (a_e * tA2_i + b_e * tA2_j)
            + vz_ij * (a_e * tA3_i + b_e * tA3_j),
        )

        du = -const.K * 0.5 * energy
        dt_i = ts_k_courant(maxvsignal, h[idx], c[idx], const.k_cour)
        return (const.K * mom_x, const.K * mom_y, const.K * mom_z, du, dt_i)

    ax, ay, az, du, dt = blocked_map(body, n, block)
    return ax, ay, az, du, jnp.min(dt)
