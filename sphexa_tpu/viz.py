"""In-situ visualization hook: per-iteration slice / column-projection
renders, written as PNG next to the run output.

The role of the reference's Ascent/Catalyst adaptors
(main/src/ascent_adaptor.h:1-156, catalyst_adaptor.h:1-135,
insitu_viz.h): an adaptor object with init / execute / finalize hooks
called around the main loop. Where the reference hands the mesh to an
external in-situ library, this renders directly — a mass-weighted 2D
histogram (column density) or a thin z-slice of it — with a small
stdlib-only PNG encoder, so the hook has zero optional dependencies and
works on any machine the simulation runs on.

Select from the CLI with ``--insitu slice|projection`` and
``--insitu-every N``.
"""

import os
import struct
import zlib
from typing import Optional

import numpy as np


def _png_bytes(img: np.ndarray) -> bytes:
    """Encode an (H, W, 3) uint8 array as PNG (stdlib zlib/struct only)."""
    h, w, _ = img.shape
    raw = b"".join(
        b"\x00" + img[row].astype(np.uint8).tobytes() for row in range(h)
    )

    def chunk(tag: bytes, data: bytes) -> bytes:
        return (
            struct.pack(">I", len(data)) + tag + data
            + struct.pack(">I", zlib.crc32(tag + data) & 0xFFFFFFFF)
        )

    ihdr = struct.pack(">IIBBBBB", w, h, 8, 2, 0, 0, 0)
    return (
        b"\x89PNG\r\n\x1a\n"
        + chunk(b"IHDR", ihdr)
        + chunk(b"IDAT", zlib.compress(raw, 6))
        + chunk(b"IEND", b"")
    )


def _colormap(v: np.ndarray) -> np.ndarray:
    """[0,1] -> inferno-like RGB ramp (piecewise-linear, (H,W,3) uint8)."""
    stops = np.array(
        [(0.00, (0, 0, 4)), (0.25, (87, 16, 110)), (0.50, (188, 55, 84)),
         (0.75, (249, 142, 9)), (1.00, (252, 255, 164))],
        dtype=object,
    )
    xs = np.array([s[0] for s in stops], np.float64)
    cs = np.array([s[1] for s in stops], np.float64)  # (5, 3)
    out = np.empty(v.shape + (3,), np.float64)
    for c in range(3):
        out[..., c] = np.interp(v, xs, cs[:, c])
    return np.clip(out, 0, 255).astype(np.uint8)


def render_field(
    x, y, weights, extent, resolution: int = 512, log_scale: bool = True
) -> np.ndarray:
    """Mass-weighted 2D histogram -> color image ((res, res, 3) uint8).

    ``extent`` = (xmin, xmax, ymin, ymax). The render is deliberately a
    deposit (not an SPH re-smoothing): at viz resolutions the histogram
    is indistinguishable and costs O(N).
    """
    xmin, xmax, ymin, ymax = extent
    img, _, _ = np.histogram2d(
        np.asarray(y), np.asarray(x), bins=resolution,
        range=[[ymin, ymax], [xmin, xmax]], weights=np.asarray(weights),
    )
    if log_scale:
        img = np.log10(img + 1e-12)
    finite = img[np.isfinite(img)]
    lo = np.percentile(finite, 1.0) if finite.size else 0.0
    hi = np.percentile(finite, 99.9) if finite.size else 1.0
    v = np.clip((img - lo) / max(hi - lo, 1e-30), 0.0, 1.0)
    return _colormap(v[::-1])  # image row 0 = top = ymax


def render_grid(grid, log_scale: bool = True,
                upsample: int = 16) -> np.ndarray:
    """Pre-deposited (G, G) field grid -> color image, same log/clip/
    colormap treatment as ``render_field``. This is the snapshot-ring
    consumer path (observables/snapshot.py frames): the deposit already
    happened in-graph, so rendering is pure host pixel work. Grid row 0
    is the low-coordinate row; the image flips so row 0 = top."""
    img = np.asarray(grid, np.float64)
    if log_scale:
        img = np.log10(np.abs(img) + 1e-12)
    finite = img[np.isfinite(img)]
    lo = np.percentile(finite, 1.0) if finite.size else 0.0
    hi = np.percentile(finite, 99.9) if finite.size else 1.0
    v = np.clip((img - lo) / max(hi - lo, 1e-30), 0.0, 1.0)
    if upsample > 1:
        v = np.repeat(np.repeat(v, upsample, axis=0), upsample, axis=1)
    return _colormap(v[::-1])


class InsituViz:
    """Per-iteration render hook (the Ascent-adaptor role).

    mode "projection": column density over (x, y).
    mode "slice": particles within a half-thickness of the z mid-plane.
    """

    def __init__(self, out_dir: str, mode: str = "projection",
                 every: int = 1, resolution: int = 512,
                 slice_rel_thickness: float = 0.05,
                 writer=None):
        if mode not in ("projection", "slice"):
            raise ValueError("insitu mode must be 'projection' or 'slice'")
        self.out_dir = out_dir
        self.mode = mode
        self.every = max(1, int(every))
        self.resolution = resolution
        self.slice_rel_thickness = slice_rel_thickness
        # test seam / alternate sink (the Catalyst-vs-Ascent choice):
        # writer(path, png_bytes) defaults to a plain file write
        self._writer = writer or self._write_file
        self.rendered = 0

    @staticmethod
    def _write_file(path: str, data: bytes):
        with open(path, "wb") as f:
            f.write(data)

    def init(self):
        os.makedirs(self.out_dir, exist_ok=True)

    def execute(self, state, box, iteration: int) -> Optional[str]:
        """Render one frame if due; returns the written path or None."""
        if iteration % self.every:
            return None
        x = np.asarray(state.x)
        y = np.asarray(state.y)
        z = np.asarray(state.z)
        m = np.asarray(state.m)
        lo = np.asarray(box.lo, np.float64)
        lengths = np.asarray(box.lengths, np.float64)
        extent = (lo[0], lo[0] + lengths[0], lo[1], lo[1] + lengths[1])
        if self.mode == "slice":
            z0 = lo[2] + 0.5 * lengths[2]
            half = self.slice_rel_thickness * lengths[2]
            keep = np.abs(z - z0) <= half
            x, y, m = x[keep], y[keep], m[keep]
        img = render_field(x, y, m, extent, self.resolution)
        path = os.path.join(
            self.out_dir, f"insitu_{self.mode}_{iteration:06d}.png"
        )
        self._writer(path, _png_bytes(img))
        self.rendered += 1
        return path

    def execute_grid(self, grid, iteration: int) -> Optional[str]:
        """Render one frame from a deposited snapshot grid (the ring
        consumer: sim.drain_snapshots() frames instead of full particle
        state — host pixel work only, zero device access). Frame naming
        and the rendered counter match execute(); a multi-field (F, G,
        G) grid renders its first field."""
        if iteration % self.every:
            return None
        g = np.asarray(grid, np.float64)
        if g.ndim == 3:
            g = g[0]
        upsample = max(1, self.resolution // max(1, g.shape[0]))
        img = render_grid(g, upsample=upsample)
        path = os.path.join(
            self.out_dir, f"insitu_{self.mode}_{iteration:06d}.png"
        )
        self._writer(path, _png_bytes(img))
        self.rendered += 1
        return path

    def finalize(self):
        return self.rendered
