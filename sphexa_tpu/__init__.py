"""sphexa-tpu: a TPU-native smoothed-particle-hydrodynamics framework.

A from-scratch JAX/XLA/Pallas re-design of the capabilities of SPH-EXA
(C++/MPI/CUDA reference, see SURVEY.md): Hilbert-curve domain decomposition,
cornerstone octrees, neighbor search, std/VE SPH pipelines, Barnes-Hut
self-gravity, turbulence stirring, checkpoint/restart and the built-in test
cases — all expressed as fixed-shape array programs that XLA can fuse, tile
onto the VPU/MXU, and scale over a device mesh with ICI collectives.
"""

__version__ = "0.1.0"
