// Native host-runtime components of sphexa-tpu.
//
// Role-equivalent of the host side of the reference's C++ runtime
// (cstone/sfc/{hilbert,morton,sfc}.hpp key generation and the
// domain-decomposition occupancy accounting): the (re)configuration path
// of the Python driver — SFC key generation for a snapshot of particle
// positions, sort-order computation, per-cell occupancy and group-window
// sizing — runs on the host, where numpy/jax round-trips are the cost.
// This translation unit packages those steps as a small C ABI consumed
// via ctypes (sphexa_tpu/native/__init__.py), with OpenMP parallel loops
// standing in for the reference's `#pragma omp parallel for` drivers.
//
// The Hilbert codec mirrors sphexa_tpu/sfc/hilbert.py (Skilling's
// public-domain transpose algorithm, AIP Conf. Proc. 707, 2004) exactly,
// bit for bit — tests/test_native.py asserts equality with the jax codec.
//
// Build:  make -C sphexa_tpu/native   (g++ -O3 -fopenmp -shared -fPIC)

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <vector>

#if defined(_OPENMP)
#include <omp.h>
#endif

namespace {

constexpr int KEY_BITS = 10;

inline uint32_t spread_bits_3d(uint32_t v) {
    v &= 0x3FFu;
    v = (v | (v << 16)) & 0x030000FFu;
    v = (v | (v << 8)) & 0x0300F00Fu;
    v = (v | (v << 4)) & 0x030C30C3u;
    v = (v | (v << 2)) & 0x09249249u;
    return v;
}

// Skilling AxesToTranspose, mirroring sphexa_tpu/sfc/hilbert.py
inline void axes_to_transpose(uint32_t X[3], int bits) {
    for (uint32_t q = 1u << (bits - 1); q > 1; q >>= 1) {
        uint32_t p = q - 1;
        for (int i = 0; i < 3; i++) {
            if (X[i] & q) {
                X[0] ^= p;
            } else {
                uint32_t t = (X[0] ^ X[i]) & p;
                X[0] ^= t;
                X[i] ^= t;
            }
        }
    }
    X[1] ^= X[0];
    X[2] ^= X[1];
    uint32_t t = 0;
    for (uint32_t q = 1u << (bits - 1); q > 1; q >>= 1) {
        if (X[2] & q) t ^= q - 1;
    }
    X[0] ^= t;
    X[1] ^= t;
    X[2] ^= t;
}

inline uint32_t hilbert_key(uint32_t ix, uint32_t iy, uint32_t iz, int bits) {
    uint32_t X[3] = {ix, iy, iz};
    axes_to_transpose(X, bits);
    return (spread_bits_3d(X[0]) << 2) | (spread_bits_3d(X[1]) << 1) |
           spread_bits_3d(X[2]);
}

inline uint32_t morton_key(uint32_t ix, uint32_t iy, uint32_t iz) {
    return (spread_bits_3d(ix) << 2) | (spread_bits_3d(iy) << 1) |
           spread_bits_3d(iz);
}

inline uint32_t to_grid(float v, float lo, float len, int ncell) {
    float scaled = (v - lo) / len * static_cast<float>(ncell);
    int g = static_cast<int>(scaled);
    return static_cast<uint32_t>(std::min(std::max(g, 0), ncell - 1));
}

}  // namespace

extern "C" {

// keys[i] = SFC key of (x, y, z)[i] in the box [lo, lo+len)^3.
// curve: 0 = Hilbert, 1 = Morton. Mirrors compute_sfc_keys (sfc/keys.py).
void sfc_compute_keys(const float* x, const float* y, const float* z,
                      int64_t n, const float* box_lo, const float* box_len,
                      int curve, uint32_t* keys) {
    const int ncell = 1 << KEY_BITS;
#pragma omp parallel for schedule(static)
    for (int64_t i = 0; i < n; i++) {
        uint32_t ix = to_grid(x[i], box_lo[0], box_len[0], ncell);
        uint32_t iy = to_grid(y[i], box_lo[1], box_len[1], ncell);
        uint32_t iz = to_grid(z[i], box_lo[2], box_len[2], ncell);
        keys[i] = curve == 0 ? hilbert_key(ix, iy, iz, KEY_BITS)
                             : morton_key(ix, iy, iz);
    }
}

// Stable argsort of keys (the host-side SfcSorter role,
// cstone/primitives/gather.hpp:26-165). order must hold n int64 slots.
void sfc_argsort(const uint32_t* keys, int64_t n, int64_t* order) {
    for (int64_t i = 0; i < n; i++) order[i] = i;
    std::stable_sort(order, order + n, [keys](int64_t a, int64_t b) {
        return keys[a] < keys[b];
    });
}

// Max level-`level` cell occupancy of sorted keys (estimate_cell_cap's
// counting loop, neighbors/cell_list.py).
int64_t sfc_max_cell_occupancy(const uint32_t* sorted_keys, int64_t n,
                               int level) {
    if (n == 0) return 0;
    const int shift = 3 * (KEY_BITS - level);
    int64_t best = 1, run = 1;
    for (int64_t i = 1; i < n; i++) {
        if ((sorted_keys[i] >> shift) == (sorted_keys[i - 1] >> shift)) {
            if (++run > best) best = run;
        } else {
            run = 1;
        }
    }
    return best;
}

// Max extent over SFC-consecutive groups of `group` particles, per
// dimension (the measurement behind estimate_group_window,
// neighbors/cell_list.py). ext_out: 3 floats.
void sfc_group_extents(const float* x, const float* y, const float* z,
                       const int64_t* order, int64_t n, int group,
                       float* ext_out) {
    const float* dims[3] = {x, y, z};
    for (int d = 0; d < 3; d++) {
        float best = 0.0f;
        for (int64_t g0 = 0; g0 < n; g0 += group) {
            int64_t g1 = std::min(g0 + static_cast<int64_t>(group), n);
            float lo = dims[d][order[g0]], hi = lo;
            for (int64_t i = g0 + 1; i < g1; i++) {
                float v = dims[d][order[i]];
                lo = std::min(lo, v);
                hi = std::max(hi, v);
            }
            best = std::max(best, hi - lo);
        }
        ext_out[d] = best;
    }
}

int sfc_runtime_abi_version() { return 1; }

}  // extern "C"
