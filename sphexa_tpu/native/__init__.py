"""ctypes bindings for the native host-runtime library.

The compute path is JAX/XLA/Pallas; the *host runtime* around it — key
generation, sort-order and occupancy/window accounting at reconfiguration
time — has a native C++ implementation (sfc_runtime.cpp), mirroring the
reference's C++ host drivers. The library is built with ``make -C
sphexa_tpu/native`` (attempted automatically once on first use); every
entry point degrades gracefully to the numpy/jax implementation when the
library is unavailable, so the package stays import-safe everywhere.
"""

import ctypes
import os
import subprocess
from typing import Optional, Tuple

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_DIR, "libsfc_runtime.so")
_lib = None
_tried_build = False


def _load() -> Optional[ctypes.CDLL]:
    """dlopen the runtime library, building it once if missing."""
    global _lib, _tried_build
    if _lib is not None:
        return _lib
    if not os.path.exists(_LIB_PATH) and not _tried_build:
        _tried_build = True
        try:
            # build to a process-unique temp name and atomically rename so
            # concurrent builders never dlopen a partially written library
            tmp = f"{_LIB_PATH}.{os.getpid()}.tmp"
            subprocess.run(
                ["g++", "-O3", "-std=c++17", "-fPIC", "-fopenmp", "-Wall",
                 "-shared", "-o", tmp,
                 os.path.join(_DIR, "sfc_runtime.cpp")],
                check=True, capture_output=True, timeout=120,
            )
            os.replace(tmp, _LIB_PATH)
        except Exception:
            return None
    if not os.path.exists(_LIB_PATH):
        return None
    try:
        lib = ctypes.CDLL(_LIB_PATH)
    except OSError:
        return None

    u32p = np.ctypeslib.ndpointer(np.uint32, flags="C_CONTIGUOUS")
    f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
    i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")

    lib.sfc_compute_keys.argtypes = [
        f32p, f32p, f32p, ctypes.c_int64, f32p, f32p, ctypes.c_int, u32p
    ]
    lib.sfc_argsort.argtypes = [u32p, ctypes.c_int64, i64p]
    lib.sfc_max_cell_occupancy.argtypes = [u32p, ctypes.c_int64, ctypes.c_int]
    lib.sfc_max_cell_occupancy.restype = ctypes.c_int64
    lib.sfc_group_extents.argtypes = [
        f32p, f32p, f32p, i64p, ctypes.c_int64, ctypes.c_int, f32p
    ]
    lib.sfc_runtime_abi_version.restype = ctypes.c_int
    if lib.sfc_runtime_abi_version() != 1:
        return None
    _lib = lib
    return _lib


def available() -> bool:
    return _load() is not None


def compute_keys(x, y, z, box_lo, box_len, curve: str = "hilbert") -> np.ndarray:
    """Host-side SFC keys (native when available, else the jax codec)."""
    if curve not in ("hilbert", "morton"):
        raise ValueError(f"unknown curve {curve!r}; have hilbert, morton")
    lib = _load()
    x = np.ascontiguousarray(x, np.float32)
    y = np.ascontiguousarray(y, np.float32)
    z = np.ascontiguousarray(z, np.float32)
    if lib is None:
        from sphexa_tpu.sfc.box import Box, BoundaryType
        from sphexa_tpu.sfc.keys import compute_sfc_keys
        import jax.numpy as jnp

        lo = np.asarray(box_lo, np.float32)
        ln = np.asarray(box_len, np.float32)
        box = Box(
            lo=jnp.asarray(lo), hi=jnp.asarray(lo + ln),
            boundaries=(BoundaryType.open,) * 3,
        )
        return np.asarray(
            compute_sfc_keys(jnp.asarray(x), jnp.asarray(y), jnp.asarray(z),
                             box, curve=curve)
        )
    keys = np.empty(len(x), np.uint32)
    lib.sfc_compute_keys(
        x, y, z, len(x),
        np.ascontiguousarray(box_lo, np.float32),
        np.ascontiguousarray(box_len, np.float32),
        0 if curve == "hilbert" else 1, keys,
    )
    return keys


def argsort_keys(keys: np.ndarray) -> np.ndarray:
    lib = _load()
    keys = np.ascontiguousarray(keys, np.uint32)
    if lib is None:
        return np.argsort(keys, kind="stable").astype(np.int64)
    order = np.empty(len(keys), np.int64)
    lib.sfc_argsort(keys, len(keys), order)
    return order


def max_cell_occupancy(sorted_keys: np.ndarray, level: int) -> int:
    lib = _load()
    sorted_keys = np.ascontiguousarray(sorted_keys, np.uint32)
    if lib is None:
        from sphexa_tpu.dtypes import KEY_BITS

        shift = 3 * (KEY_BITS - level)
        cells = (sorted_keys.astype(np.uint64) >> np.uint64(shift)).astype(np.int64)
        return int(np.bincount(cells).max()) if len(cells) else 0
    return int(lib.sfc_max_cell_occupancy(sorted_keys, len(sorted_keys), level))


def group_extents(x, y, z, order: np.ndarray, group: int) -> Tuple[float, float, float]:
    """Max per-dimension extent over SFC-consecutive particle groups."""
    lib = _load()
    x = np.ascontiguousarray(x, np.float32)
    y = np.ascontiguousarray(y, np.float32)
    z = np.ascontiguousarray(z, np.float32)
    order = np.ascontiguousarray(order, np.int64)
    if lib is None:
        out = []
        n = len(x)
        ng = -(-n // group)
        pad = ng * group - n
        for a in (x, y, z):
            s = a[order]
            if pad:
                s = np.concatenate([s, np.repeat(s[-1], pad)])
            g = s.reshape(ng, group)
            out.append(float((g.max(axis=1) - g.min(axis=1)).max()))
        return tuple(out)
    ext = np.empty(3, np.float32)
    lib.sfc_group_extents(x, y, z, order, len(x), group, ext)
    return float(ext[0]), float(ext[1]), float(ext[2])
