"""Multi-device execution: meshes, shardings, distributed steps.

TPU-native replacement of the reference's MPI layer (SURVEY.md §2e): the
particle arrays are sharded over a 1-D device mesh in SFC order (the analog
of rank-owned Hilbert slabs, P1), and the jitted step runs under GSPMD so
XLA inserts the halo gathers, redistribution all-to-alls and min/sum
collectives that the reference encodes as explicit MPI choreography
(P2-P4). ICI replaces GPU-direct RDMA natively (P7).
"""

from sphexa_tpu.parallel.mesh import (
    make_mesh,
    make_sharded_step,
    shard_state,
)

__all__ = ["make_mesh", "make_sharded_step", "shard_state"]
