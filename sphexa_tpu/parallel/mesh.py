"""Device mesh + sharded step construction.

The particle axis is sharded over a 1-D mesh axis ``"p"``. Because every
step globally re-sorts by Hilbert key, shard k of the sorted arrays IS the
k-th contiguous key slab — the same ownership model as the reference's
SfcAssignment (domaindecomp.hpp:74-110), with the sort itself playing the
role of exchangeParticles. Interaction gathers that cross slab boundaries
become XLA-inserted collectives (the halo exchange analog); scalar
reductions (dt, box, energies) become psum/pmin over ICI.
"""

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sphexa_tpu.propagator import PropagatorConfig, step_hydro_std


def make_mesh(num_devices: Optional[int] = None) -> Mesh:
    """1-D particle mesh over the first ``num_devices`` devices."""
    devices = jax.devices()
    if num_devices is not None:
        if num_devices > len(devices):
            raise ValueError(
                f"requested {num_devices} devices, only {len(devices)} available"
            )
        devices = devices[:num_devices]
    return Mesh(np.asarray(devices), ("p",))


def shard_state(state, mesh: Mesh):
    """Place particle arrays sharded over the mesh; scalars replicated."""
    psharding = NamedSharding(mesh, P("p"))
    rsharding = NamedSharding(mesh, P())

    def place(leaf):
        if leaf.ndim >= 1:
            if leaf.shape[0] % mesh.size:
                raise ValueError(
                    f"particle count {leaf.shape[0]} not divisible by mesh size "
                    f"{mesh.size}; pad the state first"
                )
            return jax.device_put(leaf, psharding)
        return jax.device_put(leaf, rsharding)

    return jax.tree.map(place, state)


def _place_aux_leaf(leaf, n: int, place, pspec, rspec):
    """SINGLE placement rule for aux (turbulence/chemistry) pytree leaves:
    per-particle arrays (first dim == n) ride the slab sharding, other
    arrays replicate, scalars pass through. Shared by the input commit
    (device_put) and the output constraint so they can never drift apart
    into two executable variants."""
    nd = getattr(leaf, "ndim", 0)
    if nd >= 1 and leaf.shape[0] == n:
        return place(leaf, pspec)
    if nd >= 1:
        return place(leaf, rspec)
    return leaf


def make_sharded_step(mesh: Mesh, cfg: PropagatorConfig, step_fn=step_hydro_std,
                      halo_window: int = 0, halo_cells=(), grav_cells=(),
                      aux_cfg=None):
    """Jit the full step with particle arrays sharded over the mesh.

    GSPMD partitions the entire program: the SFC sort's key exchange is the
    domain redistribution, neighbor gathers crossing shard boundaries
    lower to halo collectives, and jnp.min/sum reductions become pmin/psum
    (the reference's MPI_Allreduce at timestep.hpp:106 and
    conserved_quantities.hpp:118).

    When ``cfg.gravity`` is set, the returned stepper takes the gravity
    tree as a third argument: ``stepper(state, box, gtree)``; the (small)
    tree arrays stay replicated across the mesh, matching the reference's
    replicated global octree (assignment.hpp:51-53). ``grav_cells``
    (P-1 per-distance row caps from sizing.device_gravity_halo) switches
    the gravity near field to the MAC-sized sparse serve; empty ships
    full peer slabs.

    turb-ve / std-cooling carry extra per-step state through the stepper
    (the reference runs every propagator under the full MPI domain,
    turb_ve.hpp:53 / std_hydro_grackle.hpp:56): pass their static config
    as ``aux_cfg`` and call ``stepper(state, box, gtree, aux)`` with the
    TurbulenceState / ChemistryData pytree; the stepper returns
    ``(state, box, diag, new_aux)``. Turbulence phases are replicated
    (they are global mode tables); chemistry arrays are per-particle and
    ride the slab sharding + the in-step SFC sort.
    """
    from sphexa_tpu.propagator import (
        STEP_AUX_SLOT,
        step_hydro_std_blockdt,
        step_hydro_std_cooling,
        step_hydro_ve,
        step_hydro_ve_blockdt,
        step_turb_ve,
    )

    aux_props = {step_turb_ve, step_hydro_std_cooling}
    # blockdt steps carry the BlockDtState through the aux slot (4-tuple
    # return like aux_props) but take no static aux_cfg; their bin math
    # runs OUTSIDE shard_map on GSPMD-sharded arrays, so the pallas force
    # stages and their pinned collective order are reused unchanged
    blockdt_props = {step_hydro_std_blockdt, step_hydro_ve_blockdt}
    carry_props = aux_props | blockdt_props
    # GSPMD has no auto-partitioning rule for Mosaic (pallas) custom calls,
    # so the pallas pair stage runs under an explicit shard_map: each
    # device executes the fused engine on its SFC slab with windowed
    # all_to_all halos (propagator._std_forces_sharded /
    # _ve_forces_sharded). turb-ve and std-cooling reuse those same force
    # stages; their extra physics (stirring accel, cooling source) is
    # plain XLA on sharded arrays, which GSPMD partitions. The nbody step
    # has no pair stage — it falls back to the GSPMD XLA gravity path.
    if cfg.backend == "pallas":
        if step_fn in ({step_hydro_std, step_hydro_ve} | carry_props):
            cfg = dataclasses.replace(cfg, mesh=mesh, shard_axis="p",
                                      halo_window=halo_window,
                                      halo_cells=tuple(halo_cells),
                                      grav_cells=tuple(grav_cells))
        else:
            cfg = dataclasses.replace(cfg, backend="xla")
    if (cfg.gravity is not None and cfg.gravity.use_pallas
            and cfg.shard_axis is None):
        # on the GSPMD path (nbody/turb/cooling/xla steps) gravity runs
        # outside any shard_map, where a Mosaic custom call has no
        # partitioning rule — fall back to the XLA near field there. The
        # fast-path steps instead run _gravity_sharded_stage (distributed
        # upsweep + windowed near-field halos, Ewald replica shells
        # included) with the engine inside shard_map.
        cfg = dataclasses.replace(
            cfg, gravity=dataclasses.replace(cfg.gravity, use_pallas=False)
        )

    pspec = NamedSharding(mesh, P("p"))

    rspec = NamedSharding(mesh, P())

    def inner(s, b, gtree=None, aux=None):
        if step_fn in aux_props:
            new_state, new_box, diag, new_aux = step_fn(
                s, b, cfg, gtree, aux, aux_cfg
            )
        elif step_fn in blockdt_props:
            new_state, new_box, diag, new_aux = step_fn(s, b, cfg, gtree, aux)
        else:
            new_state, new_box, diag = step_fn(s, b, cfg, gtree)
            new_aux = None
        # keep the particle arrays sharded on the way out so the next step
        # starts from slab-owned arrays (no silent replication creep)...
        constrain = lambda l: (
            jax.lax.with_sharding_constraint(l, pspec) if l.ndim >= 1 else l
        )
        # ...and the (3,)-vector box replicated — a stray P('p') sharding
        # on it changes the call signature and forces a full recompile on
        # the second step
        rep = lambda l: (
            jax.lax.with_sharding_constraint(l, rspec)
            if getattr(l, "ndim", 0) >= 1 else l
        )
        # aux leaves: per-particle arrays (chemistry) stay slab-sharded,
        # global tables (turbulence modes/phases) stay replicated
        aux_place = lambda l: _place_aux_leaf(
            l, s.n, jax.lax.with_sharding_constraint, pspec, rspec
        )
        return (jax.tree.map(constrain, new_state),
                jax.tree.map(rep, new_box), diag,
                jax.tree.map(aux_place, new_aux))

    # inputs are placed by shard_state; GSPMD propagates those shardings
    # through the whole program, one compiled executable reused every step
    jitted = jax.jit(inner)

    def stepper(s, b, gtree=None, aux=None):
        # commit the box (and aux, same placement rule as aux_place)
        # replicated/sharded BEFORE the first call: an uncommitted input
        # on step 0 compiles a second executable variant vs the committed
        # step-1 outputs, and on CPU meshes two variants' collective
        # channels can collide mid-run
        b = jax.device_put(b, rspec)
        if aux is not None:
            aux = jax.tree.map(
                lambda l: _place_aux_leaf(
                    l, s.n, jax.device_put, pspec, rspec
                ),
                aux,
            )
        out = jitted(s, b, gtree, aux)
        return out if step_fn in carry_props else out[:3]

    aux_slot = STEP_AUX_SLOT.get(step_fn)

    def step_sim(sim, gtree=None):
        """Advance one step on the unified ``state.SimState`` carry:
        the sharded face of ``propagator.step_sim_state``. Routes through
        ``stepper`` (same placement commits, same jitted executable —
        lowering-neutral by construction) and replaces only the aux slot
        this step function owns, so the carry treedef is closed under
        stepping (the JXA503 invariant)."""
        aux = getattr(sim, aux_slot) if aux_slot else None
        out = stepper(sim.particles, sim.box, gtree, aux)
        new_sim = sim.with_slot(aux_slot, out[3] if aux_slot else None,
                                particles=out[0], box=out[1])
        return new_sim, out[2]

    stepper.step_sim = step_sim

    # expose the underlying jit cache so the Simulation's compile
    # watchdog (telemetry retrace events) can probe sharded launches too;
    # optional like the consumer's getattr probe — a jax without the
    # private _cache_size just loses the watchdog, not the mesh path
    cache_size = getattr(jitted, "_cache_size", None)
    if cache_size is not None:
        stepper._cache_size = cache_size
    # ...and the jitted callable itself, so tests can .lower() the step
    # and pin lowering identities (the grav_window=0 byte-identity gate)
    stepper._jitted = jitted
    return stepper
