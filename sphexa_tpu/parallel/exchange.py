"""Windowed halo exchange for the sharded Pallas fast path.

TPU-native transposition of the reference's halo subsystem
(cstone/halos/exchange_halos.hpp:43-119 pack-ranges -> p2p -> scatter,
discovery cstone/traversal/collisions.hpp:26-106). The reference sends
per-peer lists of octree-leaf row ranges; here each shard

1. runs the shared group-window prologue on its OWN slab against the
   GLOBAL cell-starts table (an O(ncells) psum of per-shard histograms —
   the update_mpi.hpp allreduce analog, no key gather),
2. derives, per source shard, the single row WINDOW [lo, hi) covering
   every candidate run it needs from that shard (discovery),
3. all_gathers the (P, P, 2) bounds matrix (the exchange_keys.hpp
   negotiation analog — O(P^2) ints),
4. receives the windows with ONE all_to_all of fixed (P, Wmax, nf)
   buffers: shard j serves dynamic slices of its slab (pack), shard k
   concatenates [own slab | annex] into the engine's j-buffer (scatter).

Comm volume per shard = (P-1) * Wmax rows per exchange stage — the
MEASURED candidate need (sized at reconfiguration, guarded in-step), not
an unconditional O(N) replication. At CI scale (1e6 particles / 8 shards,
level-4 cells) windows still span most of a slab — the halo *is* the
volume at that granularity — but Wmax shrinks relative to the shard size
as particles-per-shard grow (deeper grids, smaller surface fraction),
which is the reference's scaling property (SURVEY.md §2e P2).

A candidate run that escapes its source window (particle drift after the
last sizing) zeroes itself and trips the step's occupancy sentinel; the
CALLER owns recovery — discard the step and rebuild the sharded stepper
with a larger ``halo_window`` (tests/test_parallel.py exercises both the
sentinel and the resize), mirroring the neighbor-cap overflow contract.
"""

from typing import Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from sphexa_tpu.dtypes import KEY_BITS, KEY_DTYPE
from sphexa_tpu.sph.pallas_pairs import GroupRanges
from sphexa_tpu.util.phases import named_phase

# numpy, NOT jnp: this module is first imported INSIDE jitted stage
# functions, and a module-level jnp constant created under an active
# trace is a tracer — it leaks into later traces (UnexpectedTracerError
# in dryrun_multichip once the shard_map import shim let the pallas
# steps run). A numpy scalar weak-types identically in every jnp op.
INF32 = np.int32(2**30)


def estimate_halo_window(
    x, y, z, h, sorted_keys, box, nbr, P: int,
    margin: float = 1.4, quantum: int = 1024,
) -> int:
    """Size the static per-peer window Wmax from the current particle
    distribution (host-side, reconfiguration granularity — the halo
    discovery analog of estimate_cell_cap). Runs the shared prologue on
    the full arrays, clips candidate runs at slab boundaries, and returns
    the padded max over (dest, src) pairs of the needed row span.
    The in-step ``escaped`` guard remains the correctness backstop."""
    import numpy as np

    from sphexa_tpu.sph.pallas_pairs import group_cell_ranges

    n = x.shape[0]
    S = -(-n // P)
    ranges = group_cell_ranges(x, y, z, h, sorted_keys, box, nbr)
    starts = np.asarray(ranges.starts)
    lens = np.asarray(ranges.lens)
    g = nbr.group
    wmax = 1
    for k in range(P):
        g0 = k * S // g
        g1 = min(((k + 1) * S + g - 1) // g, starts.shape[0])
        st = starts[g0:g1].ravel()
        ln = lens[g0:g1].ravel()
        st, ln = st[ln > 0], ln[ln > 0]
        for j in range(P):
            if j == k:
                continue
            lo_j, hi_j = j * S, (j + 1) * S
            ov = (st < hi_j) & (st + ln > lo_j)
            if not ov.any():
                continue
            a = int(np.maximum(st[ov], lo_j).min())
            b = int(np.minimum(st[ov] + ln[ov], hi_j).max())
            wmax = max(wmax, b - a)
    padded = int(-(-int(wmax * margin) // quantum) * quantum)
    return min(padded, S)


@named_phase("halo-exchange")
def global_cell_table(local_keys, level: int, axis: str) -> jax.Array:
    """Cell-starts table of the level-``level`` grid over the DISTRIBUTED
    key array: per-shard cid histogram -> psum -> exclusive cumsum.
    O(ncells) comm; replicated result (update_mpi.hpp:26-106 role)."""
    shift = KEY_DTYPE(3 * (KEY_BITS - level))
    ncells = (1 << level) ** 3
    cid = (local_keys >> shift).astype(jnp.int32)
    hist = jnp.zeros(ncells, jnp.int32).at[cid].add(1)
    hist = jax.lax.psum(hist, axis)
    return jnp.concatenate(
        [jnp.zeros(1, jnp.int32), jnp.cumsum(hist)]
    ).astype(jnp.int32)


def _split_runs(starts, lens, shifts3, S: int, extra: int = 8):
    """Split candidate runs that cross shard-slab boundaries.

    A run's rows must come from ONE source shard so it maps into one
    annex window. Crossing runs (a window cell or merged run straddling
    a multiple of S — at most P-1 cells globally) are clipped at the
    boundary and the remainder pieces are appended as fresh runs;
    everything is re-compacted front-first. Returns (starts, lens,
    shifts3, nruns, overflow) with ``extra`` more slots per group.

    ``extra`` must scale with the mesh: one group can need up to P-1
    crossing remainders (callers pass max(8, P-1) — growing the halo
    window can never fix slot exhaustion, so under-sizing here would
    make the escape-sentinel retry loop diverge).
    """
    ng, w3 = starts.shape
    shx, shy, shz = shifts3
    src0 = starts // S
    src1 = jnp.where(lens > 0, (starts + lens - 1) // S, src0)
    cross = (src1 > src0) & (lens > 0)
    len1 = jnp.where(cross, (src0 + 1) * S - starts, lens)
    # remainder pieces (zero-length when no crossing)
    r_start = jnp.where(cross, (src0 + 1) * S, 0)
    r_len = jnp.where(cross, lens - len1, 0)
    # a remainder could itself cross (run longer than a whole slab):
    # flagged as overflow — Wmax resizing cannot fix it, the caller must
    # reduce run_cap below S (config error, not drift)
    r_cross = jnp.any((r_len > 0) & ((r_start + r_len - 1) // S > r_start // S))

    # compact the remainders of each group into `extra` slots
    order = jnp.argsort(~(r_len > 0), axis=1, stable=True)[:, :extra]
    take = lambda a: jnp.take_along_axis(a, order, axis=1)
    e_start, e_len = take(r_start), take(r_len)
    e_shx, e_shy, e_shz = take(shx), take(shy), take(shz)
    overflow = jnp.sum(r_len > 0, axis=1) > extra

    starts = jnp.concatenate([starts, e_start], axis=1)
    lens = jnp.concatenate([jnp.where(cross, len1, lens), e_len], axis=1)
    shx = jnp.concatenate([shx, e_shx], axis=1)
    shy = jnp.concatenate([shy, e_shy], axis=1)
    shz = jnp.concatenate([shz, e_shz], axis=1)

    # re-compact: active runs to the front (stable keeps SFC order)
    active = lens > 0
    _, act_i, starts, lens, shx, shy, shz = jax.lax.sort(
        ((~active).astype(jnp.int32), active.astype(jnp.int32),
         starts, lens, shx, shy, shz),
        num_keys=1, dimension=1, is_stable=True,
    )
    lens = jnp.where(act_i.astype(bool), lens, 0)
    starts = jnp.where(act_i.astype(bool), starts, 0)
    nruns = jnp.sum(active, axis=1).astype(jnp.int32)
    return starts, lens, (shx, shy, shz), nruns, jnp.any(overflow) | r_cross


def window_bounds(starts, lens, S: int, P: int, k, axis: str):
    """Per-source-shard row windows needed by THIS shard, then the
    all_gathered (P_dest, P_src, 2) bounds matrix (halo negotiation)."""
    active = lens > 0
    src = jnp.clip(starts // S, 0, P - 1)
    ends = starts + lens
    lo = jnp.full(P, INF32, jnp.int32)
    hi = jnp.zeros(P, jnp.int32)
    lo = lo.at[src].min(jnp.where(active, starts, INF32))
    hi = hi.at[src].max(jnp.where(active, ends, 0))
    # own slab is served locally, not through the annex
    lo = lo.at[k].set(INF32)
    hi = hi.at[k].set(0)
    mine = jnp.stack([lo, hi], axis=1)  # (P, 2)
    return mine, jax.lax.all_gather(mine, axis)  # (P, P, 2)


def _effective_lo(bounds_all, S: int, Wmax: int, P: int):
    """Deterministic serve offsets: clamp each window's lo into its
    source slab so a fixed Wmax slice stays in range. Sender and
    receiver evaluate the SAME formula on the replicated bounds."""
    lo = bounds_all[:, :, 0]  # (P_dest, P_src)
    srcs = jnp.arange(P, dtype=jnp.int32)[None, :]
    return jnp.clip(lo, srcs * S, (srcs + 1) * S - Wmax)


@named_phase("halo-exchange")
def serve_windows(fields: Sequence, bounds_all, S: int, Wmax: int,
                  P: int, k, axis: str):
    """One all_to_all exchange round: this shard serves every
    destination's window out of its slab; returns the annex — (P, Wmax)
    per field, row (j, i) holding global row lo_eff[k, j] + i."""
    lo_eff = _effective_lo(bounds_all, S, Wmax, P)  # (P_dest, P_src)
    local = jnp.stack(fields, axis=1)  # (S, nf)
    nf = local.shape[1]

    def serve_one(dest):
        off = lo_eff[dest, k] - k * S
        return jax.lax.dynamic_slice(local, (off, 0), (Wmax, nf))

    send = jax.vmap(serve_one)(jnp.arange(P, dtype=jnp.int32))  # (P, Wmax, nf)
    annex = jax.lax.all_to_all(send, axis, 0, 0, tiled=False)
    annex = annex.reshape(P * Wmax, nf)
    return [annex[:, f] for f in range(nf)]


def shard_halo_stage(x, y, z, h, keys, box, nbr, P: int, Wmax: int,
                     axis: str):
    """Shared prologue of a sharded pair-op stage: global table ->
    group windows on the local slab -> localized runs + serve/jbuf
    closures. One implementation for every sharded force stage so the
    overflow contract cannot diverge between pipelines.

    The 5th element is the per-shard telemetry dict (see
    ``exchange_metrics_windowed``) — cheap in-graph scalars the driver
    fetches at its existing flush boundary (schema-v2 ``exchange``
    events); computing them here keeps the measured quantities
    definitionally identical to what the exchange actually ships."""
    from sphexa_tpu.sph.pallas_pairs import group_cell_ranges

    S = x.shape[0]
    k = jax.lax.axis_index(axis)
    table = global_cell_table(keys, nbr.level, axis)
    granges = group_cell_ranges(x, y, z, h, None, box, nbr, table=table)
    ranges, bounds, escaped = localize_ranges(granges, S, P, Wmax, k, axis)

    def serve(fields):
        return serve_windows(fields, bounds, S, Wmax, P, k, axis)

    def jbuf(own, halo):
        return tuple(jnp.concatenate([o, a]) for o, a in zip(own, halo))

    metrics = exchange_metrics_windowed(bounds, Wmax, P, k)
    return ranges, serve, jbuf, escaped, metrics


@named_phase("shard-metrics")
def exchange_metrics_windowed(bounds_all, Wmax: int, P: int, k):
    """Per-shard comm telemetry of the windowed exchange, from the
    already-negotiated (P_dest, P_src, 2) bounds matrix: ``halo_rows`` =
    this shard's true need (sum of its per-source window spans — the
    windowed path SHIPS (P-1) * Wmax regardless), ``halo_occ`` = the
    fullest window's span / Wmax (1.0 = the static window is exactly
    consumed; drift past it trips the escape sentinel)."""
    mine = bounds_all[k]  # (P_src, 2) — own row is [INF32, 0]
    span = jnp.maximum(mine[:, 1] - jnp.minimum(mine[:, 0], mine[:, 1]), 0)
    rows = jnp.sum(span).astype(jnp.int32)
    occ = (jnp.max(span).astype(jnp.float32)
           / jnp.float32(max(Wmax, 1)))
    return {"halo_rows": rows, "halo_occ": occ}


def fold_escape_sentinel(occ, escaped, cap: int, axis: str):
    """Escaped runs mean truncated candidates: encode as an occupancy
    overflow against the CALLER's cap so the driver re-sizes the halo
    window (the shared overflow contract of every sharded stage)."""
    occ = jnp.where(escaped, jnp.int32(cap + 1), occ)
    return jax.lax.pmax(occ, axis)


def _cells_of_runs(starts, lens, table):
    """First/last cell index of every run: runs are unions of consecutive
    cells of the level grid, so [c0, c1] brackets exactly the run's rows.
    Dead runs (len 0) return a harmless [c0, c0]."""
    ends = jnp.where(lens > 0, starts + lens - 1, starts)
    c0 = jnp.searchsorted(table, starts, side="right").astype(jnp.int32) - 1
    c1 = jnp.searchsorted(table, ends, side="right").astype(jnp.int32) - 1
    ncells = table.shape[0] - 1
    return jnp.clip(c0, 0, ncells - 1), jnp.clip(c1, 0, ncells - 1)


def coverage_from_runs(starts, lens, table) -> jax.Array:
    """(ncells,) bool: cells whose rows any ACTIVE candidate run touches —
    this shard's halo NEED at cell granularity (the collision-detection
    product of the reference's halo discovery, collisions.hpp:26-106,
    transposed to the replicated level grid). Interval-marked with one
    +1/-1 scatter + cumsum; gap-bridged cells inside a merged run are
    covered too (their rows ride the run's DMA window)."""
    c0, c1 = _cells_of_runs(starts, lens, table)
    active = (lens > 0).astype(jnp.int32)
    ncells = table.shape[0] - 1
    diff = jnp.zeros(ncells + 1, jnp.int32)
    diff = diff.at[c0.ravel()].add(active.ravel())
    diff = diff.at[c1.ravel() + 1].add(-active.ravel())
    return jnp.cumsum(diff)[:ncells] > 0


def _sparse_layout(covered, table, S: int, P: int):
    """Packed-annex layout for ONE destination's coverage bitmap: per
    source shard j, the rows of every covered cell clipped to j's slab,
    packed in ascending cell order. Sender and receiver evaluate this
    SAME pure function of (covered, table) — the negotiation is one
    all_gathered bitmap, no offset exchange.

    Returns (clen, poff, need): (P, ncells) clipped lens and exclusive
    packed offsets, (P,) total rows per source."""
    t0 = table[:-1][None, :]  # (1, ncells) cell row starts
    t1 = table[1:][None, :]
    slab = jnp.arange(P, dtype=jnp.int32)[:, None] * S  # (P, 1)
    lo = jnp.clip(t0, slab, slab + S)
    hi = jnp.clip(t1, slab, slab + S)
    clen = jnp.where(covered[None, :], hi - lo, 0)  # (P, ncells)
    csum = jnp.cumsum(clen, axis=1)
    return clen, csum - clen, csum[:, -1]


def _pack_rows(clen_j, poff_j, table, S: int, k, Hmax: int):
    """Local row indices (Hmax,) materializing one (dest <- this shard)
    packed buffer: position i holds local row ridx[i] of the i-th
    requested row (ascending cell order). Tail positions past the total
    repeat row 0 — never referenced by any localized run."""
    sel = clen_j > 0
    clip_lo = jnp.maximum(table[:-1], k * S) - k * S  # local row of cell
    # off[c] = clip_lo - poff: ridx[i] = i + off[cell containing i]
    off = jnp.where(sel, clip_lo - poff_j, 0)
    ncells = off.shape[0]
    cidx = jnp.arange(ncells, dtype=jnp.int32)
    INF = jnp.int32(2**30)
    _, off_c = jax.lax.sort(
        (jnp.where(sel, cidx, INF), off), num_keys=1, dimension=0,
        is_stable=True,
    )  # selected cells' offsets compacted to the front, cell order kept
    # segment id per packed position (scatter heads at distinct poff)
    heads = jnp.zeros(Hmax, jnp.int32).at[
        jnp.where(sel, poff_j, Hmax)  # OOB drops (also guards overflow)
    ].add(1)
    seg = jnp.cumsum(heads) - 1
    i = jnp.arange(Hmax, dtype=jnp.int32)
    total = jnp.sum(clen_j)
    ridx = i + off_c[jnp.clip(seg, 0, ncells - 1)]
    return jnp.where((i < total) & (seg >= 0), ridx, 0)


def chain_after(x, dep):
    """Pin a (false) data dependency of ``x`` on ``dep`` via
    ``optimization_barrier`` — the collective-serialization primitive of
    the sparse exchange. XLA:CPU's rendezvous can pair the WRONG
    collectives when two of them become runnable concurrently and the
    per-device thread pools reach them in different orders (this
    container's jax 0.4.x; the cross-routing class the CPU-mesh drain in
    Simulation._drain guards against BETWEEN programs, here WITHIN one).
    Chaining every sparse-path collective onto its predecessor pins one
    total order on every device. Free on real TPU meshes: collectives
    there execute in program order anyway."""
    return jax.lax.optimization_barrier((x, dep))[0]


@named_phase("halo-exchange")
def serve_sparse(fields: Sequence, covered_all, table, S: int,
                 hmax: Tuple[int, ...], P: int, k, axis: str,
                 token=None):
    """Sparse halo serve: P-1 ppermute rounds, round r shipping each
    shard's packed rows to its distance-r SFC successor in a buffer of
    STATIC size hmax[r-1] — per-distance sizing is what lets the comm
    volume track the true halo surface (neighbor slabs carry ~the
    surface, distant slabs only the odd Hilbert-wrap cell) instead of a
    single max window degenerating to the whole slab
    (exchange_halos.hpp:43-119 sends exact per-peer ranges the same way).
    Returns (annex fields, token): annex rows [src at distance 1 |
    distance 2 | ...] per field — row order matches
    localize_ranges_sparse's packed offsets. ``token``: optional value
    from the PREVIOUS serve; the rounds chain on it (and on each other)
    through ``chain_after`` so the P-1 independent ppermutes execute in
    one total order on every device (rendezvous-race guard)."""
    local = jnp.stack(fields, axis=1)  # (S, nf)
    nf = local.shape[1]
    parts = []
    for r in range(1, P):
        dest = (k + r) % P
        clen, poff = _sparse_layout_dest(covered_all, dest, table, S, k)
        ridx = _pack_rows(clen, poff, table, S, k, hmax[r - 1])
        send = local[ridx]  # (Hmax_r, nf)
        if token is not None:
            send = chain_after(send, token)
        perm = [(i, (i + r) % P) for i in range(P)]
        parts.append(jax.lax.ppermute(send, axis, perm))
        token = parts[-1]
    annex = jnp.concatenate(parts, axis=0) if parts else local[:0]
    return [annex[:, f] for f in range(nf)], token


def _sparse_layout_dest(covered_all, dest, table, S: int, k):
    """One (dest <- this shard k) column of the packed layout: clen/poff
    of dest's covered cells clipped to k's slab. poff is an exclusive
    cumsum per (dest, src) pair independently, so the src = k column
    needs only dest's bitmap — sender and receiver evaluate the same
    formula without materializing the (P, P, ncells) cube."""
    covered = jax.lax.dynamic_index_in_dim(
        covered_all, dest, axis=0, keepdims=False
    )  # (ncells,)
    t0, t1 = table[:-1], table[1:]
    lo = jnp.clip(t0, k * S, (k + 1) * S)
    hi = jnp.clip(t1, k * S, (k + 1) * S)
    clen = jnp.where(covered, hi - lo, 0)
    csum = jnp.cumsum(clen)
    return clen, csum - clen


@named_phase("halo-exchange")
def localize_ranges_sparse(
    ranges: GroupRanges, table, S: int, P: int, hmax: Tuple[int, ...],
    k, axis: str,
) -> Tuple[GroupRanges, jax.Array, jax.Array, jax.Array]:
    """Sparse analog of ``localize_ranges``: rewrite global-row runs into
    j-buffer rows [own slab (S) | packed annex (sum(hmax))] using the
    cell-granular packed layout. Also computes and all_gathers this
    shard's coverage bitmap (the negotiation). Returns (localized
    ranges, covered_all (P, ncells), escaped, coverage bitmap)."""
    starts, lens, sh3, nruns, split_ovf = _split_runs(
        ranges.starts, ranges.lens,
        (ranges.shift_x, ranges.shift_y, ranges.shift_z), S,
        extra=max(8, P - 1),
    )
    if len(hmax) != P - 1:
        raise ValueError(f"hmax needs P-1={P-1} per-distance caps, got "
                         f"{len(hmax)}")
    covered = coverage_from_runs(starts, lens, table)
    covered_all = jax.lax.all_gather(covered, axis)  # (P, ncells)

    clen, poff, need = _sparse_layout(covered, table, S, P)  # per src j
    # static per-distance caps: need from src j rides round (k - j) % P
    hmax_arr = jnp.asarray((0,) + tuple(hmax), jnp.int32)  # index by r
    src_j = jnp.arange(P, dtype=jnp.int32)
    r_of_j = (k - src_j) % P
    over = (need > hmax_arr[r_of_j]) & (src_j != k)
    escaped = jnp.any(over) | split_ovf

    # annex offset of distance r: S + sum of previous rounds' caps
    prefix = np.concatenate([[0], np.cumsum(hmax)]).astype(np.int32)
    prefix_arr = jnp.asarray(prefix)  # (P,), prefix[r-1] = offset of r

    active = lens > 0
    src = jnp.clip(starts // S, 0, P - 1)
    own = src == k
    c0, _ = _cells_of_runs(starts, lens, table)
    clip_lo = jnp.maximum(table[c0], src * S)
    packed = poff[src, c0] + (starts - clip_lo)
    r_run = (k - src) % P
    cap_run = hmax_arr[r_run]
    # a run past its round's cap would index outside the annex: zero it
    # (escaped already tripped above via need > cap, so the step is
    # discarded and re-sized — same contract as the windowed path)
    in_cap = own | (packed + lens <= cap_run)
    local = jnp.where(
        own, starts - k * S,
        S + prefix_arr[jnp.clip(r_run - 1, 0, P - 1)] + packed,
    )
    lens = jnp.where(active & in_cap, lens, 0)
    local = jnp.where(lens > 0, local, 0)

    out = GroupRanges(
        starts=local, lens=lens,
        shift_x=sh3[0], shift_y=sh3[1], shift_z=sh3[2],
        ncells=nruns, occupancy=ranges.occupancy, boxl=ranges.boxl,
    )
    return out, covered_all, escaped, covered


def shard_halo_stage_sparse(x, y, z, h, keys, box, nbr, P: int,
                            hmax: Tuple[int, ...], axis: str):
    """Sparse-exchange variant of ``shard_halo_stage`` — same contract
    (ranges, serve, jbuf, escaped, metrics), comm volume sum(hmax) rows
    per serve instead of (P-1) * Wmax. The reference analog is
    exchangeHalos' per-peer leaf-range p2p (exchange_halos.hpp:43-119);
    here the range lists are implicit in the all_gathered coverage
    bitmaps + the replicated cell table, so the negotiation is
    O(P * ncells) bits."""
    from sphexa_tpu.sph.pallas_pairs import group_cell_ranges

    S = x.shape[0]
    k = jax.lax.axis_index(axis)
    table = global_cell_table(keys, nbr.level, axis)
    granges = group_cell_ranges(x, y, z, h, None, box, nbr, table=table)
    ranges, covered_all, escaped, covered = localize_ranges_sparse(
        granges, table, S, P, hmax, k, axis
    )

    # one total order over EVERY collective this stage issues, carried
    # across serve calls: the chain seed is the negotiation all_gather's
    # output, each serve's ppermute rounds link on their predecessor
    # (chain_after — the XLA:CPU rendezvous-race guard)
    chain = {"token": covered_all}

    def serve(fields):
        out, tok = serve_sparse(fields, covered_all, table, S, hmax, P,
                                k, axis, token=chain["token"])
        chain["token"] = tok
        return out

    def jbuf(own, halo):
        return tuple(jnp.concatenate([o, a]) for o, a in zip(own, halo))

    metrics = exchange_metrics_sparse(covered, table, S, hmax, P, k)
    return ranges, serve, jbuf, escaped, metrics


@named_phase("shard-metrics")
def exchange_metrics_sparse(covered, table, S: int,
                            hmax: Tuple[int, ...], P: int, k):
    """Per-shard comm telemetry of the sparse exchange, from this
    shard's own coverage bitmap (the Bédorf-2014 LET comm-volume
    accounting, PAPERS.md): ``halo_rows`` = the true remote rows this
    shard needs (sum over sources of its covered cells clipped to their
    slabs — the exchange SHIPS the static sum(hmax) regardless),
    ``halo_occ`` = the fullest per-distance buffer's need / cap (1.0
    means the sized cap is exactly consumed; beyond it the escape
    sentinel discards the step)."""
    _, _, need = _sparse_layout(covered, table, S, P)  # (P_src,)
    src_j = jnp.arange(P, dtype=jnp.int32)
    own = src_j == k
    rows = jnp.sum(jnp.where(own, 0, need)).astype(jnp.int32)
    hmax_arr = jnp.asarray((1,) + tuple(hmax), jnp.int32)  # index by r
    caps = hmax_arr[(k - src_j) % P].astype(jnp.float32)
    occ = jnp.max(jnp.where(own, 0.0, need.astype(jnp.float32) / caps))
    return {"halo_rows": rows, "halo_occ": occ}


@named_phase("halo-exchange")
def localize_ranges(
    ranges: GroupRanges, S: int, P: int, Wmax: int, k, axis: str,
) -> Tuple[GroupRanges, jax.Array, jax.Array]:
    """Rewrite a GLOBAL-row GroupRanges into j-buffer rows
    [own slab (S) | annex (P * Wmax)]. Returns (localized ranges,
    all_gathered (P, P, 2) bounds matrix, escaped flag).

    Runs outside their source's served window (drift since the last
    Wmax sizing) zero out and flip ``escaped``, which the caller folds
    into the occupancy sentinel.
    """
    starts, lens, sh3, nruns, split_ovf = _split_runs(
        ranges.starts, ranges.lens,
        (ranges.shift_x, ranges.shift_y, ranges.shift_z), S,
        extra=max(8, P - 1),
    )
    mine, bounds_all = window_bounds(starts, lens, S, P, k, axis)
    lo_eff = _effective_lo(bounds_all, S, Wmax, P)[k]  # (P_src,)

    src = jnp.clip(starts // S, 0, P - 1)
    own = src == k
    lo_run = lo_eff[src]
    in_window = own | (
        (starts >= lo_run) & (starts + lens <= lo_run + Wmax)
    )
    active = lens > 0
    escaped = jnp.any(active & ~in_window) | split_ovf

    local = jnp.where(
        own, starts - k * S, S + src * Wmax + (starts - lo_run)
    )
    lens = jnp.where(active & in_window, lens, 0)
    local = jnp.where(lens > 0, local, 0)

    out = GroupRanges(
        starts=local, lens=lens,
        shift_x=sh3[0], shift_y=sh3[1], shift_z=sh3[2],
        ncells=nruns, occupancy=ranges.occupancy, boxl=ranges.boxl,
    )
    return out, bounds_all, escaped
