"""Windowed halo exchange for the sharded Pallas fast path.

TPU-native transposition of the reference's halo subsystem
(cstone/halos/exchange_halos.hpp:43-119 pack-ranges -> p2p -> scatter,
discovery cstone/traversal/collisions.hpp:26-106). The reference sends
per-peer lists of octree-leaf row ranges; here each shard

1. runs the shared group-window prologue on its OWN slab against the
   GLOBAL cell-starts table (an O(ncells) psum of per-shard histograms —
   the update_mpi.hpp allreduce analog, no key gather),
2. derives, per source shard, the single row WINDOW [lo, hi) covering
   every candidate run it needs from that shard (discovery),
3. all_gathers the (P, P, 2) bounds matrix (the exchange_keys.hpp
   negotiation analog — O(P^2) ints),
4. receives the windows with ONE all_to_all of fixed (P, Wmax, nf)
   buffers: shard j serves dynamic slices of its slab (pack), shard k
   concatenates [own slab | annex] into the engine's j-buffer (scatter).

Comm volume per shard = (P-1) * Wmax rows per exchange stage — the
MEASURED candidate need (sized at reconfiguration, guarded in-step), not
an unconditional O(N) replication. At CI scale (1e6 particles / 8 shards,
level-4 cells) windows still span most of a slab — the halo *is* the
volume at that granularity — but Wmax shrinks relative to the shard size
as particles-per-shard grow (deeper grids, smaller surface fraction),
which is the reference's scaling property (SURVEY.md §2e P2).

A candidate run that escapes its source window (particle drift after the
last sizing) zeroes itself and trips the step's occupancy sentinel; the
CALLER owns recovery — discard the step and rebuild the sharded stepper
with a larger ``halo_window`` (tests/test_parallel.py exercises both the
sentinel and the resize), mirroring the neighbor-cap overflow contract.
"""

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp

from sphexa_tpu.dtypes import KEY_BITS, KEY_DTYPE
from sphexa_tpu.sph.pallas_pairs import GroupRanges

INF32 = jnp.int32(2**30)


def estimate_halo_window(
    x, y, z, h, sorted_keys, box, nbr, P: int,
    margin: float = 1.4, quantum: int = 1024,
) -> int:
    """Size the static per-peer window Wmax from the current particle
    distribution (host-side, reconfiguration granularity — the halo
    discovery analog of estimate_cell_cap). Runs the shared prologue on
    the full arrays, clips candidate runs at slab boundaries, and returns
    the padded max over (dest, src) pairs of the needed row span.
    The in-step ``escaped`` guard remains the correctness backstop."""
    import numpy as np

    from sphexa_tpu.sph.pallas_pairs import group_cell_ranges

    n = x.shape[0]
    S = -(-n // P)
    ranges = group_cell_ranges(x, y, z, h, sorted_keys, box, nbr)
    starts = np.asarray(ranges.starts)
    lens = np.asarray(ranges.lens)
    g = nbr.group
    wmax = 1
    for k in range(P):
        g0 = k * S // g
        g1 = min(((k + 1) * S + g - 1) // g, starts.shape[0])
        st = starts[g0:g1].ravel()
        ln = lens[g0:g1].ravel()
        st, ln = st[ln > 0], ln[ln > 0]
        for j in range(P):
            if j == k:
                continue
            lo_j, hi_j = j * S, (j + 1) * S
            ov = (st < hi_j) & (st + ln > lo_j)
            if not ov.any():
                continue
            a = int(np.maximum(st[ov], lo_j).min())
            b = int(np.minimum(st[ov] + ln[ov], hi_j).max())
            wmax = max(wmax, b - a)
    padded = int(-(-int(wmax * margin) // quantum) * quantum)
    return min(padded, S)


def global_cell_table(local_keys, level: int, axis: str) -> jax.Array:
    """Cell-starts table of the level-``level`` grid over the DISTRIBUTED
    key array: per-shard cid histogram -> psum -> exclusive cumsum.
    O(ncells) comm; replicated result (update_mpi.hpp:26-106 role)."""
    shift = KEY_DTYPE(3 * (KEY_BITS - level))
    ncells = (1 << level) ** 3
    cid = (local_keys >> shift).astype(jnp.int32)
    hist = jnp.zeros(ncells, jnp.int32).at[cid].add(1)
    hist = jax.lax.psum(hist, axis)
    return jnp.concatenate(
        [jnp.zeros(1, jnp.int32), jnp.cumsum(hist)]
    ).astype(jnp.int32)


def _split_runs(starts, lens, shifts3, S: int, extra: int = 8):
    """Split candidate runs that cross shard-slab boundaries.

    A run's rows must come from ONE source shard so it maps into one
    annex window. Crossing runs (a window cell or merged run straddling
    a multiple of S — at most P-1 cells globally) are clipped at the
    boundary and the remainder pieces are appended as fresh runs;
    everything is re-compacted front-first. Returns (starts, lens,
    shifts3, nruns, overflow) with ``extra`` more slots per group.

    ``extra`` must scale with the mesh: one group can need up to P-1
    crossing remainders (callers pass max(8, P-1) — growing the halo
    window can never fix slot exhaustion, so under-sizing here would
    make the escape-sentinel retry loop diverge).
    """
    ng, w3 = starts.shape
    shx, shy, shz = shifts3
    src0 = starts // S
    src1 = jnp.where(lens > 0, (starts + lens - 1) // S, src0)
    cross = (src1 > src0) & (lens > 0)
    len1 = jnp.where(cross, (src0 + 1) * S - starts, lens)
    # remainder pieces (zero-length when no crossing)
    r_start = jnp.where(cross, (src0 + 1) * S, 0)
    r_len = jnp.where(cross, lens - len1, 0)
    # a remainder could itself cross (run longer than a whole slab):
    # flagged as overflow — Wmax resizing cannot fix it, the caller must
    # reduce run_cap below S (config error, not drift)
    r_cross = jnp.any((r_len > 0) & ((r_start + r_len - 1) // S > r_start // S))

    # compact the remainders of each group into `extra` slots
    order = jnp.argsort(~(r_len > 0), axis=1, stable=True)[:, :extra]
    take = lambda a: jnp.take_along_axis(a, order, axis=1)
    e_start, e_len = take(r_start), take(r_len)
    e_shx, e_shy, e_shz = take(shx), take(shy), take(shz)
    overflow = jnp.sum(r_len > 0, axis=1) > extra

    starts = jnp.concatenate([starts, e_start], axis=1)
    lens = jnp.concatenate([jnp.where(cross, len1, lens), e_len], axis=1)
    shx = jnp.concatenate([shx, e_shx], axis=1)
    shy = jnp.concatenate([shy, e_shy], axis=1)
    shz = jnp.concatenate([shz, e_shz], axis=1)

    # re-compact: active runs to the front (stable keeps SFC order)
    active = lens > 0
    _, act_i, starts, lens, shx, shy, shz = jax.lax.sort(
        ((~active).astype(jnp.int32), active.astype(jnp.int32),
         starts, lens, shx, shy, shz),
        num_keys=1, dimension=1, is_stable=True,
    )
    lens = jnp.where(act_i.astype(bool), lens, 0)
    starts = jnp.where(act_i.astype(bool), starts, 0)
    nruns = jnp.sum(active, axis=1).astype(jnp.int32)
    return starts, lens, (shx, shy, shz), nruns, jnp.any(overflow) | r_cross


def window_bounds(starts, lens, S: int, P: int, k, axis: str):
    """Per-source-shard row windows needed by THIS shard, then the
    all_gathered (P_dest, P_src, 2) bounds matrix (halo negotiation)."""
    active = lens > 0
    src = jnp.clip(starts // S, 0, P - 1)
    ends = starts + lens
    lo = jnp.full(P, INF32, jnp.int32)
    hi = jnp.zeros(P, jnp.int32)
    lo = lo.at[src].min(jnp.where(active, starts, INF32))
    hi = hi.at[src].max(jnp.where(active, ends, 0))
    # own slab is served locally, not through the annex
    lo = lo.at[k].set(INF32)
    hi = hi.at[k].set(0)
    mine = jnp.stack([lo, hi], axis=1)  # (P, 2)
    return mine, jax.lax.all_gather(mine, axis)  # (P, P, 2)


def _effective_lo(bounds_all, S: int, Wmax: int, P: int):
    """Deterministic serve offsets: clamp each window's lo into its
    source slab so a fixed Wmax slice stays in range. Sender and
    receiver evaluate the SAME formula on the replicated bounds."""
    lo = bounds_all[:, :, 0]  # (P_dest, P_src)
    srcs = jnp.arange(P, dtype=jnp.int32)[None, :]
    return jnp.clip(lo, srcs * S, (srcs + 1) * S - Wmax)


def serve_windows(fields: Sequence, bounds_all, S: int, Wmax: int,
                  P: int, k, axis: str):
    """One all_to_all exchange round: this shard serves every
    destination's window out of its slab; returns the annex — (P, Wmax)
    per field, row (j, i) holding global row lo_eff[k, j] + i."""
    lo_eff = _effective_lo(bounds_all, S, Wmax, P)  # (P_dest, P_src)
    local = jnp.stack(fields, axis=1)  # (S, nf)
    nf = local.shape[1]

    def serve_one(dest):
        off = lo_eff[dest, k] - k * S
        return jax.lax.dynamic_slice(local, (off, 0), (Wmax, nf))

    send = jax.vmap(serve_one)(jnp.arange(P, dtype=jnp.int32))  # (P, Wmax, nf)
    annex = jax.lax.all_to_all(send, axis, 0, 0, tiled=False)
    annex = annex.reshape(P * Wmax, nf)
    return [annex[:, f] for f in range(nf)]


def shard_halo_stage(x, y, z, h, keys, box, nbr, P: int, Wmax: int,
                     axis: str):
    """Shared prologue of a sharded pair-op stage: global table ->
    group windows on the local slab -> localized runs + serve/jbuf
    closures. One implementation for every sharded force stage so the
    overflow contract cannot diverge between pipelines."""
    from sphexa_tpu.sph.pallas_pairs import group_cell_ranges

    S = x.shape[0]
    k = jax.lax.axis_index(axis)
    table = global_cell_table(keys, nbr.level, axis)
    granges = group_cell_ranges(x, y, z, h, None, box, nbr, table=table)
    ranges, bounds, escaped = localize_ranges(granges, S, P, Wmax, k, axis)

    def serve(fields):
        return serve_windows(fields, bounds, S, Wmax, P, k, axis)

    def jbuf(own, halo):
        return tuple(jnp.concatenate([o, a]) for o, a in zip(own, halo))

    return ranges, serve, jbuf, escaped


def fold_escape_sentinel(occ, escaped, cap: int, axis: str):
    """Escaped runs mean truncated candidates: encode as an occupancy
    overflow against the CALLER's cap so the driver re-sizes the halo
    window (the shared overflow contract of every sharded stage)."""
    occ = jnp.where(escaped, jnp.int32(cap + 1), occ)
    return jax.lax.pmax(occ, axis)


def localize_ranges(
    ranges: GroupRanges, S: int, P: int, Wmax: int, k, axis: str,
) -> Tuple[GroupRanges, jax.Array, jax.Array]:
    """Rewrite a GLOBAL-row GroupRanges into j-buffer rows
    [own slab (S) | annex (P * Wmax)]. Returns (localized ranges,
    all_gathered (P, P, 2) bounds matrix, escaped flag).

    Runs outside their source's served window (drift since the last
    Wmax sizing) zero out and flip ``escaped``, which the caller folds
    into the occupancy sentinel.
    """
    starts, lens, sh3, nruns, split_ovf = _split_runs(
        ranges.starts, ranges.lens,
        (ranges.shift_x, ranges.shift_y, ranges.shift_z), S,
        extra=max(8, P - 1),
    )
    mine, bounds_all = window_bounds(starts, lens, S, P, k, axis)
    lo_eff = _effective_lo(bounds_all, S, Wmax, P)[k]  # (P_src,)

    src = jnp.clip(starts // S, 0, P - 1)
    own = src == k
    lo_run = lo_eff[src]
    in_window = own | (
        (starts >= lo_run) & (starts + lens <= lo_run + Wmax)
    )
    active = lens > 0
    escaped = jnp.any(active & ~in_window) | split_ovf

    local = jnp.where(
        own, starts - k * S, S + src * Wmax + (starts - lo_run)
    )
    lens = jnp.where(active & in_window, lens, 0)
    local = jnp.where(lens > 0, local, 0)

    out = GroupRanges(
        starts=local, lens=lens,
        shift_x=sh3[0], shift_y=sh3[1], shift_z=sh3[2],
        ncells=nruns, occupancy=ranges.occupancy, boxl=ranges.boxl,
    )
    return out, bounds_all, escaped
