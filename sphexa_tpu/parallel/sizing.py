"""Device-side (re)configuration sizing: O(N/P)-transfer replacements for
the host gathers in make_propagator_config / Simulation._configure*.

The reference never materializes the global problem on one rank: octree
counts are allreduce-incremental (cstone/tree/update_mpi.hpp:26-106) and
assignment is rank-local (cstone/domain/assignment.hpp:84-122). The
transposition here: every sizing quantity is computed by jitted reductions
over the (possibly sharded) device arrays — GSPMD partitions them over the
mesh — and only SCALARS or O(#cells) histograms ever reach the host.

Three groups of helpers:

- ``sizing_stats``: max cell occupancy + per-dim group extents — the
  inputs of make_propagator_config's level/cap/window choice.
- ``device_halo_window``: the per-(dest, src) shard row-window maximum that
  sizes the windowed all_to_all exchange (parallel/exchange.py), computed
  with scatter-min/max instead of the host loop in estimate_halo_window.
- ``key_histogram``/``drill_histogram`` + ``leaf_array_from_device_keys``:
  the distributed-tree-build analog — a base-level key histogram plus
  targeted drill-downs of overfull cells replaces shipping the full key
  array to the host (update_mpi.hpp's node-count allreduce, transposed).
"""

import functools
from typing import Tuple

import numpy as np
import jax
import jax.numpy as jnp

from sphexa_tpu.dtypes import KEY_BITS, KEY_DTYPE

# numpy, NOT jnp: lazily-imported module — a jnp constant built while a
# trace is active would itself be a tracer and leak into later traces
# (see parallel/exchange.py INF32)
INF32 = np.int32(2**30)

# device->host bytes moved by the sizing path since the last reset — the
# transfer-size counter that PROVES reconfiguration is O(N/P): every fetch
# in the device-sizing path goes through fetch(), and tests run the whole
# configure under jax.transfer_guard_device_to_host("disallow") so a stray
# implicit np.asarray(full_array) fails loudly instead of hiding.
TRANSFER_BYTES = 0


def reset_transfer_bytes() -> None:
    global TRANSFER_BYTES
    TRANSFER_BYTES = 0


def fetch(x):
    """Explicit, metered device->host transfer (allowed under the
    device-to-host transfer guard; implicit transfers are not)."""
    global TRANSFER_BYTES
    out = jax.device_get(x)
    TRANSFER_BYTES += sum(
        a.nbytes for a in jax.tree.leaves(out) if hasattr(a, "nbytes")
    )
    return out


# ---------------------------------------------------------------------------
# neighbor-config sizing
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("level", "group", "curve"))
def sizing_stats(x, y, z, box, level: int, group: int,
                 curve: str = "hilbert", keys=None, order=None):
    """(occ_max, ext (3,)): the per-level stats make_propagator_config
    needs beyond n and h_max (h_max must be fetched BEFORE this call —
    ``level`` is static and derives from it) — one jitted pass, four
    scalars to the host.

    ``keys``/``order``: optional precomputed device keys + argsort of
    the SAME (x, y, z, box, curve). Simulation._configure passes them
    when self-gravity also needs keys, so the multi-device reconfigure
    pays keygen+argsort over N ONCE (round-4 reviewer finding: this
    helper and _configure_gravity each ran their own)."""
    from sphexa_tpu.sfc.keys import compute_sfc_keys

    if keys is None:
        keys = compute_sfc_keys(x, y, z, box, curve=curve)
    if order is None:
        order = jnp.argsort(keys)
    skeys = keys[order]
    shift = KEY_DTYPE(3 * (KEY_BITS - level))
    ncell3 = (1 << level) ** 3
    cid = (skeys >> shift).astype(jnp.int32)
    occ = jnp.max(jnp.zeros(ncell3, jnp.int32).at[cid].add(1))

    n = x.shape[0]
    ng = -(-n // group)
    pad = ng * group - n

    def ext_of(a):
        a = a[order]
        if pad:
            a = jnp.concatenate([a, jnp.broadcast_to(a[-1:], (pad,))])
        g = a.reshape(ng, group)
        return jnp.max(g.max(axis=1) - g.min(axis=1))

    ext = jnp.stack([ext_of(x), ext_of(y), ext_of(z)])
    return occ, ext


# ---------------------------------------------------------------------------
# halo-window sizing
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("nbr", "P"))
def _halo_window_spans(x, y, z, h, keys, box, nbr, P: int):
    """Max over (dest, src != dest) pairs of the source-row span dest's
    candidate runs need — the device analog of estimate_halo_window's
    host loop, via scatter-min/max into a (P, P) bounds matrix."""
    from sphexa_tpu.sph.pallas_pairs import group_cell_ranges

    order = jnp.argsort(keys)
    xs, ys, zs, hs = x[order], y[order], z[order], h[order]
    skeys = keys[order]
    ranges = group_cell_ranges(xs, ys, zs, hs, skeys, box, nbr)
    starts, lens = ranges.starts, ranges.lens  # (NG, W3)
    ng, w3 = starts.shape
    n = x.shape[0]
    S = -(-n // P)

    # a group's rows can straddle two dest slabs: charge its runs to both
    g0 = (jnp.arange(ng, dtype=jnp.int32) * nbr.group) // S
    g1 = jnp.minimum(
        ((jnp.arange(ng, dtype=jnp.int32) + 1) * nbr.group - 1) // S, P - 1
    )

    active = lens > 0
    ends = starts + lens
    # a run crossing a slab boundary contributes a clipped piece to both
    # sources (the caller clamps run_cap <= S, so a run touches at most
    # two slabs and the two pieces below cover it exactly)
    src0 = jnp.clip(starts // S, 0, P - 1)
    src1 = jnp.clip(jnp.where(active, ends - 1, starts) // S, 0, P - 1)

    lo_m = jnp.full((P, P), INF32, jnp.int32)
    hi_m = jnp.zeros((P, P), jnp.int32)

    def add_piece(lo_m, hi_m, dest, src, lo, hi, valid):
        d = jnp.broadcast_to(dest[:, None], (ng, w3))
        lo = jnp.where(valid, lo, INF32)
        hi = jnp.where(valid, hi, 0)
        lo_m = lo_m.at[d, src].min(lo)
        hi_m = hi_m.at[d, src].max(hi)
        return lo_m, hi_m

    for dest in (g0, g1):
        # piece inside the run's first source slab
        p0_hi = jnp.minimum(ends, (src0 + 1) * S)
        lo_m, hi_m = add_piece(lo_m, hi_m, dest, src0, starts, p0_hi, active)
        # remainder in the next slab (zero-width unless crossing)
        cross = active & (src1 > src0)
        lo_m, hi_m = add_piece(
            lo_m, hi_m, dest, src1, src1 * S, ends, cross
        )

    off_diag = ~jnp.eye(P, dtype=bool)
    span = jnp.where(off_diag & (hi_m > 0), hi_m - jnp.minimum(lo_m, hi_m), 0)
    return jnp.max(span)


def device_halo_window(x, y, z, h, keys, box, nbr, P: int,
                       margin: float = 1.4, quantum: int = 1024) -> int:
    """estimate_halo_window with device-side discovery: one scalar comes
    to the host. Same margin/quantum padding contract."""
    import dataclasses

    n = x.shape[0]
    S = -(-n // P)
    # the sharded force stage clamps run_cap to the slab size (a run must
    # come from one source shard, propagator._std_forces_sharded), so
    # measure with the SAME clamp — it also guarantees a run spans at most
    # two slabs, which the two-piece scatter below relies on
    if nbr.run_cap > S:
        nbr = dataclasses.replace(nbr, run_cap=S)
    wmax = max(int(fetch(_halo_window_spans(x, y, z, h, keys, box, nbr, P))), 1)
    padded = int(-(-int(wmax * margin) // quantum) * quantum)
    return min(padded, S)


@functools.partial(jax.jit, static_argnames=("nbr", "P"))
def sparse_need_matrix(x, y, z, h, keys, box, nbr, P: int):
    """(P_dest, P_src) row-need matrix of the sparse cell-granular halo
    exchange: entry [k, j] = rows shard k's covered cells clip to shard
    j's slab (diagonal = own slab, served locally). Computed from the
    same candidate-run coverage the in-step path uses
    (exchange.localize_ranges_sparse), so the in-step ``need > cap``
    escape can only fire after genuine drift — and the in-step
    telemetry ``shard_rows`` (exchange.exchange_metrics_sparse) must
    equal this matrix's off-diagonal row sums on an undrifted state
    (pinned by tests/test_parallel.py)."""
    from sphexa_tpu.parallel.exchange import _cells_of_runs, _sparse_layout
    from sphexa_tpu.sph.pallas_pairs import group_cell_ranges

    n = x.shape[0]
    if n % P:
        raise ValueError(f"sparse halo sizing needs n % P == 0 "
                         f"(shard_state's contract), got {n} % {P}")
    S = n // P
    order = jnp.argsort(keys)
    xs, ys, zs, hs = x[order], y[order], z[order], h[order]
    skeys = keys[order]
    ncells = (1 << nbr.level) ** 3
    cid = (skeys >> KEY_DTYPE(3 * (KEY_BITS - nbr.level))).astype(jnp.int32)
    table = jnp.concatenate([
        jnp.zeros(1, jnp.int32),
        jnp.cumsum(jnp.zeros(ncells, jnp.int32).at[cid].add(1)),
    ]).astype(jnp.int32)
    # per-SHARD group windows: the in-step prologue forms groups within
    # each slab (rows restart at k*S), so sizing over global group
    # boundaries would measure different bboxes whenever S % group != 0
    # and could under-size a cap with zero drift
    shard = lambda a: a.reshape(P, S)
    ranges = jax.vmap(
        lambda a, b, c, d: group_cell_ranges(a, b, c, d, None, box, nbr,
                                             table=table)
    )(shard(xs), shard(ys), shard(zs), shard(hs))
    starts, lens = ranges.starts, ranges.lens  # (P, NG_s, W3)

    c0, c1 = _cells_of_runs(starts, lens, table)
    active = (lens > 0).astype(jnp.int32)
    dest = jnp.broadcast_to(
        jnp.arange(P, dtype=jnp.int32)[:, None, None], starts.shape
    )
    diff = jnp.zeros((P, ncells + 1), jnp.int32)
    diff = diff.at[dest.ravel(), c0.ravel()].add(active.ravel())
    diff = diff.at[dest.ravel(), c1.ravel() + 1].add(-active.ravel())
    covered = jnp.cumsum(diff, axis=1)[:, :ncells] > 0  # (P_dest, ncells)

    return jax.vmap(
        lambda cov: _sparse_layout(cov, table, S, P)[2]
    )(covered)  # (P_dest, P_src)


@functools.partial(jax.jit, static_argnames=("nbr", "P"))
def _sparse_halo_needs(x, y, z, h, keys, box, nbr, P: int):
    """(P-1,) per-DISTANCE row needs: entry r-1 = max over shards k of
    the rows shard k needs from its distance-r SFC predecessor
    (parallel/exchange.serve_sparse ships round r in a buffer of exactly
    this size) — the per-distance fold of ``sparse_need_matrix``."""
    need = sparse_need_matrix(x, y, z, h, keys, box, nbr, P)
    j = jnp.arange(P, dtype=jnp.int32)
    return jnp.stack(
        [need[(j + r) % P, j].max() for r in range(1, P)]
    )  # (P-1,)


def device_sparse_halo(x, y, z, h, keys, box, nbr, P: int,
                       margin: float = 1.4, quantum: int = 256,
                       ) -> Tuple[int, ...]:
    """Size the sparse exchange's static per-distance row caps (the
    Hmax tuple of shard_halo_stage_sparse). P-1 scalars to the host."""
    import dataclasses

    n = x.shape[0]
    S = -(-n // P)
    if nbr.run_cap > S:
        nbr = dataclasses.replace(nbr, run_cap=S)
    per_r = np.asarray(fetch(_sparse_halo_needs(x, y, z, h, keys, box,
                                                nbr, P)))
    pad = lambda v: min(
        int(-(-int(max(int(v), 1) * margin) // quantum) * quantum), S
    )
    return tuple(pad(v) for v in per_r)


# ---------------------------------------------------------------------------
# gravity near-field (MAC) sizing
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("meta", "theta", "P"))
def gravity_need_matrix(xs, ys, zs, ms, skeys, box, tree, meta,
                        theta: float, P: int, shifts=None):
    """(P_dest, P_src) row-need matrix of the sparse gravity near-field
    exchange: entry [k, j] = rows of shard j's slab whose leaf cells FAIL
    the monotone MAC opening test against shard k's slab bbox — dest k's
    P2P essential set (the Warren-Salmon LET boundary). Everything the
    slab bbox accepts is already covered by M2P on the replicated coarse
    tree, so those rows never cross the wire.

    Conservative by the monotone vector MAC (traversal.py
    ``_monotone_mac_geometry``): the accept region only GROWS as the
    target bbox shrinks, so a leaf opened by any in-slab target block
    (or LET / bitmask-superblock classification) is opened by the whole
    slab bbox too — the in-step ``need > cap`` escape can only fire
    after genuine drift. ``shifts`` ((ns, 3), optional) unions the
    opened set over the Ewald replica offsets; ``compute_gravity`` adds
    a shift to the TARGET positions and the shell set is symmetric, so
    ``bc + shift`` covers every replica pass. Inputs are the SORTED
    gravity arrays (``skeys`` ascending) so slab k of ``reshape(P, S)``
    is shard k's key slab."""
    from sphexa_tpu.gravity.traversal import (
        _monotone_mac_geometry,
        compute_multipoles,
    )
    from sphexa_tpu.parallel.exchange import _sparse_layout

    n = xs.shape[0]
    if n % P:
        raise ValueError(f"gravity halo sizing needs n % P == 0 "
                         f"(shard_state's contract), got {n} % {P}")
    S = n // P
    node_mass, node_com, _, edges = compute_multipoles(
        xs, ys, zs, ms, skeys, tree, meta, order=0
    )
    valid = node_mass > 0
    gc, gs, mac2 = _monotone_mac_geometry(box, tree, meta, node_com,
                                          valid, theta)
    slab = lambda a: a.reshape(P, S)
    bmin = jnp.stack([slab(a).min(axis=1) for a in (xs, ys, zs)], axis=1)
    bmax = jnp.stack([slab(a).max(axis=1) for a in (xs, ys, zs)], axis=1)
    bc, bs = 0.5 * (bmax + bmin), 0.5 * (bmax - bmin)  # (P, 3)

    def opened_from(center):
        d = jnp.maximum(
            jnp.abs(center[:, None, :] - gc[None, :, :])
            - bs[:, None, :] - gs[None, :, :], 0.0)
        return jnp.sum(d * d, axis=2) < mac2[None, :]  # (P, num_nodes)

    opened = opened_from(bc)
    if shifts is not None:
        for i in range(shifts.shape[0]):
            opened = opened | opened_from(bc + shifts[i][None, :])
    cov = opened[:, tree.node_of_leaf]  # (P_dest, num_leaves)
    return jax.vmap(lambda c: _sparse_layout(c, edges, S, P)[2])(cov)


@functools.partial(jax.jit, static_argnames=("meta", "theta", "P"))
def _gravity_halo_needs(xs, ys, zs, ms, skeys, box, tree, meta,
                        theta: float, P: int, shifts=None):
    """(P-1,) per-DISTANCE gravity row needs: entry r-1 = max over
    shards j of the rows shard (j+r)%P needs from j (serve_sparse ships
    round r in a buffer of exactly this size) — the per-distance fold of
    ``gravity_need_matrix``, mirroring ``_sparse_halo_needs``."""
    need = gravity_need_matrix(xs, ys, zs, ms, skeys, box, tree, meta,
                               theta, P, shifts)
    j = jnp.arange(P, dtype=jnp.int32)
    return jnp.stack(
        [need[(j + r) % P, j].max() for r in range(1, P)]
    )  # (P-1,)


def device_gravity_halo(xs, ys, zs, ms, skeys, box, tree, meta,
                        theta: float, P: int, shifts=None,
                        margin: float = 1.4, quantum: int = 256,
                        ) -> Tuple[int, ...]:
    """Size the sparse gravity near-field exchange's static per-distance
    row caps (the hmax tuple compute_gravity's sparse shard path hands
    to exchange.serve_sparse). P-1 scalars to the host. A cap padded to
    S ships the full slab for that distance — the retry ceiling, where
    need <= S guarantees the escape sentinel cannot fire."""
    n = xs.shape[0]
    S = n // P
    per_r = np.asarray(fetch(_gravity_halo_needs(
        xs, ys, zs, ms, skeys, box, tree, meta, theta, P, shifts
    )))
    pad = lambda v: min(
        int(-(-int(max(int(v), 1) * margin) // quantum) * quantum), S
    )
    return tuple(pad(v) for v in per_r)


# ---------------------------------------------------------------------------
# distributed gravity-tree build (histogram pyramid + drill-down)
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("level",))
def key_histogram(keys, level: int):
    """Global cell-occupancy histogram at ``level`` over the (sharded) key
    array: the allreduce'd node-count vector of update_mpi.hpp:26-106.
    O(8^level) ints to the host, independent of N."""
    shift = KEY_DTYPE(3 * (KEY_BITS - level))
    cid = (keys >> shift).astype(jnp.int32)
    return jnp.zeros((1 << (3 * level),), jnp.int32).at[cid].add(1)


@functools.partial(jax.jit, static_argnames=("level", "sub", "k_cap"))
def drill_histogram(keys, cell_ids_sorted, level: int, sub: int, k_cap: int):
    """Counts of the 8^sub sub-cells of ``k_cap`` selected cells at
    ``level`` — the targeted refinement round for cells still above the
    bucket size (keys outside the selected cells fall in a discard bin).
    cell_ids_sorted: (k_cap,) int32 sorted cell indices, padded with 2^30.
    Returns (k_cap, 8^sub) int32."""
    nsub = 1 << (3 * sub)
    shift_hi = KEY_DTYPE(3 * (KEY_BITS - level))
    cid = (keys >> shift_hi).astype(jnp.int32)
    pos = jnp.searchsorted(cell_ids_sorted, cid).astype(jnp.int32)
    pos_c = jnp.clip(pos, 0, k_cap - 1)
    hit = cell_ids_sorted[pos_c] == cid
    shift_lo = KEY_DTYPE(3 * (KEY_BITS - level - sub))
    subid = ((keys >> shift_lo) & KEY_DTYPE(nsub - 1)).astype(jnp.int32)
    b = jnp.where(hit, pos_c * nsub + subid, k_cap * nsub)
    hist = jnp.zeros((k_cap * nsub + 1,), jnp.int32).at[b].add(1)
    return hist[: k_cap * nsub].reshape(k_cap, nsub)


def leaf_array_from_device_keys(
    keys_dev, bucket_size: int, base_level: int = 5, sub: int = 2,
    k_cap: int = 4096,
) -> np.ndarray:
    """Cornerstone leaf array (sorted start keys + KEY_MAX sentinel) built
    WITHOUT shipping the key array to the host.

    Top-down equivalent of compute_octree (csarray.hpp:456 invariant): a
    node splits while its count exceeds ``bucket_size`` (never creating a
    mergeable sibling set, so the result equals the converged rebalance,
    capped at the key resolution KEY_BITS). Counts come from one
    base-level histogram plus drill rounds over the overfull frontier.
    """
    base_level = min(base_level, KEY_BITS)
    hist = np.asarray(fetch(key_histogram(keys_dev, base_level)))
    # aggregate the pyramid upward (host, O(8^base) ints)
    pyramid = {base_level: hist.astype(np.int64)}
    for lvl in range(base_level - 1, -1, -1):
        pyramid[lvl] = pyramid[lvl + 1].reshape(-1, 8).sum(axis=1)

    leaves: list = []  # (cell_index, level)
    overfull = []      # frontier beyond the pyramid, all at base_level

    def split_through_pyramid(idx: int, lvl: int):
        stack = [(idx, lvl)]
        while stack:
            i, l = stack.pop()
            c = int(pyramid[l][i])
            if c <= bucket_size or l >= KEY_BITS:
                leaves.append((i, l))
            elif l < base_level:
                stack.extend((i * 8 + k, l + 1) for k in range(8))
            else:
                overfull.append(i)

    split_through_pyramid(0, 0)

    # drill rounds: refine every overfull cell ``sub`` levels at a time;
    # the fetched depth-``sub`` counts are aggregated back up so splitting
    # still happens one level at a time (a level+1 child under the bucket
    # must become ONE leaf, not 8 over-refined grandchildren)
    level = base_level
    pending = overfull
    while pending and level < KEY_BITS:
        step = min(sub, KEY_BITS - level)
        nsub = 1 << (3 * step)
        nxt = []
        for c0 in range(0, len(pending), k_cap):
            chunk = np.sort(np.asarray(pending[c0 : c0 + k_cap], np.int64))
            ids = np.full(k_cap, 2**30, np.int32)
            ids[: len(chunk)] = chunk.astype(np.int32)
            counts = np.asarray(
                fetch(drill_histogram(
                    keys_dev, jnp.asarray(ids), level, step, k_cap
                ))
            )
            for r, cell in enumerate(chunk):
                sums = [
                    counts[r].reshape(1 << (3 * d), -1).sum(axis=1)
                    for d in range(step + 1)
                ]
                stack = [(k, 1) for k in range(8)]  # cell is known overfull
                while stack:
                    i, d = stack.pop()
                    c = int(sums[d][i])
                    lvl = level + d
                    if c <= bucket_size or lvl >= KEY_BITS:
                        leaves.append((int(cell) * (1 << (3 * d)) + i, lvl))
                    elif d < step:
                        stack.extend((i * 8 + k, d + 1) for k in range(8))
                    else:
                        nxt.append(int(cell) * nsub + i)
        pending = nxt
        level += step

    key_of = lambda idx, lvl: np.uint64(idx) << np.uint64(3 * (KEY_BITS - lvl))
    starts = np.sort(np.asarray([key_of(i, l) for i, l in leaves], np.uint64))
    return np.concatenate([starts, [np.uint64(1) << np.uint64(3 * KEY_BITS)]])
