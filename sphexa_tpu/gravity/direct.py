"""O(N^2) direct-sum gravity, the accuracy reference for the tree solver.

Counterpart of ryoanji's directSum (ryoanji/src/ryoanji/nbody/direct.cuh):
all-pairs softened interactions, used only by tests and accuracy checks.
"""

import functools

import jax
import jax.numpy as jnp

from sphexa_tpu.gravity import multipole as mp


@functools.partial(jax.jit, static_argnames=("G",))
def direct_gravity(x, y, z, m, h, G: float = 1.0):
    """Returns (ax, ay, az, egrav) by summing every pair exactly.

    Uses the same h_i+h_j clamped softening as the tree P2P so the two
    solvers agree in the near field.
    """
    n = x.shape[0]
    block = min(n, 1024)
    num_blocks = -(-n // block)
    idx = jnp.minimum(
        jnp.arange(num_blocks * block, dtype=jnp.int32), n - 1
    ).reshape(num_blocks, block)

    def one_block(bi):
        mask = jnp.arange(n, dtype=jnp.int32)[None, :] != bi[:, None]
        return mp.p2p(x[bi], y[bi], z[bi], h[bi], x, y, z, m, h, mask)

    ax, ay, az, phi = jax.lax.map(one_block, idx)
    ax = ax.reshape(-1)[:n] * G
    ay = ay.reshape(-1)[:n] * G
    az = az.reshape(-1)[:n] * G
    phi = phi.reshape(-1)[:n] * G
    egrav = 0.5 * jnp.sum(m * phi)
    return ax, ay, az, egrav
