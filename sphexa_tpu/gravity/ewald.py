"""Ewald summation for self-gravity in periodic boxes.

TPU-native counterpart of the reference's
``ryoanji/src/ryoanji/nbody/traversal_ewald_cpu.hpp`` (computeGravityEwald):
the total periodic force is

  near field   : Barnes-Hut forces summed over +-num_replica_shells box
                 replicas (tree of the base box, shifted targets);
  real space   : per-particle correction from the ROOT multipole over
                 +-num_ewald_shells replicas, erfc-screened (erf-subtracted
                 inside the region the near field already covered);
  k space      : the smooth long-range remainder as a Fourier sum with
                 root-multipole-weighted coefficients.

The reference evaluates both corrections per particle in scalar loops; here
the shell/hvec tables are static (N, S)/(N, H) broadcasts, and the k-space
sum is a pair of cos/sin matmuls. Requires a cubic box (same restriction
as the reference, traversal_ewald_cpu.hpp:366).
"""

import dataclasses
import functools
from itertools import product
from typing import Dict, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax.scipy.special import erf, erfc

from sphexa_tpu.gravity.traversal import (
    GravityConfig,
    compute_gravity,
    compute_multipoles,
)
from sphexa_tpu.gravity.tree import GravityTree, GravityTreeMeta
from sphexa_tpu.sfc.box import Box


@dataclasses.dataclass(frozen=True)
class EwaldConfig:
    """Static Ewald parameters (ewaldInitParameters recommended values)."""

    num_replica_shells: int = 1
    lcut: float = 2.6
    hcut: float = 2.8
    alpha_scale: float = 2.0
    small_r_factor: float = 3.0e-3  # Gasoline value (traversal_ewald_cpu.hpp:147)

    @property
    def num_ewald_shells(self) -> int:
        return max(int(np.ceil(self.lcut)), self.num_replica_shells)


def _real_space_shells(cfg: EwaldConfig):
    """Static shell table: integer offsets (S, 3) + in-near-field flags."""
    s = cfg.num_ewald_shells
    r = cfg.num_replica_shells
    shells, in_near = [], []
    for ix, iy, iz in product(range(-s, s + 1), repeat=3):
        shells.append((ix, iy, iz))
        in_near.append(abs(ix) <= r and abs(iy) <= r and abs(iz) <= r)
    return np.asarray(shells, np.float32), np.asarray(in_near)


def _k_space_hvecs(cfg: EwaldConfig):
    """Static h-vector table (H, 3): 0 < |h| <= hcut."""
    reps = int(np.ceil(cfg.hcut))
    hvecs = [
        (hx, hy, hz)
        for hx, hy, hz in product(range(-reps, reps + 1), repeat=3)
        if 0 < hx * hx + hy * hy + hz * hz <= cfg.hcut**2
    ]
    return np.asarray(hvecs, np.float32)


def _eval_root_multipole(r, gamma, mass, q):
    """Potential + acceleration of the root expansion at offsets ``r``.

    Vectorized ewaldEvalMultipoleComplete (traversal_ewald_cpu.hpp:89-111):
    ``r`` (..., 3), ``gamma`` (..., 6), root monopole ``mass`` and
    trace-free quadrupole ``q`` (7,). Returns (u, a) with a (..., 3).
    """
    qxx = (q[0] + q[6]) / 3.0
    qyy = (q[3] + q[6]) / 3.0
    qzz = (q[5] + q[6]) / 3.0
    qxy, qxz, qyz = q[1] / 3.0, q[2] / 3.0, q[4] / 3.0

    rx, ry, rz = r[..., 0], r[..., 1], r[..., 2]
    qr = jnp.stack(
        [rx * qxx + ry * qxy + rz * qxz,
         rx * qxy + ry * qyy + rz * qyz,
         rx * qxz + ry * qyz + rz * qzz],
        axis=-1,
    )
    rqr = 0.5 * jnp.sum(r * qr, axis=-1)
    qtr = 0.5 * q[6]

    g0, g1, g2, g3 = gamma[..., 0], gamma[..., 1], gamma[..., 2], gamma[..., 3]
    u = -g0 * mass + g1 * qtr - g2 * rqr
    a = g2[..., None] * qr - r * (g1 * mass - g2 * qtr + g3 * rqr)[..., None]
    return u, a


def _real_space_correction(dr, mass, q, L, cfg: EwaldConfig):
    """Real-space Ewald sum over shells for particle offsets ``dr`` (N, 3).

    Gamma recurrences per traversal_ewald_cpu.hpp:199-297: erfc screening
    outside the near-field region, -erf subtraction inside it (the near
    field computed those shells exactly), Taylor series near R = 0.
    """
    shells, in_near = _real_space_shells(cfg)
    alpha = cfg.alpha_scale / L
    alpha2 = alpha * alpha
    ka = 2.0 * alpha / jnp.sqrt(jnp.pi)
    lcut2 = cfg.lcut**2 * L * L
    small_r2 = cfg.small_r_factor * L * L
    k1 = jnp.pi / (alpha2 * L**3)

    R = dr[:, None, :] + jnp.asarray(shells)[None, :, :] * L  # (N, S, 3)
    r2 = jnp.sum(R * R, axis=-1)
    in_near_j = jnp.asarray(in_near)[None, :]

    # shell selection: everything inside lcut, plus all near-field shells
    active = (r2 <= lcut2) | in_near_j

    # regular branch
    rmag = jnp.sqrt(jnp.maximum(r2, 1e-30))
    inv_r = 1.0 / rmag
    inv_r2 = inv_r * inv_r
    a_term = jnp.exp(-r2 * alpha2) * ka * inv_r2
    fn = jnp.where(in_near_j, -erf(alpha * rmag), erfc(alpha * rmag))
    g = [None] * 6
    g[0] = fn * inv_r
    g[1] = g[0] * inv_r2 + a_term
    alphan = 2 * alpha2
    g[2] = 3 * g[1] * inv_r2 + alphan * a_term
    alphan = alphan * 2 * alpha2
    g[3] = 5 * g[2] * inv_r2 + alphan * a_term
    alphan = alphan * 2 * alpha2
    g[4] = 7 * g[3] * inv_r2 + alphan * a_term
    alphan = alphan * 2 * alpha2
    g[5] = 9 * g[4] * inv_r2 + alphan * a_term
    gamma_reg = jnp.stack(g, axis=-1)  # (N, S, 6)

    # small-R series branch (cancellation-safe near the origin)
    r2a2 = r2 * alpha2
    cs = [None] * 6
    c0 = ka
    cs[0] = c0 * (r2a2 / 3.0 - 1.0)
    for i, (num, den) in enumerate(
        [(5.0, 3.0), (7.0, 5.0), (9.0, 7.0), (11.0, 9.0), (13.0, 11.0)], start=1
    ):
        c0 = c0 * 2 * alpha2
        cs[i] = c0 * (r2a2 / num - 1.0 / den)
    gamma_small = jnp.stack(cs, axis=-1)

    gamma = jnp.where((r2 < small_r2)[..., None], gamma_small, gamma_reg)
    gamma = jnp.where(active[..., None], gamma, 0.0)

    u, a = _eval_root_multipole(R, gamma, mass, q)
    # background term k1*M (compensates the mean density, :215)
    u_tot = jnp.sum(u, axis=1) + k1 * mass
    return u_tot, jnp.sum(a, axis=1)


def _k_space_correction(dr, mass, q, L, cfg: EwaldConfig):
    """Fourier-space Ewald sum (computeEwaldKSpace + hsum coefficients)."""
    hvecs = jnp.asarray(_k_space_hvecs(cfg))  # (H, 3)
    alpha = cfg.alpha_scale / L
    k4 = jnp.pi**2 / (alpha**2 * L**2)
    h2 = jnp.sum(hvecs * hvecs, axis=1)

    g0 = jnp.exp(-k4 * h2) / (jnp.pi * h2 * L)
    g1 = 2 * jnp.pi / L * g0
    g2 = -2 * jnp.pi / L * g1
    g3 = 2 * jnp.pi / L * g2
    g4 = -2 * jnp.pi / L * g3
    g5 = 2 * jnp.pi / L * g4
    zero = jnp.zeros_like(g0)
    # cos coefficients use even gammas, sin the odd ones (hsum build, :176)
    gamma_cos = jnp.stack([g0, zero, g2, zero, g4, zero], axis=-1)
    gamma_sin = jnp.stack([zero, g1, zero, g3, zero, g5], axis=-1)
    hfac_cos, _ = _eval_root_multipole(hvecs, gamma_cos, mass, q)
    hfac_sin, _ = _eval_root_multipole(hvecs, gamma_sin, mass, q)

    hr_scaled = 2 * jnp.pi / L * hvecs  # (H, 3)
    hdotx = dr @ hr_scaled.T  # (N, H)
    c, s = jnp.cos(hdotx), jnp.sin(hdotx)
    u = -(c @ hfac_cos + s @ hfac_sin)
    # acc = sum_h (hfac_cos * s - hfac_sin * c) * hr_scaled (:316)
    a = (s * hfac_cos[None, :] - c * hfac_sin[None, :]) @ hr_scaled
    return u, a


@functools.partial(jax.jit, static_argnames=("meta", "cfg", "ecfg", "shard"))
def compute_gravity_ewald(
    x, y, z, m, h, sorted_keys, box: Box,
    tree: GravityTree, meta: GravityTreeMeta, cfg: GravityConfig,
    ecfg: EwaldConfig, shard=None,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array, Dict[str, jax.Array]]:
    """Periodic-box gravity: replica near field + Ewald corrections.

    Same return contract as compute_gravity. The near field runs one
    Barnes-Hut pass per replica shell offset ((2r+1)^3 passes, each a
    static jit region), matching computeGravityEwald's use of
    computeGravity(..., numReplicaShells).

    ``shard``: (axis, P, win) when running INSIDE shard_map on a local
    slab (same contract as compute_gravity): the upsweep is the psum
    leaf-payload allreduce, each replica-shell near field rides the
    halo exchange (windowed for an int ``win``; MAC-sized sparse for a
    per-distance cap tuple — the sizing unions the opened set over the
    replica shifts, so wrap-around leaves any shifted target reaches
    are covered), and the per-particle real/k-space corrections are
    row-local (the root expansion is replicated by the psum). egrav and
    diagnostics return per-shard.
    """
    L = box.lengths[0]
    n = x.shape[0]
    r = ecfg.num_replica_shells

    if cfg.multipole_order > 0:
        raise NotImplementedError(
            "spherical multipoles are open-boundary only; the Ewald path "
            "keeps the cartesian quadrupole (traversal_ewald_cpu.hpp parity)"
        )
    if shard is not None:
        from sphexa_tpu.gravity.traversal import compute_multipoles_sharded

        mp_cache = compute_multipoles_sharded(
            x, y, z, m, sorted_keys, tree, meta, shard[0]
        )
    else:
        mp_cache = compute_multipoles(x, y, z, m, sorted_keys, tree, meta)
    node_mass, node_com, node_q, _ = mp_cache

    # replica near field: ONE traced traversal scanned over the static
    # (2r+1)^3 shift table (shift/allow_self are traced, so XLA compiles a
    # single traversal body instead of 27 inlined copies)
    shells = np.array(
        [s for s in product(range(-r, r + 1), repeat=3)], np.float32
    )
    is_base = jnp.asarray(~np.any(shells != 0, axis=1))
    shifts = jnp.asarray(shells) * L
    cfg1 = dataclasses.replace(cfg, G=1.0)

    def body(carry, inp):
        ax, ay, az, phi, dmax = carry
        shift, base = inp
        dax, day, daz, dphi, d = compute_gravity(
            x, y, z, m, h, sorted_keys, box, tree, meta, cfg1,
            shift=shift, allow_self=~base, with_phi=True, mp_cache=mp_cache,
            shard=shard,
        )
        dmax = {k: jnp.maximum(dmax[k], d[k]) for k in dmax}
        return (ax + dax, ay + day, az + daz, phi + dphi, dmax), None

    zeros = jnp.zeros(n, x.dtype)
    diag0 = {
        "m2p_max": jnp.int32(0), "p2p_max": jnp.int32(0),
        "leaf_occ": jnp.int32(0),
        # the superblock / LET candidate high-waters must survive the
        # replica scan or the Simulation's cap overflow guards cannot fire
        "c_max": jnp.int32(0),
        "let_max": jnp.int32(0),
        "compact_width": jnp.int32(0),
    }
    if shard is not None and isinstance(shard[2], tuple):
        # sparse MAC-window mode: carry the per-shell exchange telemetry
        # through the scan (max fold — the worst shell sizes the caps);
        # keys absent from diag0 are dropped by the fold above, so these
        # exist exactly when compute_gravity emits them
        diag0["halo_rows"] = jnp.int32(0)
        diag0["halo_occ"] = jnp.float32(0)
    (ax, ay, az, phi, diag), _ = jax.lax.scan(
        body, (zeros, zeros, zeros, zeros, diag0), (shifts, is_base)
    )

    root_m = node_mass[0]
    root_q = node_q[0]
    dr = jnp.stack([x, y, z], axis=1) - node_com[0][None, :]

    u_r, a_r = _real_space_correction(dr, root_m, root_q, L, ecfg)
    u_k, a_k = _k_space_correction(dr, root_m, root_q, L, ecfg)

    ax = (ax + a_r[:, 0] + a_k[:, 0]) * cfg.G
    ay = (ay + a_r[:, 1] + a_k[:, 1]) * cfg.G
    az = (az + a_r[:, 2] + a_k[:, 2]) * cfg.G
    phi = (phi + u_r + u_k) * cfg.G
    egrav = 0.5 * jnp.sum(m * phi)
    return ax, ay, az, egrav, diag
