"""Traversal-free Barnes-Hut gravity: batched MAC + fixed-cap interaction lists.

TPU-native re-design of ryoanji's warp-centric dual traversal
(ryoanji/src/ryoanji/nbody/traversal.cuh:60-79 TravConfig,
traversal_cpu.hpp:84 computeGravityGroup). Instead of a stack/ring-buffer
walk, every target group evaluates the vector MAC against *all* tree nodes
at once (the node array is small, ~N/bucket), then classifies each node by
the classic first-accepted-ancestor rule:

- M2P set: node passes the MAC and no ancestor passed it;
- P2P set: node is a leaf, and neither it nor any ancestor passed.

The ancestor predicate is a level-by-level downsweep (gather from parent),
and the sparse sets are compacted into fixed-cap index lists via a stable
argsort — overflow is reported as a diagnostic, standing in for the
reference's traversal stack-overflow detection (gravity_wrapper.hpp:120).

Target groups are fixed blocks of SFC-consecutive particles (the analog of
TravConfig's 64-particle targets), so all shapes are static. Work is
chunked with lax.map (sequential) over groups of blocks, with vmap inside,
to bound transient memory.

Softening/energy conventions follow the reference exactly: P2P clamps the
distance to h_i+h_j (kernel.hpp:515), egrav = 0.5*G*sum(m_i*phi_i)
(traversal_cpu.hpp:231).
"""

import dataclasses
import functools
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from sphexa_tpu.gravity import multipole as mp
from sphexa_tpu.gravity.tree import GravityTree, GravityTreeMeta
from sphexa_tpu.sfc.box import Box
from sphexa_tpu.util.phases import named_phase, phase_scope


@dataclasses.dataclass(frozen=True)
class GravityConfig:
    """Static gravity-solver configuration (hashable, jit-safe)."""

    theta: float = 0.5  # opening angle; accept if dist > 2*size/theta + com offset
    bucket_size: int = 64  # leaf capacity target for the gravity tree build
    target_block: int = 64  # particles per MAC target group (TravConfig analog)
    blocks_per_chunk: int = 32  # target groups processed per lax.map step
    m2p_cap: int = 512  # max accepted multipoles per target group
    p2p_cap: int = 48  # max near-field leaves per target group
    leaf_cap: int = 128  # max particles gathered per near-field leaf
    G: float = 1.0
    # multipole expansion order: 0 = cartesian quadrupole (the default
    # fast path, multipole.py); P >= 2 selects spherical multipoles with
    # P retained orders (gravity/spherical.py — the reference's EXAFMM
    # accuracy knob, kernel.hpp). Open-boundary solves only.
    multipole_order: int = 0
    # hierarchical MAC: blocks per SUPERBLOCK for the two-level
    # classification (0 = dense blocks x nodes sweep). The superblock
    # pre-pass keeps only its ancestor-closed open set + accepted cut
    # (<= super_cap nodes), and each block classifies against THAT list
    # instead of the whole tree — MAC work proportional to the accepted
    # region (VERDICT r2 #4a). MEASURED (Evrard 50^3, 3425 nodes, v5e):
    # the open set is ~60% of this small tree, so the pre-pass overhead
    # LOSES (457 vs 281 ms solve) — default 0; enable at large trees
    # (>= ~1e5 nodes) where C << num_n makes the refinement pay.
    super_factor: int = 0
    super_cap: int = 1024
    # LET analog (focused-octree role, octree_focus_mpi.hpp:50-698): on
    # SHARDED solves, classify each shard's blocks against the shard's
    # ESSENTIAL node set — the ancestor-closed open set + accepted cut
    # of the slab bbox — instead of the full replicated tree. Remote
    # regions appear only as their MAC-coarsened cut, so per-shard MAC
    # work and list sorts scale with the slab's essential tree
    # (O((N/P)^(2/3) + cut)), not num_nodes. 0 = off; sized by
    # estimate_gravity_caps(let_shards=P). The slab bbox is recomputed
    # every solve from the live positions, so the set is never stale.
    let_cap: int = 0
    # near-field engine: stream the P2P leaf ranges through the pallas
    # pair engine (sph/pallas_pairs.py) instead of XLA gathers — the
    # dominant cost of the XLA formulation at 1e5+ particles. Set by the
    # Simulation from the step backend (TPU only; CPU tests keep XLA).
    use_pallas: bool = False
    # interaction-list compaction mode. "sort": the per-block packed
    # 3-class sort (the 214 ms classification floor at 1M — every sort
    # VARIANT measured identical, docs/NEXT.md round 5). "bitmask": the
    # Mosaic bitmask+popcount-rank kernel (gravity/pallas_compact.py)
    # materializes both fixed-cap lists with no argsort anywhere on the
    # per-block path, and the first-accepted-ancestor test re-evaluates
    # the MAC on the PARENT's own arrays instead of gathering the block's
    # accept vector — exact-equivalent lists (pinned by
    # tests/test_gravity.py), and the shape the hierarchical superblock
    # path needs to pay. The dense sort stays selectable everywhere.
    compaction: str = "sort"
    # m2p cap sizing margin: M2P eval cost is linear in m2p_cap, and the
    # generic 1.5-1.6 sizing margin left ~35 ms of eval slack at 1M
    # (docs/NEXT.md round 5). Applied by estimate_gravity_caps to the m2p
    # cap only; overflow is guarded by the m2p_max diagnostic exactly
    # like let_max (Simulation regrows the margin and re-sizes on
    # overflow, so a too-tight cap costs a retry, never dropped nodes).
    m2p_cap_margin: float = 1.3


def gravity_tuning(n: int, use_pallas: bool, telemetry=None) -> dict:
    """Scale-dependent gravity-solver shape, shared by
    Simulation._configure_gravity and bench.py so the benchmarked config
    IS the production config.

    Coarser classification blocks amortize the MAC sweep at large N
    (measured 1.86x at 1M Plummer: tb=256 975 ms vs tb=64 1810 ms,
    scripts/bench_gravity_scale.py); the hierarchical bitmask compaction
    pays only where num_nodes >> super_cap (>= ~1e5-node trees) AND the
    Mosaic kernel compiles (TPU backend — interpret mode is for tests).
    super_factor=8 is the sampled-width optimum at both 1M and 4M
    Plummer (sf sweep in docs/NEXT.md round 6: the candidate cut GROWS
    with the superblock bbox, so small supers win; the pre-pass is <20%
    of the block-stage slots at sf=8).
    """
    big = n >= 500_000
    if telemetry is not None and 450_000 <= n <= 550_000:
        # the silent cliff: these knobs are a step function of N, and a
        # run sitting within 10% of the threshold can flip the whole
        # solver shape (and recompile) on a small particle-count change.
        # Near the edge, say so — a first-class ``tuning`` event (and a
        # ConsoleSink-notable line) instead of a mysterious retrace
        telemetry.event(
            "tuning", source="heuristic", note="near-threshold",
            n=int(n), threshold=500_000, big=bool(big),
        )
    return {
        "target_block": 256 if big else 64,
        "blocks_per_chunk": 8 if big else 32,
        "super_factor": 8 if (big and use_pallas) else 0,
        "compaction": "bitmask" if (big and use_pallas) else "sort",
        "use_pallas": use_pallas,
    }


@functools.partial(jax.jit, static_argnames=("blk",))
def _block_bboxes(x, y, z, blk: int):
    """Per-target-block bounding boxes, (nb, 3) min / (nb, 3) max — the
    only per-particle quantity the cap estimator needs (tail block padded
    with the last row, which only shrinks nothing)."""
    n = x.shape[0]
    nb = -(-n // blk)
    pad = nb * blk - n

    def blocked(a):
        if pad:
            a = jnp.concatenate([a, jnp.broadcast_to(a[-1:], (pad,))])
        return a.reshape(nb, blk)

    xs, ys, zs = blocked(x), blocked(y), blocked(z)
    bmin = jnp.stack([xs.min(1), ys.min(1), zs.min(1)], axis=1)
    bmax = jnp.stack([xs.max(1), ys.max(1), zs.max(1)], axis=1)
    return bmin, bmax


def estimate_gravity_caps(
    x, y, z, m, sorted_keys, box: Box,
    tree: GravityTree, meta: GravityTreeMeta, cfg: GravityConfig,
    sample_blocks: int = 256, margin: float = 1.5, quantum: int = 32,
    let_shards: int = 0,
) -> GravityConfig:
    """Size the interaction-list caps from the current distribution.

    Host-side helper run at (re)configuration time, the gravity analog of
    estimate_cell_cap: simulate the MAC classification for a sample of
    target blocks in numpy and pad the observed maxima. The caps are upper
    bounds by sampling only — the overflow diagnostics returned by
    compute_gravity remain the correctness guard.
    """
    node_mass, node_com, node_q, edges = compute_multipoles(
        x, y, z, m, sorted_keys, tree, meta
    )
    # everything fetched is O(tree) or O(N/target_block) — never the
    # particle arrays themselves (the O(N/P) reconfiguration contract,
    # VERDICT r3 #3); per-block bboxes come from one jitted reduction
    from sphexa_tpu.parallel.sizing import fetch

    n = x.shape[0]
    blk = cfg.target_block
    nb = -(-n // blk)
    # ONE batched device->host transfer: on remote-attached TPUs each
    # fetch pays a full dispatch+sync round trip (the same reason
    # Simulation._fetch_scalars batches)
    (nm, com, edges, parent, is_leaf, lengths, lo, center_frac,
     halfsize_frac, (bmin, bmax)) = (
        np.asarray(a) if not isinstance(a, tuple) else a
        for a in fetch((
            node_mass, node_com, edges, tree.parent, tree.is_leaf,
            box.lengths, jnp.stack([box.lo[0], box.lo[1], box.lo[2]]),
            tree.center_frac, tree.halfsize_frac,
            _block_bboxes(x, y, z, blk),
        ))
    )
    bmin, bmax = np.asarray(bmin), np.asarray(bmax)
    valid = nm > 0.0
    counts = np.diff(edges)

    lo = np.asarray(lo, dtype=np.float64)
    geo_center = lo[None, :] + np.asarray(center_frac) * lengths[None, :]
    geo_size = np.asarray(halfsize_frac)[:, None] * lengths[None, :]
    l_node = 2.0 * geo_size.max(axis=1)
    s_off = np.linalg.norm(com - geo_center, axis=1)
    # monotone MAC radius + subtree com box — MUST match
    # compute_gravity's upsweeps or the sampled caps drift from the
    # real classification
    smax = np.where(valid, s_off, 0.0)
    BIG = 1e15  # squares stay finite in f32
    com_lo = np.where(valid[:, None], com, BIG)
    com_hi = np.where(valid[:, None], com, -BIG)
    for s, e in reversed(meta.level_ranges[1:]):
        np.maximum.at(smax, parent[s:e], smax[s:e])
        np.minimum.at(com_lo, parent[s:e], com_lo[s:e])
        np.maximum.at(com_hi, parent[s:e], com_hi[s:e])
    ccenter = np.where(valid[:, None], 0.5 * (com_lo + com_hi), BIG)
    chalf = np.where(valid[:, None],
                     np.maximum(0.5 * (com_hi - com_lo), 0.0), 0.0)
    mac2 = (l_node / cfg.theta + smax) ** 2
    self_parent = parent == np.arange(meta.num_nodes)

    rng = np.random.default_rng(0)
    blocks = (
        np.arange(nb)
        if nb <= sample_blocks
        else np.unique(np.concatenate([[0, nb - 1], rng.integers(0, nb, sample_blocks)]))
    )

    def classify(b0, b1):
        pmin = bmin[b0:b1].min(axis=0)
        pmax = bmax[b0:b1].max(axis=0)
        bc, bs = (pmax + pmin) / 2, (pmax - pmin) / 2
        d = np.maximum(
            np.abs(bc[None, :] - ccenter) - bs[None, :] - chalf, 0.0
        )
        accept = valid & ~((d * d).sum(axis=1) < mac2)
        # monotone MAC: accepted strict ancestor == accepted parent
        anc = np.where(self_parent, False, accept[parent])
        return accept, anc

    m2p_max, p2p_max = 1, 1
    for b in blocks:
        accept, anc = classify(b, b + 1)
        m2p_max = max(m2p_max, int((accept & ~anc).sum()))
        p2p_max = max(p2p_max, int((is_leaf & valid & ~accept).sum()))

    # superblock candidate-list high water (the hierarchical MAC's cap):
    # ~anc = open set + accepted cut of the super bbox
    c_cap_max = 1
    if cfg.super_factor > 0:
        sblk = cfg.super_factor * blk
        nsb = -(-n // sblk)
        supers = (
            np.arange(nsb)
            if nsb <= sample_blocks
            else np.unique(np.concatenate(
                [[0, nsb - 1], rng.integers(0, nsb, sample_blocks)]
            ))
        )
        for b in supers:
            _, anc = classify(b * cfg.super_factor,
                              min((b + 1) * cfg.super_factor, nb))
            c_cap_max = max(c_cap_max, int((~anc).sum()))

    # per-SHARD essential-set high water (the LET cap): ~anc of the
    # slab bbox — each shard's blocks span a contiguous block range
    let_max = 0
    if let_shards > 1:
        for k in range(let_shards):
            b0 = k * nb // let_shards
            b1 = max(b0 + 1, (k + 1) * nb // let_shards)
            _, anc = classify(b0, min(b1, nb))
            let_max = max(let_max, int((~anc).sum()))

    def pad(v, mg=margin):
        return int(np.ceil(v * mg / quantum) * quantum)

    leaf_cap = pad(int(counts.max()) if len(counts) else 1)
    # the m2p cap gets its own (tighter) margin — M2P eval cost is linear
    # in the cap, and the sampled maximum is exact whenever all blocks are
    # sampled. Scaled by margin/1.5 so the driver's overflow-retry margin
    # growth still reaches any true high water.
    m2p_margin = cfg.m2p_cap_margin * margin / 1.5
    return dataclasses.replace(
        cfg,
        m2p_cap=min(pad(m2p_max, m2p_margin), meta.num_nodes),
        p2p_cap=min(pad(p2p_max), meta.num_leaves),
        leaf_cap=leaf_cap,
        # only re-size when the hierarchical path is on: clobbering the
        # configured value for sf=0 would sabotage a later enable
        super_cap=(
            min(pad(c_cap_max), meta.num_nodes)
            if cfg.super_factor > 0 else cfg.super_cap
        ),
        let_cap=(
            min(pad(let_max), meta.num_nodes)
            if let_shards > 1 else cfg.let_cap
        ),
    )


@functools.partial(jax.jit, static_argnames=("meta", "order"))
@named_phase("gravity-upsweep")
def compute_multipoles(
    x, y, z, m, sorted_keys, tree: GravityTree, meta: GravityTreeMeta,
    order: int = 0,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Masses, centers of mass and multipoles for every tree node.

    Device-side counterpart of computeLeafMultipoles + upsweepMultipoles
    (ryoanji/nbody/upsweep_cpu.hpp:26-92): leaf payload via segment sums
    over the particle->leaf assignment, then a level-by-level scatter-add
    upsweep with the M2M expansion-center shift.

    Returns (node_mass (N,), node_com (N,3), node_q, edges (L+1,)) with
    node_q (N, 7) real (cartesian quadrupole, order=0) or (N, ncoef(P))
    complex (spherical order-P coefficients).
    """
    lk = tree.leaf_keys
    num_l, num_n = meta.num_leaves, meta.num_nodes
    n = x.shape[0]
    edges = jnp.searchsorted(sorted_keys, lk, side="left").astype(jnp.int32)
    # particle -> leaf index WITHOUT the N-query u64 searchsorted (emulated
    # u64 compares x log2(L) gathers measured ~150 ms at 1M): leaf rows are
    # contiguous, so pleaf = (#leaf starts <= row) - 1 — one O(L) scatter
    # + O(N) cumsum over int32 rows
    pleaf = _pleaf_from_edges(edges, n)

    # pass 1: monopole + center of mass, leaves then upsweep. Processing
    # levels deepest-first means a node's own subtree sum is complete by the
    # time it is added to its parent. Leaf rows are contiguous in the
    # sorted arrays, so the leaf sums are cumsum differences at the leaf
    # edges (mp.edge_segment_sum) — not TPU-serializing scatter-adds.
    w = jnp.stack([m, m * x, m * y, m * z], axis=1)  # (n, 4)
    leaf_w = mp.edge_segment_sum(w, edges)  # (L, 4)
    node_mass, node_com = _upsweep_mass_com(leaf_w, tree, meta)

    if order > 0:
        from sphexa_tpu.gravity import spherical as sp

        leaf_com = node_com[tree.node_of_leaf]
        leaf_c = sp.p2m(x, y, z, m, leaf_com, edges, order, pleaf=pleaf)
        node_q = sp.upsweep(leaf_c, node_com, tree, meta,
                            tree.node_of_leaf, order)
        return node_mass, node_com, node_q, edges

    # pass 2: leaf quadrupoles around the leaf com, then M2M upsweep with
    # the expansion-center shift to the parent com
    leaf_com = node_com[tree.node_of_leaf]
    leaf_q = mp.p2m_leaf(x, y, z, m, pleaf, leaf_com, num_l,
                         edges=edges)  # (L, 7)
    node_q = _upsweep_quadrupoles(leaf_q, node_mass, node_com, tree, meta)
    return node_mass, node_com, node_q, edges


def _pleaf_from_edges(edges, n: int):
    """(n,) particle->leaf map from the (L+1 or L,) sorted leaf start
    rows: cumsum of a start-row indicator. Empty leaves (duplicate
    edges) advance the count twice and simply never appear."""
    mark = jnp.zeros(n + 1, jnp.int32).at[edges].add(1)
    return jnp.cumsum(mark)[:n] - 1


def _upsweep_mass_com(leaf_w, tree, meta):
    """Shared monopole/center-of-mass upsweep from (L, 4) leaf payloads
    (single-device and distributed callers MUST use the same loops so
    their multipoles cannot diverge)."""
    num_n = meta.num_nodes
    node_w = jnp.zeros((num_n, 4), leaf_w.dtype).at[tree.node_of_leaf].set(leaf_w)
    for s, e in reversed(meta.level_ranges[1:]):
        # parent rows are non-decreasing inside a level range (children
        # of one parent are contiguous in the level-ordered layout), so
        # the duplicate-index accumulation has a fixed segment order —
        # the JXA401 bitwise-replay contract depends on this hint
        node_w = node_w.at[tree.parent[s:e]].add(node_w[s:e],
                                                 indices_are_sorted=True)
    node_mass = node_w[:, 0]
    node_com = node_w[:, 1:4] / jnp.maximum(node_mass, 1e-30)[:, None]
    return node_mass, node_com


def _upsweep_quadrupoles(leaf_q, node_mass, node_com, tree, meta):
    """Shared M2M quadrupole upsweep from (L, 7) leaf payloads."""
    num_n = meta.num_nodes
    node_q = jnp.zeros((num_n, 7), leaf_q.dtype).at[tree.node_of_leaf].set(leaf_q)
    for s, e in reversed(meta.level_ranges[1:]):
        par = tree.parent[s:e]
        d = node_com[par] - node_com[s:e]
        # sorted parent rows, as in _upsweep_mass_com (JXA401)
        node_q = node_q.at[par].add(mp.m2m_shift(node_q[s:e], node_mass[s:e], d),
                                    indices_are_sorted=True)
    return node_q


@named_phase("gravity-upsweep")
def compute_multipoles_sharded(
    x, y, z, m, local_keys, tree: GravityTree, meta: GravityTreeMeta,
    axis: str, order: int = 0,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Distributed multipole upsweep under shard_map — the
    global_multipole.hpp:44-73 allreduce analog.

    Each shard contributes the PARTIAL leaf sums of its slab rows (leaf
    row ranges clipped to the slab; leaves are key ranges, so membership
    needs only the local keys), one psum replicates the (L, k) leaf
    payloads, and the level-by-level M2M upsweep runs replicated on the
    (small) tree. Comm is O(tree), never O(N) — no particle gather.
    Returns the compute_multipoles contract (cartesian quadrupole at
    order=0, spherical order-P complex coefficients otherwise — the
    psum runs on the complex leaf payloads) with GLOBAL row edges.
    """
    lk = tree.leaf_keys
    num_l, num_n = meta.num_leaves, meta.num_nodes
    S = x.shape[0]
    k = jax.lax.axis_index(axis)
    pos_local = jnp.searchsorted(local_keys, lk, side="left").astype(jnp.int32)
    # jaxlint: disable=JXL006 -- data-chained upsweep: every later psum
    # consumes the previous psum's result (edges -> leaf_w -> leaf_q/c),
    # so program order is already total (JXA201 proves it on the jaxpr)
    edges = jax.lax.psum(pos_local, axis)  # global leaf boundary rows
    e_clip = jnp.clip(edges - k * S, 0, S)
    # local-row particle->leaf map: leaves starting before the slab clip
    # to 0 (counted for every local row), after it to S (never counted) —
    # same contiguous-rows identity as the single-device path
    pleaf = _pleaf_from_edges(e_clip, S)

    w = jnp.stack([m, m * x, m * y, m * z], axis=1)
    # jaxlint: disable=JXL006 -- data-chained on edges (via e_clip)
    leaf_w = jax.lax.psum(mp.edge_segment_sum(w, e_clip), axis)  # (L, 4)
    node_mass, node_com = _upsweep_mass_com(leaf_w, tree, meta)

    leaf_com = node_com[tree.node_of_leaf]
    if order > 0:
        from sphexa_tpu.gravity import spherical as sp

        # jaxlint: disable=JXL006 -- data-chained on leaf_w (via leaf_com)
        leaf_c = jax.lax.psum(
            sp.p2m(x, y, z, m, leaf_com, e_clip, order, pleaf=pleaf), axis
        )
        node_q = sp.upsweep(leaf_c, node_com, tree, meta,
                            tree.node_of_leaf, order)
        return node_mass, node_com, node_q, edges
    # jaxlint: disable=JXL006 -- data-chained on leaf_w (via leaf_com)
    leaf_q = jax.lax.psum(
        mp.p2m_leaf(x, y, z, m, pleaf, leaf_com, num_l, edges=e_clip), axis
    )
    node_q = _upsweep_quadrupoles(leaf_q, node_mass, node_com, tree, meta)
    return node_mass, node_com, node_q, edges


@named_phase("gravity-p2p")
def _pallas_p2p(x, y, z, m, h, shift, allow_self, cfg: GravityConfig,
                starts, lens, jdata=None, i_offset=0):
    """Near-field P2P through the streamed pair engine.

    ``starts``/``lens`` are the per-block near-leaf ranges from the MAC
    classification, (NB, p2p_cap) in GLOBAL sorted-array offsets. Leaf
    ranges are contiguous, so adjacent ones merge into long DMA runs —
    with gap=0 ONLY: a bridged gap would stream particles of leaves whose
    mass already arrives via M2P (no distance cutoff masks them away),
    double-counting. Returns (ax, ay, az, phi), each (NB*block,).

    Under shard_map, ``jdata = (x, y, z, m, h)`` supplies the j-side
    candidate arrays (slab + halo annex) the (pre-localized) ranges
    index into, and ``i_offset`` places the local targets in that index
    space — the same contract as the SPH engine ops.
    """
    from sphexa_tpu.neighbors.cell_list import NeighborConfig
    from sphexa_tpu.sph import pallas_pairs as pp

    nb = starts.shape[0]
    blk = cfg.target_block
    nbr = NeighborConfig(
        level=1, cap=cfg.leaf_cap, group=blk,
        run_cap=max(cfg.leaf_cap, 1024), gap=0,
    )
    zero3 = jnp.zeros(starts.shape + (3,), jnp.float32)
    rs, rl, sh3, nruns = pp._merge_runs(
        starts, lens, lens > 0, zero3, nbr.run_cap, 0
    )
    ranges = pp.GroupRanges(
        starts=rs, lens=rl, shift_x=sh3[0], shift_y=sh3[1], shift_z=sh3[2],
        ncells=nruns, occupancy=jnp.int32(0),
        boxl=jnp.full((3,), 1e30, jnp.float32),
    )

    def pair_body(geom, i_fields, j_fields, accs):
        ax, ay, az, phi = accs
        hi = i_fields[3]
        mj, hj = j_fields[3], j_fields[4]
        # SPH-compatible softening: distance clamped to h_i + h_j
        # (ryoanji/nbody/kernel.hpp:515; force vanishes linearly at r->0)
        h_ij = hi + hj
        r2_eff = jnp.maximum(geom.d2, h_ij * h_ij)
        inv_r = jax.lax.rsqrt(jnp.maximum(r2_eff, 1e-30))
        w = jnp.where(geom.mask, mj * inv_r * inv_r * inv_r, 0.0)
        # geom.rx = x_i - x_j = -(source - target)
        return (ax - geom.rx * w, ay - geom.ry * w, az - geom.rz * w,
                phi - w * geom.d2)

    def finalize(i_fields, accs, nc):
        red = lambda a: jnp.sum(a, axis=1, keepdims=True)
        return tuple(red(a) for a in accs)

    engine = pp.group_pair_engine(
        pair_body, finalize, num_i=4, num_j=5, num_acc=4, cfg=nbr,
        fold=False, interpret=pp.pallas_interpret(),
        pair_cutoff=False, want_nc=False,
    )
    # i-side blocks padded to the classification's chunked block count
    # (tail groups re-evaluate the last particle; trimmed by the caller)
    npad = nb * blk
    n = x.shape[0]

    def blocked(a, off):
        a = a + off
        a = jnp.concatenate(
            [a, jnp.broadcast_to(a[-1:], (npad - n,))]
        ) if npad > n else a
        return a.reshape(nb, blk)

    i_fields = [blocked(x, shift[0]), blocked(y, shift[1]),
                blocked(z, shift[2]), blocked(h, 0.0)]
    jp = pp.pack_j_fields(jdata or (x, y, z, m, h), nbr.dma_cap)
    ax, ay, az, phi, _nc = engine(ranges, i_fields, jp, i_offset, allow_self)
    f = lambda a: a.reshape(-1)
    return f(ax), f(ay), f(az), f(phi)


@named_phase("gravity-mac")
def _monotone_mac_geometry(box, tree, meta, node_com, valid, theta):
    """MONOTONE vector-MAC acceptance geometry (macs.hpp computeVecMacR2
    role, made hierarchy-monotone): radius l/theta +
    max-over-subtree(|com - geo|), distance measured from the target bbox
    to the node's GEO BOX. Since child boxes nest and the radius is
    non-increasing down the tree, accept(parent) => accept(child) — so
    "first accepted ancestor" collapses to ONE parent lookup (no
    per-level downsweep, the 210 ms phase at 1M,
    scripts/profile_gravity_phases.py) and p2p = leaf & ~accept needs no
    ancestor chain at all. Validity: the true com distance >= box
    distance (com inside the box) and the monotone radius >= the node's
    own l/theta + s_off, so every acceptance satisfies the original
    vector-MAC error criterion — strictly conservative (measured ~+15%
    m2p work, traded for the whole downsweep).

    Returns (ccenter, chalf, mac2): the subtree-com bounding boxes and
    squared acceptance radii every block classifies against."""
    lengths = box.lengths  # (3,)
    lo = jnp.stack([box.lo[0], box.lo[1], box.lo[2]])
    geo_center = lo[None, :] + tree.center_frac * lengths[None, :]  # (N, 3)
    geo_size = tree.halfsize_frac[:, None] * lengths[None, :]  # (N, 3)
    l_node = 2.0 * jnp.max(geo_size, axis=1)
    s_off = jnp.sqrt(jnp.sum((node_com - geo_center) ** 2, axis=1))
    # empty nodes have no com (mass 0 -> com (0,0,0)); their bogus
    # s_off must not inflate any ancestor's monotone radius
    smax = jnp.where(valid, s_off, 0.0)
    # subtree com BOUNDING BOX: nests under the hierarchy like the geo
    # box (subtree com sets are subsets) but collapses toward a point at
    # depth, so the box-to-box distance below stays nearly as tight as
    # the reference's com-point distance where it matters (the deep
    # acceptance cut) — using the geo box instead measured ~2x more
    # accepted nodes at 1M/theta=0.5
    BIG = jnp.float32(1e15)  # "infinitely far"; squares stay finite in f32
    com_lo = jnp.where(valid[:, None], node_com, BIG)
    com_hi = jnp.where(valid[:, None], node_com, -BIG)
    for s, e in reversed(meta.level_ranges[1:]):
        par = tree.parent[s:e]
        smax = smax.at[par].max(smax[s:e])
        com_lo = com_lo.at[par].min(com_lo[s:e])
        com_hi = com_hi.at[par].max(com_hi[s:e])
    ccenter = jnp.where(valid[:, None], 0.5 * (com_lo + com_hi), BIG)
    chalf = jnp.where(valid[:, None],
                      jnp.maximum(0.5 * (com_hi - com_lo), 0.0), 0.0)
    mac2 = (l_node / theta + smax) ** 2  # (N,)
    return ccenter, chalf, mac2


@functools.partial(jax.jit,
                   static_argnames=("meta", "cfg", "with_phi", "shard"))
def compute_gravity(
    x, y, z, m, h, sorted_keys, box: Box,
    tree: GravityTree, meta: GravityTreeMeta, cfg: GravityConfig,
    shift=None, allow_self=None, with_phi: bool = False, mp_cache=None,
    shard=None,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array, Dict[str, jax.Array]]:
    """Gravitational acceleration + potential for all (SFC-sorted) particles.

    Returns (ax, ay, az, egrav, diagnostics) — or (..., phi, diagnostics)
    when ``with_phi`` — where diagnostics report the high-water
    interaction-list occupancies; if any exceeds its cap the caller must
    enlarge the config and re-run (Simulation handles this the same way as
    neighbor-cell overflow).

    ``shift``: optional (3,) offset added to the *target* positions — the
    replica-shell evaluation of periodic gravity (targets against the
    tree of the base box, traversal_cpu.hpp computeGravity numReplicaShells).
    ``allow_self`` (traced bool scalar) must be True for nonzero shifts: a
    particle does interact with its own periodic image. Both are traced so
    the Ewald replica loop compiles this function once.
    ``mp_cache``: optional precomputed compute_multipoles result.
    ``shard``: (axis, P, win) when running INSIDE shard_map on a local
    slab — x/y/z/... are then the slab, mp_cache must come from
    compute_multipoles_sharded (global edges), and the near field
    fetches remote leaf rows through the halo exchange
    (parallel/exchange.py) instead of indexing a global array. ``win``
    an int is the windowed exchange's Wmax (full-slab fallback at
    win == S); a (P-1,)-tuple of ints is the MAC-sized sparse exchange's
    per-distance row caps (sizing.device_gravity_halo), which also adds
    ``halo_rows``/``halo_occ`` to the diagnostics. egrav and
    diagnostics are returned per-shard (the caller psums/pmaxes).
    """
    if shard is not None and not cfg.use_pallas:
        raise ValueError("sharded gravity needs the engine near field "
                         "(cfg.use_pallas=True; interpret mode off-TPU)")
    if shard is not None and mp_cache is None:
        raise ValueError("sharded gravity needs mp_cache from "
                         "compute_multipoles_sharded")
    n = x.shape[0]
    num_n = meta.num_nodes
    order = cfg.multipole_order
    node_mass, node_com, node_q, edges = (
        mp_cache
        if mp_cache is not None
        else compute_multipoles(x, y, z, m, sorted_keys, tree, meta,
                                order=order)
    )
    valid = node_mass > 0.0
    if shift is None:
        shift = jnp.zeros(3, x.dtype)
    if allow_self is None:
        allow_self = jnp.asarray(False)

    ccenter, chalf, mac2 = _monotone_mac_geometry(
        box, tree, meta, node_com, valid, cfg.theta
    )
    self_parent = tree.parent == jnp.arange(num_n, dtype=tree.parent.dtype)

    blk = cfg.target_block
    num_blocks = -(-n // blk)
    chunk = cfg.blocks_per_chunk
    num_chunks = -(-num_blocks // chunk)
    idx = jnp.arange(num_chunks * chunk * blk, dtype=jnp.int32)
    idx = jnp.minimum(idx, n - 1).reshape(num_chunks, chunk, blk)

    leaf_occ = jnp.max(edges[1:] - edges[:-1])

    # packed node payload for ONE row-gather per block: com 3 + mass 1 +
    # either the 7 quadrupole floats (padded to 12) or the spherical
    # coefficients split re|im — per-field gathers tripled the M2P
    # memory traffic
    if order > 0:
        node_packed = jnp.concatenate(
            [node_com, node_mass[:, None],
             jnp.real(node_q), jnp.imag(node_q)],
            axis=1,
        )
    else:
        node_packed = jnp.concatenate(
            [node_com, node_q, node_mass[:, None],
             jnp.zeros((num_n, 1), node_com.dtype)],
            axis=1,
        )

    def _bbox(tx, ty, tz):
        bc = jnp.stack(
            [(jnp.max(tx) + jnp.min(tx)) * 0.5,
             (jnp.max(ty) + jnp.min(ty)) * 0.5,
             (jnp.max(tz) + jnp.min(tz)) * 0.5]
        )
        bs = jnp.stack(
            [(jnp.max(tx) - jnp.min(tx)) * 0.5,
             (jnp.max(ty) - jnp.min(ty)) * 0.5,
             (jnp.max(tz) - jnp.min(tz)) * 0.5]
        )
        return bc, bs

    def _accept(bc, bs, gc, gs, m2):
        # box-to-box distance vs the monotone MAC radius (see above);
        # nested node boxes make this monotone where the reference's
        # com-distance evaluateMac (macs.hpp) is not
        d = jnp.maximum(
            jnp.abs(bc[None, :] - gc) - bs[None, :] - gs, 0.0
        )
        return jnp.sum(d * d, axis=1) >= m2

    def _compact_candidates(cand, cap):
        """(cidx, cok, ppos) fixed-cap candidate list from a bool node
        mask: stable compaction (level-major order preserved, so the
        kept prefix is ancestor-closed whenever ``cand`` is), num_n
        sentinel on dead slots keeps the list ascending for the
        parent-position searchsorted, ppos clamped into the list."""
        ordc = jnp.argsort(~cand, stable=True)[:cap]
        cok = cand[ordc]
        cidx = jnp.where(cok, ordc, num_n).astype(jnp.int32)
        ppos = jnp.searchsorted(
            cidx, tree.parent[jnp.minimum(cidx, num_n - 1)]
        ).astype(jnp.int32)
        return cidx, cok, jnp.minimum(ppos, cap - 1)

    sf = cfg.super_factor
    if cfg.compaction not in ("sort", "bitmask"):
        raise ValueError(f"unknown compaction mode {cfg.compaction!r}")
    use_bitmask = cfg.compaction == "bitmask"
    if use_bitmask and num_n > (1 << 24):
        raise ValueError(
            f"bitmask compaction packs node indices in 24 bits; "
            f"{num_n} nodes needs compaction='sort'"
        )
    # the LET essential set composes with BOTH compactions at sf == 0;
    # with the bitmask path it additionally feeds the superblock
    # pre-pass (supers classify against the slab's essential list, not
    # the full tree — the essential-set machinery reused one level up)
    use_let = shard is not None and cfg.let_cap > 0 and (
        sf == 0 or use_bitmask
    )
    ecap = min(cfg.let_cap, num_n) if use_let else 0
    scap = min(cfg.super_cap, num_n)
    if use_let:
        # per-shard essential node set (focused-octree / LET analog,
        # octree_focus_mpi.hpp:50-698): ONE slab-bbox classification
        # shared by every block of this shard. Monotone MAC => the open
        # set + accepted cut is ancestor-closed, and any node outside it
        # has an accepted ancestor INSIDE it for every block (block
        # bboxes are subsets of the slab bbox computed from the same
        # live positions, so the superblock containment argument applies
        # with zero staleness).
        with phase_scope("gravity-mac"):
            bc_s, bs_s = _bbox(x + shift[0], y + shift[1], z + shift[2])
            accept_s = valid & _accept(bc_s, bs_s, ccenter, chalf, mac2)
            anc_s = jnp.where(self_parent, False, accept_s[tree.parent])
            cand_s = ~anc_s
            lidx_, lok, lpar = _compact_candidates(cand_s, ecap)
            let_n = jnp.sum(cand_s)

    @named_phase("gravity-m2p")
    def _m2p_eval(tx, ty, tz, order_m, m2p_ok):
        """Far-field eval of one block's fixed-cap M2P list. Shared by
        the sort and bitmask compactions: identical masked sums over
        identical slot layouts keep the two paths bitwise equal."""
        nd = node_packed[jnp.minimum(order_m, num_n - 1)]  # one row gather
        if cfg.multipole_order > 0:
            from sphexa_tpu.gravity import spherical as sp

            nc_ = sp.ncoef(cfg.multipole_order)
            coeffs = jax.lax.complex(nd[:, 4 : 4 + nc_], nd[:, 4 + nc_ :])
            return sp.m2p(tx, ty, tz, nd[:, 0:3], coeffs, m2p_ok,
                          cfg.multipole_order)
        return mp.m2p(tx, ty, tz, nd[:, 0:3], nd[:, 3:10], nd[:, 10], m2p_ok)

    @named_phase("gravity-p2p")
    def _p2p_leaf_ranges(order_p, p2p_ok):
        """Sorted-array row ranges of one block's near-field leaves."""
        order_p = jnp.minimum(order_p, num_n - 1)
        lidx = tree.leaf_of_node[order_p]  # (P,)
        start = jnp.where(p2p_ok, edges[lidx], 0)
        length = jnp.where(p2p_ok, edges[lidx + 1] - edges[lidx], 0)
        return start, length

    @named_phase("gravity-p2p")
    def _p2p_xla(tx, ty, tz, th, bi, start, length, p2p_ok):
        """Portable gather-based near field (cfg.use_pallas=False)."""
        cand = start[:, None] + jnp.arange(cfg.leaf_cap, dtype=jnp.int32)
        cand_ok = (cand < (start + length)[:, None]) & p2p_ok[:, None]
        cand = jnp.clip(cand, 0, n - 1).reshape(-1)  # (P*C,)
        cand_ok = cand_ok.reshape(-1)
        # in a shifted replica pass a particle's own image is a real pair
        pair_ok = cand_ok[None, :] & ((cand[None, :] != bi[:, None]) | allow_self)
        return mp.p2p(
            tx, ty, tz, th,
            x[cand], y[cand], z[cand], m[cand], h[cand], pair_ok,
        )

    if use_bitmask:
        from sphexa_tpu.gravity import pallas_compact as pcmp
        from sphexa_tpu.sph.pallas_pairs import pallas_interpret

        interp = pallas_interpret()
        # first-accepted-ancestor by PARENT-GEOMETRY re-evaluation:
        # anc(block, node) == accept(block, parent(node)), so evaluating
        # the MAC on the parent's own (gathered-once) arrays replaces the
        # per-block (B, N) accept[parent] gather — identical f32 inputs,
        # identical booleans, no gather on the hot path. Works for ANY
        # candidate subset without requiring the parent in the list.
        par_i = jnp.minimum(tree.parent, num_n - 1)
        pcc = ccenter[par_i]
        pch = chalf[par_i]
        pmac2 = mac2[par_i]
        anc_ok = (~self_parent) & valid[par_i]
        leaf_ok = tree.is_leaf & valid
        iota_n = jnp.arange(num_n, dtype=jnp.int32)
        dense_geo = (ccenter, chalf, mac2, pcc, pch, pmac2, anc_ok,
                     leaf_ok, valid, jnp.ones((num_n,), bool), iota_n)

        def _gather_geo(cidx, ok):
            """Candidate-space MAC arrays of one node list (gathered ONCE
            per list and shared by every block classifying against it —
            the per-block candidate gathers are what sank the round-4
            superblock formulation)."""
            ci = jnp.minimum(cidx, num_n - 1)
            return (ccenter[ci], chalf[ci], mac2[ci], pcc[ci], pch[ci],
                    pmac2[ci], anc_ok[ci] & ok, leaf_ok[ci] & ok,
                    valid[ci] & ok, ok, ci)

        def _packed_cls(bc, bs, geo):
            """Per-candidate M2P/P2P/pruned class, packed with the node
            index for the compaction kernel."""
            cc, ch, m2, pc_, ph_, pm2, aok, lfk, vld, _ok, idxs = geo
            acc = vld & _accept(bc, bs, cc, ch, m2)
            anc = aok & _accept(bc, bs, pc_, ph_, pm2)
            cls = jnp.where(acc & ~anc, 0, jnp.where(lfk & ~acc, 1, 2))
            return (cls.astype(jnp.int32) << pcmp.IDX_BITS) | idxs

        def _packed_cand(bc, bs, geo):
            """Superblock pre-pass class: candidate = parent NOT accepted
            (open set + accepted cut; ancestor-closed under the monotone
            MAC — parents have smaller level-major indices, so any cap
            prefix of the ascending list stays closed)."""
            _cc, _ch, _m2, pc_, ph_, pm2, aok, _lfk, _vld, ok, idxs = geo
            anc = aok & _accept(bc, bs, pc_, ph_, pm2)
            cls = jnp.where(ok & ~anc, 0, 2)
            return (cls.astype(jnp.int32) << pcmp.IDX_BITS) | idxs

        @named_phase("gravity-mac")
        def _block_bm(bi, geo):
            bc, bs = _bbox(x[bi] + shift[0], y[bi] + shift[1],
                           z[bi] + shift[2])
            return _packed_cls(bc, bs, geo)

        def _eval_bm(bi, om, mn, op, pn):
            tx = x[bi] + shift[0]
            ty = y[bi] + shift[1]
            tz = z[bi] + shift[2]
            th = h[bi]
            m2p_ok = jnp.arange(cfg.m2p_cap, dtype=jnp.int32) < mn
            ax, ay, az, phi = _m2p_eval(tx, ty, tz, om, m2p_ok)
            p2p_ok = jnp.arange(cfg.p2p_cap, dtype=jnp.int32) < pn
            start, length = _p2p_leaf_ranges(op, p2p_ok)
            if cfg.use_pallas:
                return ax, ay, az, phi, mn, pn, start, length
            pax, pay, paz, pphi = _p2p_xla(tx, ty, tz, th, bi, start,
                                           length, p2p_ok)
            return ax + pax, ay + pay, az + paz, phi + pphi, mn, pn

        if use_let:
            let_geo = _gather_geo(jnp.minimum(lidx_, num_n - 1), lok)

        if sf > 0:
            # two-level hierarchical classification, bitmask-compacted:
            # supers classify against the LET list (sharded) or the full
            # tree, keep their candidate cut through the SAME kernel, and
            # blocks classify only against their super's list — all node
            # data gathered once per super, never per block.
            sblk = sf * blk
            num_super = -(-n // sblk)
            sidx = jnp.arange(num_super * sblk, dtype=jnp.int32)
            sidx = jnp.minimum(sidx, n - 1).reshape(num_super, sblk)
            pre_geo = let_geo if use_let else dense_geo

            @named_phase("gravity-mac")
            def one_super_pre(si):
                bc, bs = _bbox(x[si] + shift[0], y[si] + shift[1],
                               z[si] + shift[2])
                return _packed_cand(bc, bs, pre_geo)

            spc = max(1, min(num_super, chunk))
            nsc = -(-num_super // spc)
            sidx_p = jnp.concatenate(
                [sidx, jnp.broadcast_to(sidx[-1:],
                                        (nsc * spc - num_super, sblk))]
            ) if nsc * spc > num_super else sidx

            @named_phase("gravity-mac")
            def pre_chunk(sx):
                pk = jax.vmap(one_super_pre)(sx)
                sc, sn, _, _ = pcmp.compact_class_lists(
                    pk, scap, 128, interpret=interp)
                return sc, sn

            scand, scand_n = jax.lax.map(
                pre_chunk, sidx_p.reshape(nsc, spc, sblk))
            scand = scand.reshape(-1, scap)[:num_super]
            scand_n = scand_n.reshape(-1)[:num_super]
            c_max = jnp.max(scand_n)

            idxb = jnp.arange(num_super * sf * blk, dtype=jnp.int32)
            idxb = jnp.minimum(idxb, n - 1).reshape(num_super, sf, blk)

            def one_super_main(args):
                sc, sn, bidx = args
                with phase_scope("gravity-mac"):
                    ok = jnp.arange(scap, dtype=jnp.int32) < jnp.minimum(
                        sn, scap)
                    geo = _gather_geo(sc, ok)
                    pk = jax.vmap(lambda bi: _block_bm(bi, geo))(bidx)
                    om, mn, op, pn = pcmp.compact_class_lists(
                        pk, cfg.m2p_cap, cfg.p2p_cap, interpret=interp)
                return jax.vmap(_eval_bm)(bidx, om, mn, op, pn)

            out = jax.lax.map(one_super_main, (scand, scand_n, idxb))
        else:
            geo0 = let_geo if use_let else dense_geo

            def one_chunk_bm(bidx):
                with phase_scope("gravity-mac"):
                    pk = jax.vmap(lambda bi: _block_bm(bi, geo0))(bidx)
                    om, mn, op, pn = pcmp.compact_class_lists(
                        pk, cfg.m2p_cap, cfg.p2p_cap, interpret=interp)
                return jax.vmap(_eval_bm)(bidx, om, mn, op, pn)

            out = jax.lax.map(one_chunk_bm, idx)

    if not use_bitmask and sf > 0:
        # superblock pre-pass (the two-level hierarchical classification):
        # classify a ~sf*blk-particle bbox against ALL nodes once, keep
        # its OPEN set + accepted cut — ancestor-closed, so per-block
        # refinement only re-evaluates this candidate list. Super-accept
        # implies block-accept (a block's bbox is inside the super bbox,
        # so its node distance can only grow), hence no block ever needs
        # a node outside the list.
        sblk = sf * blk
        num_super = -(-n // sblk)
        sidx = jnp.arange(num_super * sblk, dtype=jnp.int32)
        sidx = jnp.minimum(sidx, n - 1).reshape(num_super, sblk)

        @named_phase("gravity-mac")
        def one_super(si):
            bc, bs = _bbox(x[si] + shift[0], y[si] + shift[1],
                           z[si] + shift[2])
            accept = valid & _accept(bc, bs, ccenter, chalf, mac2)
            # monotone MAC: an accepted strict ancestor == accepted parent
            anc = jnp.where(self_parent, False, accept[tree.parent])
            cand = ~anc  # open nodes + the accepted cut (ancestor-closed)
            cidx, cok, ppos = _compact_candidates(cand, scap)
            return cidx, cok, ppos, jnp.sum(cand)

        nsc = -(-num_super // chunk)
        sidx_p = jnp.concatenate(
            [sidx, jnp.broadcast_to(sidx[-1:], (nsc * chunk - num_super, sblk))]
        ) if nsc * chunk > num_super else sidx
        scand, scand_ok, spar, scand_n = jax.lax.map(
            jax.vmap(one_super), sidx_p.reshape(nsc, chunk, sblk)
        )
        scand = scand.reshape(-1, scap)
        scand_ok = scand_ok.reshape(-1, scap)
        spar = spar.reshape(-1, scap)
        c_max = jnp.max(scand_n)

    def one_block(bi, bnum):
        """bi: (blk,) particle indices of one target group; bnum: its
        block index (selects the superblock candidate list)."""
        tx, ty, tz, th = x[bi] + shift[0], y[bi] + shift[1], z[bi] + shift[2], h[bi]
        with phase_scope("gravity-mac"):
            bc, bs = _bbox(tx, ty, tz)

            if sf > 0 or use_let:
                if sf > 0:
                    sid = bnum // sf
                    cidx = jnp.minimum(scand[sid], num_n - 1)
                    cok = scand_ok[sid]
                    ppos = spar[sid]
                else:
                    # LET: the shard-wide essential list, shared by blocks
                    cidx = jnp.minimum(lidx_, num_n - 1)
                    cok = lok
                    ppos = lpar
                accept = cok & valid[cidx] & _accept(
                    bc, bs, ccenter[cidx], chalf[cidx], mac2[cidx]
                )
                # monotone MAC: the first accepted ancestor IS the parent.
                # The root's parent is ITSELF — mask self-parents or an
                # accepted root (far replica shifts) would mark itself as its
                # own accepted ancestor and zero the whole interaction
                not_self = cidx[ppos] != cidx
                anc = accept[ppos] & not_self
                m2p_mask = accept & ~anc
                p2p_mask = cok & tree.is_leaf[cidx] & valid[cidx] & ~accept
            else:
                cidx = None
                accept = valid & _accept(bc, bs, ccenter, chalf, mac2)
                # monotone MAC (see mac2 above): one parent gather replaces
                # the per-level first-accepted-ancestor downsweep, and
                # ~accept already implies no accepted ancestor for leaves
                anc = jnp.where(self_parent, False, accept[tree.parent])
                m2p_mask = accept & ~anc
                p2p_mask = tree.is_leaf & valid & ~accept
            m2p_n = jnp.sum(m2p_mask)
            p2p_n = jnp.sum(p2p_mask)

            # ONE 3-class sort compacts both interaction lists: class-0 nodes
            # (M2P) land first, class-1 (P2P leaves) directly after, so the
            # P2P list is a dynamic slice at the M2P count. The class and the
            # node index ride in one PACKED int32 key (class in the top bits,
            # index below) — a single single-operand sort where a stable
            # argsort + sort pair cost ~2x (the 208 ms phase at 1M,
            # scripts/profile_gravity_phases.py); unique keys make it
            # order-preserving within a class by construction
            cls = jnp.where(m2p_mask, 0, jnp.where(p2p_mask, 1, 2))
            cls_len = cls.shape[0]
            nbits = max(1, int(np.ceil(np.log2(max(cls_len, 2)))))
            iota_k = jnp.arange(cls_len, dtype=jnp.int32)
            # measured equals: lax.top_k(k = m2p_cap + p2p_cap) on the
            # negated keys costs the SAME as the full sort at 1M/58k nodes
            # (803.8 vs 798.7 ms end-to-end) — XLA's TPU top_k is not a
            # partial sort win at k/N ~ 13%; keep the simpler full sort
            ks = jnp.sort((cls.astype(jnp.int32) << nbits) | iota_k)
            order_all = ks & jnp.int32((1 << nbits) - 1)
            cls_sorted = ks >> nbits
            if cidx is not None:
                order_all = cidx[order_all]
            # sentinel-pad so the fixed-cap slices below stay in range when
            # the candidate list is shorter than a cap (tiny trees / small
            # super lists)
            padn = max(cfg.m2p_cap, cfg.p2p_cap)
            order_all = jnp.concatenate(
                [order_all, jnp.full((padn,), num_n - 1, order_all.dtype)]
            )
            cls_sorted = jnp.concatenate(
                [cls_sorted, jnp.full((padn,), 2, cls_sorted.dtype)]
            )
            order_m = jnp.minimum(order_all[: cfg.m2p_cap], num_n - 1)
            m2p_ok = cls_sorted[: cfg.m2p_cap] == 0
        ax, ay, az, phi = _m2p_eval(tx, ty, tz, order_m, m2p_ok)

        # dynamic_slice clamps the start when m2p_n is near the array
        # end; the slice then still covers the whole class-1 block and
        # stray class-0/2 entries are masked
        order_p = jax.lax.dynamic_slice(order_all, (m2p_n,), (cfg.p2p_cap,))
        p2p_ok = jax.lax.dynamic_slice(
            cls_sorted, (m2p_n,), (cfg.p2p_cap,)
        ) == 1
        start, length = _p2p_leaf_ranges(order_p, p2p_ok)

        if cfg.use_pallas:
            # defer the near field to the streamed engine (below)
            return ax, ay, az, phi, m2p_n, p2p_n, start, length

        pax, pay, paz, pphi = _p2p_xla(tx, ty, tz, th, bi, start, length,
                                       p2p_ok)
        return ax + pax, ay + pay, az + paz, phi + pphi, m2p_n, p2p_n

    if not use_bitmask:
        bnum = jnp.arange(num_chunks * chunk, dtype=jnp.int32)
        bnum = jnp.minimum(bnum, num_blocks - 1).reshape(num_chunks, chunk)

        def one_chunk(args):
            bidx, bn = args
            return jax.vmap(one_block)(bidx, bn)

        out = jax.lax.map(one_chunk, (idx, bnum))
    escaped = jnp.asarray(False)
    grav_halo_metrics = None
    if cfg.use_pallas:
        ax, ay, az, phi, m2p_n, p2p_n, p2p_starts, p2p_lens = out
        starts2 = p2p_starts.reshape(-1, cfg.p2p_cap)
        lens2 = p2p_lens.reshape(-1, cfg.p2p_cap)
        jd = None
        if shard is not None:
            # near-field halos: leaf row ranges are GLOBAL rows; fetch
            # the remote ones through the halo exchange (the same
            # machinery the SPH stages ride; runs escaping their cap
            # flip the p2p sentinel so the driver re-sizes). The caller
            # clamps the window/caps <= slab rows (_gravity_sharded_stage).
            from sphexa_tpu.parallel import exchange as ex
            from sphexa_tpu.sph.pallas_pairs import GroupRanges

            axis, P_, win = shard
            kk = jax.lax.axis_index(axis)
            zf = jnp.zeros_like(starts2, dtype=jnp.float32)
            pr = GroupRanges(
                starts=starts2, lens=lens2, shift_x=zf, shift_y=zf,
                shift_z=zf,
                ncells=jnp.zeros(starts2.shape[0], jnp.int32),  # recomputed
                occupancy=jnp.int32(0),
                boxl=jnp.full((3,), 1e30, jnp.float32),
            )
            if isinstance(win, tuple):
                # MAC-sized sparse near field: ``edges`` (the sharded
                # upsweep's global leaf row boundaries) IS a cell table
                # in the exchange.py sense, so the cell-granular serve
                # ships only the rows of leaves this slab's essential
                # set opens — sized by sizing.device_gravity_halo, with
                # full slabs (caps == S) as the retry ceiling
                lranges, covered_all, escaped, covered = (
                    ex.localize_ranges_sparse(pr, edges, n, P_, win, kk,
                                              axis)
                )
                halo, _ = ex.serve_sparse(
                    (x, y, z, m, h), covered_all, edges, n, win, P_, kk,
                    axis, token=covered_all,
                )
                grav_halo_metrics = ex.exchange_metrics_sparse(
                    covered, edges, n, win, P_, kk
                )
            else:
                lranges, bounds, escaped = ex.localize_ranges(
                    pr, n, P_, win, kk, axis
                )
                halo = ex.serve_windows((x, y, z, m, h), bounds, n, win,
                                        P_, kk, axis)
            jd = tuple(
                jnp.concatenate([o, a])
                for o, a in zip((x, y, z, m, h), halo)
            )
            starts2, lens2 = lranges.starts, lranges.lens
        pax, pay, paz, pphi = _pallas_p2p(
            x, y, z, m, h, shift, allow_self, cfg,
            starts2, lens2, jdata=jd,
        )
        blkpad = ax.reshape(-1).shape[0]
        ax = ax.reshape(-1) + pax[:blkpad]
        ay = ay.reshape(-1) + pay[:blkpad]
        az = az.reshape(-1) + paz[:blkpad]
        phi = phi.reshape(-1) + pphi[:blkpad]
    else:
        ax, ay, az, phi, m2p_n, p2p_n = out
    ax = ax.reshape(-1)[:n] * cfg.G
    ay = ay.reshape(-1)[:n] * cfg.G
    az = az.reshape(-1)[:n] * cfg.G
    phi = phi.reshape(-1)[:n] * cfg.G
    # padded tail lanes duplicate the last particle; only [:n] is kept, and
    # egrav sums the trimmed arrays, so duplicates never double-count.
    # evaluations over REAL blocks only, matching the phantom-masked
    # numerator below: dense = blocks x nodes; hierarchical = supers x
    # nodes (pre-pass) + blocks x super_cap (refinement)
    if sf > 0:
        # supers classify against the LET list on the sharded bitmask
        # path (plus the one slab-bbox sweep that builds it), the full
        # tree otherwise
        pre_c = ecap if (use_bitmask and use_let) else num_n
        evals = num_super * pre_c + num_blocks * scap
        if use_bitmask and use_let:
            evals += num_n
    elif use_let:
        evals = num_n + num_blocks * ecap
    else:
        evals = num_blocks * num_n
    # per-block candidate width the compaction runs over — with the
    # sort path this is also the per-block sort width, so the hot-path
    # complexity proxy (blocks x width) is comparable across modes
    compact_width = scap if sf > 0 else (ecap if use_let else num_n)
    # phantom tail blocks (chunk padding re-evaluates the last particle as
    # a point bbox) classify DIFFERENTLY from any real block — a point
    # target accepts more nodes than the block containing it — and their
    # counts would inflate the cap-sizing high-water marks (their forces
    # are discarded by the [:n] trim either way)
    real_blk = (
        jnp.arange(m2p_n.size, dtype=jnp.int32) < num_blocks
    ).reshape(m2p_n.shape)
    m2p_n = jnp.where(real_blk, m2p_n, 0)
    p2p_n = jnp.where(real_blk, p2p_n, 0)
    p2p_hw = jnp.max(p2p_n)
    if shard is not None:
        # an escaped near-field run means truncated candidates: the
        # SHARED overflow contract encodes it as a p2p overflow (and
        # pmaxes) so the driver re-sizes the halo window
        from sphexa_tpu.parallel.exchange import chain_after, fold_escape_sentinel

        if cfg.use_pallas and jd is not None:
            # p2p_n comes from the PRE-exchange traversal sweep, so the
            # overflow pmax has no data order against serve_windows'
            # all_to_all without this pin (the rendezvous-race class
            # JXA201 gates)
            p2p_hw = chain_after(p2p_hw, jd[0])
        p2p_hw = fold_escape_sentinel(p2p_hw, escaped, cfg.p2p_cap, shard[0])
    diagnostics = {
        "m2p_max": jnp.max(m2p_n),
        "p2p_max": p2p_hw,
        "leaf_occ": leaf_occ,
        # superblock candidate-list high water (cap guard; 0 = dense path)
        "c_max": c_max if sf > 0 else jnp.int32(0),
        # per-shard essential-set high water (LET cap guard; 0 = off)
        "let_max": let_n if use_let else jnp.int32(0),
        # compaction complexity proxy: candidate slots each block's list
        # materialization scans (the interpret-mode op-count stand-in for
        # chip timings; bench.py records it in the phase breakdown)
        "compact_width": jnp.int32(compact_width),
        # accepted-to-evaluated MAC work (VERDICT r2 #4 diagnostic): the
        # hierarchical path shrinks the denominator by ~num_n/super_cap
        "mac_work_ratio": (
            (jnp.sum(m2p_n) + jnp.sum(p2p_n)).astype(jnp.float32)
            / jnp.float32(evals)
        ),
    }
    if grav_halo_metrics is not None:
        # sparse MAC-window mode only (the windowed / grav_window=0
        # lowering stays byte-identical): device-measured TRUE remote
        # row need + per-distance cap occupancy, folded to the schema-v7
        # gravity-stage exchange telemetry by _gravity_sharded_stage
        diagnostics["halo_rows"] = grav_halo_metrics["halo_rows"]
        diagnostics["halo_occ"] = grav_halo_metrics["halo_occ"]
    if with_phi:
        return ax, ay, az, phi, diagnostics
    egrav = 0.5 * jnp.sum(m * phi)
    return ax, ay, az, egrav, diagnostics
