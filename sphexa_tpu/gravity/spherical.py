"""Spherical multipoles with a selectable expansion order P.

The accuracy knob the reference gets from its EXAFMM spherical harmonics
(ryoanji/src/ryoanji/nbody/kernel.hpp:1-634: P2M/M2M/M2P to arbitrary
order), re-designed for JAX/TPU:

- solid-harmonic recurrences are UNROLLED at trace time for a static
  order P (the reference's template parameter), producing pure batched
  arithmetic over (nodes, ncoef) complex coefficient arrays;
- the addition theorem 1/|x-y| = sum_nm R_n^m(y) conj(S_n^m(x)) gives
  P2M as an edge-segment sum of regular harmonics and M2P as a masked
  coefficient contraction;
- the acceleration is jax.grad of the M2P potential — exact to f32
  rounding, no hand-derived gradient recurrences to get wrong (the
  reference hand-codes them; autodiff is the TPU-native equivalent);
- M2M is the O(P^4) translation M'_n^m = sum_kl R_k^l(d) M_{n-k}^{m-l},
  batched over all nodes of a level.

Conventions (Dehnen / EXAFMM "scaled" solid harmonics):
  R_0^0 = 1,  R_m^m = (x+iy)/(2m) R_{m-1}^{m-1},
  R_n^m = ((2n-1) z R_{n-1}^m - r^2 R_{n-2}^m) / ((n+m)(n-m))
  S_0^0 = 1/r, S_m^m = (2m-1)(x+iy)/r^2 S_{m-1}^{m-1},
  S_n^m = ((2n-1) z S_{n-1}^m - ((n-1)^2 - m^2) S_{n-2}^m) / r^2
with negative orders via R_n^{-m} = (-1)^m conj(R_n^m). Only m >= 0 is
stored: ncoef(P) = P (P+1) / 2 complex coefficients.

Order P counts retained expansion terms n = 0..P-1; P=3 matches the
cartesian quadrupole's information content, P>=4 beats it (pinned by
tests/test_spherical.py against direct summation).
"""

import functools
from typing import List, Tuple

import jax
import jax.numpy as jnp


def ncoef(p: int) -> int:
    return p * (p + 1) // 2


@functools.lru_cache(maxsize=None)
def _nm_index(p: int) -> dict:
    """(n, m) -> flat index for 0 <= m <= n < p."""
    idx, k = {}, 0
    for n in range(p):
        for m in range(n + 1):
            idx[(n, m)] = k
            k += 1
    return idx


def regular_harmonics(x, y, z, p: int) -> List:
    """R_n^m(x) for 0 <= m <= n < p, each entry complex, batched over x."""
    xy = jax.lax.complex(x, y)
    r2 = x * x + y * y + z * z
    zc = z  # real z multiplies complex arrays fine
    R = {}
    R[(0, 0)] = jnp.ones_like(xy)
    for m in range(1, p):
        R[(m, m)] = xy / (2.0 * m) * R[(m - 1, m - 1)]
    for m in range(0, p - 1):
        R[(m + 1, m)] = zc * R[(m, m)]
    for m in range(0, p):
        for n in range(m + 2, p):
            R[(n, m)] = (
                (2.0 * n - 1.0) * zc * R[(n - 1, m)] - r2 * R[(n - 2, m)]
            ) / float((n + m) * (n - m))
    idx = _nm_index(p)
    out = [None] * ncoef(p)
    for nm, k in idx.items():
        out[k] = R[nm]
    return out


def irregular_harmonics(x, y, z, p: int) -> List:
    """S_n^m(x) for 0 <= m <= n < p, batched; singular at the origin
    (callers only evaluate outside the MAC radius)."""
    xy = jax.lax.complex(x, y)
    r2 = x * x + y * y + z * z
    inv_r2 = 1.0 / r2
    S = {}
    S[(0, 0)] = jnp.sqrt(inv_r2).astype(xy.dtype)
    for m in range(1, p):
        S[(m, m)] = (2.0 * m - 1.0) * xy * inv_r2 * S[(m - 1, m - 1)]
    for m in range(0, p - 1):
        S[(m + 1, m)] = (2.0 * m + 1.0) * z * inv_r2 * S[(m, m)]
    for m in range(0, p):
        for n in range(m + 2, p):
            S[(n, m)] = (
                (2.0 * n - 1.0) * z * S[(n - 1, m)]
                - float((n - 1) ** 2 - m * m) * S[(n - 2, m)]
            ) * inv_r2
    idx = _nm_index(p)
    out = [None] * ncoef(p)
    for nm, k in idx.items():
        out[k] = S[nm]
    return out


def p2m(x, y, z, m_part, center, edges, p: int, pleaf=None) -> jax.Array:
    """Leaf multipoles M_n^m = sum_j m_j R_n^m(x_j - c) for contiguous
    leaf row ranges ``edges`` (the spherical P2M, kernel.hpp P2M).
    ``pleaf`` is the particle->leaf map when the caller already has it
    (compute_multipoles does)."""
    from sphexa_tpu.gravity.multipole import edge_segment_sum

    nl = center.shape[0]
    if pleaf is None:
        pleaf = jnp.searchsorted(
            edges, jnp.arange(x.shape[0], dtype=edges.dtype), side="right"
        ) - 1
        pleaf = jnp.clip(pleaf, 0, nl - 1)
    dx = x - center[pleaf, 0]
    dy = y - center[pleaf, 1]
    dz = z - center[pleaf, 2]
    R = regular_harmonics(dx, dy, dz, p)
    w = jnp.stack([m_part * Rk for Rk in R], axis=1)  # (n, NC) complex
    return edge_segment_sum(w, edges)  # (L, NC) complex


def _get(coeffs, idx, n: int, m: int):
    """M_n^m from the m>=0 storage, negative m via conjugation parity."""
    if m >= 0:
        return coeffs[..., idx[(n, m)]]
    c = jnp.conj(coeffs[..., idx[(n, -m)]])
    return c if (-m) % 2 == 0 else -c


def m2m(coeffs, d, p: int) -> jax.Array:
    """Translate child expansions by ``d = c_child - c_parent``:
    M'_n^m = sum_{k,l} R_k^l(d) M_{n-k}^{m-l} (kernel.hpp M2M),
    batched over nodes. coeffs (..., NC) complex, d (..., 3) real."""
    idx = _nm_index(p)
    R = regular_harmonics(d[..., 0], d[..., 1], d[..., 2], p)
    Rd = {}
    for (n, m), k in idx.items():
        Rd[(n, m)] = R[k]
        if m > 0:
            c = jnp.conj(R[k])
            Rd[(n, -m)] = c if m % 2 == 0 else -c
    out = []
    for n in range(p):
        for m in range(n + 1):
            acc = 0.0
            for k in range(n + 1):
                for l in range(-k, k + 1):
                    if abs(m - l) > n - k:
                        continue
                    acc = acc + Rd[(k, l)] * _get(coeffs, idx, n - k, m - l)
            out.append(acc)
    return jnp.stack(out, axis=-1)


def potential(dx, dy, dz, coeffs, p: int):
    """phi at target offsets (relative to the expansion center):
    phi = sum_n [ M_n^0 S_n^0 + 2 sum_{m>0} Re(M_n^m conj(S_n^m)) ].
    Shapes broadcast; coeffs (..., NC) complex."""
    S = irregular_harmonics(dx, dy, dz, p)
    idx = _nm_index(p)
    acc = 0.0
    for (n, m), k in idx.items():
        term = jnp.real(coeffs[..., k] * jnp.conj(S[k]))
        acc = acc + (term if m == 0 else 2.0 * term)
    return acc


def m2p(tx, ty, tz, com, coeffs, mask, p: int):
    """Far-field acceleration + potential of accepted nodes on targets.

    The acceleration is the (autodiff) negative gradient of the summed
    potential — exactly consistent with phi. Shapes: targets (B,), nodes
    (K, ...); returns (ax, ay, az, phi) each (B,).
    """

    def phi_one(px, py, pz):
        # masked slots can hold the target's OWN leaf (r -> 0, S
        # singular); the standard double-where keeps the unselected
        # branch finite so autodiff does not propagate NaN through it
        dx = jnp.where(mask, px - com[:, 0], 1.0)
        dy = jnp.where(mask, py - com[:, 1], 1.0)
        dz = jnp.where(mask, pz - com[:, 2], 1.0)
        ph = potential(dx, dy, dz, coeffs, p)
        return jnp.sum(jnp.where(mask, ph, 0.0))

    phi, grads = jax.vmap(jax.value_and_grad(phi_one, argnums=(0, 1, 2)))(
        tx, ty, tz
    )
    # the expansion is phi_exp = sum_j m_j/|x - x_j| (positive); the
    # physical potential is -phi_exp, so a = -grad(phi_phys) =
    # +grad(phi_exp), and the returned phi matches the cartesian path's
    # physical-sign convention
    return grads[0], grads[1], grads[2], -phi


def upsweep(leaf_coeffs, node_com, tree, meta, node_of_leaf, p: int):
    """Level-by-level M2M to the root (upsweepMultipoles analog)."""
    num_n = meta.num_nodes
    node_c = jnp.zeros((num_n, ncoef(p)), leaf_coeffs.dtype)
    node_c = node_c.at[node_of_leaf].set(leaf_coeffs)
    for s, e in reversed(meta.level_ranges[1:]):
        par = tree.parent[s:e]
        d = node_com[s:e] - node_com[par]  # child - parent
        # sorted parent rows (level-ordered layout), see the cartesian
        # upsweep in traversal.py — keeps the duplicate-index
        # accumulation order fixed for the JXA401 replay contract
        node_c = node_c.at[par].add(m2m(node_c[s:e], d, p),
                                    indices_are_sorted=True)
    return node_c
