"""Cartesian quadrupole operators: P2M / M2M / M2P / P2P.

TPU-native re-design of the reference's ryoanji kernels
(ryoanji/src/ryoanji/nbody/cartesian_qpole.hpp: P2M :89, addQuadrupole/M2M
:210, M2P :177; kernel.hpp P2P :515): the per-node scalar loops become
vectorized segment reductions and batched elementwise math over a
level-major node array.

Multipole layout: a (..., 7) array [qxx qxy qxz qyy qyz qzz trace] in the
*trace-free* Hernquist-1987 form (qxx = 3<m dx dx> - trace, ...). Masses
and centers-of-mass are carried separately (they are needed before the
quadrupole pass).
"""

import jax.numpy as jnp
from jax.ops import segment_sum


def edge_segment_sum(w, edges):
    """Segment sums of ROW-CONTIGUOUS segments: cumulative sum differenced
    at the segment edges. Particles arrive SFC-sorted, so a leaf's rows
    are contiguous — this replaces scatter-add segment_sum, which
    serializes on TPU (~10 ms per 65k-row scatter). The f32 prefix sum
    costs ~n*eps relative error on small segments (<= ~2e-4 at 1e6 rows),
    well under the theta-truncation error of the multipole expansion."""
    c = jnp.cumsum(w, axis=0)
    c = jnp.concatenate([jnp.zeros_like(c[:1]), c], axis=0)
    return c[edges[1:]] - c[edges[:-1]]


def p2m_leaf(x, y, z, m, pleaf, leaf_com, num_leaves, edges=None):
    """Trace-free quadrupole of every leaf around its center of mass.

    Vectorized counterpart of P2M (cartesian_qpole.hpp:89): raw second
    moments via one segment-sum per component, then the trace removal.
    ``edges`` (L+1,) row boundaries select the fast contiguous-segment
    path (edge_segment_sum); without them a scatter segment_sum runs.
    """
    dx = x - leaf_com[pleaf, 0]
    dy = y - leaf_com[pleaf, 1]
    dz = z - leaf_com[pleaf, 2]
    raw = jnp.stack(
        [m * dx * dx, m * dx * dy, m * dx * dz,
         m * dy * dy, m * dy * dz, m * dz * dz],
        axis=1,
    )
    if edges is not None:
        q = edge_segment_sum(raw, edges)  # (L, 6)
    else:
        q = segment_sum(raw, pleaf, num_segments=num_leaves)  # (L, 6)
    return _remove_trace(q)


def _remove_trace(q):
    """raw second moments (..., 6) -> trace-free form (..., 7)."""
    trace = q[..., 0] + q[..., 3] + q[..., 5]
    return jnp.stack(
        [3.0 * q[..., 0] - trace, 3.0 * q[..., 1], 3.0 * q[..., 2],
         3.0 * q[..., 3] - trace, 3.0 * q[..., 4], 3.0 * q[..., 5] - trace,
         trace],
        axis=-1,
    )


def m2m_shift(q_child, m_child, d):
    """Child quadrupole shifted to the parent expansion center.

    addQuadrupole (cartesian_qpole.hpp:210), Hernquist 1987 eq. (2.5):
    ``d = com_parent - com_child``; the returned term is scatter-added into
    the parent.
    """
    dx, dy, dz = d[..., 0], d[..., 1], d[..., 2]
    r2_3 = (dx * dx + dy * dy + dz * dz) * (1.0 / 3.0)
    ml = 3.0 * m_child
    return q_child + jnp.stack(
        [ml * (dx * dx - r2_3), ml * dx * dy, ml * dx * dz,
         ml * (dy * dy - r2_3), ml * dy * dz, ml * (dz * dz - r2_3),
         ml * r2_3],
        axis=-1,
    )


def m2p(tx, ty, tz, com, q, mass, mask):
    """Far-field contribution of nodes to target particles.

    M2P (cartesian_qpole.hpp:177), Hernquist 1987: monopole -M/r^3 * r plus
    quadrupole Q.r/r^5 - 5/2 (r.Q.r) r / r^7. Shapes: targets (B,), nodes
    (K,); returns per-target sums (ax, ay, az, phi) each (B,).
    """
    rx = tx[:, None] - com[None, :, 0]  # (B, K)
    ry = ty[:, None] - com[None, :, 1]
    rz = tz[:, None] - com[None, :, 2]
    r2 = rx * rx + ry * ry + rz * rz
    inv_r = jnp.where(mask[None, :], jnp.maximum(r2, 1e-30) ** -0.5, 0.0)
    inv_r2 = inv_r * inv_r
    inv_r5 = inv_r2 * inv_r2 * inv_r

    qxx, qxy, qxz = q[:, 0], q[:, 1], q[:, 2]
    qyy, qyz, qzz = q[:, 3], q[:, 4], q[:, 5]
    qrx = rx * qxx[None] + ry * qxy[None] + rz * qxz[None]
    qry = rx * qxy[None] + ry * qyy[None] + rz * qyz[None]
    qrz = rx * qxz[None] + ry * qyz[None] + rz * qzz[None]
    rqr = rx * qrx + ry * qry + rz * qrz

    m_ = mass[None, :]
    quad_mono = (-2.5 * rqr * inv_r5 - m_ * inv_r) * inv_r2
    phi = -(m_ * inv_r + 0.5 * inv_r5 * rqr)
    ax = inv_r5 * qrx + quad_mono * rx
    ay = inv_r5 * qry + quad_mono * ry
    az = inv_r5 * qrz + quad_mono * rz
    valid = mask[None, :]
    return (
        jnp.sum(jnp.where(valid, ax, 0.0), axis=1),
        jnp.sum(jnp.where(valid, ay, 0.0), axis=1),
        jnp.sum(jnp.where(valid, az, 0.0), axis=1),
        jnp.sum(jnp.where(valid, phi, 0.0), axis=1),
    )


def p2p(tx, ty, tz, th, sx, sy, sz, sm, sh, mask):
    """Near-field particle-particle interaction, SPH-compatible softening.

    P2P (ryoanji/nbody/kernel.hpp:515): inside the combined smoothing
    length ``h_i + h_j`` the effective distance is clamped to it, which
    makes the force vanish linearly at r -> 0 (matching the reference's
    choice, not a Plummer profile). Shapes: targets (B,), sources (S,);
    returns (ax, ay, az, phi) each (B,).
    """
    dx = sx[None, :] - tx[:, None]  # (B, S), source minus target
    dy = sy[None, :] - ty[:, None]
    dz = sz[None, :] - tz[:, None]
    r2 = dx * dx + dy * dy + dz * dz
    h_ij = th[:, None] + sh[None, :]
    r2_eff = jnp.maximum(r2, h_ij * h_ij)
    inv_r = jnp.where(mask, jnp.maximum(r2_eff, 1e-30) ** -0.5, 0.0)
    inv_r3m = sm[None, :] * inv_r * inv_r * inv_r
    phi = -inv_r3m * r2
    return (
        jnp.sum(dx * inv_r3m, axis=1),
        jnp.sum(dy * inv_r3m, axis=1),
        jnp.sum(dz * inv_r3m, axis=1),
        jnp.sum(phi, axis=1),
    )
