from sphexa_tpu.gravity.tree import (
    GravityTree,
    GravityTreeMeta,
    build_gravity_tree,
)
from sphexa_tpu.gravity.traversal import (
    GravityConfig,
    compute_gravity,
    estimate_gravity_caps,
)
from sphexa_tpu.gravity.direct import direct_gravity

__all__ = [
    "GravityTree",
    "GravityTreeMeta",
    "build_gravity_tree",
    "GravityConfig",
    "compute_gravity",
    "estimate_gravity_caps",
    "direct_gravity",
]
