"""Mosaic bitmask+popcount-rank compaction for the gravity MAC lists.

The list-materialization primitive of the hierarchical MAC classification
(gravity/traversal.py, compaction="bitmask"): given each target block's
per-candidate CLASS array (0 = M2P, 1 = P2P, anything else = pruned), it
produces both fixed-cap index lists — the job the per-block packed 3-class
sort used to do at ~214 ms/1M (docs/NEXT.md round 5; sort *variants* all
measured identical, so the sort itself was the floor).

Kernel shape, patterned on sph/pallas_pairs.py's streaming engine:

- candidates stream through VMEM in 128-lane chunks (the input rides the
  grid pipeline, so chunk t is one sublane row of the block's (T, 128)
  tile — no manual DMA needed);
- per chunk and class, the lane bitmask is popcount-ranked: the exclusive
  prefix rank comes from ONE strict-lower-triangular (128,128)@(128,1)
  MXU product on the mask transposed to sublane-major (the transpose
  itself is a diag-embed + (128,128)@(128,1) product — Mosaic has no
  lane->sublane relayout primitive, the MXU is the shuffle engine);
- compaction is a one-hot (1,128)@(128,128) MXU product: column j of the
  one-hot picks the candidate whose rank equals j - fill (mod 128), so
  the running staging offset is folded into the gather — no dynamic lane
  roll anywhere;
- compacted lanes land in a 256-lane staging window; every time it fills
  past 128 lanes one ALIGNED sublane row is emitted to the output list
  (the same fill/emit scheme as the list-walk engine's staging buffer);
- chunks with zero set bits for a class skip all of the above behind one
  scalar test — the level-major node order clusters the accepted cut
  into a few contiguous level bands, so most chunks cost only the
  popcount.

Counts are accumulated UNCLIPPED, so a list overflowing its cap keeps
reporting the true high water and the driver's diagnostic/regrow contract
(Simulation._gravity_overflowed) keeps working; the written lists are the
first ``cap`` entries in candidate order — exactly the truncation the
3-class sort produced.

Values are carried in the low IDX_BITS of the packed int32 (class in the
bits above), and ride the MXU in f32 — exact for indices < 2^24, which
bounds the tree size this kernel accepts (~16.7M nodes; a 400^3 run's
~1.4M-node tree fits with room).
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

IDX_BITS = 24
IDX_MASK = (1 << IDX_BITS) - 1
# padding slots: class 2 = pruned/dead, value 0
DEAD = 2 << IDX_BITS


def _kernel(pk_ref, out0_ref, out1_ref, cnt_ref, stage_ref):
    T = pk_ref.shape[1]
    out_rows = (out0_ref.shape[1], out1_ref.shape[1])

    out0_ref[0] = jnp.zeros((out_rows[0], 128), jnp.int32)
    out1_ref[0] = jnp.zeros((out_rows[1], 128), jnp.int32)
    stage_ref[...] = jnp.zeros((2, 8, 256), jnp.float32)

    sub2 = jax.lax.broadcasted_iota(jnp.int32, (128, 128), 0)
    lan2 = jax.lax.broadcasted_iota(jnp.int32, (128, 128), 1)
    eye = (sub2 == lan2).astype(jnp.float32)
    # L[s, u] = 1 iff u < s: rank_excl[s] = sum_{u<s} mask[u]
    lt = (lan2 < sub2).astype(jnp.float32)
    ones_col = jnp.ones((128, 1), jnp.float32)
    lane1 = jax.lax.broadcasted_iota(jnp.int32, (1, 128), 1)
    out_refs = (out0_ref, out1_ref)

    def body(t, done):
        pk = pk_ref[0, pl.ds(t, 1), :]  # (1, 128)
        cls = pk >> IDX_BITS  # packed values are nonnegative
        val = (pk & IDX_MASK).astype(jnp.float32)
        new_done = []
        for k in (0, 1):
            maskf = (cls == k).astype(jnp.float32)
            cnt = jnp.sum(maskf).astype(jnp.int32)
            fill = done[k] % 128
            row = done[k] // 128

            @pl.when(cnt > 0)
            def _(k=k, maskf=maskf, fill=fill, cnt=cnt):
                # mask to sublane-major via diag-embed + MXU column product
                dcol = jnp.dot(jnp.broadcast_to(maskf, (128, 128)) * eye,
                               ones_col, preferred_element_type=jnp.float32)
                rcol = jnp.dot(lt, dcol,
                               preferred_element_type=jnp.float32)  # (128,1)
                # one-hot gather with the staging fill folded in: column j
                # takes the candidate of rank (j - fill) mod 128
                tgt = ((lan2 - fill + 128) & 127).astype(jnp.float32)
                onehot = jnp.where(rcol == tgt, dcol, 0.0)  # (128, 128)
                comp = jnp.dot(val, onehot,
                               preferred_element_type=jnp.float32)  # (1,128)
                m0 = (lane1 >= fill) & (lane1 < fill + cnt)
                m1 = lane1 < (fill + cnt - 128)
                stage_ref[k, 0:1, :128] = jnp.where(
                    m0, comp, stage_ref[k, 0:1, :128])
                stage_ref[k, 0:1, 128:] = jnp.where(
                    m1, comp, stage_ref[k, 0:1, 128:])

            emit = fill + cnt >= 128

            @pl.when(emit & (row < out_rows[k]))
            def _(k=k, row=row):
                out_refs[k][0, pl.ds(row, 1), :] = (
                    stage_ref[k, 0:1, :128].astype(jnp.int32))

            @pl.when(emit)
            def _(k=k):
                stage_ref[k, 0:1, :128] = stage_ref[k, 0:1, 128:]
                stage_ref[k, 0:1, 128:] = jnp.zeros((1, 128), jnp.float32)

            new_done.append(done[k] + cnt)
        return tuple(new_done)

    done = jax.lax.fori_loop(0, T, body, (jnp.int32(0), jnp.int32(0)))

    for k in (0, 1):
        row = done[k] // 128

        @pl.when((done[k] % 128 > 0) & (row < out_rows[k]))
        def _(k=k, row=row):
            out_refs[k][0, pl.ds(row, 1), :] = (
                stage_ref[k, 0:1, :128].astype(jnp.int32))

    cnt_ref[0] = jnp.where(
        lane1 == 0, done[0], jnp.where(lane1 == 1, done[1], 0))


@functools.partial(jax.jit, static_argnames=("cap0", "cap1", "interpret"))
def compact_class_lists(packed, cap0: int, cap1: int,
                        interpret: bool = False):
    """Compact each row's class-0 and class-1 slots into fixed-cap lists.

    ``packed``: (B, C) int32, ``(cls << IDX_BITS) | value`` with value in
    [0, 2^IDX_BITS); cls 0/1 select the two lists, anything else is
    dropped. Returns ``(list0 (B, cap0) i32, n0 (B,) i32, list1 (B, cap1)
    i32, n1 (B,) i32)`` — values in candidate order, UNCLIPPED true counts
    (entries beyond a cap are truncated; slots beyond a count are 0 and
    must be masked by the caller).
    """
    B, C = packed.shape
    T = max(1, -(-C // 128))
    if T * 128 > C:
        packed = jnp.concatenate(
            [packed, jnp.full((B, T * 128 - C), DEAD, jnp.int32)], axis=1
        )
    pk = packed.reshape(B, T, 128)
    r0 = max(1, -(-cap0 // 128))
    r1 = max(1, -(-cap1 // 128))
    outs = pl.pallas_call(
        _kernel,
        grid=(B,),
        in_specs=[pl.BlockSpec((1, T, 128), lambda b: (b, 0, 0))],
        out_specs=[
            pl.BlockSpec((1, r0, 128), lambda b: (b, 0, 0)),
            pl.BlockSpec((1, r1, 128), lambda b: (b, 0, 0)),
            pl.BlockSpec((1, 1, 128), lambda b: (b, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, r0, 128), jnp.int32),
            jax.ShapeDtypeStruct((B, r1, 128), jnp.int32),
            jax.ShapeDtypeStruct((B, 1, 128), jnp.int32),
        ],
        scratch_shapes=[pltpu.VMEM((2, 8, 256), jnp.float32)],
        interpret=interpret,
    )(pk)
    list0 = outs[0].reshape(B, r0 * 128)[:, :cap0]
    list1 = outs[1].reshape(B, r1 * 128)[:, :cap1]
    return list0, outs[2][:, 0, 0], list1, outs[2][:, 0, 1]
