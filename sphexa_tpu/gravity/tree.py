"""Host-side linked octree construction for the gravity solver.

TPU-native counterpart of the reference's internal-octree linkage
(cstone/tree/octree.hpp:132 linkTreeCpu: prefixes, childOffsets, parents,
levelRange). Instead of child offsets + explicit traversal, the structure
here is a *level-major node array* with a parent index per node — exactly
what a vectorized upsweep (scatter-add child->parent per level) and a
batched downsweep (gather parent->child per level) need.

The build runs on host (numpy) at configuration granularity, like the
cell-list grid: node *structure* is static between reconfigurations while
all node *payload* (masses, centers-of-mass, multipoles) is recomputed on
device every step from the current particle arrays, so a stale structure
costs only balance, never correctness (leaf occupancy overflow is guarded
by a diagnostic, mirroring the reference's GPU stack-overflow detection,
gravity_wrapper.hpp:120).

Node geometry is stored as box-relative fractions so the traced Box can
grow (open boundaries) without invalidating the host structure.
"""

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from sphexa_tpu.dtypes import KEY_BITS
from sphexa_tpu.sfc.hilbert import hilbert_decode
from sphexa_tpu.sfc.morton import morton_decode
from sphexa_tpu.tree.csarray import KEY_RANGE, compute_octree, node_levels


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class GravityTree:
    """Device arrays describing the linked octree (level-major node order)."""

    leaf_keys: jax.Array  # (L+1,) uint32 cornerstone leaf boundaries
    parent: jax.Array  # (N,) int32, parent node index (root: 0)
    is_leaf: jax.Array  # (N,) bool
    leaf_of_node: jax.Array  # (N,) int32 leaf index, or 0 for internal (mask!)
    node_of_leaf: jax.Array  # (L,) int32
    center_frac: jax.Array  # (N, 3) float32 box-relative geometric center
    halfsize_frac: jax.Array  # (N,) float32 box-relative half edge length


@dataclasses.dataclass(frozen=True)
class GravityTreeMeta:
    """Static (hashable) structure metadata selecting the compiled code."""

    num_leaves: int
    num_nodes: int
    # (start, end) node-index range per level, root level first
    level_ranges: Tuple[Tuple[int, int], ...]


def build_gravity_tree(
    sorted_keys, bucket_size: int, curve: str = "hilbert"
) -> Tuple[GravityTree, GravityTreeMeta]:
    """Build the cornerstone leaf array + internal linkage from host keys.

    Counterpart of computeOctree (csarray.hpp:456) followed by
    updateInternalTree (octree.hpp). SFC octants are cubes at every level
    for both Morton and Hilbert curves, so a node's geometry follows from
    decoding its range-start key and truncating to its level.
    """
    keys = np.asarray(sorted_keys, dtype=np.uint64)
    leaf_tree, _counts = compute_octree(keys, bucket_size)
    return linkage_from_leaves(leaf_tree, curve)


def linkage_from_leaves(
    leaf_tree, curve: str = "hilbert"
) -> Tuple[GravityTree, GravityTreeMeta]:
    """Internal linkage + geometry from a prebuilt cornerstone leaf array
    (updateInternalTree, octree.hpp role). Callers that never materialize
    the global key array on the host — the distributed histogram-pyramid
    build (parallel/sizing.py, the update_mpi.hpp transposition) — enter
    here with their leaf boundaries."""
    leaf_tree = np.asarray(leaf_tree, dtype=np.uint64)
    leaf_levels = node_levels(leaf_tree)
    leaf_starts = leaf_tree[:-1]
    num_leaves = len(leaf_starts)
    max_level = int(leaf_levels.max()) if num_leaves > 1 else 0

    # node set per level: leaves at that level + ancestors of deeper leaves
    per_level = []
    for lvl in range(max_level + 1):
        span = KEY_RANGE >> np.uint64(3 * lvl)
        here = leaf_starts[leaf_levels == lvl]
        deeper = leaf_starts[leaf_levels > lvl]
        anc = np.unique((deeper // span) * span) if len(deeper) else deeper
        per_level.append(np.unique(np.concatenate([here, anc])))

    level_offsets = np.concatenate([[0], np.cumsum([len(p) for p in per_level])])
    num_nodes = int(level_offsets[-1])
    node_key = np.concatenate(per_level)
    node_level = np.concatenate(
        [np.full(len(p), lvl, dtype=np.int64) for lvl, p in enumerate(per_level)]
    )

    # parent: truncate key to the parent level's span, binary-search that level
    parent = np.zeros(num_nodes, dtype=np.int32)
    for lvl in range(1, max_level + 1):
        s, e = level_offsets[lvl], level_offsets[lvl + 1]
        pspan = KEY_RANGE >> np.uint64(3 * (lvl - 1))
        pkeys = (node_key[s:e] // pspan) * pspan
        pos = np.searchsorted(per_level[lvl - 1], pkeys)
        parent[s:e] = level_offsets[lvl - 1] + pos

    # leaf identification: a node is the leaf with the same start iff levels match
    leaf_pos = np.searchsorted(leaf_starts, node_key)
    leaf_pos = np.clip(leaf_pos, 0, num_leaves - 1)
    is_leaf = (leaf_starts[leaf_pos] == node_key) & (leaf_levels[leaf_pos] == node_level)
    leaf_of_node = np.where(is_leaf, leaf_pos, 0).astype(np.int32)
    node_of_leaf = np.zeros(num_leaves, dtype=np.int32)
    node_of_leaf[leaf_of_node[is_leaf]] = np.flatnonzero(is_leaf)

    # geometry: decode range-start key at full depth, truncate to node level
    decode = hilbert_decode if curve == "hilbert" else morton_decode
    ix, iy, iz = decode(jnp.asarray(node_key.astype(np.uint32)))
    cells = np.stack([np.asarray(ix), np.asarray(iy), np.asarray(iz)], axis=1)
    shift = (KEY_BITS - node_level)[:, None]
    octant = cells >> shift
    inv = 1.0 / (1 << node_level).astype(np.float64)
    center_frac = ((octant + 0.5) * inv[:, None]).astype(np.float32)
    halfsize_frac = (0.5 * inv).astype(np.float32)

    tree = GravityTree(
        leaf_keys=jnp.asarray(leaf_tree.astype(np.uint32)),
        parent=jnp.asarray(parent),
        is_leaf=jnp.asarray(is_leaf),
        leaf_of_node=jnp.asarray(leaf_of_node),
        node_of_leaf=jnp.asarray(node_of_leaf),
        center_frac=jnp.asarray(center_frac),
        halfsize_frac=jnp.asarray(halfsize_frac),
    )
    meta = GravityTreeMeta(
        num_leaves=num_leaves,
        num_nodes=num_nodes,
        level_ranges=tuple(
            (int(level_offsets[l]), int(level_offsets[l + 1]))
            for l in range(max_level + 1)
        ),
    )
    return tree, meta
