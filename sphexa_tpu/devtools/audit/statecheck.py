"""statecheck: symbolic state-schema lock + vmap-batchability report.

    sphexa-audit schema [targets] [--lock F] [--diff] [--write]
                        [--vmap] [--entries ...] [--json]

The sixth static-analysis layer (docs/STATIC_ANALYSIS.md): where jaxdiff
locks what each entry's program IS, statecheck locks what each entry's
program RETURNS — the carry/output schema the ensemble mode (ROADMAP
item 3) depends on. For every registered audit entry the output pytree
is flattened to per-leaf rows: path, dtype, weak_type, and each axis as
a linear polynomial in the particle count N, fitted exactly (rational
arithmetic, no tolerance) from the entry's existing two-point ``grow``
probe — the JXA204 byte-growth probe generalized to per-leaf symbolic
shapes. ``const`` axes don't scale, ``extensive`` axes are a·N,
``affine`` axes are a·N+b, and anything else (capacity-padded pow2
working sets, O(tree) arrays) stays ``data`` with both observed sizes.
The rows for the whole registry live in the committed
``STATE_SCHEMA.json``; drift exits 1 with a per-leaf structural diff and
is re-locked with ``--write`` after review.

``--vmap`` adds the JXA502 batchability report: each single-device
entry is traced under ``jax.vmap`` over a synthetic member axis and
every construct that breaks or degrades batching is reported as a
finding, not a crash — trace-time failures captured per entry, host
callbacks in the vmapped body, and batched ops falling back to
serialized while/scan loops. A non-batchable entry carries an explicit
inline waiver (``# jaxaudit: disable=JXA502 -- reason``) or fails the
gate: the ensemble mode's admission check is static.

jax-free at import (the lint layer's own hygiene rule); every expensive
artifact is cached on the shared ``EntryTrace``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from fractions import Fraction
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple

__all__ = [
    "SCHEMA_VERSION",
    "DEFAULT_SCHEMA_PATH",
    "LockError",
    "entry_schema",
    "vmap_probe",
    "load_lock",
    "write_lock",
    "schema_diff",
    "format_axes",
    "main",
]

SCHEMA_VERSION = 1
DEFAULT_SCHEMA_PATH = "STATE_SCHEMA.json"

#: leaf-change rows rendered per entry in the text diff
_DIFF_LIMIT = 12


class LockError(ValueError):
    """Unreadable/corrupt/wrong-version schema lock (CLI exit 2)."""


# ---------------------------------------------------------------------------
# symbolic schema inference
# ---------------------------------------------------------------------------


def _slab_rows(jaxpr) -> int:
    """Largest leading dim over entry invars — the same N anchor JXA204
    and the JXA2xx spmd report key their slab arithmetic on."""
    s = 0
    for v in jaxpr.invars:
        shape = getattr(v.aval, "shape", ())
        if shape:
            s = max(s, int(shape[0]))
    return s


def _fit_axes(dims1, dims2, n1: int, n2: int) -> List[Dict[str, Any]]:
    """Per-axis linear polynomial in N from the two probe points,
    fitted EXACTLY in rational arithmetic: d(N) = a·N + b with a, b
    recovered from (n1, d1), (n2, d2). No tolerance — an axis either
    is a polynomial in N or it is ``data`` (both observations kept)."""
    axes: List[Dict[str, Any]] = []
    for d1, d2 in zip(dims1, dims2):
        d1, d2 = int(d1), int(d2)
        if d1 == d2:
            axes.append({"kind": "const", "dim": d1})
            continue
        a = Fraction(d2 - d1, n2 - n1)
        b = Fraction(d1) - a * n1
        if b == 0:
            axes.append({"kind": "extensive", "per_n": str(a)})
        elif b.denominator == 1 and a > 0:
            axes.append({"kind": "affine", "per_n": str(a),
                         "offset": int(b)})
        else:
            axes.append({"kind": "data", "observed": [d1, d2]})
    return axes


def format_axes(axes) -> str:
    """Human form of a shape row: ``f32[N, 3]``-style axis list."""
    parts = []
    for ax in axes:
        kind = ax.get("kind")
        if kind == "const":
            parts.append(str(ax["dim"]))
        elif kind == "extensive":
            a = ax["per_n"]
            parts.append("N" if a == "1" else f"{a}N")
        elif kind == "affine":
            off = int(ax["offset"])
            a = ax["per_n"]
            head = "N" if a == "1" else f"{a}N"
            parts.append(f"{head}{off:+d}")
        else:
            lo, hi = ax.get("observed", ["?", "?"])
            parts.append(f"data({lo}..{hi})")
    return "[" + ", ".join(parts) + "]"


def _fmt_leaf(leaf: Dict[str, Any]) -> str:
    return (f"{leaf.get('dtype')}{format_axes(leaf.get('shape', []))}"
            + (" weak" if leaf.get("weak_type") else ""))


def _flat_leaves(trace) -> List[Tuple[str, Any, bool]]:
    """[(path, ShapeDtypeStruct, weak_type)] over the entry's output
    pytree — the out_shape tree and the jaxpr's out_avals share one
    trace and one flatten order, so weak_type zips on exactly."""
    import jax

    leaves = jax.tree_util.tree_flatten_with_path(trace.out_shape)[0]
    avals = trace.closed_jaxpr.out_avals
    return [
        (jax.tree_util.keystr(path), leaf,
         bool(getattr(aval, "weak_type", False)))
        for (path, leaf), aval in zip(leaves, avals)
    ]


def entry_schema(trace) -> Dict[str, Any]:
    """Cached symbolic output schema of one entry (the lock row): pytree
    paths, dtype, weak_type, and each axis as a polynomial in N. Shares
    the EntryTrace's single ``return_shape`` trace; the grown probe is
    traced once and only for entries that declare ``case.grow``."""
    cached = getattr(trace, "_schema", None)
    if cached is not None:
        return cached
    from sphexa_tpu.devtools.audit.core import EntryTrace, audit_context

    base = _flat_leaves(trace)
    n1 = _slab_rows(trace.closed_jaxpr.jaxpr)
    row: Dict[str, Any] = {
        "mesh": audit_context().mesh_size,
        "n_base": n1 or None,
        "grow": None,
        "leaves": {},
    }
    grown = None
    n2 = 0
    if trace.case.grow is not None and n1:
        grown_case, _ratio = trace.case.grow()
        gtrace = EntryTrace(trace.entry, grown_case)
        grown = _flat_leaves(gtrace)
        n2 = _slab_rows(gtrace.closed_jaxpr.jaxpr)
        if len(grown) != len(base) or n2 == n1:
            raise ValueError(
                f"entry {trace.entry.name}: grow probe changed the output "
                f"STRUCTURE ({len(base)} -> {len(grown)} leaves at "
                f"N {n1} -> {n2}) — the schema is not well-defined")
        row["grow"] = str(Fraction(n2, n1))
    for i, (path, leaf, weak) in enumerate(base):
        if grown is not None:
            gpath, gleaf, _gw = grown[i]
            if gpath != path or len(gleaf.shape) != len(leaf.shape):
                raise ValueError(
                    f"entry {trace.entry.name}: leaf {path} changed "
                    f"path/rank across the grow probe")
            axes = _fit_axes(leaf.shape, gleaf.shape, n1, n2)
        else:
            axes = [{"kind": "const", "dim": int(d)} for d in leaf.shape]
        row["leaves"][path] = {
            "dtype": str(leaf.dtype),
            "weak_type": weak,
            "shape": axes,
        }
    trace._schema = row
    return row


# ---------------------------------------------------------------------------
# vmap-batchability probe (JXA502's shared analysis)
# ---------------------------------------------------------------------------


def _is_callback_prim(name: str) -> bool:
    return "callback" in name or name in ("infeed", "outfeed")


def _loop_count(closed) -> int:
    from sphexa_tpu.devtools.audit.core import subjaxprs

    return sum(
        1 for eqn in subjaxprs(closed.jaxpr)
        if eqn.primitive.name in ("while", "scan")
    )


def vmap_probe(trace, members: int) -> Dict[str, Any]:
    """Trace the entry under ``jax.vmap`` over a leading member axis of
    width ``members`` (abstract args — no member batch is materialized)
    and report what happens to batching. Cached per EntryTrace."""
    cached = getattr(trace, "_vmap", None)
    if cached is not None and cached.get("members") == members:
        return cached
    import jax
    from jax.api_util import shaped_abstractify

    def member_struct(leaf):
        aval = shaped_abstractify(leaf)
        return jax.ShapeDtypeStruct((members,) + tuple(aval.shape),
                                    aval.dtype)

    report: Dict[str, Any] = {
        "members": members,
        "error": None,
        "callbacks": [],
        "base_loops": _loop_count(trace.closed_jaxpr),
        "vmap_loops": 0,
    }
    batched_args = jax.tree.map(member_struct, trace.case.args)
    try:
        with trace._x64_scope():
            closed = jax.make_jaxpr(jax.vmap(trace.case.fn))(*batched_args)
    except Exception as e:  # noqa: BLE001 - captured as a finding
        report["error"] = f"{e.__class__.__name__}: {e}"
        trace._vmap = report
        return report
    from sphexa_tpu.devtools.audit.core import subjaxprs

    callbacks: Dict[str, int] = {}
    for eqn in subjaxprs(closed.jaxpr):
        if _is_callback_prim(eqn.primitive.name):
            callbacks[eqn.primitive.name] = \
                callbacks.get(eqn.primitive.name, 0) + 1
    report["callbacks"] = sorted(callbacks.items())
    report["vmap_loops"] = _loop_count(closed)
    trace._vmap = report
    return report


# ---------------------------------------------------------------------------
# lock IO (the lowerdiff contract: version, corrupt -> LockError -> exit 2)
# ---------------------------------------------------------------------------


def load_lock(path) -> Dict[str, Dict[str, Any]]:
    p = Path(path)
    try:
        payload = json.loads(p.read_text())
    except OSError as e:
        raise LockError(f"cannot read schema lock {p}: {e}") from e
    except json.JSONDecodeError as e:
        raise LockError(f"corrupt schema lock {p}: {e}") from e
    if not isinstance(payload, dict) or "entries" not in payload:
        raise LockError(f"corrupt schema lock {p}: no 'entries' object")
    if payload.get("version") != SCHEMA_VERSION:
        raise LockError(
            f"schema lock {p} has version {payload.get('version')!r}, this "
            f"tool writes {SCHEMA_VERSION} (regenerate with --write)")
    return payload["entries"]


def write_lock(path, entries: Dict[str, Dict[str, Any]]) -> None:
    p = Path(path)
    payload = {
        "version": SCHEMA_VERSION,
        "tool": "statecheck",
        "comment": "symbolic carry/output schema per audit entry (axis "
                   "polynomials in N from the two-point grow probe); "
                   "regenerate with: sphexa-audit schema --write",
        "entries": {k: entries[k] for k in sorted(entries)},
    }
    p.write_text(json.dumps(payload, indent=1, sort_keys=True) + "\n")


# ---------------------------------------------------------------------------
# structural diff
# ---------------------------------------------------------------------------


def schema_diff(name: str, locked: Dict[str, Any], current: Dict[str, Any],
                verbose: bool = False) -> List[str]:
    """Reviewable per-leaf diff of a drifted schema row — the PR
    artifact, so a relock is reviewed as added/removed/changed leaves,
    never as an opaque digest flip."""
    lines = [f"entry {name}: state schema drifted vs lock"]
    lo = locked.get("leaves", {})
    cu = current.get("leaves", {})
    added = sorted(set(cu) - set(lo))
    removed = sorted(set(lo) - set(cu))
    changed = sorted(p for p in set(lo) & set(cu) if lo[p] != cu[p])
    for meta in ("mesh", "n_base", "grow"):
        if locked.get(meta) != current.get(meta):
            lines.append(f"  {meta}: {locked.get(meta)} -> "
                         f"{current.get(meta)}")
    limit = len(added) + len(removed) + len(changed) if verbose \
        else _DIFF_LIMIT
    rows = ([("+", p, None, cu[p]) for p in added]
            + [("-", p, lo[p], None) for p in removed]
            + [("~", p, lo[p], cu[p]) for p in changed])
    for mark, p, old, new in rows[:limit]:
        if mark == "+":
            lines.append(f"  + {p}: {_fmt_leaf(new)}")
        elif mark == "-":
            lines.append(f"  - {p}: {_fmt_leaf(old)}")
        else:
            lines.append(f"  ~ {p}: {_fmt_leaf(old)} -> {_fmt_leaf(new)}")
    if len(rows) > limit:
        lines.append(f"  ... {len(rows) - limit} more leaf change(s) "
                     f"(--diff for all)")
    lines.append(f"  summary: +{len(added)} -{len(removed)} ~{len(changed)} "
                 f"leaves (locked {len(lo)}, current {len(cu)})")
    return lines


def _delta_summary(locked: Dict[str, Any], current: Dict[str, Any]
                   ) -> Dict[str, Any]:
    lo = locked.get("leaves", {})
    cu = current.get("leaves", {})
    return {
        "added": sorted(set(cu) - set(lo)),
        "removed": sorted(set(lo) - set(cu)),
        "changed": sorted(p for p in set(lo) & set(cu) if lo[p] != cu[p]),
    }


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="sphexa-audit schema",
        description="statecheck: verify every registered entry's symbolic "
                    "carry/output schema (pytree paths, dtype, weak_type, "
                    "axis polynomials in N) against the committed "
                    "STATE_SCHEMA.json; mismatches exit 1 with a per-leaf "
                    "structural diff. Re-lock an intentional change with "
                    "--write. --vmap adds the JXA502 member-axis "
                    "batchability report.",
    )
    ap.add_argument("targets", nargs="*", default=["sphexa_tpu"],
                    help="registry modules (default: the package registry)")
    ap.add_argument("--lock", default=DEFAULT_SCHEMA_PATH, metavar="FILE",
                    help=f"schema lock file (default: {DEFAULT_SCHEMA_PATH})")
    ap.add_argument("--write", action="store_true",
                    help="rewrite the lock from the current schemas (merges "
                         "over rows of entries not audited in this run) "
                         "and exit 0")
    ap.add_argument("--diff", action="store_true",
                    help="print EVERY leaf change of each drifted entry "
                         "(default: first %d)" % _DIFF_LIMIT)
    ap.add_argument("--vmap", action="store_true",
                    help="also trace each single-device entry under "
                         "jax.vmap over a member axis and report "
                         "batchability breaks as JXA502 findings")
    ap.add_argument("--members", type=int, default=2, metavar="M",
                    help="member-axis width for --vmap (default: 2)")
    ap.add_argument("--entries", metavar="NAMES",
                    help="comma-separated entry names (default: all; "
                         "staleness of lock rows is only checked on "
                         "full-registry runs)")
    ap.add_argument("--json", action="store_true",
                    help="emit the machine-readable payload instead of "
                         "the text report")
    ap.add_argument("--cpu-devices", type=int,
                    default=int(os.environ.get("SPHEXA_AUDIT_DEVICES", "2")),
                    metavar="N",
                    help="bootstrap an N-virtual-device CPU backend so "
                         "sharded entries trace (default: "
                         "$SPHEXA_AUDIT_DEVICES or 2; 0 = ambient "
                         "backend). The committed lock is written at "
                         "the default mesh.")
    return ap


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)

    if args.cpu_devices and args.cpu_devices > 0:
        from sphexa_tpu.util.cpu_mesh import force_cpu_mesh

        try:
            force_cpu_mesh(args.cpu_devices)
        except RuntimeError as e:
            print(f"sphexa-audit schema: note: CPU-mesh bootstrap "
                  f"skipped ({e})", file=sys.stderr)

    import dataclasses as _dc

    from sphexa_tpu.devtools.audit.cli import _load_target
    from sphexa_tpu.devtools.audit.core import (
        Auditor,
        EntrySkip,
        EntryTrace,
        audit_context,
        entries_from_namespace,
        set_audit_context,
    )

    ctx = audit_context()
    if args.cpu_devices > 2:
        ctx = _dc.replace(ctx, mesh_size=args.cpu_devices)
    if args.vmap:
        ctx = _dc.replace(ctx, vmap_members=max(args.members, 1))
    ctx = _dc.replace(ctx, state_schema_path=args.lock)
    prev = set_audit_context(ctx)
    try:
        entries = []
        for target in args.targets:
            try:
                mod = _load_target(target)
            except (ImportError, OSError, SyntaxError) as e:
                print(f"sphexa-audit schema: cannot load target "
                      f"{target!r}: {e}", file=sys.stderr)
                return 2
            entries += entries_from_namespace(vars(mod))
        filtered = bool(args.entries)
        if filtered:
            want = {s.strip() for s in args.entries.split(",") if s.strip()}
            unknown = want - {e.name for e in entries}
            if unknown:
                print(f"sphexa-audit schema: unknown entry name(s): "
                      f"{sorted(unknown)}", file=sys.stderr)
                return 2
            entries = [e for e in entries if e.name in want]

        locked: Dict[str, Dict[str, Any]] = {}
        if not args.write or Path(args.lock).exists():
            try:
                locked = load_lock(args.lock)
            except LockError as e:
                if args.write and not Path(args.lock).exists():
                    locked = {}
                else:
                    print(f"sphexa-audit schema: {e}", file=sys.stderr)
                    return 2

        # the carry-closure and (under --vmap) batchability rules run on
        # the SAME traces as the schema rows; JXA501 itself is the lock
        # compare below, so it is not re-run here
        select = ["JXA503"] + (["JXA502"] if args.vmap else [])
        auditor = Auditor(select=select)

        current: Dict[str, Dict[str, Any]] = {}
        findings: List[Any] = []
        suppressed: List[Any] = []
        vmap_reports: Dict[str, Any] = {}
        errors: List[str] = []
        skipped: List[str] = []
        for entry in entries:
            try:
                case = entry.build()
            except EntrySkip as e:
                skipped.append(f"{entry.name}: {e}")
                continue
            except Exception as e:  # noqa: BLE001 - reported, exit 1
                errors.append(f"{entry.name}: {e.__class__.__name__}: {e}")
                continue
            trace = EntryTrace(entry, case)
            try:
                current[entry.name] = entry_schema(trace)
            except Exception as e:  # noqa: BLE001 - reported, exit 1
                errors.append(f"{entry.name}: {e.__class__.__name__}: {e}")
                continue
            table = auditor._suppression_table(entry.path)
            for rule in auditor.rules.values():
                try:
                    found = rule.check(trace)
                except Exception as e:  # noqa: BLE001 - reported, exit 1
                    errors.append(f"{entry.name}: {rule.id} crashed: "
                                  f"{e.__class__.__name__}: {e}")
                    continue
                for f in found:
                    (suppressed if table.is_suppressed(f.rule, f.line)
                     else findings).append(f)
            if args.vmap and not entry.mesh_axes:
                vmap_reports[entry.name] = vmap_probe(
                    trace, max(args.members, 1))

        if args.write:
            merged = dict(locked)
            merged.update(current)
            write_lock(args.lock, merged)
            print(f"sphexa-audit schema: wrote {len(current)} schema "
                  f"row(s) to {args.lock} ({len(merged)} total)")
            for note in skipped:
                print(f"sphexa-audit schema: skipped {note}",
                      file=sys.stderr)
            return 1 if errors else 0

        mismatched: List[str] = []
        missing: List[str] = []
        stale: List[str] = []
        mesh_skipped: List[str] = []
        report: List[str] = []
        payload: List[Dict[str, Any]] = []
        for name, row in current.items():
            lrow = locked.get(name)
            if lrow is None:
                missing.append(name)
                payload.append({"entry": name, "match": False,
                                "locked": False, "deltas": None})
                continue
            if lrow.get("mesh") != row.get("mesh"):
                # a row locked at another mesh size is neither stale nor
                # drifted — sharded shapes legitimately depend on P
                mesh_skipped.append(
                    f"{name}: locked at mesh={lrow.get('mesh')}, "
                    f"running mesh={row.get('mesh')}")
                payload.append({"entry": name, "match": None,
                                "locked": True, "deltas": None})
                continue
            match = lrow == row
            payload.append({
                "entry": name, "match": match, "locked": True,
                "leaves": len(row.get("leaves", {})),
                "deltas": None if match else _delta_summary(lrow, row),
            })
            if not match:
                mismatched.append(name)
                report += schema_diff(name, lrow, row, verbose=args.diff)
        if not filtered:
            audited = set(current) | {s.split(":", 1)[0] for s in skipped}
            stale = sorted(set(locked) - audited)

        bad = bool(mismatched or missing or stale or errors or findings)
        if args.json:
            print(json.dumps({
                "tool": "statecheck",
                "lock": str(args.lock),
                "entries": payload,
                "mismatched": sorted(mismatched),
                "missing_from_lock": sorted(missing),
                "stale_lock_rows": stale,
                "mesh_skipped": mesh_skipped,
                "findings": [f.to_json() for f in findings],
                "suppressed": [f.to_json() for f in suppressed],
                "vmap": vmap_reports,
                "errors": errors,
                "skipped": skipped,
            }, indent=2, sort_keys=True))
            return 1 if bad else 0

        for note in skipped:
            print(f"sphexa-audit schema: skipped {note}", file=sys.stderr)
        for note in mesh_skipped:
            print(f"sphexa-audit schema: mesh-skipped {note}",
                  file=sys.stderr)
        for line in report:
            print(line)
        for name in missing:
            print(f"entry {name}: not in the schema lock (re-lock with "
                  f"--write)")
        for name in stale:
            print(f"lock row {name}: no such registry entry (stale — "
                  f"re-lock with --write)")
        for f in findings:
            print(f.format())
        for err in errors:
            print(f"entry error: {err}", file=sys.stderr)
        if args.vmap:
            clean = sorted(n for n, r in vmap_reports.items()
                           if not r["error"] and not r["callbacks"]
                           and r["vmap_loops"] <= r["base_loops"])
            print(f"vmap report: {len(clean)}/{len(vmap_reports)} "
                  f"single-device entries batch clean over "
                  f"{max(args.members, 1)} members")
        ok = len(current) - len(mismatched) - len(missing) \
            - len(mesh_skipped)
        print(f"sphexa-audit schema: {ok}/{len(current)} entries match "
              f"{args.lock}"
              + (f"; {len(mismatched)} drifted" if mismatched else "")
              + (f"; {len(missing)} unlocked" if missing else "")
              + (f"; {len(stale)} stale" if stale else "")
              + (f"; {len(findings)} finding(s)" if findings else "")
              + (f"; {len(suppressed)} suppressed" if suppressed else "")
              + (f"; {len(errors)} errors" if errors else ""))
        return 1 if bad else 0
    finally:
        set_audit_context(prev)


if __name__ == "__main__":
    sys.exit(main())
