"""jaxaudit core: entry-point model, trace cache, rule registry, runner.

Where jaxlint reads SOURCE (ast, never imports the code), jaxaudit reads
what the TRACER produces: it imports the package, traces each registered
entry point on small synthetic example args, and checks invariants on the
resulting jaxpr / lowered module. The two layers are complementary — an
AST pass structurally cannot see a silent f64 promotion inside a jitted
step, a missed buffer donation, a constant baked into the jaxpr, or a
step-2 retrace; the tracer sees exactly those.

Model
-----
- An ``EntryPoint`` is a *declaration*: a name, audit metadata (declared
  donation, declared mesh axes, const-size budget), and a lazy ``build``
  callable returning an ``EntryCase`` with the traced function + example
  args. Building is lazy so importing a registry module stays cheap and
  device-free (the same hygiene jaxlint enforces on the package).
- ``EntryTrace`` caches everything expensive per entry — the closed
  jaxpr, the lowering, the executed output for the recompile carry — so
  each rule pays only for what it reads and nothing is traced twice.
- Rules are ``check(trace) -> [Finding]`` callables registered under JXA
  ids, mirroring the lint rule registry. Findings anchor at the entry's
  *registration site* (the decorated builder in the registry module), so
  the shared inline-suppression grammar applies:
  ``# jaxaudit: disable=JXA103 -- reason`` on or directly above the
  ``@entrypoint`` line.

``JXA000`` is reserved for entries whose build or trace raises — a broken
registry entry can never silently shrink coverage.
"""

from __future__ import annotations

import dataclasses
import traceback
from pathlib import Path
from typing import (
    Any,
    Callable,
    Dict,
    Iterable,
    List,
    Optional,
    Sequence,
    Tuple,
)

from sphexa_tpu.devtools.common import (
    Finding,
    SuppressionTable,
    make_disable_re,
    parse_suppressions,
)

__all__ = [
    "AuditContext",
    "audit_context",
    "set_audit_context",
    "EntryCase",
    "EntryPoint",
    "EntryTrace",
    "EntrySkip",
    "entrypoint",
    "entries_from_namespace",
    "Rule",
    "register",
    "all_rules",
    "Auditor",
    "subjaxprs",
    "all_closed_jaxprs",
]

_DISABLE_RE = make_disable_re("jaxaudit")


@dataclasses.dataclass(frozen=True)
class AuditContext:
    """Process-wide knobs the SPMD (JXA2xx) rules and the registry read.

    ``mesh_size`` is the virtual CPU mesh the sharded registry entries
    trace on (the CLI's --cpu-devices / preflight's --mesh); the
    campaign fields parameterize JXA202's symbolic rescale (per-device
    slab = campaign_n / campaign_devices) and the per-device HBM gate.
    """

    mesh_size: int = 2
    campaign_n: int = 64_000_000
    campaign_devices: int = 16
    hbm_budget_bytes: int = 16 << 30          # v5e: 16 GiB HBM per chip
    repl_threshold_bytes: int = 1 << 20       # campaign-scale replication gate
    # --- jaxcost (JXA3xx / JXA204) knobs ---------------------------------
    # device model the cost rules predict against (devices.py)
    cost_device: str = "v5e"
    # JXA301 default: minimum attributed-FLOP share per entry (per-entry
    # phase_coverage_min overrides; the step builders sit near 1.0)
    phase_coverage_min: float = 0.7
    # JXA302 default budget file (repo-root committed); an entry may pin
    # its own via EntryPoint.cost_budget_file. A missing DEFAULT file
    # skips the gate (out-of-repo use); a missing DECLARED file fails.
    cost_budget_path: str = "COST_BUDGET.json"
    # JXA204: growth-probe slack over linear-in-N for the exempt
    # (non-slab) buffer class
    tree_growth_slack: float = 1.25
    # --- statecheck (JXA5xx) knobs ---------------------------------------
    # JXA501 default schema lock (repo-root committed, like the cost
    # budget); a missing DEFAULT file skips the gate (out-of-repo use)
    state_schema_path: str = "STATE_SCHEMA.json"
    # JXA502 member-axis width for the vmap-batchability probe; 0
    # disables the probe (the package audit/tier-1 default — the vmap
    # report is the `sphexa-audit schema --vmap` gate's job)
    vmap_members: int = 0


_CONTEXT = AuditContext()


def audit_context() -> AuditContext:
    return _CONTEXT


def set_audit_context(ctx: AuditContext) -> AuditContext:
    """Install a new context; returns the previous one (for restore)."""
    global _CONTEXT
    prev = _CONTEXT
    _CONTEXT = ctx
    return prev


class EntrySkip(Exception):
    """Raised by a builder when its environment prerequisites are absent
    (e.g. a sharded entry on a single-device host). Skips are REPORTED,
    not errors — but the tier-1 gate asserts none occur under the test
    mesh, so coverage can't rot silently."""


@dataclasses.dataclass
class EntryCase:
    """The concrete traced case an entry's builder produces.

    ``fn`` takes ONLY traced arguments (close over static configs in the
    builder) so ``jax.make_jaxpr(fn)(*args)`` works directly. ``lower``
    is the AOT lowering thunk for the donation audit — for jitted
    functions return ``jitted.lower(*full_args)`` of the variant the hot
    path actually uses (the donated twin where one exists). ``carry``
    rebuilds step-2 args from (step-1 args, step-1 outputs) for the
    recompile audit; it must only REARRANGE pytree leaves.
    """

    fn: Callable
    args: Tuple[Any, ...]
    lower: Optional[Callable[[], Any]] = None
    carry: Optional[Callable[[Tuple[Any, ...], Any], Tuple[Any, ...]]] = None
    # optional weak-type probe: a variant of ``args`` with host-fed
    # scalars (Python floats where the public API tolerates either);
    # the traced OUTPUT signature must match the canonical one
    perturb: Optional[Callable[[Tuple[Any, ...]], Tuple[Any, ...]]] = None
    # JXA203 volume gate: the analytic cross-shard bytes/step this case
    # is expected to ship (sizing.sparse_need_matrix / shipped_rows
    # derived); None = no volume check for this entry
    exchange_budget_bytes: Optional[int] = None
    # slack factor on the volume gate (negotiation/metrics overhead)
    exchange_slack: float = 2.0
    # JXA204 growth probe: rebuild the SAME entry at a larger toy N
    # (returns (grown EntryCase, n_ratio)); None = no growth probe
    grow: Optional[Callable[[], Tuple["EntryCase", float]]] = None
    # JXA402 knob-inertness probes: a thunk returning the list of
    # lowerdiff.KnobProbe off-vs-unset comparisons this entry vouches
    # for (the registry's knob_inertness entry wires
    # production_knob_probes here); None = rule does not apply
    knob_probes: Optional[Callable[[], Any]] = None


@dataclasses.dataclass
class EntryPoint:
    """A registered auditable entry: declaration + lazy case builder."""

    name: str
    build: Callable[[], EntryCase]
    # positions in the lowered ``args_info`` tuple whose WHOLE pytree
    # must be donated (static args are elided from args_info; count only
    # traced positionals)
    donate: Tuple[int, ...] = ()
    # collective axis names the entry's declared sharding provides;
    # () = unsharded (any named-axis collective is then a finding)
    mesh_axes: Tuple[str, ...] = ()
    # jaxpr-constant size budget (bytes) for the const-bloat audit
    const_bytes_limit: int = 1 << 20
    # trace under jax.experimental.enable_x64 (fixture use: the f64
    # rule can't fire with x64 off — jax silently demotes)
    x64: bool = False
    # per-entry override of the JXA202 per-device HBM budget (bytes);
    # None = the AuditContext default (16 GiB)
    hbm_budget: Optional[int] = None
    # JXA301 override: minimum attributed-FLOP share (None = the
    # AuditContext default; 0.0 exempts reconfigure-time programs that
    # legitimately run outside the step-phase taxonomy)
    phase_coverage_min: Optional[float] = None
    # JXA302 override: per-entry budget file instead of the context
    # default COST_BUDGET.json (fixtures pin doctored budgets this way)
    cost_budget_file: Optional[str] = None
    # JXA303: phases this entry DECLARES compute-bound; one of them
    # sitting below the device ridge point is a finding (an interaction
    # kernel that degraded into a bandwidth-bound gather loop)
    expect_compute_bound: Tuple[str, ...] = ()
    path: str = "?"
    line: int = 0


def _display_path(filename: str) -> str:
    """cwd-relative posix path when possible: findings (and therefore the
    committed baseline's (rule, path, hash) keys) must not embed the
    machine-specific absolute checkout path, or a baseline written on one
    machine never matches on another."""
    p = Path(filename)
    try:
        return p.relative_to(Path.cwd()).as_posix()
    except ValueError:
        return p.as_posix()


def entrypoint(name: str, *, donate: Tuple[int, ...] = (),
               mesh_axes: Tuple[str, ...] = (),
               const_bytes_limit: int = 1 << 20,
               x64: bool = False,
               hbm_budget: Optional[int] = None,
               phase_coverage_min: Optional[float] = None,
               cost_budget_file: Optional[str] = None,
               expect_compute_bound: Tuple[str, ...] = ()) -> Callable:
    """Decorator: declare a builder function as an audit entry point.

    The decorated function runs lazily (per audit run) and returns an
    ``EntryCase``. The binding in the module namespace becomes the
    registry entry; findings anchor at the builder's definition line.
    """

    def deco(build: Callable[[], EntryCase]) -> EntryPoint:
        code = getattr(build, "__code__", None)
        return EntryPoint(
            name=name, build=build, donate=tuple(donate),
            mesh_axes=tuple(mesh_axes),
            const_bytes_limit=const_bytes_limit, x64=x64,
            hbm_budget=hbm_budget,
            phase_coverage_min=phase_coverage_min,
            cost_budget_file=cost_budget_file,
            expect_compute_bound=tuple(expect_compute_bound),
            path=_display_path(code.co_filename) if code else "?",
            line=code.co_firstlineno if code else 0,
        )

    return deco


def entries_from_namespace(ns: Dict[str, Any]) -> List[EntryPoint]:
    """Collect EntryPoint bindings from a module namespace, in source
    order (the module-level registry contract: decorate builders with
    ``@entrypoint`` and this picks them up — no global mutable state)."""
    entries = [v for v in ns.values() if isinstance(v, EntryPoint)]
    names = [e.name for e in entries]
    dupes = {n for n in names if names.count(n) > 1}
    if dupes:
        raise ValueError(f"duplicate audit entry name(s): {sorted(dupes)}")
    return sorted(entries, key=lambda e: (e.path, e.line))


# ---------------------------------------------------------------------------
# jaxpr walking helpers
# ---------------------------------------------------------------------------


def subjaxprs(jaxpr) -> Iterable:
    """Yield every eqn of ``jaxpr`` and of all nested sub-jaxprs (pjit
    bodies, scan/while/cond branches, shard_map bodies, custom_* calls)."""
    for eqn in jaxpr.eqns:
        yield eqn
        for v in eqn.params.values():
            for w in (v if isinstance(v, (list, tuple)) else (v,)):
                if hasattr(w, "eqns"):            # raw Jaxpr
                    yield from subjaxprs(w)
                elif hasattr(w, "jaxpr") and hasattr(
                        getattr(w, "jaxpr"), "eqns"):  # ClosedJaxpr
                    yield from subjaxprs(w.jaxpr)


def all_closed_jaxprs(closed) -> Iterable:
    """Yield ``closed`` and every nested ClosedJaxpr (their ``consts``
    are where pjit-internal constants hide)."""
    seen = set()

    def walk(cj):
        if id(cj) in seen:
            return
        seen.add(id(cj))
        yield cj
        for eqn in cj.jaxpr.eqns:
            for v in eqn.params.values():
                for w in (v if isinstance(v, (list, tuple)) else (v,)):
                    if hasattr(w, "jaxpr") and hasattr(w, "consts"):
                        yield from walk(w)
                    elif hasattr(w, "eqns"):
                        # raw Jaxpr: constvars but no const VALUES; the
                        # values live on an enclosing ClosedJaxpr
                        for eq2 in subjaxprs(w):
                            for v2 in eq2.params.values():
                                for w2 in (v2 if isinstance(v2, (list, tuple))
                                           else (v2,)):
                                    if hasattr(w2, "jaxpr") and hasattr(
                                            w2, "consts"):
                                        yield from walk(w2)

    yield from walk(closed)


# ---------------------------------------------------------------------------
# per-entry trace cache
# ---------------------------------------------------------------------------


class EntryTrace:
    """Lazily computed, cached trace artifacts for one entry.

    Rules pull ``closed_jaxpr`` (tracing only — no compile), ``lowered``
    (AOT lowering — no compile), or ``out`` (one real execution, only the
    recompile rule needs it: weak_type does not survive into
    ShapeDtypeStructs, so carried avals must come from concrete outputs).
    """

    def __init__(self, entry: EntryPoint, case: EntryCase):
        self.entry = entry
        self.case = case
        self._closed = None
        self._out_shape = None
        self._lowered = None
        self._out = dataclasses.MISSING

    def _x64_scope(self):
        import contextlib

        if not self.entry.x64:
            return contextlib.nullcontext()
        from jax.experimental import enable_x64

        return enable_x64()

    @property
    def closed_jaxpr(self):
        if self._closed is None:
            import jax

            with self._x64_scope():
                # return_shape=True: the SAME trace also yields the
                # output pytree of ShapeDtypeStructs, so statecheck's
                # schema inference costs no extra trace
                self._closed, self._out_shape = jax.make_jaxpr(
                    self.case.fn, return_shape=True)(*self.case.args)
        return self._closed

    @property
    def out_shape(self):
        """Output pytree of ShapeDtypeStructs (same trace as the jaxpr);
        ``closed_jaxpr.out_avals`` carries the matching flat-order
        weak_type bits."""
        if self._out_shape is None:
            self.closed_jaxpr  # noqa: B018 - fills the cache
        return self._out_shape

    @property
    def lowered(self):
        if self._lowered is None and self.case.lower is not None:
            with self._x64_scope():
                self._lowered = self.case.lower()
        return self._lowered

    @property
    def out(self):
        if self._out is dataclasses.MISSING:
            with self._x64_scope():
                self._out = self.case.fn(*self.case.args)
        return self._out

    def finding(self, rule: str, message: str) -> Finding:
        e = self.entry
        return Finding(rule=rule, path=e.path, line=e.line, col=0,
                       message=f"[{e.name}] {message}",
                       snippet=f"entry:{e.name}")


# ---------------------------------------------------------------------------
# rule registry (mirrors devtools/lint/core.py)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Rule:
    id: str
    name: str
    description: str
    check: Callable[[EntryTrace], List[Finding]]


_REGISTRY: Dict[str, Rule] = {}


def register(id: str, name: str, description: str):
    """Decorator: register ``check(trace) -> [Finding]`` under a rule id."""

    def deco(fn: Callable[[EntryTrace], List[Finding]]):
        if id in _REGISTRY:
            raise ValueError(f"duplicate rule id {id}")
        _REGISTRY[id] = Rule(id=id, name=name, description=description,
                             check=fn)
        return fn

    return deco


def all_rules() -> Dict[str, Rule]:
    # importing the rules package populates the registry
    import sphexa_tpu.devtools.audit.rules  # noqa: F401

    return dict(_REGISTRY)


# ---------------------------------------------------------------------------
# runner
# ---------------------------------------------------------------------------


class Auditor:
    def __init__(self, select: Optional[Sequence[str]] = None):
        rules = all_rules()
        if select:
            unknown = set(select) - set(rules)
            if unknown:
                raise ValueError(f"unknown rule id(s): {sorted(unknown)}")
            rules = {k: v for k, v in rules.items() if k in select}
        self.rules = rules
        self._suppressions: Dict[str, SuppressionTable] = {}

    def _suppression_table(self, path: str) -> SuppressionTable:
        if path not in self._suppressions:
            try:
                source = Path(path).read_text()
            except OSError:
                source = ""
            self._suppressions[path] = parse_suppressions(source, _DISABLE_RE)
        return self._suppressions[path]

    def run_entries(self, entries: Sequence[EntryPoint]
                    ) -> Tuple[List[Finding], List[Finding], List[Finding],
                               List[str]]:
        """(active, suppressed, errors, skipped_names) over the entries.

        A builder/trace failure becomes a ``JXA000`` pseudo-finding (not
        suppressible away by accident: it carries the exception). An
        ``EntrySkip`` lands in ``skipped_names`` for the caller to gate.
        """
        active: List[Finding] = []
        suppressed: List[Finding] = []
        errors: List[Finding] = []
        skipped: List[str] = []
        for entry in entries:
            try:
                case = entry.build()
            except EntrySkip as e:
                skipped.append(f"{entry.name}: {e}")
                continue
            except Exception as e:  # noqa: BLE001 - reported as JXA000
                errors.append(Finding(
                    rule="JXA000", path=entry.path, line=entry.line, col=0,
                    message=f"[{entry.name}] entry build failed: "
                            f"{e.__class__.__name__}: {e}",
                ))
                continue
            trace = EntryTrace(entry, case)
            table = self._suppression_table(entry.path)
            for rule in self.rules.values():
                try:
                    found = rule.check(trace)
                except Exception as e:  # noqa: BLE001 - reported as JXA000
                    tb = traceback.format_exc(limit=3)
                    errors.append(Finding(
                        rule="JXA000", path=entry.path, line=entry.line,
                        col=0,
                        message=f"[{entry.name}] {rule.id} crashed: "
                                f"{e.__class__.__name__}: {e}\n{tb}",
                    ))
                    continue
                for f in found:
                    if table.is_suppressed(f.rule, f.line):
                        suppressed.append(f)
                    else:
                        active.append(f)
        key = lambda f: (f.path, f.line, f.rule, f.message)
        return (sorted(active, key=key), sorted(suppressed, key=key),
                sorted(errors, key=key), skipped)
