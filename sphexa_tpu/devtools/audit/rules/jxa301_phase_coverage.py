"""JXA301: static phase-attribution coverage.

The cost model (and the chip-harvest traceview attribution it predicts)
is only as good as the ``sphexa/<phase>`` named scopes: an eqn outside
every scope rolls into the unattributed bucket, invisible to both the
static ranking and the measured per-phase table. Two ways the scopes
rot land here:

- the entry's **attributed-FLOP share** falls below the threshold
  (``AuditContext.phase_coverage_min``, or the entry's own
  ``phase_coverage_min`` — reconfigure-time programs like
  ``tree_build_sizing`` run outside the step taxonomy and declare 0.0);
- an eqn lands in a ``sphexa/<x>`` scope with **x outside the
  util/phases.py taxonomy** — a typo'd or ad-hoc scope name that
  traceview would silently bucket as a brand-new phase.
"""

from __future__ import annotations

from typing import List

from sphexa_tpu.devtools.audit.core import EntryTrace, audit_context, register
from sphexa_tpu.devtools.audit.costmodel import cost_report
from sphexa_tpu.devtools.common import Finding


@register(
    "JXA301", "phase-coverage",
    "attributed-FLOP share below the per-entry threshold, or an eqn "
    "stamped with a scope outside the util/phases.py taxonomy",
)
def check(trace: EntryTrace) -> List[Finding]:
    ctx = audit_context()
    rep = cost_report(trace, ctx)
    out: List[Finding] = []

    if rep.unknown_scopes:
        out.append(trace.finding(
            "JXA301",
            f"eqns stamped with scope(s) outside the util/phases.py "
            f"taxonomy: {', '.join(rep.unknown_scopes)} — traceview would "
            f"bucket these as brand-new phases; use util.phases.named_phase "
            f"(or extend PHASES) instead of ad-hoc scope strings.",
        ))

    floor = trace.entry.phase_coverage_min
    if floor is None:
        floor = ctx.phase_coverage_min
    if rep.total_flops > 0 and rep.coverage < floor:
        out.append(trace.finding(
            "JXA301",
            f"only {rep.coverage:.1%} of static FLOPs attribute to named "
            f"phases (threshold {floor:.0%}) — "
            f"{rep.unattributed.flops:.3g} FLOPs run outside every "
            f"sphexa/<phase> scope and will be invisible in chip captures; "
            f"wrap the unattributed stages with util.phases.named_phase.",
        ))
    return out
