"""JXA502: vmap-batchability audit (the ensemble-mode admission check).

ROADMAP item 3 serves ensembles by vmapping the step over a member
axis. Whether an entry CAN be vmapped — and whether the batched program
is still one fused device program rather than a serialized fallback —
is decidable at trace time, so the ensemble mode's admission check is
static: each single-device entry is traced under ``jax.vmap`` over a
synthetic leading member axis (abstract args; no member batch is ever
materialized) and everything that breaks or degrades batching is a
finding, not a crash:

- **trace failure**: the vmapped trace raises (a primitive with no
  batching rule, shape logic keyed on concrete leading dims). Captured
  and reported with the exception.
- **host callbacks**: callback/infeed/outfeed primitives in the vmapped
  body (the JXA104 deny family). Under vmap these serialize per member
  — N members pay N host round trips per step.
- **serialized fallback**: more while/scan equations in the vmapped
  jaxpr than in the base jaxpr. vmap with no batching rule for a loop
  construct unrolls members into a sequential scan — the batch runs
  members one after another on one device, which is exactly what the
  ensemble mode exists to avoid.

Off by default (``vmap_members=0`` in the AuditContext keeps the extra
trace out of the package-audit tier-1 path); ``sphexa-audit schema
--vmap`` enables it. Sharded entries are out of scope — members
multiply the DEVICE mesh there, not a vmap axis. A legitimately
non-batchable entry carries an explicit inline waiver
(``# jaxaudit: disable=JXA502 -- reason``) at its registration site.
"""

from __future__ import annotations

from typing import List

from sphexa_tpu.devtools.audit.core import (
    EntryTrace,
    audit_context,
    register,
)
from sphexa_tpu.devtools.common import Finding


@register(
    "JXA502", "vmap-batchability",
    "entry fails or degrades under jax.vmap over a member axis "
    "(trace failure, per-member host callbacks, serialized loop "
    "fallback) — not admissible to the ensemble mode",
)
def check(trace: EntryTrace) -> List[Finding]:
    from sphexa_tpu.devtools.audit import statecheck

    ctx = audit_context()
    members = ctx.vmap_members
    if members <= 0 or trace.entry.mesh_axes:
        return []
    report = statecheck.vmap_probe(trace, members)
    out: List[Finding] = []
    if report["error"] is not None:
        out.append(trace.finding(
            "JXA502",
            f"does not trace under jax.vmap over {members} members: "
            f"{report['error']} — the entry cannot serve ensembles; "
            f"fix the batching break or waive with a reason.",
        ))
        return out
    for name, n in report["callbacks"]:
        out.append(trace.finding(
            "JXA502",
            f"`{name}` x{n} in the vmapped body — host callbacks "
            f"serialize per member ({members} members = {members}x host "
            f"round trips per step). Hoist it to the driver or gate it "
            f"off the ensemble path.",
        ))
    if report["vmap_loops"] > report["base_loops"]:
        out.append(trace.finding(
            "JXA502",
            f"vmap falls back to serialized loops: "
            f"{report['vmap_loops']} while/scan eqns batched vs "
            f"{report['base_loops']} unbatched — members run "
            f"sequentially instead of as one batched program.",
        ))
    return out
