"""JXA503: carry closure — step outputs must BE step inputs.

``jax.lax.scan``, the ensemble server's member loop, and the driver's
``step_sim_state`` all demand the same invariant: the carry pytree a
step returns is aval- and structure-identical to the one it consumed.
JXA102 checks the flattened leaf *signature* (its target is the silent
step-2 retrace); this rule lifts the check to the full carry
STRUCTURE, where the classic break is invisible to a flat zip: a
``None`` aux slot on step 1 becoming an array on step 2 (or vice
versa) changes the treedef itself — ``scan`` rejects it outright, and
under the unified SimState carry it means a propagator family wrote
into a slot it does not own.

Two layers, structural first:

- **treedef**: flatten both carries with paths; report leaves that
  exist on only one side (path-anchored, None<->array flips called out
  by name) — a structural break makes the per-leaf zip meaningless, so
  it short-circuits.
- **per-leaf avals**: shape, dtype, weak_type via shaped_abstractify —
  the JXA102 carry check re-anchored to closure (the two co-fire on a
  dtype-drifting carry; JXA102 says "this retraces", this rule says
  "this is not a scan carry").

Runs on every entry that declares a ``carry`` — all five propagator
families, including the blockdt/turb/chem aux carries.
"""

from __future__ import annotations

from typing import List

from sphexa_tpu.devtools.audit.core import EntryTrace, register
from sphexa_tpu.devtools.common import Finding


def _paths(tree):
    """{keystr path: leaf} with paths for structural anchoring."""
    import jax

    return {
        jax.tree_util.keystr(path): leaf
        for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]
    }


@register(
    "JXA503", "carry-closure",
    "step-2 carry differs from step-1 carry in treedef or leaf avals "
    "(None<->array flips, shape/dtype/weak_type drift) — not a valid "
    "scan/ensemble carry",
)
def check(trace: EntryTrace) -> List[Finding]:
    case = trace.case
    if case.carry is None:
        return []
    import jax
    from jax.api_util import shaped_abstractify

    args2 = case.carry(case.args, trace.out)
    td1 = jax.tree_util.tree_structure(case.args)
    td2 = jax.tree_util.tree_structure(args2)
    if td1 != td2:
        p1, p2 = _paths(case.args), _paths(args2)
        dropped = sorted(set(p1) - set(p2))
        grown = sorted(set(p2) - set(p1))
        bits = []
        if dropped:
            bits.append("leaves only in step-1 args: "
                        + ", ".join(dropped[:6])
                        + (" ..." if len(dropped) > 6 else ""))
        if grown:
            bits.append("leaves only in step-2 args: "
                        + ", ".join(grown[:6])
                        + (" ..." if len(grown) > 6 else ""))
        if not bits:
            # same leaf paths, different treedef: a None slot flipped
            # to/from a leaf-bearing subtree or a container type changed
            bits.append(f"treedefs differ with identical leaf paths "
                        f"({td1} vs {td2})")
        return [trace.finding(
            "JXA503",
            "carry is not closed — the step changes its own carry "
            "STRUCTURE: " + "; ".join(bits) + ". A None<->array flip in "
            "an aux slot means this propagator family writes a slot it "
            "does not own; scan/ensemble loops reject the carry "
            "outright.",
        )]
    leaves1 = jax.tree_util.tree_flatten_with_path(case.args)[0]
    leaves2 = jax.tree_util.tree_leaves(args2)
    out: List[Finding] = []
    drifted = [
        (jax.tree_util.keystr(path), str(shaped_abstractify(l1)),
         str(shaped_abstractify(l2)))
        for (path, l1), l2 in zip(leaves1, leaves2)
        if str(shaped_abstractify(l1)) != str(shaped_abstractify(l2))
    ]
    for path, a1, a2 in drifted[:8]:
        out.append(trace.finding(
            "JXA503",
            f"carry leaf {path or '<root>'} is not closed under the "
            f"step: {a1} in, {a2} out — scan/ensemble loops reject the "
            f"carry; commit the leaf to its policy aval where the state "
            f"is built.",
        ))
    return out
