"""JXA401: bitwise-nondeterminism audit (the replay-contract class).

The repo's lowering lock (``lowerdiff.py``) pins WHAT program runs; this
rule pins that the program is bitwise-replayable at all. Three lowering
shapes break replay even with an identical jaxpr digest:

- a float ``scatter-add``/``scatter-mul`` carrying BOTH
  ``unique_indices=False`` and ``indices_are_sorted=False``: XLA may
  combine colliding updates in any order, and float addition does not
  commute in rounding. The gravity upsweeps accumulate children into
  parents with duplicate indices on purpose — they stay silent here
  because the level-ordered layout makes parent rows non-decreasing and
  the scatters honestly declare ``indices_are_sorted=True``, fixing the
  segment order (gravity/traversal.py, gravity/spherical.py).
- a ``reduce_precision`` eqn: the deliberate-precision-drop escape hatch
  is banned from audited entries (dtype policy lives in util/dtypes.py,
  not in per-eqn rounding).
- a float-REDUCTION collective (psum/pmean/psum_scatter/reduce_scatter —
  not pmax/pmin, whose results are order-insensitive) that participates
  in a JXA201 unordered pair: with no proven total order the reduction
  tree may associate differently per run. Chained collectives
  (exchange.chain_after) are already excluded by the spmd dependency
  walk.
"""

from __future__ import annotations

from typing import List

import numpy as np

from sphexa_tpu.devtools.audit.core import (
    EntryTrace,
    audit_context,
    register,
    subjaxprs,
)
from sphexa_tpu.devtools.audit.spmd import spmd_report
from sphexa_tpu.devtools.common import Finding

#: scatter variants whose combiner is order-sensitive on floats
_UNORDERED_SCATTERS = ("scatter-add", "scatter-mul")

#: collectives whose cross-device combiner is order-sensitive on floats
_FLOAT_REDUCTIONS = frozenset(
    {"psum", "pmean", "psum_scatter", "reduce_scatter"})


def _is_float(aval) -> bool:
    dtype = getattr(aval, "dtype", None)
    return dtype is not None and np.issubdtype(dtype, np.inexact)


@register(
    "JXA401", "nondeterminism",
    "bitwise-replay hazards: unordered float scatter accumulation, "
    "reduce_precision, float-reduction collectives outside a proven "
    "order",
)
def check(trace: EntryTrace) -> List[Finding]:
    findings: List[Finding] = []
    scatters = 0
    scatter_example = ""
    precisions = 0
    for eqn in subjaxprs(trace.closed_jaxpr.jaxpr):
        prim = eqn.primitive.name
        if prim in _UNORDERED_SCATTERS:
            if (not eqn.params.get("unique_indices", False)
                    and not eqn.params.get("indices_are_sorted", False)
                    and any(_is_float(v.aval) for v in eqn.outvars)):
                scatters += 1
                if not scatter_example:
                    scatter_example = (
                        f"{prim} -> "
                        f"{getattr(eqn.outvars[0], 'aval', '?')}")
        elif prim == "reduce_precision":
            precisions += 1
    if scatters:
        findings.append(trace.finding(
            "JXA401",
            f"{scatters} float {'/'.join(_UNORDERED_SCATTERS)} eqn(s) "
            f"with unique_indices=False AND indices_are_sorted=False "
            f"(e.g. {scatter_example}) — colliding updates may combine "
            f"in any order and float addition does not commute in "
            f"rounding, so replays are not bitwise. Declare "
            f"indices_are_sorted=True where a segment order is "
            f"guaranteed (the gravity-upsweep pattern), "
            f"unique_indices=True where indices cannot collide, or "
            f"restructure as a segment_sum.",
        ))
    if precisions:
        findings.append(trace.finding(
            "JXA401",
            f"{precisions} reduce_precision eqn(s) — per-eqn rounding "
            f"drops bits outside the util/dtypes.py policy and breaks "
            f"bitwise replay; lower the dtype of the array instead.",
        ))

    rep = spmd_report(trace, audit_context())
    if rep.unordered_pairs:
        hazard = sorted({
            f"{rep.collectives[cid].prim}#{cid}"
            f"[{rep.collectives[cid].where}]"
            for pair in rep.unordered_pairs for cid in pair
            if rep.collectives[cid].prim in _FLOAT_REDUCTIONS})
        if hazard:
            findings.append(trace.finding(
                "JXA401",
                f"{len(hazard)} float-reduction collective(s) in "
                f"mutually order-unconstrained pairs: "
                f"{'; '.join(hazard[:4])}"
                + (f"; +{len(hazard) - 4} more" if len(hazard) > 4 else "")
                + " — with no proven total order the cross-device "
                  "reduction may associate differently per run. Pin the "
                  "order with exchange.chain_after (also clears JXA201).",
            ))
    return findings
