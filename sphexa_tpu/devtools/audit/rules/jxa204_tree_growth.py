"""JXA204: two-point tree-growth probe for the JXA202 rescale exemption.

JXA202's campaign rescale multiplies only EXTENSIVE buffers (whole
per-device particle slabs: elems a multiple of the slab rows S); scan
accumulators, cell-grid tiles and O(tree) coarse arrays stay at traced
size. docs/NEXT.md round-10 carried the caution: a tree that grows
SUPERLINEARLY in N hides inside that exemption — its buffers stay
"traced size" in the estimate while really exploding at campaign N.

This closes it with a two-point probe: entries that declare a ``grow``
builder (the same case at a larger toy N) are retraced at both sizes
and the summed bytes of the exempt buffer class are compared. The
exempt class must scale no worse than linearly in N
(``growth <= n_ratio x AuditContext.tree_growth_slack``) — an N^2 pair
matrix or a superlinear tree build mislabeled as "fixed-size work
buffer" fails the gate, and the JXA202 campaign estimate for it can no
longer be trusted silently. Entries without a ``grow`` builder are not
probed.
"""

from __future__ import annotations

from typing import List

from sphexa_tpu.devtools.audit.core import (
    EntryTrace,
    audit_context,
    register,
)
from sphexa_tpu.devtools.audit.spmd import _sub_jaxprs, aval_bytes, format_bytes
from sphexa_tpu.devtools.common import Finding


def _slab_rows(jaxpr) -> int:
    """Largest leading dim over entry invars (the spmd_report anchor)."""
    s = 0
    for v in jaxpr.invars:
        shape = getattr(v.aval, "shape", ())
        if shape:
            s = max(s, int(shape[0]))
    return s


def _exempt_bytes(jaxpr, s_toy: int) -> int:
    """Summed bytes of distinct rescale-EXEMPT buffers across the
    program, nested jaxprs included; pallas kernel bodies are VMEM
    views and are skipped.

    Extensive means a whole multiple of the slab rows OR of the padded
    particle capacity (next power of two >= slab) — the neighbor-list
    working set is capacity-padded, so without the pow2 candidate its
    classification flips with the slab's divisors and the two probe
    points would not be comparable."""
    candidates = [s for s in (
        s_toy, 1 << max(int(s_toy) - 1, 0).bit_length() if s_toy else 0,
    ) if s]
    seen = set()
    total = 0

    def visit(v):
        nonlocal total
        if id(v) in seen:
            return
        seen.add(id(v))
        aval = getattr(v, "aval", None)
        b = aval_bytes(aval)
        if not b:
            return
        itemsize = getattr(getattr(aval, "dtype", None), "itemsize", 1) or 1
        elems = b // itemsize
        if not any(elems >= s and elems % s == 0 for s in candidates):
            total += b

    def walk(jx):
        for v in (*jx.invars, *jx.constvars):
            visit(v)
        for eqn in jx.eqns:
            for ov in eqn.outvars:
                visit(ov)
            if eqn.primitive.name == "pallas_call":
                continue
            for sj in _sub_jaxprs(eqn):
                walk(sj)

    walk(jaxpr)
    return total


@register(
    "JXA204", "tree-growth",
    "rescale-exempt (non-slab) buffer bytes grow superlinearly in N "
    "between the entry's two growth-probe trace points",
)
def check(trace: EntryTrace) -> List[Finding]:
    if trace.case.grow is None:
        return []
    ctx = audit_context()
    grown_case, n_ratio = trace.case.grow()
    grown = EntryTrace(trace.entry, grown_case)

    jx1 = trace.closed_jaxpr.jaxpr
    jx2 = grown.closed_jaxpr.jaxpr
    e1 = _exempt_bytes(jx1, _slab_rows(jx1))
    e2 = _exempt_bytes(jx2, _slab_rows(jx2))
    if e1 <= 0:
        return []
    growth = e2 / e1
    allowed = float(n_ratio) * ctx.tree_growth_slack
    if growth <= allowed:
        return []
    return [trace.finding(
        "JXA204",
        f"rescale-exempt buffers grew {growth:.2f}x "
        f"({format_bytes(e1)} -> {format_bytes(e2)}) across a "
        f"{n_ratio:.2f}x N growth probe (allowed <= {allowed:.2f}x = "
        f"linear x slack {ctx.tree_growth_slack:g}) — an O(tree) or "
        f"work-buffer array is scaling superlinearly in N, so JXA202's "
        f"traced-size exemption under-estimates its campaign HBM; make "
        f"the buffer extensive (slab-multiple) or cap its growth.",
    )]
