"""Rule modules register themselves on import (core.register)."""

from sphexa_tpu.devtools.audit.rules import (  # noqa: F401
    jxa101_dtype_promotion,
    jxa102_recompile,
    jxa103_donation,
    jxa104_host_boundary,
    jxa105_const_bloat,
    jxa106_collective_axes,
    jxa201_collective_order,
    jxa202_peak_hbm,
    jxa203_sharding_propagation,
    jxa204_tree_growth,
    jxa301_phase_coverage,
    jxa302_cost_budget,
    jxa303_memory_bound,
    jxa401_nondeterminism,
    jxa402_knob_inertness,
    jxa501_schema_drift,
    jxa502_vmap,
    jxa503_carry_closure,
)
