"""JXA303: declared-compute-bound phase sitting below the ridge point.

The roofline's qualitative claim per phase — compute- or memory-bound —
is what the chip-harvest protocol acts on (fuse the memory-bound
phases, tune block shapes on the compute-bound ones). The full
memory-bound ranking is a REPORT (``sphexa-audit cost`` prints it; it
statically orders ROADMAP item-2's fused-IAD+divv / resort-cadence
candidates). The rule has teeth only where an entry DECLARES an
expectation: a phase listed in ``expect_compute_bound`` whose
arithmetic intensity sits below the device ridge point means the
interaction kernel degraded into a bandwidth-bound gather loop — the
regression class the Bonsai-lineage traversal papers tune against.
"""

from __future__ import annotations

from typing import List

from sphexa_tpu.devtools.audit.core import EntryTrace, audit_context, register
from sphexa_tpu.devtools.audit.costmodel import cost_report, predict
from sphexa_tpu.devtools.audit.devices import get_device
from sphexa_tpu.devtools.common import Finding


@register(
    "JXA303", "memory-bound-phase",
    "a phase the entry declares compute-bound has arithmetic intensity "
    "below the device-model ridge point",
)
def check(trace: EntryTrace) -> List[Finding]:
    expect = trace.entry.expect_compute_bound
    if not expect:
        return []
    ctx = audit_context()
    dev = get_device(ctx.cost_device)
    pred = predict(cost_report(trace, ctx), dev)
    out: List[Finding] = []
    for phase in expect:
        row = pred.row(phase)
        if row is None:
            out.append(trace.finding(
                "JXA303",
                f"phase {phase!r} is declared compute-bound but no eqn "
                f"attributes to it — the scope vanished or the declaration "
                f"is stale.",
            ))
            continue
        ridge = dev.ridge(row.dtype)
        if row.ai < ridge:
            out.append(trace.finding(
                "JXA303",
                f"phase {phase!r} is declared compute-bound but its "
                f"arithmetic intensity {row.ai:.3g} FLOPs/B sits below the "
                f"{dev.name} ridge point {ridge:.3g} ({row.dtype}) — the "
                f"kernel moves more HBM bytes than its FLOPs can hide "
                f"(predicted {row.ms:.4g}ms, {row.bound}-bound); check for "
                f"a lost blocking/reuse structure in the traversal.",
            ))
    return out
