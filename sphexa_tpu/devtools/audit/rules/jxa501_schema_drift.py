"""JXA501: state-schema drift vs the committed STATE_SCHEMA.json.

The symbolic carry/output schema of every entry — pytree paths, dtype,
weak_type, each axis a polynomial in N (statecheck.entry_schema) — is a
public contract: the ensemble server allocates member slots from it, the
telemetry schema rows mirror it, and the restart format round-trips it.
This rule pins the live schema against the committed lock so a carry
change (a new diagnostics key, an f32 leaf silently widening, a
capacity-padded axis becoming extensive) lands as a reviewed lock diff
in the same PR, never as a silent downstream break.

Skips quietly when the default lock file is absent (the JXA302 budget
pattern: fixtures and fresh checkouts are not findings); a CORRUPT lock
is a finding — an unreadable contract gates as loudly as a broken one.
Rows recorded at a different mesh size are skipped: sharded shapes
legitimately depend on P, and the lock is committed at the default
mesh. Entries missing from the lock are the CLI's business (`--write`
to relock), not a rule finding — existing fixtures stay clean.
"""

from __future__ import annotations

from typing import List

from sphexa_tpu.devtools.audit.core import (
    EntryTrace,
    audit_context,
    register,
)
from sphexa_tpu.devtools.common import Finding


@register(
    "JXA501", "state-schema-drift",
    "entry carry/output schema (pytree paths, dtype, weak_type, axis "
    "polynomials in N) drifted from the committed STATE_SCHEMA.json",
)
def check(trace: EntryTrace) -> List[Finding]:
    from pathlib import Path

    from sphexa_tpu.devtools.audit import statecheck

    ctx = audit_context()
    path = ctx.state_schema_path
    if not Path(path).exists():
        # no committed schema to gate against (fixture runs, fresh
        # checkouts) — same silent skip as the JXA302 budget file
        return []
    try:
        locked = statecheck.load_lock(path)
    except statecheck.LockError as e:
        return [trace.finding(
            "JXA501",
            f"schema lock unreadable: {e} — fix or regenerate with "
            f"`sphexa-audit schema --write`.",
        )]
    row = locked.get(trace.entry.name)
    if row is None:
        # unlocked entries are surfaced by the CLI verify (missing /
        # stale accounting), not per-entry findings
        return []
    current = statecheck.entry_schema(trace)
    if row.get("mesh") != current.get("mesh"):
        # locked at another mesh size: sharded shapes depend on P
        return []
    if row == current:
        return []
    diff = statecheck.schema_diff(trace.entry.name, row, current)
    return [trace.finding(
        "JXA501",
        "; ".join(line.strip() for line in diff[1:])
        + " — review the change and relock with "
          "`sphexa-audit schema --write`.",
    )]
