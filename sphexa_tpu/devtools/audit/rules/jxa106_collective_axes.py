"""JXA106: collective-axis audit against the entry's declared sharding.

Every psum/ppermute/all_gather/... in the traced body names a mesh axis;
the registry entry declares which axes its sharding provides
(``mesh_axes=("p",)``). An axis outside the declaration means the code
and the registry disagree about the mesh — either a renamed axis that a
copy-pasted collective still references (it resolves fine against an
unrelated axis of the same name on a larger mesh and reduces over the
WRONG devices), or a collective that escaped into an entry registered as
unsharded. shard_map eqns are cross-checked the same way: the mesh they
bind must only carry declared axes.
"""

from __future__ import annotations

from typing import Dict, List

from sphexa_tpu.devtools.audit.core import (
    EntryTrace,
    register,
    subjaxprs,
)
from sphexa_tpu.devtools.common import Finding

_AXIS_PARAM_KEYS = ("axes", "axis_name")


def _string_axes(value) -> List[str]:
    vals = value if isinstance(value, (tuple, list)) else (value,)
    return [v for v in vals if isinstance(v, str)]


@register(
    "JXA106", "collective-axis",
    "collective over an axis name outside the entry's declared mesh "
    "sharding",
)
def check(trace: EntryTrace) -> List[Finding]:
    declared = set(trace.entry.mesh_axes)
    unknown: Dict[str, str] = {}  # axis -> first primitive
    for eqn in subjaxprs(trace.closed_jaxpr.jaxpr):
        names: List[str] = []
        for key in _AXIS_PARAM_KEYS:
            if key in eqn.params:
                names += _string_axes(eqn.params[key])
        mesh = eqn.params.get("mesh")
        if mesh is not None and hasattr(mesh, "axis_names"):
            names += _string_axes(tuple(mesh.axis_names))
        for name in names:
            if name not in declared and name not in unknown:
                unknown[name] = eqn.primitive.name
    return [
        trace.finding(
            "JXA106",
            f"`{prim}` uses axis {name!r} but the registry declares "
            f"mesh_axes={tuple(sorted(declared))} for this entry — the "
            f"code and the declared sharding disagree; fix the axis name "
            f"or the registration.",
        )
        for name, prim in sorted(unknown.items())
    ]
