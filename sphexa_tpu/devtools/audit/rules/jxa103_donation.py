"""JXA103: donation audit — declared-donatable buffers that aren't donated.

The particle-state pytree is the MB-to-GB-scale resident of every step:
without ``donate_argnums``/``donate_argnames`` XLA must double-buffer it
(input + output live simultaneously), which halves the largest runnable N
per chip and forfeits in-place update fusion. Registry entries declare
which lowered argument positions hold such buffers (``donate=(0,)``);
this rule lowers the entry's HOT variant and verifies every leaf of each
declared position is actually donated.

Indices count positions in ``Lowered.args_info`` — static args are
elided there, so count only traced positionals of the lowering call.
"""

from __future__ import annotations

import math
from typing import List

from sphexa_tpu.devtools.audit.core import EntryTrace, register
from sphexa_tpu.devtools.common import Finding


def _leaf_bytes(aval) -> int:
    shape = getattr(aval, "shape", ())
    dtype = getattr(aval, "dtype", None)
    if dtype is None:
        return 0
    return math.prod(shape) * dtype.itemsize if shape else dtype.itemsize


@register(
    "JXA103", "donation",
    "declared-donatable buffers (the particle-state pytree) not donated "
    "in the entry's lowering",
)
def check(trace: EntryTrace) -> List[Finding]:
    entry = trace.entry
    if not entry.donate:
        return []
    lowered = trace.lowered
    if lowered is None:
        return [trace.finding(
            "JXA103",
            "entry declares donatable args but provides no `lower` thunk "
            "— register `<fn>_donated.lower(*args)` so donation is "
            "auditable.",
        )]
    import jax

    args_info = lowered.args_info[0] if isinstance(
        lowered.args_info, tuple) else lowered.args_info
    out: List[Finding] = []
    for idx in entry.donate:
        if idx >= len(args_info):
            out.append(trace.finding(
                "JXA103",
                f"declared donate index {idx} out of range for the "
                f"lowering's {len(args_info)} traced args.",
            ))
            continue
        leaves = jax.tree_util.tree_leaves(
            args_info[idx], is_leaf=lambda x: hasattr(x, "donated")
        )
        missed = [l for l in leaves if not getattr(l, "donated", False)]
        if missed:
            lost = sum(_leaf_bytes(getattr(l, "aval", None)) for l in missed)
            out.append(trace.finding(
                "JXA103",
                f"arg {idx}: {len(missed)}/{len(leaves)} leaves NOT "
                f"donated ({lost} bytes double-buffered at example scale; "
                f"scales with N). Add donate_argnames for this pytree on "
                f"the hot jit (propagator step_*_donated pattern).",
            ))
    return out
