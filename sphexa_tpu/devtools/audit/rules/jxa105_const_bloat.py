"""JXA105: large constants captured in the jaxpr.

A host array closed over by a jitted function is baked into the program
as a CONSTANT: it is re-uploaded per compiled executable, bloats the
serialized computation, defeats donation (constants are never donated),
and — the sneaky variant — a whole particle array accidentally captured
by closure instead of passed as an argument silently freezes step-1 data
into every later step. Entries budget constants via
``const_bytes_limit`` (default 1 MiB: lookup tables are legal, particle
arrays are not).

Constants of nested pjit bodies are walked too — that is where closure
captures of inner jitted helpers land.
"""

from __future__ import annotations

from typing import List

from sphexa_tpu.devtools.audit.core import (
    EntryTrace,
    all_closed_jaxprs,
    register,
)
from sphexa_tpu.devtools.common import Finding


@register(
    "JXA105", "const-bloat",
    "constant above the entry's size budget captured in the jaxpr "
    "(closure-baked array)",
)
def check(trace: EntryTrace) -> List[Finding]:
    limit = trace.entry.const_bytes_limit
    out: List[Finding] = []
    seen = set()
    for cj in all_closed_jaxprs(trace.closed_jaxpr):
        for c in cj.consts:
            if id(c) in seen:
                continue
            seen.add(id(c))
            nbytes = getattr(c, "nbytes", 0)
            if nbytes > limit:
                out.append(trace.finding(
                    "JXA105",
                    f"constant {getattr(c, 'dtype', '?')}"
                    f"{tuple(getattr(c, 'shape', ()))} of {nbytes} bytes "
                    f"baked into the jaxpr (budget {limit}). Pass it as "
                    f"an argument (pytree leaf) instead of closing over "
                    f"it.",
                ))
    return out
