"""JXA201: collective-order audit (the PR-5 rendezvous-race class).

XLA guarantees no program order between collectives that are not
connected through dataflow. On the XLA:CPU rendezvous they can then
complete in different interleavings on different devices (cross-wired
payloads or a deadlock — exactly the sparse-exchange race PR 5 fixed by
hand); on real chips an unpinned order costs ICI stalls and makes
step-time nondeterministic. The repo's contract is a TOTAL order pinned
by ``exchange.chain_after`` (an ``optimization_barrier`` data edge), so
the dependency walk in ``spmd.py`` sees a chained collective as the
ancestor of its successor. Any pair of named-axis collectives with no
ancestor relation in either direction is a finding.

Entries with fewer than two collectives are trivially ordered.
"""

from __future__ import annotations

from typing import List

from sphexa_tpu.devtools.audit.core import EntryTrace, audit_context, register
from sphexa_tpu.devtools.audit.spmd import spmd_report
from sphexa_tpu.devtools.common import Finding


@register(
    "JXA201", "collective-order",
    "mutually order-unconstrained collectives (XLA rendezvous-race "
    "class) — pin a total order with exchange.chain_after",
)
def check(trace: EntryTrace) -> List[Finding]:
    rep = spmd_report(trace, audit_context())
    if len(rep.collectives) < 2 or not rep.unordered_pairs:
        return []
    examples = []
    for i, j in rep.unordered_pairs[:4]:
        a, b = rep.collectives[i], rep.collectives[j]
        examples.append(f"{a.prim}#{i}[{a.where}] <-> {b.prim}#{j}[{b.where}]")
    more = len(rep.unordered_pairs) - len(examples)
    return [trace.finding(
        "JXA201",
        f"{len(rep.unordered_pairs)} mutually order-unconstrained "
        f"collective pair(s) among {len(rep.collectives)} collectives — "
        f"XLA may rendezvous them in different interleavings per device "
        f"(deadlock/cross-wired payloads on CPU meshes, ICI stalls on "
        f"chips). Pin a total order with exchange.chain_after. "
        f"Unordered: {'; '.join(examples)}"
        + (f"; +{more} more" if more > 0 else ""),
    )]
