"""JXA202: donation-aware static peak-HBM liveness vs the device budget.

A live-interval sweep over the entry's jaxpr (``spmd._peak_liveness``)
bounds the per-device residency XLA needs: entry args + consts live the
whole program, intermediates from definition to last use, nested jaxprs
contribute their internal excess, and a donated arg's matched result is
credited zero (input-output aliasing — the property JXA103 verifies
actually lowers). Two numbers come out of one sweep:

- the **toy peak** at the traced N (gated for every entry), and
- for sharded entries, the **campaign peak**: every buffer at least one
  per-device slab large is rescaled by
  ``(campaign_n / campaign_devices) / toy_slab_rows`` — a deliberate
  upper bound (toy halos cover the whole slab, so they rescale as full
  campaign slabs).

Either exceeding the per-device budget (entry ``hbm_budget`` override,
else the AuditContext default / ``--hbm-budget``) is a finding: the 64M
campaign config would OOM at launch, caught chip-free.
"""

from __future__ import annotations

from typing import List

from sphexa_tpu.devtools.audit.core import EntryTrace, audit_context, register
from sphexa_tpu.devtools.audit.spmd import format_bytes, spmd_report
from sphexa_tpu.devtools.common import Finding


@register(
    "JXA202", "peak-hbm-liveness",
    "donation-aware static peak-HBM estimate (toy N and campaign "
    "rescale) exceeds the per-device budget",
)
def check(trace: EntryTrace) -> List[Finding]:
    ctx = audit_context()
    rep = spmd_report(trace, ctx)
    budget = trace.entry.hbm_budget or ctx.hbm_budget_bytes
    over = []
    if rep.toy_peak_bytes > budget:
        over.append(f"traced toy N: {format_bytes(rep.toy_peak_bytes)}")
    if (rep.campaign_peak_bytes is not None
            and rep.campaign_peak_bytes > budget):
        slab = ctx.campaign_n // max(ctx.campaign_devices, 1)
        over.append(
            f"campaign N={ctx.campaign_n} / P={ctx.campaign_devices} "
            f"({slab} rows/device): "
            f"{format_bytes(rep.campaign_peak_bytes)}")
    if not over:
        return []
    return [trace.finding(
        "JXA202",
        f"static peak-HBM liveness exceeds the per-device budget "
        f"{format_bytes(budget)}: {'; '.join(over)} — shrink live "
        f"buffers (donation, narrower halos, staged gravity arrays) or "
        f"raise the budget if the device really has the headroom.",
    )]
