"""JXA104: host-boundary leaks inside a traced entry.

A callback / device_put / infeed primitive inside the hot jaxpr means the
step round-trips to the host (or re-places a buffer) EVERY iteration —
the per-step analog of the JXL002 host-sync class, but visible only after
tracing (the AST pass cannot see a callback smuggled in through a helper
in another module). Debug prints count too: ``jax.debug.print`` lowers to
``debug_callback`` and serializes the device stream.

``with_sharding_constraint``/collectives are NOT flagged — they are
device-side. ``jax.named_scope`` (the sphexa/<phase> attribution
scopes, util/phases.py) never appears here at all: it pushes a
tracing-time name stack and lowers to NO primitive, so the phase
taxonomy is invisible to this rule by construction (pinned by the
audit gate staying at zero findings with every step entry scoped).
The deny set is the callback/transfer family. ``device_put``
needs care: jax stages ``jnp.asarray(np_constant)`` inside a traced body
as a device_put eqn with no target (``devices=[None]``, alias
semantics) — that is constant staging, not a transfer (JXA105 budgets
its size instead). Only device_put with an EXPLICIT placement target is
a re-placement inside the hot body and gets flagged.
"""

from __future__ import annotations

from collections import Counter
from typing import List

from sphexa_tpu.devtools.audit.core import (
    EntryTrace,
    register,
    subjaxprs,
)
from sphexa_tpu.devtools.common import Finding

_DENY = {
    "pure_callback": "host callback per step",
    "io_callback": "host IO callback per step",
    "debug_callback": "debug print/callback serializes the device stream",
    "callback": "host callback per step",
    "infeed": "host infeed per step",
    "outfeed": "host outfeed per step",
    "device_put": "explicitly re-places a buffer inside the traced body",
}


def _is_constant_staging(eqn) -> bool:
    """device_put with no explicit target = jax staging an np constant."""
    devices = eqn.params.get("devices", ())
    srcs = eqn.params.get("srcs", ())
    return all(d is None for d in devices) and all(s is None for s in srcs)


@register(
    "JXA104", "host-boundary",
    "callback/device_put/infeed primitives inside the traced body "
    "(per-step host round trip)",
)
def check(trace: EntryTrace) -> List[Finding]:
    counts: Counter = Counter()
    for eqn in subjaxprs(trace.closed_jaxpr.jaxpr):
        name = eqn.primitive.name
        if name in _DENY:
            if name == "device_put" and _is_constant_staging(eqn):
                continue
            counts[name] += 1
    return [
        trace.finding(
            "JXA104",
            f"`{name}` x{n} in the traced body — {_DENY[name]}. Move it "
            f"to the driver loop (Simulation host code) or behind a "
            f"debug-only flag.",
        )
        for name, n in sorted(counts.items())
    ]
