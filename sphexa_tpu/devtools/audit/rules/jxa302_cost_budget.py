"""JXA302: predicted per-phase step time vs the committed budget file.

The static analog of ``TELEMETRY_LOCK.json``: ``COST_BUDGET.json``
commits, per audited entry, a per-phase predicted-ms ceiling (and
optionally a total) at a named device model. A refactor that balloons a
phase's FLOPs or HBM traffic moves the prediction past its ceiling and
fails HERE — before any chip time — the way the telemetry lock catches
a measured regression after the fact.

Resolution order: the entry's own ``cost_budget_file`` (fixtures pin
doctored budgets this way), else ``AuditContext.cost_budget_path``.
A missing DEFAULT file skips the gate quietly (out-of-repo audit runs);
a missing or invalid DECLARED file is a finding — a broken gate must
not pass silently. Entries absent from the file are not gated.
"""

from __future__ import annotations

import os
from typing import List

from sphexa_tpu.devtools.audit.core import EntryTrace, audit_context, register
from sphexa_tpu.devtools.audit.costmodel import (
    cost_report,
    load_budget,
    predict,
)
from sphexa_tpu.devtools.common import Finding


@register(
    "JXA302", "cost-budget",
    "predicted per-phase (or total) step ms exceeds the committed "
    "COST_BUDGET.json ceiling for this entry",
)
def check(trace: EntryTrace) -> List[Finding]:
    ctx = audit_context()
    declared = trace.entry.cost_budget_file
    path = declared or ctx.cost_budget_path
    if not path or (declared is None and not os.path.exists(path)):
        return []
    try:
        budget = load_budget(path)
    except (OSError, ValueError) as e:
        return [trace.finding(
            "JXA302",
            f"cost budget file unusable: {e} — fix or regenerate it "
            f"(scripts/check.sh --cost-only validates the committed one).",
        )]
    spec = (budget.get("entries") or {}).get(trace.entry.name)
    if not spec:
        return []

    pred = predict(cost_report(trace, ctx), str(budget["device"]))
    out: List[Finding] = []
    for phase, ceiling in sorted((spec.get("phases") or {}).items()):
        row = pred.row(phase)
        got = row.ms if row is not None else 0.0
        if got > float(ceiling):
            out.append(trace.finding(
                "JXA302",
                f"predicted {phase} time {got:.4g}ms exceeds the committed "
                f"budget {float(ceiling):.4g}ms on {pred.device} — the "
                f"phase's static FLOP/HBM cost grew; optimize it back or "
                f"re-derive the budget (docs/STATIC_ANALYSIS.md, "
                f"calibration workflow) with the regression understood.",
            ))
    total = spec.get("total_ms")
    if total is not None and pred.total_ms > float(total):
        out.append(trace.finding(
            "JXA302",
            f"predicted total step time {pred.total_ms:.4g}ms exceeds the "
            f"committed budget {float(total):.4g}ms on {pred.device}.",
        ))
    return out
