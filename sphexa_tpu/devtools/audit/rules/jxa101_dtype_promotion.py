"""JXA101: dtype promotion above the dtypes.py policy in a traced entry.

The package policy (sphexa_tpu/dtypes.py) is 32-bit everywhere on device:
f32 coordinates/hydro fields, i32 indices, u32 SFC keys. A 64-bit (or
c128) value anywhere in a hot jaxpr means either an explicit f64 request
or a silent promotion (np.float64 scalar, Python int too big for i32,
x64-enabled run) — on TPU that's a big slowdown (no fast f64) and off-TPU
it silently doubles memory traffic and de-synchronizes CI numerics from
chip numerics.

With x64 DISABLED jax demotes f64 requests on the spot, so the rule can
only fire under ``jax.experimental.enable_x64`` — entries opt in via
``x64=True`` (the fixture does; package entries trace under the ambient
config so this is the forward guard for x64-enabled diagnostics runs).

One finding per offending dtype per entry (first offending primitive
named), not one per eqn — a single upcast usually cascades through the
rest of the step.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

from sphexa_tpu.devtools.audit.core import (
    EntryTrace,
    register,
    subjaxprs,
)
from sphexa_tpu.devtools.common import Finding

_MAX_ITEMSIZE = 4  # the dtypes.py policy is 32-bit device values


def _offending(dtype) -> bool:
    kind = getattr(dtype, "kind", None)
    if kind in ("f", "i", "u"):
        return dtype.itemsize > _MAX_ITEMSIZE
    if kind == "c":
        return dtype.itemsize > 2 * _MAX_ITEMSIZE  # complex128
    return False


def _scan_aval(aval, where: str, hits: Dict[str, Tuple[str, int]]):
    dtype = getattr(aval, "dtype", None)
    if dtype is not None and _offending(dtype):
        key = str(dtype)
        if key not in hits:
            hits[key] = (where, 0)
        hits[key] = (hits[key][0], hits[key][1] + 1)


@register(
    "JXA101", "dtype-promotion",
    "64-bit value in a traced entry (dtypes.py policy is 32-bit on device)",
)
def check(trace: EntryTrace) -> List[Finding]:
    closed = trace.closed_jaxpr
    hits: Dict[str, Tuple[str, int]] = {}
    for aval in closed.in_avals:
        _scan_aval(aval, "entry input", hits)
    for c in closed.consts:
        _scan_aval(c, "jaxpr constant", hits)
    for eqn in subjaxprs(closed.jaxpr):
        for var in eqn.outvars:
            _scan_aval(getattr(var, "aval", None),
                       f"`{eqn.primitive.name}` output", hits)
    return [
        trace.finding(
            "JXA101",
            f"{dtype} appears in the traced body ({count} value(s); first "
            f"at {where}) — above the 32-bit dtypes.py policy. Pin the "
            f"input/constant to a policy dtype or cast at the host "
            f"boundary.",
        )
        for dtype, (where, count) in sorted(hits.items())
    ]
