"""JXA203: sharding-propagation audit — silent replication and exchange
volume beyond the analytic expectation.

Two ways sharding propagation goes wrong land here:

- a **particle-shaped operand enters a shard_map fully replicated**
  (empty ``in_names``): the partitioner materializes all N rows on
  every device — the implicit all-gather the Warren-Salmon LET program
  exists to avoid. Flagged when the operand's campaign-rescaled bytes
  clear the AuditContext threshold; small replicated tables and the
  O(tree) coarse gravity arrays (leading dim != N) are the design and
  stay clean.
- the entry's **summed collective output bytes exceed the analytic
  budget** its registry builder declared (``exchange_budget_bytes``,
  derived from sizing.sparse_need_matrix / _halo_info shipped_rows)
  by more than ``exchange_slack``: a partitioner-inserted collective is
  shipping particle fields the explicit exchange didn't account for.
  Entries without a declared budget skip the volume gate.
"""

from __future__ import annotations

from typing import List

from sphexa_tpu.devtools.audit.core import EntryTrace, audit_context, register
from sphexa_tpu.devtools.audit.spmd import format_bytes, spmd_report
from sphexa_tpu.devtools.common import Finding


@register(
    "JXA203", "sharding-propagation",
    "particle-shaped operand replicated into a shard_map, or cross-shard "
    "collective volume beyond the sizing-derived expectation",
)
def check(trace: EntryTrace) -> List[Finding]:
    ctx = audit_context()
    rep = spmd_report(trace, ctx)
    out: List[Finding] = []

    big = [r for r in rep.replicated
           if r.campaign_bytes >= ctx.repl_threshold_bytes]
    if big:
        desc = "; ".join(
            f"operand#{r.pos}[{r.where}] {r.shape} {r.dtype} "
            f"({format_bytes(r.toy_bytes)} traced, "
            f"{format_bytes(r.campaign_bytes)} at campaign N)"
            for r in big[:4])
        more = len(big) - min(len(big), 4)
        out.append(trace.finding(
            "JXA203",
            f"{len(big)} particle-shaped operand(s) enter a shard_map "
            f"fully replicated — every device materializes all N rows "
            f"(an implicit all-gather of particle fields): {desc}"
            + (f"; +{more} more" if more > 0 else "")
            + ". Shard them with PartitionSpec('p') or slice per shard.",
        ))

    case = trace.case
    budget = getattr(case, "exchange_budget_bytes", None)
    if budget:
        slack = getattr(case, "exchange_slack", 2.0) or 1.0
        allowed = int(budget * slack)
        measured = rep.collective_out_bytes
        if measured > allowed:
            out.append(trace.finding(
                "JXA203",
                f"cross-shard collective volume {format_bytes(measured)} "
                f"exceeds the analytic expectation "
                f"{format_bytes(budget)} x slack {slack:g} = "
                f"{format_bytes(allowed)} — a partitioner-inserted "
                f"collective is shipping rows the explicit exchange "
                f"didn't account for (check with_sharding_constraint "
                f"placement and the sizing-derived halo caps).",
            ))
    return out
