"""JXA102: recompile-signature audit (step-2 retrace, weak-type drift).

A jitted step recompiles whenever the abstract signature of its inputs
changes — shape, dtype, OR weak_type. The classic silent version: step 1
is fed a Python float (weak f32) or a host-built scalar, the step returns
a committed strong-f32 array in that slot, and step 2 retraces the whole
program — a one-time multi-second stall per reconfiguration that profiles
as "mysterious slow second step" on real chips.

Two sub-checks:

- **carry**: entries with a ``carry`` (the step builders) run ONCE on the
  example args; ``carry(args, out)`` rearranges the outputs into step-2
  args, and the flattened aval signature (shape, dtype, weak_type) of
  step-2 args must equal step-1's, leaf by leaf. Execution (not
  eval_shape) is required: weak_type does not survive into
  ShapeDtypeStruct, and weak-type drift is the main target.
- **perturb**: entries with a ``perturb`` variant of the args (host-fed
  Python scalars where the API tolerates either) are traced both ways;
  the OUTPUT avals must match, proving the entry normalizes scalars
  internally instead of letting caller-side weak types leak downstream.
"""

from __future__ import annotations

from typing import List

from sphexa_tpu.devtools.audit.core import EntryTrace, register
from sphexa_tpu.devtools.common import Finding


def _signature(tree):
    """[(path, aval_str)] over the flattened pytree, weak types visible."""
    import jax
    from jax.api_util import shaped_abstractify

    leaves = jax.tree_util.tree_flatten_with_path(tree)[0]
    return [
        (jax.tree_util.keystr(path), str(shaped_abstractify(leaf)))
        for path, leaf in leaves
    ]


@register(
    "JXA102", "recompile-signature",
    "step-2-shaped inputs or weak-type-perturbed scalars change the "
    "trace signature (silent per-step recompile)",
)
def check(trace: EntryTrace) -> List[Finding]:
    case = trace.case
    out: List[Finding] = []

    if case.carry is not None:
        sig1 = _signature(case.args)
        args2 = case.carry(case.args, trace.out)
        sig2 = _signature(args2)
        if len(sig1) != len(sig2):
            out.append(trace.finding(
                "JXA102",
                f"carried step-2 args have {len(sig2)} leaves vs "
                f"{len(sig1)} at step 1 — the pytree structure itself "
                f"drifts, every step retraces.",
            ))
        else:
            drift = [
                (p1, a1, a2)
                for (p1, a1), (_p2, a2) in zip(sig1, sig2)
                if a1 != a2
            ]
            for path, a1, a2 in drift[:8]:
                out.append(trace.finding(
                    "JXA102",
                    f"arg leaf {path or '<root>'} changes signature across "
                    f"steps: {a1} (step 1) vs {a2} (step 2) — the second "
                    f"step retraces. Commit the scalar to a policy dtype "
                    f"where the state is built.",
                ))

    if case.perturb is not None:
        import jax

        canonical = jax.make_jaxpr(case.fn)(*case.args)
        perturbed = jax.make_jaxpr(case.fn)(*case.perturb(case.args))
        o1 = [str(a) for a in canonical.out_avals]
        o2 = [str(a) for a in perturbed.out_avals]
        if o1 != o2:
            diffs = [f"{a} vs {b}" for a, b in zip(o1, o2) if a != b]
            out.append(trace.finding(
                "JXA102",
                f"host-fed weak scalars leak into the outputs: "
                f"{'; '.join(diffs[:4])} — normalize scalars "
                f"(jnp.asarray(..., policy_dtype)) at the function "
                f"boundary so callers can't perturb the signature.",
            ))
    return out
