"""JXA402: knob-inertness meta-rule.

Every tuning knob that declares an ``off_sentinel`` in
``tuning/knobs.py`` promises: resolving the knob to that value through
``tuned=`` leaves the step lowering fingerprint-identical to never
mentioning the knob at all. That is the contract the hand-written
byte-identity pins used to check one knob at a time (dt_bins=None,
grav_window=0); this rule checks it for the WHOLE registry with zero
per-knob test code — a new knob adds ``off_sentinel=...`` to its
KnobSpec and is probed automatically.

The probes live on ``EntryCase.knob_probes`` (the registry's
``knob_inertness`` entry wires ``lowerdiff.production_knob_probes``,
which first runs ``knobs.validate_off_sentinels()`` so a renamed
resolution site fails LOUDLY rather than letting the probe pass
vacuously). Each probe compares two canonical lowering fingerprints
(``lowerdiff.fingerprint_callable`` over the exact launch routing,
``sim._step_fn(donated=sim._donate_active)``), so an off-path leak shows
up whether it adds eqns, swaps a const, or silently re-routes to a
different step twin.
"""

from __future__ import annotations

from typing import List

from sphexa_tpu.devtools.audit.core import EntryTrace, register
from sphexa_tpu.devtools.common import Finding


@register(
    "JXA402", "knob-inertness",
    "a tuning knob's declared off sentinel perturbs the baseline step "
    "lowering — the off path leaks into the never-mentioned program",
)
def check(trace: EntryTrace) -> List[Finding]:
    if trace.case.knob_probes is None:
        return []
    from sphexa_tpu.devtools.audit.lowerdiff import _deltas

    findings: List[Finding] = []
    for probe in trace.case.knob_probes():
        if probe.off.digest == probe.base.digest:
            continue
        d = _deltas(probe.base.lock_payload(), probe.off)
        where = (f"first divergence at eqn #{d['first_divergence']} "
                 f"(phase {d['first_divergence_phase']})"
                 if d["first_divergence"] is not None
                 else "consts differ (no per-eqn divergence)")
        findings.append(trace.finding(
            "JXA402",
            f"knob {probe.knob!r}: tuned={{{probe.knob}: "
            f"{probe.off_value!r}}} does not lower identically to "
            f"leaving the knob unset ({probe.detail}); "
            f"eqn delta {d['eqns']:+d}, {where}"
            + (f", phases changed: {', '.join(d['phases_changed'][:3])}"
               if d["phases_changed"] else "")
            + (f", phases added: {', '.join(d['phases_added'][:3])}"
               if d["phases_added"] else "")
            + " — the off sentinel must be indistinguishable from "
              "absence (fix the resolution default or the sentinel "
              "declaration in tuning/knobs.py).",
        ))
    return findings
