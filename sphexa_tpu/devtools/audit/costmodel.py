"""jaxcost: static per-phase roofline cost model over traced jaxprs.

The fourth static layer (after jaxlint/AST, jaxaudit/trace, shardcheck/
SPMD): predict the per-phase device-time table ``sphexa-telemetry
trace`` measures from a chip capture, without a chip. The walk reuses
``spmd.py``'s unwrap conventions (nested ClosedJaxprs, shard_map/scan
bodies, ``pallas_call`` treated as a call-site leaf) and attributes
every eqn to the ``util/phases.py`` taxonomy through the
``sphexa/<phase>`` named scopes PR 7 stamped into
``eqn.source_info.name_stack`` — the same scopes traceview reads back
out of an xplane, so the static and measured tables join phase-by-phase.

Per eqn the model accumulates:

- **FLOPs** from per-primitive cost rules (``FLOP_RULES`` /
  ``ELEMENTWISE_WEIGHTS``): dot/conv from dimension numbers, elementwise
  and reductions from operand sizes (transcendentals weighted), scan
  bodies multiplied by the static trip count, ``while`` bodies counted
  once (trip count is dynamic — a documented lower bound), ``cond``
  charged at its most expensive branch, ``pallas_call`` kernels at body
  FLOPs x grid when the grid is readable.
- **HBM bytes** from operand+result avals, twice: an upper bound (every
  eqn reads/writes HBM — no fusion) and a lower bound with a same-phase
  fusion discount (each value is charged once per phase — perfect
  intra-phase fusion, the XLA-on-TPU asymptote).
- **ICI bytes** for collective primitives (``spmd.COLLECTIVE_PRIMS``),
  per-shard result volume — the same accounting JXA203 gates.

``predict`` divides the tallies by a ``devices.py`` model into a
per-phase ms table + arithmetic intensity and classifies each phase
against the ridge point. Eqns with no sphexa scope roll up into an
``unattributed`` bucket and a FLOP-coverage fraction, mirroring
traceview's coverage gate.

Calibration (``sphexa-telemetry trace <dir> --predict``) joins a
measured capture against the prediction for the program that produced
it and gates the per-phase measured/predicted ratios inside a committed
band — the model can never silently drift from what chips do.

Jax-free at import (the ``spmd.py`` contract): everything here walks
already-traced jaxprs; jax only loads lazily when a calibration target
has to be traced.
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
from typing import Any, Dict, List, Optional, Tuple

from sphexa_tpu.devtools.audit.devices import DeviceModel, get_device
from sphexa_tpu.devtools.audit.spmd import (
    COLLECTIVE_PRIMS,
    _is_var,
    _sub_jaxprs,
    aval_bytes,
)
from sphexa_tpu.telemetry.traceview import PHASE_RE

__all__ = [
    "PhaseCost",
    "CostReport",
    "PhasePrediction",
    "Prediction",
    "analyze_jaxpr",
    "cost_report",
    "predict",
    "load_budget",
    "validate_budget",
    "load_calibration",
    "calibration_join",
    "predict_for_target",
]

UNATTRIBUTED = "unattributed"

# ---------------------------------------------------------------------------
# per-primitive FLOP cost rules
# ---------------------------------------------------------------------------

#: FLOPs charged per OUTPUT element for elementwise-shaped primitives.
#: Primitives absent from every table below default to weight 1 (one
#: vector op per element); pure data movement is weight 0. These are the
#: "per-primitive cost rules" the calibration fixture pins — corrupting
#: one moves a phase's predicted ms outside the committed band.
ELEMENTWISE_WEIGHTS: Dict[str, float] = {
    # transcendentals: multi-pass polynomial/Newton implementations
    "exp": 8.0, "exp2": 8.0, "log": 8.0, "log1p": 8.0, "expm1": 8.0,
    "sin": 8.0, "cos": 8.0, "tan": 8.0, "tanh": 8.0, "logistic": 8.0,
    "erf": 8.0, "erfc": 8.0, "erf_inv": 8.0, "atan2": 8.0,
    "asin": 8.0, "acos": 8.0, "atan": 8.0, "sinh": 8.0, "cosh": 8.0,
    "asinh": 8.0, "acosh": 8.0, "atanh": 8.0, "pow": 8.0,
    # divide/rsqrt-class: iterative refinement
    "div": 4.0, "sqrt": 4.0, "rsqrt": 4.0, "cbrt": 4.0, "rem": 4.0,
    "integer_pow": 2.0,
    # data movement: bytes are charged, arithmetic is not
    "broadcast_in_dim": 0.0, "reshape": 0.0, "transpose": 0.0,
    "squeeze": 0.0, "expand_dims": 0.0, "slice": 0.0, "rev": 0.0,
    "concatenate": 0.0, "pad": 0.0, "gather": 0.0, "dynamic_slice": 0.0,
    "dynamic_update_slice": 0.0, "copy": 0.0, "convert_element_type": 0.0,
    "bitcast_convert_type": 0.0, "iota": 0.0, "stop_gradient": 0.0,
    "device_put": 0.0, "split": 0.0, "optimization_barrier": 0.0,
    "axis_index": 0.0,
}

#: primitives whose FLOPs scale with the INPUT (reduction-shaped):
#: one op per input element
_REDUCE_PRIMS = frozenset({
    "reduce_sum", "reduce_max", "reduce_min", "reduce_prod",
    "reduce_and", "reduce_or", "reduce_xor", "reduce_precision",
    "argmax", "argmin", "reduce",
    "cumsum", "cumprod", "cummax", "cummin", "cumlogsumexp",
    "scatter", "scatter-add", "scatter_add", "scatter_mul",
    "scatter_min", "scatter_max",
})


def _elems(aval) -> int:
    shape = getattr(aval, "shape", None)
    if not shape:
        return 1
    n = 1
    for d in shape:
        n *= int(d)
    return n


def _dtype_name(aval) -> str:
    dt = getattr(aval, "dtype", None)
    return getattr(dt, "name", "float32") if dt is not None else "float32"


def _out_elems(eqn) -> int:
    return sum(_elems(v.aval) for v in eqn.outvars if hasattr(v, "aval"))


def _in_elems(eqn) -> int:
    return sum(_elems(v.aval) for v in eqn.invars if hasattr(v, "aval"))


def _dot_general_flops(eqn) -> float:
    """2 * batch * M * N * K from the dimension numbers + lhs/rhs avals."""
    (lc, rc), (lb, _rb) = eqn.params["dimension_numbers"]
    lhs, rhs = (v.aval for v in eqn.invars[:2])
    batch = 1
    for d in lb:
        batch *= int(lhs.shape[d])
    contract = 1
    for d in lc:
        contract *= int(lhs.shape[d])
    lhs_free = _elems(lhs) // max(batch * contract, 1)
    rc_set = set(rc)
    rb_set = set(_rb)
    rhs_free = 1
    for i, d in enumerate(rhs.shape):
        if i not in rc_set and i not in rb_set:
            rhs_free *= int(d)
    return 2.0 * batch * lhs_free * rhs_free * contract


def _conv_flops(eqn) -> float:
    """2 * output elements * kernel taps per output feature."""
    out = eqn.outvars[0].aval
    rhs = eqn.invars[1].aval
    dn = eqn.params.get("dimension_numbers")
    out_feature = int(rhs.shape[dn.rhs_spec[0]]) if dn is not None \
        else int(rhs.shape[-1])
    taps = _elems(rhs) / max(out_feature, 1)
    groups = int(eqn.params.get("feature_group_count", 1) or 1)
    return 2.0 * _elems(out) * taps / max(groups, 1)


def _sort_flops(eqn) -> float:
    n = _in_elems(eqn)
    return float(n) * max(math.log2(max(n, 2)), 1.0)


def _reduce_window_flops(eqn) -> float:
    window = eqn.params.get("window_dimensions") or ()
    taps = 1
    for d in window:
        taps *= int(d)
    return float(_out_elems(eqn)) * max(taps, 1)


#: primitive name -> flops(eqn); consulted before the elementwise tables
FLOP_RULES: Dict[str, Any] = {
    "dot_general": _dot_general_flops,
    "conv_general_dilated": _conv_flops,
    "sort": _sort_flops,
    "reduce_window_sum": _reduce_window_flops,
    "reduce_window_max": _reduce_window_flops,
    "reduce_window_min": _reduce_window_flops,
    "reduce_window": _reduce_window_flops,
    "select_and_scatter_add": _reduce_window_flops,
}


def eqn_flops(eqn) -> float:
    """Per-primitive FLOP estimate for one leaf eqn."""
    prim = eqn.primitive.name
    rule = FLOP_RULES.get(prim)
    if rule is not None:
        return float(rule(eqn))
    if prim in _REDUCE_PRIMS:
        return float(_in_elems(eqn))
    return float(_out_elems(eqn)) * ELEMENTWISE_WEIGHTS.get(prim, 1.0)


# ---------------------------------------------------------------------------
# the per-phase accumulator + jaxpr walk
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class PhaseCost:
    """Accumulated static cost of one phase bucket."""

    phase: str
    flops: float = 0.0
    flops_by_dtype: Dict[str, float] = dataclasses.field(default_factory=dict)
    hbm_lower: float = 0.0      # same-phase fusion discount applied
    hbm_upper: float = 0.0      # every eqn round-trips HBM
    ici_bytes: float = 0.0
    eqns: int = 0

    def dominant_dtype(self) -> str:
        if not self.flops_by_dtype:
            return "float32"
        return max(self.flops_by_dtype.items(), key=lambda kv: kv[1])[0]


@dataclasses.dataclass
class CostReport:
    """Per-phase static cost of one traced entry."""

    phases: Dict[str, PhaseCost]      # taxonomy phases + any unknown scopes
    unattributed: PhaseCost           # eqns with no sphexa/ scope at all
    unknown_scopes: Tuple[str, ...]   # sphexa/<x> with x outside PHASES
    total_flops: float
    coverage: float                   # on-taxonomy FLOP share (1.0 if 0 FLOPs)


class _Acc:
    """Mutable walk state: phase buckets + per-phase fusion seen-sets."""

    def __init__(self) -> None:
        self.buckets: Dict[str, PhaseCost] = {}
        self._seen: Dict[str, set] = {}

    def bucket(self, phase: str) -> PhaseCost:
        b = self.buckets.get(phase)
        if b is None:
            b = self.buckets[phase] = PhaseCost(phase=phase)
            self._seen[phase] = set()
        return b

    def add_eqn(self, phase: str, flops: float, dtype: str,
                io_vars, mult: float, ici: float = 0.0) -> None:
        b = self.bucket(phase)
        b.eqns += 1
        b.flops += flops * mult
        if flops:
            b.flops_by_dtype[dtype] = \
                b.flops_by_dtype.get(dtype, 0.0) + flops * mult
        b.ici_bytes += ici * mult
        seen = self._seen[phase]
        for v in io_vars:
            nb = aval_bytes(getattr(v, "aval", None))
            b.hbm_upper += nb * mult
            if id(v) not in seen:
                seen.add(id(v))
                b.hbm_lower += nb * mult

    def merge(self, other: "_Acc") -> None:
        for phase, ob in other.buckets.items():
            b = self.bucket(phase)
            b.eqns += ob.eqns
            b.flops += ob.flops
            for d, f in ob.flops_by_dtype.items():
                b.flops_by_dtype[d] = b.flops_by_dtype.get(d, 0.0) + f
            b.hbm_lower += ob.hbm_lower
            b.hbm_upper += ob.hbm_upper
            b.ici_bytes += ob.ici_bytes

    def total_flops(self) -> float:
        return sum(b.flops for b in self.buckets.values())


def _phase_of(eqn, inherited: str) -> str:
    info = getattr(eqn, "source_info", None)
    stack = getattr(info, "name_stack", None) if info is not None else None
    if stack is None:
        return inherited
    found = PHASE_RE.findall(str(stack))
    return found[-1] if found else inherited


def _pallas_leaf(eqn, phase: str, mult: float, acc: _Acc) -> None:
    """pallas_call is a liveness LEAF (the JXA202 convention): HBM at the
    call-site operands/results; FLOPs best-effort from the kernel body x
    grid steps (0 when the grid is unreadable on this jax version)."""
    flops = 0.0
    dtype = "float32"
    try:
        gm = eqn.params.get("grid_mapping")
        grid = tuple(int(g) for g in (getattr(gm, "grid", None) or ()))
        steps = 1
        for g in grid:
            steps *= max(g, 1)
        body = eqn.params.get("jaxpr")
        inner = getattr(body, "jaxpr", body)
        if inner is not None and hasattr(inner, "eqns"):
            flops = sum(eqn_flops(e) for e in inner.eqns
                        if not _sub_jaxprs(e)) * steps
        out0 = next((v for v in eqn.outvars if hasattr(v, "aval")), None)
        if out0 is not None:
            dtype = _dtype_name(out0.aval)
    except Exception:  # noqa: BLE001 - a cost estimate must not crash audits
        flops = 0.0
    io = [v for v in eqn.invars if _is_var(v)] + list(eqn.outvars)
    acc.add_eqn(phase, flops, dtype, io, mult)


def _walk(jaxpr, inherited: str, mult: float, acc: _Acc) -> None:
    for eqn in jaxpr.eqns:
        phase = _phase_of(eqn, inherited)
        prim = eqn.primitive.name

        if prim == "pallas_call":
            _pallas_leaf(eqn, phase, mult, acc)
            continue

        if prim == "cond":
            # charge the most expensive branch, not the sum of all
            branch_accs = []
            for br in eqn.params.get("branches", ()):
                sub = getattr(br, "jaxpr", br)
                a = _Acc()
                _walk(sub, phase, mult, a)
                branch_accs.append(a)
            if branch_accs:
                acc.merge(max(branch_accs, key=lambda a: a.total_flops()))
                continue

        subs = _sub_jaxprs(eqn)
        if subs:
            submult = mult
            if prim == "scan":
                submult = mult * max(int(eqn.params.get("length", 1) or 1), 1)
            # while bodies are charged once: the trip count is dynamic,
            # so the model is a documented lower bound there
            for sub in subs:
                _walk(sub, phase, submult, acc)
            continue

        out0 = next((v for v in eqn.outvars if hasattr(v, "aval")), None)
        dtype = _dtype_name(out0.aval) if out0 is not None else "float32"
        ici = 0.0
        if prim in COLLECTIVE_PRIMS:
            ici = float(sum(aval_bytes(v.aval) for v in eqn.outvars
                            if hasattr(v, "aval")))
        io = [v for v in eqn.invars if _is_var(v)] + list(eqn.outvars)
        acc.add_eqn(phase, eqn_flops(eqn), dtype, io, mult, ici=ici)


def analyze_jaxpr(jaxpr) -> CostReport:
    """Walk one (raw) jaxpr into a per-phase ``CostReport``. Accepts a
    ClosedJaxpr too (``.jaxpr`` is unwrapped)."""
    jaxpr = getattr(jaxpr, "jaxpr", jaxpr)
    acc = _Acc()
    _walk(jaxpr, "", 1.0, acc)

    from sphexa_tpu.util.phases import PHASES  # lazy: phases.py imports jax

    taxonomy = set(PHASES)
    unattributed = acc.buckets.pop("", None) or PhaseCost(phase=UNATTRIBUTED)
    unattributed.phase = UNATTRIBUTED
    unknown = tuple(sorted(p for p in acc.buckets if p not in taxonomy))
    total = sum(b.flops for b in acc.buckets.values()) + unattributed.flops
    on_tax = sum(b.flops for p, b in acc.buckets.items() if p in taxonomy)
    return CostReport(
        phases=dict(sorted(acc.buckets.items())),
        unattributed=unattributed,
        unknown_scopes=unknown,
        total_flops=total,
        coverage=(on_tax / total) if total > 0 else 1.0,
    )


def cost_report(trace, ctx=None) -> CostReport:
    """Cached per-entry report (the ``spmd_report`` contract: one walk
    per ``EntryTrace``, shared by every JXA3xx rule and the cost CLI)."""
    cached = getattr(trace, "_cost_report", None)
    if cached is not None:
        return cached
    report = analyze_jaxpr(trace.closed_jaxpr.jaxpr)
    trace._cost_report = report
    return report


# ---------------------------------------------------------------------------
# roofline prediction
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class PhasePrediction:
    phase: str
    flops: float
    hbm_lower: float
    hbm_upper: float
    ici_bytes: float
    ai: float              # FLOPs / fused (lower-bound) HBM bytes
    compute_ms: float
    hbm_ms: float          # fused bytes / HBM BW
    hbm_ms_upper: float    # unfused bytes / HBM BW
    ici_ms: float
    ms: float              # roofline headline: max(compute, hbm, ici)
    ms_upper: float
    bound: str             # "compute" | "memory" | "ici"
    dtype: str

    def as_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


@dataclasses.dataclass(frozen=True)
class Prediction:
    device: str
    rows: Tuple[PhasePrediction, ...]   # phases sorted by headline ms desc
    unattributed: PhasePrediction
    total_ms: float                     # all buckets, headline bound
    total_ms_upper: float
    coverage: float
    unknown_scopes: Tuple[str, ...]

    def row(self, phase: str) -> Optional[PhasePrediction]:
        if phase == UNATTRIBUTED:
            return self.unattributed
        return next((r for r in self.rows if r.phase == phase), None)


def _predict_bucket(b: PhaseCost, dev: DeviceModel) -> PhasePrediction:
    compute_s = sum(f / dev.peak_for(d) for d, f in b.flops_by_dtype.items())
    hbm_s = b.hbm_lower / dev.hbm_bytes_per_s
    hbm_up_s = b.hbm_upper / dev.hbm_bytes_per_s
    ici_s = b.ici_bytes / dev.ici_bytes_per_s
    ms = max(compute_s, hbm_s, ici_s) * 1e3
    ms_upper = max(compute_s, hbm_up_s, ici_s) * 1e3
    if ici_s >= max(compute_s, hbm_s):
        bound = "ici"
    elif compute_s >= hbm_s:
        bound = "compute"
    else:
        bound = "memory"
    return PhasePrediction(
        phase=b.phase, flops=b.flops, hbm_lower=b.hbm_lower,
        hbm_upper=b.hbm_upper, ici_bytes=b.ici_bytes,
        ai=b.flops / b.hbm_lower if b.hbm_lower > 0 else float("inf"),
        compute_ms=compute_s * 1e3, hbm_ms=hbm_s * 1e3,
        hbm_ms_upper=hbm_up_s * 1e3, ici_ms=ici_s * 1e3,
        ms=ms, ms_upper=ms_upper, bound=bound, dtype=b.dominant_dtype(),
    )


def predict(report: CostReport, device) -> Prediction:
    """Classify a ``CostReport`` against a device model (name or
    ``DeviceModel``) into the predicted per-phase ms table."""
    dev = device if isinstance(device, DeviceModel) else get_device(device)
    rows = tuple(sorted(
        (_predict_bucket(b, dev) for b in report.phases.values()),
        key=lambda r: -r.ms))
    un = _predict_bucket(report.unattributed, dev)
    return Prediction(
        device=dev.name, rows=rows, unattributed=un,
        total_ms=sum(r.ms for r in rows) + un.ms,
        total_ms_upper=sum(r.ms_upper for r in rows) + un.ms_upper,
        coverage=report.coverage, unknown_scopes=report.unknown_scopes,
    )


def memory_bound_phases(pred: Prediction, dev: Optional[DeviceModel] = None,
                        ) -> List[PhasePrediction]:
    """Phases whose arithmetic intensity sits below the device ridge
    point, heaviest first — the static ranking of ROADMAP item-2's
    fusion/cadence candidates."""
    dev = dev or get_device(pred.device)
    return [r for r in pred.rows if r.ai < dev.ridge(r.dtype)]


# ---------------------------------------------------------------------------
# committed per-phase budget file (the static analog of TELEMETRY_LOCK)
# ---------------------------------------------------------------------------

BUDGET_SCHEMA = 1


def validate_budget(doc: Any) -> List[str]:
    """Schema errors for a COST_BUDGET.json document; [] when valid."""
    errs: List[str] = []
    if not isinstance(doc, dict):
        return ["budget document is not a JSON object"]
    if doc.get("schema") != BUDGET_SCHEMA:
        errs.append(f"schema must be {BUDGET_SCHEMA}, got {doc.get('schema')!r}")
    try:
        get_device(str(doc.get("device")))
    except ValueError as e:
        errs.append(str(e))
    entries = doc.get("entries")
    if not isinstance(entries, dict) or not entries:
        errs.append("entries must be a non-empty object keyed by entry name")
        return errs
    for name, spec in entries.items():
        if not isinstance(spec, dict):
            errs.append(f"{name}: entry spec is not an object")
            continue
        phases = spec.get("phases")
        if not isinstance(phases, dict) or not phases:
            errs.append(f"{name}: phases must be a non-empty object")
            continue
        for ph, ms in phases.items():
            if not isinstance(ms, (int, float)) or ms <= 0:
                errs.append(f"{name}: phase {ph!r} budget must be a "
                            f"positive number, got {ms!r}")
        total = spec.get("total_ms")
        if total is not None and (not isinstance(total, (int, float))
                                  or total <= 0):
            errs.append(f"{name}: total_ms must be a positive number")
    return errs


def load_budget(path: str) -> Dict[str, Any]:
    """Load + validate a budget file; raises ``ValueError`` with every
    schema problem (a broken gate must not pass silently)."""
    with open(path, encoding="utf-8") as fh:
        doc = json.load(fh)
    errs = validate_budget(doc)
    if errs:
        raise ValueError(f"{path}: " + "; ".join(errs))
    return doc


# ---------------------------------------------------------------------------
# calibration against a measured capture (trace --predict)
# ---------------------------------------------------------------------------

CALIBRATION_FILE = "calibration.json"


def load_calibration(trace_dir: str) -> Optional[Dict[str, Any]]:
    """The capture's committed calibration declaration, or None. Format::

        {"schema": 1,
         "target": "scripts/make_trace_fixture.py::trace_fixture",
         "device": "cpu-smoke", "tolerance": 1.8,
         "phases": {"density": {"ratio": 123.4}, ...}}

    ``ratio`` is the recorded measured_us / predicted_us for the phase;
    the gate holds while fresh ratios stay within ``tolerance`` x of it.
    """
    path = os.path.join(trace_dir, CALIBRATION_FILE)
    if not os.path.exists(path):
        return None
    with open(path, encoding="utf-8") as fh:
        doc = json.load(fh)
    errs: List[str] = []
    if not isinstance(doc.get("target"), str) or "::" not in doc["target"]:
        errs.append("target must be '<module-or-file>::<entry-name>'")
    try:
        get_device(str(doc.get("device")))
    except ValueError as e:
        errs.append(str(e))
    phases = doc.get("phases")
    if not isinstance(phases, dict) or not phases:
        errs.append("phases must be a non-empty object")
    else:
        for ph, spec in phases.items():
            r = spec.get("ratio") if isinstance(spec, dict) else None
            if not isinstance(r, (int, float)) or r <= 0:
                errs.append(f"phase {ph!r}: ratio must be a positive number")
    tol = doc.get("tolerance", 2.0)
    if not isinstance(tol, (int, float)) or tol <= 1.0:
        errs.append("tolerance must be a number > 1")
    if errs:
        raise ValueError(f"{path}: " + "; ".join(errs))
    return doc


def predict_for_target(target: str, device: str) -> Prediction:
    """Trace a registry target (``<module-or-file>::<entry>``) and
    predict it — the only jax-loading path in this module."""
    mod_name, _, entry_name = target.partition("::")
    from sphexa_tpu.devtools.audit.cli import _load_target
    from sphexa_tpu.devtools.audit.core import (
        EntryTrace,
        entries_from_namespace,
    )

    mod = _load_target(mod_name)
    entries = {e.name: e for e in entries_from_namespace(vars(mod))}
    if entry_name not in entries:
        raise ValueError(f"{mod_name}: no @entrypoint named {entry_name!r} "
                         f"(has: {sorted(entries)})")
    entry = entries[entry_name]
    trace = EntryTrace(entry, entry.build())
    return predict(cost_report(trace), device)


def calibration_join(summary: Dict[str, Any], calib: Dict[str, Any],
                     ) -> Dict[str, Any]:
    """Join a traceview summary against the static prediction of the
    calibration target; returns rows + band violations.

    A calibrated phase missing from either side is a violation: the
    capture and the program drifting apart is exactly the failure this
    gate exists to catch.
    """
    pred = predict_for_target(calib["target"], calib["device"])
    tol = float(calib.get("tolerance", 2.0))
    measured = {p["phase"]: float(p["us"]) for p in summary.get("phases", ())}
    rows: List[Dict[str, Any]] = []
    violations: List[str] = []
    for phase, spec in sorted(calib["phases"].items()):
        ref = float(spec["ratio"])
        lo, hi = ref / tol, ref * tol
        row: Dict[str, Any] = {"phase": phase, "ref_ratio": ref,
                               "band": [lo, hi]}
        prow = pred.row(phase)
        mus = measured.get(phase)
        if prow is None or prow.ms <= 0:
            row["status"] = "no-prediction"
            violations.append(f"{phase}: no static prediction for the "
                              f"calibration target")
        elif mus is None:
            row["status"] = "no-measurement"
            violations.append(f"{phase}: absent from the measured capture")
        else:
            row["measured_us"] = mus
            row["predicted_us"] = prow.ms * 1e3
            ratio = mus / (prow.ms * 1e3)
            row["ratio"] = ratio
            row["status"] = "ok" if lo <= ratio <= hi else "out-of-band"
            if row["status"] != "ok":
                violations.append(
                    f"{phase}: measured/predicted ratio {ratio:.3g} outside "
                    f"[{lo:.3g}, {hi:.3g}] (recorded {ref:.3g} x tolerance "
                    f"{tol:g}) — the cost rules drifted from the capture")
        rows.append(row)
    return {
        "target": calib["target"],
        "device": calib["device"],
        "tolerance": tol,
        "rows": rows,
        "violations": violations,
        "ok": not violations,
    }
