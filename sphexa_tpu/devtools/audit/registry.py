"""jaxaudit entry-point registry: the package's hot jitted functions.

Each ``@entrypoint`` builder constructs a SMALL synthetic case (the
``init/`` case builders at tiny N, the same ``Simulation`` configuration
machinery production uses — so the audited config IS the shipped config)
and returns the traced callable + example args. Builders run lazily per
audit run and import jax-heavy modules inside the function body, so
importing this module stays cheap and device-free.

Conventions:

- step entries audit the plain jit for tracing/execution and the
  ``*_donated`` twin's lowering for the donation rule (``donate=(0,)`` =
  the ParticleState pytree at lowered arg position 0; static args are
  elided from ``args_info``).
- ``carry`` maps (step-1 args, step-1 out) -> step-2 args, giving the
  recompile rule real committed avals (weak types visible).
- sharded entries declare ``mesh_axes`` and size their mesh from
  ``audit_context().mesh_size`` (CLI default 2; ``preflight --mesh P``
  and ``--cpu-devices P`` retrace at campaign-shaped P) — when the
  process has fewer devices they raise ``EntrySkip`` (the tier-1 gate
  runs under the 8-virtual-device CPU mesh and asserts no skips).
- entries carrying an ``exchange_budget_bytes`` declare the analytic
  cross-shard volume (sizing-derived) the JXA203 gate checks the traced
  collective output bytes against.
"""

from __future__ import annotations

import dataclasses
import functools

from sphexa_tpu.devtools.audit.core import (
    EntryCase,
    EntrySkip,
    audit_context,
    entrypoint,
)

# tiny-but-nondegenerate case sizes: big enough for a real neighbor grid
# and a multi-level gravity tree, small enough that a full step traces
# and runs in ~seconds on a CPU host
_SIDE = 6          # 216 particles (cube cases)
_SIDE_GRAV = 6     # sphere cuts (evrard) keep ~half of side^3
# second trace point for the JXA204 tree-growth probe: large enough for
# a real N jump, small enough that the extra retrace stays cheap
_SIDE_GROW = 8

# headroom added to every analytic exchange budget before the JXA203
# volume gate: covers the small fixed-size collectives riding the stage
# (escape sentinels, the all_gathered telemetry scalars, range bounds)
_EXCHANGE_HEADROOM = 262_144


def _mesh_size_and_side():
    """Mesh size for sharded entries, from the audit context (CLI
    default 2 keeps tier-1 cheap; ``preflight --mesh P`` retraces the
    same builders at campaign-shaped P), plus a cube side whose particle
    count splits evenly across it (216 doesn't divide by 16)."""
    import jax

    P = audit_context().mesh_size
    if len(jax.devices()) < P:
        raise EntrySkip(f"needs >= {P} devices for the 'p' mesh "
                        "(sphexa-audit bootstraps one; in-process callers "
                        "use util.cpu_mesh.force_cpu_mesh)")
    side = _SIDE if (_SIDE ** 3) % P == 0 else 8
    return P, side


@functools.lru_cache(maxsize=None)
def _sim(case: str, side: int, prop: str = "std"):
    """Memoized Simulation construction: entries only READ the sim's
    state/config products, so sharing one build between entries (e.g.
    step_nbody + gravity_solve both want the configured evrard nbody
    sim, gravity caps included) halves the audit's setup cost."""
    from sphexa_tpu.init import make_initializer
    from sphexa_tpu.simulation import Simulation

    state, box, const = make_initializer(case)(side)
    return Simulation(state, box, const, prop=prop)


# ---------------------------------------------------------------------------
# propagator step builders (the five production steps)
# ---------------------------------------------------------------------------


def _step_std_case(side: int) -> EntryCase:
    from sphexa_tpu import propagator as prop

    sim = _sim("sedov", side, prop="std")
    cfg, state, box = sim._cfg, sim.state, sim.box
    return EntryCase(
        fn=lambda s, b: prop.step_hydro_std(s, b, cfg, None),
        args=(state, box),
        lower=lambda: prop.step_hydro_std_donated.lower(state, box, cfg,
                                                        None),
        carry=lambda a, out: (out[0], out[1]),
    )


@entrypoint("step_std", donate=(0,))
def step_std():
    case = _step_std_case(_SIDE)
    # JXA204 growth probe: the same step at _SIDE_GROW — cell grids and
    # scan accumulators must not grow superlinearly in N
    case.grow = lambda: (_step_std_case(_SIDE_GROW),
                         _SIDE_GROW ** 3 / _SIDE ** 3)
    return case


@entrypoint("step_ve", donate=(0,))
def step_ve():
    from sphexa_tpu import propagator as prop

    sim = _sim("sedov", _SIDE, prop="ve")
    cfg, state, box = sim._cfg, sim.state, sim.box
    return EntryCase(
        fn=lambda s, b: prop.step_hydro_ve(s, b, cfg, None),
        args=(state, box),
        lower=lambda: prop.step_hydro_ve_donated.lower(state, box, cfg,
                                                       None),
        carry=lambda a, out: (out[0], out[1]),
    )


@entrypoint("step_nbody", donate=(0,))
def step_nbody():
    from sphexa_tpu import propagator as prop

    sim = _sim("evrard", _SIDE_GRAV, prop="nbody")
    cfg, state, box, gtree = sim._cfg, sim.state, sim.box, sim._gtree
    return EntryCase(
        fn=lambda s, b, g: prop.step_nbody(s, b, cfg, g),
        args=(state, box, gtree),
        lower=lambda: prop.step_nbody_donated.lower(state, box, cfg, gtree),
        carry=lambda a, out: (out[0], out[1], a[2]),
    )


@entrypoint("step_turb_ve", donate=(0,))
def step_turb_ve():
    from sphexa_tpu import propagator as prop

    sim = _sim("turbulence", _SIDE, prop="turb-ve")
    cfg, state, box = sim._cfg, sim.state, sim.box
    turb_cfg, turb = sim.turb_cfg, sim.turb_state
    return EntryCase(
        fn=lambda s, b, t: prop.step_turb_ve(s, b, cfg, None, t, turb_cfg),
        args=(state, box, turb),
        lower=lambda: prop.step_turb_ve_donated.lower(
            state, box, cfg, None, turb, turb_cfg),
        carry=lambda a, out: (out[0], out[1], out[3]),
    )


@entrypoint("step_std_cooling", donate=(0,))
def step_std_cooling():
    from sphexa_tpu import propagator as prop

    sim = _sim("evrard-cooling", _SIDE_GRAV, prop="std-cooling")
    cfg, state, box, gtree = sim._cfg, sim.state, sim.box, sim._gtree
    cool_cfg, chem = sim.cooling_cfg, sim.chem
    return EntryCase(
        fn=lambda s, b, g, ch: prop.step_hydro_std_cooling(
            s, b, cfg, g, ch, cool_cfg),
        args=(state, box, gtree, chem),
        lower=lambda: prop.step_hydro_std_cooling_donated.lower(
            state, box, cfg, gtree, chem, cool_cfg),
        carry=lambda a, out: (out[0], out[1], a[2], out[3]),
    )


# ---------------------------------------------------------------------------
# gravity solve (gravity/traversal.py)
# ---------------------------------------------------------------------------


def _gravity_case(side: int):
    """(EntryCase, n) for the evrard gravity solve at one toy side."""
    import jax.numpy as jnp
    import numpy as np

    from sphexa_tpu import native
    from sphexa_tpu.gravity.traversal import compute_gravity

    sim = _sim("evrard", side, prop="nbody")
    s, box = sim.state, sim.box
    keys = native.compute_keys(
        np.asarray(s.x), np.asarray(s.y), np.asarray(s.z),
        np.asarray(box.lo), np.asarray(box.lengths), sim.curve,
    )
    order = native.argsort_keys(keys)
    skeys = jnp.asarray(keys[order])
    xs, ys, zs, ms, hs = (
        jnp.asarray(np.asarray(f)[order])
        for f in (s.x, s.y, s.z, s.m, s.h)
    )
    meta, gcfg = sim._cfg.grav_meta, sim._cfg.gravity
    return EntryCase(
        fn=lambda x, y, z, m, h, sk, b, gt: compute_gravity(
            x, y, z, m, h, sk, b, gt, meta, gcfg),
        args=(xs, ys, zs, ms, hs, skeys, box, sim._gtree),
    ), int(s.n)


@entrypoint("gravity_solve")
def gravity_solve():
    case, n = _gravity_case(_SIDE_GRAV)
    # JXA204 growth probe: the round-10 carried caution names exactly
    # this entry — a superlinear TREE build hiding in the traced-size
    # exemption. Two-point probe at _SIDE_GROW closes it.
    def grow():
        grown, n2 = _gravity_case(_SIDE_GROW)
        return grown, n2 / n

    case.grow = grow
    return case


# ---------------------------------------------------------------------------
# sparse halo exchange (parallel/exchange.py) — sharded on the CPU mesh
# ---------------------------------------------------------------------------


@entrypoint("halo_exchange_sparse", mesh_axes=("p",))
def halo_exchange_sparse():
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec

    from sphexa_tpu import native
    from sphexa_tpu.init import make_initializer
    from sphexa_tpu.parallel import exchange as ex
    from sphexa_tpu.parallel import make_mesh
    from sphexa_tpu.propagator import shard_map
    from sphexa_tpu.simulation import make_propagator_config

    P, side = _mesh_size_and_side()
    state, box, const = make_initializer("sedov")(side)
    cfg = make_propagator_config(state, box, const)
    # globally SFC-sorted arrays, as the sharded step provides them
    keys = native.compute_keys(
        np.asarray(state.x), np.asarray(state.y), np.asarray(state.z),
        np.asarray(box.lo), np.asarray(box.lengths), cfg.curve,
    )
    order = native.argsort_keys(keys)
    skeys = jnp.asarray(keys[order])
    x, y, z, h, m = (
        jnp.asarray(np.asarray(f)[order])
        for f in (state.x, state.y, state.z, state.h, state.m)
    )
    mesh = make_mesh(P)
    S_shard = state.n // P
    nbr = cfg.nbr
    if nbr.run_cap > S_shard:  # same clamp as the sharded force stages
        nbr = dataclasses.replace(nbr, run_cap=S_shard)
    hmax = (S_shard,) * (P - 1)  # full per-distance coverage at tiny N

    def stage(b, keys, x, y, z, h, m):
        # the 5-tuple contract: the per-shard telemetry dict rides the
        # audited trace too, so JXA104/JXA106 cover the schema-v2 metric
        # plumbing (all_gathered exchange scalars) alongside the exchange
        from sphexa_tpu.propagator import _shard_metrics

        ranges, serve, jbuf, escaped, hmetrics = ex.shard_halo_stage_sparse(
            x, y, z, h, keys, b, nbr, P, hmax, "p"
        )
        halo = serve((x, y, z, m))
        jx, jy, jz, jm = jbuf((x, y, z, m), halo)
        # chain the tail reductions after the exchange and each other —
        # escaped/hmetrics are computed PRE-serve, so without the pins
        # these collectives race the ppermutes (the JXA201 class)
        esc = jax.lax.pmax(
            ex.chain_after(jnp.asarray(escaped, jnp.int32), jx), "p"
        )
        smetrics = _shard_metrics(ranges, escaped, hmetrics, "p", token=esc)
        return jx, jy, jz, jm, esc, smetrics

    Pp, Pr = PartitionSpec("p"), PartitionSpec()
    from sphexa_tpu.propagator import SHARD_DIAG_KEYS

    fn = jax.jit(shard_map(
        stage, mesh=mesh,
        in_specs=(Pr, Pp, Pp, Pp, Pp, Pp, Pp),
        out_specs=(Pp, Pp, Pp, Pp, Pr, {k: Pr for k in SHARD_DIAG_KEYS}),
        check_vma=False,
    ))
    return EntryCase(
        fn=fn, args=(box, skeys, x, y, z, h, m),
        # analytic serve volume: hmax rows per peer distance x 4 fields
        exchange_budget_bytes=sum(hmax) * 4 * 4 + _EXCHANGE_HEADROOM,
    )


@entrypoint("halo_exchange_windowed", mesh_axes=("p",))
def halo_exchange_windowed():
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec

    from sphexa_tpu import native
    from sphexa_tpu.init import make_initializer
    from sphexa_tpu.parallel import exchange as ex
    from sphexa_tpu.parallel import make_mesh
    from sphexa_tpu.propagator import shard_map
    from sphexa_tpu.simulation import make_propagator_config

    P, side = _mesh_size_and_side()
    state, box, const = make_initializer("sedov")(side)
    cfg = make_propagator_config(state, box, const)
    keys = native.compute_keys(
        np.asarray(state.x), np.asarray(state.y), np.asarray(state.z),
        np.asarray(box.lo), np.asarray(box.lengths), cfg.curve,
    )
    order = native.argsort_keys(keys)
    skeys = jnp.asarray(keys[order])
    x, y, z, h, m = (
        jnp.asarray(np.asarray(f)[order])
        for f in (state.x, state.y, state.z, state.h, state.m)
    )
    mesh = make_mesh(P)
    S_shard = state.n // P
    Wmax = S_shard  # full-slab windows, as the gravity near field uses
    nbr = cfg.nbr
    if nbr.run_cap > S_shard:
        nbr = dataclasses.replace(nbr, run_cap=S_shard)

    def stage(b, keys, x, y, z, h, m):
        from sphexa_tpu.propagator import _shard_metrics

        ranges, serve, jbuf, escaped, hmetrics = ex.shard_halo_stage(
            x, y, z, h, keys, b, nbr, P, Wmax, "p"
        )
        halo = serve((x, y, z, m))
        jx, jy, jz, jm = jbuf((x, y, z, m), halo)
        esc = jax.lax.pmax(
            ex.chain_after(jnp.asarray(escaped, jnp.int32), jx), "p"
        )
        smetrics = _shard_metrics(ranges, escaped, hmetrics, "p", token=esc)
        return jx, jy, jz, jm, esc, smetrics

    Pp, Pr = PartitionSpec("p"), PartitionSpec()
    from sphexa_tpu.propagator import SHARD_DIAG_KEYS

    fn = jax.jit(shard_map(
        stage, mesh=mesh,
        in_specs=(Pr, Pp, Pp, Pp, Pp, Pp, Pp),
        out_specs=(Pp, Pp, Pp, Pp, Pr, {k: Pr for k in SHARD_DIAG_KEYS}),
        check_vma=False,
    ))
    return EntryCase(
        fn=fn, args=(box, skeys, x, y, z, h, m),
        # analytic serve volume: one all_to_all of P windows x 4 fields
        exchange_budget_bytes=P * Wmax * 4 * 4 + _EXCHANGE_HEADROOM,
    )


# ---------------------------------------------------------------------------
# sharded gravity: psum multipole upsweep + LET traversal + windowed
# near-field exchange (propagator._gravity_sharded_stage) — the campaign
# gravity program, traced whole so the JXA2xx rules see the full
# collective schedule
# ---------------------------------------------------------------------------


@entrypoint("gravity_sharded", mesh_axes=("p",))
def gravity_sharded():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from sphexa_tpu import native
    from sphexa_tpu import propagator as prop
    from sphexa_tpu.init import make_initializer
    from sphexa_tpu.parallel import make_mesh
    from sphexa_tpu.simulation import Simulation

    P, _ = _mesh_size_and_side()
    state, box, const = make_initializer("evrard")(_SIDE_GRAV)
    # evrard's sphere cut leaves an arbitrary n; trim to a multiple of
    # 16 so one state shards on any audited mesh size
    n16 = (state.n // 16) * 16
    state = jax.tree.map(
        lambda a: a[:n16] if getattr(a, "ndim", 0) == 1 else a, state)
    sim = Simulation(state, box, const, prop="nbody")
    s = sim.state
    keys = native.compute_keys(
        np.asarray(s.x), np.asarray(s.y), np.asarray(s.z),
        np.asarray(sim.box.lo), np.asarray(sim.box.lengths), sim.curve,
    )
    order = native.argsort_keys(keys)
    skeys = jnp.asarray(keys[order])
    xs, ys, zs, ms, hs = (
        jnp.asarray(np.asarray(f)[order])
        for f in (s.x, s.y, s.z, s.m, s.h)
    )
    sstate = dataclasses.replace(s, x=xs, y=ys, z=zs, m=ms, h=hs)
    cfg_sh = dataclasses.replace(sim._cfg, mesh=make_mesh(P),
                                 shard_axis="p")
    # gtree rides as a TRACED arg (O(tree) replicated coarse structure,
    # too big for a baked-in jaxpr constant)
    return EntryCase(
        fn=lambda st, bb, k, gt: prop._gravity_sharded_stage(
            st, bb, cfg_sh, gt, k),
        args=(sstate, sim.box, skeys, sim._gtree),
    )


@entrypoint("gravity_sharded_windowed", mesh_axes=("p",))
def gravity_sharded_windowed():
    """The MAC-sized sparse gravity near field: gravity_sharded's
    program with per-distance row caps from sizing.device_gravity_halo
    bound into the serve (exchange.serve_sparse riding the stage). Sized
    at a node count and opening angle where the MAC genuinely prunes
    (evrard side 20, theta 0.8 — at ``--mesh 4`` the sized volume sits
    strictly below the full-slab baseline; docs/NEXT.md round 13), so
    JXA203 records the gravity comm diet next to the full-slab entry's
    number and JXA201 proves the longer chained collective schedule."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from sphexa_tpu import native
    from sphexa_tpu import propagator as prop
    from sphexa_tpu.init import make_initializer
    from sphexa_tpu.parallel import make_mesh
    from sphexa_tpu.parallel.sizing import device_gravity_halo
    from sphexa_tpu.simulation import Simulation

    P, _ = _mesh_size_and_side()
    state, box, const = make_initializer("evrard")(20)
    n16 = (state.n // 16) * 16
    state = jax.tree.map(
        lambda a: a[:n16] if getattr(a, "ndim", 0) == 1 else a, state)
    sim = Simulation(state, box, const, prop="nbody", theta=0.8)
    s = sim.state
    keys = native.compute_keys(
        np.asarray(s.x), np.asarray(s.y), np.asarray(s.z),
        np.asarray(sim.box.lo), np.asarray(sim.box.lengths), sim.curve,
    )
    order = native.argsort_keys(keys)
    skeys = jnp.asarray(keys[order])
    xs, ys, zs, ms, hs = (
        jnp.asarray(np.asarray(f)[order])
        for f in (s.x, s.y, s.z, s.m, s.h)
    )
    sstate = dataclasses.replace(s, x=xs, y=ys, z=zs, m=ms, h=hs)
    cells = device_gravity_halo(
        xs, ys, zs, ms, skeys, sim.box, sim._gtree, sim._cfg.grav_meta,
        theta=sim.theta, P=P,
    )
    cfg_sh = dataclasses.replace(sim._cfg, mesh=make_mesh(P),
                                 shard_axis="p", grav_cells=cells)
    # 5 served fields (x/y/z/m/h) x f32; the replicated multipole psum
    # and the all_gathered telemetry scalars ride the headroom
    return EntryCase(
        fn=lambda st, bb, k, gt: prop._gravity_sharded_stage(
            st, bb, cfg_sh, gt, k),
        args=(sstate, sim.box, skeys, sim._gtree),
        exchange_budget_bytes=sum(cells) * 5 * 4 + _EXCHANGE_HEADROOM,
    )


# ---------------------------------------------------------------------------
# sharded hydro step: the exact campaign entry — make_sharded_step's
# propagator config (windowed/sparse halo sizing included) traced over
# the audit mesh, with the analytic _halo_info exchange budget as the
# JXA203 volume gate
# ---------------------------------------------------------------------------


@entrypoint("step_std_sharded", mesh_axes=("p",))
def step_std_sharded():
    from sphexa_tpu import propagator as prop
    from sphexa_tpu.init import make_initializer
    from sphexa_tpu.simulation import Simulation

    P, side = _mesh_size_and_side()
    state, box, const = make_initializer("sedov")(side)
    sim = Simulation(state, box, const, prop="std", backend="pallas",
                     num_devices=P)
    hi = sim._halo_info
    # mirror make_sharded_step's config replace so the audited trace IS
    # the stepper's program (tracing the stepper itself would audit its
    # device_put re-sharding prologue, a false JXA104 host boundary)
    cfg_sh = dataclasses.replace(
        sim._cfg, mesh=sim._mesh, shard_axis="p",
        halo_window=(hi["wmax"] if hi["mode"] == "windowed" else 0),
        halo_cells=tuple(hi.get("caps", ())),
    )
    return EntryCase(
        fn=lambda s, b: prop.step_hydro_std(s, b, cfg_sh, None),
        args=(sim.state, sim.box),
        exchange_budget_bytes=hi["bytes_per_step"] + _EXCHANGE_HEADROOM,
    )


# ---------------------------------------------------------------------------
# hierarchical block-timestep step (sph/blockdt.py): the std builder with
# per-particle Δt bins — audited at dt_bins=4 so the fold-key sort, the
# drift-aware resort cond, the due-mask compaction and the masked
# integrate all appear in the traced program (JXA301 covers the new
# sphexa/dt-bins taxonomy phase; the sharded twin holds the JXA201
# collective-order rule over the unchanged force-stage exchange)
# ---------------------------------------------------------------------------


@entrypoint("step_std_blockdt", donate=(0,))
def step_std_blockdt():
    from sphexa_tpu import propagator as prop
    from sphexa_tpu.init import make_initializer
    from sphexa_tpu.simulation import Simulation

    state, box, const = make_initializer("sedov")(_SIDE)
    sim = Simulation(state, box, const, prop="std", dt_bins=4,
                     bin_resort_drift=0.01)
    cfg, bst = sim._cfg, sim._bstate
    state, box = sim.state, sim.box
    return EntryCase(
        fn=lambda s, b, bd: prop.step_hydro_std_blockdt(
            s, b, cfg, None, bd),
        args=(state, box, bst),
        lower=lambda: prop.step_hydro_std_blockdt_donated.lower(
            state, box, cfg, None, bst),
        carry=lambda a, out: (out[0], out[1], out[3]),
    )


@entrypoint("step_std_blockdt_sharded", mesh_axes=("p",))
def step_std_blockdt_sharded():
    from sphexa_tpu import propagator as prop
    from sphexa_tpu.init import make_initializer
    from sphexa_tpu.simulation import Simulation

    P, side = _mesh_size_and_side()
    state, box, const = make_initializer("sedov")(side)
    sim = Simulation(state, box, const, prop="std", backend="pallas",
                     num_devices=P, dt_bins=4)
    hi = sim._halo_info
    # same config mirror as step_std_sharded: the audited trace IS the
    # stepper's program, without its device_put re-sharding prologue
    cfg_sh = dataclasses.replace(
        sim._cfg, mesh=sim._mesh, shard_axis="p",
        halo_window=(hi["wmax"] if hi["mode"] == "windowed" else 0),
        halo_cells=tuple(hi.get("caps", ())),
    )
    return EntryCase(
        fn=lambda s, b, bd: prop.step_hydro_std_blockdt(
            s, b, cfg_sh, None, bd),
        args=(sim.state, sim.box, sim._bstate),
        exchange_budget_bytes=hi["bytes_per_step"] + _EXCHANGE_HEADROOM,
    )


# ---------------------------------------------------------------------------
# in-graph observable ledger (observables/ledger.py) — the science
# reductions every step tail runs; audited standalone so JXA101 (dtype)
# and JXA104 (host boundary) hold the ledger itself, single-device and
# over a 2-device mesh (where each sum lowers to a chained collective)
# ---------------------------------------------------------------------------


# jaxaudit: disable=JXA502 -- the ledger's optimization_barrier (pinned
# summation-order fence, JXA401) has no vmap batching rule in this jax;
# ensembles reduce observables per member OUTSIDE the batched step
@entrypoint("observable_ledger")
def observable_ledger():
    import jax.numpy as jnp

    from sphexa_tpu.observables.ledger import (
        ObservableSpec,
        ledger_diagnostics,
    )

    sim = _sim("sedov", _SIDE, prop="std")
    s, box, const = sim.state, sim.box, sim.const
    ngmax = sim._cfg.nbr.ngmax
    spec = ObservableSpec(extra="mach")  # exercises the case-extra path
    rho = jnp.ones_like(s.m)
    c = jnp.ones_like(s.m)
    nc = jnp.full((s.n,), const.ng0 - 1, jnp.int32)

    def fn(state, b, rho, nc, c):
        return ledger_diagnostics(state, rho, nc, const, ngmax, spec=spec,
                                  egrav=0.0, box=b, c=c)

    return EntryCase(fn=fn, args=(s, box, rho, nc, c))


@entrypoint("observable_ledger_sharded", mesh_axes=("p",))
def observable_ledger_sharded():
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec

    from sphexa_tpu.init import make_initializer
    from sphexa_tpu.observables.ledger import ledger_diagnostics
    from sphexa_tpu.parallel import make_mesh, shard_state
    from sphexa_tpu.simulation import make_propagator_config

    P, side = _mesh_size_and_side()
    state, box, const = make_initializer("sedov")(side)
    cfg = make_propagator_config(state, box, const)
    mesh = make_mesh(P)
    sstate = shard_state(state, mesh)
    pspec = NamedSharding(mesh, PartitionSpec("p"))
    rho = jax.device_put(jnp.ones((state.n,)), pspec)
    nc = jax.device_put(jnp.full((state.n,), const.ng0 - 1, jnp.int32),
                        pspec)

    def fn(st, rho, nc):
        return ledger_diagnostics(st, rho, nc, const, cfg.nbr.ngmax)

    return EntryCase(fn=jax.jit(fn), args=(sstate, rho, nc))


# ---------------------------------------------------------------------------
# in-graph field snapshot (observables/snapshot.py) — the fixed-shape
# scatter-add deposit the live-science surface rides; audited standalone
# (like the ledger) single-device and over a 2-device mesh, where the
# replicated grid output makes GSPMD insert exactly one psum for the
# whole stacked (F, G*G) deposit
# ---------------------------------------------------------------------------


# jaxaudit: disable=JXA502 -- the snapshot's chain_after (the same
# collective-order fence as the ledger's, JXA401) has no vmap batching
# rule in this jax; ensembles snapshot per member OUTSIDE the batched
# step
# jaxaudit: disable=JXA401 -- the deposit is a colliding histogram
# scatter BY DESIGN (many particles per cell); the grid is a viz/
# monitoring surface whose contract is the cell sum up to rounding,
# not bitwise replay — the science ledger (observable_ledger) keeps
# the deterministic pinned-order path
@entrypoint("observable_snapshot")
def observable_snapshot():
    import jax.numpy as jnp

    from sphexa_tpu.observables.snapshot import (
        SnapshotSpec,
        snapshot_diagnostics,
    )

    sim = _sim("sedov", _SIDE, prop="std")
    s, box = sim.state, sim.box
    # exercises the multi-field stack AND the particle-subsample tap
    spec = SnapshotSpec(fields=("rho", "temp"), grid=8, stride=7)
    rho = jnp.ones_like(s.m)

    def fn(state, b, rho):
        return snapshot_diagnostics(state, rho, b, spec)

    return EntryCase(fn=fn, args=(s, box, rho))


# jaxaudit: disable=JXA502 -- same optimization_barrier fence as above
# jaxaudit: disable=JXA401 -- same deliberate histogram scatter as the
# single-device entry above
@entrypoint("observable_snapshot_sharded", mesh_axes=("p",))
def observable_snapshot_sharded():
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec

    from sphexa_tpu.init import make_initializer
    from sphexa_tpu.observables.snapshot import (
        SnapshotSpec,
        snapshot_diagnostics,
    )
    from sphexa_tpu.parallel import make_mesh, shard_state

    P, side = _mesh_size_and_side()
    state, box, const = make_initializer("sedov")(side)
    mesh = make_mesh(P)
    sstate = shard_state(state, mesh)
    pspec = NamedSharding(mesh, PartitionSpec("p"))
    rho = jax.device_put(jnp.ones((state.n,)), pspec)
    spec = SnapshotSpec(fields=("rho",), grid=8)

    def fn(st, rho, b):
        return snapshot_diagnostics(st, rho, b, spec)

    return EntryCase(fn=jax.jit(fn), args=(sstate, rho, box))


# ---------------------------------------------------------------------------
# tree build / sizing (parallel/sizing.py)
# ---------------------------------------------------------------------------


# phase_coverage_min=0: reconfigure-time program — none of its work runs
# inside a step-phase scope, so JXA301's taxonomy gate does not apply.
@entrypoint("tree_build_sizing", phase_coverage_min=0.0)
def tree_build_sizing():
    from sphexa_tpu.init import make_initializer
    from sphexa_tpu.parallel import sizing
    from sphexa_tpu.sfc.keys import compute_sfc_keys

    state, box, const = make_initializer("sedov")(_SIDE)
    level, group = 2, 64
    keys = compute_sfc_keys(state.x, state.y, state.z, box)

    def fn(x, y, z, b, keys):
        occ, ext = sizing.sizing_stats(x, y, z, b, level, group)
        hist = sizing.key_histogram(keys, level)
        return occ, ext, hist

    return EntryCase(fn=fn, args=(state.x, state.y, state.z, box, keys))


@entrypoint("knob_inertness", phase_coverage_min=0.0)
def knob_inertness():
    """JXA402 carrier: the traced fn is a stub (the rule's real work is
    the off-vs-unset probe pairs built by production_knob_probes, which
    fingerprint probe Simulations for every off-sentinel KnobSpec in
    tuning/knobs.py). A dedicated entry keeps the probes out of every
    step entry's rule loop while still running in every package audit.
    """
    import jax.numpy as jnp

    from sphexa_tpu.devtools.audit.lowerdiff import production_knob_probes

    return EntryCase(
        fn=lambda x: x * 1.0,
        args=(jnp.ones((8,), jnp.float32),),
        knob_probes=production_knob_probes,
    )
