"""Shared SPMD jaxpr analysis for the shardcheck (JXA2xx) rule family.

One walk over an entry's closed jaxpr produces everything the three
rules and the ``sphexa-audit preflight`` table read:

- **Collective order graph** (JXA201): every named-axis collective
  (psum/ppermute/all_gather/all_to_all/... at any nesting depth,
  shard_map bodies included) with its set of collective *ancestors*
  through the data-dependency graph. ``optimization_barrier`` — the
  ``exchange.chain_after`` primitive — is an ordinary eqn here, so a
  chained collective inherits its predecessor as an ancestor for free.
  Two collectives neither of which is an ancestor of the other carry no
  program order, and XLA may rendezvous them in different interleavings
  on different devices (the PR-5 deadlock/garbage class on CPU meshes,
  and an ICI stall hazard on real chips).
- **Donation-aware peak-HBM liveness** (JXA202): a live-interval sweep
  over per-device buffer bytes. Top-level avals whose leading dim is
  divisible by the traced mesh size count as one shard's slice;
  shard_map-interior avals are already per-shard. Donated entry args
  (the property JXA103 verifies actually lowers to input-output
  aliasing) credit their matched output buffer as zero bytes. Nested
  jaxprs (pjit/scan/cond bodies) contribute their own internal excess
  over their operand/result footprint at the call site. The same sweep
  carries a *campaign rescale*: every buffer holding a whole number of
  per-device slabs ("extensive" — particle fields, (S,3) vectors, halo
  annexes of k*S rows) is multiplied by
  ``(campaign_n / campaign_devices) / toy_slab_rows``; fixed-size work
  buffers (scan chunk accumulators, pallas tiles, O(tree) coarse
  arrays) stay at traced size. Full-slab halo windows rescale as full
  campaign slabs, so the bound is deliberately above the real Wmax.
- **Sharding-propagation facts** (JXA203): particle-shaped operands
  entering a shard_map fully replicated (empty ``in_names`` — the
  partitioner will materialize N rows per device), and the summed
  output bytes of all collectives (the measured cross-shard volume the
  rule gates against the analytic ``sizing``-derived budget a registry
  builder declares).

The report is cached on the EntryTrace so the three rules and the
preflight table pay for one analysis per entry.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, FrozenSet, List, Optional, Set, Tuple

__all__ = [
    "COLLECTIVE_PRIMS",
    "Collective",
    "ReplicatedOperand",
    "SpmdReport",
    "spmd_report",
    "format_bytes",
]

# jax.lax collective primitives that synchronize over a NAMED mesh axis.
# axis_index is deliberately absent: it reads the coordinate, no comm.
COLLECTIVE_PRIMS = frozenset({
    "psum", "pmax", "pmin", "pmean", "ppermute", "pshuffle",
    "all_gather", "all_gather_invariant", "all_to_all",
    "psum_scatter", "reduce_scatter", "pgather",
})

_AXIS_PARAM_KEYS = ("axes", "axis_name")
_EMPTY: FrozenSet[int] = frozenset()


@dataclasses.dataclass(frozen=True)
class Collective:
    cid: int
    prim: str
    axes: Tuple[str, ...]
    out_bytes: int       # per-shard result bytes (shard_map-interior aval)
    where: str           # nesting path, e.g. "pjit/shard_map"


@dataclasses.dataclass(frozen=True)
class ReplicatedOperand:
    where: str
    pos: int             # shard_map operand position
    shape: Tuple[int, ...]
    dtype: str
    toy_bytes: int
    campaign_bytes: int


@dataclasses.dataclass
class SpmdReport:
    mesh_size: int                       # largest shard_map mesh traced (1 = none)
    collectives: List[Collective]
    # ancestor sets parallel to ``collectives``: anc[j] holds the cids
    # that are data-ordered BEFORE collective j
    ancestors: List[FrozenSet[int]]
    unordered_pairs: List[Tuple[int, int]]
    toy_peak_bytes: int                  # per-device, at the traced toy N
    campaign_peak_bytes: Optional[int]   # rescaled; None for unsharded entries
    toy_slab_rows: int                   # per-device rows the rescale anchors on
    campaign_ratio: Optional[float]
    replicated: List[ReplicatedOperand]
    collective_out_bytes: int            # summed per-shard collective results
    n_global: int                        # largest leading dim over entry invars


def format_bytes(b: Optional[int]) -> str:
    if b is None:
        return "-"
    if b >= 1 << 30:
        return f"{b / (1 << 30):.2f}GiB"
    if b >= 1 << 20:
        return f"{b / (1 << 20):.2f}MiB"
    if b >= 1 << 10:
        return f"{b / (1 << 10):.1f}KiB"
    return f"{b}B"


def _is_var(v) -> bool:
    # Literals carry .val; Vars (and DropVars) don't
    return not hasattr(v, "val")


def _named_axes(eqn) -> Tuple[str, ...]:
    names: List[str] = []
    for key in _AXIS_PARAM_KEYS:
        if key in eqn.params:
            v = eqn.params[key]
            vals = v if isinstance(v, (tuple, list)) else (v,)
            names += [a for a in vals if isinstance(a, str)]
    return tuple(names)


def _sub_jaxprs(eqn) -> List[Any]:
    """Raw sub-jaxprs in an eqn's params (pjit ClosedJaxpr bodies,
    scan/while/cond branches, shard_map bodies, custom_* calls)."""
    subs: List[Any] = []
    for v in eqn.params.values():
        for w in (v if isinstance(v, (list, tuple)) else (v,)):
            # ClosedJaxpr forwards .eqns, so require .invars to pick the
            # RAW jaxpr (positional invar mapping needs it)
            if hasattr(w, "eqns") and hasattr(w, "invars"):
                subs.append(w)
            elif hasattr(w, "jaxpr") and hasattr(getattr(w, "jaxpr"), "eqns"):
                subs.append(w.jaxpr)
    return subs


def aval_bytes(aval) -> int:
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    n = 1
    for d in shape:
        n *= int(d)
    return n * dtype.itemsize


# ---------------------------------------------------------------------------
# collective-order graph
# ---------------------------------------------------------------------------


def _collective_order(jaxpr) -> Tuple[List[Collective], List[FrozenSet[int]],
                                      List[Tuple[int, int]]]:
    """Extract collectives + transitive collective-ancestor sets.

    Dataflow abstract interpretation: each var maps to the set of
    collective ids on some path to it. Sub-jaxpr invars/outvars are
    mapped positionally to the call eqn's when the arities line up
    (pjit, scan, shard_map, cond modulo the predicate); otherwise the
    call is treated as a unit (all inner collectives become ancestors of
    all eqn outputs) — optimistic only across a call boundary, which is
    where XLA schedules calls as units anyway."""
    infos: List[Collective] = []
    anc: List[FrozenSet[int]] = []

    def walk(jx, in_anc: Dict[Any, Set[int]], where: str
             ) -> Tuple[Set[int], List[Set[int]]]:
        env: Dict[Any, Set[int]] = dict(in_anc)
        ids_here: Set[int] = set()
        for eqn in jx.eqns:
            prim = eqn.primitive.name
            in_a: Set[int] = set()
            for v in eqn.invars:
                if _is_var(v):
                    in_a |= env.get(v, _EMPTY)
            subs = _sub_jaxprs(eqn)
            if subs:
                inner_all: Set[int] = set()
                out_accum: Optional[List[Set[int]]] = None
                positional = True
                for sj in subs:
                    sub_env: Dict[Any, Set[int]] = {}
                    ivs, evs = list(sj.invars), list(eqn.invars)
                    if len(ivs) == len(evs):
                        pairs = list(zip(ivs, evs))
                    elif len(ivs) == len(evs) - 1:   # cond: evs[0] = index
                        pairs = list(zip(ivs, evs[1:]))
                    else:
                        pairs = None
                    if pairs is None:
                        for iv in ivs:
                            sub_env[iv] = set(in_a)
                    else:
                        for iv, ev in pairs:
                            sub_env[iv] = (set(env.get(ev, _EMPTY))
                                           if _is_var(ev) else set())
                    sub_ids, sub_out = walk(
                        sj, sub_env, f"{where}/{prim}" if where else prim)
                    inner_all |= sub_ids
                    if len(sub_out) == len(eqn.outvars):
                        if out_accum is None:
                            out_accum = [set(s) for s in sub_out]
                        else:
                            for k in range(len(out_accum)):
                                out_accum[k] |= sub_out[k]
                    else:
                        positional = False
                ids_here |= inner_all
                if positional and out_accum is not None:
                    for k, ov in enumerate(eqn.outvars):
                        env[ov] = in_a | out_accum[k]
                else:
                    for ov in eqn.outvars:
                        env[ov] = in_a | inner_all
            elif prim in COLLECTIVE_PRIMS and _named_axes(eqn):
                cid = len(infos)
                infos.append(Collective(
                    cid=cid, prim=prim, axes=_named_axes(eqn),
                    out_bytes=sum(aval_bytes(ov.aval) for ov in eqn.outvars),
                    where=where or "jit",
                ))
                anc.append(frozenset(in_a))
                out_a = in_a | {cid}
                ids_here.add(cid)
                for ov in eqn.outvars:
                    env[ov] = out_a
            else:
                for ov in eqn.outvars:
                    env[ov] = in_a
        out_anc = [set(env.get(v, _EMPTY)) if _is_var(v) else set()
                   for v in jx.outvars]
        return ids_here, out_anc

    walk(jaxpr, {}, "")
    # close ancestor sets transitively (an ancestor's ancestors order too)
    closed: List[Set[int]] = [set(a) for a in anc]
    for j in range(len(closed)):
        stack = list(closed[j])
        while stack:
            i = stack.pop()
            for k in closed[i]:
                if k not in closed[j]:
                    closed[j].add(k)
                    stack.append(k)
    anc = [frozenset(a) for a in closed]
    unordered = [
        (i, j)
        for j in range(len(infos))
        for i in range(j)
        if i not in anc[j] and j not in anc[i]
    ]
    return infos, anc, unordered


# ---------------------------------------------------------------------------
# donation-aware peak liveness
# ---------------------------------------------------------------------------


def _per_device_bytes(aval, P: int, scaled: bool) -> int:
    b = aval_bytes(aval)
    if scaled and P > 1:
        shape = getattr(aval, "shape", ())
        if shape and int(shape[0]) >= P and int(shape[0]) % P == 0:
            b //= P
    return b


def _campaign_bytes(bt: int, aval, s_toy: int, ratio: float) -> int:
    if ratio <= 1.0 or not s_toy:
        return bt
    itemsize = getattr(getattr(aval, "dtype", None), "itemsize", 0)
    if not itemsize:
        return bt
    elems = bt // itemsize
    # extensive (scales with the slab) iff a whole number of per-device
    # slabs: particle-derived buffers are always k*S elements (fields,
    # (S,3) vectors, concat halo annexes = P*S windows), while the
    # fixed-size work buffers that must NOT rescale (scan chunk
    # accumulators, cell-grid tiles, O(tree) coarse arrays) are sized by
    # config constants unrelated to S
    if elems >= s_toy and elems % s_toy == 0:
        return int(bt * ratio)
    return bt


def _peak_liveness(jaxpr, P: int, s_toy: int, ratio: float,
                   donated_positions: Set[int]) -> Tuple[int, int]:
    """(toy_peak, campaign_peak) per-device bytes over the program.

    Buffers live from definition to last use (entry args, consts and
    results live the whole program). A donated entry arg's matched
    result (same shape+dtype, greedy) is credited zero — XLA aliases it
    onto the input buffer. A nested jaxpr adds only its internal excess
    over the call's operand/result footprint."""
    zero_vars: Set[Any] = set()
    invar_set = set(jaxpr.invars)
    matched: Set[int] = set()
    for pos in sorted(donated_positions):
        if pos >= len(jaxpr.invars):
            continue
        iv = jaxpr.invars[pos]
        ish = getattr(iv.aval, "shape", None)
        idt = getattr(iv.aval, "dtype", None)
        for k, ov in enumerate(jaxpr.outvars):
            if k in matched or not _is_var(ov) or ov in invar_set:
                continue
            if (getattr(ov.aval, "shape", None) == ish
                    and getattr(ov.aval, "dtype", None) == idt):
                matched.add(k)
                zero_vars.add(ov)
                break

    def sweep(jx, scaled: bool, top: bool) -> Tuple[int, int]:
        n = len(jx.eqns)
        end = n
        first: Dict[Any, int] = {}
        last: Dict[Any, int] = {}
        for v in (*jx.invars, *jx.constvars):
            first[v] = 0
            last[v] = end
        for i, eqn in enumerate(jx.eqns):
            for ov in eqn.outvars:
                first.setdefault(ov, i)
                last.setdefault(ov, i)
            for iv in eqn.invars:
                if _is_var(iv):
                    first.setdefault(iv, 0)
                    last[iv] = max(last.get(iv, 0), i)
        for ov in jx.outvars:
            if _is_var(ov):
                first.setdefault(ov, 0)
                last[ov] = end
        delta_t = [0] * (end + 2)
        delta_c = [0] * (end + 2)
        for v, f0 in first.items():
            if top and v in zero_vars:
                continue
            bt = _per_device_bytes(v.aval, P, scaled)
            bc = _campaign_bytes(bt, v.aval, s_toy, ratio)
            l0 = last.get(v, f0)
            delta_t[f0] += bt
            delta_t[l0 + 1] -= bt
            delta_c[f0] += bc
            delta_c[l0 + 1] -= bc
        extra_t = [0] * (end + 1)
        extra_c = [0] * (end + 1)
        for i, eqn in enumerate(jx.eqns):
            if eqn.primitive.name == "pallas_call":
                # kernel-body avals are VMEM block/tile views, not HBM
                # buffers — the call's HBM footprint is its operands and
                # results, already counted at this level
                continue
            subs = _sub_jaxprs(eqn)
            if not subs:
                continue
            sub_scaled = scaled and eqn.primitive.name != "shard_map"
            io_t = io_c = 0
            for v in (*eqn.invars, *eqn.outvars):
                if not _is_var(v):
                    continue
                bt = _per_device_bytes(v.aval, P, scaled)
                io_t += bt
                io_c += _campaign_bytes(bt, v.aval, s_toy, ratio)
            for sj in subs:
                pt, pc = sweep(sj, sub_scaled, top=False)
                extra_t[i] = max(extra_t[i], max(0, pt - io_t))
                extra_c[i] = max(extra_c[i], max(0, pc - io_c))
        peak_t = peak_c = run_t = run_c = 0
        for p in range(end + 1):
            run_t += delta_t[p]
            run_c += delta_c[p]
            peak_t = max(peak_t, run_t + extra_t[p])
            peak_c = max(peak_c, run_c + extra_c[p])
        return peak_t, peak_c

    return sweep(jaxpr, scaled=True, top=True)


# ---------------------------------------------------------------------------
# sharding propagation
# ---------------------------------------------------------------------------


def _replicated_operands(jaxpr, n_global: int, campaign_n: int
                         ) -> List[ReplicatedOperand]:
    out: List[ReplicatedOperand] = []

    def walk(jx, where: str):
        for eqn in jx.eqns:
            prim = eqn.primitive.name
            if prim == "shard_map":
                in_names = eqn.params.get("in_names", ())
                for pos, names in enumerate(in_names):
                    if names or pos >= len(eqn.invars):
                        continue       # some dim is sharded, or arity drift
                    v = eqn.invars[pos]
                    aval = getattr(v, "aval", None)
                    shape = tuple(getattr(aval, "shape", ()) or ())
                    if not shape or n_global <= 1 or int(shape[0]) != n_global:
                        continue       # not particle-shaped: replication is
                        #                the design (coarse tree, tables)
                    tb = aval_bytes(aval)
                    cb = int(tb * (campaign_n / n_global)) if campaign_n else tb
                    out.append(ReplicatedOperand(
                        where=where or "jit", pos=pos, shape=shape,
                        dtype=str(getattr(aval, "dtype", "?")),
                        toy_bytes=tb, campaign_bytes=cb,
                    ))
            for sj in _sub_jaxprs(eqn):
                walk(sj, f"{where}/{prim}" if where else prim)

    walk(jaxpr, "")
    return out


def _mesh_size(jaxpr) -> int:
    best = 1

    def walk(jx):
        nonlocal best
        for eqn in jx.eqns:
            if eqn.primitive.name == "shard_map":
                mesh = eqn.params.get("mesh")
                size = getattr(mesh, "size", None)
                if size is None and hasattr(mesh, "shape"):
                    size = 1
                    for d in dict(mesh.shape).values():
                        size *= int(d)
                if size:
                    best = max(best, int(size))
            for sj in _sub_jaxprs(eqn):
                walk(sj)

    walk(jaxpr)
    return best


# ---------------------------------------------------------------------------
# the one-call report
# ---------------------------------------------------------------------------


def spmd_report(trace, ctx) -> SpmdReport:
    """Analyze an EntryTrace under an AuditContext; cached on the trace."""
    cached = getattr(trace, "_spmd_report", None)
    if cached is not None:
        return cached
    closed = trace.closed_jaxpr
    jx = closed.jaxpr
    P = _mesh_size(jx)
    infos, ancestors, unordered = _collective_order(jx)

    donated: Set[int] = set()
    if trace.entry.donate:
        from jax import tree_util

        spans = [len(tree_util.tree_leaves(a)) for a in trace.case.args]
        offsets = [sum(spans[:i]) for i in range(len(spans))]
        for p in trace.entry.donate:
            if p < len(spans):
                donated |= set(range(offsets[p], offsets[p] + spans[p]))

    n_global = 0
    s_toy = 0
    for v in jx.invars:
        shape = getattr(v.aval, "shape", ())
        if shape:
            d0 = int(shape[0])
            n_global = max(n_global, d0)
            rows = d0 // P if (P > 1 and d0 >= P and d0 % P == 0) else d0
            s_toy = max(s_toy, rows)

    sharded = bool(trace.entry.mesh_axes)
    ratio: Optional[float] = None
    if sharded and s_toy:
        ratio = (ctx.campaign_n / max(ctx.campaign_devices, 1)) / s_toy
    toy_peak, campaign_peak = _peak_liveness(
        jx, P, s_toy, ratio or 0.0, donated)

    replicated = (_replicated_operands(jx, n_global, ctx.campaign_n)
                  if sharded else [])

    report = SpmdReport(
        mesh_size=P,
        collectives=infos,
        ancestors=ancestors,
        unordered_pairs=unordered,
        toy_peak_bytes=toy_peak,
        campaign_peak_bytes=(campaign_peak if (sharded and ratio) else None),
        toy_slab_rows=s_toy,
        campaign_ratio=ratio,
        replicated=replicated,
        collective_out_bytes=sum(c.out_bytes for c in infos),
        n_global=n_global,
    )
    trace._spmd_report = report
    return report
