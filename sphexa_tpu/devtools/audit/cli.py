"""jaxaudit CLI.

    python -m sphexa_tpu.devtools.audit sphexa_tpu
    sphexa-audit sphexa_tpu --format json
    sphexa-audit sphexa_tpu --baseline jaxaudit_baseline.json
    sphexa-audit tests/audit_fixtures/jxa105_const.py --select JXA105

Exit status mirrors sphexa-lint: 0 = clean (no non-baselined findings),
1 = findings or entry errors, 2 = usage error.

Unlike the lint CLI this one IMPORTS and TRACES the code it audits, so
it needs a jax backend. By default it bootstraps a small virtual CPU
mesh (``--cpu-devices``, default 2) before jax initializes, so sharded
registry entries are auditable from a plain shell; pass
``--cpu-devices 0`` to audit on the ambient backend instead (e.g. to
inspect real TPU lowerings).
"""

from __future__ import annotations

import argparse
import importlib
import importlib.util
import os
import sys
from pathlib import Path
from typing import List, Optional

from sphexa_tpu.devtools.common import finish_cli

_DEFAULT_TARGET = "sphexa_tpu"
_PACKAGE_REGISTRY = "sphexa_tpu.devtools.audit.registry"


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="sphexa-audit",
        description="jaxaudit: trace-level jaxpr/lowering auditor "
                    "(rules JXA101-JXA106, SPMD shardcheck "
                    "JXA201-JXA204, cost rules JXA301-JXA303, "
                    "determinism/knob-inertness JXA401-JXA402, "
                    "statecheck JXA501-JXA503) over the "
                    "registered hot entry points. 'sphexa-audit "
                    "preflight --help' for the campaign preflight mode, "
                    "'sphexa-audit cost --help' for the static roofline "
                    "cost gate, 'sphexa-audit lowering --help' for the "
                    "jaxdiff lowering-fingerprint lock, 'sphexa-audit "
                    "schema --help' for the statecheck state-schema "
                    "lock and vmap-batchability report.",
    )
    ap.add_argument("targets", nargs="*", default=[_DEFAULT_TARGET],
                    help="registry modules: 'sphexa_tpu' (the package "
                         "registry), a dotted module name, or a .py file "
                         "defining @entrypoint builders "
                         "(default: sphexa_tpu)")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--select", metavar="IDS",
                    help="comma-separated rule ids to run (default: all)")
    ap.add_argument("--entries", metavar="NAMES",
                    help="comma-separated entry names to audit "
                         "(default: all registered)")
    ap.add_argument("--baseline", metavar="FILE",
                    help="baseline JSON of grandfathered findings")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite --baseline with the current findings "
                         "and exit 0")
    ap.add_argument("--show-suppressed", action="store_true",
                    help="also list inline-suppressed and baselined "
                         "findings (text format)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    ap.add_argument("--list-entries", action="store_true",
                    help="print the registered entry points and exit")
    ap.add_argument("--cpu-devices", type=int,
                    default=int(os.environ.get("SPHEXA_AUDIT_DEVICES", "2")),
                    metavar="N",
                    help="bootstrap an N-virtual-device CPU backend "
                         "before jax initializes so sharded entries "
                         "trace (default $SPHEXA_AUDIT_DEVICES or 2; "
                         "0 = use the ambient backend)")
    return ap


def _load_target(target: str):
    """Import a registry target: the package alias, a module, or a file."""
    if target == _DEFAULT_TARGET:
        target = _PACKAGE_REGISTRY
    p = Path(target)
    if p.suffix == ".py" and p.exists():
        spec = importlib.util.spec_from_file_location(p.stem, p)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod
    return importlib.import_module(target)


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "preflight":
        from sphexa_tpu.devtools.audit.preflight import main as preflight_main

        return preflight_main(argv[1:])
    if argv and argv[0] == "cost":
        from sphexa_tpu.devtools.audit.costcli import main as cost_main

        return cost_main(argv[1:])
    if argv and argv[0] == "lowering":
        from sphexa_tpu.devtools.audit.lowerdiff import main as lowering_main

        return lowering_main(argv[1:])
    if argv and argv[0] == "schema":
        from sphexa_tpu.devtools.audit.statecheck import main as schema_main

        return schema_main(argv[1:])
    args = build_parser().parse_args(argv)

    # heavy imports AFTER argparse so --help stays instant
    from sphexa_tpu.devtools.audit.core import (
        Auditor,
        all_rules,
        audit_context,
        entries_from_namespace,
        set_audit_context,
    )

    if args.list_rules:
        for rule in all_rules().values():
            print(f"{rule.id}  {rule.name}: {rule.description}")
        return 0

    if args.cpu_devices and args.cpu_devices > 0:
        from sphexa_tpu.util.cpu_mesh import force_cpu_mesh

        try:
            force_cpu_mesh(args.cpu_devices)
        except RuntimeError as e:
            # ambient backend already up (in-process use) — sharded
            # entries skip themselves if it can't host their mesh
            print(f"sphexa-audit: note: CPU-mesh bootstrap skipped ({e})",
                  file=sys.stderr)
        if args.cpu_devices > 2:
            # sharded registry builders size their mesh from the audit
            # context, so --cpu-devices 8 really traces a P=8 program
            import dataclasses

            set_audit_context(dataclasses.replace(
                audit_context(), mesh_size=args.cpu_devices))

    entries = []
    for target in args.targets:
        try:
            mod = _load_target(target)
        except (ImportError, OSError, SyntaxError) as e:
            print(f"sphexa-audit: cannot load target {target!r}: {e}",
                  file=sys.stderr)
            return 2
        entries += entries_from_namespace(vars(mod))
    if args.entries:
        want = {s.strip() for s in args.entries.split(",") if s.strip()}
        unknown = want - {e.name for e in entries}
        if unknown:
            print(f"sphexa-audit: unknown entry name(s): {sorted(unknown)}",
                  file=sys.stderr)
            return 2
        entries = [e for e in entries if e.name in want]

    if args.list_entries:
        for e in entries:
            extras = []
            if e.donate:
                extras.append(f"donate={e.donate}")
            if e.mesh_axes:
                extras.append(f"mesh_axes={e.mesh_axes}")
            print(f"{e.name}  ({e.path}:{e.line})"
                  + (f"  [{', '.join(extras)}]" if extras else ""))
        return 0

    select = None
    if args.select:
        select = [s.strip() for s in args.select.split(",") if s.strip()]
    try:
        auditor = Auditor(select=select)
    except ValueError as e:
        print(f"sphexa-audit: {e}", file=sys.stderr)
        return 2

    if args.update_baseline and not args.baseline:
        print("sphexa-audit: --update-baseline requires --baseline",
              file=sys.stderr)
        return 2

    active, suppressed, errors, skipped = auditor.run_entries(entries)
    for note in skipped:
        print(f"sphexa-audit: skipped {note}", file=sys.stderr)
    return finish_cli("sphexa-audit", "jaxaudit", args, active, suppressed,
                      errors)


if __name__ == "__main__":
    sys.exit(main())
