"""Device models for the static roofline cost layer (jaxcost).

A ``DeviceModel`` is the small set of numbers a roofline needs: peak
FLOP/s by dtype, HBM bandwidth, and ICI bandwidth. ``costmodel.predict``
divides the per-phase FLOP/byte tallies by these to get a predicted
per-phase ms table and classifies each phase against the ridge point
(peak FLOP/s / HBM B/s — the arithmetic intensity above which a kernel
is compute-bound).

These are MODELS, not measurements. Assumptions, in one place:

- ``v5e``: 197 TFLOP/s bf16 (the public MXU peak), f32 modeled at 1/4
  of that (MXU f32 passes + the VPU's elementwise rate — SPH phases are
  VPU-heavy, so this is deliberately conservative), 16 GiB HBM at
  819 GB/s, and 4x ICI links modeled at 180 GB/s aggregate per chip.
- ``cpu-smoke``: a deliberately round model of the CI host XLA-CPU
  backend (a few GFLOP/s, tens of GB/s DRAM). It exists so the
  calibration fixture (``sphexa-telemetry trace tests/trace_fixture
  --predict``) has a device to predict against; its absolute numbers
  only shift every phase's ratio by a COMMON factor, which the
  committed per-phase calibration band absorbs.

Integer/bool arithmetic is charged at the f32 rate (``default_peak``):
the audited programs are f32-dominated and the sort/key phases mix int
ops through the same vector units.

Import-light by design (stdlib only): the costmodel contract mirrors
``spmd.py`` — importable without jax, CLI-safe for --help paths.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Tuple

__all__ = ["DeviceModel", "DEVICES", "get_device"]


@dataclasses.dataclass(frozen=True)
class DeviceModel:
    """Roofline parameters for one device class."""

    name: str
    description: str
    #: peak FLOP/s keyed by numpy dtype name ("float32", "bfloat16", ...)
    peak_flops: Dict[str, float]
    #: FLOP/s charged for dtypes absent from ``peak_flops`` (ints, bools)
    default_peak: float
    #: HBM (or DRAM) bandwidth, bytes/s
    hbm_bytes_per_s: float
    #: aggregate inter-chip interconnect bandwidth, bytes/s
    ici_bytes_per_s: float

    def peak_for(self, dtype_name: str) -> float:
        return self.peak_flops.get(dtype_name, self.default_peak)

    def ridge(self, dtype_name: str = "float32") -> float:
        """Arithmetic intensity (FLOPs/byte) at the compute/memory-bound
        boundary for ``dtype_name``."""
        return self.peak_for(dtype_name) / self.hbm_bytes_per_s


DEVICES: Dict[str, DeviceModel] = {
    "v5e": DeviceModel(
        name="v5e",
        description="TPU v5e chip (the ROADMAP campaign target)",
        peak_flops={
            "bfloat16": 197e12,
            "float32": 49.25e12,
            "float64": 1e12,     # software f64: the JXA101 policy bans it
        },
        default_peak=49.25e12,
        hbm_bytes_per_s=819e9,
        ici_bytes_per_s=180e9,
    ),
    "cpu-smoke": DeviceModel(
        name="cpu-smoke",
        description="CI-host XLA-CPU backend (calibration fixture only)",
        peak_flops={
            "bfloat16": 4e9,
            "float32": 8e9,
            "float64": 4e9,
        },
        default_peak=8e9,
        hbm_bytes_per_s=20e9,
        ici_bytes_per_s=1e9,
    ),
}


def device_names() -> Tuple[str, ...]:
    return tuple(sorted(DEVICES))


def get_device(name: str) -> DeviceModel:
    try:
        return DEVICES[name]
    except KeyError:
        raise ValueError(
            f"unknown device model {name!r} (known: {', '.join(device_names())})"
        ) from None
