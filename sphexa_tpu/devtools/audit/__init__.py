"""jaxaudit: trace-level semantic auditor for the package's hot jits.

The AST layer (devtools/lint) enforces what SOURCE must look like; this
layer enforces what the TRACER must produce. Each registered entry point
(devtools/audit/registry.py) is traced/lowered on tiny synthetic args
and checked against the invariants the ROADMAP's perf posture depends
on:

- JXA101  dtype promotion above the 32-bit dtypes.py policy
- JXA102  recompile-signature drift (step-2 retrace, weak-type leaks)
- JXA103  declared-donatable buffers not donated in the hot lowering
- JXA104  callback/device_put host-boundary leaks in the traced body
- JXA105  oversized constants baked into the jaxpr
- JXA106  collectives over axes outside the declared mesh sharding

The JXA2xx *shardcheck* series audits the SPMD program itself (shared
analysis in ``spmd.py``; surfaced as ``sphexa-audit preflight``):

- JXA201  mutually order-unconstrained collectives (the rendezvous-race
          class) not pinned by exchange.chain_after
- JXA202  donation-aware static peak-HBM liveness — traced toy N and
          the 64M/P=16 campaign rescale — vs the per-device budget
- JXA203  particle-shaped operands replicated into shard_map / exchange
          volume beyond the sizing-derived analytic expectation
- JXA204  rescale-exempt (tree/work) buffers growing superlinearly in N
          across a two-point trace probe

The JXA3xx *jaxcost* series is the static roofline cost model
(``costmodel.py`` + ``devices.py``; surfaced as ``sphexa-audit cost``):
per-phase FLOPs/HBM/ICI off the jaxpr via the ``sphexa/<phase>``
name-stack scopes, classified against a device model:

- JXA301  static FLOPs falling outside the phase taxonomy (coverage
          floor + off-taxonomy scope names)
- JXA302  predicted per-phase ms above the committed COST_BUDGET.json
          ceilings (the static analog of TELEMETRY_LOCK.json)
- JXA303  a declared-compute-bound phase whose arithmetic intensity
          sits below the device ridge point

The JXA4xx *jaxdiff* series certifies the lowering's IDENTITY
(``lowerdiff.py``; surfaced as ``sphexa-audit lowering``): every
entry's canonical jaxpr fingerprint is locked in the committed
``LOWERING_LOCK.json`` — drift exits 1 with a phase-attributed
structural diff, intentional changes re-lock with ``--write``:

- JXA401  bitwise-replay hazards: float scatter accumulation with
          neither unique nor sorted indices, reduce_precision eqns,
          float-reduction collectives outside a proven total order
- JXA402  a tuning knob's declared off sentinel perturbing the
          baseline step lowering (off-vs-unset fingerprint compare for
          every off_sentinel KnobSpec, zero per-knob test code)

The JXA5xx *statecheck* series certifies the carry/output SCHEMA
(``statecheck.py``; surfaced as ``sphexa-audit schema``): each entry's
output pytree — paths, dtype, weak_type, every axis a polynomial in N
fitted from the two-point grow probe — is locked in the committed
``STATE_SCHEMA.json``, and the unified ``state.SimState`` carry the
ensemble mode (ROADMAP item 3) steps over is audited for closure and
batchability:

- JXA501  carry/output schema drift vs the committed lock (per-leaf
          structural diff; intentional changes re-lock with --write)
- JXA502  vmap-batchability over a member axis (trace failure,
          per-member host callbacks, serialized loop fallback) —
          the ensemble mode's static admission check (--vmap)
- JXA503  carry not closed under the step: treedef or leaf-aval drift
          between step-1 and step-2 carries (None<->array aux-slot
          flips; JXA102 lifted to the full carry structure)

Usage::

    python -m sphexa_tpu.devtools.audit sphexa_tpu
    sphexa-audit sphexa_tpu --format json
    sphexa-audit preflight --mesh 4
    sphexa-audit cost --device v5e
    sphexa-audit lowering --diff
    sphexa-audit schema --vmap
    sphexa-audit --list-rules

Suppress a finding with an inline comment (with a reason) on or directly
above the entry's ``@entrypoint`` registration::

    # jaxaudit: disable=JXA105 -- deliberate precomputed mode table

``JXA000`` is reserved for entries whose build or trace fails — broken
registry entries can never silently shrink coverage.
"""

from sphexa_tpu.devtools.audit.core import (  # noqa: F401
    AuditContext,
    Auditor,
    EntryCase,
    EntryPoint,
    EntrySkip,
    all_rules,
    audit_context,
    entries_from_namespace,
    entrypoint,
    set_audit_context,
)
from sphexa_tpu.devtools.common import Baseline, Finding  # noqa: F401
