"""jaxaudit: trace-level semantic auditor for the package's hot jits.

The AST layer (devtools/lint) enforces what SOURCE must look like; this
layer enforces what the TRACER must produce. Each registered entry point
(devtools/audit/registry.py) is traced/lowered on tiny synthetic args
and checked against the invariants the ROADMAP's perf posture depends
on:

- JXA101  dtype promotion above the 32-bit dtypes.py policy
- JXA102  recompile-signature drift (step-2 retrace, weak-type leaks)
- JXA103  declared-donatable buffers not donated in the hot lowering
- JXA104  callback/device_put host-boundary leaks in the traced body
- JXA105  oversized constants baked into the jaxpr
- JXA106  collectives over axes outside the declared mesh sharding

The JXA2xx *shardcheck* series audits the SPMD program itself (shared
analysis in ``spmd.py``; surfaced as ``sphexa-audit preflight``):

- JXA201  mutually order-unconstrained collectives (the rendezvous-race
          class) not pinned by exchange.chain_after
- JXA202  donation-aware static peak-HBM liveness — traced toy N and
          the 64M/P=16 campaign rescale — vs the per-device budget
- JXA203  particle-shaped operands replicated into shard_map / exchange
          volume beyond the sizing-derived analytic expectation

Usage::

    python -m sphexa_tpu.devtools.audit sphexa_tpu
    sphexa-audit sphexa_tpu --format json
    sphexa-audit preflight --mesh 4
    sphexa-audit --list-rules

Suppress a finding with an inline comment (with a reason) on or directly
above the entry's ``@entrypoint`` registration::

    # jaxaudit: disable=JXA105 -- deliberate precomputed mode table

``JXA000`` is reserved for entries whose build or trace fails — broken
registry entries can never silently shrink coverage.
"""

from sphexa_tpu.devtools.audit.core import (  # noqa: F401
    AuditContext,
    Auditor,
    EntryCase,
    EntryPoint,
    EntrySkip,
    all_rules,
    audit_context,
    entries_from_namespace,
    entrypoint,
    set_audit_context,
)
from sphexa_tpu.devtools.common import Baseline, Finding  # noqa: F401
