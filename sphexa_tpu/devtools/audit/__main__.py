"""``python -m sphexa_tpu.devtools.audit`` entry point."""

import sys

from sphexa_tpu.devtools.audit.cli import main

sys.exit(main())
